#!/bin/bash
# CI task runner (parity: tests/travis/run_test.sh task dispatch).
# Tasks compose the same make targets developers run locally, so a CI
# failure is always reproducible with one command.
#
#   TASK=lint        python lint (pyflakes if present, else compileall)
#                    + the mxlint graph-lint sweep over the model zoo
#   TASK=python      fast suite on the virtual CPU mesh (tests/conftest.py
#                    forces JAX_PLATFORMS=cpu + 8 fake devices)
#   TASK=python_nonative  same suite with the native .so disabled —
#                    certifies the pure-python fallback
#   TASK=cpp         native engine/recordio unit tests
#   TASK=capi        C ABI consumers (needs python headers)
#   TASK=nightly     multi-process distributed suite (slow)
#   TASK=resilience  fault-injection recovery matrix + graph lint
#   TASK=observability  telemetry unit tests + the 2-process drill +
#                    an mxtop --json smoke over the drill's event dir
#   TASK=perf        overlap unit suite + the 2-process overlap drill
#                    (asserts overlap_ratio > 1.05, bit-identical math)
#   TASK=autotune    chip-free config search (docs/perf.md "Autotuning
#                    & chip windows"): byte-identical manifest
#                    determinism on ResNet-50/v5e and the dp=2,tp=2
#                    transformer, the v5e ranking pin (b512 first),
#                    and the slo-gated replay over the pinned fixture
#   TASK=serving     serving unit suite (planner/batcher/server + KV
#                    cache + generation) + the serve_load and
#                    serve_generate acceptance drills (>= 3x serial
#                    batch-1; decode == full forward; structured KV
#                    429s; zero lowerings after warmup) +
#                    serve_bench/mxtop smoke in both modes + the
#                    networked-fleet chaos drill (KV partition +
#                    leader-router SIGKILL, zero client errors) and
#                    an mxkv TCP-server smoke
set -e
cd "$(dirname "$0")/../.."

case "${TASK:-python}" in
  lint)
    if python -c "import pyflakes" 2>/dev/null; then
      python -m pyflakes mxnet_tpu tools bench.py __graft_entry__.py
    else
      python -m compileall -q mxnet_tpu tools bench.py __graft_entry__.py
    fi
    # fast pre-merge step: lint only what this change touches (changed
    # symbol JSONs, models whose builders changed, changed framework
    # .py through the MXL-D rank-divergence pass) before the full
    # sweeps below — a quick early exit for broken changes
    if git rev-parse --verify -q HEAD~1 >/dev/null; then
      JAX_PLATFORMS=cpu python tools/mxlint.py --diff HEAD~1 \
        --fail-on=error --format=github
    fi
    # graph lint sweep over the bundled model zoo (docs/graph_lint.md):
    # every model must carry zero error-severity findings
    JAX_PLATFORMS=cpu python tools/mxlint.py --all-models --fail-on=error
    # SPMD sweep: sharding propagation + collective audit + peak-HBM
    # report on the transformer under a dp=2,tp=2 logical mesh — no
    # implicit reshard (MXL-P001) may appear at error severity
    JAX_PLATFORMS=cpu python tools/mxlint.py --model transformer \
      --mesh dp=2,tp=2 --fail-on=error
    # kernel + roofline sweep (docs/graph_lint.md MXL-K/MXL-R): every
    # registered Pallas kernel spec must satisfy Mosaic's tile rules,
    # and the static roofline must price resnet at training batch
    # sizes without an error-severity finding — all chip-free
    JAX_PLATFORMS=cpu python tools/mxlint.py --model resnet \
      --select 'MXL-K*,MXL-R*' --shapes "data=(64,3,224,224)" \
      --fail-on=error --format=github
    JAX_PLATFORMS=cpu python tools/mxlint.py --model resnet \
      --select 'MXL-K*,MXL-R*' --shapes "data=(256,3,224,224)" \
      --fail-on=error --format=github
    JAX_PLATFORMS=cpu python tools/mxlint.py --model transformer \
      --mesh dp=2,tp=2 --select 'MXL-K*,MXL-R*' \
      --fail-on=error --format=github
    # distributed sweep (docs/graph_lint.md MXL-D): the per-rank
    # collective-trace diff over the zoo at a simulated 4-rank pod,
    # plus the rank-divergence dataflow self-lint over mxnet_tpu/ —
    # the framework's own source must carry zero error-severity
    # divergence findings (intentional seams are @collective_seam /
    # rank-divergent-ok annotated)
    JAX_PLATFORMS=cpu python tools/mxlint.py --all-models \
      --distributed --world-size 4 --fail-on=error --format=github
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      --world-size 4 mxnet_tpu --fail-on=error --format=github
    # the elastic re-mesh protocol is the most divergence-sensitive
    # code in the tree (rank 0 proposes, everyone else adopts): pin
    # its self-lint as an explicit leg so a sweep-config change can
    # never silently drop it
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      mxnet_tpu/resilience/elastic.py --fail-on=error --format=github
    # the async-collective machinery (bucketed push, FIFO launcher) is
    # the newest divergence-sensitive seam — pinned for the same reason
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      mxnet_tpu/parallel/overlap.py --fail-on=error --format=github
    # the serving scheduler rides those same launchers and makes its
    # own per-process dispatch decisions (queue depth, timers) — pin
    # its self-lint so the divergence pass always prices it
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      mxnet_tpu/serving --fail-on=error --format=github
    # the fleet router makes the most divergence-sensitive serving
    # decisions of all (per-replica dispatch, generation verdicts,
    # rotation during hot-swap) — pinned on top of the directory sweep
    # so a sweep-config change can never silently drop it
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      mxnet_tpu/serving/fleet.py --fail-on=error --format=github
    # the coordination KV + lease (docs/serving.md "Networked fleet")
    # sits under every cross-process verdict the fleet makes — pinned
    # explicitly like fleet.py so the sweep can never drop it
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      mxnet_tpu/resilience/netkv.py --fail-on=error --format=github
    # generative serving's cache allocator + engine make per-process
    # admission and scheduling decisions (block budgets, prefill/decode
    # alternation) — pinned explicitly on top of the directory sweep so
    # a future sweep-config change can never silently drop them
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      mxnet_tpu/serving/kvcache.py mxnet_tpu/serving/generate.py \
      --fail-on=error --format=github
    # the paged KV cache's (block_size, head_dim) decode layout must
    # stay MXL-K tile-legal at every serving dtype — including the
    # int8 the quantized tier will bind — straight from the registered
    # kernel spec
    JAX_PLATFORMS=cpu python -c '
from mxnet_tpu.serving.kvcache import cache_kernel_spec
from mxnet_tpu.analysis.tiling import spec_findings
for dt in ("float32", "bfloat16", "int8"):
    bad = [f for f in spec_findings(cache_kernel_spec(dtype=dt))
           if f[1] == "error"]
    assert not bad, (dt, bad)
print("paged_kv_cache MXL-K sweep OK (f32/bf16/int8)")
'
    # the quantized + fused kernel tier (docs/perf.md "Quantization &
    # fused kernels"): all three Pallas specs — dequant matmul, flash
    # decode, fused optimizer sweep — must stay Mosaic tile-legal at
    # every compute dtype they serve
    JAX_PLATFORMS=cpu python -c '
from mxnet_tpu.analysis.tiling import spec_findings
from mxnet_tpu.kernels.flash_decode import flash_decode_kernel_spec
from mxnet_tpu.kernels.fused_opt import fused_opt_kernel_spec
from mxnet_tpu.kernels.quantize import qmm_kernel_spec
for mk in (qmm_kernel_spec, flash_decode_kernel_spec,
           fused_opt_kernel_spec):
    for dt in ("float32", "bfloat16", "int8"):
        spec = mk(dtype=dt)
        bad = [f for f in spec_findings(spec) if f[1] == "error"]
        assert not bad, (spec["name"], bad)
print("kernel-tier MXL-K sweep OK "
      "(qmm/flash_decode/fused_opt x f32/bf16/int8)")
'
    # ...and the kernel tier itself (env-gated dispatch, bucket plans)
    # must stay divergence-clean under the MXL-D self-lint
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      mxnet_tpu/kernels --fail-on=error --format=github
    # the tracing tier touches every collective seam (rank-uniform seq
    # counters, the flight ledger, the SLO sentry's emit path) — its
    # three modules must stay divergence-clean under MXL-D
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      mxnet_tpu/observability/trace.py \
      mxnet_tpu/observability/flight.py \
      mxnet_tpu/observability/slo.py --fail-on=error --format=github
    # warm elasticity's shard-directory agreement is another pod-wide
    # decision protocol (rank 0 publishes, everyone adopts) — pin its
    # MXL-D self-lint like elastic.py's
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      mxnet_tpu/resilience/hotstate.py --fail-on=error --format=github
    # the autotuner plans pod-wide chip windows (per-rank bench
    # commands, sharding grammars, pruning verdicts) — its own source
    # must stay divergence-clean under the MXL-D self-lint
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      mxnet_tpu/analysis/autotune.py --fail-on=error --format=github
    # the pre-fix PR-3 regression fixtures are expected-FAIL inputs:
    # MXL-D must keep flagging each with its documented rule id
    fx=tests/fixtures/divergence
    for f in "$fx/pid_scratch_path.py:MXL-D004" \
             "$fx/per_rank_barrier_probe.py:MXL-D005" \
             "$fx/device0_sentinel.py:MXL-D005"; do
      file="${f%:*}"; rule="${f##*:}"
      if out=$(JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
          "$file" --fail-on=error --format=github); then
        echo "FIXTURE NOT FLAGGED: $file"; exit 1
      fi
      echo "$out" | grep -q "$rule" || {
        echo "FIXTURE $file missing $rule:"; echo "$out"; exit 1; }
      echo "fixture $file flagged with $rule (expected-fail OK)"
    done
    # concurrency self-lint (docs/graph_lint.md MXL-Q): the threaded
    # serving/resilience/observability runtime must carry zero
    # error-severity race / lock-order / callback-context findings
    # (intentional lock-free handshakes are thread-shared-ok annotated
    # with their happens-before argument)
    JAX_PLATFORMS=cpu python tools/mxlint.py --concurrency \
      mxnet_tpu --fail-on=error --format=github
    # the networked fleet's lock-densest files (router lease/takeover,
    # KV connection handling, bget parking) — pinned on top of the
    # directory sweep so a sweep-config change can never drop them
    JAX_PLATFORMS=cpu python tools/mxlint.py --concurrency \
      mxnet_tpu/resilience/netkv.py mxnet_tpu/serving/fleet.py \
      --fail-on=error --format=github
    # the pre-fix concurrency regression fixtures are expected-FAIL
    # inputs: MXL-Q must keep flagging each with its documented rule id
    qx=tests/fixtures/concurrency
    for f in "$qx/torch_callback_race.py:MXL-Q005" \
             "$qx/prefetcher_shutdown_race.py:MXL-Q001"; do
      file="${f%:*}"; rule="${f##*:}"
      if out=$(JAX_PLATFORMS=cpu python tools/mxlint.py --concurrency \
          "$file" --fail-on=error --format=github); then
        echo "FIXTURE NOT FLAGGED: $file"; exit 1
      fi
      echo "$out" | grep -q "$rule" || {
        echo "FIXTURE $file missing $rule:"; echo "$out"; exit 1; }
      echo "fixture $file flagged with $rule (expected-fail OK)"
    done
    # retrace-stability self-lint (docs/graph_lint.md MXL-X): the
    # traced/jitted surface must carry zero error-severity retrace
    # findings — tensor-dependent host branching, unstable cache-key
    # ingredients, per-step jit construction, unbucketed AOT shapes,
    # and donated-buffer reuse all break the zero-steady-state-
    # lowerings contract the serving benches assert at runtime
    JAX_PLATFORMS=cpu python tools/mxlint.py --retrace \
      mxnet_tpu --fail-on=error --format=github
    # the networked-fleet swap path re-aims AOT programs at new params
    # mid-serve — pin its files so MXL-X always prices them
    JAX_PLATFORMS=cpu python tools/mxlint.py --retrace \
      mxnet_tpu/resilience/netkv.py mxnet_tpu/serving/fleet.py \
      --fail-on=error --format=github
    # the pre-fix retrace regression fixture (the PR-17 id()-keyed
    # fused-step cache bug) is an expected-FAIL input: MXL-X must keep
    # flagging it with its documented rule id
    rx=tests/fixtures/retrace
    for f in "$rx/id_keyed_program_cache.py:MXL-X002"; do
      file="${f%:*}"; rule="${f##*:}"
      if out=$(JAX_PLATFORMS=cpu python tools/mxlint.py --retrace \
          "$file" --fail-on=error --format=github); then
        echo "FIXTURE NOT FLAGGED: $file"; exit 1
      fi
      echo "$out" | grep -q "$rule" || {
        echo "FIXTURE $file missing $rule:"; echo "$out"; exit 1; }
      echo "fixture $file flagged with $rule (expected-fail OK)"
    done
    # schedule lint (docs/graph_lint.md MXL-E): the pipeline-parallel
    # transformer sweep (dp=2,pp=4 flops-balanced auto-split) and the
    # expert-parallel MoE sweep (top-1 routing, ep=4, the priced
    # dispatch/combine all-to-all pair replayed through the MXL-D
    # collective trace at world 4) must both price clean
    JAX_PLATFORMS=cpu python tools/mxlint.py --model transformer \
      --mesh dp=2,pp=4 --schedule --fail-on=error --format=github
    JAX_PLATFORMS=cpu python tools/mxlint.py --model transformer_moe \
      --mesh dp=1,ep=4 --schedule --distributed --world-size 4 \
      --fail-on=error --format=github
    # the MXL-E analyzer, the MoE op and the 1F1B runtime are
    # themselves lint subjects: pin the divergence/concurrency/retrace
    # self-lints on them so the pricing machinery stays clean under
    # the families that police it
    JAX_PLATFORMS=cpu python tools/mxlint.py --distributed \
      --concurrency --retrace mxnet_tpu/analysis/schedule.py \
      mxnet_tpu/ops/moe.py mxnet_tpu/parallel/pipeline.py \
      --fail-on=error --format=github
    # the pre-fix schedule regression fixtures are expected-FAIL
    # symbol graphs: MXL-E must keep flagging each with its
    # documented rule id (an imbalanced ctx_group split, a
    # cross-stage back-edge, an expert count the ep mesh cannot
    # divide)
    sx=tests/fixtures/schedule
    for f in "$sx/imbalanced_stages.json|MXL-E001|data=(256,4096)|" \
             "$sx/cross_stage_backedge.json|MXL-E003|data=(256,4096)|" \
             "$sx/indivisible_experts.json|MXL-E006|data=(512,64)|ep=4"
    do
      IFS='|' read -r file rule shapes mesh <<< "$f"
      cmd=(tools/mxlint.py "$file" --schedule --shapes "$shapes"
           --fail-on=error --format=github)
      [ -n "$mesh" ] && cmd+=(--mesh "$mesh")
      if out=$(JAX_PLATFORMS=cpu python "${cmd[@]}"); then
        echo "FIXTURE NOT FLAGGED: $file"; exit 1
      fi
      echo "$out" | grep -q "$rule" || {
        echo "FIXTURE $file missing $rule:"; echo "$out"; exit 1; }
      echo "fixture $file flagged with $rule (expected-fail OK)"
    done
    ;;
  python)
    make -s all || echo "native build unavailable; python fallback"
    python -m pytest tests/ -x -q
    ;;
  python_nonative)
    MXTPU_NO_NATIVE=1 python -m pytest tests/ -x -q
    ;;
  cpp)
    make -s test-cpp
    ;;
  capi)
    make -s test-capi
    ;;
  nightly)
    make -s all
    MXTPU_NIGHTLY=1 python -m pytest tests/test_nightly_dist.py -x -q
    ;;
  resilience)
    # the whole leg runs under the lock-discipline sanitizer
    # (docs/graph_lint.md "MXL-Q"): every package lock records
    # per-thread acquisition order, and a lock-order inversion anywhere
    # in the sentinel/watchdog/elastic threads fails the suite as a
    # structured ResilienceError(kind="lock_order") instead of an
    # intermittent hang
    export MXTPU_LOCKCHECK=1
    # ...and under the retrace sentry (docs/graph_lint.md "MXL-X"):
    # every post-warmup lowering is counted and attributed to the
    # divergent cache-key ingredient, so a recovery path that silently
    # re-lowers steady-state programs surfaces as a structured
    # "retrace" telemetry event instead of a latency mystery
    export MXTPU_RETRACE_SENTRY=1
    # fault-injection matrix (docs/resilience.md): injected NaN/hang/
    # ckpt-crash/dead-node faults must each hit their recovery path,
    # plus the kill-one-worker resume smoke
    JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q \
      --deselect tests/test_resilience.py::test_elastic_shrink_grow_drill \
      --deselect tests/test_resilience.py::test_warm_shrink_grow_drill \
      --deselect tests/test_resilience.py::test_warm_corrupt_shard_falls_back_to_checkpoint \
      --deselect tests/test_resilience.py::test_multihost_warm_shrink_grow_drill
    # elasticity acceptance (docs/resilience.md "Elasticity"): its own
    # leg so a skip/deselect upstream can never silently drop it —
    # kill one of three workers, agree a generation-stamped shrink
    # verdict, resume resharded, grow back, and match the fixed-world
    # reference losses bit-for-bit
    JAX_PLATFORMS=cpu python -m pytest -q \
      tests/test_resilience.py::test_elastic_shrink_grow_drill
    # warm-elasticity acceptance (docs/resilience.md "Warm elasticity"):
    # the same kill/shrink/grow drill with MXTPU_WARM_REMESH=1 — losses
    # must stay bit-identical to the cold references while the telemetry
    # log shows zero checkpoint reads on the warm path
    JAX_PLATFORMS=cpu python -m pytest -q \
      tests/test_resilience.py::test_warm_shrink_grow_drill
    # structured degradation: a CRC-corrupt hot shard on rank 0 must fall
    # back to the PR-3 checkpoint with a named fallback_reason, never crash
    JAX_PLATFORMS=cpu python -m pytest -q \
      tests/test_resilience.py::test_warm_corrupt_shard_falls_back_to_checkpoint
    # multi-host-sim shrink/grow: 4 workers over 2 simulated hosts, lose a
    # whole host, rebuild from ring-buddy copies on the survivor
    JAX_PLATFORMS=cpu python -m pytest -q \
      tests/test_resilience.py::test_multihost_warm_shrink_grow_drill
    # lint must stay clean under the resilience wiring (github-annotated
    # output so findings land on the PR diff)
    JAX_PLATFORMS=cpu python tools/mxlint.py --all-models \
      --format=github --fail-on=error
    ;;
  observability)
    # telemetry suite (docs/observability.md): event-log semantics, the
    # <2% enabled-overhead bound, and the 2-process acceptance drill
    # (sentinel -> watchdog -> ckpt must land in the merged report);
    # plus the quantile-sketch/registry and SLO-engine unit suites
    JAX_PLATFORMS=cpu python -m pytest tests/test_observability.py \
      tests/test_metrics.py tests/test_sloengine.py -q
    # end-to-end CLI smoke: a real 2-worker run's event dir must render
    # through mxtop --json with a nonempty pod rollup
    TELDIR="$(mktemp -d)"
    MXTPU_TELEMETRY=1 MXTPU_TELEMETRY_DIR="$TELDIR" MXTPU_RUN_ID=ci \
      MXTPU_SENTINEL=1 MXTPU_FAULT_SPEC="step=2:kind=nan" \
      MXTPU_TEL_PREFIX="$TELDIR/ckpt" \
      python tools/launch.py -n 2 --launcher local --port 9899 \
      python tests/nightly/dist_telemetry.py
    python tools/mxtop.py "$TELDIR" --json | python -c '
import json, sys
rep = json.load(sys.stdin)
assert len(rep["per_rank"]) == 2, rep
assert rep["pod"]["step_ms_p50"] is not None, rep
print("mxtop --json smoke OK")
'
    # trace-merge smoke: the same run must render through mxtrace as a
    # valid Chrome-trace document with one process track per rank and
    # cross-rank flow events stitching the collectives
    python tools/mxtrace.py "$TELDIR" -o "$TELDIR/trace.json"
    python -c '
import json, sys
doc = json.load(open(sys.argv[1]))
evs = doc["traceEvents"]
assert isinstance(evs, list) and evs, "empty trace"
assert doc["displayTimeUnit"] == "ms", doc.keys()
pids = {e["pid"] for e in evs if e["ph"] == "M"}
assert pids == {0, 1}, pids
flows = [e for e in evs if e["ph"] in ("s", "f")]
assert flows, "no cross-rank flow events"
print("mxtrace smoke OK: %d events, %d flow arrows"
      % (len(evs), len(flows)))
' "$TELDIR/trace.json"
    rm -rf "$TELDIR"
    # hung-collective flight-dump drill: kill one of two workers
    # mid-allreduce; the survivor must dump a postmortem naming the
    # hung seq and the absent rank (asserted inside the drill), and
    # mxtrace must fold the dump's pending marker into the trace.
    # MXTPU_STEP_TIMEOUT_S stays unset: the drill arms its own watchdog.
    TELDIR="$(mktemp -d)"
    MXTPU_TELEMETRY=1 MXTPU_TELEMETRY_DIR="$TELDIR" MXTPU_RUN_ID=ci-flight \
      python tools/launch.py -n 2 --launcher local --port 9898 \
      python tests/nightly/dist_flight.py
    python tools/mxtrace.py "$TELDIR" -o "$TELDIR/trace.json"
    python -c '
import json, sys
doc = json.load(open(sys.argv[1]))
pend = [e for e in doc["traceEvents"]
        if e["ph"] == "i" and e["name"].startswith("PENDING")]
assert pend, "flight dump pending marker missing from trace"
print("flight drill trace OK: %s" % pend[0]["name"])
' "$TELDIR/trace.json"
    rm -rf "$TELDIR"
    # perf-regression gate: benchdiff must pass an unchanged run and
    # flag a synthetic +20% step-time regression against a pinned
    # baseline (a single file: zero noise, the 10% floor applies)
    python tools/benchdiff.py --baseline BENCH_r05.json \
      --against BENCH_r05.json
    if python tools/benchdiff.py --baseline BENCH_r05.json \
        --metrics "$(python -c '
import json
doc = json.load(open("BENCH_r05.json"))
print(json.dumps({"step_time_ms": doc["parsed"]["step_time_ms"] * 1.2}))
')"; then
      echo "benchdiff FAILED to flag a +20% step-time regression"
      exit 1
    fi
    echo "benchdiff gate OK (clean run passes, +20% regression flags)"
    # live SLO drill (docs/observability.md "Live metrics & SLO
    # engine"): /metrics exposition smoke (Prometheus-parseable,
    # counters monotone across two scrapes), then the burn-rate drill —
    # bursty open-loop traffic must stay quiet clean and must page +
    # recommend_grow within the fast window under an injected
    # serve_dispatch latency fault (asserted inside the drill)
    JAX_PLATFORMS=cpu python tests/nightly/serve_slo_drill.py
    ;;
  perf)
    # overlap machinery (docs/perf.md "Overlap"): prefetcher/bucketing/
    # compile-cache unit suite, then the 2-process acceptance drill —
    # the async feed must yield overlap_ratio > 1.05 with parameters
    # bit-identical to the serial run (asserted inside the drill)
    JAX_PLATFORMS=cpu python -m pytest tests/test_overlap.py -q
    TELDIR="$(mktemp -d)"
    JAX_PLATFORMS=cpu MXTPU_TELEMETRY=1 MXTPU_TELEMETRY_DIR="$TELDIR" \
      MXTPU_RUN_ID=ci-perf MXTPU_PREFETCH=1 MXTPU_BUCKET_MB=0.001 \
      python tools/launch.py -n 2 --launcher local --port 9899 \
      python tests/nightly/dist_overlap.py
    # the same events must surface through the operator CLI
    python tools/mxtop.py "$TELDIR" --json | python -c '
import json, sys
rep = json.load(sys.stdin)
ratio = rep["pod"].get("overlap_ratio")
assert ratio is not None and ratio > 1.05, rep["pod"]
print("mxtop overlap_ratio %.3f OK" % ratio)
'
    rm -rf "$TELDIR"
    ;;
  autotune)
    # autotuner unit suite (docs/perf.md "Autotuning & chip windows"):
    # the pinned v5e ceiling table, pruning-before-pricing, memoized
    # sweeps, manifest determinism, the replay/correction loop
    JAX_PLATFORMS=cpu python -m pytest tests/test_autotune.py -q
    ATDIR="$(mktemp -d)"
    # manifest determinism (snapshot assert): the same search inputs
    # must produce byte-identical manifests.  Two fresh runs + cmp is
    # the right snapshot — the provenance block pins the git commit,
    # so a repo-committed byte snapshot would break on every merge.
    JAX_PLATFORMS=cpu python tools/autotune.py --model resnet50 \
      --device-kind v5e -o "$ATDIR/resnet.a.json"
    JAX_PLATFORMS=cpu python tools/autotune.py --model resnet50 \
      --device-kind v5e -o "$ATDIR/resnet.b.json"
    cmp "$ATDIR/resnet.a.json" "$ATDIR/resnet.b.json"
    echo "autotune manifest determinism OK (resnet50/v5e)"
    # the v5e ranking pin: batch 512 (the 0.331 AOT ceiling) must rank
    # above batch 256 (0.293) for ResNet-50, and the HBM-infeasible
    # tail must have been pruned before pricing
    python -c '
import json, sys
man = json.load(open(sys.argv[1]))
top = man["configs"][0]
assert top["config"]["batch"] == 512, top["config"]
assert abs(top["predicted"]["mfu_ceiling"] - 0.331) < 0.01, top
nxt = [e for e in man["configs"] if e["config"]["batch"] == 256][0]
assert abs(nxt["predicted"]["mfu_ceiling"] - 0.293) < 0.01, nxt
assert top["predicted"]["mfu_ceiling"] > nxt["predicted"]["mfu_ceiling"]
assert top["bench_cmd"].startswith("BENCH_BATCH="), top["bench_cmd"]
print("autotune v5e ranking pin OK: b512 %.4f > b256 %.4f"
      % (top["predicted"]["mfu_ceiling"], nxt["predicted"]["mfu_ceiling"]))
' "$ATDIR/resnet.a.json"
    # dp=2,tp=2 transformer sweep: the SPMD axes must price (ICI bytes
    # present) and the manifest must stay deterministic there too
    JAX_PLATFORMS=cpu python tools/autotune.py --model transformer \
      --space "sharding=dp2tp2;batch=8,16" -o "$ATDIR/tfm.a.json"
    JAX_PLATFORMS=cpu python tools/autotune.py --model transformer \
      --space "sharding=dp2tp2;batch=8,16" -o "$ATDIR/tfm.b.json"
    cmp "$ATDIR/tfm.a.json" "$ATDIR/tfm.b.json"
    python -c '
import json, sys
man = json.load(open(sys.argv[1]))
assert man["configs"], man
for e in man["configs"]:
    assert e["config"]["sharding"] == "dp2tp2", e["config"]
    assert e["predicted"]["ici_bytes"] and e["predicted"]["ici_bytes"] > 0, e
print("autotune dp2tp2 transformer OK: %d configs, ici %.1f MB at top"
      % (len(man["configs"]),
         man["configs"][0]["predicted"]["ici_bytes"] / 1e6))
' "$ATDIR/tfm.a.json"
    # pipeline/MoE axes (docs/graph_lint.md MXL-E): the dp2pp2 sweep
    # must price with a simulated 1F1B bubble, the indivisible expert
    # count must be mxl-e-pruned before pricing, and the manifest must
    # stay byte-identical over the new axes
    JAX_PLATFORMS=cpu python tools/autotune.py --model transformer_moe \
      --space "sharding=dp2pp2,ep4;batch=8;microbatches=4,8;experts=8,6;capacity_factor=1.25" \
      -o "$ATDIR/moe.a.json"
    JAX_PLATFORMS=cpu python tools/autotune.py --model transformer_moe \
      --space "sharding=dp2pp2,ep4;batch=8;microbatches=4,8;experts=8,6;capacity_factor=1.25" \
      -o "$ATDIR/moe.b.json"
    cmp "$ATDIR/moe.a.json" "$ATDIR/moe.b.json"
    python -c '
import json, sys
man = json.load(open(sys.argv[1]))
piped = [e for e in man["configs"] if e["config"]["sharding"] == "dp2pp2"]
assert piped, [e["config"] for e in man["configs"]]
for e in piped:
    b = e["predicted"]["bubble_fraction"]
    assert b is not None and 0.0 < b < 1.0, e["predicted"]
    assert "BENCH_PP_STAGES=2" in e["bench_cmd"], e["bench_cmd"]
bad = [p for p in man["pruned"] if p["config"].get("experts") == 6
       and p["config"]["sharding"] == "ep4"]
assert bad and all(p["reason"].startswith("mxl-e:") for p in bad), \
    man["pruned"]
print("autotune pp/MoE axes OK: %d pipelined configs priced with "
      "bubbles, %d expert-indivisible config(s) mxl-e-pruned"
      % (len(piped), len(bad)))
' "$ATDIR/moe.a.json"
    # replay gate over the pinned fixture: the recorded chip-window
    # payloads must pass the slo sentry clean against the committed
    # BENCH_r05 baseline, fit a correction, and emit a corrected order
    JAX_PLATFORMS=cpu python tools/autotune.py \
      --replay "$ATDIR/resnet.a.json" \
      --results tests/fixtures/autotune/replay_results.json \
      --baseline BENCH_r05.json --fail-on-regression \
      > "$ATDIR/replay.json"
    python -c '
import json, sys
rep = json.load(open(sys.argv[1]))
assert rep["regressions"] == 0, rep
assert rep["correction"] and rep["correction"]["n"] >= 2, rep["correction"]
assert rep["corrected_order"], rep
ok = [r for r in rep["runs"] if r["status"] == "ok"]
assert ok and all(r.get("slo_checked") for r in ok), rep["runs"]
print("autotune replay gate OK: %d runs, correction a=%.3f"
      % (len(ok), rep["correction"]["a"]))
' "$ATDIR/replay.json"
    # ...and a synthetic halved-throughput window must flag through the
    # same gate (exit 1), like the observability benchdiff leg
    python -c '
import json, sys
doc = json.load(open("tests/fixtures/autotune/replay_results.json"))
for run in doc["runs"]:
    run["value"] = run["value"] * 0.5
    run["step_time_ms"] = run["step_time_ms"] * 2.0
json.dump(doc, open(sys.argv[1], "w"))
' "$ATDIR/regressed.json"
    if JAX_PLATFORMS=cpu python tools/autotune.py \
        --replay "$ATDIR/resnet.a.json" --results "$ATDIR/regressed.json" \
        --baseline BENCH_r05.json --fail-on-regression \
        > "$ATDIR/replay_bad.json"; then
      echo "autotune replay FAILED to flag a halved-throughput window"
      exit 1
    fi
    echo "autotune replay regression gate OK (clean passes, halved flags)"
    rm -rf "$ATDIR"
    ;;
  serving)
    # the whole leg runs under the lock-discipline sanitizer — the
    # batcher/fleet/router threads are the most lock-dense code in the
    # tree; a lock-order inversion fails as a structured error instead
    # of a flaky hang (docs/graph_lint.md "MXL-Q")
    export MXTPU_LOCKCHECK=1
    # ...and under the retrace sentry (docs/graph_lint.md "MXL-X"):
    # after each model's warmup boundary every unexpected lowering is
    # counted and attributed to its divergent cache-key ingredient —
    # the zero-steady-state-lowerings contract becomes an observable,
    # not a hope.  serve_bench stamps retraces_after_warmup into its
    # BENCH line below, which must stay 0
    export MXTPU_RETRACE_SENTRY=1
    # serving stack (docs/serving.md): planner/batcher/server unit
    # suite, then the acceptance drill — continuous batching must beat
    # the serial batch-1 Predictor >= 3x at bounded p95 with zero
    # lowerings after warmup (all asserted inside the drill)
    JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py \
      tests/test_kvcache.py tests/test_generate.py tests/test_kernels.py -q
    JAX_PLATFORMS=cpu python tests/nightly/serve_load.py
    # fleet unit suite + the multi-process fleet drill (docs/serving.md
    # "Fleet"): 3 real replica processes behind the router; SIGKILL one
    # and hot-swap weights mid-load — zero client-visible errors, p95
    # within the degraded-window bound, zero swap lowerings, post-swap
    # outputs bit-identical, and a generation-stamped replica_death
    # verdict in the fleet ledger (all asserted inside the drill)
    JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q
    JAX_PLATFORMS=cpu python tests/nightly/serve_load_fleet.py
    # networked-fleet stack (docs/serving.md "Networked fleet"): the
    # KV backend-parity + lease + fault-discipline unit suite runs
    # file:// and tcp:// through one contract, then the chaos drill —
    # 3 replica processes + 2 router doors over a tcp:// KV survive a
    # 5s KV partition AND SIGKILL of the leader router with zero
    # client-visible errors, zero fabricated death verdicts (the
    # partition must HOLD the last liveness verdict, not invent
    # deaths), a lease takeover, client address failover, a converged
    # swap-on-commit to v2 (bit-identical outputs), and bounded p95
    # (all asserted inside the drill)
    JAX_PLATFORMS=cpu python -m pytest tests/test_netkv.py -q
    JAX_PLATFORMS=cpu python tests/nightly/serve_fleet_net.py
    # mxkv smoke: the standalone TCP KV server must answer the CLI
    # client ops (ping/set/get/dir/del) over tcp://
    MXKV_URL="tcp://127.0.0.1:8979"
    python tools/mxkv.py serve --port 8979 &
    MXKV_PID=$!
    for _ in $(seq 1 50); do
      python tools/mxkv.py --kv "$MXKV_URL" ping >/dev/null 2>&1 \
        && break
      sleep 0.2
    done
    python tools/mxkv.py --kv "$MXKV_URL" ping | grep -q '"ok": true'
    python tools/mxkv.py --kv "$MXKV_URL" set smoke/k v1
    test "$(python tools/mxkv.py --kv "$MXKV_URL" get smoke/k)" = "v1"
    python tools/mxkv.py --kv "$MXKV_URL" dir smoke/ | grep -q "^smoke/k"
    python tools/mxkv.py --kv "$MXKV_URL" del smoke/k
    if python tools/mxkv.py --kv "$MXKV_URL" get smoke/k 2>/dev/null; then
      echo "mxkv: deleted key still readable"; exit 1
    fi
    kill "$MXKV_PID"; wait "$MXKV_PID" 2>/dev/null || true
    echo "mxkv smoke OK"
    # generative acceptance drill (docs/serving.md "Generation"):
    # decode == full forward, zero lowerings, structured 429 under KV
    # pressure while running decodes finish, bounded p95 TTFT
    JAX_PLATFORMS=cpu python tests/nightly/serve_generate.py
    # bench smoke with telemetry on: the BENCH JSON line must show an
    # intact AOT contract and carry the latency/occupancy/waste fields
    # the SLO dashboards read
    TELDIR="$(mktemp -d)"
    JAX_PLATFORMS=cpu MXTPU_TELEMETRY=1 MXTPU_TELEMETRY_DIR="$TELDIR" \
      MXTPU_RUN_ID=ci-serve \
      python tools/serve_bench.py --requests 200 | python -c '
import json, sys
rep = json.loads(sys.stdin.readlines()[-1])
assert rep["lowerings_after_warmup"] == 0, rep
assert rep.get("retraces_after_warmup", 0) == 0, rep
assert rep["completed"] == 200 and rep["errors"] == 0, rep
assert rep["latency_ms"]["p95"] is not None, rep
assert 0.0 < rep["occupancy"] <= 1.0, rep
assert rep["padding_waste"] is not None, rep
print("serve_bench smoke OK: %.0f rps, p95 %.2f ms"
      % (rep["value"], rep["latency_ms"]["p95"]))
'
    # the per-batch serve events must surface through the operator CLI
    python tools/mxtop.py "$TELDIR" --json --serve | python -c '
import json, sys
sv = json.load(sys.stdin)
assert sv["models"], sv
assert sv["total"]["requests"] >= 200, sv["total"]
print("mxtop --serve smoke OK: %d requests" % sv["total"]["requests"])
'
    rm -rf "$TELDIR"
    # generative bench smoke: the tokens/sec BENCH line must show the
    # AOT contract intact (zero lowerings across prefill AND decode)
    # and carry the TTFT/ITL percentiles the SLO sentry prices
    JAX_PLATFORMS=cpu python tools/serve_bench.py --generate \
      --requests 40 --max-new 8 | python -c '
import json, sys
rep = json.loads(sys.stdin.readlines()[-1])
assert rep["metric"] == "serve_tokens_per_sec", rep
assert rep["lowerings_after_warmup"] == 0, rep
assert rep["errors"] == 0 and rep["requests"] == 40, rep
assert rep["ttft_ms"]["p95"] is not None, rep
assert rep["itl_ms"]["p95"] is not None, rep
print("serve_bench --generate smoke OK: %.0f tok/s, ttft p95 %.2f ms"
      % (rep["value"], rep["ttft_ms"]["p95"]))
'
    # fleet bench smoke: the fleet_throughput_rps BENCH line must show
    # a balanced fleet, an AOT-clean mid-run hot-swap (zero lowerings,
    # enforced by serve_bench itself via exit 1), and carry the
    # balance/swap-pause fields the SLO sentry prices
    JAX_PLATFORMS=cpu python tools/serve_bench.py --fleet 2 \
      --requests 120 | python -c '
import json, sys
rep = json.loads(sys.stdin.readlines()[-1])
assert rep["metric"] == "fleet_throughput_rps", rep
assert rep["errors"] == 0, rep
assert rep["swap_lowerings"] == 0, rep
assert rep["balance_ratio"] is not None, rep
assert rep["swap_pause_ms_p95"] is not None, rep
assert sorted(rep["version_skew"]) == ["v2"], rep
print("serve_bench --fleet smoke OK: %.0f rps, balance %.2f"
      % (rep["value"], rep["balance_ratio"]))
'
    # quantized serving smoke (docs/perf.md "Quantization & fused
    # kernels"): int8 weight-only generation must keep the AOT contract
    # (zero steady-state lowerings) AND pass the logits-equivalence
    # gate — per-step cosine >= 0.999 vs the f32 reference, enforced
    # both by serve_bench itself (exit 1) and re-asserted here
    JAX_PLATFORMS=cpu python tools/serve_bench.py --generate \
      --quantize int8 --check-logits --requests 24 --max-new 6 \
      | python -c '
import json, sys
rep = json.loads(sys.stdin.readlines()[-1])
assert rep["lowerings_after_warmup"] == 0, rep
assert rep["errors"] == 0, rep
assert rep["quantize"] == "int8" and rep["serving_dtype"] == "int8", rep
assert rep["logits_cosine_min"] >= 0.999, rep
print("quantized serve_bench smoke OK: %.0f tok/s at int8, "
      "logits cosine %.5f" % (rep["value"], rep["logits_cosine_min"]))
'
    ;;
  *)
    echo "unknown TASK=${TASK}" >&2
    exit 1
    ;;
esac
