"""Multi-process sharded checkpoint: each worker writes/reads only its
own shards (the pod-scale resume path, ShardedTrainer.save_checkpoint /
load_checkpoint over orbax), across REAL process boundaries.

Both workers train a dp=2-sharded model 3 steps, save the distributed
checkpoint to a shared directory, restore into a FRESH trainer in every
process, and assert the next step matches a trainer that never stopped.

Run directly:
    MXTPU_SHCKPT_DIR=/tmp/shckpt python tools/launch.py -n 2 \
        --launcher local python tests/nightly/dist_sharded_ckpt.py
"""
import os
import sys

import numpy as np

import mxnet_tpu as mx  # noqa: F401  (boots jax.distributed via kvstore)


def net():
    d = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(d, num_hidden=8, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    out = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(out, name="softmax")


def main():
    workdir = os.environ.get("MXTPU_SHCKPT_DIR",
                             "/tmp/mxtpu_shckpt")
    kv = mx.kv.create("dist_sync")
    nw = kv.num_workers
    assert nw == 2, "launch with -n 2"

    import jax
    from mxnet_tpu import parallel

    n_dev = len(jax.devices())          # GLOBAL devices over all workers
    mesh = parallel.make_mesh(jax.devices(), dp=n_dev)
    gbatch = 2 * n_dev
    shapes = {"data": (gbatch, 6)}
    lshapes = {"softmax_label": (gbatch,)}

    def make():
        opt = mx.optimizer.create("adam", learning_rate=0.05)
        return parallel.ShardedTrainer(net(), opt, mesh)

    tr = make()
    mx.random.seed(11)
    params, state, aux = tr.init_params(shapes, label_shapes=lshapes)
    # each process feeds its LOCAL shard (reference num_parts protocol);
    # derived from one seeded global batch so the run is deterministic
    rng = np.random.RandomState(4)
    gdata = rng.rand(gbatch, 6).astype(np.float32)
    glabel = (rng.rand(gbatch) * 4).astype(np.float32)
    lo = kv.rank * gbatch // nw
    hi = (kv.rank + 1) * gbatch // nw
    batch = tr.shard_batch({"data": gdata[lo:hi],
                            "softmax_label": glabel[lo:hi]})
    for _ in range(3):
        params, state, aux, _ = tr.step(params, state, aux, batch)

    ckpt = os.path.join(workdir, "ck")
    kv.barrier()
    tr.save_checkpoint(ckpt, params, state, aux)   # every process calls
    kv.barrier()

    tr2 = make()
    p2, s2, a2 = tr2.load_checkpoint(ckpt, shapes, label_shapes=lshapes)
    assert tr2.num_update == 3

    pa, _, _, _ = tr.step(params, state, aux, batch)
    pb, _, _, _ = tr2.step(p2, s2, a2, batch)
    for name in pa:
        ga = np.asarray(jax.device_get(pa[name]))
        gb = np.asarray(jax.device_get(pb[name]))
        assert np.allclose(ga, gb, atol=1e-6), name

    kv.barrier()
    if kv.rank == 0:
        print("OK sharded checkpoint across processes")


if __name__ == "__main__":
    main()
