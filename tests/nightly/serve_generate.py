"""Generative serving drill: mixed prompt-length load through the
batching server's prefill/decode scheduler.

The acceptance run for docs/serving.md "Generation" (wired as the CI
smoke in tests/ci/run_test.sh TASK=serving), all on the virtual CPU
mesh:

1. **Correctness under concurrency** — every request's streamed tokens
   must equal its future's ``tokens``, and a singleton re-run of each
   distinct prompt through the inline engine loop must reproduce the
   batched result (iteration-level batching never changes tokens).
2. **AOT proof** — zero lowerings after ``add_generative_model``
   returns, across the entire mixed prefill/decode run, from the
   program-registry counters.
3. **Backpressure** — with the pool nearly full, further admissions
   raise structured 429s carrying ``blocks_free`` while every running
   decode completes; afterwards the pool drains back to zero blocks
   used.
4. **Tail latency** — p95 TTFT stays under a generous bound derived
   from the measured single-prefill device time (the scheduler must
   not starve prefills behind decode batches).

Prints one JSON line with every figure.  Exit codes: 0 OK, 4 = an
expectation failed.

Run:  JAX_PLATFORMS=cpu python tests/nightly/serve_generate.py
"""
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import mxnet_tpu as mx                                  # noqa: E402,F401
from mxnet_tpu import ndarray as nd                     # noqa: E402
from mxnet_tpu.executor import program_registry_stats  # noqa: E402
from mxnet_tpu.models import transformer as tf          # noqa: E402
from mxnet_tpu.serving import (ModelServer, ServerBusy)  # noqa: E402

N_REQUESTS = int(os.environ.get("SERVE_GEN_REQUESTS", "48"))
CONCURRENCY = int(os.environ.get("SERVE_GEN_CONCURRENCY", "8"))
MAX_NEW = int(os.environ.get("SERVE_GEN_MAX_NEW", "8"))
V, L, H, E, S = 64, 2, 4, 32, 48


def fail(msg, report):
    report["failed"] = msg
    print(json.dumps(report), flush=True)
    print("serve_generate FAILED: %s" % msg, file=sys.stderr, flush=True)
    os._exit(4)


def toy_params():
    full = tf.get_symbol(vocab_size=V, num_layers=L, num_heads=H,
                         dim=E, seq_len=S)
    rng = np.random.RandomState(0)
    shapes = full.infer_shape(data=(1, S), softmax_label=(1, S))[0]
    params = {}
    for name, shp in zip(full.list_arguments(), shapes):
        if name in ("data", "softmax_label"):
            continue
        params[name] = nd.array(rng.randn(*shp).astype(np.float32) * 0.05)
    return params


def main():
    params = toy_params()
    srv = ModelServer(max_delay_ms=2.0)
    engine = srv.add_generative_model(
        "lm", params, vocab_size=V, num_layers=L, num_heads=H, dim=E,
        max_seq_len=S, max_new_tokens=MAX_NEW,
        prompt_buckets=(8, 16, 32), decode_buckets=(1, 2, 4, 8),
        kv_blocks=64, kv_block_size=8)
    lowerings_at_warmup = program_registry_stats()["lowerings"]

    # measured single-prefill device time on the largest bucket — the
    # TTFT bound's unit of work
    rng = np.random.RandomState(7)
    t_pre = []
    for _ in range(5):
        t0 = time.perf_counter()
        engine.generate([[1] * 30], max_new_tokens=1)
        t_pre.append(time.perf_counter() - t0)
    prefill_ms = sorted(t_pre)[len(t_pre) // 2] * 1e3

    # -- 1+2: mixed concurrent load, streams vs futures ----------------
    prompts = [list(map(int, rng.randint(1, V, size=n)))
               for n in rng.choice([3, 7, 12, 20, 30], size=N_REQUESTS)]
    results = [None] * N_REQUESTS
    ttfts = []
    errors = []
    lock = threading.Lock()
    cursor = [0]

    def worker():
        while True:
            with lock:
                i = cursor[0]
                if i >= N_REQUESTS:
                    return
                cursor[0] += 1
            try:
                t0 = time.perf_counter()
                while True:
                    try:
                        future, stream = srv.generate(
                            "lm", prompts[i], max_new_tokens=MAX_NEW)
                        break
                    except ServerBusy as busy:
                        time.sleep((busy.retry_after_ms or 10) / 1e3)
                streamed, first = [], None
                for tok in stream:
                    if first is None:
                        first = time.perf_counter() - t0
                    streamed.append(tok)
                res = future.result(timeout=120)
                if res["tokens"] != streamed:
                    raise AssertionError(
                        "stream %r != future %r" % (streamed,
                                                    res["tokens"]))
                results[i] = res["tokens"]
                with lock:
                    ttfts.append(first * 1e3)
            except Exception as exc:
                with lock:
                    errors.append(exc)
                return

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(CONCURRENCY)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    total_tokens = sum(len(r) for r in results if r)
    lowerings_after = program_registry_stats()["lowerings"] \
        - lowerings_at_warmup

    # batched tokens must equal the inline singleton run per prompt
    singleton_ok = True
    for i in (0, N_REQUESTS // 2, N_REQUESTS - 1):
        alone = engine.generate([prompts[i]], max_new_tokens=MAX_NEW)[0]
        if results[i] != alone:
            singleton_ok = False
            break

    # -- 3: backpressure while decodes progress ------------------------
    blocks_total = engine.cache.blocks_total()
    hogs = []
    rejected = None
    for _ in range(500):        # admission outruns completion quickly
        try:
            hogs.append(srv.generate("lm", [1] * 30,
                                     max_new_tokens=MAX_NEW))
        except ServerBusy as busy:
            rejected = busy
            break
    hog_tokens = [fut.result(timeout=120)["tokens"] for fut, _s in hogs]
    deadline = time.time() + 30
    while engine.cache.blocks_used() and time.time() < deadline:
        time.sleep(0.01)
    blocks_left = engine.cache.blocks_used()

    stats = srv.stats()["models"]["lm"]
    srv.close()

    ttfts.sort()
    ttft_p95 = ttfts[int(0.95 * (len(ttfts) - 1))] if ttfts else None
    # generous: an admission window + 8 largest-bucket prefills ahead
    # of ours plus scheduling slack — catches starvation, not jitter
    bound_ms = 2.0 + 8.0 * prefill_ms + 250.0
    report = {
        "metric": "serve_generate_drill",
        "requests": N_REQUESTS,
        "concurrency": CONCURRENCY,
        "tokens": total_tokens,
        "tokens_per_sec": round(total_tokens / wall_s, 1),
        "wall_s": round(wall_s, 2),
        "ttft_ms_p95": round(ttft_p95, 3) if ttft_p95 else None,
        "ttft_bound_ms": round(bound_ms, 1),
        "prefill_ms": round(prefill_ms, 3),
        "prompt_buckets": list(engine.prompt_buckets),
        "decode_buckets": list(engine.decode_buckets),
        "kv_blocks_high_water": stats.get("blocks_high_water"),
        "blocks_total": blocks_total,
        "rejected_needs": rejected.extra.get("blocks_needed")
        if rejected else None,
        "rejected_free": rejected.extra.get("blocks_free")
        if rejected else None,
        "lowerings_after_warmup": lowerings_after,
        "errors": len(errors),
    }
    if errors:
        fail("request errors: %r" % errors[0], report)
    if any(r is None for r in results):
        fail("missing results", report)
    if not singleton_ok:
        fail("batched tokens differ from singleton inline run", report)
    if lowerings_after != 0:
        fail("%d lowerings after warmup (AOT contract broken)"
             % lowerings_after, report)
    if rejected is None:
        fail("full pool did not raise ServerBusy", report)
    if rejected.code != 429 or "blocks_free" not in rejected.extra:
        fail("rejection not a structured 429: %r"
             % rejected.to_dict(), report)
    if any(len(toks) != MAX_NEW for toks in hog_tokens):
        fail("running decodes did not complete under cache pressure",
             report)
    if blocks_left:
        fail("%d blocks leaked after drain" % blocks_left, report)
    if ttft_p95 is None or ttft_p95 > bound_ms:
        fail("ttft p95 %.1f ms exceeds bound %.1f ms"
             % (ttft_p95 or -1, bound_ms), report)
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
