"""Distributed LeNet convergence (multi-process, synthetic data shards).

Parity: tests/nightly/dist_lenet.py — dist_sync training converges.
Each worker trains on its own shard (num_parts/part_index semantics) and
parameters stay in sync through the kvstore.

Run:  python tools/launch.py -n 2 --launcher local \
          python tests/nightly/dist_lenet.py
"""
import sys

import numpy as np

import mxnet_tpu as mx


def main():
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers

    np.random.seed(0)  # SAME data on all workers; shard below
    n = 512
    protos = np.random.uniform(-1, 1, (10, 1, 28, 28)).astype(np.float32)
    y = np.random.randint(0, 10, n).astype(np.float32)
    X = (protos[y.astype(int)]
         + 0.3 * np.random.randn(n, 1, 28, 28)).astype(np.float32)

    shard = slice(rank * n // nw, (rank + 1) * n // nw)
    train = mx.io.NDArrayIter(X[shard], y[shard], batch_size=32,
                              shuffle=True)
    val = mx.io.NDArrayIter(X, y, batch_size=64)

    net = mx.models.get_lenet(num_classes=10)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train, eval_data=val, num_epoch=3, kvstore=kv,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            initializer=mx.init.Xavier(factor_type="in", magnitude=2.34))
    score = dict(mod.score(val, "acc"))
    print("rank %d/%d accuracy %.3f" % (rank, nw, score["accuracy"]),
          flush=True)
    assert score["accuracy"] > 0.9, score
    kv.barrier()
    return 0


if __name__ == "__main__":
    sys.exit(main())
