"""Cross-process allreduce bandwidth (multi-process on one box).

The BASELINE secondary metric (kvstore push/pull -> allreduce bandwidth,
reference tools/bandwidth/measure.py:16-40) measured across REAL process
boundaries: each launch.py worker holds one shard of a global array on
its own device and a jitted sum over the worker axis runs the collective.

Prints one line per size:
    ALLREDUCE size=<bytes> devices=<n> time_ms=<t> busbw_gbps=<bw>
and asserts the bandwidth is a real number > 0.

Run directly:
    python tools/launch.py -n 2 --launcher local \
        python tests/nightly/dist_allreduce_bench.py
"""
import sys
import time

import numpy as np

import mxnet_tpu as mx  # noqa: F401  (boots jax.distributed via kvstore)


def main():
    kv = mx.kv.create("dist_sync")
    nw = kv.num_workers
    assert nw > 1, "launch with -n >= 2"

    import jax
    import jax.numpy as jnp
    from mxnet_tpu.kvstore import _csum_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _csum_mesh()
    summed = jax.jit(lambda x: jnp.sum(x, axis=0),
                     out_shardings=NamedSharding(mesh, P()))
    for size in (1 << 20, 16 << 20):
        elems = size // 4
        local = jnp.ones((1, elems), jnp.float32)
        sharding = NamedSharding(mesh, P("w", None))
        garr = jax.make_array_from_process_local_data(sharding, local)
        summed(garr).block_until_ready()       # compile
        kv.barrier()
        repeat = 8
        t0 = time.perf_counter()
        for _ in range(repeat):
            out = summed(garr)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / repeat
        moved = 2 * (nw - 1) / nw * size
        bw = moved / dt / 1e9
        assert np.isfinite(bw) and bw > 0, bw
        if kv.rank == 0:
            print("ALLREDUCE size=%d devices=%d time_ms=%.3f "
                  "busbw_gbps=%.3f" % (size, nw, dt * 1e3, bw))
    kv.barrier()
    if kv.rank == 0:
        print("OK allreduce bench")


if __name__ == "__main__":
    main()
    sys.exit(0)
