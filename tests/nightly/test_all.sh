#!/bin/bash
# Nightly distributed tests (parity: tests/nightly/test_all.sh).
# Multi-process on one box via the local launcher.
set -e
cd "$(dirname "$0")/../.."

echo "== dist_sync_kvstore (2 workers) =="
python tools/launch.py -n 2 --launcher local \
    python tests/nightly/dist_sync_kvstore.py

echo "== dist_lenet (2 workers) =="
python tools/launch.py -n 2 --launcher local \
    python tests/nightly/dist_lenet.py

echo "ALL NIGHTLY TESTS PASSED"
