"""2-worker telemetry drill: one faulty run -> one coherent event log.

Acceptance (ISSUE 4): a 2-process CPU run with ``MXTPU_TELEMETRY=1``
must leave per-rank JSONL whose merged ``mxtop --json`` report contains
step-time p50/p95, samples/sec, straggler gap, per-rank heartbeat age,
and the injected fault's sentinel -> watchdog -> ckpt events in order.

The script stages exactly that incident sequence on every rank:

1. ``FeedForward.fit`` over a dist_sync kvstore with the sentinel armed
   and ``MXTPU_FAULT_SPEC=step=2:kind=nan`` (set by the wrapper test):
   the injected NaN gradients trip a ``sentinel_skip`` fault event
   mid-epoch, while the fit loop emits step records and data_wait
   spans and the kvstore push emits collective events.
2. A deliberately-too-slow call under ``run_with_timeout`` raises the
   watchdog's ResilienceError -> ``watchdog_timeout`` fault event.
3. Rank 0 writes a classic checkpoint -> ``ckpt`` commit event.

Afterwards every rank publishes its live summary through the
coordination KV; rank 0 merges the pod view and emits a
``heartbeat_ages`` counter derived from the EXISTING ``mxtpu_hb/``
liveness stamps so the offline report carries true heartbeat ages.

Exit codes: 0 OK, 4 = a telemetry expectation failed.

Run (tests/test_observability.py wraps this):
    python tools/launch.py -n 2 --launcher local \
        python tests/nightly/dist_telemetry.py
"""
import os
import sys
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import observability as obs

PREFIX = os.environ.get("MXTPU_TEL_PREFIX", "/tmp/mxtpu_dist_telemetry")


def fail(rank, msg):
    print("rank %d FAILED: %s" % (rank, msg), flush=True)
    os._exit(4)


def build_data(rank, nw):
    rng = np.random.RandomState(7)
    X = rng.randn(160, 16).astype(np.float32)
    w = rng.randn(16)
    y = (X @ w > 0).astype(np.float32)
    shard = slice(rank * len(X) // nw, (rank + 1) * len(X) // nw)
    return X[shard], y[shard]


def main():
    if not obs.enabled():
        fail(0, "telemetry not enabled in drill env")
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers

    # ---- incident 1: sentinel skip inside a real fit loop ------------
    X, y = build_data(rank, nw)
    train = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=True, seed=11)
    net = mx.models.get_mlp(num_classes=2, hidden=(16,))
    model = mx.FeedForward(net, ctx=mx.context.cpu(), num_epoch=2,
                           optimizer="sgd", learning_rate=0.1)
    if rank != 0:
        # a manufactured straggler: rank>0 pays a small per-batch tax so
        # the pod report's straggler gap is visibly nonzero
        _orig = mx.io.NDArrayIter.next

        def _slow_next(self):
            time.sleep(0.02)
            return _orig(self)
        mx.io.NDArrayIter.next = _slow_next
    model.fit(X=train, kvstore=kv,
              batch_end_callback=mx.callback.Speedometer(20, frequent=2))
    sentinel_wall = time.time()

    # ---- incident 2: watchdog timeout --------------------------------
    from mxnet_tpu.resilience import run_with_timeout, ResilienceError
    try:
        run_with_timeout(lambda: time.sleep(5.0), 0.2,
                         phase="drill_stall", step=99)
        fail(rank, "watchdog did not fire")
    except ResilienceError:
        pass
    watchdog_wall = time.time()

    # ---- incident 3: checkpoint commit -------------------------------
    kv.barrier()
    if rank == 0:
        mx.model.save_checkpoint(PREFIX, 1, model.symbol,
                                 model.arg_params, model.aux_params)
    kv.barrier()

    # ---- live aggregation over the coordination KV -------------------
    if not obs.publish_summary(step=99):
        fail(rank, "publish_summary did not reach the coordination KV")
    kv.barrier()
    if rank == 0:
        view = obs.pod_view(num_workers=nw)
        if len(view["per_rank"]) != nw:
            fail(rank, "pod view has %d ranks, want %d"
                 % (len(view["per_rank"]), nw))
        ages = obs.heartbeat_ages(num_workers=nw)
        if any(a is None or a > 60 for a in ages.values()):
            fail(rank, "stale/missing heartbeat ages: %r" % (ages,))
        # land the true KV-derived ages in the event log so the offline
        # mxtop report shows heartbeat age per rank even after exit
        obs.emit("counter", name="heartbeat_ages",
                 ages={str(r): a for r, a in ages.items()})
        print("rank 0 pod view: ranks=%s straggler_gap_ms=%s"
              % (view["ranks"], view["pod"]["straggler_gap_ms"]),
              flush=True)

    # ---- self-check: this rank's own log tells the story in order ----
    obs.flush()
    fault = obs.last_fault()
    if fault is None or fault.get("fault") != "watchdog_timeout":
        fail(rank, "last fault is %r, want watchdog_timeout" % (fault,))
    del sentinel_wall, watchdog_wall
    kv.barrier()
    print("rank %d TELEMETRY DRILL OK" % rank, flush=True)
    sys.stdout.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
