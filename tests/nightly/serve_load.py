"""Serving load drill: continuous batching must beat serial batch-1.

The acceptance run for docs/serving.md (wired as the CI smoke in
tests/ci/run_test.sh TASK=serving), all on the virtual CPU mesh:

1. **Serial baseline** — N batch-1 ``Predictor.forward`` calls in a
   loop (the pre-serving deployment story): requests/sec.
2. **Batched server** — the same toy model behind ``ModelServer`` with
   buckets {1, 32}, N single-sample requests from a closed loop of
   concurrent clients.  Must sustain **>= 3x** the serial throughput.
3. **Bounded latency** — server p95 <= ``max_delay_ms`` + 2x the
   measured single-batch device time (the SLO the admission timer
   promises: a request waits at most one admission window plus the
   batch ahead of it and its own).
4. **AOT proof** — zero lowerings after warmup, from the executor
   program-registry counters, after every request has completed.

Prints one JSON line with every figure.  Exit codes: 0 OK, 4 = an
expectation failed.

Run:  JAX_PLATFORMS=cpu python tests/nightly/serve_load.py
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import mxnet_tpu as mx                                  # noqa: E402
from mxnet_tpu.executor import program_registry_stats  # noqa: E402
from mxnet_tpu.serving import ModelServer              # noqa: E402

N_REQUESTS = int(os.environ.get("SERVE_LOAD_REQUESTS", "800"))
CONCURRENCY = int(os.environ.get("SERVE_LOAD_CONCURRENCY", "32"))
MAX_DELAY_MS = float(os.environ.get("SERVE_LOAD_MAX_DELAY_MS", "25"))
FEATURES = 128


def fail(msg, report):
    report["failed"] = msg
    print(json.dumps(report), flush=True)
    print("serve_load FAILED: %s" % msg, file=sys.stderr, flush=True)
    os._exit(4)


def main():
    net = mx.models.get_mlp(num_classes=10, hidden=(64,) * 20)
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (2, FEATURES))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    arg_params, aux_params = mod.get_params()
    params = {"arg:" + k: v for k, v in arg_params.items()}
    params.update({"aux:" + k: v for k, v in aux_params.items()})

    rng = np.random.RandomState(11)
    x1 = rng.rand(1, FEATURES).astype("float32")

    # -- 1. serial batch-1 baseline ------------------------------------
    serial = mx.Predictor(net.tojson(), params, {"data": (1, FEATURES)})
    serial.forward(data=x1)                             # warm the compile
    t0 = time.perf_counter()
    for _ in range(N_REQUESTS):
        serial.forward(data=x1)
    serial_s = time.perf_counter() - t0
    serial_rps = N_REQUESTS / serial_s

    # -- 2. batched server over the same model -------------------------
    srv = ModelServer(max_delay_ms=MAX_DELAY_MS)
    plan = srv.add_model("toy", net.tojson(), params,
                         {"data": (FEATURES,)}, buckets=(1, 32))
    # measured single-batch device time on the largest bucket (median
    # of a few warm runs) — the latency bound's second term
    big = plan.max_batch
    xb = rng.rand(big, FEATURES).astype("float32")
    times = []
    for _ in range(20):
        t = time.perf_counter()
        srv._entries["toy"].predictors[big].forward(data=xb)
        times.append(time.perf_counter() - t)
    batch_ms = sorted(times)[len(times) // 2] * 1e3

    srv.predict("toy", x1)                              # pipeline warm
    lowerings_at_warmup = program_registry_stats()["lowerings"]

    import threading
    cursor, lock, errors = [0], threading.Lock(), []
    window = max(1, CONCURRENCY // 8)       # outstanding per client

    def worker():
        while True:
            with lock:
                i = cursor[0]
                if i >= N_REQUESTS:
                    return
                take = min(window, N_REQUESTS - i)
                cursor[0] += take
            try:
                futs = [srv.submit("toy", x1) for _ in range(take)]
                for fut in futs:
                    out = fut.result(timeout=60.0)
                    assert out[0].shape == (1, 10), out[0].shape
            except Exception as exc:
                errors.append(exc)
                return

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, CONCURRENCY // window))]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    server_s = time.perf_counter() - t0
    server_rps = N_REQUESTS / server_s

    stats = srv.stats()
    lowerings_after = program_registry_stats()["lowerings"] \
        - lowerings_at_warmup
    srv.close()

    p95 = (stats.get("latency_ms") or {}).get("p95")
    bound_ms = MAX_DELAY_MS + 2.0 * batch_ms
    report = {
        "metric": "serve_load_speedup",
        "value": round(server_rps / serial_rps, 2),
        "unit": "x vs serial batch-1",
        "serial_rps": round(serial_rps, 1),
        "server_rps": round(server_rps, 1),
        "requests": N_REQUESTS,
        "concurrency": CONCURRENCY,
        "buckets": list(plan.buckets),
        "occupancy": stats.get("occupancy"),
        "padding_waste": stats.get("padding_waste"),
        "latency_ms": stats.get("latency_ms"),
        "p95_bound_ms": round(bound_ms, 3),
        "single_batch_ms": round(batch_ms, 3),
        "lowerings_after_warmup": lowerings_after,
        "errors": len(errors),
    }
    if errors:
        fail("request errors: %r" % errors[0], report)
    if server_rps < 3.0 * serial_rps:
        fail("throughput %.1f rps < 3x serial %.1f rps"
             % (server_rps, serial_rps), report)
    if p95 is None or p95 > bound_ms:
        fail("p95 %.3f ms exceeds bound %.3f ms (max_delay %.1f + 2x "
             "batch %.3f)" % (p95 or -1, bound_ms, MAX_DELAY_MS,
                              batch_ms), report)
    if lowerings_after != 0:
        fail("%d lowerings after warmup (AOT contract broken)"
             % lowerings_after, report)
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
