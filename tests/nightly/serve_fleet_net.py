"""Networked-fleet chaos drill: KV partition + leader-router SIGKILL.

The acceptance run for docs/serving.md "Networked fleet" (wired as the
CI multi-process drill in tests/ci/run_test.sh TASK=serving).  The
parent embeds a :class:`TcpKVServer` (the coordination plane), spawns
REPLICAS real replica processes heartbeating into it over
``MXTPU_KV_URL=tcp://``, and TWO router front-door processes
(``mxfleet serve --adopt``) that elect a leader through the expiring
KV lease.  A :class:`FleetClient` drives closed-loop load across both
front doors while the drill injects, in order:

1. **A 5 s KV partition** (server-side: every connection accepted and
   dropped) at ~1/3 of the run.  The KV fault discipline must hold:
   routers hold their last liveness verdict (``kv_held`` in stats),
   ZERO death verdicts are fabricated, the ledger stays empty, and the
   serving datapath — which never touches the KV — keeps answering.
2. **SIGKILL of the leader router** (no drain, no goodbye) after the
   partition heals.  The standby must take the lease within a few
   TTLs; clients fail over between front doors with ZERO visible
   errors.
3. **Swap-on-commit leg**: the surviving leader applies a
   versioned-params pointer published into the KV (the
   ``MXTPU_FLEET_SWAP_ON_COMMIT`` consumer path) — every replica ends
   on v2 and fleet outputs are bit-identical to a local v2 Predictor.
4. **p95 SLO gate** — client-observed p95 bounded by the closed-loop
   single-door term plus a takeover allowance.

Prints one JSON line with every figure.  Exit codes: 0 OK, 4 = an
expectation failed.

Run:  JAX_PLATFORMS=cpu python tests/nightly/serve_fleet_net.py
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import mxnet_tpu as mx                                  # noqa: E402
from mxnet_tpu import ndarray as nd                     # noqa: E402
from mxnet_tpu.resilience import elastic                # noqa: E402
from mxnet_tpu.resilience.netkv import (                # noqa: E402
    TcpKV, TcpKVServer)
from mxnet_tpu.serving.fleet import (                   # noqa: E402
    _SWAP_PTR_KEY, FleetClient, HTTPReplicaClient, fleet_ledger_path,
    spawn_replica)

N_REQUESTS = int(os.environ.get("FLEET_NET_REQUESTS", "240"))
CONCURRENCY = int(os.environ.get("FLEET_NET_CONCURRENCY", "8"))
MAX_DELAY_MS = float(os.environ.get("FLEET_NET_MAX_DELAY_MS", "25"))
REPLICAS = int(os.environ.get("FLEET_NET_REPLICAS", "3"))
BASE_PORT = int(os.environ.get("FLEET_NET_BASE_PORT", "8981"))
ROUTER_PORTS = (BASE_PORT + REPLICAS + 1, BASE_PORT + REPLICAS + 2)
PARTITION_S = float(os.environ.get("FLEET_NET_PARTITION_S", "5"))
LEASE_TTL_S = 2.0
FEATURES = 64
BUCKETS = (1, 8)
MXFLEET = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "..", "..", "tools", "mxfleet.py")


def fail(msg, report):
    report["failed"] = msg
    print(json.dumps(report, default=str), flush=True)
    print("serve_fleet_net FAILED: %s" % msg, file=sys.stderr,
          flush=True)
    os._exit(4)


def _wait_http(client, proc, what, deadline):
    while True:
        try:
            if client.healthz():
                return
        except Exception:
            pass
        if proc is not None and proc.poll() is not None:
            raise RuntimeError("%s exited with %s during startup"
                               % (what, proc.returncode))
        if time.monotonic() > deadline:
            raise RuntimeError("%s not healthy in time" % what)
        time.sleep(0.1)


def _spawn_router(router_id, port, kv_url, fleet_dir):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, MXFLEET, "serve", "--adopt",
           "--kv", kv_url, "--router-id", router_id,
           "--port", str(port), "--replicas", str(REPLICAS),
           "--base-port", str(BASE_PORT), "--dir", fleet_dir,
           "--lease-ttl", str(LEASE_TTL_S)]
    return subprocess.Popen(cmd, env=env)


def _router_stats(port, timeout=10.0):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    try:
        conn.request("GET", "/v1/stats")
        resp = conn.getresponse()
        return json.loads(resp.read().decode())
    finally:
        conn.close()


def _leader_port(report, deadline_s=30.0):
    """Poll both doors until exactly one claims the lease."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        roles = {}
        for port in ROUTER_PORTS:
            try:
                roles[port] = _router_stats(port).get("role")
            except Exception:
                pass
        leaders = [p for p, r in roles.items() if r == "leader"]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.25)
    fail("no unique leader elected: %s" % roles, report)


def main():
    net = mx.models.get_mlp(num_classes=10, hidden=(64, 32))
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (2, FEATURES))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    arg_params, aux_params = mod.get_params()
    v1 = {"arg:" + k: v for k, v in arg_params.items()}
    v1.update({"aux:" + k: v for k, v in aux_params.items()})
    v2 = {k: nd.array(v.asnumpy() * 1.25 + 0.01) for k, v in v1.items()}
    v2_np = {k: v.asnumpy() for k, v in v2.items()}

    tmp = tempfile.mkdtemp(prefix="fleet_net_")
    fleet_dir = os.path.join(tmp, "fleet")
    os.makedirs(fleet_dir, exist_ok=True)
    sym_path = os.path.join(tmp, "net-symbol.json")
    with open(sym_path, "w") as fout:
        fout.write(net.tojson())
    v1_path = os.path.join(tmp, "net-v1.params")
    nd.save(v1_path, v1)
    v2_path = os.path.join(tmp, "net-v2.params")
    nd.save(v2_path, v2)
    spec_path = os.path.join(tmp, "fleet.json")
    with open(spec_path, "w") as fout:
        json.dump({"models": [{
            "name": "net", "symbol": sym_path, "params": v1_path,
            "input_shapes": {"data": [FEATURES]},
            "buckets": list(BUCKETS)}],
            "version": "v1", "max_delay_ms": MAX_DELAY_MS}, fout)

    # local batch-time reference for the latency bound
    rng = np.random.RandomState(11)
    xb = rng.rand(max(BUCKETS), FEATURES).astype("float32")
    ref_pred = mx.Predictor(net.tojson(),
                            {k: v.asnumpy() for k, v in v1.items()},
                            {"data": xb.shape})
    ref_pred.forward(data=xb)
    times = []
    for _ in range(20):
        t = time.perf_counter()
        ref_pred.forward(data=xb)
        times.append(time.perf_counter() - t)
    batch_ms = sorted(times)[len(times) // 2] * 1e3

    report = {"metric": "fleet_net_drill", "replicas": REPLICAS,
              "requests": N_REQUESTS, "concurrency": CONCURRENCY,
              "partition_s": PARTITION_S}

    # 1. the coordination plane: an embedded TCP KV
    kvsrv = TcpKVServer(port=0).start()
    kv_url = kvsrv.url
    report["kv_url"] = kv_url

    procs = []
    routers = []
    try:
        # 2. replicas, heartbeating over tcp://
        clients = []
        for i in range(REPLICAS):
            procs.append(spawn_replica(
                spec_path, i, BASE_PORT + i, fleet_dir,
                extra_env={"MXTPU_KV_URL": kv_url,
                           "JAX_PLATFORMS": "cpu"}))
            clients.append(HTTPReplicaClient("127.0.0.1",
                                             BASE_PORT + i))
        deadline = time.monotonic() + 300.0
        for i, client in enumerate(clients):
            _wait_http(client, procs[i], "replica %d" % i, deadline)

        # 3. two router front doors over the same KV + fleet
        for rid, port in zip(("r1", "r2"), ROUTER_PORTS):
            routers.append((rid, port,
                            _spawn_router(rid, port, kv_url,
                                          fleet_dir)))
        deadline = time.monotonic() + 120.0
        for rid, port, proc in routers:
            _wait_http(HTTPReplicaClient("127.0.0.1", port), proc,
                       "router %s" % rid, deadline)
        leader0 = _leader_port(report)
        report["first_leader_port"] = leader0

        fc = FleetClient(routers=["http://127.0.0.1:%d" % p
                                  for p in ROUTER_PORTS], timeout=60.0)
        x1 = rng.rand(1, FEATURES).astype("float32")
        rtts = []
        for _ in range(4 * REPLICAS):
            t = time.perf_counter()
            fc.predict("net", {"data": x1}, timeout=60.0)
            rtts.append((time.perf_counter() - t) * 1e3)
        rtt_ms = sorted(rtts)[len(rtts) // 2]

        partition_at = N_REQUESTS // 3
        cursor, lock = [0], threading.Lock()
        errors, lat_ms = [], []
        partition_fired = threading.Event()
        partition_over = threading.Event()
        kv_held_seen = []
        killed = threading.Event()
        kill_info = {}

        def do_partition():
            kvsrv.partition(PARTITION_S)
            partition_fired.set()
            t_end = time.monotonic() + PARTITION_S
            # sample router stats mid-partition: the leader must be
            # HOLDING (kv_held), not inventing deaths
            time.sleep(PARTITION_S / 2)
            for port in ROUTER_PORTS:
                try:
                    st = _router_stats(port, timeout=5.0)
                    kv_held_seen.append(
                        {"port": port, "kv_held": st.get("kv_held"),
                         "generation": st.get("generation"),
                         "states": sorted(
                             r["state"] for r in
                             st.get("replicas", {}).values())})
                except Exception:
                    pass
            time.sleep(max(0.0, t_end - time.monotonic()) + 1.0)
            partition_over.set()

        def do_kill():
            # only after the partition heals: the drill separates the
            # two faults so each assertion is attributable
            partition_over.wait(timeout=60.0)
            port = _leader_port(report)
            proc = next(p for rid, prt, p in routers if prt == port)
            kill_info["port"] = port
            proc.kill()                # SIGKILL, mid-whatever
            killed.set()

        def worker():
            while True:
                with lock:
                    i = cursor[0]
                    if i >= N_REQUESTS:
                        return
                    cursor[0] += 1
                if i == partition_at:
                    threading.Thread(target=do_partition,
                                     daemon=True).start()
                    threading.Thread(target=do_kill,
                                     daemon=True).start()
                t = time.perf_counter()
                try:
                    out = fc.predict("net", {"data": x1}, timeout=60.0)
                    assert out[0].shape == (1, 10), out[0].shape
                except Exception as exc:
                    errors.append(exc)
                    return
                lat_ms.append((time.perf_counter() - t) * 1e3)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(CONCURRENCY)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_s = time.perf_counter() - t0

        # the load may finish before the kill thread fires: keep the
        # client loop's invariants but let both faults land
        partition_over.wait(timeout=PARTITION_S + 60.0)
        killed.wait(timeout=60.0)

        # takeover: the surviving door must hold the lease
        survivor = next(prt for rid, prt, p in routers
                        if prt != kill_info.get("port"))
        takeover_deadline = time.monotonic() + 10 * LEASE_TTL_S
        st = None
        while time.monotonic() < takeover_deadline:
            try:
                st = _router_stats(survivor)
                if st.get("role") == "leader":
                    break
            except Exception:
                pass
            time.sleep(0.25)
        if not st or st.get("role") != "leader":
            fail("survivor on %d never took the lease: %s"
                 % (survivor, (st or {}).get("role")), report)

        # post-takeover traffic: aim the sticky cursor at the DEAD
        # door first so the address-failover path provably runs even
        # if the closed loop drained before the kill landed
        fc._idx = next(i for i, u in enumerate(fc.routers)
                       if u.endswith(":%d" % kill_info["port"]))
        for _ in range(5):
            fc.predict("net", {"data": x1}, timeout=60.0)

        # 4. swap-on-commit leg: publish the pointer, leader applies
        kvc = TcpKV(kvsrv.host, kvsrv.port, timeout_s=5.0)
        kvc.key_value_set(_SWAP_PTR_KEY, json.dumps(
            {"params": v2_path, "version": "v2"}, sort_keys=True))
        swap_deadline = time.monotonic() + 120.0
        skew = None
        while time.monotonic() < swap_deadline:
            st = _router_stats(survivor)
            skew = st.get("version_skew") or {}
            if sorted(skew.get("v2", [])) == list(range(REPLICAS)):
                break
            time.sleep(0.5)
        fleet_out = fc.predict("net", {"data": x1}, timeout=60.0)
        final_stats = _router_stats(survivor)
    finally:
        for rid, port, proc in routers:
            try:
                proc.terminate()
                proc.wait(timeout=10)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        for proc in procs:
            try:
                proc.terminate()
                proc.wait(timeout=10)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        kvsrv.stop()

    lat_sorted = sorted(lat_ms)
    p95 = lat_sorted[int(0.95 * (len(lat_sorted) - 1))] \
        if lat_sorted else None
    # closed-loop single-door tail + one failover/takeover allowance
    bound_ms = MAX_DELAY_MS + 2.0 * batch_ms \
        + 2.0 * CONCURRENCY * rtt_ms + 2e3 * LEASE_TTL_S
    led = elastic.read_ledger(path=fleet_ledger_path(fleet_dir))
    report.update({
        "value": round(len(lat_ms) / wall_s, 1) if wall_s else 0,
        "unit": "req/s",
        "wall_s": round(wall_s, 3),
        "completed": len(lat_ms),
        "errors": len(errors),
        "p95_ms": round(p95, 3) if p95 is not None else None,
        "p95_bound_ms": round(bound_ms, 3),
        "single_batch_ms": round(batch_ms, 3),
        "warm_rtt_ms": round(rtt_ms, 3),
        "client_failovers": fc.failovers,
        "killed_router_port": kill_info.get("port"),
        "survivor_port": survivor,
        "kv_held_samples": kv_held_seen,
        "takeovers": final_stats.get("takeovers"),
        "generation": final_stats.get("generation"),
        "version_skew": final_stats.get("version_skew"),
        "ledger": led,
    })

    if errors:
        fail("client-visible errors: %r (partition + router kill must "
             "be absorbed)" % errors[0], report)
    if len(lat_ms) != N_REQUESTS:
        fail("completed %d != %d requested"
             % (len(lat_ms), N_REQUESTS), report)
    if not partition_fired.is_set():
        fail("KV partition never fired", report)
    if not killed.is_set():
        fail("leader kill never fired", report)
    # zero false deaths: no replica died, so the ledger must carry no
    # replica_death verdict and the generation must never have moved
    if led and led.get("reason") == "replica_death":
        fail("KV partition fabricated a death verdict: %s" % (led,),
             report)
    if int(final_stats.get("generation") or 0) != 0:
        fail("generation %s moved with every replica alive"
             % final_stats.get("generation"), report)
    states = sorted(r["state"] for r in
                    (final_stats.get("replicas") or {}).values())
    if states != ["ready"] * REPLICAS:
        fail("replica states %s: all must be ready" % states, report)
    held = [s for s in kv_held_seen if s.get("kv_held")]
    if not held:
        fail("no router reported kv_held during the partition "
             "(samples: %s)" % kv_held_seen, report)
    if any(s["generation"] for s in kv_held_seen):
        fail("generation moved DURING the partition: %s"
             % kv_held_seen, report)
    if fc.failovers < 1:
        fail("client never failed over between front doors", report)
    if sorted((final_stats.get("version_skew") or {}).get("v2", [])) \
            != list(range(REPLICAS)):
        fail("swap-on-commit never converged: skew %s"
             % final_stats.get("version_skew"), report)
    ref = mx.Predictor(net.tojson(), v2_np,
                       {"data": x1.shape}).forward(data=x1)[0]
    if not np.array_equal(np.asarray(fleet_out[0]), np.asarray(ref)):
        fail("post-swap fleet output differs from local v2 predictor",
             report)
    if p95 is None or p95 > bound_ms:
        fail("p95 %.3f ms exceeds bound %.3f ms"
             % (p95 or -1, bound_ms), report)
    print(json.dumps(report, default=str), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
