"""Fleet serving drill: replica death + live weight hot-swap under load.

The acceptance run for docs/serving.md "Fleet" (wired as the CI
multi-process drill in tests/ci/run_test.sh TASK=serving).  Spawns
REPLICAS real replica processes (each its own ModelServer + AOT bucket
set, heartbeating into the fleet FileKV) behind an in-process
FleetRouter, then — under sustained closed-loop load:

1. **Kill a replica** (SIGKILL, no warning) at ~1/3 of the run.  The
   router must absorb it: transport failures fail over to survivors,
   the client-visible error count stays ZERO, and the fleet ledger
   gains a generation-stamped ``replica_death`` shrink verdict whose
   members exclude the killed index.
2. **Hot-swap weights** (``router.swap`` to perturbed v2 params) at
   ~2/3 of the run, WITHOUT drain.  Each surviving replica re-binds
   through the program registry: the per-replica ``lowerings`` delta
   must be 0, and the post-run version-skew map must show every
   survivor on v2.
3. **p95 SLO gate** — client-observed p95 (HTTP round trip through
   the router) <= admission window + 2x measured batch time + the
   closed-loop single-server queueing term: with a kill AND a swap in
   the window the fleet briefly degrades to ONE ready replica, so the
   tail request can find every other client queued ahead of it
   (CONCURRENCY warm round trips, x2 for the contended CI host).
4. **Bit-identity** — post-swap fleet outputs match a local Predictor
   over the v2 params exactly (the swap moved WEIGHTS, not numerics).

Prints one JSON line with every figure.  Exit codes: 0 OK, 4 = an
expectation failed.

Run:  JAX_PLATFORMS=cpu python tests/nightly/serve_load_fleet.py
"""
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import mxnet_tpu as mx                                  # noqa: E402
from mxnet_tpu import ndarray as nd                     # noqa: E402
from mxnet_tpu.resilience import elastic                # noqa: E402
from mxnet_tpu.serving.fleet import (                   # noqa: E402
    fleet_ledger_path, launch_fleet)

N_REQUESTS = int(os.environ.get("FLEET_LOAD_REQUESTS", "300"))
CONCURRENCY = int(os.environ.get("FLEET_LOAD_CONCURRENCY", "12"))
MAX_DELAY_MS = float(os.environ.get("FLEET_LOAD_MAX_DELAY_MS", "25"))
REPLICAS = int(os.environ.get("FLEET_LOAD_REPLICAS", "3"))
BASE_PORT = int(os.environ.get("FLEET_LOAD_BASE_PORT", "8961"))
KILL_INDEX = REPLICAS - 1
FEATURES = 64
BUCKETS = (1, 8)


def fail(msg, report):
    report["failed"] = msg
    print(json.dumps(report, default=str), flush=True)
    print("serve_load_fleet FAILED: %s" % msg, file=sys.stderr,
          flush=True)
    os._exit(4)


def main():
    net = mx.models.get_mlp(num_classes=10, hidden=(64, 32))
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (2, FEATURES))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    arg_params, aux_params = mod.get_params()
    v1 = {"arg:" + k: v for k, v in arg_params.items()}
    v1.update({"aux:" + k: v for k, v in aux_params.items()})
    v2 = {k: nd.array(v.asnumpy() * 1.25 + 0.01) for k, v in v1.items()}
    v2_np = {k: v.asnumpy() for k, v in v2.items()}

    tmp = tempfile.mkdtemp(prefix="fleet_drill_")
    sym_path = os.path.join(tmp, "net-symbol.json")
    with open(sym_path, "w") as fout:
        fout.write(net.tojson())
    v1_path = os.path.join(tmp, "net-v1.params")
    nd.save(v1_path, v1)
    v2_path = os.path.join(tmp, "net-v2.params")
    nd.save(v2_path, v2)
    spec_path = os.path.join(tmp, "fleet.json")
    with open(spec_path, "w") as fout:
        json.dump({"models": [{
            "name": "net", "symbol": sym_path, "params": v1_path,
            "input_shapes": {"data": [FEATURES]},
            "buckets": list(BUCKETS)}],
            "version": "v1", "max_delay_ms": MAX_DELAY_MS}, fout)

    # local batch-time reference for the latency bound (same model,
    # largest bucket, this host)
    rng = np.random.RandomState(11)
    xb = rng.rand(max(BUCKETS), FEATURES).astype("float32")
    ref_pred = mx.Predictor(net.tojson(),
                            {k: v.asnumpy() for k, v in v1.items()},
                            {"data": xb.shape})
    ref_pred.forward(data=xb)
    times = []
    for _ in range(20):
        t = time.perf_counter()
        ref_pred.forward(data=xb)
        times.append(time.perf_counter() - t)
    batch_ms = sorted(times)[len(times) // 2] * 1e3

    # respawn off: the drill asserts the SHRINK verdict is the final
    # ledger state (a grow verdict would supersede its member list)
    router = launch_fleet(spec_path, n_replicas=REPLICAS,
                          directory=os.path.join(tmp, "fleet"),
                          base_port=BASE_PORT, respawn=False,
                          startup_timeout_s=300.0)
    report = {"metric": "fleet_drill", "replicas": REPLICAS,
              "requests": N_REQUESTS, "concurrency": CONCURRENCY}
    try:
        x1 = rng.rand(1, FEATURES).astype("float32")
        # warm transport + every replica's pipeline (untimed), and
        # measure the warm single-request round trip
        rtts = []
        for _ in range(4 * REPLICAS):
            t = time.perf_counter()
            router.predict("net", {"data": x1}, timeout=60.0)
            rtts.append((time.perf_counter() - t) * 1e3)
        rtt_ms = sorted(rtts)[len(rtts) // 2]

        kill_at = N_REQUESTS // 3
        swap_at = (2 * N_REQUESTS) // 3
        cursor, lock = [0], threading.Lock()
        errors, lat_ms = [], []
        killed = threading.Event()
        swap_result = {}
        swap_err = []

        def do_kill():
            rep = router._replicas[KILL_INDEX]
            rep.proc.kill()        # SIGKILL: no drain, no goodbye
            killed.set()

        def do_swap():
            try:
                swap_result.update(router.swap(v2_path, version="v2"))
            except Exception as exc:       # pragma: no cover
                swap_err.append(exc)

        def worker():
            while True:
                with lock:
                    i = cursor[0]
                    if i >= N_REQUESTS:
                        return
                    cursor[0] += 1
                if i == kill_at:
                    threading.Thread(target=do_kill,
                                     daemon=True).start()
                if i == swap_at:
                    threading.Thread(target=do_swap,
                                     daemon=True).start()
                t = time.perf_counter()
                try:
                    out = router.predict("net", {"data": x1},
                                         timeout=60.0)
                    assert out[0].shape == (1, 10), out[0].shape
                except Exception as exc:
                    errors.append(exc)
                    return
                lat_ms.append((time.perf_counter() - t) * 1e3)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(CONCURRENCY)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_s = time.perf_counter() - t0

        # post-swap bit-identity through the fleet
        fleet_out = router.predict("net", {"data": x1}, timeout=60.0)
        st = router.stats()
    finally:
        router.close(drain=False)

    lat_sorted = sorted(lat_ms)
    p95 = lat_sorted[int(0.95 * (len(lat_sorted) - 1))] \
        if lat_sorted else None
    # degraded-window tail bound: one replica killed + one rebinding
    # leaves a single ready server, so the worst request queues behind
    # every other closed-loop client (see module docstring, gate 3)
    bound_ms = MAX_DELAY_MS + 2.0 * batch_ms \
        + 2.0 * CONCURRENCY * rtt_ms
    swap_lowerings = {str(i): r.get("lowerings")
                      for i, r in (swap_result.get("replicas")
                                   or {}).items()
                      if isinstance(r, dict) and "error" not in r}
    led = elastic.read_ledger(
        path=fleet_ledger_path(os.path.join(tmp, "fleet")))
    report.update({
        "value": round(len(lat_ms) / wall_s, 1) if wall_s else 0,
        "unit": "req/s",
        "wall_s": round(wall_s, 3),
        "completed": len(lat_ms),
        "errors": len(errors),
        "p95_ms": round(p95, 3) if p95 is not None else None,
        "p95_bound_ms": round(bound_ms, 3),
        "single_batch_ms": round(batch_ms, 3),
        "warm_rtt_ms": round(rtt_ms, 3),
        "killed_replica": KILL_INDEX,
        "swap_lowerings": swap_lowerings,
        "swap_pause_ms": swap_result.get("swap_pause_ms"),
        "version_skew": st.get("version_skew"),
        "generation": st.get("generation"),
        "ledger": led,
        "router": {k: st.get(k) for k in
                   ("requests", "retries", "failed", "rejected")},
    })

    if errors:
        fail("client-visible errors: %r (failover must absorb the "
             "kill)" % errors[0], report)
    if len(lat_ms) != N_REQUESTS:
        fail("completed %d != %d requested"
             % (len(lat_ms), N_REQUESTS), report)
    if not killed.is_set():
        fail("kill never fired", report)
    if swap_err or not swap_result:
        fail("swap failed: %r" % (swap_err or "never ran"), report)
    survivors = sorted(i for i in range(REPLICAS) if i != KILL_INDEX)
    bad_swaps = {i: r for i, r in
                 (swap_result.get("replicas") or {}).items()
                 if not isinstance(r, dict) or "error" in r}
    if bad_swaps:
        fail("per-replica swap errors: %s" % bad_swaps, report)
    if any(v != 0 for v in swap_lowerings.values()):
        fail("swap performed new lowerings: %s (must re-bind through "
             "the program registry)" % swap_lowerings, report)
    if st.get("version_skew", {}).get("v2") != survivors:
        fail("version skew %s: survivors %s must all serve v2"
             % (st.get("version_skew"), survivors), report)
    if p95 is None or p95 > bound_ms:
        fail("p95 %.3f ms exceeds bound %.3f ms with kill+swap in "
             "window" % (p95 or -1, bound_ms), report)
    if not led or led.get("reason") != "replica_death":
        fail("ledger %s: want a replica_death shrink verdict" % (led,),
             report)
    if led.get("generation", 0) < 1:
        fail("ledger generation %s never advanced" % led.get(
            "generation"), report)
    if KILL_INDEX in (led.get("members") or []):
        fail("ledger members %s still include killed replica %d"
             % (led.get("members"), KILL_INDEX), report)
    ref = mx.Predictor(net.tojson(), v2_np,
                       {"data": x1.shape}).forward(data=x1)[0]
    if not np.array_equal(np.asarray(fleet_out[0]), np.asarray(ref)):
        fail("post-swap fleet output differs from local v2 predictor "
             "(swap must be bit-identical)", report)
    print(json.dumps(report, default=str), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
