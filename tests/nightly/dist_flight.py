"""2-worker hung-collective drill: kill one rank mid-allreduce, prove
the survivor's flight dump names the hung (op, seq) and the absent rank.

Acceptance (ISSUE 10): with the flight recorder always on, a pod that
wedges because a peer died mid-collective must leave a postmortem that
answers "which collective, which rank" — even though the collective
itself can never complete.  The drill stages exactly that:

1. Both ranks complete ``ROUNDS`` synchronous ``kv.push`` allreduces
   (sequence numbers ``0..ROUNDS-1`` retire from the pending ledger;
   the ``collective`` events carry ``seq`` so ``mxtrace`` can stitch
   cross-rank flow arrows from this run's JSONLs).
2. Rank 1 signals "dying" through the coordination KV, flushes its
   telemetry, and exits without participating further.
3. Rank 0 pushes again — allreduce ``seq=ROUNDS`` can never complete.
   The push runs under ``run_with_timeout`` (armed LONGER than the
   heartbeat staleness window, so the liveness probe has named rank 1
   dead by the time the watchdog fires); the timeout's ``_emit_fault``
   seam dumps the flight recorder.
4. Rank 0 verifies its own dump: ``reason=watchdog_timeout``, a
   pending ``allreduce`` entry with ``seq=ROUNDS``, ``absent_ranks``
   containing rank 1, and a ring tail of recent events.

Exit codes: 0 OK, 4 = a flight-recorder expectation failed.

Run (tests/test_observability.py wraps this):
    python tools/launch.py -n 2 --launcher local \
        python tests/nightly/dist_flight.py
"""
import glob
import json
import os
import sys
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import observability as obs

ROUNDS = 3
#: watchdog for the doomed push: must exceed the heartbeat staleness
#: window (5 * kvstore._HB_INTERVAL = 10s) so dead_nodes() can already
#: name the dead peer when the dump is written
HANG_TIMEOUT_S = 13.0


def fail(rank, msg):
    print("rank %d FAILED: %s" % (rank, msg), flush=True)
    os._exit(4)


def main():
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    if nw != 2:
        fail(rank, "drill needs exactly 2 workers, got %d" % nw)

    val = mx.nd.ones((64,)) * (rank + 1)
    kv.init("w", val)
    out = mx.nd.zeros((64,))
    for _ in range(ROUNDS):
        kv.push("w", mx.nd.ones((64,)) * (rank + 1))
        kv.pull("w", out=out)
    if not np.all(np.isfinite(out.asnumpy())):
        fail(rank, "warmup pushes produced non-finite values")
    pend = obs.flight.pending_collectives()
    if pend:
        fail(rank, "completed collectives still pending: %r" % (pend,))

    from mxnet_tpu.kvstore import _dist_client
    client = _dist_client()
    if client is None:
        fail(rank, "no coordination-service client in drill env")

    if rank == 1:
        # die "mid-collective": rank 0 is about to launch seq=ROUNDS,
        # this rank never will.  Flush telemetry first so mxtrace gets
        # both ranks' completed-collective records, then vanish.
        obs.flush()
        client.key_value_set("drill_flight/dying", "1")
        print("rank 1 exiting without seq=%d" % ROUNDS, flush=True)
        sys.stdout.flush()
        os._exit(0)

    # ---- rank 0: the survivor ----------------------------------------
    client.blocking_key_value_get("drill_flight/dying", 60_000)
    from mxnet_tpu.resilience import run_with_timeout, ResilienceError
    t0 = time.time()
    try:
        # MXTPU_STEP_TIMEOUT_S is unset, so the kvstore's own inner
        # timeouts stay long (600s) and THIS watchdog is the one that
        # fires — its _emit_fault seam writes the flight dump
        run_with_timeout(
            lambda: kv.push("w", mx.nd.ones((64,))), HANG_TIMEOUT_S,
            phase="drill_hung_push", step=ROUNDS)
        fail(rank, "push completed against a dead peer")
    except ResilienceError:
        pass
    waited = time.time() - t0
    if waited < 10.0:
        fail(rank, "watchdog fired after %.1fs — before the heartbeat "
                   "staleness window; absent_ranks would be a guess"
             % waited)

    dumps = sorted(glob.glob(os.path.join(
        os.environ["MXTPU_TELEMETRY_DIR"], "flight-rank00000-*.json")))
    if not dumps:
        fail(rank, "watchdog fired but no flight dump was written")
    with open(dumps[-1]) as fin:
        doc = json.load(fin)
    if doc.get("reason") != "watchdog_timeout":
        fail(rank, "dump reason %r, want watchdog_timeout"
             % (doc.get("reason"),))
    pend = {(e.get("op"), e.get("seq"))
            for e in doc.get("pending_collectives") or ()}
    if ("allreduce", ROUNDS) not in pend:
        fail(rank, "pending ledger %r does not name allreduce seq=%d"
             % (pend, ROUNDS))
    if 1 not in (doc.get("absent_ranks") or ()):
        fail(rank, "absent_ranks %r does not name dead rank 1"
             % (doc.get("absent_ranks"),))
    if not doc.get("events"):
        fail(rank, "dump carries no ring events")
    seqs = doc.get("collective_seq") or {}
    if seqs.get("allreduce") != ROUNDS + 1:
        fail(rank, "collective_seq %r, want allreduce=%d"
             % (seqs, ROUNDS + 1))
    obs.flush()
    print("survivor dump names allreduce seq=%d, absent rank 1 (%s)"
          % (ROUNDS, os.path.basename(dumps[-1])), flush=True)
    print("rank %d FLIGHT DRILL OK" % rank, flush=True)
    sys.stdout.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
