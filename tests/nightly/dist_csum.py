"""2-worker cluster-wide-decision drill (ISSUE 6 satellite).

The kvstore makes two pod-wide protocol choices through
``@collective_seam`` functions — ``_decide_csum_path`` (XLA collective
sum vs coordination-KV fallback) and ``_decide_barrier_path`` (XLA
device fence vs ``wait_at_barrier`` RPC).  Each is decided ONCE by
rank 0 and published through the coordination KV; a per-rank decision
is exactly the pre-fix PR-3 bug snapshotted in
``tests/fixtures/divergence/per_rank_barrier_probe.py``.

This drill runs 2 real processes and asserts the contract end to end:

1. a gradient allreduce returns the true cross-worker sum on both
   ranks (so the chosen path actually works);
2. every rank's adopted ``_CSUM_CACHE`` verdict equals the one rank 0
   published under ``mxtpu_csum/enabled``;
3. both ranks adopted the SAME barrier implementation
   (``_BARRIER_STATE['xla_ok']``) and pass a ``global_barrier``;
4. the verdict pair is cross-published per rank and compared, so a
   divergence fails loudly instead of deadlocking.

Exit codes: 0 OK, 4 = a verdict/value expectation failed.

Run (tests/test_kvstore.py wraps this):
    python tools/launch.py -n 2 --launcher local \
        python tests/nightly/dist_csum.py
"""
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import kvstore as kvmod


def fail(rank, msg):
    print("rank %d FAILED: %s" % (rank, msg), flush=True)
    os._exit(4)


def main():
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers

    # 1. the allreduce works: each rank contributes ones*(rank+1)
    out = np.asarray(kv._allreduce(np.ones((4, 3), np.float32)
                                   * (rank + 1)))
    want = sum(range(1, nw + 1))
    if not np.allclose(out, want):
        fail(rank, "allreduce sum %r != %r" % (out.ravel()[0], want))

    # 2. the adopted verdict is the published one
    verdict = kvmod._CSUM_CACHE.get("enabled")
    if verdict is None:
        fail(rank, "no csum verdict cached after an allreduce")
    client = kvmod._dist_client()
    if client is None:
        fail(rank, "no coordination client in a 2-process run")
    published = client.blocking_key_value_get("mxtpu_csum/enabled",
                                              60_000)
    if published != ("1" if verdict else "0"):
        fail(rank, "adopted csum verdict %r but rank 0 published %r"
             % (verdict, published))

    # 3. one barrier implementation pod-wide, and it actually fences
    kvmod.global_barrier("csum_drill")
    bar = kvmod._BARRIER_STATE.get("xla_ok")
    if bar is None:
        fail(rank, "no barrier-path verdict after global_barrier")

    # 4. cross-check the (csum, barrier) verdict pair across ranks
    mine = "%d/%d" % (int(verdict), int(bar))
    client.key_value_set("mxtpu_csum_drill/%d" % rank, mine,
                         allow_overwrite=True)
    for peer in range(nw):
        theirs = client.blocking_key_value_get(
            "mxtpu_csum_drill/%d" % peer, 60_000)
        if theirs != mine:
            fail(rank, "rank %d adopted %s but rank %d adopted %s"
                 % (rank, mine, peer, theirs))

    print("rank %d verdicts csum=%s barrier=%s OK"
          % (rank, published, int(bar)), flush=True)
    os._exit(0)


if __name__ == "__main__":
    main()
