"""SLO-engine acceptance drill: live /metrics + burn-rate alerting.

The CI leg for ISSUE 19 (wired in tests/ci/run_test.sh
TASK=observability), all on the virtual CPU mesh:

1. **Exposition smoke** — a real mxserve HTTP door in-process; two
   ``GET /metrics`` scrapes around a burst of traffic must parse as
   Prometheus text and every ``_total`` counter must be monotone
   non-decreasing (requests_total strictly increases).
2. **Clean control** — bursty open-loop traffic (serve_bench's
   arrival shaper) against a healthy server, the SLO engine
   evaluating continuously: **zero** alerts, **zero** scale
   recommendations.  A drill that only proves the alert fires proves
   nothing — the control proves it stays quiet.
3. **Burn-rate drill** — the same traffic with an injected latency
   fault (``kind=slow:seam=serve_dispatch`` via the standard
   MXTPU_FAULT_SPEC seams): a **page-tier** ``slo_alert`` must fire
   within the fast window (+ grace) of fault onset, and exactly the
   fault run must write a generation-stamped ``recommend_grow``
   under ``mxtpu_slo/`` in the (fake) coordination KV.

Prints one JSON line with every figure.  Exit codes: 0 OK, 4 = an
expectation failed.

Run:  JAX_PLATFORMS=cpu python tests/nightly/serve_slo_drill.py
"""
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "tools"))

import mxnet_tpu as mx                                   # noqa: E402
from mxnet_tpu.resilience import faultinject             # noqa: E402
from mxnet_tpu.serving import ModelServer                # noqa: E402
from mxnet_tpu.observability import metrics as _metrics  # noqa: E402
from mxnet_tpu.observability.sloengine import (          # noqa: E402
    SLO_PREFIX, SloEngine, parse_specs)
from serve_bench import arrival_offsets                  # noqa: E402

FEATURES = 32
RATE = float(os.environ.get("SLO_DRILL_RATE", "40"))
PHASE_S = float(os.environ.get("SLO_DRILL_PHASE_S", "6"))
#: SLO windows scaled for CI wall-clock: fast=2s, slow=4s pair
SPEC = ("metric=mxtpu_serve_latency_ms:target=100:budget=0.02:"
        "fast=2:slow=4:tfast=4:tslow=8:hold=2:min_n=8")
SLOW_S = 0.25          # injected dispatch latency — 2.5x the target


def fail(msg, report):
    report["failed"] = msg
    print(json.dumps(report, default=str), flush=True)
    print("serve_slo_drill FAILED: %s" % msg, file=sys.stderr,
          flush=True)
    os._exit(4)


class FakeKV(object):
    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        self.store[key] = value


def build_server():
    net = mx.models.get_mlp(num_classes=10, hidden=(32, 32))
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (2, FEATURES))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    arg_params, aux_params = mod.get_params()
    params = {"arg:" + k: v for k, v in arg_params.items()}
    params.update({"aux:" + k: v for k, v in aux_params.items()})
    srv = ModelServer(max_delay_ms=5.0)
    srv.add_model("toy", net.tojson(), params,
                  {"data": (FEATURES,)}, buckets=(1, 8))
    return srv


def drive_bursty(srv, x, seconds, seed):
    """Open-loop bursty arrivals at RATE req/s for ``seconds``; every
    completed batch feeds the live registry via serving telemetry."""
    offs = arrival_offsets("bursty", RATE, int(RATE * seconds), seed,
                           param=2.0)
    futs, errs = [], [0]
    t0 = time.perf_counter()
    for off in offs:
        delay = t0 + off - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        try:
            futs.append(srv.submit("toy", {"data": x}))
        except Exception:
            errs[0] += 1
    for f in futs:
        try:
            f.result(timeout=60)
        except Exception:
            errs[0] += 1
    return len(futs), errs[0]


def main():
    report = {"drill": "serve_slo"}
    srv = build_server()
    rng = np.random.RandomState(3)
    x = rng.rand(1, FEATURES).astype("float32")
    srv.submit("toy", {"data": x}).result(timeout=60)    # warm compile

    # -- 1. exposition smoke over a real HTTP door ---------------------
    from http.server import ThreadingHTTPServer
    import mxserve
    httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                mxserve.make_handler(srv))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def scrape():
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/metrics" % port, timeout=10) as r:
            ctype = r.headers.get("Content-Type", "")
            return r.read().decode(), ctype

    text1, ctype = scrape()
    if not ctype.startswith("text/plain"):
        fail("bad /metrics content-type %r" % ctype, report)
    rows1 = _metrics.parse_prometheus(text1)
    for _ in range(20):
        srv.submit("toy", {"data": x}).result(timeout=60)
    text2, _ = scrape()
    rows2 = _metrics.parse_prometheus(text2)
    c1 = {(n, tuple(sorted(l.items()))): v for n, l, v in rows1
          if n.endswith("_total")}
    c2 = {(n, tuple(sorted(l.items()))): v for n, l, v in rows2
          if n.endswith("_total")}
    if not c1:
        fail("no counters in /metrics", report)
    for key, v1 in c1.items():
        if c2.get(key, 0) < v1:
            fail("counter %s went backwards: %s -> %s"
                 % (key, v1, c2.get(key)), report)
    req_key = ("mxtpu_serve_requests_total", ())
    if c2[req_key] < c1[req_key] + 20:
        fail("requests_total did not advance across scrapes", report)
    report["scrape_samples"] = len(rows2)
    report["requests_total"] = c2[req_key]

    # -- 2. clean control: bursty load, engine quiet -------------------
    _metrics.reset_registry()
    kv = FakeKV()
    eng = SloEngine(specs=parse_specs(SPEC), kv=kv, source="drill")
    alerts = []
    stop = threading.Event()

    def poll():
        while not stop.wait(0.25):
            alerts.extend(eng.evaluate())

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    n, errs = drive_bursty(srv, x, PHASE_S, seed=7)
    time.sleep(1.0)                       # let the engine see the tail
    stop.set()
    poller.join(timeout=5)
    report["control_requests"] = n
    report["control_errors"] = errs
    report["control_alerts"] = len(alerts)
    if alerts:
        fail("clean control raised %d alert(s): %r"
             % (len(alerts), alerts[0]), report)
    # sustained near-zero burn legitimately writes recommend_shrink
    # (the fleet IS oversized for a drill's trickle) — but a healthy
    # run must never recommend growth
    ctl_recos = [json.loads(v) for k, v in kv.store.items()
                 if k.startswith(SLO_PREFIX + "reco-")]
    report["control_shrinks"] = len(
        [r for r in ctl_recos if r["action"] == "recommend_shrink"])
    if any(r["action"] == "recommend_grow" for r in ctl_recos):
        fail("clean control recommended growth: %s"
             % ctl_recos, report)

    # -- 3. fault run: injected latency must page + recommend_grow ----
    _metrics.reset_registry()
    kv = FakeKV()
    eng = SloEngine(specs=parse_specs(SPEC), kv=kv, source="drill")
    os.environ["MXTPU_FAULT_SPEC"] = (
        "kind=slow:seam=serve_dispatch:seconds=%g:sticky=1" % SLOW_S)
    faultinject.reset()
    alerts = []
    stop = threading.Event()
    fault_t0 = time.perf_counter()

    def poll2():
        while not stop.wait(0.25):
            for a in eng.evaluate():
                a["_seen_s"] = time.perf_counter() - fault_t0
                alerts.append(a)

    poller = threading.Thread(target=poll2, daemon=True)
    poller.start()
    n, errs = drive_bursty(srv, x, PHASE_S, seed=11)
    time.sleep(1.0)
    stop.set()
    poller.join(timeout=5)
    os.environ.pop("MXTPU_FAULT_SPEC", None)
    faultinject.reset()

    pages = [a for a in alerts
             if a["tier"] == "page" and a["edge"] == "fire"]
    report["fault_requests"] = n
    report["fault_errors"] = errs
    report["fault_alerts"] = len(alerts)
    report["page_fires"] = len(pages)
    if not pages:
        fail("fault run raised no page-tier alert", report)
    # "within the fast window": the page must land within slow + fast
    # + poll grace of fault onset (the slow window has to fill first)
    first_s = pages[0]["_seen_s"]
    report["page_latency_s"] = round(first_s, 2)
    budget_s = 4.0 + 2.0 + 2.0
    if first_s > budget_s:
        fail("page fired %.1fs after onset (budget %.1fs)"
             % (first_s, budget_s), report)
    recos = [json.loads(v) for k, v in kv.store.items()
             if k.startswith(SLO_PREFIX + "reco-")]
    grows = [r for r in recos if r["action"] == "recommend_grow"]
    report["recommend_grow"] = len(grows)
    if len(grows) != 1:
        fail("expected exactly one recommend_grow, got %d"
             % len(grows), report)
    if SLO_PREFIX + "latest" not in kv.store:
        fail("mxtpu_slo/latest not written", report)
    if grows[0]["gen"] != 1 or grows[0]["metric"] != \
            "mxtpu_serve_latency_ms":
        fail("malformed recommendation: %r" % grows[0], report)

    srv.close()
    httpd.shutdown()
    report["ok"] = True
    print(json.dumps(report, default=str), flush=True)


if __name__ == "__main__":
    main()
