"""Shrink/grow elasticity drill: kill one worker -> agreed re-mesh ->
resharded resume -> grow back (docs/resilience.md "Elasticity").

Run under the elastic supervise loop (the wrapper in
tests/test_resilience.py does this):

    python tools/launch.py -n 3 --elastic --min-world 2 \
        --elastic-dir <dir> --max-restarts 4 \
        python tests/nightly/dist_elastic.py

One launch covers the whole timeline; the script keys its behavior off
the generation the launcher stamped into the environment:

  generation 0 (world 3): epochs 0,1 checkpoint as steps 1,2.  After
      step 2 commits the victim (MXTPU_DRILL_KILL, default rank 2 at
      epoch 1) drops the capacity file to 2 and dies without goodbye.
      The post-epoch agreement round sees a still-fresh heartbeat and
      publishes "no verdict"; epoch 2's first allreduce then wedges on
      the dead peer, the kvstore watchdog aborts it within
      MXTPU_STEP_TIMEOUT_S, and the survivors confirm the death in a
      ``recover-2`` agreement round -> shrink verdict (generation 1,
      world 2) -> EXIT_RESTART.
  generation 1 (world 2): resumes from step 2, re-partitions the SAME
      seeded epoch-2 batch permutation across 2 parts, trains epoch 2
      (step 3).  MXTPU_DRILL_GROW (default: capacity back to 3 at
      epoch 2) raises the capacity signal; the post-epoch round
      proposes the grow verdict (generation 2, world 3) -> restart.
  generation 2 (world 3): resumes from step 3, trains epochs 3,4
      (steps 4,5), polls find nothing to change, exits 0 -- which ends
      the supervise loop.

Reference mode (MXTPU_ELASTIC_REFERENCE=1 + MXTPU_RESUME_STEP=N +
MXTPU_STOP_EPOCH=M): restore exactly step N, train epochs N..M-1 with
no polls/kills/checkpoint writes and record the loss trajectory --
the wrapper launches one per transition and asserts the elastic run's
post-transition losses are identical (the agreement protocol must not
perturb the math; training is deterministic end-to-end: seeded init,
seeded per-epoch partition, rank-ordered KV allreduce).

Warm mode (MXTPU_WARM_REMESH=1): every stable point also host-snapshots
the param tree into the handoff area (own copy + off-host buddy), the
victim burns its whole simulated host (hotstate.simulate_host_loss) on
the way down, and each resume tries hotstate.warm_resume first — the
checkpoint manager is only the fallback rung.  The resume transition
event carries path="warm"/"cold" (+ fallback_reason), so the wrapper
can assert the warm run never read a checkpoint and still produced
bit-identical losses.  MXTPU_DRILL_EPOCHS overrides the epoch count
(the corrupt-shard drill runs a shortened 3-epoch timeline).

Artifacts under MXTPU_ELASTIC_DIR: ``losses-elastic.jsonl`` (rank 0,
one line per finished epoch, appended across incarnations),
``losses-ref-w<W>-s<N>.jsonl`` (reference runs), and
``part-g<G>-e<E>-r<R>.json`` (the sample indices each rank actually
drew -- the wrapper asserts each completed epoch's parts tile the
dataset exactly).

Exit codes: 0 done, 3 restart signal (re-mesh agreed), 4 drill
assertion failure.
"""
import json
import os
import sys

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.resilience import elastic, hotstate

TOTAL_EPOCHS = int(os.environ.get("MXTPU_DRILL_EPOCHS", "5"))
BATCH = 20
DATA_SEED = 11          # seeded shuffle: batch order = f(seed, epoch)
INIT_SEED = 5           # rank-uniform init (np global RNG feeds Uniform)
DEAD_TIMEOUT = 6.0


def build_data():
    rng = np.random.RandomState(7)     # every rank builds the full set;
    X = rng.randn(240, 16).astype(np.float32)   # the iterator partitions
    w = rng.randn(16)
    y = (X @ w > 0).astype(np.float32)
    return X, y


def tree_of(mod):
    args, auxs = mod.get_params()
    return {"args": {k: v.asnumpy() for k, v in args.items()},
            "aux": {k: v.asnumpy() for k, v in auxs.items()}}


def abstract_tree_of(mod):
    args, auxs = mod.get_params()
    return {"args": {k: np.zeros_like(v.asnumpy()) for k, v in args.items()},
            "aux": {k: np.zeros_like(v.asnumpy()) for k, v in auxs.items()}}


def load_tree(mod, tree):
    mod.set_params({k: mx.nd.array(v) for k, v in tree["args"].items()},
                   {k: mx.nd.array(v) for k, v in tree["aux"].items()})


def eval_loss(mod, eval_it):
    losses = []
    for batch in eval_it:
        mod.forward(batch, is_train=False)
        p = mod.get_outputs()[0].asnumpy()
        lbl = batch.label[0].asnumpy().astype(int)
        losses.append(-np.log(p[np.arange(len(lbl)), lbl] + 1e-8).mean())
    eval_it.reset()
    return float(np.mean(losses))


def _spec(name, default):
    """'a:b:c' -> (a, b, c) ints, or None when set to empty."""
    raw = os.environ.get(name, default)
    if not raw:
        return None
    return tuple(int(p) for p in raw.split(":"))


def _write_capacity(value):
    with open(elastic.capacity_path(), "w") as f:
        f.write("%d\n" % value)


def _record_loss(path, gen, world, epoch, step, loss):
    with open(path, "a") as f:
        f.write(json.dumps({"generation": gen, "world": world,
                            "epoch": epoch, "step": step,
                            "loss": loss}, sort_keys=True) + "\n")


def _record_partition(edir, gen, epoch, rank, world, idx):
    path = os.path.join(edir, "part-g%d-e%03d-r%02d.json" % (gen, epoch,
                                                             rank))
    with open(path, "w") as f:
        json.dump({"generation": gen, "epoch": epoch, "rank": rank,
                   "world": world,
                   "indices": sorted(int(i) for i in idx)}, f)


def main():
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    gen = elastic.generation()
    reference = os.environ.get("MXTPU_ELASTIC_REFERENCE") == "1"
    edir = elastic.elastic_dir()
    os.makedirs(edir, exist_ok=True)
    ckptdir = os.path.join(edir, "ckpt")

    X, y = build_data()
    train = mx.io.NDArrayIter(X, y, batch_size=BATCH, shuffle=True,
                              seed=DATA_SEED, num_parts=nw,
                              part_index=rank)
    # same batch size as the bound training shapes (Module binds once)
    eval_it = mx.io.NDArrayIter(X, y, batch_size=BATCH)

    net = mx.models.get_mlp(num_classes=2, hidden=(16,))
    mod = mx.mod.Module(net, context=mx.context.cpu())
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    np.random.seed(INIT_SEED)            # rank-uniform starting params
    mod.init_params(mx.init.Uniform(0.1))

    # keep=0: the reference runs restore intermediate steps later
    mgr = mx.resilience.CheckpointManager(ckptdir, keep=0,
                                          payload_format="host")
    abstract = abstract_tree_of(mod)
    if reference:
        step = int(os.environ["MXTPU_RESUME_STEP"])
        tree, step = mgr.restore(abstract, step=step)
        load_tree(mod, tree)
        start_epoch, stop_epoch = step, int(os.environ["MXTPU_STOP_EPOCH"])
        loss_path = os.path.join(edir,
                                 "losses-ref-w%d-s%d.jsonl" % (nw, step))
    else:
        # warm rung first (host-memory handoff, no checkpoint reads),
        # checkpoint rung on any HotStateUnavailable — the ladder the
        # docs promise.  Both rungs land on the same committed step.
        got, resume_path, fallback_reason = None, "cold", None
        if hotstate.warm_enabled():
            try:
                tree, step, _meta = hotstate.warm_resume(abstract, kv=kv)
                got, resume_path = (tree, step), "warm"
            except hotstate.HotStateUnavailable as cold:
                fallback_reason = cold.reason
        if got is None:
            got = mgr.auto_resume(abstract)
        if got is not None:
            load_tree(mod, got[0])
        start_epoch = 0 if got is None else got[1]
        stop_epoch = TOTAL_EPOCHS
        loss_path = os.path.join(edir, "losses-elastic.jsonl")
        elastic.emit_transition("resume", step=start_epoch, world_size=nw,
                                fresh=got is None, path=resume_path,
                                fallback_reason=fallback_reason)
        print("rank %d gen %d world %d: %s at epoch %d (path=%s%s)" % (
            rank, gen, nw,
            "fresh start" if got is None else "resumed step %d" % got[1],
            start_epoch, resume_path,
            " fallback=%s" % fallback_reason if fallback_reason else ""),
            flush=True)

    mod.init_optimizer(kvstore=kv, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.3})

    kill = _spec("MXTPU_DRILL_KILL", "0:1:2")    # gen:epoch:rank
    grow = _spec("MXTPU_DRILL_GROW", "1:2:3")    # gen:epoch:capacity

    for epoch in range(start_epoch, stop_epoch):
        train.set_state({"epoch": epoch, "cursor": -train.batch_size})
        if not reference:
            _record_partition(edir, gen, epoch, rank, nw, train.idx)
        try:
            for batch in train:
                mod.forward_backward(batch)
                mod.update()
                if not reference and kv.dead_nodes(timeout=DEAD_TIMEOUT):
                    raise mx.resilience.ResilienceError(
                        "dead peer detected mid-epoch",
                        phase="drill_liveness", rank=rank)
        except Exception as exc:  # noqa: BLE001 - fault path by design
            if reference:
                raise
            print("rank %d gen %d epoch %d failed (%s); recovery round"
                  % (rank, gen, epoch, exc), flush=True)
            try:
                verdict = elastic.poll_remesh(
                    kv, elastic.recover_round(epoch),
                    dead_timeout=DEAD_TIMEOUT)
            except mx.resilience.ResilienceError as orphan:
                mx.resilience.exit_for_restart(orphan)
            if verdict is not None:
                elastic.exit_for_remesh(verdict)
            print("rank %d FAILED: epoch blew up with all peers live"
                  % rank, flush=True)
            os._exit(4)
        loss = eval_loss(mod, eval_it)
        print("rank %d gen %d epoch %d loss %.6f" % (rank, gen, epoch,
                                                     loss), flush=True)
        if rank == 0:
            _record_loss(loss_path, gen, nw, epoch, epoch + 1, loss)
        if reference:
            continue
        kv.barrier()
        stable = tree_of(mod)
        mgr.save(stable, epoch + 1)
        if hotstate.warm_enabled():
            # every stable point refreshes the handoff area, so a
            # later torn-epoch death still warm-resumes from here
            hotstate.snapshot(stable, step=epoch + 1)
        if kill is not None and (gen, epoch, rank) == kill:
            _write_capacity(nw - 1)      # capacity drops WITH the node
            if hotstate.warm_enabled():
                # host RAM dies with the host: survivors must serve
                # this rank's state from the off-host buddy replica
                hotstate.simulate_host_loss(hotstate.host_index(rank, nw))
            print("rank %d: simulated preemption (capacity -> %d)"
                  % (rank, nw - 1), flush=True)
            sys.stdout.flush()
            os._exit(1)                  # dies without saying goodbye
        if grow is not None and gen == grow[0] and epoch == grow[1] \
                and rank == 0:
            _write_capacity(grow[2])     # capacity came back
        try:
            verdict = elastic.poll_remesh(kv, epoch,
                                          dead_timeout=DEAD_TIMEOUT)
        except mx.resilience.ResilienceError as orphan:
            # coordinator died before publishing: restart and let the
            # launcher bump the generation itself
            mx.resilience.exit_for_restart(orphan)
        if verdict is not None:
            # clean adopt: state is stable here, so hand it to the
            # handoff area once more on the way out (fault-path exits
            # above ride the last post-save snapshot instead)
            elastic.exit_for_remesh(verdict, hot_state=stable,
                                    step=epoch + 1)

    print("rank %d done at gen %d (world %d)" % (rank, gen, nw),
          flush=True)


if __name__ == "__main__":
    main()
