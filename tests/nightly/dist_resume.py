"""Kill-one-worker -> detect -> resume-from-checkpoint integration test.

Parity story: the reference's fault surface is ps-lite heartbeats exposed
as ``get_num_dead_node`` (kvstore_dist.h:149-158) plus "worker may rejoin"
recovery branches; the TPU-native recovery model (SURVEY §5) is
checkpoint/resume with pod restart.  This script exercises both halves:

Phase A (``MXTPU_FAULT_RANK`` set): all workers train one epoch and
checkpoint; then the fault rank dies without warning (os._exit).  The
survivor detects it via ``kv.num_dead_nodes`` within a few heartbeats and
aborts cleanly with exit code 3 (the restart signal) instead of hanging
in a collective.

Phase B (``MXTPU_RESUME=1``): a fresh launch discovers the phase-A
checkpoint with ``Module.load_latest``, verifies the seeded data
iterator replays the EXACT batch order the uninterrupted run would
have used for this epoch (order hash recorded by phase A), and trains
one more epoch asserting the loss kept improving — the restart half of
kill-and-resume.

Exit codes follow docs/resilience.md: 0 OK, 3 = restart signal
(``mx.resilience.EXIT_RESTART``), 4 = detection/replay failure.

Run (the wrapper in tests/test_nightly_dist.py does this):
    python tools/launch.py -n 2 --launcher local \
        python tests/nightly/dist_resume.py
"""
import os
import sys
import time

import numpy as np

import mxnet_tpu as mx

PREFIX = os.environ.get("MXTPU_RESUME_PREFIX", "/tmp/mxtpu_dist_resume")
DATA_SEED = 11          # seeded shuffle: batch order = f(seed, epoch)


def order_hash(it):
    """Fingerprint of the iterator's upcoming batch order."""
    import hashlib
    return hashlib.sha1(it.idx.tobytes()).hexdigest()


def build_data(rank, nw):
    rng = np.random.RandomState(7)           # same data, sharded by rank
    X = rng.randn(240, 16).astype(np.float32)
    w = rng.randn(16)
    y = (X @ w > 0).astype(np.float32)
    shard = slice(rank * len(X) // nw, (rank + 1) * len(X) // nw)
    return X[shard], y[shard]


def softmax_ce(mod, it):
    losses = []
    for batch in it:
        mod.forward(batch, is_train=False)
        p = mod.get_outputs()[0].asnumpy()
        lbl = batch.label[0].asnumpy().astype(int)
        losses.append(-np.log(p[np.arange(len(lbl)), lbl] + 1e-8).mean())
    it.reset()
    return float(np.mean(losses))


def main():
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    fault_rank = os.environ.get("MXTPU_FAULT_RANK")
    resume = os.environ.get("MXTPU_RESUME") == "1"

    X, y = build_data(rank, nw)
    train = mx.io.NDArrayIter(X, y, batch_size=30, shuffle=True,
                              seed=DATA_SEED)
    net = mx.models.get_mlp(num_classes=2, hidden=(16,))
    mod = mx.mod.Module(net, context=mx.context.cpu())

    epoch0 = 0
    if resume:
        mod, epoch0 = mx.mod.Module.load_latest(
            PREFIX, load_optimizer_states=True, context=mx.context.cpu())
        if mod is None:
            print("rank %d FAILED: no checkpoint to resume from" % rank,
                  flush=True)
            os._exit(4)
        # replay the interrupted run's batch stream: position the
        # iterator at (epoch0, start) and check the order is the one
        # the uninterrupted run recorded (acceptance (d))
        train.set_state({"epoch": epoch0, "cursor": -train.batch_size})
        expected = open("%s.order%d" % (PREFIX, rank)).read().strip()
        if order_hash(train) != expected:
            print("rank %d FAILED: resumed batch order diverged" % rank,
                  flush=True)
            os._exit(4)
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.init.Uniform(0.1))
    mod.init_optimizer(kvstore=kv, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.3})

    loss_before = softmax_ce(mod, train)
    for batch in train:
        mod.forward_backward(batch)
        mod.update()
    train.reset()
    loss_after = softmax_ce(mod, train)
    print("rank %d epoch %d loss %.4f -> %.4f" % (rank, epoch0,
                                                  loss_before, loss_after),
          flush=True)
    assert loss_after < loss_before

    if resume:
        print("rank %d resume OK" % rank, flush=True)
        sys.stdout.flush()
        os._exit(0)

    # phase A: checkpoint + record the batch order the next epoch will
    # use (pure function of (seed, epoch) — phase B must replay it),
    # then inject the fault
    probe = mx.io.NDArrayIter(X, y, batch_size=30, shuffle=True,
                              seed=DATA_SEED)
    probe.set_state({"epoch": 1, "cursor": -probe.batch_size})
    with open("%s.order%d" % (PREFIX, rank), "w") as f:
        f.write(order_hash(probe))
    if rank == 0:
        mod.save_checkpoint(PREFIX, 1, save_optimizer_states=True)
    kv.barrier()
    if fault_rank is not None and rank == int(fault_rank):
        os._exit(1)                      # dies without saying goodbye
    # survivors: poll the fault surface instead of walking into a
    # collective that would hang on the dead peer
    deadline = time.time() + 30
    while time.time() < deadline:
        time.sleep(2)
        dead = kv.num_dead_nodes(timeout=6.0)
        if dead > 0:
            print("rank %d detected %d dead node(s); aborting for restart"
                  % (rank, dead), flush=True)
            sys.stdout.flush()
            os._exit(mx.resilience.EXIT_RESTART)   # restart signal
    print("rank %d FAILED to detect dead worker" % rank, flush=True)
    os._exit(4)


if __name__ == "__main__":
    main()
