"""Distributed kvstore semantics test (multi-process on one box).

Parity: tests/nightly/dist_sync_kvstore.py — launched via
``tools/launch.py -n K --launcher local``; asserts that a pull after
every worker pushed sees the sum of all workers' contributions
(sync-mode semantics, kvstore_dist_server.h:164-198 in the reference),
including a big tensor (the reference's big-array server-sharding case;
here the collective shards nothing but must still sum correctly).

Run directly:
    python tools/launch.py -n 2 --launcher local \
        python tests/nightly/dist_sync_kvstore.py
"""
import sys

import numpy as np

import mxnet_tpu as mx

SHAPE = (2, 3)
BIG_SHAPE = (1200, 1200)  # > the reference's BIGARRAY_BOUND


def main():
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers

    kv.init(3, mx.nd.ones(SHAPE))
    kv.init(99, mx.nd.ones(BIG_SHAPE))

    # every worker pushes rank+1; sync pull must see sum(1..nw)
    kv.push(3, mx.nd.ones(SHAPE) * (rank + 1))
    kv.push(99, mx.nd.ones(BIG_SHAPE) * (rank + 1))
    kv.barrier()

    want = sum(range(1, nw + 1))
    out = mx.nd.zeros(SHAPE)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), want)

    big = mx.nd.zeros(BIG_SHAPE)
    kv.pull(99, out=big)
    np.testing.assert_allclose(big.asnumpy(), want)

    # updater path: server-side SGD-like update (set_optimizer contract)
    kv.set_optimizer(mx.optimizer.create("test"))
    kv.push(3, mx.nd.ones(SHAPE))
    out2 = mx.nd.zeros(SHAPE)
    kv.pull(3, out=out2)
    assert np.isfinite(out2.asnumpy()).all()

    kv.barrier()
    print("dist_sync_kvstore rank %d/%d OK" % (rank, nw), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
