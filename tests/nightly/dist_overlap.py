"""2-worker overlap drill: the async feed must provably hide the input
pipeline, without changing the math (docs/perf.md "Overlap").

Staged on every rank over a dist_sync kvstore (so the bucketed
push_async path runs real cross-worker collectives):

1. A *serial* reference fit — prefetch forced off, telemetry off — over
   a deliberately slow iterator (per-``next`` sleep) and a sleep-padded
   ``forward_backward`` (stands in for device compute long enough to
   hide the fetch under).
2. The same fit — fresh module, same seeds — with ``prefetch=True`` and
   telemetry ON.  The DevicePrefetcher's producer thread emits the
   ``data_wait`` spans that now run during the step.
3. Both fits must produce BIT-IDENTICAL parameters: the overlap
   machinery moves the wait, never the numbers.
4. A compile-cache probe: two identical ShardedTrainer binds — the
   second must perform zero new lowerings.
5. Rank 0 merges the event log and asserts
   ``overlap_report().overlap_ratio > 1.05`` with ``data_wait`` phase
   time recorded — wall < serial is the proof the wait went under the
   step.

Exit codes: 0 OK, 4 = an overlap expectation failed.

Run (tests/ci/run_test.sh TASK=perf wraps this):
    MXTPU_TELEMETRY=1 MXTPU_TELEMETRY_DIR=<dir> MXTPU_BUCKET_MB=0.001 \
        python tools/launch.py -n 2 --launcher local --port 9899 \
        python tests/nightly/dist_overlap.py
"""
import os
import sys
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import observability as obs
from mxnet_tpu.observability import events as _events

FETCH_S = 0.02      # per-batch synthetic decode/augment cost
STEP_S = 0.03       # per-batch synthetic device-compute cost


def fail(rank, msg):
    print("rank %d FAILED: %s" % (rank, msg), flush=True)
    os._exit(4)


class SlowIter(mx.io.NDArrayIter):
    """NDArrayIter that pays a fixed host tax per batch — the stand-in
    for decode/augment the prefetcher is supposed to hide."""

    def next(self):
        time.sleep(FETCH_S)
        return super(SlowIter, self).next()


def build_data(rank, nw):
    rng = np.random.RandomState(7)
    X = rng.randn(160, 16).astype(np.float32)
    w = rng.randn(16)
    y = (X @ w > 0).astype(np.float32)
    shard = slice(rank * len(X) // nw, (rank + 1) * len(X) // nw)
    return X[shard], y[shard]


def run_fit(kv, X, y, prefetch):
    """One deterministic 2-epoch fit; returns the trained arg params."""
    # Both fits share one dist kv (a second dist_sync store would reuse
    # the coordination-KV round keys).  kv.init is rank-local, so
    # clearing the store between runs is safe — and both runs seed
    # identical initial weights anyway.
    kv._store.clear()
    mx.random.seed(0)
    train = SlowIter(X, y, batch_size=10)
    net = mx.models.get_mlp(num_classes=2, hidden=(16,))
    mod = mx.mod.Module(net, context=mx.context.cpu())

    orig_fb = mod.forward_backward

    def slow_fb(batch):
        orig_fb(batch)
        time.sleep(STEP_S)      # stands in for waiting on the device
    mod.forward_backward = slow_fb

    mod.fit(train, kvstore=kv, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1}, num_epoch=2,
            prefetch=prefetch)
    arg_params, _aux = mod.get_params()
    return {k: v.asnumpy().copy() for k, v in arg_params.items()}


def main():
    teldir = os.environ.get("MXTPU_TELEMETRY_DIR")
    if not teldir:
        fail(0, "drill needs MXTPU_TELEMETRY_DIR")
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    X, y = build_data(rank, nw)

    # ---- 1: serial reference, telemetry off so its (ratio ~1.0) steps
    # don't dilute the overlapped run's event window -------------------
    saved = os.environ.pop("MXTPU_TELEMETRY", None)
    os.environ["MXTPU_TELEMETRY"] = "0"
    _events.refresh()
    serial_params = run_fit(kv, X, y, prefetch=False)

    # ---- 2: overlapped run under full telemetry ----------------------
    if saved is None:
        os.environ.pop("MXTPU_TELEMETRY", None)
    else:
        os.environ["MXTPU_TELEMETRY"] = saved
    _events.refresh()
    if not obs.enabled():
        fail(rank, "telemetry not enabled in drill env")
    overlap_params = run_fit(kv, X, y, prefetch=True)

    # ---- 3: bit-identical math ---------------------------------------
    if sorted(serial_params) != sorted(overlap_params):
        fail(rank, "param sets differ: %s vs %s"
             % (sorted(serial_params), sorted(overlap_params)))
    for k in serial_params:
        if not (serial_params[k] == overlap_params[k]).all():
            fail(rank, "param %s differs between serial and prefetch runs"
                 % k)

    # ---- 4: compile cache: second identical bind lowers nothing ------
    from mxnet_tpu import parallel
    from mxnet_tpu.parallel import overlap as ov
    ov.compile_cache_clear()
    import jax
    net = mx.models.get_mlp(num_classes=2, hidden=(16,))
    # local devices only: a cross-process mesh is not computable on the
    # CPU backend, and the cache probe is per-process anyway
    local = jax.local_devices()
    mesh = parallel.make_mesh(local, dp=len(local))
    rng = np.random.RandomState(1)
    batch_np = {"data": rng.randn(8, 16).astype(np.float32),
                "softmax_label": (rng.rand(8) > 0.5).astype(np.float32)}

    def bind_and_step():
        opt = mx.optimizer.create("sgd", learning_rate=0.1)
        tr = parallel.ShardedTrainer(net, opt, mesh)
        params, opt_state, aux = tr.init_params(
            {"data": (8, 16)}, label_shapes={"softmax_label": (8,)})
        tr.step(params, opt_state, aux, tr.shard_batch(dict(batch_np)))
    bind_and_step()
    st1 = ov.compile_cache_stats()
    bind_and_step()
    st2 = ov.compile_cache_stats()
    if st2["lowerings"] != st1["lowerings"]:
        fail(rank, "second identical bind re-lowered: %s -> %s"
             % (st1, st2))
    if st2["hits"] < st1["hits"] + 1:
        fail(rank, "second bind did not hit the cache: %s -> %s"
             % (st1, st2))

    # ---- 5: rank 0 proves the overlap from the merged event log ------
    obs.flush()
    kv.barrier()
    if rank == 0:
        from mxnet_tpu.observability.aggregate import read_events
        from mxnet_tpu.observability.spans import overlap_report
        rep = overlap_report(read_events(teldir))
        if rep["overlap_ratio"] is None:
            fail(rank, "no overlap ratio from %s (steps=%s)"
                 % (teldir, rep["steps"]))
        if rep["overlap_ratio"] <= 1.05:
            fail(rank, "overlap_ratio %.3f <= 1.05: the wait did not go "
                 "under the step (report: %r)"
                 % (rep["overlap_ratio"], rep))
        if "data_wait" not in rep["phase_ms"]:
            fail(rank, "no data_wait phase time in %r" % (rep,))
        print("rank 0 overlap_ratio=%.3f wall=%.0fms serial=%.0fms "
              "phase_p50=%r"
              % (rep["overlap_ratio"], rep["wall_ms"], rep["serial_ms"],
                 rep["phase_p50_ms"]), flush=True)
    kv.barrier()
    print("rank %d OVERLAP DRILL OK" % rank, flush=True)
    sys.stdout.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
