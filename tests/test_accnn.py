"""tools/accnn: low-rank model acceleration (reference tools/accnn).

Train a small convnet, compress it with automatic rank selection, and
check the accelerated checkpoint loads and keeps accuracy; also check
the pure-SVD single-layer paths preserve outputs at full rank."""
import json
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# import the tool modules, then drop the path again: generic names like
# "utils" must not shadow other tests' imports for the session
sys.path.insert(0, os.path.join(_ROOT, "tools", "accnn"))
try:
    import utils            # noqa: E402
    import acc_fc           # noqa: E402
    import acc_conv         # noqa: E402
    import rank_selection   # noqa: E402
    import accnn as accnn_mod  # noqa: E402
finally:
    sys.path.pop(0)

rng = np.random.RandomState(0)


def _toy_conv_model(tmp_path, epochs=10):
    n, classes = 256, 3
    patterns = rng.randn(classes, 8, 6, 6).astype(np.float32) * 1.5
    y = rng.randint(0, classes, size=n)
    X = (patterns[y] + rng.randn(n, 8, 6, 6)).astype(np.float32)

    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                             pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    it = mx.io.NDArrayIter(X, y.astype(np.float32), batch_size=32,
                           shuffle=True)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=epochs, optimizer="adam",
            optimizer_params={"learning_rate": 0.005})
    val = mx.io.NDArrayIter(X, y.astype(np.float32), batch_size=32)
    acc = dict(mod.score(val, "acc"))["accuracy"]
    prefix = str(tmp_path / "net")
    mod.save_checkpoint(prefix, 0)
    return prefix, X, y, acc


def _score(prefix, epoch, X, y):
    sym, args, aux = mx.model.load_checkpoint(prefix, epoch)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", X.shape[:1] and (32,) + X.shape[1:])],
             label_shapes=[("softmax_label", (32,))])
    mod.set_params(args, aux)
    val = mx.io.NDArrayIter(X, y.astype(np.float32), batch_size=32)
    return dict(mod.score(val, "acc"))["accuracy"]


def test_full_rank_decomposition_preserves_outputs(tmp_path):
    """At full rank the SVD factors reproduce the original layer, so the
    surgery itself must be numerically transparent — this isolates graph
    splicing from approximation error."""
    prefix, X, y, _ = _toy_conv_model(tmp_path, epochs=2)
    model = utils.load_model(prefix, 0)

    m_fc = acc_fc.fc_decomposition(model, "fc1", K=10**9)
    m_cv = acc_conv.conv_vh_decomposition(model, "conv1", K=10**9)
    for m2 in (m_fc, m_cv):
        assert "softmax_label" in m2["symbol"].list_arguments()
        utils.save_model(m2, str(tmp_path / "t"), 0)
        a_orig = _score(prefix, 0, X, y)
        a_new = _score(str(tmp_path / "t"), 0, X, y)
        assert abs(a_orig - a_new) < 0.02, (a_orig, a_new)


def test_accnn_whole_model(tmp_path):
    """Ratio-driven acceleration: fewer params, model still loads, runs,
    and keeps accuracy near the original (min_energy floor active)."""
    prefix, X, y, acc0 = _toy_conv_model(tmp_path)
    assert acc0 > 0.9, "toy model failed to train (%.2f)" % acc0
    model = utils.load_model(prefix, 0)
    cfg = rank_selection.get_ranksel(model, ratio=2.0, min_energy=0.97)
    assert cfg, "rank selection chose nothing"
    m2 = accnn_mod.accelerate(model, cfg)
    p0, p1 = accnn_mod.param_count(model), accnn_mod.param_count(m2)
    assert p1 < p0, (p0, p1)
    utils.save_model(m2, str(tmp_path / "fast"), 0)
    acc1 = _score(str(tmp_path / "fast"), 0, X, y)
    assert acc1 > acc0 - 0.1, (acc0, acc1)


def test_replace_layer_preserves_producer_output_index(tmp_path):
    """The decomposed layer may consume a NON-FIRST output of its
    producer (review regression): splice must keep that output index."""
    data = mx.sym.Variable("data")
    halves = mx.sym.SliceChannel(data, num_outputs=2, name="split")
    fc = mx.sym.FullyConnected(halves[1], num_hidden=4, name="fc1")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")

    x = rng.rand(2, 6).astype(np.float32)
    w = rng.rand(4, 3).astype(np.float32)
    b = rng.rand(4).astype(np.float32)
    args = {"fc1_weight": mx.nd.array(w), "fc1_bias": mx.nd.array(b)}

    def run(sym, params):
        exe = sym.simple_bind(mx.cpu(0), data=(2, 6))
        exe.copy_params_from(params, allow_extra_params=True)
        return exe.forward(data=x)[0].asnumpy()

    want = run(net, args)
    model = {"symbol": net, "arg_params": dict(args), "aux_params": {}}
    m2 = acc_fc.fc_decomposition(model, "fc1", K=10**9)  # full rank
    got = run(m2["symbol"], m2["arg_params"])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
