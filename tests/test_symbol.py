"""Symbol composition tests (modeled on tests/python/unittest/test_symbol.py)."""
import os
import tempfile

import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.base import MXNetError


def mlp2():
    data = sym.Variable("data")
    out = sym.FullyConnected(data=data, name="fc1", num_hidden=1000)
    out = sym.Activation(data=out, act_type="relu")
    out = sym.FullyConnected(data=out, name="fc2", num_hidden=10)
    return out


def test_symbol_basic():
    m = mlp2()
    assert m.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                  "fc2_weight", "fc2_bias"]
    assert m.list_outputs() == ["fc2_output"]


def test_symbol_compose():
    data = sym.Variable("data")
    net1 = sym.FullyConnected(data=data, name="fc1", num_hidden=10)
    net1 = sym.FullyConnected(data=net1, name="fc2", num_hidden=100)
    assert net1.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                     "fc2_weight", "fc2_bias"]
    # explicit weight supply suppresses auto-creation
    w = sym.Variable("myweight")
    net2 = sym.FullyConnected(data=data, weight=w, name="fc3", num_hidden=10)
    assert net2.list_arguments() == ["data", "myweight", "fc3_bias"]


def test_symbol_internals():
    m = mlp2()
    internals = m.get_internals()
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == ["data", "fc1_weight", "fc1_bias"]
    assert "fc1_output" in internals.list_outputs()


def test_symbol_group():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=10)
    fc2 = sym.FullyConnected(data, name="fc2", num_hidden=10)
    g = sym.Group([fc1, fc2])
    assert g.list_outputs() == ["fc1_output", "fc2_output"]
    assert len(g) == 2
    assert g[1].list_outputs() == ["fc2_output"]


def test_symbol_arith():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a + b
    assert set(c.list_arguments()) == {"a", "b"}
    d = (a * 2 - b / 3) ** 2.0
    _, out_shapes, _ = d.infer_shape(a=(3, 4), b=(3, 4))
    assert out_shapes[0] == (3, 4)
    e = 1.0 - a
    _, o, _ = e.infer_shape(a=(2, 2))
    assert o[0] == (2, 2)


def test_symbol_json_roundtrip():
    m = mlp2()
    js = m.tojson()
    m2 = sym.load_json(js)
    assert m2.list_arguments() == m.list_arguments()
    assert m2.list_outputs() == m.list_outputs()
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "sym.json")
        m.save(fname)
        m3 = sym.load(fname)
        assert m3.tojson() == js


def test_symbol_attr():
    data = sym.Variable("data", attr={"mood": "angry"})
    assert data.attr("mood") == "angry"
    with mx.AttrScope(ctx_group="stage1"):
        fc = sym.FullyConnected(data, num_hidden=10, name="fc")
    assert fc.attr("ctx_group") == "stage1"
    ad = fc.attr_dict()
    assert ad["data"]["mood"] == "angry"
    assert ad["fc"]["ctx_group"] == "stage1"


def test_symbol_errors():
    data = sym.Variable("data")
    with pytest.raises(MXNetError):
        sym.FullyConnected(data, num_hidden=10, bogus_attr_xyz=3)
    with pytest.raises(MXNetError):
        sym.Activation(data, act_type="bogus")
    with pytest.raises(MXNetError):
        mlp2()["nonexistent_output"]


def test_variable_shape_hint():
    x = sym.Variable("x", shape=(4, 5))
    y = sym.sqrt(x)
    _, out, _ = y.infer_shape()
    assert out[0] == (4, 5)


def test_vararg_ops():
    a, b, c = sym.Variable("a"), sym.Variable("b"), sym.Variable("c")
    cat = sym.Concat(a, b, c, dim=1, name="cat")
    arg_shapes, out_shapes, _ = cat.infer_shape(a=(2, 3), b=(2, 4), c=(2, 5))
    assert out_shapes[0] == (2, 12)
    s = sym.ElementWiseSum(a, b, c, name="esum")
    _, out_shapes, _ = s.infer_shape(a=(2, 3), b=(2, 3), c=(2, 3))
    assert out_shapes[0] == (2, 3)


def test_slice_channel_outputs():
    data = sym.Variable("data")
    sc = sym.SliceChannel(data, num_outputs=3, name="sc")
    assert sc.list_outputs() == ["sc_output0", "sc_output1", "sc_output2"]
    _, out_shapes, _ = sc.infer_shape(data=(2, 6, 4))
    assert out_shapes == [(2, 2, 4)] * 3


def test_deep_chain_infer_fixpoint():
    """Fixpoint inference must not cap iteration depth (review regression)."""
    x = sym.Variable("x")
    zs = [sym.Variable("z%d" % i) for i in range(6)]
    ys = [x + zs[0]]
    for i in range(5):
        ys.append(zs[i] + zs[i + 1])
    g = sym.Group(list(reversed(ys)))
    arg_shapes, out_shapes, _ = g.infer_shape(x=(2, 3))
    assert arg_shapes is not None
    assert all(s == (2, 3) for s in out_shapes)


def test_load_json_custom_attrs():
    """Nodes carrying user attrs must reload (review regression)."""
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=3, name="fc",
                            attr={"mood": "happy", "ctx_group": "g1"})
    s2 = sym.load_json(fc.tojson())
    assert s2.attr("mood") == "happy"
    assert s2.list_arguments() == fc.list_arguments()


def test_infer_type_cast():
    import numpy as np
    data = sym.Variable("data")
    c = sym.Cast(data, dtype="float16")
    arg_types, out_types, _ = c.infer_type(data=np.float32)
    assert out_types[0] == np.float16
    assert arg_types[0] == np.float32
    with pytest.raises(MXNetError):
        c.infer_type(bogus=np.float32)


def test_symbol_grad():
    """Symbol.grad (Symbol::Grad parity, reference symbol.cc:569): the
    grad symbol takes base args + head-grad vars named
    '<headnode>_<idx>_grad' (static_graph.cc:448-452) and its outputs
    match the executor backward of the same graph."""
    import numpy as np
    import mxnet_tpu as mx

    x = sym.Variable("x")
    w = sym.Variable("w")
    y = sym.FullyConnected(x, weight=w, num_hidden=3, no_bias=True,
                           name="fc")
    g = y.grad(["x", "w"])
    assert g.list_arguments() == ["x", "w", "fc_0_grad"]
    assert [o.split("_", 1)[1] for o in g.list_outputs()] == \
        ["x_grad", "w_grad"]

    ex = g.simple_bind(mx.cpu(), grad_req="null", x=(2, 4), w=(3, 4),
                       fc_0_grad=(2, 3))
    rng = np.random.RandomState(3)
    xs = rng.rand(2, 4).astype("f")
    ws = rng.rand(3, 4).astype("f")
    hg = rng.rand(2, 3).astype("f")
    ex.arg_dict["x"][:] = xs
    ex.arg_dict["w"][:] = ws
    ex.arg_dict["fc_0_grad"][:] = hg
    ex.forward()
    gx, gw = [o.asnumpy() for o in ex.outputs]
    assert np.allclose(gx, hg @ ws, atol=1e-5)
    assert np.allclose(gw, hg.T @ xs, atol=1e-5)

    with pytest.raises(MXNetError):
        y.grad(["nope"])


def test_symbol_grad_aux_train_mode():
    """grad differentiates the TRAINING graph: BatchNorm uses batch
    statistics, matching executor backward (not inference mode)."""
    import numpy as np
    import mxnet_tpu as mx

    x = sym.Variable("x")
    net = sym.FullyConnected(x, num_hidden=4, name="fc")
    net = sym.BatchNorm(net, name="bn")
    g = net.grad(["x"])
    assert any(a.endswith("bn_moving_mean")
               for a in g.list_auxiliary_states())

    ex = g.simple_bind(mx.cpu(), grad_req="null", x=(3, 5),
                       **{"bn_0_grad": (3, 4)})
    rng = np.random.RandomState(5)
    for name, arr in ex.arg_dict.items():
        arr[:] = rng.uniform(-1, 1, arr.shape).astype("f")
    ex.forward()
    gx = ex.outputs[0].asnumpy()

    ex2 = net.simple_bind(mx.cpu(), grad_req="write", x=(3, 5))
    for name in ex2.arg_dict:
        ex2.arg_dict[name][:] = ex.arg_dict[name].asnumpy()
    ex2.forward(is_train=True)
    ex2.backward(out_grads=[mx.nd.array(
        ex.arg_dict["bn_0_grad"].asnumpy())])
    assert np.allclose(gx, ex2.grad_dict["x"].asnumpy(), atol=1e-4)
