"""Tracing tier tests (ISSUE 10).

Covers trace/span id propagation (MXTPU_TRACE), the rank-uniform
collective sequence counter, the always-on crash flight recorder (ring
bound, pending-collective ledger, crash-seam dumps), the SLO
perf-regression sentry + benchdiff gate, the mxtrace Chrome-trace
merger, the rotation-safe EventTailer behind ``mxtop --follow``, the
shared phase registry, the telemetry-env recheck/rotation-boundary
integrity satellites, and the 2-process hung-collective drill
(tests/nightly/dist_flight.py).
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (forces conftest device setup)
from mxnet_tpu import observability as obs
from mxnet_tpu.observability import (aggregate, counters, events, flight,
                                     phases, slo, spans, trace)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    """Telemetry/trace off, fresh singletons, bounded flight ring."""
    for var in ("MXTPU_TELEMETRY", "MXTPU_TELEMETRY_DIR", "MXTPU_RUN_ID",
                "MXTPU_TRACE", "MXTPU_FLIGHT_DEPTH",
                "MXTPU_SLO_BASELINE"):
        monkeypatch.delenv(var, raising=False)
    events.refresh()
    trace.refresh()
    flight.reset()
    counters.reset()
    yield
    events.refresh()
    trace.refresh()
    flight.reset()
    counters.reset()


def _enable(monkeypatch, tmp_path, run_id="tracerun", trace_on=True):
    d = str(tmp_path / "tel")
    monkeypatch.setenv("MXTPU_TELEMETRY", "1")
    monkeypatch.setenv("MXTPU_TELEMETRY_DIR", d)
    monkeypatch.setenv("MXTPU_RUN_ID", run_id)
    if trace_on:
        monkeypatch.setenv("MXTPU_TRACE", "1")
    events.refresh()
    trace.refresh()
    return d


# ----------------------------------------------------------------------
# trace.py
# ----------------------------------------------------------------------
def test_trace_off_by_default():
    assert not trace.enabled()
    assert trace.begin_span("step") == {}
    trace.end_span()                      # imbalance never raises
    assert trace.ids() == {}


def test_trace_nesting_and_ids(monkeypatch):
    monkeypatch.setenv("MXTPU_TRACE", "1")
    trace.refresh()
    outer = trace.begin_span("step")
    inner = trace.begin_span("allreduce")
    assert outer["trace_id"] == inner["trace_id"]
    assert inner["parent_span"] == outer["span_id"]
    assert "parent_span" not in outer
    # an emit inside the inner span binds to it
    bound = trace.ids()
    assert bound["span_id"] == inner["span_id"]
    trace.end_span()
    assert trace.ids()["span_id"] == outer["span_id"]
    trace.end_span()
    # stack empty: ids() still names the thread's trace
    assert trace.ids() == {"trace_id": outer["trace_id"]}


def test_trace_ids_are_per_thread(monkeypatch):
    monkeypatch.setenv("MXTPU_TRACE", "1")
    trace.refresh()
    main_id = trace.current_trace()
    seen = {}

    def worker():
        seen["trace"] = trace.current_trace()
        seen["span"] = trace.begin_span("data_wait")
        trace.end_span()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert seen["trace"] != main_id
    assert seen["span"]["trace_id"] == seen["trace"]


def test_set_trace_adoption(monkeypatch):
    monkeypatch.setenv("MXTPU_TRACE", "1")
    trace.refresh()
    mine = trace.current_trace()
    prev = trace.set_trace("feedbeef00000001")
    assert trace.current_trace() == "feedbeef00000001"
    trace.clear_trace(prev)
    assert trace.current_trace() == mine


def test_trace_env_probe_is_rate_limited(monkeypatch):
    monkeypatch.setenv("MXTPU_TRACE", "1")
    assert trace.refresh()
    monkeypatch.delenv("MXTPU_TRACE")
    # within the recheck window the cached verdict holds ...
    assert trace.enabled()
    # ... and refresh() re-probes immediately
    assert not trace.refresh()


def test_next_seq_per_op_and_snapshot():
    base_ar = trace.next_seq("allreduce")
    assert trace.next_seq("allreduce") == base_ar + 1
    base_b = trace.next_seq("barrier")
    assert trace.next_seq("barrier") == base_b + 1
    # independent counters; snapshot reports counts issued
    snap = trace.seq_snapshot()
    assert snap["allreduce"] == base_ar + 2
    assert snap["barrier"] == base_b + 2


def test_span_records_carry_trace_ids(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    with spans.span("step", step=7):
        with spans.span("allreduce", step=7):
            pass
    events.flush()
    recs = aggregate.read_events(d)
    by_name = {r["name"]: r for r in recs if r["kind"] == "span"}
    assert by_name["allreduce"]["trace_id"] == \
        by_name["step"]["trace_id"]
    assert by_name["allreduce"]["parent_span"] == \
        by_name["step"]["span_id"]


def test_timed_iter_carries_trace_ids(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    list(spans.timed_iter([1, 2], name="data_wait"))
    events.flush()
    recs = [r for r in aggregate.read_events(d) if r["kind"] == "span"]
    assert len(recs) == 2
    assert all(r.get("trace_id") and r.get("span_id") for r in recs)


def test_span_records_clean_without_trace(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path, trace_on=False)
    with spans.span("step", step=1):
        pass
    events.flush()
    rec = [r for r in aggregate.read_events(d)
           if r["kind"] == "span"][0]
    assert "trace_id" not in rec and "span_id" not in rec


# ----------------------------------------------------------------------
# flight.py
# ----------------------------------------------------------------------
def test_flight_ring_is_bounded(monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHT_DEPTH", "8")
    rec = flight.reset()
    for i in range(50):
        flight.note("step", i, {"dur_ms": 1.0})
    snap = rec.snapshot()
    assert len(snap["events"]) == 8
    assert [e["step"] for e in snap["events"]] == list(range(42, 50))


def test_flight_depth_zero_disables(monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHT_DEPTH", "0")
    assert flight.reset() is None
    flight.note("step", 1, {})            # silent no-op
    assert flight.pending_collectives() == []
    assert flight.dump("unit") is None


def test_flight_captures_with_telemetry_off(tmp_path):
    """The whole point: events land in the ring with MXTPU_TELEMETRY
    unset, and a dump still renders them."""
    assert events.get() is None
    events.emit("fault", step=3, fault="watchdog_stall", phase="x")
    path = flight.dump("unit_test", directory=str(tmp_path))
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    tail = [e for e in doc["events"] if e["kind"] == "fault"]
    assert tail and tail[-1]["fault"] == "watchdog_stall"
    assert doc["reason"] == "unit_test"


def test_flight_pending_ledger(tmp_path):
    flight.reset()
    flight.collective_begin("allreduce", 4, participants=[0, 1],
                            bytes=1024)
    flight.collective_begin("barrier", 9, participants=[0, 1])
    flight.collective_end("barrier", 9)
    pend = flight.pending_collectives()
    assert [(e["op"], e["seq"]) for e in pend] == [("allreduce", 4)]
    doc = json.load(open(flight.dump("unit", directory=str(tmp_path))))
    entry = doc["pending_collectives"][0]
    assert entry["participants"] == [0, 1]
    assert entry["bytes"] == 1024
    assert entry["age_ms"] >= 0
    assert "allreduce" in doc["collective_seq"] or True  # snapshot dict
    # retiring clears it from later dumps
    flight.collective_end("allreduce", 4)
    assert flight.pending_collectives() == []


def test_flight_dump_includes_liveness(tmp_path):
    flight.reset()
    flight.set_liveness_probe(lambda: [1, 3])
    doc = json.load(open(flight.dump("unit", directory=str(tmp_path))))
    assert doc["absent_ranks"] == [1, 3]


def test_flight_dump_never_raises(tmp_path):
    flight.reset()
    flight.set_liveness_probe(lambda: 1 / 0)
    doc = json.load(open(flight.dump("unit", directory=str(tmp_path))))
    assert doc["absent_ranks"] is None    # probe failure ≠ dump failure
    # unwritable directory: returns None instead of raising
    assert flight.dump("unit", directory="/dev/null/nope") is None


def test_watchdog_timeout_dumps_flight(monkeypatch, tmp_path):
    d = str(tmp_path / "tel")
    monkeypatch.setenv("MXTPU_TELEMETRY_DIR", d)   # dump dir only:
    monkeypatch.setenv("MXTPU_TELEMETRY", "0")     # telemetry itself OFF
    events.refresh()
    flight.reset()
    flight.collective_begin("allreduce", 2, participants=[0, 1])
    from mxnet_tpu.resilience import ResilienceError, run_with_timeout
    with pytest.raises(ResilienceError):
        run_with_timeout(lambda: time.sleep(5.0), 0.2,
                         phase="drill_stall", step=42)
    dumps = [f for f in os.listdir(d) if f.startswith("flight-rank")]
    assert len(dumps) == 1
    doc = json.load(open(os.path.join(d, dumps[0])))
    assert doc["reason"] == "watchdog_timeout"
    assert doc["phase"] == "drill_stall" and doc["step"] == 42
    assert [(e["op"], e["seq"]) for e in doc["pending_collectives"]] \
        == [("allreduce", 2)]


def test_sentinel_escalation_dumps_flight(monkeypatch, tmp_path):
    d = str(tmp_path / "tel")
    monkeypatch.setenv("MXTPU_TELEMETRY_DIR", d)
    monkeypatch.setenv("MXTPU_TELEMETRY", "0")
    events.refresh()
    flight.reset()
    from mxnet_tpu.resilience import ResilienceError
    from mxnet_tpu.resilience.sentinel import Sentinel
    sent = Sentinel(max_consecutive_skips=2)
    with pytest.raises(ResilienceError):
        for step in range(5):
            sent.check(step=step, loss=float("nan"))
    dumps = [f for f in os.listdir(d) if f.startswith("flight-rank")]
    assert len(dumps) == 1
    doc = json.load(open(os.path.join(d, dumps[0])))
    assert doc["reason"] == "sentinel_escalate"
    # the ring tail shows the skip events that led to the escalation
    skips = [e for e in doc["events"]
             if e.get("fault") == "sentinel_skip"]
    assert len(skips) >= 1


def test_exit_for_restart_dumps_flight(tmp_path):
    """os._exit path: run in a subprocess, assert the dump exists."""
    d = str(tmp_path / "tel")
    code = (
        "import mxnet_tpu.observability as obs\n"
        "obs.flight.collective_begin('allreduce', 7, participants=[0])\n"
        "from mxnet_tpu.resilience import ResilienceError, "
        "exit_for_restart\n"
        "exit_for_restart(ResilienceError('drill', phase='p', step=1))\n")
    env = dict(os.environ, MXTPU_TELEMETRY_DIR=d, MXTPU_TELEMETRY="0",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], cwd=_ROOT,
                          env=env, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 3, proc.stderr
    assert "FLIGHT RECORDER: dumped" in proc.stderr
    dumps = [f for f in os.listdir(d) if f.startswith("flight-rank")]
    doc = json.load(open(os.path.join(d, dumps[0])))
    assert doc["reason"] == "exit_restart"
    assert [(e["op"], e["seq"]) for e in doc["pending_collectives"]] \
        == [("allreduce", 7)]


def test_sigterm_dumps_flight(tmp_path):
    d = str(tmp_path / "tel")
    code = (
        "import os, signal, sys, time\n"
        "import mxnet_tpu.observability as obs\n"
        "obs.flight.get()\n"                 # install the handler
        "obs.emit('step', step=5, dur_ms=1.0)\n"
        "print('READY', flush=True)\n"
        "time.sleep(60)\n")
    env = dict(os.environ, MXTPU_TELEMETRY_DIR=d, MXTPU_TELEMETRY="0",
               JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-u", "-c", code],
                            cwd=_ROOT, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    finally:
        proc.kill()
    dumps = [f for f in os.listdir(d) if f.startswith("flight-rank")]
    assert dumps, "no flight dump after SIGTERM"
    doc = json.load(open(os.path.join(d, dumps[0])))
    assert doc["reason"] == "sigterm"
    assert any(e["kind"] == "step" for e in doc["events"])


# ----------------------------------------------------------------------
# satellite (c): env recheck + rotation-boundary integrity
# ----------------------------------------------------------------------
def test_events_refresh_bypasses_rate_limit(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path, trace_on=False)
    log = events.get()
    assert log is not None
    monkeypatch.setenv("MXTPU_TELEMETRY", "0")
    # inside the recheck window get() serves the cached singleton
    assert events.get() is log
    # refresh() re-derives immediately
    assert events.refresh() is None
    # and re-enabling rebuilds a NEW log against the same dir
    monkeypatch.setenv("MXTPU_TELEMETRY", "1")
    log2 = events.refresh()
    assert log2 is not None and log2 is not log
    assert log2.directory == d


def test_rotation_never_tears_a_record(tmp_path):
    """Every line on both sides of a rotation parses as complete JSON —
    a record is written entirely before or entirely after the cut."""
    log = events.EventLog(str(tmp_path), rank=0, run_id="rot",
                          max_bytes=4096, flush_interval_s=3600.0)
    payload = "x" * 100
    n = 200
    for i in range(n):
        log.emit("step", step=i, dur_ms=1.0, pad=payload)
        if i % 7 == 0:
            log.flush()                   # rotations happen mid-stream
    log.close()
    kept = []
    for suffix in (".1", ""):
        path = log.path + suffix
        if not os.path.exists(path):
            continue
        with open(path) as fin:
            for line in fin:
                rec = json.loads(line)    # torn line would raise here
                kept.append(rec["step"])
    assert kept == sorted(kept)
    # bounded: at most one predecessor kept, so the tail survives
    assert kept[-1] == n - 1
    assert os.path.exists(log.path + ".1")


def test_event_tailer_incremental(tmp_path):
    d = str(tmp_path)
    path = os.path.join(d, "events-rank00000.jsonl")
    tailer = aggregate.EventTailer(d)
    assert tailer.poll() == []
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "step", "step": 1,
                            "wall_ms": 10}) + "\n")
    assert [r["step"] for r in tailer.poll()] == [1]
    assert tailer.poll() == []            # nothing new
    with open(path, "a") as f:
        f.write(json.dumps({"kind": "step", "step": 2,
                            "wall_ms": 20}) + "\n")
    assert [r["step"] for r in tailer.poll()] == [2]


def test_event_tailer_carries_partial_lines(tmp_path):
    d = str(tmp_path)
    path = os.path.join(d, "events-rank00000.jsonl")
    line = json.dumps({"kind": "step", "step": 1, "wall_ms": 10}) + "\n"
    with open(path, "w") as f:
        f.write(line[:10])                # a record mid-write
    assert aggregate.EventTailer(d).poll() == []
    tailer = aggregate.EventTailer(d)
    tailer.poll()
    with open(path, "a") as f:
        f.write(line[10:])                # writer finishes the record
    assert [r["step"] for r in tailer.poll()] == [1]


def test_event_tailer_survives_rotation(tmp_path):
    """Satellite (a): the --follow reader keeps reading after the
    writer rotates — drains the renamed inode from its old offset and
    starts the fresh live file at zero, no loss, no duplicates."""
    d = str(tmp_path)
    path = os.path.join(d, "events-rank00000.jsonl")

    def rec(i):
        return json.dumps({"kind": "step", "step": i,
                           "wall_ms": i * 10}) + "\n"

    with open(path, "w") as f:
        f.write(rec(1) + rec(2))
    tailer = aggregate.EventTailer(d)
    assert [r["step"] for r in tailer.poll()] == [1, 2]
    with open(path, "a") as f:
        f.write(rec(3))                   # written before the rotation,
    os.rename(path, path + ".1")          # not yet polled
    with open(path, "w") as f:
        f.write(rec(4))                   # the fresh live file
    got = [r["step"] for r in tailer.poll()]
    assert sorted(got) == [3, 4]
    assert tailer.poll() == []


def test_mxtop_follow_survives_rotation(tmp_path):
    """Satellite (a) at the tool level: a following mxtop keeps
    reporting records appended AFTER the live file was rotated."""
    d = str(tmp_path / "tel")
    os.makedirs(d)
    path = os.path.join(d, "events-rank00000.jsonl")

    def rec(i):
        return json.dumps({"run_id": "rot", "rank": 0, "kind": "step",
                           "step": i, "wall_ms": 1000 + i,
                           "dur_ms": 2.0}) + "\n"

    with open(path, "w") as f:
        f.write(rec(1) + rec(2))
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_ROOT, "tools", "mxtop.py"), d,
         "--follow", "--json", "--interval", "0.3"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        time.sleep(1.2)                   # first polls see steps 1-2
        os.rename(path, path + ".1")      # writer rotates ...
        with open(path, "w") as f:        # ... and keeps appending
            f.write(rec(3))
        time.sleep(1.2)
        proc.send_signal(signal.SIGINT)
        out, _err = proc.communicate(timeout=60)
    finally:
        proc.kill()
    # the LAST report must include the post-rotation step: 3 steps total
    decoder = json.JSONDecoder()
    docs, idx = [], 0
    while idx < len(out):
        try:
            doc, end = decoder.raw_decode(out, idx)
        except ValueError:
            break
        docs.append(doc)
        idx = end + 1
    assert docs, out[:500]
    assert docs[-1]["per_rank"]["0"]["steps"] == 3, docs[-1]


# ----------------------------------------------------------------------
# shared phase registry (satellite b)
# ----------------------------------------------------------------------
def test_phase_registry_is_shared():
    assert phases.PHASES == phases.TRAIN_PHASES + phases.SERVE_PHASES
    assert spans.SPAN_NAMES is phases.TRAIN_PHASES
    from mxnet_tpu import profiler
    assert profiler.PHASES is phases.PHASES
    from mxnet_tpu.serving import telemetry as stel
    assert stel.SERVE_PHASES is phases.SERVE_PHASES
    # the serve record schema derives from the registry
    assert [f for _k, f in stel._PHASE_FIELDS] == \
        [p + "_ms" for p in phases.SERVE_PHASES]
    assert phases.is_canonical("allreduce")
    assert not phases.is_canonical("made_up_phase")


def test_parse_log_serve_phase_columns(tmp_path):
    d = str(tmp_path / "tel")
    os.makedirs(d)
    rec = {"run_id": "r", "rank": 0, "kind": "serve", "model": "m",
           "bucket": 8, "n_requests": 2, "n_samples": 4,
           "occupancy": 0.5, "padding_waste": 0.5, "queue_depth": 1,
           "queue_wait_ms": 2.0, "pack_ms": 1.0, "device_ms": 5.0,
           "unpack_ms": 0.5, "lat_ms": [8.0, 9.0], "wall_ms": 1000}
    rec2 = dict(rec, wall_ms=2000)
    step = {"run_id": "r", "rank": 0, "kind": "step", "step": 1,
            "dur_ms": 5.0, "wall_ms": 500}
    with open(os.path.join(d, "events-rank00000.jsonl"), "w") as f:
        f.write("\n".join(json.dumps(r)
                          for r in (step, rec, rec2)) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "parse_log.py"),
         d], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    for phase in phases.SERVE_PHASES:
        assert "serve-%s-ms" % phase.replace("_", "-") in proc.stdout


# ----------------------------------------------------------------------
# kvstore/serving integration (single process)
# ----------------------------------------------------------------------
def test_collective_seq_and_ledger_roundtrip(monkeypatch, tmp_path):
    """Single-process _allreduce is the identity (no dist), so drive
    the seam pieces directly the way kvstore does."""
    flight.reset()
    seq = trace.next_seq("allreduce")
    flight.collective_begin("allreduce", seq, participants=[0], bytes=64)
    assert [(e["op"], e["seq"])
            for e in flight.pending_collectives()] == [("allreduce", seq)]
    flight.collective_end("allreduce", seq)
    assert flight.pending_collectives() == []


def test_serving_requests_get_trace_ids(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    from mxnet_tpu.serving import telemetry as stel
    stel.emit_batch(model="m", bucket=8, n_requests=2, n_samples=4,
                    occupancy=0.5, padding_waste=0.5, queue_depth=0,
                    queue_wait_ms=1.0, pack_ms=1.0, device_ms=1.0,
                    unpack_ms=1.0, lat_ms=[4.0, 5.0],
                    trace_ids=["aaaa", "bbbb"])
    events.flush()
    rec = [r for r in aggregate.read_events(d)
           if r["kind"] == "serve"][0]
    assert rec["trace_ids"] == ["aaaa", "bbbb"]
    # and the Request object mints an id iff tracing is on
    from mxnet_tpu.serving.batcher import Request
    assert Request("m", None, 1).trace_id
    monkeypatch.delenv("MXTPU_TRACE")
    trace.refresh()
    assert Request("m", None, 1).trace_id is None


# ----------------------------------------------------------------------
# slo.py + benchdiff
# ----------------------------------------------------------------------
def test_rel_spread():
    assert counters.rel_spread([]) == 0.0
    assert counters.rel_spread([5.0]) == 0.0
    assert counters.rel_spread([10.0, 10.0, 10.0]) == 0.0
    spread = counters.rel_spread([100.0, 110.0, 90.0, 105.0])
    assert 0.0 < spread < 0.2


def test_load_bench_schema(tmp_path):
    # the committed BENCH schema
    p = tmp_path / "BENCH_a.json"
    p.write_text(json.dumps({
        "n": 1, "cmd": "bench", "rc": 0,
        "parsed": {"metric": "train_epoch", "value": 2.0,
                   "unit": "images/sec", "step_time_ms": 100.0}}))
    m = slo.load_bench(str(p))
    assert m == {"step_time_ms": 100.0, "images_per_sec": 2.0}
    # a failed round is skipped, not fatal
    q = tmp_path / "BENCH_b.json"
    q.write_text(json.dumps({"n": 2, "cmd": "bench", "rc": 1,
                             "parsed": None}))
    assert slo.load_bench(str(q)) is None
    # a bare metric dict (benchdiff --metrics snapshots)
    r = tmp_path / "cur.json"
    r.write_text(json.dumps({"step_time_ms": 120.0, "unknown": 5}))
    assert slo.load_bench(str(r)) == {"step_time_ms": 120.0}
    assert slo.load_bench(str(tmp_path / "missing.json")) is None


def test_load_trajectory_globs_in_name_order(tmp_path):
    for name, val in (("BENCH_r01.json", 100.0),
                      ("BENCH_r02.json", 90.0)):
        (tmp_path / name).write_text(json.dumps(
            {"rc": 0, "parsed": {"step_time_ms": val}}))
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps({"rc": 1, "parsed": None}))   # failed round skipped
    traj = slo.load_trajectory(str(tmp_path / "BENCH_*.json"))
    assert [os.path.basename(p) for p, _m in traj] == \
        ["BENCH_r01.json", "BENCH_r02.json"]
    assert [m["step_time_ms"] for _p, m in traj] == [100.0, 90.0]


def test_compare_directions_and_floor():
    base = {"step_time_ms": 100.0, "images_per_sec": 50.0}
    # +20% step time (worse-up) and -20% throughput (worse-down) flag
    regs, checked = slo.compare({"step_time_ms": 120.0,
                                 "images_per_sec": 40.0}, base)
    assert {f["metric"] for f in regs} == {"step_time_ms",
                                           "images_per_sec"}
    assert all(f["threshold_pct"] == 10.0 for f in checked)
    # equal-size IMPROVEMENTS never flag
    regs, _ = slo.compare({"step_time_ms": 80.0,
                           "images_per_sec": 60.0}, base)
    assert regs == []
    # inside the 10% floor: quiet
    regs, _ = slo.compare({"step_time_ms": 105.0}, base)
    assert regs == []


def test_compare_noise_widens_threshold():
    base = {"step_time_ms": 100.0}
    cur = {"step_time_ms": 125.0}
    regs, _ = slo.compare(cur, base)                 # floor: flags
    assert regs
    regs, checked = slo.compare(cur, base,
                                noise={"step_time_ms": 0.15})
    assert regs == []                                # 3*0.15=45% > 25%
    assert checked[0]["threshold_pct"] == 45.0


def test_telemetry_metrics_mapping():
    report = {"pod": {"step_ms_p50": 10.0, "step_ms_p95": 12.0,
                      "samples_per_sec": 640.0, "overlap_ratio": 1.3,
                      "mfu": 0.41},
              "serve": {"total": {"padding_waste": 0.2, "qps": 55.0,
                                  "latency_ms": {"p95": 30.0}}}}
    m = slo.telemetry_metrics(report)
    assert m == {"step_ms_p50": 10.0, "step_ms_p95": 12.0,
                 "samples_per_sec": 640.0, "overlap_ratio": 1.3,
                 "mfu": 0.41, "serve_padding_waste": 0.2,
                 "serve_qps": 55.0, "serve_ms_p95": 30.0}


def test_emit_regressions_lands_fault_events(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path, trace_on=False)
    regs, _ = slo.compare({"step_time_ms": 200.0},
                          {"step_time_ms": 100.0})
    slo.emit_regressions(regs, step=9, baseline_name="BENCH_x.json")
    recs = [r for r in aggregate.read_events(d)
            if r.get("fault") == "perf_regression"]
    assert len(recs) == 1
    assert recs[0]["metric"] == "step_time_ms"
    assert recs[0]["baseline_name"] == "BENCH_x.json"
    assert recs[0]["delta_pct"] == 100.0


def _benchdiff(*args):
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "benchdiff.py")]
        + list(args), cwd=_ROOT, capture_output=True, text=True,
        timeout=180)


def test_benchdiff_gate(tmp_path):
    """CI-gate contract: unchanged run exits 0, a +20% step-time
    regression against a pinned baseline exits 1, usage errors exit 2."""
    baseline = {"rc": 0, "parsed": {"step_time_ms": 100.0,
                                    "transformer_tokens_per_sec": 5e4}}
    bpath = str(tmp_path / "BENCH_base.json")
    with open(bpath, "w") as f:
        json.dump(baseline, f)
    proc = _benchdiff("--baseline", bpath, "--against", bpath)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _benchdiff("--baseline", bpath, "--metrics",
                      json.dumps({"step_time_ms": 120.0}))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "REGRESSION" in proc.stdout
    proc = _benchdiff("--baseline", bpath, "--metrics",
                      json.dumps({"step_time_ms": 120.0}), "--json")
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["regressions"][0]["metric"] == "step_time_ms"
    # improvements pass
    proc = _benchdiff("--baseline", bpath, "--metrics",
                      json.dumps({"step_time_ms": 50.0,
                                  "transformer_tokens_per_sec": 9e4}))
    assert proc.returncode == 0
    # usage errors: no source, missing baseline
    assert _benchdiff("--baseline", bpath).returncode == 2
    assert _benchdiff("--baseline",
                      str(tmp_path / "nope.json"),
                      "--metrics", "{}").returncode == 2


def test_benchdiff_against_committed_trajectory():
    """The repo's own BENCH_*.json trajectory loads and self-compares
    clean (this is the CI smoke invocation)."""
    proc = _benchdiff("--against", "BENCH_r05.json")
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ----------------------------------------------------------------------
# mxtrace
# ----------------------------------------------------------------------
def _write_rank(d, rank, recs):
    with open(os.path.join(d, "events-rank%05d.jsonl" % rank), "w") as f:
        for r in recs:
            r = dict(r, run_id="mx", rank=rank)
            f.write(json.dumps(r) + "\n")


def test_mxtrace_merges_ranks_and_stitches_flows(tmp_path):
    d = str(tmp_path / "tel")
    os.makedirs(d)
    base = 1_700_000_000_000
    for rank in (0, 1):
        _write_rank(d, rank, [
            {"kind": "step", "step": 1, "wall_ms": base + 100,
             "dur_ms": 50},
            {"kind": "span", "name": "allreduce", "step": 1,
             "wall_ms": base + 95, "dur_ms": 10, "trace_id": "t",
             "span_id": "s%d" % rank},
            {"kind": "collective", "op": "allreduce", "seq": 0,
             "wall_ms": base + 95, "dur_ms": 9, "num_workers": 2},
            {"kind": "fault", "fault": "watchdog_timeout",
             "wall_ms": base + 300},
        ])
    out = str(tmp_path / "trace.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "mxtrace.py"),
         d, "-o", out], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    doc = json.load(open(out))
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and doc["displayTimeUnit"] == "ms"
    # per-rank process tracks
    names = {(e["pid"], e["args"]["name"]) for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {(0, "rank 0"), (1, "rank 1")}
    # slices exist on both ranks and carry trace ids
    slices = [e for e in evs if e["ph"] == "X"]
    assert {e["pid"] for e in slices} == {0, 1}
    ar = [e for e in slices if e["name"] == "allreduce"]
    assert {e["args"]["span_id"] for e in ar} == {"s0", "s1"}
    # ≥1 cross-rank flow pair stitching (op, seq) across ranks
    starts = [e for e in evs if e["ph"] == "s"]
    finishes = [e for e in evs if e["ph"] == "f"]
    assert starts and finishes
    assert starts[0]["id"] == finishes[0]["id"]
    assert starts[0]["pid"] != finishes[0]["pid"]
    assert starts[0]["name"] == "allreduce seq=0"
    # faults render as instants
    assert any(e["ph"] == "i" and "watchdog_timeout" in e["name"]
               for e in evs)


def test_mxtrace_ingests_flight_dumps(tmp_path):
    d = str(tmp_path / "tel")
    os.makedirs(d)
    _write_rank(d, 0, [{"kind": "step", "step": 1,
                        "wall_ms": 1000, "dur_ms": 5}])
    with open(os.path.join(d, "flight-rank00000-0.json"), "w") as f:
        json.dump({"reason": "watchdog_timeout", "rank": 0,
                   "wall_ms": 2000, "absent_ranks": [1],
                   "pending_collectives": [
                       {"op": "allreduce", "seq": 3,
                        "launch_wall_ms": 1500,
                        "participants": [0, 1]}],
                   "events": []}, f)
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "mxtrace.py"), d],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout)
    pend = [e for e in doc["traceEvents"]
            if e["ph"] == "i" and e["name"].startswith("PENDING")]
    assert pend and pend[0]["name"] == "PENDING allreduce seq=3"
    assert pend[0]["args"]["absent_ranks"] == [1]


def test_mxtrace_empty_dir_exits_1(tmp_path):
    d = str(tmp_path / "tel")
    os.makedirs(d)
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "mxtrace.py"), d],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1


# ----------------------------------------------------------------------
# acceptance: overhead bound with tracing + flight recorder ON
# ----------------------------------------------------------------------
def test_overhead_under_2_percent_with_tracing(monkeypatch, tmp_path):
    """The ISSUE 4 <2% bound must hold with MXTPU_TRACE=1 and the
    flight recorder active: per-call cost of a traced span + record_step
    (now also ring-noting) vs the median of a small real step.  Same
    median-of-medians methodology as the ISSUE 4 test."""
    a = np.random.RandomState(0).rand(512, 512)

    def work():
        return (a @ a).sum()

    _enable(monkeypatch, tmp_path)        # telemetry + MXTPU_TRACE=1
    flight.reset()
    obs.record_step(0, 0.001)
    for _ in range(10):
        work()
    steps = []
    for _ in range(50):
        t0 = time.perf_counter()
        work()
        steps.append(time.perf_counter() - t0)
    steps.sort()
    step_s = steps[len(steps) // 2]

    costs = []
    for i in range(2000):
        t0 = time.perf_counter()
        with spans.span("step", step=i):
            pass
        obs.record_step(i, 0.001, batch_size=8)
        costs.append(time.perf_counter() - t0)
    events.flush()
    costs.sort()
    cost_s = costs[len(costs) // 2]

    ratio = (step_s + cost_s) / step_s
    assert ratio < 1.02, \
        "tracing overhead %.1f%% (hook %.1fus on a %.2fms step)" \
        % ((ratio - 1) * 100, cost_s * 1e6, step_s * 1e3)


# ----------------------------------------------------------------------
# acceptance: the 2-process hung-collective drill
# ----------------------------------------------------------------------
def test_dist_flight_drill(tmp_path):
    """Kill one worker mid-allreduce: the survivor's flight dump names
    the hung collective's seq and the absent rank, and mxtrace merges
    the run's JSONLs into a valid Chrome trace with per-rank tracks and
    cross-rank flow events."""
    tel_dir = str(tmp_path / "tel")
    cmd = [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
           "-n", "2", "--launcher", "local", "--workdir", _ROOT,
           "--port", "9904",
           sys.executable, os.path.join("tests", "nightly",
                                        "dist_flight.py")]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.update({"MXTPU_TELEMETRY": "1", "MXTPU_TELEMETRY_DIR": tel_dir,
                "MXTPU_RUN_ID": "flightdrill"})
    proc = subprocess.run(cmd, cwd=_ROOT, env=env, timeout=420,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True)
    assert proc.returncode == 0, proc.stdout[-2000:]
    assert "FLIGHT DRILL OK" in proc.stdout, proc.stdout[-2000:]

    # the survivor's dump: hung seq + absent rank (drill asserts too;
    # re-assert here so the test stands alone)
    dumps = [f for f in os.listdir(tel_dir)
             if f.startswith("flight-rank00000")]
    assert dumps, os.listdir(tel_dir)
    doc = json.load(open(os.path.join(tel_dir, sorted(dumps)[-1])))
    assert doc["reason"] == "watchdog_timeout"
    assert ("allreduce", 3) in {(e["op"], e["seq"])
                                for e in doc["pending_collectives"]}
    assert 1 in doc["absent_ranks"]

    # mxtrace merges the drill's JSONLs: valid Chrome trace, per-rank
    # tracks, ≥1 cross-rank flow event, and the pending marker
    out = str(tmp_path / "trace.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "mxtrace.py"),
         tel_dir, "-o", out], capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stderr
    trace_doc = json.load(open(out))
    evs = trace_doc["traceEvents"]
    assert {e["pid"] for e in evs if e["ph"] == "M"} == {0, 1}
    starts = [e for e in evs if e["ph"] == "s"]
    finishes = [e for e in evs if e["ph"] == "f"]
    assert starts and finishes
    assert {e["pid"] for e in starts + finishes} == {0, 1}
    assert any(e["ph"] == "i" and "PENDING allreduce seq=3" in e["name"]
               for e in evs)
