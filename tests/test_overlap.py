"""Overlap machinery (docs/perf.md "Overlap"): DevicePrefetcher,
AsyncLauncher, gradient bucketing, and the persistent compile cache.

All CPU-only: the prefetcher/launcher are host threads, bucketing is
identity math checked numerically, and the compile cache is asserted
through its lowering counter — none of it needs a chip.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.parallel import overlap


# ---------------------------------------------------------------------------
# DevicePrefetcher
# ---------------------------------------------------------------------------

def _slow_feed(n, fetch_s):
    for i in range(n):
        time.sleep(fetch_s)
        yield i


def test_prefetcher_hides_fetch_time():
    """With fetch and 'compute' each t seconds, serial is 2nt; the
    prefetcher pipelines them to ~nt.  Assert well under serial."""
    n, t = 8, 0.02
    pf = overlap.DevicePrefetcher(_slow_feed(n, t), depth=2)
    try:
        got = []
        t0 = time.perf_counter()
        for _ in range(n):
            got.append(next(pf))
            time.sleep(t)           # stands in for device compute
        wall = time.perf_counter() - t0
    finally:
        pf.close()
    assert got == list(range(n))
    serial = 2.0 * n * t
    assert wall < 0.8 * serial, (wall, serial)


def test_prefetcher_exhaustion_and_close_idempotent():
    pf = overlap.DevicePrefetcher(iter(range(3)), depth=2)
    assert [next(pf) for _ in range(3)] == [0, 1, 2]
    with pytest.raises(StopIteration):
        next(pf)
    pf.close()
    pf.close()


def test_prefetcher_propagates_producer_error():
    def bad():
        yield 1
        raise ValueError("boom in producer")

    pf = overlap.DevicePrefetcher(bad(), depth=2)
    try:
        with pytest.raises(ValueError, match="boom in producer"):
            for _ in range(3):
                next(pf)
    finally:
        pf.close()


def test_prefetcher_place_fn_runs_on_producer():
    placed = []

    def place(x):
        placed.append(x)
        return jnp.asarray(x)

    pf = overlap.DevicePrefetcher(iter([1.0, 2.0]), place_fn=place)
    try:
        a = next(pf)
        assert isinstance(a, jax.Array) and float(a) == 1.0
        assert float(next(pf)) == 2.0
        assert placed == [1.0, 2.0]
    finally:
        pf.close()


def test_prefetch_preserves_batch_stream():
    """Same iterator state machine with and without the prefetcher:
    identical batch order, data, labels, and pads across epochs
    (including the reset() at the epoch boundary)."""
    rng = np.random.RandomState(42)
    data = rng.rand(22, 3).astype(np.float32)   # 22 % 4 != 0: pads too
    label = np.arange(22, dtype=np.float32)

    def collect(it):
        out = []
        while True:
            try:
                b = it.next()
            except StopIteration:
                break
            out.append((b.data[0].asnumpy().copy(),
                        b.label[0].asnumpy().copy(), b.pad))
        return out

    plain = mx.io.NDArrayIter(data, label, batch_size=4)
    pf = overlap.DevicePrefetcher(
        mx.io.NDArrayIter(data, label, batch_size=4))
    try:
        for _epoch in range(2):
            a, b = collect(plain), collect(pf)
            assert len(a) == len(b) > 0
            for (da, la, pa), (db, lb, pb) in zip(a, b):
                np.testing.assert_array_equal(da, db)
                np.testing.assert_array_equal(la, lb)
                assert pa == pb
            plain.reset()
            pf.reset()
    finally:
        pf.close()


def test_prefetcher_reset_mid_epoch():
    """reset() drains the in-flight batches and rewinds — the stream
    restarts from batch 0, not from wherever the producer had raced
    ahead to."""
    data = np.arange(64, dtype=np.float32).reshape(16, 4)
    pf = overlap.DevicePrefetcher(
        mx.io.NDArrayIter(data, batch_size=4), depth=3)
    try:
        first = pf.next().data[0].asnumpy().copy()
        pf.reset()
        again = pf.next().data[0].asnumpy().copy()
        np.testing.assert_array_equal(first, again)
    finally:
        pf.close()


# ---------------------------------------------------------------------------
# AsyncLauncher
# ---------------------------------------------------------------------------

def test_async_launcher_fifo_and_barrier():
    seen = []
    launcher = overlap.AsyncLauncher(name="t")
    try:
        for i in range(20):
            launcher.submit(lambda i=i: seen.append(i))
        launcher.wait_all(timeout=10)
        assert seen == list(range(20)), "single worker must preserve order"
    finally:
        launcher.close()


def test_async_launcher_reraises_first_error():
    launcher = overlap.AsyncLauncher(name="t")
    try:
        launcher.submit(lambda: (_ for _ in ()).throw(RuntimeError("first")))
        launcher.submit(lambda: None)
        with pytest.raises(RuntimeError, match="first"):
            launcher.wait_all(timeout=10)
    finally:
        launcher.close()


# ---------------------------------------------------------------------------
# gradient bucketing
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mb", [0.0001, 0.001, 0.1, 25.0])
def test_partition_buckets_covers_every_grad_once(mb):
    shapes = [(3,), (128, 128), (1000,), (7, 11), (2048, 64), (5,), (1,)]
    sized = [("g%d" % i, int(np.prod(s)) * 4)
             for i, s in enumerate(shapes)]
    buckets = partitioned = overlap.partition_buckets(
        sized, bucket_nbytes=int(mb * (1 << 20)))
    flat = [k for b in partitioned for k in b]
    assert flat == [k for k, _ in sized], "order-preserving, each exactly once"
    assert all(b for b in buckets), "no empty buckets"
    target = int(mb * (1 << 20))
    for b in buckets:
        size = sum(n for k, n in sized if k in b)
        # only a single oversize item may exceed the target
        assert size <= target or len(b) == 1


def test_partition_buckets_disabled_is_single_bucket():
    sized = [("a", 100), ("b", 200)]
    assert overlap.partition_buckets(sized, bucket_nbytes=0) == [["a", "b"]]


def test_interleave_grad_buckets_is_identity_math():
    rng = np.random.RandomState(3)
    grads = {"w%d" % i: jnp.asarray(rng.randn(64, 64).astype(np.float32))
             for i in range(6)}

    def f(gs):
        out = overlap.interleave_grad_buckets(gs, bucket_nbytes=64 * 64 * 4)
        assert set(out) == set(gs)
        return out

    out = jax.jit(f)(grads)
    for k in grads:
        np.testing.assert_array_equal(np.asarray(out[k]),
                                      np.asarray(grads[k]))


def test_kvstore_bucketed_push_matches_sync_push(monkeypatch):
    """push_async + wait_all (bucketed, async worker) must be
    numerically identical to the plain sync push."""
    monkeypatch.setenv("MXTPU_BUCKET_MB", "0.001")  # force many buckets
    shape = (16, 16)
    rng = np.random.RandomState(0)
    vals = {k: [mx.nd.array(rng.randn(*shape).astype(np.float32))
                for _ in range(3)] for k in (5, 7, 11, 13)}

    def run(asynchronous):
        kv = mx.kv.create()
        for k in vals:
            kv.init(k, mx.nd.zeros(shape))
        for k, vs in vals.items():
            if asynchronous:
                kv.push_async(k, list(vs))
            else:
                kv.push(k, list(vs))
        if asynchronous:
            kv.wait_all()
        out = {}
        for k in vals:
            o = mx.nd.empty(shape)
            kv.pull(k, out=o)
            out[k] = o.asnumpy()
        return out

    a, b = run(False), run(True)
    for k in vals:
        np.testing.assert_array_equal(a[k], b[k])


# ---------------------------------------------------------------------------
# compile cache
# ---------------------------------------------------------------------------

def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _run_trainer_step(net, mesh):
    opt = mx.optimizer.create("sgd", learning_rate=0.1,
                              rescale_grad=1.0 / 16)
    tr = parallel.ShardedTrainer(net, opt, mesh)
    mx.random.seed(0)
    params, opt_state, aux = tr.init_params(
        {"data": (16, 8)}, label_shapes={"softmax_label": (16,)})
    rng = np.random.RandomState(1)
    batch = tr.shard_batch({
        "data": rng.randn(16, 8).astype(np.float32),
        "softmax_label": (rng.rand(16) * 4).astype(np.float32)})
    params, opt_state, aux, outs = tr.step(params, opt_state, aux, batch)
    return np.asarray(outs[0])


def test_second_trainer_bind_skips_lowering():
    """Two ShardedTrainers over the same (graph, shapes, mesh, rules,
    optimizer hypers): the second adopts the cached jitted step — the
    lowering counter must not move, and outputs must agree."""
    overlap.compile_cache_clear()
    net = _mlp()
    mesh = parallel.auto_mesh()
    o1 = _run_trainer_step(net, mesh)
    st1 = overlap.compile_cache_stats()
    assert st1["lowerings"] >= 1
    o2 = _run_trainer_step(net, mesh)
    st2 = overlap.compile_cache_stats()
    assert st2["lowerings"] == st1["lowerings"], \
        "identical second bind must not lower again: %s -> %s" % (st1, st2)
    assert st2["hits"] >= st1["hits"] + 1
    np.testing.assert_allclose(o1, o2, rtol=1e-6, atol=1e-7)


def test_different_optimizer_hypers_miss_cache():
    """Changed learning rate -> different baked constants -> the key
    must miss (correctness over reuse)."""
    overlap.compile_cache_clear()
    net = _mlp()
    mesh = parallel.auto_mesh()
    _run_trainer_step(net, mesh)
    st1 = overlap.compile_cache_stats()

    opt = mx.optimizer.create("sgd", learning_rate=0.5,
                              rescale_grad=1.0 / 16)
    tr = parallel.ShardedTrainer(net, opt, mesh)
    params, opt_state, aux = tr.init_params(
        {"data": (16, 8)}, label_shapes={"softmax_label": (16,)})
    rng = np.random.RandomState(1)
    batch = tr.shard_batch({
        "data": rng.randn(16, 8).astype(np.float32),
        "softmax_label": (rng.rand(16) * 4).astype(np.float32)})
    tr.step(params, opt_state, aux, batch)
    st2 = overlap.compile_cache_stats()
    assert st2["lowerings"] == st1["lowerings"] + 1


def test_executor_program_registry_hits_fresh_symbol():
    """A structurally identical but FRESH Symbol (rebind-after-rebuild)
    reuses the traced program via the graph-hash registry."""
    overlap.compile_cache_clear()

    def build():
        d = mx.sym.Variable("data")
        w = mx.sym.Variable("w")
        return mx.sym.FullyConnected(data=d, weight=w, no_bias=True,
                                     num_hidden=4, name="fc")

    build().simple_bind(mx.cpu(), data=(2, 3), w=(4, 3))
    st1 = overlap.compile_cache_stats()
    build().simple_bind(mx.cpu(), data=(2, 3), w=(4, 3))
    st2 = overlap.compile_cache_stats()
    assert st2["lowerings"] == st1["lowerings"]
    assert st2["hits"] == st1["hits"] + 1


def test_cache_key_components_change_key():
    k0 = overlap.cache_key("a", (1, 2), "x")
    assert k0 == overlap.cache_key("a", (1, 2), "x"), "deterministic"
    assert k0 != overlap.cache_key("a", (1, 3), "x")
    assert k0 != overlap.cache_key("a", (1, 2), "y")


# ---------------------------------------------------------------------------
# overlap_report
# ---------------------------------------------------------------------------

def _rec(kind, wall_ms, dur_ms, name=None, rank=0):
    r = {"kind": kind, "wall_ms": wall_ms, "dur_ms": dur_ms, "rank": rank}
    if name:
        r["name"] = name
    return r


def test_overlap_report_serial_vs_overlapped():
    from mxnet_tpu.observability import overlap_report
    # serial: steps tile the wall exactly, no spans inside the window
    serial = [_rec("step", 1000.0 * i, 1000.0) for i in range(1, 6)]
    rep = overlap_report(serial)
    assert rep["steps"] == 5
    assert abs(rep["overlap_ratio"] - 1.0) < 1e-6
    # overlapped: producer data_wait spans land INSIDE the same wall
    # (a span stamped past the last step record is outside the window)
    overlapped = serial + [
        _rec("span", 1000.0 * i + 500.0, 900.0, name="data_wait")
        for i in range(2, 5)]
    rep2 = overlap_report(overlapped)
    assert rep2["overlap_ratio"] > 1.5
    assert rep2["phase_ms"]["data_wait"] == pytest.approx(2700.0)
    assert rep2["phase_p50_ms"]["data_wait"] == pytest.approx(900.0)


def test_overlap_report_excludes_first_step_and_outside_spans():
    from mxnet_tpu.observability import overlap_report
    recs = [
        _rec("step", 0.0, 60000.0),          # compile step: bounds only
        _rec("step", 61000.0, 1000.0),
        _rec("step", 62000.0, 1000.0),
        # span before the window: excluded
        _rec("span", -5.0, 500.0, name="data_wait"),
    ]
    rep = overlap_report(recs)
    assert rep["serial_ms"] == pytest.approx(2000.0)
    assert rep["wall_ms"] == pytest.approx(62000.0)


def test_overlap_report_too_few_steps():
    from mxnet_tpu.observability import overlap_report
    rep = overlap_report([_rec("step", 0.0, 10.0)])
    assert rep["overlap_ratio"] is None
