"""BENCH_SMOKE contract: the <60s chip-health tier emits one JSON line
with the step/donation/decode signals (docs/perf.md session-start
ritual).  Runs the measurement child directly on forced-CPU — the
orchestrator's probe/fallback logic is exercised by the driver."""
import json
import os
import subprocess
import sys
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_smoke_contract():
    env = dict(os.environ)
    env.update({
        "MXTPU_BENCH_CHILD": "1",
        "BENCH_SMOKE": "1",
        "BENCH_FORCE_PLATFORM": "cpu",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _ROOT,  # no ambient site dirs: never touch a real backend
    })
    p = subprocess.run([sys.executable, os.path.join(_ROOT, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=500)
    assert p.returncode == 0, p.stderr[-1500:]
    lines = [l for l in p.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1, p.stdout
    d = json.loads(lines[0])
    assert d["smoke"] is True
    assert d["metric"] == "smoke_resnet18_step_ms" and d["value"] > 0
    assert d["donation_ok"] is True
    # decode check ran (float ms/record, or an explicit failure string —
    # never silently absent)
    assert "decode_ms_per_record" in d
    assert d["compile_s"] > 0 and d["total_s"] > 0


@pytest.mark.slow
def test_bench_smoke_disabled_by_zero():
    """BENCH_SMOKE=0 must run the FULL bench, not the smoke tier (the
    file's boolean-knob convention: "0" disables)."""
    env = dict(os.environ)
    env.update({
        "MXTPU_BENCH_CHILD": "1",
        "BENCH_SMOKE": "0",
        "BENCH_FORCE_PLATFORM": "cpu",
        "JAX_PLATFORMS": "cpu",
        "BENCH_LAYERS": "18",
        "BENCH_BATCH": "2",
        "BENCH_STEPS": "1",
        "BENCH_AUTOTUNE": "0",
        "BENCH_SECONDARY": "0",
        "PYTHONPATH": _ROOT,  # no ambient site dirs: never touch a real backend
    })
    p = subprocess.run([sys.executable, os.path.join(_ROOT, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=500)
    assert p.returncode == 0, p.stderr[-1500:]
    d = json.loads([l for l in p.stdout.splitlines()
                    if l.startswith("{")][-1])
    assert d["metric"] == "resnet18_train_images_per_sec", d
    assert "smoke" not in d

@pytest.mark.slow
def test_bench_replay_of_session_harvest(tmp_path):
    """When every probe fails, the operator opted in with
    BENCH_ALLOW_REPLAY=1, and a real-TPU measurement was banked earlier
    in the session (by the chip watcher), the orchestrator must replay
    it with explicit provenance markers — including a metric renamed
    with the _replayed suffix so naive consumers can't mistake it for a
    fresh measurement — instead of emitting a meaningless CPU number."""
    import time
    harvest = {"metric": "resnet50_train_images_per_sec", "value": 2500.0,
               "unit": "images/sec", "vs_baseline": 14.7,
               "platform": "tpu", "device_kind": "TPU v5 lite",
               "measured_at_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime()),
               "mfu": 0.31}
    path = tmp_path / "harvest.json"
    path.write_text(json.dumps(harvest) + "\n")
    env = dict(os.environ)
    env.update({
        # invalid platform -> the probe child errors out instantly, so
        # the orchestrator reaches its fallback chain without touching
        # any real backend
        "JAX_PLATFORMS": "__no_such_platform__",
        "BENCH_PROBE_RETRIES": "1",
        "BENCH_PROBE_TIMEOUT": "60",
        "BENCH_ALLOW_REPLAY": "1",
        "BENCH_SESSION_HARVEST": str(path),
        "PYTHONPATH": _ROOT,  # no ambient site dirs: never touch a real backend
    })
    p = subprocess.run([sys.executable, os.path.join(_ROOT, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=500)
    assert p.returncode == 0, p.stderr[-1500:]
    d = json.loads([l for l in p.stdout.splitlines()
                    if l.startswith("{")][-1])
    assert d["platform"] == "tpu" and d["value"] == 2500.0
    assert d["metric"] == "resnet50_train_images_per_sec_replayed", d
    assert d["replayed_from_session_harvest"] is True
    assert "banked_at_utc" in d and "banked at" in d["note"]

    # BENCH_NO_REPLAY must disable the replay (honest-fallback knob).
    # The orchestrator's attempt-4 child overrides JAX_PLATFORMS to cpu,
    # so this leg lands on a real (tiny) CPU measurement — the assertion
    # is that it is a fresh measurement, not a replay
    env["BENCH_NO_REPLAY"] = "1"
    env["BENCH_CPU_STEPS"] = "1"
    env["BENCH_CPU_BATCH"] = "2"
    env["BENCH_LAYERS"] = "18"   # keep the cpu-fallback leg fast
    env["BENCH_SECONDARY"] = "0"
    p = subprocess.run([sys.executable, os.path.join(_ROOT, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=500)
    assert p.returncode == 0, p.stderr[-1500:]
    d = json.loads([l for l in p.stdout.splitlines()
                    if l.startswith("{")][-1])
    assert "replayed_from_session_harvest" not in d
    assert d.get("platform") == "cpu"   # fresh cpu-fallback measurement


@pytest.mark.slow
def test_bench_replay_rejects_smoke_and_stale(tmp_path):
    """A banked smoke line, an over-age measurement, or a payload with
    no embedded emit-time stamp must never be replayed as the headline
    number (code-review findings r5)."""
    import time
    env_base = dict(os.environ)
    env_base.update({
        "JAX_PLATFORMS": "__no_such_platform__",
        "BENCH_PROBE_RETRIES": "1",
        "BENCH_PROBE_TIMEOUT": "60",
        "BENCH_CPU_STEPS": "1",
        "BENCH_CPU_BATCH": "2",
        "BENCH_LAYERS": "18",
        "BENCH_SECONDARY": "0",
        "PYTHONPATH": _ROOT,  # no ambient site dirs: never touch a real backend
    })
    # opted in: the rejections below must hold even when replay is allowed
    env_base["BENCH_ALLOW_REPLAY"] = "1"
    cases = {
        "smoke": {"metric": "smoke_resnet18_step_ms", "value": 100.0,
                  "smoke": True, "platform": "tpu",
                  "measured_at_utc": time.strftime(
                      "%Y-%m-%dT%H:%M:%SZ", time.gmtime())},
        "unstamped": {"metric": "resnet50_train_images_per_sec",
                      "value": 2500.0, "platform": "tpu"},
        "stale": {"metric": "resnet50_train_images_per_sec",
                  "value": 2500.0, "platform": "tpu",
                  "measured_at_utc": "2026-01-01T00:00:00Z"},
        "preliminary": {"metric": "resnet50_train_images_per_sec",
                        "value": 1200.0, "platform": "tpu",
                        "note": "preliminary (autotune sweep in progress)",
                        "measured_at_utc": time.strftime(
                            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())},
    }
    for name, harvest in cases.items():
        path = tmp_path / ("%s.json" % name)
        path.write_text(json.dumps(harvest) + "\n")
        env = dict(env_base)
        env["BENCH_SESSION_HARVEST"] = str(path)
        p = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "bench.py")],
            env=env, capture_output=True, text=True, timeout=500)
        assert p.returncode == 0, p.stderr[-1500:]
        d = json.loads([l for l in p.stdout.splitlines()
                        if l.startswith("{")][-1])
        assert "replayed_from_session_harvest" not in d, (name, d)

    # a fully eligible harvest without the BENCH_ALLOW_REPLAY=1 opt-in
    # must also fall through to a fresh measurement
    harvest = {"metric": "resnet50_train_images_per_sec", "value": 2500.0,
               "platform": "tpu",
               "measured_at_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime())}
    path = tmp_path / "eligible.json"
    path.write_text(json.dumps(harvest) + "\n")
    env = dict(env_base)
    env.pop("BENCH_ALLOW_REPLAY")
    env["BENCH_SESSION_HARVEST"] = str(path)
    p = subprocess.run([sys.executable, os.path.join(_ROOT, "bench.py")],
                       env=env, capture_output=True, text=True, timeout=500)
    assert p.returncode == 0, p.stderr[-1500:]
    d = json.loads([l for l in p.stdout.splitlines()
                    if l.startswith("{")][-1])
    assert "replayed_from_session_harvest" not in d, d
    assert d.get("platform") == "cpu"
