"""BENCH_SMOKE contract: the <60s chip-health tier emits one JSON line
with the step/donation/decode signals (docs/perf.md session-start
ritual).  Runs the measurement child directly on forced-CPU — the
orchestrator's probe/fallback logic is exercised by the driver."""
import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_contract():
    env = dict(os.environ)
    env.update({
        "MXTPU_BENCH_CHILD": "1",
        "BENCH_SMOKE": "1",
        "BENCH_FORCE_PLATFORM": "cpu",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _ROOT + os.pathsep + env.get("PYTHONPATH", ""),
    })
    p = subprocess.run([sys.executable, os.path.join(_ROOT, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=500)
    assert p.returncode == 0, p.stderr[-1500:]
    lines = [l for l in p.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1, p.stdout
    d = json.loads(lines[0])
    assert d["smoke"] is True
    assert d["metric"] == "smoke_resnet18_step_ms" and d["value"] > 0
    assert d["donation_ok"] is True
    # decode check ran (float ms/record, or an explicit failure string —
    # never silently absent)
    assert "decode_ms_per_record" in d
    assert d["compile_s"] > 0 and d["total_s"] > 0


def test_bench_smoke_disabled_by_zero():
    """BENCH_SMOKE=0 must run the FULL bench, not the smoke tier (the
    file's boolean-knob convention: "0" disables)."""
    env = dict(os.environ)
    env.update({
        "MXTPU_BENCH_CHILD": "1",
        "BENCH_SMOKE": "0",
        "BENCH_FORCE_PLATFORM": "cpu",
        "JAX_PLATFORMS": "cpu",
        "BENCH_LAYERS": "18",
        "BENCH_BATCH": "2",
        "BENCH_STEPS": "1",
        "BENCH_AUTOTUNE": "0",
        "BENCH_SECONDARY": "0",
        "PYTHONPATH": _ROOT + os.pathsep + env.get("PYTHONPATH", ""),
    })
    p = subprocess.run([sys.executable, os.path.join(_ROOT, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=500)
    assert p.returncode == 0, p.stderr[-1500:]
    d = json.loads([l for l in p.stdout.splitlines()
                    if l.startswith("{")][-1])
    assert d["metric"] == "resnet18_train_images_per_sec", d
    assert "smoke" not in d
