"""Ring attention / flash kernel / transformer ops.

Ring vs full-attention equality runs on the 8-device CPU mesh from
conftest (the multi-chip stand-in, SURVEY §4)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.parallel.ring_attention import (
    attention_reference, blockwise_combine, flash_attention, ring_attention)
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient

rng = np.random.RandomState(11)


def _qkv(B=2, H=2, S=32, D=8):
    q = rng.randn(B, H, S, D).astype(np.float32)
    k = rng.randn(B, H, S, D).astype(np.float32)
    v = rng.randn(B, H, S, D).astype(np.float32)
    return q, k, v


def test_blockwise_combine_matches_full():
    q, k, v = _qkv()
    full = attention_reference(q, k, v)
    blocks = [(k[..., i:i + 8, :], v[..., i:i + 8, :])
              for i in range(0, 32, 8)]
    blk = blockwise_combine(q, blocks)
    assert_almost_equal(np.asarray(blk), np.asarray(full),
                        rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_interpret_matches_reference(causal):
    q, k, v = _qkv(B=1, H=2, S=16, D=8)
    want = attention_reference(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                          interpret=True)
    assert_almost_equal(np.asarray(got), np.asarray(want),
                        rtol=1e-4, atol=1e-5)


def test_flash_force_ignored_outside_aot_scope(monkeypatch):
    """A leaked MXTPU_FLASH_FORCE on a cpu backend must fall back to the
    reference path (forcing Mosaic there aborts execution); inside
    aot_lowering_scope() the override is honored for compile-only
    lowering."""
    from mxnet_tpu.parallel import ring_attention as ra
    monkeypatch.setenv("MXTPU_FLASH_FORCE", "1")
    q, k, v = _qkv(B=1, H=2, S=256, D=8)   # multiple of the 128 blocks
    want = attention_reference(q, k, v)
    got = flash_attention(q, k, v)   # env leaked, no scope: reference
    assert_almost_equal(np.asarray(got), np.asarray(want),
                        rtol=1e-4, atol=1e-5)
    # inside the scope the override IS honored: flash_attention takes
    # the Mosaic kernel path, which the cpu backend cannot lower — the
    # error (instead of a silent reference fallback) proves the branch
    with ra.aot_lowering_scope():
        assert ra._AOT_LOWERING_DEPTH == 1
        with pytest.raises(Exception):
            jax.jit(lambda a, b, c: flash_attention(a, b, c)
                    ).lower(q, k, v)
    assert ra._AOT_LOWERING_DEPTH == 0


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    n_sp = 4
    B, H, S, D = 2, 2, 32, 8
    q, k, v = _qkv(B, H, S, D)
    want = attention_reference(q, k, v, causal=causal)

    devs = np.array(jax.devices()[:n_sp])
    mesh = Mesh(devs, ("sp",))

    def f(q, k, v):
        return ring_attention(q, k, v, axis_name="sp", causal=causal)

    sharded = shard_map(f, mesh=mesh,
                        in_specs=(P(None, None, "sp", None),) * 3,
                        out_specs=P(None, None, "sp", None))
    got = jax.jit(sharded)(q, k, v)
    assert_almost_equal(np.asarray(got), np.asarray(want),
                        rtol=1e-4, atol=1e-5)


def test_ring_attention_grad_flows():
    n_sp = 2
    B, H, S, D = 1, 1, 16, 4
    q, k, v = _qkv(B, H, S, D)
    devs = np.array(jax.devices()[:n_sp])
    mesh = Mesh(devs, ("sp",))

    def loss_ring(q, k, v):
        f = shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis_name="sp"),
            mesh=mesh, in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(None, None, "sp", None))
        return jnp.sum(f(q, k, v) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(attention_reference(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        assert_almost_equal(np.asarray(gr), np.asarray(gf),
                            rtol=1e-3, atol=1e-4)


# ------------------------------------------------- symbolic ops
def test_layernorm_forward_backward():
    x = rng.randn(4, 6).astype(np.float64)
    d = sym.Variable("x")
    s = sym.LayerNorm(data=d, name="ln")
    ex = s.simple_bind(mx.cpu(), x=x.shape)
    ex.arg_dict["x"][:] = x.astype(np.float32)
    ex.arg_dict["ln_gamma"][:] = np.ones(6, np.float32)
    ex.arg_dict["ln_beta"][:] = np.zeros(6, np.float32)
    out = ex.forward()[0].asnumpy()
    mu = x.mean(-1, keepdims=True)
    want = (x - mu) / np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    assert_almost_equal(out, want, rtol=1e-4, atol=1e-5)

    check_numeric_gradient(sym.sum(s * s), {
        "x": x, "ln_gamma": rng.rand(6) + 0.5, "ln_beta": rng.randn(6)},
        rtol=2e-2, atol=2e-3)


def test_mha_matches_manual():
    B, S, E, H = 2, 8, 16, 2
    x = rng.randn(B, S, E).astype(np.float32)
    wqkv = rng.randn(3 * E, E).astype(np.float32) * 0.2
    bqkv = rng.randn(3 * E).astype(np.float32) * 0.1
    wo = rng.randn(E, E).astype(np.float32) * 0.2
    bo = rng.randn(E).astype(np.float32) * 0.1

    d = sym.Variable("x")
    s = sym.MultiHeadAttention(data=d, num_heads=H, causal=True, name="att")
    ex = s.simple_bind(mx.cpu(), x=x.shape)
    ex.arg_dict["x"][:] = x
    ex.arg_dict["att_qkv_weight"][:] = wqkv
    ex.arg_dict["att_qkv_bias"][:] = bqkv
    ex.arg_dict["att_out_weight"][:] = wo
    ex.arg_dict["att_out_bias"][:] = bo
    out = ex.forward()[0].asnumpy()

    qkv = x @ wqkv.T + bqkv
    q, k, v = np.split(qkv, 3, axis=-1)
    to_heads = lambda t: t.reshape(B, S, H, E // H).transpose(0, 2, 1, 3)
    o = attention_reference(to_heads(q), to_heads(k), to_heads(v),
                            causal=True)
    o = np.asarray(o).transpose(0, 2, 1, 3).reshape(B, S, E)
    want = o @ wo.T + bo
    assert_almost_equal(out, want, rtol=1e-4, atol=1e-5)


def test_transformer_trains():
    np.random.seed(0)
    V, S = 30, 12
    net = mx.models.transformer.get_symbol(vocab_size=V, num_layers=1,
                                           num_heads=2, dim=16, seq_len=S)
    # learn to predict the next token of a fixed cyclic sequence
    seq = (np.arange(64 * S) * 7 % V).reshape(64, S).astype(np.float32)
    lbl = np.roll(seq.reshape(-1), -1).reshape(64, S)
    it = mx.io.NDArrayIter(seq, lbl, batch_size=16, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=15, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.init.Xavier())
    score = dict(mod.score(mx.io.NDArrayIter(
        seq, lbl, batch_size=16, label_name="softmax_label"), "acc"))
    assert score["accuracy"] > 0.8, score


def test_transformer_sharded_trainer_sp():
    """Full fused train step over a dp×sp mesh: MHA lowers to ring
    attention; outputs match the single-device step bit-for-bit-ish."""
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu import optimizer as opt_mod

    V, S, B = 20, 16, 4
    net = mx.models.transformer.get_symbol(vocab_size=V, num_layers=1,
                                           num_heads=2, dim=8, seq_len=S)
    r = np.random.RandomState(0)
    data = r.randint(0, V, (B, S)).astype(np.float32)
    label = r.randint(0, V, (B, S)).astype(np.float32)

    outs = {}
    for tag, kwargs in [("single", dict(dp=1)),
                        ("sp", dict(dp=2, sp=2))]:
        mesh = make_mesh(jax.devices()[:np.prod(
            [v for v in kwargs.values()])], **kwargs)
        mx.random.seed(42)  # identical param init across both runs
        opt = opt_mod.create("sgd", learning_rate=0.1)
        tr = ShardedTrainer(net, opt, mesh,
                            seq_axis=1 if "sp" in kwargs else None)
        params, opt_state, aux = tr.init_params(
            {"data": (B, S)}, label_shapes={"softmax_label": (B, S)},
            initializer=mx.init.Xavier(rnd_type="gaussian"))
        batch = tr.shard_batch({"data": data, "softmax_label": label})
        params, opt_state, aux, out = tr.step(params, opt_state, aux,
                                              batch)
        outs[tag] = np.asarray(out[0])
    assert_almost_equal(outs["single"], outs["sp"], rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_differentiable(causal):
    """The pallas forward carries a blockwise flash backward (recompute
    from saved logsumexp, O(Sq·block_k) memory) — must match reference
    grads exactly."""
    q, k, v = _qkv(B=1, H=1, S=16, D=8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=8,
                                       block_k=8, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert_almost_equal(np.asarray(a), np.asarray(b),
                            rtol=1e-3, atol=1e-4)
