"""Predictor API + plugin ops (warpctc CTC, torch bridge)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.test_utils import assert_almost_equal

rng = np.random.RandomState(5)


# ---------------------------------------------------------------- predictor
def test_predictor_roundtrip(tmp_path):
    net = mx.models.get_mlp(num_classes=3, hidden=(8,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    prefix = str(tmp_path / "m")
    arg_params, aux_params = mod.get_params()
    mx.model.save_checkpoint(prefix, 1, net, arg_params, aux_params)

    from mxnet_tpu.predictor import Predictor
    x = rng.rand(4, 10).astype(np.float32)
    pred = Predictor(prefix + "-symbol.json", prefix + "-0001.params",
                     {"data": (4, 10), "softmax_label": (4,)})
    out = pred.forward(data=x)[0]

    batch = mx.io.DataBatch(data=[mx.nd.array(x)],
                            label=[mx.nd.zeros((4,))])
    mod.forward(batch, is_train=False)
    want = mod.get_outputs()[0].asnumpy()
    assert_almost_equal(out, want, rtol=1e-5, atol=1e-6)

    # reshape path
    pred2 = pred.reshape({"data": (2, 10), "softmax_label": (2,)})
    out2 = pred2.forward(data=x[:2])[0]
    assert_almost_equal(out2, want[:2], rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------- warpctc
def test_warpctc_forward_and_grad():
    import mxnet_tpu.plugin.warpctc  # noqa: F401  registers WarpCTC
    optax = pytest.importorskip("optax")
    import jax
    import jax.numpy as jnp

    T, N, K, L = 6, 2, 5, 3
    acts = rng.randn(T * N, K).astype(np.float32)
    labels = np.array([[1, 2, 0], [3, 0, 0]], np.float32)  # 0-padded

    data = sym.Variable("data")
    label = sym.Variable("label")
    s = sym.WarpCTC(data=data, label=label, label_length=L,
                    input_length=T)
    ex = s.simple_bind(mx.cpu(), data=acts.shape, label=labels.shape,
                       grad_req={"data": "write", "label": "null"})
    ex.arg_dict["data"][:] = acts
    ex.arg_dict["label"][:] = labels
    out = ex.forward(is_train=True)[0].asnumpy()
    want_soft = np.exp(acts - acts.max(-1, keepdims=True))
    want_soft /= want_soft.sum(-1, keepdims=True)
    assert_almost_equal(out, want_soft, rtol=1e-4, atol=1e-5)

    ex.backward()
    got_grad = ex.grad_dict["data"].asnumpy()

    # independent reference: optax ctc grad computed directly
    logits = acts.reshape(T, N, K).transpose(1, 0, 2)
    lp = (labels == 0).astype(np.float32)

    def loss(lg):
        return jnp.sum(optax.ctc_loss(lg, jnp.zeros((N, T)),
                                      labels.astype(np.int32), lp,
                                      blank_id=0))

    g = np.asarray(jax.grad(loss)(jnp.asarray(logits)))
    want_grad = g.transpose(1, 0, 2).reshape(T * N, K)
    assert_almost_equal(got_grad, want_grad, rtol=1e-3, atol=1e-4)


# ---------------------------------------------------------------- torch
def test_torch_bridge_forward_backward():
    torch = pytest.importorskip("torch")
    from mxnet_tpu.plugin import torch_bridge

    lin = torch.nn.Linear(4, 3)
    x = rng.rand(5, 4).astype(np.float32)

    data = sym.Variable("x")
    s = torch_bridge.torch_module(lin, data, name="t0")
    ex = s.simple_bind(mx.cpu(), x=x.shape, grad_req="write")
    ex.arg_dict["x"][:] = x
    out = ex.forward(is_train=True)[0].asnumpy()
    with torch.no_grad():
        want = lin(torch.from_numpy(x)).numpy()
    assert_almost_equal(out, want, rtol=1e-5, atol=1e-6)

    og = rng.rand(5, 3).astype(np.float32)
    ex.backward([mx.nd.array(og)])
    want_grad = og @ lin.weight.detach().numpy()
    assert_almost_equal(ex.grad_dict["x"].asnumpy(), want_grad,
                        rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------- server shim
def test_kvstore_server_shim_runs():
    from mxnet_tpu import kvstore_server
    kv = mx.kv.create("local")
    server = kvstore_server.KVStoreServer(kv)
    server.run()  # no-op, must not raise
    ctrl = server._controller()
    import pickle
    ctrl(0, pickle.dumps(mx.optimizer.create("sgd", learning_rate=0.1)))
    assert kv._updater is not None


def test_caffe_converter_lenet():
    """tools/caffe_converter: prototxt -> Symbol (no caffe install needed);
    the classic LeNet deploy definition binds and runs."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.caffe_converter.convert_symbol import convert
    import numpy as np
    import mxnet_tpu as mx

    prototxt = '''
    name: "LeNet"
    input: "data"
    layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
      convolution_param { num_output: 20 kernel_size: 5 stride: 1 } }
    layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
      pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
    layer { name: "conv2" type: "Convolution" bottom: "pool1" top: "conv2"
      convolution_param { num_output: 50 kernel_size: 5 } }
    layer { name: "pool2" type: "Pooling" bottom: "conv2" top: "pool2"
      pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
    layer { name: "ip1" type: "InnerProduct" bottom: "pool2" top: "ip1"
      inner_product_param { num_output: 500 } }
    layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
    layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
      inner_product_param { num_output: 10 } }
    layer { name: "prob" type: "Softmax" bottom: "ip2" top: "prob" }
    '''
    sym, inputs = convert(prototxt)
    assert inputs == ["data"]
    args = sym.list_arguments()
    assert "conv1_weight" in args and "ip2_weight" in args
    exe = sym.simple_bind(mx.cpu(), grad_req="null",
                          data=(2, 1, 28, 28), softmax_label=(2,))
    out = exe.forward(is_train=False)[0]
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1), np.ones(2),
                               rtol=1e-5)


def test_sframe_iter_plugin():
    """plugin/sframe analog: dict-of-columns dataframe -> DataBatches ->
    Module.fit."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.plugin.sframe import SFrameIter

    rng = np.random.RandomState(0)
    frame = {"f1": rng.randn(200), "f2": rng.randn(200),
             "f3": rng.randn(200)}
    frame["y"] = (frame["f1"] + frame["f2"] > 0).astype(np.float32)
    it = SFrameIter(frame, data_cols=["f1", "f2", "f3"], label_col="y",
                    batch_size=20, shuffle=True)
    b = next(it)
    assert b.data[0].shape == (20, 3) and b.label[0].shape == (20,)
    it.reset()
    mod = mx.mod.Module(mx.models.get_mlp(2, (8,)), context=mx.cpu())
    mod.fit(it, optimizer="sgd", optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier(), num_epoch=6)
    it.reset()
    score = dict(mod.score(it, "acc"))
    assert score["accuracy"] > 0.9, score


def test_caffe_op_forward_backward():
    """CaffeOp (caffe_op.cc:46 analog): a prototxt-described InnerProduct
    runs as a graph op with learnable weight/bias arguments."""
    from mxnet_tpu.plugin import caffe
    rng = np.random.RandomState(3)
    data = mx.sym.Variable("data")
    fc = caffe.CaffeOp(data, prototxt='layer { type: "InnerProduct" '
                       'inner_product_param { num_output: 4 } }',
                       name="cfc")
    assert fc.list_arguments() == ["data", "cfc_weight", "cfc_bias"]
    x = rng.rand(2, 3, 2).astype(np.float32)     # caffe IP flattens
    w = rng.rand(4, 6).astype(np.float32)
    b = rng.rand(4).astype(np.float32)
    arg_shapes, out_shapes, _ = fc.infer_shape(data=(2, 3, 2))
    assert arg_shapes[1] == (4, 6) and out_shapes[0] == (2, 4)
    from mxnet_tpu.test_utils import (check_symbolic_forward,
                                      check_numeric_gradient)
    want = x.reshape(2, 6).dot(w.T) + b
    check_symbolic_forward(fc, [x, w, b], [want], rtol=1e-4, atol=1e-5)
    check_numeric_gradient(fc, {"data": x.astype(np.float64),
                                "cfc_weight": w.astype(np.float64),
                                "cfc_bias": b.astype(np.float64)},
                           rtol=2e-2, atol=2e-3)
    # activation layer with zero weights
    relu = caffe.CaffeOp(data, prototxt='layer { type: "ReLU" }', name="cr")
    assert relu.list_arguments() == ["data"]
    xa = rng.rand(3, 4).astype(np.float32) - 0.5
    check_symbolic_forward(relu, [xa], [np.maximum(xa, 0)])


def test_caffe_loss_forward_backward():
    """CaffeLoss (caffe_loss.cc:46 analog): loss-layer contract — head
    gradient ignored, grad_scale applied, no label gradient."""
    from mxnet_tpu.plugin import caffe
    from mxnet_tpu.test_utils import (check_symbolic_forward,
                                      check_symbolic_backward)
    rng = np.random.RandomState(4)
    data = mx.sym.Variable("x")
    label = mx.sym.Variable("l")

    # SoftmaxWithLoss delegates to the SoftmaxOutput contract
    sm = caffe.CaffeLoss(data, label, prototxt='layer '
                         '{ type: "SoftmaxWithLoss" }', name="cl")
    d = rng.rand(3, 5).astype(np.float32)
    lab = np.array([0, 2, 4], np.float32)
    e = np.exp(d - d.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    check_symbolic_forward(sm, [d, lab], [p], rtol=1e-3)
    onehot = np.eye(5, dtype=np.float32)[lab.astype(int)]
    og = np.full_like(d, 7.0)     # must be ignored
    check_symbolic_backward(sm, [d, lab], [og], {"x": p - onehot},
                            rtol=1e-3)

    # EuclideanLoss: fwd 1/(2N)||d-l||^2, bwd (d-l)/N * grad_scale
    eu = caffe.CaffeLoss(data, label, prototxt='layer '
                         '{ type: "EuclideanLoss" }', grad_scale=2.0,
                         name="ce")
    l2 = rng.rand(3, 5).astype(np.float32)
    want = np.array([np.sum((d - l2) ** 2) / 6.0], np.float32)
    check_symbolic_forward(eu, [d, l2], [want], rtol=1e-3)
    check_symbolic_backward(eu, [d, l2], [np.ones((1,), np.float32) * 9],
                            {"x": (d - l2) / 3.0 * 2.0,
                             "l": np.zeros_like(l2)}, rtol=1e-3)


def test_torch_criterion_forward_backward():
    """TorchCriterion (torch_criterion.cc:24 analog): torch loss as a
    loss-layer op; backward = d(loss)/d(data)*scale, head grad ignored."""
    torch = pytest.importorskip("torch")
    from mxnet_tpu.plugin import torch_bridge
    from mxnet_tpu.test_utils import (check_symbolic_forward,
                                      check_symbolic_backward)
    rng = np.random.RandomState(5)
    crit = torch.nn.MSELoss()
    data = mx.sym.Variable("x")
    label = mx.sym.Variable("l")
    s = torch_bridge.torch_criterion(crit, data, label, grad_scale=3.0,
                                     name="tc")
    d = rng.rand(4, 3).astype(np.float32)
    lab = rng.rand(4, 3).astype(np.float32)
    want = np.array([np.mean((d - lab) ** 2)], np.float32)
    check_symbolic_forward(s, [d, lab], [want], rtol=1e-4)
    # MSE grad: 2*(d-l)/numel, scaled by 3; head grad 5 must be ignored
    check_symbolic_backward(s, [d, lab], [np.full((1,), 5.0, np.float32)],
                            {"x": 2.0 * (d - lab) / d.size * 3.0,
                             "l": np.zeros_like(lab)}, rtol=1e-3)


def test_prototxt_bool_literals():
    """protobuf text-format booleans must parse as bools: bias_term: false
    means NO bias (review regression — truthy-string inversion)."""
    from mxnet_tpu.plugin import caffe
    parsed = caffe.parse_prototxt(
        'layer { type: "InnerProduct" inner_product_param '
        '{ num_output: 3 bias_term: false } }')
    assert parsed["layer"]["inner_product_param"]["bias_term"] is False
    data = mx.sym.Variable("data")
    fc = caffe.CaffeOp(data, prototxt='layer { type: "InnerProduct" '
                       'inner_product_param { num_output: 3 '
                       'bias_term: false } }', name="nb")
    assert fc.list_arguments() == ["data", "nb_weight"]
    # enum-style bare idents stay strings
    parsed2 = caffe.parse_prototxt(
        'layer { type: "Pooling" pooling_param { pool: MAX '
        'kernel_size: 2 } }')
    assert parsed2["layer"]["pooling_param"]["pool"] == "MAX"
