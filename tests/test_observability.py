"""Observability subsystem tests (ISSUE 4).

Covers the event log (off-by-default, buffering, rotation), spans,
counters/percentiles, the aggregate report builder, mxtop --json, the
Speedometer/StepTimer/Monitor satellites, the <2% overhead acceptance
bound, and the 2-process telemetry drill (tier-1 promotion of
tests/nightly/dist_telemetry.py).
"""
import json
import logging
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import observability as obs
from mxnet_tpu.observability import aggregate, counters, events, spans

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry_off(monkeypatch):
    """Each test starts with telemetry off and a pristine singleton."""
    monkeypatch.delenv("MXTPU_TELEMETRY", raising=False)
    monkeypatch.delenv("MXTPU_TELEMETRY_DIR", raising=False)
    monkeypatch.delenv("MXTPU_RUN_ID", raising=False)
    events.refresh()      # get() rate-limits env probes; force recheck
    counters.reset()
    yield
    events.refresh()      # fold env restoration into the singleton
    counters.reset()


def _enable(monkeypatch, tmp_path, run_id="testrun"):
    d = str(tmp_path / "tel")
    monkeypatch.setenv("MXTPU_TELEMETRY", "1")
    monkeypatch.setenv("MXTPU_TELEMETRY_DIR", d)
    monkeypatch.setenv("MXTPU_RUN_ID", run_id)
    events.refresh()
    return d


# ----------------------------------------------------------------------
# events.py
# ----------------------------------------------------------------------
def test_disabled_by_default():
    assert not events.enabled()
    assert events.get() is None
    events.emit("step", step=1, dur_ms=1.0)      # must be a silent no-op
    events.flush()
    assert events.last_fault() is None


def test_emit_flush_roundtrip(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    events.emit("step", step=1, dur_ms=5.0)
    events.emit("fault", step=2, fault="sentinel_skip", phase="sentinel")
    events.flush()
    path = os.path.join(d, "events-rank00000.jsonl")
    assert os.path.exists(path)
    recs = [json.loads(l) for l in open(path)]
    assert [r["kind"] for r in recs] == ["step", "fault"]
    for r in recs:
        assert r["run_id"] == "testrun"
        assert r["rank"] == 0
        assert isinstance(r["wall_ms"], int)
    assert events.last_fault()["fault"] == "sentinel_skip"


def test_emit_is_buffered_not_written(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    log = events.get()
    log.emit("step", step=1, dur_ms=1.0)
    # nothing on disk until a flush (the emit hot path does no IO)
    assert not os.path.exists(log.path) \
        or os.path.getsize(log.path) == 0
    log.flush()
    assert os.path.getsize(log.path) > 0


def test_rotation_bounds_file(tmp_path):
    log = events.EventLog(str(tmp_path), rank=3, run_id="r",
                          max_bytes=4096)
    for i in range(500):
        log.emit("step", step=i, dur_ms=1.23456, pad="x" * 40)
        if i % 50 == 0:
            log.flush()
    log.close()
    assert os.path.exists(log.path + ".1")           # one predecessor
    assert os.path.getsize(log.path) <= 4096 + 8192  # bounded
    # both generations merge in read_events
    recs = aggregate.read_events(str(tmp_path))
    assert all(r["rank"] == 3 for r in recs)


def test_env_rebuild_swaps_log(monkeypatch, tmp_path):
    _enable(monkeypatch, tmp_path, run_id="a")
    first = events.get()
    monkeypatch.setenv("MXTPU_RUN_ID", "b")
    second = events.refresh()
    assert first is not second
    assert second.run_id == "b"


# ----------------------------------------------------------------------
# spans.py
# ----------------------------------------------------------------------
def test_span_null_when_disabled():
    s1, s2 = spans.span("step"), spans.span("h2d")
    assert s1 is s2                          # shared null object
    with s1:
        pass


def test_span_records_duration(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    with spans.span("ckpt_save", step=7, extra="x"):
        time.sleep(0.01)
    events.flush()
    recs = aggregate.read_events(d)
    assert len(recs) == 1
    rec = recs[0]
    assert rec["kind"] == "span" and rec["name"] == "ckpt_save"
    assert rec["step"] == 7 and rec["extra"] == "x"
    assert rec["dur_ms"] >= 9.0


def test_timed_iter(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    out = list(spans.timed_iter([1, 2, 3], name="data_wait"))
    assert out == [1, 2, 3]
    events.flush()
    recs = aggregate.read_events(d)
    assert [r["name"] for r in recs] == ["data_wait"] * 3


# ----------------------------------------------------------------------
# counters.py
# ----------------------------------------------------------------------
def test_percentile():
    vals = list(range(1, 101))
    assert counters.percentile(vals, 50) == 50 or \
        counters.percentile(vals, 50) == 51
    assert counters.percentile(vals, 95) in (95, 96)
    assert counters.percentile([], 50) is None
    assert counters.percentile([4.0], 95) == 4.0


def test_step_stats_snapshot():
    st = counters.StepStats(batch_size=32)
    for i in range(100):
        st.observe(0.010 + (0.010 if i == 99 else 0.0), step=i)
    snap = st.snapshot()
    assert snap["steps"] == 100 and snap["last_step"] == 99
    assert snap["step_ms_p50"] == pytest.approx(10.0, rel=0.01)
    assert snap["step_ms_p95"] == pytest.approx(10.0, rel=0.01)
    assert snap["step_ms_ema"] > 10.0          # the spike moved the EMA
    assert snap["samples_per_sec"] == pytest.approx(32 / 0.0101, rel=0.01)


def test_collective_bytes_from_cost_model():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    from mxnet_tpu import parallel
    rep = counters.collective_bytes(net, parallel.auto_mesh(),
                                    shapes={"data": (16, 4)})
    assert rep is None or "total_bytes" in rep


# ----------------------------------------------------------------------
# aggregate.py report builder
# ----------------------------------------------------------------------
def _mk(kind, rank, wall_ms, **f):
    return dict(run_id="r", rank=rank, kind=kind, wall_ms=wall_ms,
                step=f.pop("step", None), **f)


def test_build_report_straggler_and_faults():
    recs = []
    t = 1000
    for step in range(10):
        recs.append(_mk("step", 0, t, step=step, dur_ms=10.0,
                        samples_per_sec=100.0))
        recs.append(_mk("step", 1, t + 1, step=step, dur_ms=30.0,
                        samples_per_sec=40.0))
        t += 40
    recs.append(_mk("fault", 1, t, step=9, fault="sentinel_skip"))
    recs.append(_mk("ckpt", 0, t + 1, step=9, phase="commit"))
    recs.append(_mk("counter", 0, t + 2, name="heartbeat_ages",
                    ages={"0": 1.5, "1": 2.5}))
    rep = aggregate.build_report(recs)
    pod = rep["pod"]
    assert pod["step_ms_p50"] is not None
    assert pod["step_ms_p95"] is not None
    assert pod["samples_per_sec"] == pytest.approx(140.0)
    # straggler gap = max(mean) - median(mean) = 30 - 20 = 10
    assert pod["straggler_gap_ms"] == pytest.approx(10.0)
    assert rep["per_rank"]["0"]["heartbeat_age_s"] == 1.5
    assert rep["per_rank"]["1"]["heartbeat_age_s"] == 2.5
    assert rep["per_rank"]["1"]["last_fault"]["fault"] == "sentinel_skip"
    kinds = [r["kind"] for r in rep["incidents"]]
    assert kinds == ["fault", "ckpt"]


def test_build_report_elastic_generation_rollup():
    """Elastic records are incidents AND set the pod's current
    generation/world (newest wins) plus each rank's adopted
    generation (docs/resilience.md "Elasticity")."""
    recs = [
        _mk("step", 0, 1000, step=0, dur_ms=10.0),
        _mk("elastic", 0, 1001, event="propose", generation=1,
            world_size=2, reason="dead_node", from_world=3),
        _mk("elastic", 1, 1002, event="adopt", generation=1,
            world_size=2, reason="dead_node", from_world=3),
        _mk("elastic", 0, 1003, event="resume", generation=2,
            world_size=3),
    ]
    rep = aggregate.build_report(recs)
    pod = rep["pod"]
    assert pod["generation"] == 2
    assert pod["world_size"] == 3
    assert pod["last_elastic"]["event"] == "resume"
    assert rep["per_rank"]["0"]["generation"] == 2
    assert rep["per_rank"]["1"]["generation"] == 1
    assert [r["kind"] for r in rep["incidents"]] == ["elastic"] * 3


def test_read_events_skips_torn_lines(tmp_path):
    p = tmp_path / "events-rank00000.jsonl"
    p.write_text('{"kind":"step","rank":0,"wall_ms":2}\n'
                 '{"kind":"st')                       # torn final write
    recs = aggregate.read_events(str(tmp_path))
    assert len(recs) == 1


def test_timeline_around():
    recs = [{"i": i} for i in range(20)]
    win = aggregate.timeline_around(recs, 10, before=2, after=3)
    assert [r["i"] for r in win] == [8, 9, 10, 11, 12, 13]


# ----------------------------------------------------------------------
# mxtop CLI
# ----------------------------------------------------------------------
def test_mxtop_json(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    for i in range(5):
        obs.record_step(i, 0.01, batch_size=8)
    events.emit("fault", step=3, fault="watchdog_timeout", phase="step")
    events.flush()
    env = dict(os.environ)
    env.pop("MXTPU_TELEMETRY", None)     # mxtop reads files, not env
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "mxtop.py"),
         d, "--json"], capture_output=True, text=True, env=env,
        timeout=120)
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["pod"]["step_ms_p50"] is not None
    assert "mfu" in rep["pod"]
    assert rep["per_rank"]["0"]["last_fault"]["fault"] == \
        "watchdog_timeout"


def test_mxtop_surfaces_elastic_generation(monkeypatch, tmp_path):
    """The pod report shows the current generation/world and --fault
    timelines anchor on elastic transitions too."""
    d = _enable(monkeypatch, tmp_path)
    obs.record_step(0, 0.01, batch_size=8)
    events.emit("elastic", event="propose", generation=1, world_size=2,
                reason="dead_node", from_world=3)
    events.emit("elastic", event="resume", generation=1, world_size=2)
    events.flush()
    env = dict(os.environ)
    env.pop("MXTPU_TELEMETRY", None)
    mxtop = os.path.join(_ROOT, "tools", "mxtop.py")
    out = subprocess.run([sys.executable, mxtop, d],
                         capture_output=True, text=True, env=env,
                         timeout=120)
    assert out.returncode == 0, out.stderr
    assert "elastic generation 1" in out.stdout, out.stdout
    assert "world size 2" in out.stdout
    out = subprocess.run([sys.executable, mxtop, d, "--fault"],
                         capture_output=True, text=True, env=env,
                         timeout=120)
    assert out.returncode == 0, out.stderr
    assert "elastic propose generation 1 (world 2)" in out.stdout, \
        out.stdout
    assert "elastic resume generation 1 (world 2)" in out.stdout


# ----------------------------------------------------------------------
# wiring: fit loops, resilience seams
# ----------------------------------------------------------------------
def _tiny_fit(**fit_kw):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    X = rng.rand(40, 8).astype(np.float32)
    y = rng.randint(0, 4, (40,))
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    model = mx.FeedForward(net, ctx=mx.context.cpu(), num_epoch=1,
                           learning_rate=0.1)
    logging.disable(logging.CRITICAL)
    try:
        model.fit(X=it, **fit_kw)
    finally:
        logging.disable(logging.NOTSET)
    return model


def test_feedforward_fit_emits_steps_and_data_wait(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    _tiny_fit()
    events.flush()
    recs = aggregate.read_events(d)
    steps = [r for r in recs if r["kind"] == "step"]
    waits = [r for r in recs if r["kind"] == "span"
             and r["name"] == "data_wait"]
    assert len(steps) == 4 and len(waits) == 4
    assert all(r["batch_size"] == 10 for r in steps)


def test_sentinel_skip_emits_fault(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    from mxnet_tpu.resilience import Sentinel
    s = Sentinel()
    s.check(1, loss=float("nan"))
    events.flush()
    recs = aggregate.read_events(d)
    faults = [r for r in recs if r["kind"] == "fault"]
    assert len(faults) == 1
    assert faults[0]["fault"] == "sentinel_skip"
    assert faults[0]["verdict"] == "skip-nonfinite"
    assert faults[0]["step"] == 1


def test_watchdog_timeout_emits_fault(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    from mxnet_tpu.resilience import ResilienceError, run_with_timeout
    with pytest.raises(ResilienceError):
        run_with_timeout(lambda: time.sleep(2.0), 0.1, phase="t",
                         step=5)
    events.flush()
    faults = [r for r in aggregate.read_events(d)
              if r["kind"] == "fault"]
    assert faults and faults[0]["fault"] == "watchdog_timeout"
    assert faults[0]["phase"] == "t" and faults[0]["step"] == 5


def test_retry_emits_fault(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    from mxnet_tpu.resilience import RetryPolicy, retry_call
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise RuntimeError("connection refused")
        return "ok"

    assert retry_call(flaky, RetryPolicy(max_tries=3),
                      sleep=lambda s: None) == "ok"
    events.flush()
    faults = [r for r in aggregate.read_events(d)
              if r["kind"] == "fault"]
    assert faults and faults[0]["fault"] == "retry"
    assert faults[0]["attempt"] == 1


def test_classic_save_checkpoint_emits_ckpt(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    args = {"fc_weight": mx.nd.array(np.ones((2, 3), np.float32)),
            "fc_bias": mx.nd.array(np.zeros(2, np.float32))}
    mx.model.save_checkpoint(str(tmp_path / "m"), 1, net, args, {})
    events.flush()
    recs = aggregate.read_events(d)
    ckpts = [r for r in recs if r["kind"] == "ckpt"]
    assert ckpts and ckpts[0]["phase"] == "commit"
    assert ckpts[0]["format"] == "classic"
    assert any(r["kind"] == "span" and r["name"] == "ckpt_save"
               for r in recs)


def test_exit_for_restart_flushes_fault(monkeypatch, tmp_path):
    """exit_for_restart must drain the telemetry buffer before
    os._exit (which skips atexit) — run in a child process."""
    d = str(tmp_path / "tel")
    code = (
        "import os\n"
        "from mxnet_tpu.resilience import ResilienceError, "
        "exit_for_restart\n"
        "err = ResilienceError('boom', phase='drill', step=42, "
        "kind='timeout')\n"
        "exit_for_restart(err)\n")
    env = {k: v for k, v in os.environ.items()}
    env.update(MXTPU_TELEMETRY="1", MXTPU_TELEMETRY_DIR=d,
               MXTPU_RUN_ID="x", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 3
    recs = aggregate.read_events(d)
    faults = [r for r in recs if r["kind"] == "fault"]
    assert faults and faults[-1]["fault"] == "exit_restart"
    assert faults[-1]["step"] == 42


def test_sharded_trainer_step_records(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    from mxnet_tpu import parallel
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mesh = parallel.auto_mesh()
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    tr = parallel.ShardedTrainer(net, opt, mesh)
    mx.random.seed(0)
    params, opt_state, aux = tr.init_params(
        {"data": (16, 8)}, label_shapes={"softmax_label": (16,)})
    rng = np.random.RandomState(0)
    batch = tr.shard_batch(
        {"data": rng.rand(16, 8).astype(np.float32),
         "softmax_label": (rng.rand(16) * 4).astype(np.float32)})
    for _ in range(3):
        params, opt_state, aux, _out = tr.step(params, opt_state, aux,
                                               batch)
    tr.emit_telemetry_counters(step_time_s=0.01)
    events.flush()
    recs = aggregate.read_events(d)
    steps = [r for r in recs if r["kind"] == "step"]
    assert len(steps) == 3
    assert all(r["batch_size"] == 16 for r in steps)
    assert any(r["kind"] == "span" and r["name"] == "h2d" for r in recs)
    cost = [r for r in recs if r["kind"] == "counter"
            and r.get("name") == "trainer_cost"]
    assert cost and cost[0]["flops_per_step"] > 0


# ----------------------------------------------------------------------
# satellites: Speedometer, StepTimer, Monitor
# ----------------------------------------------------------------------
class _Param(object):
    def __init__(self, epoch, nbatch):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = None
        self.locals = None


def test_speedometer_uses_actual_batch_count(monkeypatch, caplog):
    """After a mid-stream (re)start the window is shorter than
    ``frequent``; speed must use the true batch count."""
    sp = mx.callback.Speedometer(batch_size=10, frequent=4)
    now = [1000.0]
    monkeypatch.setattr(time, "time", lambda: now[0])
    sp(_Param(0, 3))                       # init tick at batch 3
    now[0] += 1.0
    with caplog.at_level(logging.INFO):
        sp(_Param(0, 4))                   # only ONE batch elapsed
    assert "Speed: 10.00 samples/sec" in caplog.text  # 1*10/1s, not 4*10


def test_speedometer_auto_reset_false():
    class Metric(object):
        def __init__(self):
            self.resets = 0

        def get_name_value(self):
            return [("acc", 0.5)]

        def reset(self):
            self.resets += 1

    m = Metric()
    sp = mx.callback.Speedometer(batch_size=2, frequent=1,
                                 auto_reset=False)
    p = _Param(0, 1)
    p.eval_metric = m
    sp(p)
    p = _Param(0, 2)
    p.eval_metric = m
    time.sleep(0.001)
    sp(p)
    assert m.resets == 0
    sp2 = mx.callback.Speedometer(batch_size=2, frequent=1)
    p = _Param(0, 1)
    p.eval_metric = m
    sp2(p)
    p = _Param(0, 2)
    p.eval_metric = m
    time.sleep(0.001)
    sp2(p)
    assert m.resets == 1                   # default resets per report


def test_speedometer_emits_telemetry(monkeypatch, tmp_path):
    d = _enable(monkeypatch, tmp_path)
    sp = mx.callback.Speedometer(batch_size=6, frequent=1)
    sp(_Param(0, 1))
    time.sleep(0.002)
    sp(_Param(0, 2))
    events.flush()
    recs = [r for r in aggregate.read_events(d)
            if r.get("source") == "speedometer"]
    assert len(recs) == 1
    assert recs[0]["batch_size"] == 6
    assert recs[0]["samples_per_sec"] > 0


def test_steptimer_summary_percentiles():
    t = mx.profiler.StepTimer(batch_size=16)
    for dur in [0.01] * 94 + [0.10] * 6:
        t.times.append(dur)
    s = t.summary(skip_first=0)
    assert s["steps"] == 100
    assert s["p50_s"] == pytest.approx(0.01)
    assert s["p95_s"] == pytest.approx(0.10)
    assert s["samples_per_sec"] > 0
    assert mx.profiler.StepTimer().summary() == {}


def test_monitor_nonfinite_first_nan_localized():
    """alarm_nonfinite records the FIRST poisoned tensor by name."""
    mon = mx.monitor.Monitor(interval=1, alarm_nonfinite=True)
    mon.activated = True
    mon._record("clean", mx.nd.array(np.ones(4, np.float32)))
    mon._record("first_bad",
                mx.nd.array(np.array([np.nan, 1.0], np.float32)))
    mon._record("second_bad",
                mx.nd.array(np.array([np.inf], np.float32)))
    assert len(mon.nonfinite_records) == 2
    _step, name, _stat = mon.nonfinite_records[0]
    assert name == "first_bad"


def test_monitor_nonfinite_bounded_to_100():
    mon = mx.monitor.Monitor(interval=1, alarm_nonfinite=True)
    mon.activated = True
    bad = mx.nd.array(np.array([np.nan], np.float32))
    for i in range(250):
        mon._record("bad_%d" % i, bad)
    assert len(mon.nonfinite_records) == 100
    # the record window keeps the MOST RECENT entries
    assert mon.nonfinite_records[-1][1] == "bad_249"


# ----------------------------------------------------------------------
# acceptance: overhead bound
# ----------------------------------------------------------------------
def test_enabled_overhead_under_2_percent(monkeypatch, tmp_path):
    """The enabled emit path (tuple append, no IO) must add <2% to a
    trivial-but-real step loop.

    Methodology: the hook is purely additive host code, so the loop's
    overhead IS the per-call cost of ``record_step``.  Measure the real
    step time and the hook cost as separate per-sample medians instead
    of A/B-ing two whole loops — on a shared box the BLAS wall time
    swings far more than 2% between runs, and a subtraction of two
    noisy aggregates can't resolve the bound, while each median is
    stable."""
    a = np.random.RandomState(0).rand(512, 512)

    def work():
        # a few ms of real numpy work — the smallest credible "step"
        return (a @ a).sum()

    _enable(monkeypatch, tmp_path)
    obs.record_step(0, 0.001)              # build the log + flusher
    for _ in range(10):                    # warm the BLAS path
        work()
    steps = []
    for _ in range(50):
        t0 = time.perf_counter()
        work()
        steps.append(time.perf_counter() - t0)
    steps.sort()
    step_s = steps[len(steps) // 2]

    costs = []
    for i in range(2000):                  # flusher runs alongside
        t0 = time.perf_counter()
        obs.record_step(i, 0.001, batch_size=8)
        costs.append(time.perf_counter() - t0)
    events.flush()
    costs.sort()
    cost_s = costs[len(costs) // 2]

    ratio = (step_s + cost_s) / step_s
    assert ratio < 1.02, \
        "telemetry overhead %.1f%% (hook %.1fus on a %.2fms step)" \
        % ((ratio - 1) * 100, cost_s * 1e6, step_s * 1e3)
    # the bound above was measured WITH the metrics registry live:
    # global StepStats feeds the mxtpu_step_ms histogram on every
    # record_step, so prove the registry actually saw the samples
    from mxnet_tpu.observability import metrics as _metrics
    fed = sum(h.cumulative.count
              for h in _metrics.registry().histograms("mxtpu_step_ms"))
    assert fed >= 2000


# ----------------------------------------------------------------------
# acceptance: the 2-process drill (tier-1 promotion)
# ----------------------------------------------------------------------
def _launch(script, tmp_path, n=2, port=9901, extra_env=None,
            expect_rc=0):
    cmd = [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
           "-n", str(n), "--launcher", "local", "--workdir", _ROOT,
           "--port", str(port),
           sys.executable, os.path.join("tests", "nightly", script)]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.update(extra_env or {})
    proc = subprocess.run(cmd, cwd=_ROOT, env=env, timeout=420,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True)
    assert proc.returncode == expect_rc, (proc.returncode,
                                          proc.stdout[-2000:])
    return proc.stdout


def test_dist_telemetry_drill(tmp_path):
    """Acceptance: 2-process CPU run with telemetry on produces
    per-rank JSONL whose merged mxtop --json report carries step-time
    p50/p95, samples/sec, straggler gap, per-rank heartbeat age, and
    the injected sentinel -> watchdog -> ckpt incidents in order."""
    tel_dir = str(tmp_path / "tel")
    prefix = str(tmp_path / "drillckpt")
    out = _launch("dist_telemetry.py", tmp_path, port=9903,
                  extra_env={"MXTPU_TELEMETRY": "1",
                             "MXTPU_TELEMETRY_DIR": tel_dir,
                             "MXTPU_RUN_ID": "drill",
                             "MXTPU_SENTINEL": "1",
                             "MXTPU_FAULT_SPEC": "step=2:kind=nan",
                             "MXTPU_TEL_PREFIX": prefix})
    assert out.count("TELEMETRY DRILL OK") == 2, out[-1500:]

    # per-rank JSONL exists for both ranks
    for rank in (0, 1):
        assert os.path.exists(os.path.join(
            tel_dir, "events-rank%05d.jsonl" % rank)), os.listdir(tel_dir)

    # merged mxtop --json report carries the acceptance fields
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "mxtop.py"),
         tel_dir, "--json"], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    rep = json.loads(proc.stdout)
    assert sorted(rep["ranks"]) == [0, 1]
    assert rep["run_ids"] == ["drill"]
    pod = rep["pod"]
    assert pod["step_ms_p50"] is not None
    assert pod["step_ms_p95"] is not None
    assert pod["samples_per_sec"] is not None
    assert pod["straggler_gap_ms"] is not None
    assert "mfu" in pod
    for rank in ("0", "1"):
        age = rep["per_rank"][rank]["heartbeat_age_s"]
        assert age is not None and age < 300

    # the injected incident story, in order, on every rank:
    # sentinel_skip (the NaN batch) -> watchdog_timeout -> ckpt commit
    records = aggregate.read_events(tel_dir)
    for rank in (0, 1):
        mine = [r for r in records if r.get("rank") == rank]
        sent = [i for i, r in enumerate(mine)
                if r["kind"] == "fault"
                and r.get("fault") == "sentinel_skip"]
        wdog = [i for i, r in enumerate(mine)
                if r["kind"] == "fault"
                and r.get("fault") == "watchdog_timeout"]
        assert sent, "rank %d missing sentinel_skip" % rank
        assert wdog, "rank %d missing watchdog_timeout" % rank
        assert sent[0] < wdog[0]
    ckpt = [r for r in records if r["kind"] == "ckpt"
            and r.get("phase") == "commit"]
    assert ckpt and ckpt[0]["rank"] == 0
    wdog_wall = max(r["wall_ms"] for r in records
                    if r["kind"] == "fault"
                    and r.get("fault") == "watchdog_timeout")
    assert ckpt[0]["wall_ms"] >= wdog_wall

    # collective traffic from the dist_sync push path made it in
    assert any(r["kind"] == "collective" for r in records)

    # parse_log.py reads the telemetry dir directly
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "parse_log.py"),
         tel_dir], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "step-ms" in proc.stdout


# ----------------------------------------------------------------------
# ISSUE 19: sketch-backed StepStats + exact fleet/pod sketch merges
# ----------------------------------------------------------------------
def test_step_stats_sketch_backing():
    """StepStats percentiles come from a mergeable sketch and the
    snapshot carries the serialized sketch for pod rollups."""
    from mxnet_tpu.observability.metrics import QuantileSketch
    st = counters.StepStats(batch_size=8)
    for i in range(50):
        st.observe(0.010 + 0.0001 * i, step=i)
    snap = st.snapshot()
    assert "step_sketch" in snap
    back = QuantileSketch.from_dict(snap["step_sketch"])
    assert back.count == 50
    assert back.quantile(0.5) == pytest.approx(snap["step_ms_p50"],
                                               abs=1e-3)


def test_fleet_rollup_sketch_merge_exact():
    """Acceptance: the fleet-wide latency percentiles are the EXACT
    sketch-merge of per-replica streams — identical to one sketch fed
    the concatenated stream, never an average of percentiles."""
    from mxnet_tpu.observability.metrics import QuantileSketch
    from mxnet_tpu.serving.telemetry import fleet_report
    import random
    rng = random.Random(19)
    recs, all_lats = [], []
    t = 1000.0
    for replica in range(3):
        for batch in range(20):
            lats = [rng.lognormvariate(3.0, 0.8) for _ in range(8)]
            all_lats.extend(lats)
            recs.append(dict(kind="serve", replica=replica,
                             model="echo", n_requests=len(lats),
                             lat_ms=lats, wall_ms=t))
            t += 10.0
    fl = fleet_report(recs)
    assert len(fl["replicas"]) == 3
    whole = QuantileSketch()
    whole.extend(all_lats)
    lat = fl["latency_ms"]
    assert lat["p50"] == round(whole.percentile(50), 3)
    assert lat["p95"] == round(whole.percentile(95), 3)
    assert lat["p99"] == round(whole.percentile(99), 3)


def test_pod_rollup_merges_step_sketches():
    """build_report's pod p50/p95 come from merging per-rank step
    sketches — identical to one sketch over every rank's durations."""
    from mxnet_tpu.observability.metrics import QuantileSketch
    recs = []
    t = 1000
    durs = {0: 10.0, 1: 30.0}
    for step in range(20):
        for rank in (0, 1):
            recs.append(_mk("step", rank, t + rank, step=step,
                            dur_ms=durs[rank]))
        t += 40
    report = aggregate.build_report(recs)
    whole = QuantileSketch(alpha=counters.StepStats.SKETCH_ALPHA)
    for rank in (0, 1):
        whole.extend([durs[rank]] * 20)
    assert report["pod"]["step_ms_p50"] == \
        pytest.approx(whole.percentile(50), abs=1e-3)
    assert report["pod"]["step_ms_p95"] == \
        pytest.approx(whole.percentile(95), abs=1e-3)
    for s in report["per_rank"].values():
        assert "step_sketch" in s


def test_build_report_slo_rollup_and_mxtop_pane():
    """slo_alert / slo_recommendation records roll up into
    report['slo'] and mxtop renders the SLO pane from it."""
    import io
    recs = [
        _mk("step", 0, 1000, step=0, dur_ms=10.0),
        _mk("slo_alert", 0, 1010, metric="mxtpu_serve_latency_ms",
            tier="page", edge="fire", target=250.0, budget=0.01,
            threshold_burn=14.0, windows_s=[60, 10],
            burns={"60": 31.2, "10": 48.0}, at=1.01, source="mxserve"),
        _mk("counter", 0, 1011, name="slo_recommendation",
            action="recommend_grow", gen=1,
            metric="mxtpu_serve_latency_ms", reason="page-tier burn"),
        _mk("slo_alert", 0, 1050, metric="mxtpu_serve_latency_ms",
            tier="page", edge="clear", target=250.0, budget=0.01,
            threshold_burn=14.0, windows_s=[60, 10],
            burns={"60": 0.4, "10": 0.0}, at=1.05, source="mxserve"),
    ]
    report = aggregate.build_report(recs)
    slo = report["slo"]
    assert slo["alerts"] == 1            # fire edges only
    assert slo["page_alerts"] == 1
    assert slo["active"] == []           # the clear closed it
    assert slo["last_alert"]["edge"] == "clear"
    assert slo["recommendations"] == 1
    assert slo["last_recommendation"]["action"] == "recommend_grow"
    sys.path.insert(0, os.path.join(_ROOT, "tools"))
    import mxtop
    buf = io.StringIO()
    mxtop.render_slo(report, stream=buf)
    text = buf.getvalue()
    assert "SLO" in text
    assert "recommend_grow" in text


def test_metrics_exposition_from_serving_telemetry(monkeypatch):
    """The always-on serving feed: emit_batch lands in the registry
    and render_prometheus exposes it (what GET /metrics serves)."""
    from mxnet_tpu.observability import metrics as _metrics
    from mxnet_tpu.serving import telemetry as stel
    _metrics.reset_registry()
    stel.emit_batch(model="echo", bucket=8, n_requests=4, n_samples=8,
                    occupancy=0.5, padding_waste=0.5, queue_depth=2,
                    queue_wait_ms=1.0, pack_ms=0.1, device_ms=4.0,
                    unpack_ms=0.1, lat_ms=[5.0, 9.0, 12.0, 30.0])
    text = _metrics.render_prometheus()
    rows = _metrics.parse_prometheus(text)
    vals = {(n, tuple(sorted(l.items()))): v for n, l, v in rows}
    assert vals[("mxtpu_serve_requests_total", ())] == 4.0
    assert vals[("mxtpu_serve_batches_total", ())] == 1.0
    assert vals[("mxtpu_serve_queue_depth", ())] == 2.0
    assert any(n == "mxtpu_serve_latency_ms" for n, _, _ in rows)
    _metrics.reset_registry()
