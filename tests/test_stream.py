"""Remote stream URIs (dmlc::Stream parity, VERDICT r3 #6).

Every persistence path — NDArray save/load, Symbol save/load,
checkpoints, RecordIO, ImageRecordIter — must accept scheme URIs the way
the reference's dmlc::Stream makes S3/HDFS paths work everywhere
(docs/how_to/cloud.md:84).  fsspec's ``memory://`` filesystem is the
in-process fake remote."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio as rio
from mxnet_tpu.test_utils import assert_almost_equal

pytest.importorskip("fsspec")

rng = np.random.RandomState(0)


def _uri(name):
    return "memory://mxtpu-test/%s" % name


def test_ndarray_save_load_memory_uri():
    arrs = {"w": mx.nd.array(rng.rand(3, 4).astype(np.float32)),
            "b": mx.nd.array(rng.rand(4).astype(np.float32))}
    uri = _uri("nd.params")
    mx.nd.save(uri, arrs)
    back = mx.nd.load(uri)
    assert sorted(back) == ["b", "w"]
    for k in arrs:
        assert_almost_equal(back[k].asnumpy(), arrs[k].asnumpy())


def test_symbol_save_load_memory_uri():
    net = mx.models.get_mlp(2, (8,))
    uri = _uri("net-symbol.json")
    net.save(uri)
    back = mx.sym.load(uri)
    assert back.list_arguments() == net.list_arguments()


def test_checkpoint_roundtrip_memory_uri():
    net = mx.models.get_mlp(2, (8,))
    arg_shapes, _, aux_shapes = net.infer_shape(data=(4, 10))
    args = {n: mx.nd.array(rng.rand(*s).astype(np.float32))
            for n, s in zip(net.list_arguments(), arg_shapes)
            if n not in ("data", "softmax_label")}
    prefix = _uri("ckpt/model")
    mx.model.save_checkpoint(prefix, 3, net, args, {})
    sym2, args2, aux2 = mx.model.load_checkpoint(prefix, 3)
    assert sym2.list_arguments() == net.list_arguments()
    for k in args:
        assert_almost_equal(args2[k].asnumpy(), args[k].asnumpy())


def test_recordio_roundtrip_memory_uri():
    uri = _uri("data.rec")
    w = rio.MXRecordIO(uri, "w")
    payloads = [b"rec-%d" % i * (i + 1) for i in range(20)]
    for p in payloads:
        w.write(p)
    w.close()

    r = rio.MXRecordIO(uri, "r")
    got = []
    while True:
        item = r.read()
        if item is None:
            break
        got.append(item)
    r.close()
    assert got == payloads


def test_indexed_recordio_memory_uri():
    rec = _uri("idx_data.rec")
    idx = _uri("idx_data.idx")
    w = rio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(10):
        w.write_idx(i, b"payload-%03d" % i)
    w.close()

    r = rio.MXIndexedRecordIO(idx, rec, "r")
    assert r.keys == list(range(10))
    assert r.read_idx(7) == b"payload-007"
    assert r.read_idx(2) == b"payload-002"
    r.close()


def test_image_record_iter_memory_uri():
    uri = _uri("images.rec")
    w = rio.MXRecordIO(uri, "w")
    img = rng.randint(0, 255, (3, 8, 8), np.uint8)
    for i in range(16):
        w.write(rio.pack(rio.IRHeader(0, float(i % 4), i, 0), img.tobytes()))
    w.close()

    it = mx.io.ImageRecordIter(path_imgrec=uri, data_shape=(3, 8, 8),
                               batch_size=4, dtype="uint8",
                               preprocess_threads=1, prefetch_buffer=2)
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (4, 3, 8, 8)
