"""Random sampling tests (modeled on tests/python/unittest/test_random.py)."""
import numpy as np

import mxnet_tpu as mx


def test_seed_determinism():
    mx.random.seed(7)
    a = mx.random.uniform(0, 1, shape=(100,)).asnumpy()
    mx.random.seed(7)
    b = mx.random.uniform(0, 1, shape=(100,)).asnumpy()
    assert np.allclose(a, b)
    c = mx.random.uniform(0, 1, shape=(100,)).asnumpy()
    assert not np.allclose(b, c)


def test_uniform_range():
    mx.random.seed(0)
    a = mx.random.uniform(-2, 3, shape=(10000,)).asnumpy()
    assert a.min() >= -2 and a.max() < 3
    assert abs(a.mean() - 0.5) < 0.1


def test_normal_moments():
    mx.random.seed(0)
    a = mx.random.normal(1.0, 2.0, shape=(50000,)).asnumpy()
    assert abs(a.mean() - 1.0) < 0.1
    assert abs(a.std() - 2.0) < 0.1


def test_out_param():
    out = mx.nd.zeros((50,))
    mx.random.uniform(0, 1, out=out)
    assert out.asnumpy().max() > 0
