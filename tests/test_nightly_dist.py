"""Gated wrapper running the multi-process nightly dist tests through
``tools/launch.py --launcher local`` (the reference pattern:
tests/nightly/test_all.sh invoking dist scripts via the tracker).

Enabled with MXTPU_NIGHTLY=1 (``make test-nightly``); skipped in the fast
suite — each case boots real jax.distributed worker processes.
"""
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    not os.environ.get("MXTPU_NIGHTLY"),
    reason="multi-process dist tests are nightly (set MXTPU_NIGHTLY=1)")


def _launch(script, n=2, port=9890, extra_env=None, expect_rc=0):
    cmd = [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
           "-n", str(n), "--launcher", "local", "--workdir", _ROOT,
           "--port", str(port),
           sys.executable, os.path.join("tests", "nightly", script)]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.update(extra_env or {})
    proc = subprocess.run(cmd, cwd=_ROOT, env=env, timeout=600,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True)
    assert proc.returncode == expect_rc, (proc.returncode,
                                          proc.stdout[-2000:])
    return proc.stdout


def test_dist_sync_kvstore():
    out = _launch("dist_sync_kvstore.py", port=9890)
    assert out.count("OK") >= 2


def test_dist_lenet_converges():
    out = _launch("dist_lenet.py", port=9891)
    accs = [float(line.rsplit(None, 1)[-1]) for line in out.splitlines()
            if "accuracy" in line]
    assert len(accs) >= 2 and min(accs) > 0.9, out[-500:]


def test_kill_worker_detect_and_resume(tmp_path):
    """VERDICT r2 #7: kill one worker mid-job; the survivor's
    kv.num_dead_nodes notices within a few heartbeats and aborts for
    restart; a fresh launch resumes from the checkpoint and keeps
    improving."""
    prefix = str(tmp_path / "resume")
    # phase A: rank 1 dies after the first checkpoint; rank 0 detects it
    # (exit 3 = restart signal) instead of hanging -> launcher rc 1|3 = 3
    out = _launch("dist_resume.py", port=9893,
                  extra_env={"MXTPU_FAULT_RANK": "1",
                             "MXTPU_RESUME_PREFIX": prefix},
                  expect_rc=3)
    assert "detected 1 dead node" in out, out[-1500:]
    assert os.path.exists(prefix + "-0001.params")
    # phase B: restart resumes from the checkpoint
    out = _launch("dist_resume.py", port=9894,
                  extra_env={"MXTPU_RESUME": "1",
                             "MXTPU_RESUME_PREFIX": prefix})
    assert out.count("resume OK") == 2, out[-1500:]


def test_dist_allreduce_bandwidth():
    """VERDICT r3 #3: the allreduce-bandwidth secondary metric must come
    from >1 device: two real processes, one shard each, jitted sum over
    the worker axis."""
    out = _launch("dist_allreduce_bench.py", port=9895)
    lines = [l for l in out.splitlines() if l.startswith("ALLREDUCE")]
    assert lines, out[-1000:]
    for line in lines:
        fields = dict(kv.split("=") for kv in line.split()[1:])
        assert int(fields["devices"]) > 1
        assert float(fields["busbw_gbps"]) > 0
    assert "OK allreduce bench" in out


def test_dist_sharded_checkpoint(tmp_path):
    """Pod-scale resume across real process boundaries: both workers
    write only their own shards, restore into fresh trainers, and the
    next step matches a never-stopped trainer."""
    out = _launch("dist_sharded_ckpt.py", port=9897,
                  extra_env={"MXTPU_SHCKPT_DIR": str(tmp_path)})
    assert "OK sharded checkpoint across processes" in out, out[-1500:]


def test_elastic_coordinator_loss_orphan_path(tmp_path):
    """Elasticity's worst case: the COORDINATOR dies, so no shrink
    verdict is ever published.  Survivors take the orphan path (exit
    for restart without an agreement), the supervise loop bumps the
    generation itself and clamps to the dropped capacity, and the run
    still finishes: world 3 -> 2 -> grown back to 3.  (The clean
    agreed shrink/grow drill runs in tier-1, tests/test_resilience.py
    ::test_elastic_shrink_grow_drill.)"""
    edir = str(tmp_path / "elastic")
    cmd = [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
           "-n", "3", "--launcher", "local", "--workdir", _ROOT,
           "--port", "9898", "--elastic", "--min-world", "2",
           "--elastic-dir", edir, "--max-restarts", "4",
           sys.executable,
           os.path.join("tests", "nightly", "dist_elastic.py")]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.update({"MXTPU_STEP_TIMEOUT_S": "12",
                "MXTPU_DRILL_KILL": "0:1:0"})     # rank 0 is the victim
    proc = subprocess.run(cmd, cwd=_ROOT, env=env, timeout=600,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True)
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-3000:])
    assert "no newer verdict in ledger" in proc.stdout
    import json
    with open(os.path.join(edir, "losses-elastic.jsonl")) as f:
        rows = [json.loads(line) for line in f]
    assert [r["epoch"] for r in rows] == [0, 1, 2, 3, 4]
    assert [r["world"] for r in rows] == [3, 3, 2, 3, 3]
    with open(os.path.join(edir, "LEDGER.json")) as f:
        led = json.load(f)
    assert led["generation"] == 2 and led["world_size"] == 3
