"""Shape inference tests (modeled on tests/python/unittest/test_infer_shape.py)."""
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.base import MXNetError


def _assert_shapes(symbol, arg_shapes_expect, out_shapes_expect=None, **kwargs):
    arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**kwargs)
    assert arg_shapes is not None
    assert dict(zip(symbol.list_arguments(), arg_shapes)) == arg_shapes_expect
    if out_shapes_expect is not None:
        assert out_shapes == out_shapes_expect


def test_mlp_infer():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data=data, name="fc1", num_hidden=30)
    net = sym.SoftmaxOutput(fc1, name="sm")
    _assert_shapes(net,
                   {"data": (100, 50), "fc1_weight": (30, 50),
                    "fc1_bias": (30,), "sm_label": (100,)},
                   [(100, 30)],
                   data=(100, 50))


def test_incomplete_returns_none():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=10)
    a, o, x = net.infer_shape()
    assert a is None and o is None and x is None
    # partial still reports what it can
    a, o, x = net.infer_shape_partial()
    assert a[0] is None


def test_conv_chain():
    data = sym.Variable("data")
    conv = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1), name="c1")
    pool = sym.Pooling(conv, kernel=(2, 2), stride=(2, 2), pool_type="max")
    flat = sym.Flatten(pool)
    fc = sym.FullyConnected(flat, num_hidden=10, name="fc")
    arg_shapes, out_shapes, _ = fc.infer_shape(data=(2, 3, 28, 28))
    shapes = dict(zip(fc.list_arguments(), arg_shapes))
    assert shapes["c1_weight"] == (8, 3, 3, 3)
    assert shapes["fc_weight"] == (10, 8 * 14 * 14)
    assert out_shapes[0] == (2, 10)


def test_backfill_from_weight():
    """Weight shape determines nothing upstream, but label backfills from data."""
    data = sym.Variable("data")
    out = sym.SoftmaxOutput(sym.FullyConnected(data, num_hidden=4, name="fc"),
                            name="sm")
    arg_shapes, _, _ = out.infer_shape(data=(10, 6))
    shapes = dict(zip(out.list_arguments(), arg_shapes))
    assert shapes["sm_label"] == (10,)


def test_mismatch_raises():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a + b
    with pytest.raises(MXNetError):
        c.infer_shape(a=(2, 3), b=(4, 5))


def test_batchnorm_aux_shapes():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data, name="bn")
    arg_shapes, out_shapes, aux_shapes = bn.infer_shape(data=(8, 5, 4, 4))
    assert aux_shapes == [(5,), (5,)]
    assert dict(zip(bn.list_arguments(), arg_shapes))["bn_gamma"] == (5,)


def test_reshape_infer():
    data = sym.Variable("data")
    r = sym.Reshape(data, shape=(0, -1))
    _, out_shapes, _ = r.infer_shape(data=(4, 3, 2))
    assert out_shapes[0] == (4, 6)
    r2 = sym.Reshape(data, target_shape=(0, 6))
    _, out_shapes, _ = r2.infer_shape(data=(4, 3, 2))
    assert out_shapes[0] == (4, 6)


def test_deconv_infer():
    data = sym.Variable("data")
    d = sym.Deconvolution(data, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
                          num_filter=8, name="dc")
    arg_shapes, out_shapes, _ = d.infer_shape(data=(1, 16, 8, 8))
    assert out_shapes[0] == (1, 8, 16, 16)
    assert dict(zip(d.list_arguments(), arg_shapes))["dc_weight"] == (16, 8, 4, 4)
