"""MXL-D distributed-correctness lint (analysis/distributed.py +
analysis/divergence.py): per-rank collective-trace diff (D001..003),
rank-divergence source dataflow (D004..006), the marker vocabulary,
stable anchors, and the clean bill on the fixed framework code."""
import os

import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.analysis import (GraphIssue, analyze, analyze_source_paths,
                                collective_seam)
from mxnet_tpu.analysis.distributed import parse_rank_cond

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "fixtures", "divergence")


def _rules(issues):
    return sorted({i.rule_id for i in issues})


# ----------------------------------------------------------------------
# __rank_cond__ grammar
# ----------------------------------------------------------------------
def test_rank_cond_grammar():
    assert [p(0) for p in parse_rank_cond("coordinator")] == [True]
    assert [p(3) for p in parse_rank_cond("coordinator")] == [False]
    assert [p(3) for p in parse_rank_cond("noncoordinator")] == [True]
    assert [p(2) for p in parse_rank_cond("rank==2")] == [True]
    assert [p(2) for p in parse_rank_cond("rank!=2")] == [False]
    assert [p(1) for p in parse_rank_cond("rank<2")] == [True]
    assert [p(2) for p in parse_rank_cond("rank<=2")] == [True]
    assert [p(3) for p in parse_rank_cond("rank>2")] == [True]
    assert [p(2) for p in parse_rank_cond("rank>=3")] == [False]
    assert [p(5) for p in parse_rank_cond("rank%2==1")] == [True]
    both = parse_rank_cond("rank>0; rank<3")
    assert [all(p(r) for p in both) for r in (0, 1, 2, 3)] == \
        [False, True, True, False]
    for bad in ("rank**2", "rank=1", "rank%0==0", "pid==0"):
        with pytest.raises(ValueError):
            parse_rank_cond(bad)
    assert parse_rank_cond("") == []     # no constraints


# ----------------------------------------------------------------------
# D001..D003: the graph-level trace diff
# ----------------------------------------------------------------------
def _coordinator_barrier_graph():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=8, name="fc")
    fc._set_attr(__rank_cond__="coordinator", __collective__="barrier")
    return sym.SoftmaxOutput(data=fc, name="softmax")


def test_d003_rank_conditional_collective():
    out = _coordinator_barrier_graph()
    issues = out.validate(data=(4, 8), world_size=4, select=["MXL-D*"])
    assert _rules(issues) == ["MXL-D003"]
    assert issues[0].severity == "error"
    assert "only rank 0 of 4" in issues[0].message
    assert "coordinator" in issues[0].message


def test_d003_inherited_condition():
    """A collective DOWNSTREAM of a rank-conditioned node inherits the
    condition: its inputs only exist on the conditioned ranks."""
    v = sym.Variable("data")
    gate = sym.FullyConnected(data=v, num_hidden=8, name="gate")
    gate._set_attr(__rank_cond__="rank==0")
    act = sym.Activation(data=gate, act_type="relu", name="act")
    act._set_attr(__collective__="allreduce:dp")
    issues = sym.SoftmaxOutput(data=act, name="s").validate(
        data=(4, 8), world_size=4, select=["MXL-D*"])
    assert _rules(issues) == ["MXL-D003"]
    assert "from node gate" in issues[0].message


def test_d001_order_mismatch():
    """Rank 0 issues a barrier where every other rank issues an
    allreduce: same trace length, different collective — deadlock."""
    v = sym.Variable("data")
    a = sym.FullyConnected(data=v, num_hidden=8, name="a")
    a._set_attr(__rank_cond__="rank==0", __collective__="barrier")
    b = sym.Activation(data=v, act_type="relu", name="b")
    b._set_attr(__rank_cond__="rank!=0", __collective__="allreduce:dp")
    g = sym.Group([sym.SoftmaxOutput(data=a, name="s1"),
                   sym.SoftmaxOutput(data=b, name="s2")])
    issues = g.validate(data=(4, 8), world_size=4, select=["MXL-D*"])
    assert _rules(issues) == ["MXL-D001"]
    assert len(issues) == 1          # deduped per program position
    assert "rank 0 issues barrier" in issues[0].message


def test_d002_signature_mismatch():
    """Same kind at the same position but different mesh axes."""
    v = sym.Variable("data")
    a = sym.FullyConnected(data=v, num_hidden=8, name="a")
    a._set_attr(__rank_cond__="rank%2==0", __collective__="allreduce:dp")
    b = sym.Activation(data=v, act_type="relu", name="b")
    b._set_attr(__rank_cond__="rank%2==1", __collective__="allreduce:tp")
    g = sym.Group([sym.SoftmaxOutput(data=a, name="s1"),
                   sym.SoftmaxOutput(data=b, name="s2")])
    issues = g.validate(data=(4, 8), world_size=4, select=["MXL-D*"])
    assert _rules(issues) == ["MXL-D002"]


def test_d003_unparseable_cond_is_warning_not_crash():
    v = sym.Variable("data")
    fc = sym.FullyConnected(data=v, num_hidden=8, name="fc")
    fc._set_attr(__rank_cond__="rank**2", __collective__="barrier")
    issues = sym.SoftmaxOutput(data=fc, name="s").validate(
        data=(4, 8), world_size=2, select=["MXL-D*"])
    assert _rules(issues) == ["MXL-D003"]
    assert issues[0].severity == "warning"
    assert "unparseable" in issues[0].message


def test_unconditional_collectives_are_clean():
    v = sym.Variable("data")
    fc = sym.FullyConnected(data=v, num_hidden=8, name="fc")
    fc._set_attr(__collective__="allreduce:dp")
    issues = sym.SoftmaxOutput(data=fc, name="s").validate(
        data=(4, 8), world_size=4, select=["MXL-D*"])
    assert issues == []


def test_world_size_gates_the_family():
    out = _coordinator_barrier_graph()
    assert out.validate(data=(4, 8), select=["MXL-D*"]) == []
    assert out.validate(data=(4, 8), world_size=1,
                        select=["MXL-D*"]) == []


def test_env_knobs_enable_the_family(monkeypatch):
    monkeypatch.setenv("MXTPU_LINT_DISTRIBUTED", "1")
    monkeypatch.setenv("MXTPU_LINT_WORLD_SIZE", "8")
    out = _coordinator_barrier_graph()
    issues = out.validate(data=(4, 8), select=["MXL-D*"])
    assert _rules(issues) == ["MXL-D003"]
    assert "of 8" in issues[0].message


def test_lint_ignore_attr_suppresses():
    out = _coordinator_barrier_graph()
    list(out._topo())  # noqa: F841 — attrs live on the graph nodes
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=8, name="fc")
    fc._set_attr(__rank_cond__="coordinator", __collective__="barrier",
                 __lint_ignore__="MXL-D003")
    issues = sym.SoftmaxOutput(data=fc, name="s").validate(
        data=(4, 8), world_size=4, select=["MXL-D*"])
    assert issues == []


# ----------------------------------------------------------------------
# D004..D006: the source dataflow pass over the regression fixtures
# ----------------------------------------------------------------------
def test_fixture_pid_scratch_path_is_d004():
    fs = analyze_source_paths(
        [os.path.join(FIXTURES, "pid_scratch_path.py")], root=ROOT)
    assert sorted({f["rule"] for f in fs}) == ["MXL-D004"]
    f = fs[0]
    assert f["anchor"].endswith(
        "pid_scratch_path.py:save_checkpoint_atomic")
    assert "getpid" in f["message"] and "ocp_save" in f["message"]


def test_fixture_barrier_probe_is_d005():
    fs = analyze_source_paths(
        [os.path.join(FIXTURES, "per_rank_barrier_probe.py")], root=ROOT)
    rules = sorted({f["rule"] for f in fs})
    assert "MXL-D005" in rules          # the documented rule id
    assert "MXL-D006" in rules          # the swallowed probe failure
    assert all(f["anchor"].endswith(":global_barrier") for f in fs)


def test_fixture_device0_sentinel_is_d005():
    fs = analyze_source_paths(
        [os.path.join(FIXTURES, "device0_sentinel.py")], root=ROOT)
    assert sorted({f["rule"] for f in fs}) == ["MXL-D005"]
    assert "addressable_data" in fs[0]["message"]


def test_fixtures_through_analyze_entrypoint():
    """source_paths= on analyze() routes to the dataflow rules and
    yields GraphIssues with anchors + lines."""
    issues = analyze(None, source_paths=[FIXTURES], select=["MXL-D*"])
    assert set(_rules(issues)) == {"MXL-D004", "MXL-D005", "MXL-D006"}
    for i in issues:
        assert i.anchor and ":" in i.anchor
        assert isinstance(i.line, int) and i.line > 0


# ----------------------------------------------------------------------
# taint sources/sinks and the marker vocabulary
# ----------------------------------------------------------------------
def _lint_snippet(tmp_path, code, name="snippet.py"):
    p = tmp_path / name
    p.write_text(code)
    return analyze_source_paths([str(p)], root=str(tmp_path))


def test_suppression_marker_on_line(tmp_path):
    fs = _lint_snippet(tmp_path, (
        "import os\n"
        "def f(kv, g):\n"
        "    if os.getpid() % 2:\n"
        "        kv.all_reduce(g)  # mxl: rank-divergent-ok\n"))
    assert fs == []


def test_suppression_marker_with_rule_filter(tmp_path):
    code = ("import os\n"
            "def f(kv, g):\n"
            "    if os.getpid() % 2:\n"
            "        # mxl: rank-divergent-ok (MXL-D004)\n"
            "        kv.all_reduce(g)\n")
    fs = _lint_snippet(tmp_path, code)
    assert sorted({f["rule"] for f in fs}) == ["MXL-D005"]  # wrong id


def test_suppression_marker_on_def_line(tmp_path):
    fs = _lint_snippet(tmp_path, (
        "import os\n"
        "def f(kv, g):  # mxl: rank-divergent-ok (MXL-D005)\n"
        "    if os.getpid() % 2:\n"
        "        kv.all_reduce(g)\n"))
    assert fs == []


def test_collective_seam_certifies_return(tmp_path):
    """A seam-decorated decision function's verdict is rank-uniform:
    gating a collective on it is the FIXED protocol, not a bug."""
    buggy = ("import jax\n"
             "def decide():\n"
             "    return jax.process_index() == 0\n"
             "def run(kv, g):\n"
             "    if decide():\n"
             "        kv.all_reduce(g)\n")
    fs = _lint_snippet(tmp_path, buggy)
    assert sorted({f["rule"] for f in fs}) == ["MXL-D005"]
    fixed = buggy.replace(
        "import jax\n",
        "import jax\nfrom mxnet_tpu.base import collective_seam\n"
    ).replace("def decide():", "@collective_seam\ndef decide():")
    assert _lint_snippet(tmp_path, fixed, "fixed.py") == []


def test_seam_body_exempt_from_d005(tmp_path):
    """Rank-asymmetry INSIDE a seam body is the protocol itself."""
    fs = _lint_snippet(tmp_path, (
        "import jax\n"
        "from mxnet_tpu.base import collective_seam\n"
        "@collective_seam\n"
        "def rendezvous(client, g):\n"
        "    if jax.process_index() == 0:\n"
        "        client.sync_global_devices('probe')\n"))
    assert fs == []


def test_divergent_returner_one_hop(tmp_path):
    """_is_coordinator-style helpers spread taint to their callers."""
    fs = _lint_snippet(tmp_path, (
        "import jax\n"
        "def _is_coordinator():\n"
        "    return jax.process_index() == 0\n"
        "def save(mgr, tree, step):\n"
        "    if _is_coordinator():\n"
        "        mgr.global_barrier('pre')\n"))
    assert sorted({f["rule"] for f in fs}) == ["MXL-D005"]


def test_common_names_do_not_poison(tmp_path):
    """One divergent `def get` must not taint unrelated .get() calls
    (consensus rule: every definition of the name must be divergent)."""
    fs = _lint_snippet(tmp_path, (
        "import time, os\n"
        "class Clock(object):\n"
        "    def get(self):\n"
        "        return time.monotonic()\n"
        "class Config(object):\n"
        "    def get(self, key):\n"
        "        return key\n"
        "def run(kv, g, cfg):\n"
        "    if cfg.get('enabled'):\n"
        "        kv.all_reduce(g)\n"))
    assert fs == []


def test_seeded_rng_is_uniform(tmp_path):
    fs = _lint_snippet(tmp_path, (
        "import numpy as np\n"
        "def run(kv, g):\n"
        "    r = np.random.RandomState(7)\n"
        "    if r.rand() > 0.5:\n"
        "        kv.all_reduce(g)\n"))
    assert fs == []


def test_unseeded_rng_is_divergent(tmp_path):
    fs = _lint_snippet(tmp_path, (
        "import random\n"
        "def run(kv, g):\n"
        "    if random.random() > 0.5:\n"
        "        kv.all_reduce(g)\n"))
    assert sorted({f["rule"] for f in fs}) == ["MXL-D005"]


def test_d006_exit_between_paired_collectives(tmp_path):
    fs = _lint_snippet(tmp_path, (
        "import os\n"
        "def run(kv, g):\n"
        "    kv.all_reduce(g)\n"
        "    if os.getpid() % 2:\n"
        "        return None\n"
        "    kv.all_reduce(g)\n"))
    assert sorted({f["rule"] for f in fs}) == ["MXL-D006"]
    assert "between paired collectives" in fs[0]["message"]


def test_d004_coordinated_kwarg(tmp_path):
    fs = _lint_snippet(tmp_path, (
        "import os, tempfile\n"
        "def save(tree, step):\n"
        "    d = tempfile.mkdtemp()\n"
        "    ocp_save(path=d, tree=tree, step=step)\n"))
    assert sorted({f["rule"] for f in fs}) == ["MXL-D004"]


def test_filesystem_reads_not_tainted(tmp_path):
    """Shared-filesystem listings are how ranks legitimately agree
    (latest_step): they must not count as divergence sources."""
    fs = _lint_snippet(tmp_path, (
        "import os\n"
        "def resume(kv, g, path):\n"
        "    if os.path.exists(path) and os.listdir(path):\n"
        "        kv.all_reduce(g)\n"))
    assert fs == []


# ----------------------------------------------------------------------
# the clean bill: the fixed framework self-lints clean
# ----------------------------------------------------------------------
def test_framework_self_lint_clean():
    """kvstore/parallel/resilience — the subsystems whose pre-fix bugs
    the fixtures snapshot — produce zero MXL-D findings now that the
    seams are marked and the intentional divergence is annotated."""
    fs = analyze_source_paths(
        [os.path.join(ROOT, "mxnet_tpu")], root=ROOT)
    assert fs == [], "\n".join(
        "%s %s L%s: %s" % (f["rule"], f["anchor"], f["line"],
                           f["message"]) for f in fs)


def test_collective_seam_is_runtime_noop():
    @collective_seam
    def f(x):
        return x + 1

    @collective_seam(protocol="kv")
    def g(x):
        return x + 2

    assert f(1) == 2 and g(1) == 3
    assert mx.base.collective_seam is collective_seam


# ----------------------------------------------------------------------
# anchors: stable identity, volatile line
# ----------------------------------------------------------------------
def test_anchor_identity_excludes_line():
    a = GraphIssue("MXL-D004", "error", None, "m", anchor="f.py:g",
                   line=10)
    b = GraphIssue("MXL-D004", "error", None, "m", anchor="f.py:g",
                   line=99)
    c = GraphIssue("MXL-D004", "error", None, "m", anchor="f.py:h",
                   line=10)
    assert a == b and hash(a) == hash(b)
    assert a != c
    d = a.as_dict()
    assert d["anchor"] == "f.py:g" and d["line"] == 10
    assert "anchor" not in GraphIssue("X", "error", "n", "m").as_dict()


def test_anchor_survives_unrelated_edit(tmp_path):
    """The same finding keeps the same anchor when lines shift — the
    property mxlint --baseline keys on."""
    code = ("import os\n"
            "def save(tree, step):\n"
            "    ocp_save('%d' % os.getpid(), tree, step)\n")
    before = _lint_snippet(tmp_path, code, "v1.py")
    shifted = "# header comment\n\n\n" + code
    after = _lint_snippet(tmp_path, shifted, "v1.py")
    assert before[0]["anchor"] == after[0]["anchor"]
    assert before[0]["line"] != after[0]["line"]
