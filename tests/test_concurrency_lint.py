"""MXL-Q concurrency lint (analysis/concurrency.py) + the
MXTPU_LOCKCHECK runtime lock-discipline sanitizer
(observability/locktrace.py): race / lock-order / blocking-under-lock
/ thread-leak / callback-context / condition-wait rules, the marker
vocabulary, the two historical regression fixtures, and the live
inversion witness."""
import os
import threading

import pytest

from mxnet_tpu.analysis.concurrency import analyze_concurrency_paths
from mxnet_tpu.base import thread_entry
from mxnet_tpu.observability import locktrace
from mxnet_tpu.resilience import ResilienceError

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "fixtures", "concurrency")


def _rules(findings):
    return sorted({f["rule"] for f in findings})


def _lint(tmp_path, code, name="snippet.py"):
    p = tmp_path / name
    p.write_text(code)
    return analyze_concurrency_paths([str(p)], root=str(tmp_path))


# ----------------------------------------------------------------------
# Q001: shared-attribute race
# ----------------------------------------------------------------------
def test_q001_thread_write_main_read(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._t.start()\n"
        "    def _work(self):\n"
        "        self._latest = 1\n"
        "    def read(self):\n"
        "        return self._latest\n"
        "    def close(self):\n"
        "        self._t.join()\n"))
    assert "MXL-Q001" in _rules(fs)
    hit = [f for f in fs if f["rule"] == "MXL-Q001"][0]
    assert "_latest" in hit["message"]
    assert hit["anchor"].endswith(":C._work")


def test_q001_clean_with_common_lock(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._t.start()\n"
        "    def _work(self):\n"
        "        with self._lock:\n"
        "            self._latest = 1\n"
        "    def read(self):\n"
        "        with self._lock:\n"
        "            return self._latest\n"
        "    def close(self):\n"
        "        self._t.join()\n"))
    assert _rules(fs) == []


def test_q001_main_main_not_flagged(tmp_path):
    # two unlocked accessors, but no thread entry anywhere: single-
    # threaded class, nothing to race with
    fs = _lint(tmp_path, (
        "class C:\n"
        "    def set(self, v):\n"
        "        self._v = v\n"
        "    def get(self):\n"
        "        return self._v\n"))
    assert _rules(fs) == []


def test_q001_executor_submit_counts_as_thread(tmp_path):
    fs = _lint(tmp_path, (
        "class C:\n"
        "    def kick(self, pool):\n"
        "        return pool.submit(self._work)\n"
        "    def _work(self):\n"
        "        self._result = 42\n"
        "    def read(self):\n"
        "        return self._result\n"))
    assert "MXL-Q001" in _rules(fs)


def test_q001_helper_called_only_under_lock_is_clean(tmp_path):
    # the write lives in a helper scanned with held=∅, but every call
    # site holds the lock: effective_locks() must credit it
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._t.start()\n"
        "    def _work(self):\n"
        "        with self._lock:\n"
        "            self._bump()\n"
        "    def _bump(self):\n"
        "        self._n = 1\n"
        "    def read(self):\n"
        "        with self._lock:\n"
        "            return self._n\n"
        "    def close(self):\n"
        "        self._t.join()\n"))
    assert _rules(fs) == []


def test_q001_module_global(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "STATE = {}\n"
        "def _work():\n"
        "    global STATE\n"
        "    STATE = {'x': 1}\n"
        "def start():\n"
        "    t = threading.Thread(target=_work)\n"
        "    t.start()\n"
        "    return t\n"
        "def read():\n"
        "    return STATE\n"))
    assert "MXL-Q001" in _rules(fs)


def test_q001_init_writes_exempt(tmp_path):
    # __init__ runs before the thread exists: publication via
    # constructor is the universal safe idiom, never flagged
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._latest = None\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._t.start()\n"
        "    def _work(self):\n"
        "        x = self._latest\n"
        "    def close(self):\n"
        "        self._t.join()\n"))
    assert "MXL-Q001" not in _rules(fs)


def test_q001_mutator_call_is_a_write(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._t.start()\n"
        "    def _work(self):\n"
        "        self._out.append(1)\n"
        "    def drain(self):\n"
        "        return list(self._out)\n"
        "    def close(self):\n"
        "        self._t.join()\n"))
    assert "MXL-Q001" in _rules(fs)


# ----------------------------------------------------------------------
# Q002: lock-order cycle
# ----------------------------------------------------------------------
def test_q002_two_lock_inversion(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def fwd(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def rev(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"))
    assert "MXL-Q002" in _rules(fs)


def test_q002_three_lock_ring(tmp_path):
    # a->b, b->c, c->a: no two-lock inversion anywhere, only the ring
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "        self._c = threading.Lock()\n"
        "    def ab(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def bc(self):\n"
        "        with self._b:\n"
        "            with self._c:\n"
        "                pass\n"
        "    def ca(self):\n"
        "        with self._c:\n"
        "            with self._a:\n"
        "                pass\n"))
    assert "MXL-Q002" in _rules(fs)


def test_q002_consistent_order_clean(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"))
    assert "MXL-Q002" not in _rules(fs)


def test_q002_cross_method_via_call(tmp_path):
    # fwd holds a and CALLS a method that takes b; rev takes b then a:
    # the edge must flow through the one-hop call graph
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def fwd(self):\n"
        "        with self._a:\n"
        "            self._inner()\n"
        "    def _inner(self):\n"
        "        with self._b:\n"
        "            pass\n"
        "    def rev(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"))
    assert "MXL-Q002" in _rules(fs)


# ----------------------------------------------------------------------
# Q003: blocking call under lock
# ----------------------------------------------------------------------
def test_q003_sleep_under_lock(tmp_path):
    fs = _lint(tmp_path, (
        "import threading, time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def poll(self):\n"
        "        with self._lock:\n"
        "            time.sleep(0.5)\n"))
    assert "MXL-Q003" in _rules(fs)


def test_q003_future_result_under_lock(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def wait_done(self, fut):\n"
        "        with self._lock:\n"
        "            return fut.result()\n"))
    assert "MXL-Q003" in _rules(fs)


def test_q003_sleep_outside_lock_clean(tmp_path):
    fs = _lint(tmp_path, (
        "import threading, time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def poll(self):\n"
        "        with self._lock:\n"
        "            pass\n"
        "        time.sleep(0.5)\n"))
    assert "MXL-Q003" not in _rules(fs)


def test_q003_condition_wait_on_held_lock_exempt(tmp_path):
    # cv.wait() RELEASES the lock it waits on: the canonical pattern
    # must not be called "blocking under lock"
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._cv = threading.Condition(self._lock)\n"
        "    def take(self):\n"
        "        with self._cv:\n"
        "            while not self._ready:\n"
        "                self._cv.wait()\n"))
    assert "MXL-Q003" not in _rules(fs)


def test_q003_nonblocking_queue_get_clean(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def drain(self):\n"
        "        with self._lock:\n"
        "            return self._queue.get(block=False)\n"))
    assert "MXL-Q003" not in _rules(fs)


# ----------------------------------------------------------------------
# Q004: thread leak
# ----------------------------------------------------------------------
def test_q004_unjoined_thread(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._work).start()\n"
        "    def _work(self):\n"
        "        pass\n"))
    assert "MXL-Q004" in _rules(fs)


def test_q004_joined_thread_clean(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._t.start()\n"
        "    def _work(self):\n"
        "        pass\n"
        "    def close(self):\n"
        "        self._t.join()\n"))
    assert "MXL-Q004" not in _rules(fs)


def test_q004_swap_alias_join_credited(tmp_path):
    # the idiomatic teardown: t, self._t = self._t, None; t.join()
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._t.start()\n"
        "    def _work(self):\n"
        "        pass\n"
        "    def close(self):\n"
        "        t, self._t = self._t, None\n"
        "        if t is not None:\n"
        "            t.join(timeout=2.0)\n"))
    assert "MXL-Q004" not in _rules(fs)


def test_q004_registry_call_credited(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def start(self):\n"
        "        t = threading.Thread(target=self._work)\n"
        "        t.start()\n"
        "        _register_producer(t)\n"
        "    def _work(self):\n"
        "        pass\n"))
    assert "MXL-Q004" not in _rules(fs)


# ----------------------------------------------------------------------
# Q005: callback-context violation
# ----------------------------------------------------------------------
def test_q005_pure_callback_mutation(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "class C:\n"
        "    def run(self, x):\n"
        "        return jax.pure_callback(self._cb, x, x)\n"
        "    def _cb(self, x):\n"
        "        self._count = self._count + 1\n"
        "        return x\n"
        "    def report(self):\n"
        "        return self._count\n"))
    assert "MXL-Q005" in _rules(fs)


def test_q005_host_callback_class_attr(tmp_path):
    # host_callback = True marks forward/backward as callback roots
    # (the torch_bridge idiom)
    fs = _lint(tmp_path, (
        "class Op:\n"
        "    host_callback = True\n"
        "    def forward(self, x):\n"
        "        self._cache[x.shape] = x\n"
        "        return x\n"
        "    def stats(self):\n"
        "        return len(self._cache)\n"))
    assert "MXL-Q005" in _rules(fs)


def test_q005_locked_callback_clean(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class Op:\n"
        "    host_callback = True\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def forward(self, x):\n"
        "        with self._lock:\n"
        "            self._cache[x.shape] = x\n"
        "        return x\n"
        "    def stats(self):\n"
        "        with self._lock:\n"
        "            return len(self._cache)\n"))
    assert "MXL-Q005" not in _rules(fs)


# ----------------------------------------------------------------------
# Q006: condition wait without predicate re-check
# ----------------------------------------------------------------------
def test_q006_bare_wait(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._cv = threading.Condition(self._lock)\n"
        "    def take(self):\n"
        "        with self._cv:\n"
        "            self._cv.wait()\n"
        "            return self._item\n"))
    assert "MXL-Q006" in _rules(fs)


def test_q006_while_predicate_clean(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._cv = threading.Condition(self._lock)\n"
        "    def take(self):\n"
        "        with self._cv:\n"
        "            while self._item is None:\n"
        "                self._cv.wait()\n"
        "            return self._item\n"))
    assert "MXL-Q006" not in _rules(fs)


# ----------------------------------------------------------------------
# markers: @thread_entry and # mxl: thread-shared-ok
# ----------------------------------------------------------------------
def test_thread_entry_decorator_is_noop():
    @thread_entry
    def f():
        return 7

    @thread_entry(daemon=True)
    def g():
        return 8

    assert f() == 7 and g() == 8


def test_thread_entry_decorator_marks_context(tmp_path):
    # no Thread(...) call in sight — the decorator alone must tag
    # _work as a thread root so the race is visible
    fs = _lint(tmp_path, (
        "from mxnet_tpu.base import thread_entry\n"
        "class C:\n"
        "    @thread_entry\n"
        "    def _work(self):\n"
        "        self._latest = 1\n"
        "    def read(self):\n"
        "        return self._latest\n"))
    assert "MXL-Q001" in _rules(fs)


def test_suppression_marker_on_line(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._t.start()\n"
        "    def _work(self):\n"
        "        self._latest = 1  # mxl: thread-shared-ok\n"
        "    def read(self):\n"
        "        return self._latest\n"
        "    def close(self):\n"
        "        self._t.join()\n"))
    assert "MXL-Q001" not in _rules(fs)


def test_suppression_marker_rule_filtered(tmp_path):
    # suppressing a DIFFERENT rule must not hide the Q001 finding
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._t.start()\n"
        "    def _work(self):\n"
        "        self._latest = 1  # mxl: thread-shared-ok (MXL-Q003)\n"
        "    def read(self):\n"
        "        return self._latest\n"
        "    def close(self):\n"
        "        self._t.join()\n"))
    assert "MXL-Q001" in _rules(fs)


def test_suppression_marker_on_def(tmp_path):
    fs = _lint(tmp_path, (
        "import threading\n"
        "class C:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._t.start()\n"
        "    # mxl: thread-shared-ok (MXL-Q001)\n"
        "    def _work(self):\n"
        "        self._latest = 1\n"
        "    def read(self):\n"
        "        return self._latest\n"
        "    def close(self):\n"
        "        self._t.join()\n"))
    assert "MXL-Q001" not in _rules(fs)


def test_parse_error_is_a_warning_finding(tmp_path):
    fs = _lint(tmp_path, "def broken(:\n", name="broken.py")
    assert len(fs) == 1
    assert fs[0]["rule"] == "MXL-Q001"
    assert "cannot parse" in fs[0]["message"]


# ----------------------------------------------------------------------
# historical regression fixtures
# ----------------------------------------------------------------------
def test_fixture_torch_callback_race():
    fs = analyze_concurrency_paths(
        [os.path.join(FIXTURES, "torch_callback_race.py")], root=ROOT)
    assert "MXL-Q005" in _rules(fs)
    hit = [f for f in fs if f["rule"] == "MXL-Q005"][0]
    assert "_stats" in hit["message"]


def test_fixture_prefetcher_shutdown_race():
    fs = analyze_concurrency_paths(
        [os.path.join(FIXTURES, "prefetcher_shutdown_race.py")],
        root=ROOT)
    rules = _rules(fs)
    assert "MXL-Q001" in rules and "MXL-Q004" in rules
    q1 = [f for f in fs if f["rule"] == "MXL-Q001"]
    assert any("_staged" in f["message"] for f in q1)


def test_framework_self_lint_clean():
    # the acceptance gate: the shipped package carries no MXL-Q
    # findings (real fixes + audited annotations)
    pkg = os.path.join(ROOT, "mxnet_tpu")
    fs = analyze_concurrency_paths([pkg], root=ROOT)
    assert fs == [], [(f["rule"], f["anchor"], f["line"]) for f in fs]


# ----------------------------------------------------------------------
# mxlint CLI family plumbing
# ----------------------------------------------------------------------
def test_mxlint_concurrency_family(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "mxlint", os.path.join(ROOT, "tools", "mxlint.py"))
    mxlint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mxlint)
    p = tmp_path / "racy.py"
    p.write_text(
        "import threading\n"
        "class C:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._work).start()\n"
        "    def _work(self):\n"
        "        self._x = 1\n"
        "    def read(self):\n"
        "        return self._x\n")
    _label, issues, _ctx = mxlint.lint_sources(
        [str(p)], None, [], families=["MXL-Q*"])
    rules = {i.rule_id for i in issues}
    assert "MXL-Q001" in rules and "MXL-Q004" in rules
    # the distributed family alone must NOT surface Q findings
    _label, issues_d, _ctx = mxlint.lint_sources(
        [str(p)], None, [], families=["MXL-D*"])
    assert {i.rule_id for i in issues_d} == set()
    # --select wildcard narrows within the family
    _label, issues_sel, _ctx = mxlint.lint_sources(
        [str(p)], ["MXL-Q004"], [])
    assert {i.rule_id for i in issues_sel} == {"MXL-Q004"}


# ----------------------------------------------------------------------
# runtime sanitizer: observability/locktrace.py
# ----------------------------------------------------------------------
@pytest.fixture
def traced():
    was = locktrace.installed()
    locktrace.install()
    locktrace.reset_order_graph()
    yield
    locktrace.reset_order_graph()
    if not was:
        locktrace.uninstall()


def test_locktrace_live_inversion(traced):
    a = threading.Lock()
    b = threading.Lock()     # NB: distinct creation lines — the graph
    # keys locks by site, same-line locks coalesce into one node
    with a:
        with b:
            pass
    with pytest.raises(ResilienceError) as exc:
        with b:
            with a:
                pass
    assert exc.value.kind == "lock_order"
    assert "inversion" in str(exc.value)
    # the failed acquire must not leave `a` wedged
    assert a.acquire(blocking=False)
    a.release()


def test_locktrace_consistent_order_ok(traced):
    a = threading.Lock()
    b = threading.Lock()
    for _ in range(3):
        with a:
            with b:
                pass
    assert len(locktrace.order_edges()) == 1


def test_locktrace_rlock_reentrancy_no_edge(traced):
    r = threading.RLock()
    with r:
        with r:
            pass
    assert locktrace.order_edges() == []


def test_locktrace_condition_wait_releases(traced):
    # a condition wait must not pin the cv's lock into order edges
    # against locks taken by the notifier
    cv = threading.Condition(threading.Lock())
    other = threading.Lock()
    ready = []

    def notifier():
        with other:
            pass
        with cv:
            ready.append(1)
            cv.notify()

    t = threading.Thread(target=notifier)
    with cv:
        t.start()
        while not ready:
            cv.wait(timeout=5.0)
    t.join()
    # now take (cv's lock -> other) on this thread: if wait() had NOT
    # released through the traced path, bookkeeping would still show
    # cv held during notifier's `other` and this would look inverted
    with cv:
        with other:
            pass


def test_locktrace_uninstall_restores():
    was = locktrace.installed()
    locktrace.install()
    assert threading.Lock is locktrace.TracedLock
    if not was:
        locktrace.uninstall()
        assert threading.Lock is locktrace._ORIG_LOCK
        assert not locktrace.installed()


def test_locktrace_cross_thread_edges(traced):
    # the graph is process-global: the two opposing orders never
    # interleave, they run SEQUENTIALLY on two different threads, and
    # the second still trips
    a = threading.Lock()
    b = threading.Lock()
    caught = []

    def fwd():
        with a:
            with b:
                pass

    def rev():
        try:
            with b:
                with a:
                    pass
        except ResilienceError as e:
            caught.append(e)

    t1 = threading.Thread(target=fwd)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=rev)
    t2.start()
    t2.join()
    assert len(caught) == 1 and caught[0].kind == "lock_order"


# ----------------------------------------------------------------------
# flight recorder: per-thread stacks in the postmortem
# ----------------------------------------------------------------------
def test_flight_snapshot_has_thread_stacks():
    from mxnet_tpu.observability import flight
    ev = threading.Event()
    t = threading.Thread(target=ev.wait, name="wedged-worker")
    t.start()
    try:
        doc = flight.FlightRecorder(depth=8).snapshot(reason="test")
        ths = doc["threads"]
        names = [x["name"] for x in ths]
        assert "wedged-worker" in names
        assert ths[0]["current"] is True     # snapshotting thread first
        wedged = [x for x in ths if x["name"] == "wedged-worker"][0]
        assert "wait" in wedged["stack"]
        assert wedged["daemon"] is False
    finally:
        ev.set()
        t.join()
