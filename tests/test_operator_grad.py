"""Gradient coverage for EVERY registered operator.

Parity target: the reference's numeric-gradient suite
(tests/python/unittest/test_operator.py, check_numeric_gradient usage
throughout).  Three tiers:

- GRAD_SPECS: ops whose backward is d(forward) — checked against central
  differences (check_numeric_gradient on sum(outputs)).
- CONTRACT_SPECS: ops whose backward deliberately is NOT d(forward)
  (custom_vjp loss layers, BlockGrad, element_mask's gradient-free mask)
  — checked against the reference's documented backward formula.
- EXEMPT: ops with no gradient story (samplers, host-callback infra),
  each with the reason recorded.

test_every_registered_op_has_gradient_coverage closes the loop: any op
registered without an entry in one of the three tables fails the suite.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  check_symbolic_backward)

rng = np.random.RandomState(777)


def _f64(*shape):
    return rng.uniform(-1, 1, size=shape).astype(np.float64)


def _pos64(*shape):
    return rng.uniform(0.5, 2.0, size=shape).astype(np.float64)


def _away_from_zero(*shape):
    """Values in ±[0.25, 1.0]: keeps |x| kinks (abs/relu/leaky) away from
    the numeric-diff epsilon."""
    mag = rng.uniform(0.25, 1.0, size=shape)
    return (mag * np.where(rng.rand(*shape) > 0.5, 1.0, -1.0)).astype(np.float64)


def _distinct64(*shape):
    """All-distinct values: max/min/pool_max subgradients are exact."""
    n = int(np.prod(shape))
    vals = rng.permutation(n).astype(np.float64) / n + rng.uniform(0, 1e-3)
    return vals.reshape(shape)


def _separated_pair(*shape):
    """(a, b) with |a-b| >= 0.3 everywhere: elementwise max/min never
    flips inside the numeric-diff epsilon."""
    a = _f64(*shape)
    offs = np.where(rng.rand(*shape) > 0.5, 1.0, -1.0) * rng.uniform(
        0.3, 0.8, size=shape)
    return a, a + offs


V = sym.Variable

# ---------------------------------------------------------------------------
# Tier 1: backward == d(forward); checked vs central differences.
# name -> (symbol builder, location dict, kwargs for check_numeric_gradient)
# ---------------------------------------------------------------------------
GRAD_SPECS = {
    # elementwise binary (+ broadcast aliases)
    "_Plus": lambda: (V("a") + V("b"), {"a": _f64(3, 4), "b": _f64(3, 4)}, {}),
    "_Minus": lambda: (V("a") - V("b"), {"a": _f64(3, 4), "b": _f64(3, 4)}, {}),
    "_Mul": lambda: (V("a") * V("b"), {"a": _f64(3, 4), "b": _f64(3, 4)}, {}),
    "_Div": lambda: (V("a") / V("b"), {"a": _f64(3, 4), "b": _pos64(3, 4)}, {}),
    "_Power": lambda: (V("a") ** V("b"),
                       {"a": _pos64(3, 4), "b": _f64(3, 4)}, {}),
    "_Maximum": lambda: (sym._Maximum(V("a"), V("b")),
                         dict(zip("ab", _separated_pair(3, 4))), {}),
    "_Minimum": lambda: (sym._Minimum(V("a"), V("b")),
                         dict(zip("ab", _separated_pair(3, 4))), {}),
    # scalar variants
    "_PlusScalar": lambda: (V("a") + 1.5, {"a": _f64(3, 4)}, {}),
    "_MinusScalar": lambda: (V("a") - 1.5, {"a": _f64(3, 4)}, {}),
    "_RMinusScalar": lambda: (1.5 - V("a"), {"a": _f64(3, 4)}, {}),
    "_MulScalar": lambda: (V("a") * 2.5, {"a": _f64(3, 4)}, {}),
    "_DivScalar": lambda: (V("a") / 2.5, {"a": _f64(3, 4)}, {}),
    "_RDivScalar": lambda: (2.5 / V("a"), {"a": _pos64(3, 4)}, {}),
    "_PowerScalar": lambda: (V("a") ** 2.0, {"a": _pos64(3, 4)}, {}),
    "_RPowerScalar": lambda: (sym._RPowerScalar(V("a"), scalar=2.0),
                              {"a": _f64(3, 4)}, {}),
    "_MaximumScalar": lambda: (sym._MaximumScalar(V("a"), scalar=0.1),
                               {"a": _away_from_zero(3, 4)}, {}),
    "_MinimumScalar": lambda: (sym._MinimumScalar(V("a"), scalar=0.1),
                               {"a": _away_from_zero(3, 4)}, {}),
    # unary math
    "sqrt": lambda: (sym.sqrt(V("a")), {"a": _pos64(3, 4)}, {}),
    "rsqrt": lambda: (sym.rsqrt(V("a")), {"a": _pos64(3, 4)}, {}),
    "exp": lambda: (sym.exp(V("a")), {"a": _f64(3, 4)}, {}),
    "log": lambda: (sym.log(V("a")), {"a": _pos64(3, 4)}, {}),
    "cos": lambda: (sym.cos(V("a")), {"a": _f64(3, 4)}, {}),
    "sin": lambda: (sym.sin(V("a")), {"a": _f64(3, 4)}, {}),
    "abs": lambda: (sym.abs(V("a")), {"a": _away_from_zero(3, 4)}, {}),
    "square": lambda: (sym.square(V("a")), {"a": _f64(3, 4)}, {}),
    "negative": lambda: (sym.negative(V("a")), {"a": _f64(3, 4)}, {}),
    "_copy": lambda: (sym._copy(V("a")), {"a": _f64(3, 4)}, {}),
    "_CrossDeviceCopy": lambda: (sym._CrossDeviceCopy(V("a")),
                                 {"a": _f64(3, 4)}, {}),
    "smooth_l1": lambda: (sym.smooth_l1(V("a"), scalar=1.0),
                          # keep |x| off the transition point 1/sigma^2
                          {"a": np.array([[-2.0, -0.5, 0.3, 1.7]])}, {}),
    # reductions
    "sum": lambda: (sym.sum(V("a"), axis=(1,)), {"a": _f64(2, 3, 4)}, {}),
    "max": lambda: (sym.max(V("a"), axis=(1,)), {"a": _distinct64(2, 3, 4)}, {}),
    "min": lambda: (sym.min(V("a"), axis=(1,)), {"a": _distinct64(2, 3, 4)}, {}),
    "norm": lambda: (sym.norm(V("a")), {"a": _pos64(3, 4)}, {}),
    # matrix
    "dot": lambda: (sym.dot(V("a"), V("b")),
                    {"a": _f64(3, 4), "b": _f64(4, 2)}, {}),
    "batch_dot": lambda: (sym.batch_dot(V("a"), V("b")),
                          {"a": _f64(2, 3, 4), "b": _f64(2, 4, 2)}, {}),
    # shape manipulation
    "transpose": lambda: (sym.transpose(V("a"), axes=(1, 0, 2)),
                          {"a": _f64(2, 3, 4)}, {}),
    "expand_dims": lambda: (sym.expand_dims(V("a"), axis=1),
                            {"a": _f64(3, 4)}, {}),
    "flip": lambda: (sym.flip(V("a"), axis=1), {"a": _f64(3, 4)}, {}),
    "slice_axis": lambda: (sym.slice_axis(V("a"), axis=1, begin=1, end=3),
                           {"a": _f64(3, 4)}, {}),
    "Reshape": lambda: (sym.Reshape(V("a"), shape=(2, 12)),
                        {"a": _f64(2, 3, 4)}, {}),
    "Flatten": lambda: (sym.Flatten(V("a")), {"a": _f64(2, 3, 4)}, {}),
    "SwapAxis": lambda: (sym.SwapAxis(V("a"), dim1=0, dim2=2),
                         {"a": _f64(2, 3, 4)}, {}),
    "Concat": lambda: (sym.Concat(V("a"), V("b"), dim=1, name="cc"),
                       {"a": _f64(2, 3), "b": _f64(2, 2)}, {}),
    "SliceChannel": lambda: (sym.SliceChannel(V("a"), num_outputs=2,
                                              name="sc"),
                             {"a": _f64(2, 4)}, {}),
    "Crop": lambda: (sym.Crop(V("a"), num_args=1, h_w=(3, 3), name="cr"),
                     {"a": _f64(1, 2, 5, 5)}, {}),
    "broadcast_axis": lambda: (sym.broadcast_axis(V("a"), axis=(0,), size=(3,)),
                               {"a": _f64(1, 4)}, {}),
    "broadcast_to": lambda: (sym.broadcast_to(V("a"), shape=(3, 4)),
                             {"a": _f64(1, 4)}, {}),
    "ElementWiseSum": lambda: (sym.ElementWiseSum(V("a"), V("b"), V("c"),
                                                  name="ews"),
                               {"a": _f64(3, 4), "b": _f64(3, 4),
                                "c": _f64(3, 4)}, {}),
    "element_mask": lambda: (sym.element_mask(V("a"), V("m")),
                             {"a": _f64(4, 3),
                              "m": np.array([1.0, 0.0, 1.0, 1.0])},
                             {"grad_nodes": ["a"]}),
    "Cast": lambda: (sym.Cast(V("a"), dtype="float32"), {"a": _f64(3, 4)}, {}),
    # nn layers
    "Activation": lambda: (sym.Activation(V("a"), act_type="sigmoid"),
                           {"a": _f64(3, 4)}, {}),
    "LeakyReLU": lambda: (sym.LeakyReLU(V("a"), act_type="leaky", slope=0.25),
                          {"a": _away_from_zero(3, 4)}, {}),
    "SoftmaxActivation": lambda: (sym.SoftmaxActivation(V("a")),
                                  {"a": _f64(3, 4)}, {}),
    "FullyConnected": lambda: (
        sym.FullyConnected(V("a"), num_hidden=3, name="fc"),
        {"a": _f64(2, 4), "fc_weight": _f64(3, 4), "fc_bias": _f64(3)}, {}),
    "Convolution": lambda: (
        sym.Convolution(V("a"), kernel=(3, 3), num_filter=2, pad=(1, 1),
                        name="cv"),
        {"a": _f64(1, 2, 4, 4), "cv_weight": _f64(2, 2, 3, 3),
         "cv_bias": _f64(2)},
        {"rtol": 5e-2, "atol": 5e-2}),
    "Deconvolution": lambda: (
        sym.Deconvolution(V("a"), kernel=(3, 3), num_filter=2, pad=(1, 1),
                          name="dc"),
        {"a": _f64(1, 2, 4, 4), "dc_weight": _f64(2, 2, 3, 3),
         "dc_bias": _f64(2)},
        {"rtol": 5e-2, "atol": 5e-2}),
    "Pooling": lambda: (
        sym.Pooling(V("a"), kernel=(2, 2), stride=(2, 2), pool_type="avg"),
        {"a": _f64(1, 2, 4, 4)}, {}),
    "BatchNorm": lambda: (
        sym.BatchNorm(V("a"), fix_gamma=False, name="bn"),
        {"a": _f64(4, 3), "bn_gamma": _pos64(3), "bn_beta": _f64(3)},
        {"aux_states": [np.zeros(3, np.float32), np.ones(3, np.float32)],
         "rtol": 5e-2, "atol": 5e-2}),
    "LayerNorm": lambda: (
        sym.LayerNorm(V("a"), name="ln"),
        {"a": _f64(4, 3), "ln_gamma": _pos64(3), "ln_beta": _f64(3)},
        {"rtol": 5e-2, "atol": 5e-2}),
    "LRN": lambda: (sym.LRN(V("a"), nsize=3),
                    {"a": _pos64(1, 4, 3, 3)}, {"rtol": 5e-2, "atol": 5e-2}),
    "L2Normalization": lambda: (sym.L2Normalization(V("a")),
                                {"a": _f64(2, 3, 2)},
                                {"rtol": 5e-2, "atol": 5e-2}),
    "Dropout": lambda: (sym.Dropout(V("a"), p=0.0), {"a": _f64(3, 4)}, {}),
    "Embedding": lambda: (
        sym.Embedding(V("ids"), input_dim=4, output_dim=3, name="em"),
        {"ids": np.array([1.0, 0.0, 3.0, 2.0]), "em_weight": _f64(4, 3)},
        {"grad_nodes": ["em_weight"]}),
    "UpSampling": lambda: (
        sym.UpSampling(V("a"), scale=2, sample_type="nearest", num_args=1),
        {"a": _f64(1, 2, 3, 3)}, {}),
    "Correlation": lambda: (
        sym.Correlation(V("a"), V("b"), kernel_size=1, max_displacement=1,
                        pad_size=1),
        {"a": _f64(1, 2, 4, 4), "b": _f64(1, 2, 4, 4)},
        {"rtol": 5e-2, "atol": 5e-2}),
    "SpatialTransformer": lambda: (
        sym.SpatialTransformer(V("a"), V("loc"), target_shape=(4, 4),
                               transform_type="affine",
                               sampler_type="bilinear"),
        {"a": _f64(1, 2, 4, 4),
         "loc": np.array([[0.9, 0.05, 0.03, -0.05, 1.1, 0.07]])},
        {"rtol": 5e-2, "atol": 5e-2}),
    "ROIPooling": lambda: (
        sym.ROIPooling(V("a"), V("rois"), pooled_size=(2, 2),
                       spatial_scale=1.0),
        {"a": _distinct64(1, 2, 6, 6),
         "rois": np.array([[0.0, 0.0, 0.0, 5.0, 5.0]])},
        {"grad_nodes": ["a"], "rtol": 5e-2, "atol": 5e-2}),
    "RNN": lambda: (
        sym.RNN(V("a"), state_size=3, num_layers=1, mode="lstm", name="rn"),
        {"a": _f64(3, 2, 3),
         "rn_parameters": rng.uniform(-0.4, 0.4,
                                      (3 * (3 + 3 + 2) * 4,)),
         "rn_state": np.zeros((1, 2, 3)),
         "rn_state_cell": np.zeros((1, 2, 3))},
        {"grad_nodes": ["a", "rn_parameters"], "rtol": 5e-2, "atol": 5e-2}),
    "MoE": lambda: (
        sym.MoE(V("a"), num_experts=2, hidden_size=4, name="mo"),
        # gate logits get a wide margin (scaled gate weights on
        # well-spread tokens) so routing never flips inside the
        # numeric-diff epsilon and the top-1 mask stays constant
        {"a": _distinct64(6, 4) * 2.0,
         "mo_gate_weight": np.array([[3.0, 0, 0, 0], [0, 3.0, 0, 0]]),
         "mo_expert_fc1_weight": _f64(2, 4, 4) * 0.4,
         "mo_expert_fc1_bias": _f64(2, 4) * 0.1 + 0.5,
         "mo_expert_fc2_weight": _f64(2, 4, 4) * 0.4,
         "mo_expert_fc2_bias": _f64(2, 4) * 0.1},
        {"rtol": 5e-2, "atol": 5e-3}),
    "MultiHeadAttention": lambda: (
        sym.MultiHeadAttention(V("a"), num_heads=2, use_flash=False,
                               name="mh"),
        {"a": _f64(1, 3, 4), "mh_qkv_weight": _f64(12, 4) * 0.4,
         "mh_qkv_bias": _f64(12) * 0.1, "mh_out_weight": _f64(4, 4) * 0.4,
         "mh_out_bias": _f64(4) * 0.1},
        {"rtol": 5e-2, "atol": 5e-2}),
    "SequenceLast": lambda: (sym.SequenceLast(V("a")),
                             {"a": _f64(4, 2, 3)}, {}),
    "SequenceReverse": lambda: (sym.SequenceReverse(V("a")),
                                {"a": _f64(4, 2, 3)}, {}),
    "SequenceMask": lambda: (sym.SequenceMask(V("a")),
                             {"a": _f64(4, 2, 3)}, {}),
    "softmax_cross_entropy": lambda: (
        sym.softmax_cross_entropy(V("a"), V("l")),
        {"a": _f64(3, 4), "l": np.array([0.0, 2.0, 1.0])},
        {"grad_nodes": ["a"], "rtol": 5e-2, "atol": 5e-2}),
}

# ---------------------------------------------------------------------------
# Tier 2: backward is a documented contract, not d(forward).
# name -> callable running the contract check.
# ---------------------------------------------------------------------------


def _contract_blockgrad():
    a = _f64(3, 4).astype(np.float32)
    s = sym.BlockGrad(V("x"))
    check_symbolic_backward(s, [a], [np.ones_like(a)], [np.zeros_like(a)])


def _contract_softmax_output():
    data = _f64(4, 5).astype(np.float32)
    label = np.array([0, 2, 4, 1], np.float32)
    e = np.exp(data - data.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    onehot = np.eye(5, dtype=np.float32)[label.astype(int)]
    # head gradient deliberately NOT ones: backward must ignore it
    og = np.full_like(data, 3.0)
    check_symbolic_backward(sym.SoftmaxOutput(V("x"), name="sm"),
                            [data, label], [og], {"x": p - onehot},
                            rtol=1e-3)


def _contract_svm_output():
    data = _f64(3, 4).astype(np.float32)
    label = np.array([1, 0, 3], np.float32)
    s = sym.SVMOutput(V("x"), name="svm", margin=0.5, use_linear=True)
    scores = data
    lab = label.astype(int)
    grad = np.zeros_like(scores)
    for i in range(3):
        sl = scores[i, lab[i]]
        for k in range(4):
            if k == lab[i]:
                continue
            if scores[i, k] - sl + 0.5 > 0:
                grad[i, k] = 1.0
                grad[i, lab[i]] -= 1.0
    og = np.full_like(data, 9.0)  # must be ignored
    check_symbolic_backward(s, [data, label], [og], {"x": grad}, rtol=1e-3)


def _contract_regressions():
    data = _f64(4, 3).astype(np.float32)
    label = _f64(4, 3).astype(np.float32)
    og = np.full_like(data, 7.0)  # must be ignored
    check_symbolic_backward(sym.LinearRegressionOutput(V("x"), name="a"),
                            [data, label], [og], {"x": data - label},
                            rtol=1e-3)
    sig = 1 / (1 + np.exp(-data))
    check_symbolic_backward(sym.LogisticRegressionOutput(V("x"), name="b"),
                            [data, label], [og], {"x": sig - label},
                            rtol=1e-3)
    check_symbolic_backward(sym.MAERegressionOutput(V("x"), name="c"),
                            [data, label], [og],
                            {"x": np.sign(data - label)}, rtol=1e-3)


def _contract_makeloss():
    a = _f64(3, 4).astype(np.float32)
    og = np.full_like(a, 5.0)  # must be ignored: grad is grad_scale
    check_symbolic_backward(sym.MakeLoss(V("x"), grad_scale=2.0),
                            [a], [og], [np.full_like(a, 2.0)])


def _contract_kl_sparse_reg():
    data = _pos64(4, 3).astype(np.float32) * 0.3
    s = sym.IdentityAttachKLSparseReg(V("x"), sparseness_target=0.1,
                                      penalty=0.01, momentum=0.0)
    avg = data.mean(axis=0)
    pen = 0.01 * (-0.1 / (avg + 1e-8) + 0.9 / (1 - avg + 1e-8))
    og = np.ones_like(data)
    check_symbolic_backward(s, [data], [og], {"x": og + pen[None, :]},
                            aux_states=[np.zeros(3, np.float32)], rtol=1e-3)


def _contract_element_mask():
    a = _f64(4, 3).astype(np.float32)
    m = np.array([1, 0, 1, 0], np.float32)
    og = np.ones((4, 3), np.float32)
    check_symbolic_backward(sym.element_mask(V("x"), V("m")), [a, m], [og],
                            {"m": np.zeros_like(m)})


def _contract_zero_grad_unaries():
    """Piecewise-constant ops: gradient is identically zero (matches the
    reference kernels, e.g. sign_grad/round have no backward)."""
    a = _away_from_zero(3, 4).astype(np.float32)
    og = np.ones_like(a)
    for s in (sym.sign(V("x")), sym.round(V("x")), sym.ceil(V("x")),
              sym.floor(V("x"))):
        check_symbolic_backward(s, [a], [og], [np.zeros_like(a)])


def _contract_argmax_channel():
    a = _distinct64(3, 4).astype(np.float32)
    og = np.ones((3,), np.float32)
    check_symbolic_backward(sym.argmax_channel(V("x")), [a], [og],
                            [np.zeros_like(a)])


CONTRACT_SPECS = {
    "BlockGrad": _contract_blockgrad,
    "SoftmaxOutput": _contract_softmax_output,
    "SVMOutput": _contract_svm_output,
    "LinearRegressionOutput": _contract_regressions,
    "LogisticRegressionOutput": _contract_regressions,
    "MAERegressionOutput": _contract_regressions,
    "MakeLoss": _contract_makeloss,
    "IdentityAttachKLSparseReg": _contract_kl_sparse_reg,
    "element_mask": _contract_element_mask,
    "sign": _contract_zero_grad_unaries,
    "round": _contract_zero_grad_unaries,
    "ceil": _contract_zero_grad_unaries,
    "floor": _contract_zero_grad_unaries,
    "argmax_channel": _contract_argmax_channel,
}

# ---------------------------------------------------------------------------
# Tier 3: no gradient story, with reasons.
# ---------------------------------------------------------------------------
EXEMPT = {
    "_sample_uniform": "random sampler: no inputs to differentiate",
    "_sample_normal": "random sampler: no inputs to differentiate",
    "Custom": "host-callback op: fwd+bwd covered by tests/test_custom_op.py",
    "_Native": "legacy host-callback op: covered by tests/test_custom_op.py",
    "CachedMultiHeadAttention":
        "serving-only prefill/decode op with no backward (generation "
        "graphs are inference-only); forward equivalence against the "
        "trainable attention path is pinned by tests/test_generate.py::"
        "test_decode_matches_full_forward",
    "QuantizedDense":
        "inference-only weight-quantized FullyConnected (quantize_symbol "
        "rewrites predict/serve graphs, never training graphs — training "
        "keeps f32 FullyConnected); forward equivalence vs the f32 path "
        "is pinned by tests/test_kernels.py::"
        "test_predictor_quantized_cosine",
}


@pytest.mark.parametrize("name", sorted(GRAD_SPECS))
def test_numeric_gradient(name):
    s, location, kwargs = GRAD_SPECS[name]()
    kwargs.setdefault("rtol", 2e-2)
    kwargs.setdefault("atol", 2e-3)
    aux = kwargs.pop("aux_states", None)
    check_numeric_gradient(s, location, aux_states=aux, **kwargs)


@pytest.mark.parametrize("name", sorted(CONTRACT_SPECS))
def test_backward_contract(name):
    CONTRACT_SPECS[name]()


def test_every_registered_op_has_gradient_coverage():
    """The audit: no op may be registered without a gradient check or a
    recorded exemption."""
    from mxnet_tpu.ops.registry import OP_REGISTRY
    # dedupe aliases: one class == one op, any of its names may be covered
    by_class = {}
    for name, cls in OP_REGISTRY._entries.values():
        by_class.setdefault(cls, []).append(name)
    covered = set(GRAD_SPECS) | set(CONTRACT_SPECS) | set(EXEMPT)
    covered_lower = {c.lower() for c in covered}
    missing = sorted(
        names[0] for names in by_class.values()
        if not any(n.lower() in covered_lower for n in names))
    assert not missing, (
        "registered ops without gradient coverage (add to GRAD_SPECS, "
        "CONTRACT_SPECS, or EXEMPT with a reason): %s" % missing)
