"""Tests for metrics, initializers, callbacks (reference test style)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import metric, initializer


def test_accuracy():
    m = metric.create("acc")
    pred = mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = mx.nd.array([1, 0, 0])
    m.update([label], [pred])
    name, value = m.get()
    assert name == "accuracy"
    assert abs(value - 2.0 / 3.0) < 1e-6


def test_topk():
    m = metric.create("top_k_accuracy", top_k=2)
    pred = mx.nd.array([[0.5, 0.3, 0.2], [0.1, 0.2, 0.7]])
    label = mx.nd.array([1, 0])
    m.update([label], [pred])
    _, value = m.get()
    assert abs(value - 0.5) < 1e-6


def test_mse_mae_rmse():
    pred = mx.nd.array([[1.0], [2.0]])
    label = mx.nd.array([0.0, 4.0])
    for name, expect in [("mse", (1.0 + 4.0) / 2), ("mae", (1 + 2) / 2.0)]:
        m = metric.create(name)
        m.update([label], [pred])
        assert abs(m.get()[1] - expect) < 1e-6, name


def test_cross_entropy():
    m = metric.create("ce")
    pred = mx.nd.array([[0.2, 0.8], [0.9, 0.1]])
    label = mx.nd.array([1, 0])
    m.update([label], [pred])
    expect = (-np.log(0.8 + 1e-8) - np.log(0.9 + 1e-8)) / 2
    assert abs(m.get()[1] - expect) < 1e-6


def test_perplexity_ignore():
    m = metric.Perplexity(ignore_label=0)
    pred = mx.nd.array([[0.2, 0.8], [0.5, 0.5]])
    label = mx.nd.array([1, 0])
    m.update([label], [pred])
    _, val = m.get()
    assert abs(val - np.exp(-np.log(0.8))) < 1e-5


def test_composite_and_custom():
    def feval(label, pred):
        return float(np.sum(label))
    comp = metric.create(["acc", metric.np(feval)])
    pred = mx.nd.array([[0.3, 0.7]])
    label = mx.nd.array([1])
    comp.update([label], [pred])
    names, values = comp.get()
    assert len(names) == 2 and len(values) == 2


def test_f1():
    m = metric.create("f1")
    pred = mx.nd.array([[0.3, 0.7], [0.8, 0.2], [0.1, 0.9]])
    label = mx.nd.array([1, 0, 1])
    m.update([label], [pred])
    assert abs(m.get()[1] - 1.0) < 1e-6


# --------------------------- initializers ----------------------------------
def test_uniform_normal_ranges():
    mx.random.seed(42)
    arr = mx.nd.zeros((100, 100))
    initializer.Uniform(0.1)("fc_weight", arr)
    a = arr.asnumpy()
    assert a.min() >= -0.1 and a.max() <= 0.1 and abs(a.mean()) < 0.01
    initializer.Normal(2.0)("fc_weight", arr)
    a = arr.asnumpy()
    assert abs(a.std() - 2.0) < 0.1


def test_init_name_dispatch():
    ini = initializer.Uniform(0.5)
    bias = mx.nd.ones((4,))
    ini("fc_bias", bias)
    np.testing.assert_allclose(bias.asnumpy(), 0.0)
    gamma = mx.nd.zeros((4,))
    ini("bn_gamma", gamma)
    np.testing.assert_allclose(gamma.asnumpy(), 1.0)
    mmean = mx.nd.ones((4,))
    ini("bn_moving_mean", mmean)
    np.testing.assert_allclose(mmean.asnumpy(), 0.0)
    mvar = mx.nd.zeros((4,))
    ini("bn_moving_var", mvar)
    np.testing.assert_allclose(mvar.asnumpy(), 1.0)


def test_xavier_scale():
    mx.random.seed(0)
    arr = mx.nd.zeros((64, 64))
    initializer.Xavier(factor_type="avg", magnitude=3)("fc_weight", arr)
    a = arr.asnumpy()
    bound = np.sqrt(3.0 / 64)
    assert a.min() >= -bound - 1e-6 and a.max() <= bound + 1e-6


def test_orthogonal():
    mx.random.seed(0)
    arr = mx.nd.zeros((16, 16))
    initializer.Orthogonal(scale=1.0)("fc_weight", arr)
    a = arr.asnumpy()
    np.testing.assert_allclose(a @ a.T, np.eye(16), atol=1e-4)


def test_msra_prelu():
    mx.random.seed(0)
    arr = mx.nd.zeros((128, 128))
    initializer.MSRAPrelu()("fc_weight", arr)
    a = arr.asnumpy()
    expect_std = np.sqrt(2.0 / (1 + 0.25 ** 2) / 128)
    assert abs(a.std() - expect_std) / expect_std < 0.15


def test_load_and_mixed():
    src = {"arg:fc_weight": mx.nd.ones((2, 2))}
    ini = initializer.Load(src, default_init=initializer.Zero())
    w = mx.nd.zeros((2, 2))
    ini("fc_weight", w)
    np.testing.assert_allclose(w.asnumpy(), 1.0)
    other = mx.nd.ones((3,))
    ini("other_weight", other)
    np.testing.assert_allclose(other.asnumpy(), 0.0)

    mixed = initializer.Mixed([".*bias", ".*"],
                              [initializer.One(), initializer.Zero()])
    b = mx.nd.zeros((3,))
    mixed("fc_bias", b)
    np.testing.assert_allclose(b.asnumpy(), 1.0)


def test_speedometer_and_batch_end():
    from mxnet_tpu.callback import Speedometer, BatchEndParam
    s = Speedometer(batch_size=32, frequent=1)
    m = metric.create("acc")
    m.update([mx.nd.array([1])], [mx.nd.array([[0.2, 0.8]])])
    for i in range(3):
        s(BatchEndParam(epoch=0, nbatch=i, eval_metric=m, locals=None))


def test_profiler_step_timer_and_annotate():
    from mxnet_tpu import profiler
    t = profiler.StepTimer(batch_size=8)
    for _ in range(3):
        t.start()
        t.stop()
    s = t.summary()
    assert s["steps"] == 2 and s["samples_per_sec"] > 0
    with profiler.annotate("region"):
        pass
