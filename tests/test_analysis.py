"""mxnet_tpu/analysis/: every lint rule gets a positive hit on a
known-bad graph AND stays silent on the bundled clean models; plus the
three wiring surfaces (Symbol.validate, the Executor validate= knob,
analyze_json for saved graphs)."""
import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu.analysis import (GraphIssue, GraphLintWarning, analyze,
                                analyze_json, max_severity)
from mxnet_tpu.base import MXNetError


def _ids(issues):
    return {i.rule_id for i in issues}


def _only(issues, rule_id):
    return [i for i in issues if i.rule_id == rule_id]


# ----------------------------------------------------------------------
# clean models: no false positives
# ----------------------------------------------------------------------
@pytest.mark.parametrize("builder,shapes", [
    (lambda: mx.models.get_mlp(), {"data": (32, 784)}),
    (lambda: mx.models.get_alexnet(), {"data": (2, 3, 224, 224)}),
])
def test_clean_models_have_no_findings(builder, shapes):
    issues = builder().validate(shapes=shapes)
    assert issues == [], analysis.format_issues(issues)


def test_clean_model_without_shapes_only_info():
    """No shape hints: unknown shapes are expected, so MXL-S001 reports
    at info severity and nothing else fires."""
    issues = mx.models.get_mlp().validate()
    assert all(i.severity == "info" for i in issues), issues
    assert _ids(issues) <= {"MXL-S001"}


# ----------------------------------------------------------------------
# MXL-S / MXL-T: shape & dtype re-verification
# ----------------------------------------------------------------------
def test_s001_unknown_shape_is_warning_with_hints():
    net = mx.models.get_mlp()
    # a hint that leaves fc weights underdetermined: batch dim only
    issues = net.validate(select={"MXL-S001"})
    assert _only(issues, "MXL-S001"), "expected unknown-shape findings"


def test_s002_contradictory_shapes():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=5, name="fc")
    bad = fc + data          # (N, 5) + (N, 784): contradiction
    issues = bad.validate(data=(8, 784))
    hits = _only(issues, "MXL-S002")
    assert hits and all(i.severity == "error" for i in hits)
    # errors sort first
    assert issues[0].severity == "error"


def test_t001_mixed_float_widths():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = a + b
    issues = out.validate(shapes={"a": (4, 4), "b": (4, 4)},
                          type_dict={"a": np.float32, "b": jnp.bfloat16})
    hits = _only(issues, "MXL-T001")
    assert len(hits) == 1 and hits[0].severity == "warning"
    assert "bfloat16" in hits[0].message
    # uniform dtypes: silent
    clean = out.validate(shapes={"a": (4, 4), "b": (4, 4)},
                         type_dict={"a": np.float32, "b": np.float32})
    assert not _only(clean, "MXL-T001")


def test_t002_infer_type_failure():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=3, name="fc")

    def boom(in_types):
        raise TypeError("synthetic infer_type failure")

    fc._heads[0][0].op.infer_type = boom
    issues = fc.validate(data=(2, 8), select={"MXL-T002"})
    hits = _only(issues, "MXL-T002")
    assert hits and hits[0].severity == "error"
    assert "synthetic" in hits[0].message


# ----------------------------------------------------------------------
# MXL-G: dead / unused / alias / duplicate names
# ----------------------------------------------------------------------
def _saved_graph_with_orphans():
    """mlp JSON + one orphan op node (dead) + one orphan variable."""
    graph = json.loads(mx.models.get_mlp().tojson())
    n = len(graph["nodes"])
    graph["nodes"].append({"op": "null", "name": "orphan_var",
                           "attr": {}, "inputs": []})
    graph["nodes"].append({"op": "Flatten", "name": "orphan_op",
                           "attr": {}, "inputs": [[n, 0]]})
    graph["arg_nodes"].append(n)
    return graph


def test_g001_g002_dead_nodes_in_saved_graph():
    issues = analyze_json(_saved_graph_with_orphans())
    dead = _only(issues, "MXL-G001")
    unused = _only(issues, "MXL-G002")
    assert [i.node for i in dead] == ["orphan_op"]
    assert [i.node for i in unused] == ["orphan_var"]
    # the clean round-trip has neither
    assert not _ids(analyze_json(mx.models.get_mlp().tojson())) & \
        {"MXL-G001", "MXL-G002"}


def test_g002_ignored_bind_dict_keys():
    net = mx.models.get_mlp()
    issues = analyze(net, args={"data": None, "not_an_arg": None},
                     select={"MXL-G002"})
    hits = _only(issues, "MXL-G002")
    assert len(hits) == 1 and "not_an_arg" in hits[0].message


def test_g003_output_aliases_input():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=2, name="fc")
    grouped = mx.sym.Group([fc, data])      # head 1 is a bare variable
    hits = _only(grouped.validate(), "MXL-G003")
    assert hits and hits[0].node == "data"
    dup = mx.sym.Group([fc, fc])            # duplicate head
    assert _only(dup.validate(), "MXL-G003")


def test_g004_duplicate_node_names():
    a = mx.sym.Variable("x")
    f1 = mx.sym.FullyConnected(data=a, num_hidden=2, name="same")
    f2 = mx.sym.FullyConnected(data=f1, num_hidden=2, name="same")
    hits = _only(f2.validate(), "MXL-G004")
    assert hits and hits[0].severity == "error"
    assert "same" in hits[0].message


# ----------------------------------------------------------------------
# MXL-B: bind contract
# ----------------------------------------------------------------------
def _two_var_sum():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    return a + b


def test_b001_shared_grad_buffer():
    net = _two_var_sum()
    g = mx.nd.zeros((4,))
    issues = analyze(net, args_grad={"a": g, "b": g}, grad_req="write")
    hits = _only(issues, "MXL-B001")
    assert {i.node for i in hits} == {"a", "b"}
    assert all(i.severity == "error" for i in hits)
    # grad_req='add' on shared buffers is the supported pattern
    assert not _only(analyze(net, args_grad={"a": g, "b": g},
                             grad_req="add"), "MXL-B001")


def test_b002_partial_args_grad():
    net = _two_var_sum()
    issues = analyze(net, args_grad={"a": mx.nd.zeros((4,))},
                     grad_req="write")
    hits = _only(issues, "MXL-B002")
    assert [i.node for i in hits] == ["b"]
    # all-None args_grad = intentional forward-only: silent
    assert not _only(analyze(net, grad_req="write"), "MXL-B002")


def test_b003_aux_name_collision():
    data = mx.sym.Variable("data")
    bn1 = mx.sym.BatchNorm(data=data, name="bn")
    bn2 = mx.sym.BatchNorm(data=bn1, name="bn")
    issues = analyze(bn2, grad_req="write")
    assert _only(issues, "MXL-B003")
    assert _only(issues, "MXL-G004")    # same root cause, both surfaced


def test_b004_invalid_grad_req():
    issues = analyze(_two_var_sum(), grad_req="wirte")   # typo'd "write"
    hits = _only(issues, "MXL-B004")
    assert hits and all(i.severity == "error" for i in hits)


def test_b005_unmapped_ctx_group():
    with mx.AttrScope(ctx_group="dev1"):
        net = _two_var_sum()
    issues = analyze(net, group2ctx={"dev2": mx.cpu()})
    assert _only(issues, "MXL-B005")
    # empty group2ctx: the attrs are inert, no finding
    assert not _only(analyze(net), "MXL-B005")


# ----------------------------------------------------------------------
# MXL-L: TPU lowering lint
# ----------------------------------------------------------------------
def test_l001_unsupported_platform():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=2, name="fc")
    fc._heads[0][0].op.unsupported_platforms = ("tpu",)
    hits = _only(fc.validate(target="tpu"), "MXL-L001")
    assert hits and hits[0].severity == "error"
    assert not _only(fc.validate(target="cpu"), "MXL-L001")


def test_l001_unregistered_op_in_saved_graph():
    graph = json.loads(mx.models.get_mlp().tojson())
    for spec in graph["nodes"]:
        if spec["op"] == "FullyConnected":
            spec["op"] = "NoSuchOp"
            break
    issues = analyze_json(graph)
    hits = _only(issues, "MXL-L001")
    assert hits and "NoSuchOp" in hits[0].message


class _LintDemoProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []


mx.operator.register("analysis_lintdemo")(_LintDemoProp)


def test_l002_l003_host_callback():
    data = mx.sym.Variable("data")
    plain = mx.sym.Custom(data=data, op_type="analysis_lintdemo")
    out = mx.sym.FullyConnected(data=plain, num_hidden=2, name="fc")
    issues = out.validate(data=(2, 4))
    assert _only(issues, "MXL-L003")          # info: fusion barrier
    assert not _only(issues, "MXL-L002")      # not mirrored: no error

    mirrored = mx.sym.Custom(data=data, op_type="analysis_lintdemo",
                             attr={"force_mirroring": "1"})
    out2 = mx.sym.FullyConnected(data=mirrored, num_hidden=2, name="fc")
    hits = _only(out2.validate(data=(2, 4)), "MXL-L002")
    assert hits and hits[0].severity == "error"


def test_l004_sharding_axes_vs_mesh():
    from jax.sharding import Mesh, PartitionSpec as P
    from mxnet_tpu.parallel.sharding import ShardingRules
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    net = mx.models.get_mlp()
    bad = ShardingRules([(r".*_weight", lambda s, m: P("mp", None))])
    hits = _only(net.validate(data=(8, 784), mesh=mesh,
                              sharding_rules=bad), "MXL-L004")
    assert hits and all(i.severity == "error" for i in hits)
    assert "mp" in hits[0].message
    ok = ShardingRules([(r"fc1_weight", lambda s, m: P(None, "tp"))])
    assert not _only(net.validate(data=(8, 784), mesh=mesh,
                                  sharding_rules=ok), "MXL-L004")


# ----------------------------------------------------------------------
# framework: suppression, select/skip, ordering, issue type
# ----------------------------------------------------------------------
def test_suppression_via_node_attr():
    data = mx.sym.Variable("data")
    quiet = mx.sym.Custom(data=data, op_type="analysis_lintdemo",
                          attr={"force_mirroring": "1",
                                "__lint_ignore__": "MXL-L002,MXL-L003"})
    out = mx.sym.FullyConnected(data=quiet, num_hidden=2, name="fc")
    issues = out.validate(data=(2, 4))
    assert not _ids(issues) & {"MXL-L002", "MXL-L003"}

    all_quiet = mx.sym.Custom(data=data, op_type="analysis_lintdemo",
                              attr={"force_mirroring": "1",
                                    "__lint_ignore__": "all"})
    out2 = mx.sym.FullyConnected(data=all_quiet, num_hidden=2, name="fc")
    assert not _ids(out2.validate(data=(2, 4))) & {"MXL-L002", "MXL-L003"}


def test_select_and_skip():
    net = mx.models.get_mlp()
    only = net.validate(select={"MXL-S001"})
    assert _ids(only) <= {"MXL-S001"}
    skipped = net.validate(skip={"MXL-S001"})
    assert "MXL-S001" not in _ids(skipped)


def test_issue_type_and_ordering():
    i = GraphIssue("MXL-X999", "warning", "node1", "msg")
    assert i.as_dict() == {"rule_id": "MXL-X999", "severity": "warning",
                           "node": "node1", "message": "msg"}
    assert "MXL-X999" in repr(i)
    assert max_severity([]) is None
    assert max_severity([i]) == "warning"
    # registry sanity: every registered rule id is well-formed & unique
    ids = list(analysis.RULE_REGISTRY)
    assert len(ids) == len(set(ids))
    assert all(r.startswith("MXL-") for r in ids)
    assert all(analysis.RULE_REGISTRY[r].severity in analysis.SEVERITIES
               for r in ids)


# ----------------------------------------------------------------------
# Executor wiring: validate="warn"|"error"|"off"
# ----------------------------------------------------------------------
def _bad_bind_kwargs():
    net = _two_var_sum()
    g = mx.nd.zeros((4,))
    args = {"a": mx.nd.zeros((4,)), "b": mx.nd.zeros((4,))}
    return net, dict(args=args, args_grad={"a": g, "b": g},
                     grad_req="write")


def test_bind_validate_default_warns():
    net, kw = _bad_bind_kwargs()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        exe = net.bind(mx.cpu(), **kw)
    lint = [w for w in rec if issubclass(w.category, GraphLintWarning)]
    assert len(lint) == 1 and "MXL-B001" in str(lint[0].message)
    assert {i.rule_id for i in exe.bind_issues} >= {"MXL-B001"}


def test_bind_validate_error_raises():
    net, kw = _bad_bind_kwargs()
    with pytest.raises(MXNetError, match="MXL-B001"):
        net.bind(mx.cpu(), validate="error", **kw)


def test_bind_validate_off_is_silent():
    net, kw = _bad_bind_kwargs()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        exe = net.bind(mx.cpu(), validate="off", **kw)
    assert not [w for w in rec
                if issubclass(w.category, GraphLintWarning)]
    assert exe.bind_issues == []


def test_bind_validate_env_default(monkeypatch):
    monkeypatch.setenv("MXTPU_BIND_VALIDATE", "error")
    net, kw = _bad_bind_kwargs()
    with pytest.raises(MXNetError, match="bind validation failed"):
        net.bind(mx.cpu(), **kw)


def test_bind_validate_bad_mode_rejected():
    net, kw = _bad_bind_kwargs()
    with pytest.raises(MXNetError, match="validate"):
        net.bind(mx.cpu(), validate="loud", **kw)


def test_clean_bind_emits_no_warning():
    net = mx.models.get_mlp()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        exe = net.simple_bind(mx.cpu(), data=(8, 784))
    assert not [w for w in rec
                if issubclass(w.category, GraphLintWarning)]
    assert exe.bind_issues == []


# ----------------------------------------------------------------------
# MXL-P/M/C: SPMD propagation, memory, collective audit
# ----------------------------------------------------------------------
def _mesh22():
    from mxnet_tpu.parallel import LogicalMesh
    return LogicalMesh(dp=2, tp=2)


def _transformer():
    from mxnet_tpu.models.transformer import get_symbol
    return get_symbol(vocab_size=512, num_layers=2, num_heads=4, dim=64,
                      seq_len=64), {"data": (2, 64), "softmax_label": (2, 64)}


def test_spmd_transformer_clean_under_mesh():
    """The bundled transformer under dp=2,tp=2 has no sharding errors:
    only the expected row-parallel psum (info) and the one-sided
    contractions the default policy leaves open (warning)."""
    net, shapes = _transformer()
    issues = net.validate(shapes=shapes, mesh=_mesh22())
    assert max_severity(issues) != "error", analysis.format_issues(issues)
    assert _only(issues, "MXL-P004")
    assert _only(issues, "MXL-C003")
    # and the communication report prices the implied collectives
    ctxs = []
    analyze(net, shapes=shapes, mesh=_mesh22(), _ctx_out=ctxs)
    comm = analysis.comm_report(ctxs[0])
    assert comm["complete"] and comm["total_bytes"] > 0
    assert comm["by_kind"]["reduce"]["count"] >= 1


def _mis_sharded():
    """fc1 col-parallel makes its output tp-sharded on dim 1; fc2's rule
    claims dp on the same contraction dim -> forced reshard (MXL-P001)."""
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.sharding import ShardingRules
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    fc2 = mx.sym.FullyConnected(data=fc1, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(data=fc2, name="softmax")
    rules = ShardingRules([
        (r"fc1_weight", lambda s, m: P("tp", None)),
        (r"fc2_weight", lambda s, m: P(None, "dp")),
        (r".*_bias", lambda s, m: P(None)),
    ])
    return net, {"data": (8, 16), "softmax_label": (8,)}, rules


def test_p001_mis_sharded_graph_errors_with_bytes():
    net, shapes, rules = _mis_sharded()
    ctxs = []
    issues = analyze(net, shapes=shapes, mesh=_mesh22(),
                     sharding_rules=rules, _ctx_out=ctxs)
    hits = _only(issues, "MXL-P001")
    assert hits and all(i.severity == "error" for i in hits)
    assert hits[0].node == "fc2"
    assert "reshard" in hits[0].message
    resh = analysis.comm_report(ctxs[0])["by_kind"]["reshard"]
    assert resh["bytes"] > 0
    # without the conflicting rules the same graph is reshard-free
    clean = analyze(net, shapes=shapes, mesh=_mesh22())
    assert not _only(clean, "MXL-P001")


def test_p002_sharded_value_consumed_replicated():
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.sharding import ShardingRules
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    net = mx.sym.SoftmaxOutput(data=fc, name="softmax")
    # weight replicated but bias tp-sharded: the add needs it whole
    rules = ShardingRules([(r"fc1_weight", lambda s, m: P(None, None)),
                           (r"fc1_bias", lambda s, m: P("tp"))])
    issues = analyze(net, shapes={"data": (8, 16), "softmax_label": (8,)},
                     mesh=_mesh22(), sharding_rules=rules)
    hits = _only(issues, "MXL-P002")
    assert hits and hits[0].severity == "warning"
    assert "all-gather" in hits[0].message


def test_p003_non_divisible_param_degrades_to_replicated():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=3, name="fc1")
    net = mx.sym.SoftmaxOutput(data=fc, name="softmax")
    # (3, 5) has no dim divisible by tp=2: the default policy degrades
    issues = analyze(net, shapes={"data": (4, 5), "softmax_label": (4,)},
                     mesh=_mesh22())
    hits = _only(issues, "MXL-P003")
    assert any(i.node == "fc1_weight" for i in hits)
    assert all(i.severity == "info" for i in hits)
    assert "replicated" in hits[0].message


def test_p004_row_parallel_contraction_psum():
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.sharding import ShardingRules
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    fc2 = mx.sym.FullyConnected(data=fc1, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(data=fc2, name="softmax")
    rules = ShardingRules([(r"fc1_weight", lambda s, m: P("tp", None)),
                           (r"fc2_weight", lambda s, m: P(None, "tp")),
                           (r".*_bias", lambda s, m: P(None))])
    issues = analyze(net, shapes={"data": (8, 16), "softmax_label": (8,)},
                     mesh=_mesh22(), sharding_rules=rules)
    hits = _only(issues, "MXL-P004")
    assert any(i.node == "fc2" for i in hits)
    assert "psum" in hits[0].message
    assert not _only(issues, "MXL-P001")


def test_c003_one_sided_contraction():
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.sharding import ShardingRules
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    net = mx.sym.SoftmaxOutput(data=fc, name="softmax")
    # only the weight's contraction dim is sharded: XLA must gather
    rules = ShardingRules([(r"fc1_weight", lambda s, m: P(None, "tp")),
                           (r"fc1_bias", lambda s, m: P(None))])
    issues = analyze(net, shapes={"data": (8, 16), "softmax_label": (8,)},
                     mesh=_mesh22(), sharding_rules=rules)
    hits = _only(issues, "MXL-C003")
    assert hits and hits[0].node == "fc1"
    assert hits[0].severity == "warning"


def test_c001_kvstore_scope():
    from mxnet_tpu.parallel import LogicalMesh
    net = mx.models.get_mlp()
    # unknown type: error even without a mesh
    issues = analyze(net, shapes={"data": (8, 784)}, kvstore="bogus")
    hits = _only(issues, "MXL-C001")
    assert hits and hits[0].severity == "error"
    # device-scope kvstore under a pod-sized mesh: silently local
    big = LogicalMesh(dp=64, tp=4)
    issues = analyze(net, shapes={"data": (8, 784)}, kvstore="device",
                     mesh=big)
    hits = _only(issues, "MXL-C001")
    assert hits and hits[0].severity == "error"
    assert "dist_sync" in hits[0].message
    # dist_async: documented sync-semantics divergence, warning only
    issues = analyze(net, shapes={"data": (8, 784)}, kvstore="dist_async",
                     mesh=big)
    hits = _only(issues, "MXL-C001")
    assert hits and hits[0].severity == "warning"
    # a matching scope is silent
    issues = analyze(net, shapes={"data": (8, 784)}, kvstore="dist_sync",
                     mesh=big)
    assert not _only(issues, "MXL-C001")


def test_c002_collective_across_pipeline_stage():
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.sharding import ShardingRules
    data = mx.sym.Variable("data")
    with mx.AttrScope(ctx_group="stage0"):
        fc1 = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    with mx.AttrScope(ctx_group="stage1"):
        fc2 = mx.sym.FullyConnected(data=fc1, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(data=fc2, name="softmax")
    rules = ShardingRules([(r"fc1_weight", lambda s, m: P("tp", None)),
                           (r"fc2_weight", lambda s, m: P(None, "tp")),
                           (r".*_bias", lambda s, m: P(None))])
    shapes = {"data": (8, 16), "softmax_label": (8,)}
    issues = analyze(net, shapes=shapes, mesh=_mesh22(),
                     sharding_rules=rules)
    hits = _only(issues, "MXL-C002")
    assert hits and hits[0].node == "fc2"
    assert "pipeline" in hits[0].message
    # a single-stage graph never trips the audit
    single = _mis_sharded()[0]
    assert not _only(analyze(single, shapes=shapes, mesh=_mesh22()),
                     "MXL-C002")


def test_m001_peak_hbm_over_budget():
    net = mx.models.get_mlp()
    issues = net.validate(data=(8, 784), mesh=_mesh22(), hbm_bytes=1024)
    hits = _only(issues, "MXL-M001")
    assert hits and hits[0].severity == "error"
    assert "exceeds the budget" in hits[0].message
    assert "params" in hits[0].message       # breakdown included
    # generous budget: silent
    ok = net.validate(data=(8, 784), mesh=_mesh22(), hbm_bytes=1 << 40)
    assert not _only(ok, "MXL-M001")


def test_m002_big_replicated_param():
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.sharding import ShardingRules
    net = mx.models.get_mlp()
    repl = ShardingRules([(r".*", lambda s, m: P(*([None] * len(s))))])
    issues = net.validate(data=(8, 784), mesh=_mesh22(),
                          sharding_rules=repl, hbm_bytes=1_500_000)
    hits = _only(issues, "MXL-M002")
    assert any(i.node == "fc1_weight" for i in hits)
    assert all(i.severity == "warning" for i in hits)
    # sharded by the default policy: nothing to reclaim
    sharded = net.validate(data=(8, 784), mesh=_mesh22(),
                           hbm_bytes=1_500_000)
    assert not _only(sharded, "MXL-M002")


def test_memory_estimate_matches_analytic():
    """Training-mode peak on a graph small enough to price by hand:
    the estimate must land within the documented 2% tolerance (it is
    exact here — no mirroring, no fusion credit taken)."""
    from mxnet_tpu.analysis import AnalysisContext, peak_hbm_report
    from mxnet_tpu.parallel import LogicalMesh
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(data=fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(data=act, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(data=fc2, name="softmax")
    ctx = AnalysisContext(net, shapes={"data": (4, 8),
                                      "softmax_label": (4,)},
                          mesh=LogicalMesh(dp=1), grad_req="write")
    rep = peak_hbm_report(ctx)
    params = 4 * (16 * 8 + 16 + 2 * 16 + 2 + 4 * 8 + 4)  # + data + label
    grads = 4 * (16 * 8 + 16 + 2 * 16 + 2)
    acts = 4 * (4 * 16 + 4 * 16 + 4 * 2 + 4 * 2)
    assert rep["mode"] == "training" and rep["complete"]
    assert rep["params_bytes"] == params
    assert rep["grads_bytes"] == grads
    assert rep["activations_bytes"] == acts
    analytic = params + grads + acts
    assert abs(rep["peak_bytes"] - analytic) <= 0.02 * analytic
    # inference mode: no grads, liveness peak <= sum of activations
    infer = AnalysisContext(net, shapes={"data": (4, 8),
                                         "softmax_label": (4,)},
                            mesh=LogicalMesh(dp=1), grad_req="null")
    irep = peak_hbm_report(infer)
    assert irep["mode"] == "inference"
    assert irep["grads_bytes"] == 0
    assert irep["activations_bytes"] <= acts
    assert irep["peak_bytes"] < rep["peak_bytes"]


def test_spmd_rules_respect_lint_ignore():
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.sharding import ShardingRules
    rules = ShardingRules([(r"fc1_weight", lambda s, m: P(None, "tp")),
                           (r"fc1_bias", lambda s, m: P(None))])
    shapes = {"data": (8, 16), "softmax_label": (8,)}

    def build(attr):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1",
                                   attr=attr)
        return mx.sym.SoftmaxOutput(data=fc, name="softmax")

    loud = analyze(build(None), shapes=shapes, mesh=_mesh22(),
                   sharding_rules=rules)
    assert _only(loud, "MXL-C003")
    quiet = analyze(build({"__lint_ignore__": "MXL-C003"}), shapes=shapes,
                    mesh=_mesh22(), sharding_rules=rules)
    assert not _only(quiet, "MXL-C003")


def test_wildcard_select_isolates_spmd_family():
    net, shapes, rules = _mis_sharded()
    issues = analyze(net, shapes=shapes, mesh=_mesh22(),
                     sharding_rules=rules, select={"MXL-P*"})
    assert issues
    assert all(i.rule_id.startswith("MXL-P") for i in issues)
    skipped = analyze(net, shapes=shapes, mesh=_mesh22(),
                      sharding_rules=rules, skip={"MXL-P*"})
    assert not any(i.rule_id.startswith("MXL-P") for i in skipped)


# ----------------------------------------------------------------------
# MXL-K: static Mosaic tile-rule validation of Pallas kernel specs
# ----------------------------------------------------------------------
def test_k_min_tile_table():
    from mxnet_tpu.analysis.tiling import min_tile
    assert min_tile("float32") == (8, 128)
    assert min_tile("bfloat16") == (16, 128)
    assert min_tile("int8") == (32, 128)


def test_k_registered_flash_spec_is_clean():
    """The FIXED flash kernel (lse broadcast across _LSE_LANES) must
    lint clean — including its head_dim=64 lane dims, legal because the
    blocks cover the whole array dim (Mosaic pads the single tile)."""
    from mxnet_tpu.analysis.tiling import (KERNEL_SPECS,
                                           _ensure_builtin_specs,
                                           kernel_spec_issues)
    _ensure_builtin_specs()
    assert "parallel.ring_attention.flash_forward" in KERNEL_SPECS
    assert kernel_spec_issues() == []


def test_k_flash_lse_regression_fixture():
    """Regression fixture for the round-5 flash bug: the lse stats row
    was written through a 1-D ``(block_q,)`` block, which Mosaic rejects
    (no lane dim to tile).  MXL-K001 must report a spec with that
    layout; the registered (fixed) spec stays clean (test above)."""
    from mxnet_tpu.analysis.tiling import (register_kernel_spec,
                                           unregister_kernel_spec)
    from mxnet_tpu.parallel.ring_attention import flash_kernel_spec
    bad = flash_kernel_spec()
    for blk in bad["blocks"]:
        if blk["name"] == "lse":          # regress to the pre-fix layout
            blk["block"] = (None, 128)    # (block_q,) after squeezing
            blk["array"] = (8, 512)
    register_kernel_spec("test.flash_forward_prefix_bug", bad)
    try:
        issues = analyze(None, select={"MXL-K001"})
        hits = _only(issues, "MXL-K001")
        assert hits and all(i.severity == "error" for i in hits)
        assert any("lse" in i.message for i in hits), hits
    finally:
        unregister_kernel_spec("test.flash_forward_prefix_bug")
    assert not analyze(None, select={"MXL-K*"})   # registry clean again


def test_k_rules_silent_off_tpu_target():
    from mxnet_tpu.analysis.tiling import (register_kernel_spec,
                                           unregister_kernel_spec)
    register_kernel_spec("test.bad_rank1", {
        "name": "bad_rank1", "grid": (4,),
        "blocks": [{"role": "out", "name": "o", "block": (128,),
                    "array": (512,), "dtype": "float32"}]})
    try:
        assert analyze(None, select={"MXL-K*"}, target="cpu") == []
        assert _only(analyze(None, select={"MXL-K*"}), "MXL-K001")
    finally:
        unregister_kernel_spec("test.bad_rank1")


def test_k002_partial_lane_tiling_off_granule():
    from mxnet_tpu.analysis.tiling import block_findings
    rules = {r for r, _s, _m in block_findings((8, 64), (8, 256),
                                               "float32")}
    assert rules == {"MXL-K002"}


def test_k003_grid_padding_is_warning_only():
    from mxnet_tpu.analysis.tiling import block_findings
    out = block_findings((40, 128), (250, 128), "float32")
    assert [(r, s) for r, s, _m in out] == [("MXL-K003", "warning")]


def test_k004_block_exceeds_array():
    from mxnet_tpu.analysis.tiling import block_findings
    out = block_findings((16, 256), (8, 128), "float32")
    assert {r for r, _s, _m in out} == {"MXL-K004"}


def test_k_whole_array_blocks_legal_at_any_size():
    from mxnet_tpu.analysis.tiling import block_findings
    # flash kernel shape: full-array lane dim of 64 (< 128) is fine
    assert block_findings((None, 128, 64), (8, 512, 64),
                          "bfloat16") == []
    # and rtc-style whole-array 2-D blocks of any shape are fine
    assert block_findings(None, (3, 5), "float32") == []


# ----------------------------------------------------------------------
# MXL-R: static roofline / MFU ceiling
# ----------------------------------------------------------------------
def _big_fc(num_hidden=4096, k=4096, batch=1024):
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=num_hidden,
                               name="fc")
    return fc, {"data": (batch, k)}


def test_r_static_resnet50_b256_ceiling_matches_measured_table():
    """docs/mfu_gap.md, b256 row: XLA cost analysis says 6.28 TF/step
    and the v5e roofline caps MFU at 0.293.  The chip-free static model
    must reproduce both without lowering anything."""
    from mxnet_tpu.models.resnet import get_symbol
    rep = analysis.static_mfu_ceiling(
        get_symbol(num_classes=1000, num_layers=50),
        {"data": (256, 3, 224, 224)})
    assert rep["complete"], rep
    assert rep["bound"] == "bandwidth"
    assert abs(rep["flops_per_step"] / 1e12 - 6.28) < 0.1, rep
    assert abs(rep["mfu_ceiling"] - 0.293) <= 0.03, rep


def test_r_mxu_padding_waste():
    from mxnet_tpu.analysis.roofline import mxu_padding_waste
    assert mxu_padding_waste([(256, 256, 256)], "bfloat16") == 0.0
    # k and n each pad 64 -> 128: the MXU does 4x the useful work
    assert mxu_padding_waste([(256, 64, 64)], "bfloat16") == 0.75


def test_r002_padding_waste_flagged():
    sym, shapes = _big_fc(num_hidden=192, k=4096, batch=32768)
    issues = analyze(sym, shapes=shapes, select={"MXL-R002"})
    hits = _only(issues, "MXL-R002")
    assert hits and "pads" in hits[0].message


def test_r003_fp32_dot_only_fires_at_fp32():
    sym, shapes = _big_fc()
    at32 = analyze(sym, shapes=shapes, select={"MXL-R003"},
                   compute_dtype="float32")
    assert _only(at32, "MXL-R003")
    at16 = analyze(sym, shapes=shapes, select={"MXL-R003"})  # bf16 dflt
    assert not at16


def test_r004_long_bf16_reduction():
    sym, shapes = _big_fc(num_hidden=1024, k=8192, batch=2048)
    issues = analyze(sym, shapes=shapes, select={"MXL-R004"})
    hits = _only(issues, "MXL-R004")
    assert hits and "accumulates over 8192" in hits[0].message
    # the same contraction at f32 accumulation is safe
    assert not analyze(sym, shapes=shapes, select={"MXL-R004"},
                       compute_dtype="float32")


def test_r005_graph_summary_and_significance_floor():
    sym, shapes = _big_fc()
    issues = analyze(sym, shapes=shapes, select={"MXL-R005"})
    hits = _only(issues, "MXL-R005")
    assert hits and hits[0].severity == "info"
    assert "MFU ceiling" in hits[0].message
    # a toy graph stays below the 1e10-flops floor: no findings at all
    tiny, tiny_shapes = _big_fc(num_hidden=8, k=16, batch=4)
    assert analyze(tiny, shapes=tiny_shapes, select={"MXL-R*"}) == []


def test_r_rules_silent_off_tpu_target():
    sym, shapes = _big_fc()
    assert analyze(sym, shapes=shapes, select={"MXL-R*"},
                   target="cpu") == []
