"""Rtc custom kernels + check_consistency harness + nightly smoke."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.test_utils import assert_almost_equal, check_consistency

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
rng = np.random.RandomState(3)


def test_rtc_jax_function():
    from mxnet_tpu.rtc import Rtc
    a = mx.nd.array(rng.rand(4, 5).astype(np.float32))
    b = mx.nd.array(rng.rand(4, 5).astype(np.float32))
    rtc = Rtc(lambda x, y: x + 2.0 * y, n_outputs=1)
    (out,) = rtc.push([a, b])
    assert_almost_equal(out.asnumpy(), a.asnumpy() + 2 * b.asnumpy(),
                        rtol=1e-6, atol=1e-7)


def test_rtc_pallas_kernel():
    from mxnet_tpu.rtc import Rtc

    def kern(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] * y_ref[...] + 1.0

    a = mx.nd.array(rng.rand(8, 8).astype(np.float32))
    b = mx.nd.array(rng.rand(8, 8).astype(np.float32))
    rtc = Rtc(kern, n_outputs=1, pallas=True)
    (out,) = rtc.push([a, b])
    assert_almost_equal(out.asnumpy(), a.asnumpy() * b.asnumpy() + 1.0,
                        rtol=1e-5, atol=1e-6)


def test_check_consistency_catches_agreement():
    x = sym.Variable("x")
    net = sym.FullyConnected(x, num_hidden=4, name="fc")
    net = sym.Activation(net, act_type="tanh")
    check_consistency(net, {
        "x": rng.rand(3, 5).astype(np.float32),
        "fc_weight": rng.rand(4, 5).astype(np.float32) * 0.3,
        "fc_bias": np.zeros(4, np.float32)})


def test_nightly_dist_sync_kvstore_smoke():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/launch.py"), "-n", "2",
         "--launcher", "local", sys.executable,
         os.path.join(REPO, "tests/nightly/dist_sync_kvstore.py")],
        env=env, capture_output=True, text=True, timeout=360)
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-1000:])
    assert r.stdout.count("OK") == 2, r.stdout
