"""Smoke tests over the example/ tree (parity: tests/python/train)."""
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cwd, *argv, timeout=420):
    env = dict(os.environ)
    # PYTHONPATH is REPO only: an accelerator plugin registered via
    # sitecustomize (e.g. a tunneled TPU) would make the subprocess block
    # in jax.devices() when the accelerator is unreachable
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return subprocess.run([sys.executable] + list(argv), cwd=cwd, env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_train_mnist_synthetic():
    r = _run(os.path.join(REPO, "example/image-classification"),
             "train_mnist.py", "--network", "mlp", "--num-epochs", "1",
             "--batch-size", "64", "--synthetic", "--lr", "0.05")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Train-accuracy" in (r.stderr + r.stdout)


def test_rcnn_end2end_smoke():
    r = _run(os.path.join(REPO, "example/rcnn"), "train_end2end.py",
             "--steps", "1", "--image-size", "64", "--rois", "8")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "smoke OK" in (r.stderr + r.stdout)


def test_bucket_sentence_iter():
    sys.path.insert(0, os.path.join(REPO, "example/rnn"))
    try:
        from bucket_io import BucketSentenceIter, synthetic_corpus
    finally:
        sys.path.pop(0)
    sents = synthetic_corpus(num_sentences=100, vocab_size=30)
    it = BucketSentenceIter(sents, batch_size=8, buckets=[8, 16, 24, 32])
    seen = 0
    for batch in it:
        seen += 1
        assert batch.data[0].shape == (8, batch.bucket_key)
        lbl = batch.label[0].asnumpy()
        dat = batch.data[0].asnumpy()
        np.testing.assert_allclose(lbl[:, :-1], dat[:, 1:])
    assert seen > 0
