"""Smoke tests over the example/ tree (parity: tests/python/train)."""
import pytest
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cwd, *argv, timeout=420):
    env = dict(os.environ)
    # PYTHONPATH is REPO only: an accelerator plugin registered via
    # sitecustomize (e.g. a tunneled TPU) would make the subprocess block
    # in jax.devices() when the accelerator is unreachable
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return subprocess.run([sys.executable] + list(argv), cwd=cwd, env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_train_mnist_synthetic():
    r = _run(os.path.join(REPO, "example/image-classification"),
             "train_mnist.py", "--network", "mlp", "--num-epochs", "1",
             "--batch-size", "64", "--synthetic", "--lr", "0.05")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Train-accuracy" in (r.stderr + r.stdout)


def test_rcnn_end2end_smoke():
    r = _run(os.path.join(REPO, "example/rcnn"), "train_end2end.py",
             "--steps", "1", "--image-size", "64", "--rois", "8")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "smoke OK" in (r.stderr + r.stdout)


def test_bucket_sentence_iter():
    sys.path.insert(0, os.path.join(REPO, "example/rnn"))
    try:
        from bucket_io import BucketSentenceIter, synthetic_corpus
    finally:
        sys.path.pop(0)
    sents = synthetic_corpus(num_sentences=100, vocab_size=30)
    it = BucketSentenceIter(sents, batch_size=8, buckets=[8, 16, 24, 32])
    seen = 0
    for batch in it:
        seen += 1
        assert batch.data[0].shape == (8, batch.bucket_key)
        lbl = batch.label[0].asnumpy()
        dat = batch.data[0].asnumpy()
        np.testing.assert_allclose(lbl[:, :-1], dat[:, 1:])
    assert seen > 0


def test_gan_example_learns():
    """example/gan/dcgan.py: adversarial Modules (G trained through D's
    input grads) — the generator must spread toward the data mixture."""
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "example", "gan",
                        "dcgan.py")
    spec = importlib.util.spec_from_file_location("gan_example", path)
    gan = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gan)
    # train() pins all RNGs from `seed`, so this run is order-independent
    samples, _ = gan.train(epochs=300, seed=0, log=False)
    std = samples.std(axis=0)
    # data mixture spread is ~(2.0, 1.0); collapsed generators sit near 0
    assert std[0] > 0.5 and std[1] > 0.25, std


def test_opencv_plugin_roundtrip():
    import numpy as np
    from mxnet_tpu.plugin import opencv as cv
    from mxnet_tpu.image import imencode
    img = np.random.RandomState(0).randint(0, 255, (24, 32, 3), np.uint8)
    buf = imencode(img, img_fmt=".png")
    dec = cv.imdecode(buf)
    assert dec.shape == (24, 32, 3)
    np.testing.assert_array_equal(dec.asnumpy(), img)   # png is lossless
    small = cv.imresize(dec, 16, 12)
    assert small.shape == (12, 16, 3)
    padded = cv.copy_make_border(dec, 2, 2, 3, 3, fill_value=7)
    assert padded.shape == (28, 38, 3)
    assert (padded.asnumpy()[:2] == 7).all()


def _load_example(rel, name):
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "example", rel)
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_autoencoder_example_reconstructs():
    ae = _load_example("autoencoder/autoencoder.py", "ae_example")
    mse, power = ae.train(epochs=15)
    assert mse < 0.1 * power, (mse, power)


def test_adversary_fgsm_example():
    fg = _load_example("adversary/fgsm.py", "fgsm_example")
    clean, adv = fg.run(eps=0.3, epochs=6)
    assert clean > 0.9
    assert adv < clean - 0.2, (clean, adv)


def test_neural_style_example_descends():
    ns = _load_example("neural-style/neural_style.py", "ns_example")
    hist = ns.run(steps=40)
    assert hist[-1] < hist[0] * 0.5, (hist[0], hist[-1])


def test_stochastic_depth_example():
    """Module-level residual gating (reference example/stochastic-depth):
    SequentialModule of StochasticDepthModules learns, and eval runs with
    every block active."""
    r = _run(os.path.join(REPO, "example/stochastic-depth"),
             "sd_mnist.py", "--epochs", "6")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "final eval-acc" in r.stdout


def test_warpctc_example():
    """CTC training (reference example/warpctc): loss descends and greedy
    decode recovers the labels exactly."""
    r = _run(os.path.join(REPO, "example/warpctc"), "lstm_ocr.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK warpctc example" in r.stdout


def test_caffe_example():
    """CaffeOp/CaffeLoss net + converted prototxt net both train."""
    r = _run(os.path.join(REPO, "example/caffe"), "caffe_net.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK caffe example" in r.stdout


def test_torch_example():
    """torch module + criterion embedded in a native graph co-train."""
    r = _run(os.path.join(REPO, "example/torch"), "torch_net.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK torch example" in r.stdout


def test_svm_example():
    """SVMOutput hinge-loss head trains (reference example/svm_mnist)."""
    r = _run(os.path.join(REPO, "example/svm_mnist"), "svm_mnist.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK svm example" in r.stdout


def test_multitask_example():
    """Two loss heads via sym.Group + per-head metric."""
    r = _run(os.path.join(REPO, "example/multi-task"), "multitask_mlp.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK multi-task example" in r.stdout


def test_module_example():
    """Explicit bind/forward/backward/update loop + fit with checkpoint
    and resume (reference example/module)."""
    r = _run(os.path.join(REPO, "example/module"), "mnist_mlp.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK module example" in r.stdout


def test_bilstm_sort_example():
    """Bidirectional RNN learns to sort (reference example/bi-lstm-sort)."""
    r = _run(os.path.join(REPO, "example/bi-lstm-sort"), "sort_io.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK bi-lstm-sort example" in r.stdout


def test_sgld_example():
    """SGLD posterior sampling: mean near truth, nonzero spread."""
    r = _run(os.path.join(REPO, "example/bayesian-methods"), "sgld_demo.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK sgld example" in r.stdout


def test_text_cnn_example():
    """Kim-CNN text classifier (reference example/cnn_text_classification)."""
    r = _run(os.path.join(REPO, "example/cnn_text_classification"),
             "text_cnn.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK text-cnn example" in r.stdout


def test_fcn_example():
    """FCN segmentation: Deconvolution (bilinear-init) + Crop +
    multi_output softmax trained end-to-end (reference example/fcn-xs)."""
    r = _run(os.path.join(REPO, "example/fcn-xs"), "fcn_toy.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK fcn example" in r.stdout


def test_nce_example():
    """NCE: true class outscores sampled noise via per-candidate logistic
    losses over Embedding + batch_dot (reference example/nce-loss)."""
    r = _run(os.path.join(REPO, "example/nce-loss"), "nce_demo.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK nce example" in r.stdout


def test_dec_example():
    """Deep Embedded Clustering: AE pretrain + KL refinement with an
    external cotangent improves cluster accuracy (reference example/dec)."""
    r = _run(os.path.join(REPO, "example/dec"), "dec_toy.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK dec example" in r.stdout


def test_glregression_example():
    """Linear/logistic/MAE regression heads (reference example/GLRegression)."""
    r = _run(os.path.join(REPO, "example/GLRegression"), "glregression.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK glregression example" in r.stdout


def test_mlloss_example():
    """Contrastive metric loss via MakeLoss + siamese shared weights
    (reference example/MLLoss)."""
    r = _run(os.path.join(REPO, "example/MLLoss"), "metric_loss.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK mlloss example" in r.stdout


def test_python_howto_scripts():
    """The three how-to walkthroughs run clean (reference
    example/python-howto): custom DataIter, Monitor stats, multi-output
    symbols + get_internals."""
    for script, marker in [("data_iter.py", "OK data_iter howto"),
                           ("monitor_weights.py", "OK monitor howto"),
                           ("multiple_outputs.py",
                            "OK multiple_outputs howto")]:
        r = _run(os.path.join(REPO, "example/python-howto"), script)
        assert r.returncode == 0, (script, r.stderr[-1200:])
        assert marker in r.stdout, script


def test_rtc_example():
    """Runtime-compiled Pallas / traceable kernels on NDArrays."""
    r = _run(os.path.join(REPO, "example/rtc"), "pallas_kernel.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK rtc example" in r.stdout


def test_moe_example():
    """Expert-parallel MoE training over a dp x ep mesh."""
    r = _run(os.path.join(REPO, "example/moe"), "moe_ep.py")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "OK moe example" in r.stdout


def test_cpp_predict_example(tmp_path):
    """example/cpp: standalone C++ predictor over the MXPred ABI (role
    parity: reference example/cpp/image-classification)."""
    import shutil
    if shutil.which("g++") is None or shutil.which("make") is None:
        import pytest
        pytest.skip("no native toolchain")
    build = subprocess.run(["make", "-s", "capi"], cwd=REPO,
                           capture_output=True, text=True, timeout=300)
    if build.returncode != 0 and "Python.h" in (build.stderr or ""):
        import pytest
        pytest.skip("python headers unavailable")
    assert build.returncode == 0, build.stderr[-1500:]

    ex_dir = os.path.join(REPO, "example/cpp/image-classification")
    build = subprocess.run(["make", "-s"], cwd=ex_dir, capture_output=True,
                           text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-1500:]

    import json
    import mxnet_tpu as mx
    sym = mx.models.get_mlp(num_classes=10, hidden=(16,))
    mod = mx.mod.Module(sym, context=mx.context.cpu())
    mod.bind(data_shapes=[("data", (1, 32))],
             label_shapes=[("softmax_label", (1,))])
    mod.init_params(mx.init.Xavier())
    mod.save_checkpoint(str(tmp_path / "mlp"), 0)
    (tmp_path / "shapes.json").write_text(json.dumps({"data": [1, 32]}))

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [os.path.join(ex_dir, "image-classification-predict"),
         str(tmp_path / "mlp-symbol.json"),
         str(tmp_path / "mlp-0000.params"),
         str(tmp_path / "shapes.json")],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr[-1500:])
    assert "CPP PREDICT OK" in r.stdout
    assert "predicted class:" in r.stdout


def test_notebooks_execute(tmp_path):
    """example/notebooks: every code cell runs top-to-bottom (role
    parity: the reference's notebook tutorials, kept executable)."""
    import json
    import glob
    nbs = sorted(glob.glob(os.path.join(REPO, "example/notebooks/*.ipynb")))
    assert len(nbs) >= 2
    for path in nbs:
        nb = json.load(open(path))
        code = "\n\n".join(
            "".join(c["source"]) for c in nb["cells"]
            if c["cell_type"] == "code")
        script = tmp_path / (os.path.basename(path) + ".py")
        script.write_text(code)
        r = _run(str(tmp_path), str(script))
        assert r.returncode == 0, (path, r.stderr[-2000:])


def test_gru_bucketing_example():
    """example/rnn/gru_bucketing.py trains hermetically on the synthetic
    corpus (GRU cell parity with the reference's gru_bucketing)."""
    r = _run(os.path.join(REPO, "example/rnn"), "gru_bucketing.py",
             "--num-epochs", "1", "--batch-size", "8", "--num-hidden",
             "16", "--num-embed", "16", "--num-gru-layer", "1",
             "--buckets", "8,16")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Perplexity" in (r.stderr + r.stdout)


def test_lstm_inference_model_matches_unrolled():
    """rnn_model.py: stepwise stateful inference reproduces the
    unrolled network's per-position distributions exactly (states carry
    correctly through the one-step executor)."""
    import importlib.util
    import mxnet_tpu as mx
    from mxnet_tpu.models.lstm import lstm_unroll

    spec = importlib.util.spec_from_file_location(
        "rnn_model", os.path.join(REPO, "example/rnn/rnn_model.py"))
    rnn_model = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rnn_model)

    V, H, E, L, S = 30, 12, 8, 2, 5
    rng = np.random.RandomState(1)

    unrolled = lstm_unroll(L, S, V, num_hidden=H, num_embed=E, num_label=V)
    shapes = {"data": (1, S), "softmax_label": (1, S)}
    shapes.update({"l%d_init_c" % i: (1, H) for i in range(L)})
    shapes.update({"l%d_init_h" % i: (1, H) for i in range(L)})
    exe = unrolled.simple_bind(mx.context.cpu(), grad_req="null", **shapes)
    weights = {}
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label") or name.endswith(
                ("_init_c", "_init_h")):
            continue
        w = rng.uniform(-0.2, 0.2, arr.shape).astype(np.float32)
        arr[:] = w
        weights[name] = mx.nd.array(w)
    toks = rng.randint(0, V, size=S).astype(np.float32)
    exe.arg_dict["data"][:] = toks[None, :]
    want = exe.forward()[0].asnumpy()          # (S, V): row t = position t

    model = rnn_model.LSTMInferenceModel(L, V, H, E, V,
                                         arg_params=weights)
    for t in range(S):
        got = model.forward(np.array([toks[t]], np.float32),
                            new_seq=(t == 0))[0]
        assert np.allclose(got, want[t], atol=1e-5), t


@pytest.mark.slow
def test_memcost_mirroring_example():
    """Activation recompute demo (reference example/memcost): asserts the
    mirrored step recomputes in backward, shrinks the fwd->bwd residual
    set, and leaves numerics unchanged — a demo that CAN fail."""
    r = _run(os.path.join(REPO, "example/memcost"),
             "inception_memcost.py", "--batch-size", "2",
             "--image-size", "64")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "memcost demo OK" in r.stderr + r.stdout


def test_gpipe_example():
    """Pipeline-parallel LM demo: pipelined == sequential, trains."""
    r = _run(os.path.join(REPO, "example/pipeline"), "gpipe_lm.py",
             "--steps", "15")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "gpipe demo OK" in r.stderr + r.stdout


def test_long_context_example():
    """Long-context LM demo: sp ring attention == single-device
    numerics, per-layer remat shrinks residuals, trains."""
    r = _run(os.path.join(REPO, "example/long-context"),
             "train_lm_long.py", "--steps", "10")
    assert r.returncode == 0, r.stderr[-1500:]
    assert "long-context demo OK" in r.stderr + r.stdout
