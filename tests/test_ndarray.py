"""NDArray tests (modeled on reference tests/python/unittest/test_ndarray.py)."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd


def reldiff(a, b):
    diff = np.sum(np.abs(a - b))
    norm = np.sum(np.abs(a)) + 1e-8
    return diff / norm


def test_creation():
    a = nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert np.all(a.asnumpy() == 0)
    b = nd.ones((2, 3), dtype=np.float64)
    assert b.asnumpy().dtype == np.float64
    c = nd.full((2, 2), 3.5)
    assert np.all(c.asnumpy() == 3.5)
    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    e = nd.arange(0, 10, 2)
    assert np.allclose(e.asnumpy(), np.arange(0, 10, 2))


def test_elementwise():
    rng = np.random.RandomState(0)
    x = rng.rand(4, 5).astype(np.float32)
    y = rng.rand(4, 5).astype(np.float32)
    a, b = nd.array(x), nd.array(y)
    assert reldiff((a + b).asnumpy(), x + y) < 1e-6
    assert reldiff((a - b).asnumpy(), x - y) < 1e-6
    assert reldiff((a * b).asnumpy(), x * y) < 1e-6
    assert reldiff((a / b).asnumpy(), x / y) < 1e-5
    assert reldiff((a + 2).asnumpy(), x + 2) < 1e-6
    assert reldiff((2 - a).asnumpy(), 2 - x) < 1e-6
    assert reldiff((-a).asnumpy(), -x) < 1e-6
    assert reldiff((a ** 2).asnumpy(), x ** 2) < 1e-5


def test_inplace():
    x = np.ones((3, 3), dtype=np.float32)
    a = nd.array(x)
    a += 2
    assert np.all(a.asnumpy() == 3)
    a *= 2
    assert np.all(a.asnumpy() == 6)
    a -= 1
    assert np.all(a.asnumpy() == 5)
    a /= 5
    assert np.all(a.asnumpy() == 1)


def test_slice_view_aliasing():
    """Reference semantics: slices are views into the parent chunk
    (include/mxnet/ndarray.h:241-275)."""
    a = nd.zeros((4, 3))
    s = a[1:3]
    s[:] = 7
    out = a.asnumpy()
    assert np.all(out[1:3] == 7)
    assert np.all(out[0] == 0) and np.all(out[3] == 0)
    # writes to parent visible through the view
    a[:] = 1
    assert np.all(s.asnumpy() == 1)
    # at() view
    row = a.at(2)
    row[:] = 5
    assert np.all(a.asnumpy()[2] == 5)


def test_setitem():
    a = nd.zeros((4, 3))
    a[1] = 2.0
    assert np.all(a.asnumpy()[1] == 2)
    a[2:4] = nd.ones((2, 3))
    assert np.all(a.asnumpy()[2:4] == 1)


def test_reshape_view():
    a = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    b = a.reshape((4, 3))
    assert b.shape == (4, 3)
    b[:] = 0
    assert np.all(a.asnumpy() == 0)
    c = a.reshape((2, -1))
    assert c.shape == (2, 6)


def test_copyto():
    a = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = nd.zeros((2, 3))
    a.copyto(b)
    assert np.allclose(b.asnumpy(), a.asnumpy())
    c = a.copyto(mx.cpu(0))
    assert np.allclose(c.asnumpy(), a.asnumpy())
    d = a.copy()
    d += 1
    assert not np.allclose(d.asnumpy(), a.asnumpy())


def test_registered_functions():
    rng = np.random.RandomState(1)
    x = rng.rand(3, 4).astype(np.float32) + 0.5
    a = nd.array(x)
    assert reldiff(nd.sqrt(a).asnumpy(), np.sqrt(x)) < 1e-5
    assert reldiff(nd.exp(a).asnumpy(), np.exp(x)) < 1e-5
    assert reldiff(nd.log(a).asnumpy(), np.log(x)) < 1e-5
    assert reldiff(nd.square(a).asnumpy(), x ** 2) < 1e-5
    assert reldiff(nd.clip(a, 0.6, 0.9).asnumpy(), np.clip(x, 0.6, 0.9)) < 1e-6
    assert reldiff(nd.sum(a).asnumpy(), x.sum()) < 1e-5
    assert reldiff(nd.norm(a).asnumpy(), np.sqrt((x ** 2).sum())) < 1e-5
    assert reldiff(nd.transpose(a).asnumpy(), x.T) < 1e-6


def test_dot():
    rng = np.random.RandomState(2)
    x = rng.rand(3, 4).astype(np.float32)
    y = rng.rand(4, 5).astype(np.float32)
    assert reldiff(nd.dot(nd.array(x), nd.array(y)).asnumpy(), x.dot(y)) < 1e-4
    bx = rng.rand(2, 3, 4).astype(np.float32)
    by = rng.rand(2, 4, 5).astype(np.float32)
    assert reldiff(nd.batch_dot(nd.array(bx), nd.array(by)).asnumpy(),
                   np.matmul(bx, by)) < 1e-4


def test_onehot_and_choose():
    idx = nd.array(np.array([1, 0, 2], dtype=np.float32))
    out = nd.zeros((3, 3))
    nd.onehot_encode(idx, out)
    expect = np.eye(3, dtype=np.float32)[[1, 0, 2]]
    assert np.allclose(out.asnumpy(), expect)
    mat = nd.array(np.arange(9, dtype=np.float32).reshape(3, 3))
    picked = nd.choose_element_0index(mat, idx)
    assert np.allclose(picked.asnumpy(), [1, 3, 8])


def test_save_load():
    rng = np.random.RandomState(3)
    arrays = [nd.array(rng.rand(3, 4).astype(np.float32)),
              nd.array(rng.rand(5,).astype(np.float32))]
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "test.params")
        nd.save(fname, arrays)
        loaded = nd.load(fname)
        assert len(loaded) == 2
        for a, b in zip(arrays, loaded):
            assert np.allclose(a.asnumpy(), b.asnumpy())
        named = {"w": arrays[0], "b": arrays[1]}
        nd.save(fname, named)
        loaded = nd.load(fname)
        assert set(loaded) == {"w", "b"}
        assert np.allclose(loaded["w"].asnumpy(), arrays[0].asnumpy())


def test_scalar_and_compare():
    a = nd.array(np.array([[2.0]], dtype=np.float32))
    assert a.asscalar() == 2.0
    x = nd.array(np.array([1.0, 2.0, 3.0], dtype=np.float32))
    y = nd.array(np.array([2.0, 2.0, 2.0], dtype=np.float32))
    assert np.allclose((x > y).asnumpy(), [0, 0, 1])
    assert np.allclose((x == y).asnumpy(), [0, 1, 0])


def test_broadcast():
    a = nd.array(np.arange(3, dtype=np.float32).reshape(1, 3))
    b = nd.broadcast_to(a, (4, 3))
    assert b.shape == (4, 3)
    assert np.all(b.asnumpy() == np.broadcast_to(np.arange(3), (4, 3)))
    c = nd.broadcast_axis(a, axis=0, size=5)
    assert c.shape == (5, 3)


def test_context():
    a = nd.zeros((2, 2), ctx=mx.cpu(0))
    assert a.context == mx.cpu(0)
    b = a.as_in_context(mx.cpu(1))
    assert b.context == mx.cpu(1)
    assert np.allclose(a.asnumpy(), b.asnumpy())
    # gpu() aliases to accelerator; on cpu-only test env falls back to cpu
    c = nd.zeros((2, 2), ctx=mx.gpu(0))
    assert c.shape == (2, 2)


def test_waitall():
    a = nd.ones((10, 10))
    b = a * 2
    nd.waitall()
    assert np.all(b.asnumpy() == 2)
