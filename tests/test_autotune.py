"""Chip-free autotuner (mxnet_tpu/analysis/autotune.py +
tools/autotune.py): the v5e ResNet-50 ceiling table is a pinned
regression fixture, infeasible configs are pruned BEFORE pricing,
sweeps memoize per-graph analysis, manifests are deterministic, and
the replay loop fits a measured-vs-predicted correction."""
import json
import os
import time

import pytest

from mxnet_tpu.analysis import autotune as at
from mxnet_tpu.analysis import static_ceiling_summary, static_mfu_ceiling

from test_examples import _run, REPO as ROOT

AUTOTUNE = os.path.join(ROOT, "tools", "autotune.py")


def _resnet50():
    from mxnet_tpu.models import resnet
    return resnet.get_symbol(num_classes=1000, num_layers=50)


# ----------------------------------------------------------------------
# the pinned v5e table (docs/mfu_gap.md / AOT_r05.json): the calibrated
# MXL-R model must keep reproducing the compiled AOT ceilings
# ----------------------------------------------------------------------
V5E_TABLE = [
    # batch, compiled mfu ceiling, compiled TF/step (AOT_r05.json)
    (64, 0.193, 1.572),
    (256, 0.293, 6.282),
    (512, 0.331, 12.564),
]


@pytest.mark.parametrize("batch,ceiling,tflops", V5E_TABLE)
def test_v5e_resnet50_ceiling_table_fixture(batch, ceiling, tflops):
    rep = static_mfu_ceiling(_resnet50(),
                             {"data": (batch, 3, 224, 224)},
                             device_kind="v5e",
                             compute_dtype="bfloat16", grad_req="write")
    assert abs(rep["mfu_ceiling"] - ceiling) <= 0.01, \
        "b%d: %.4f vs compiled %.3f" % (batch, rep["mfu_ceiling"],
                                        ceiling)
    assert abs(rep["flops_per_step"] / 1e12 - tflops) <= 0.05
    # the calibrated traffic model stays transparent: raw per-op bytes
    # and the calibration constants ride along in the report
    assert rep["calibration"] is not None
    assert set(rep["calibration"]) == {"fusion_factor",
                                       "staging_bytes_per_param"}
    assert rep["op_hbm_bytes_per_step"] > 0
    assert rep["param_count"] > 25e6


def test_ceiling_table_is_batch_monotone():
    reps = [static_mfu_ceiling(_resnet50(),
                               {"data": (b, 3, 224, 224)},
                               device_kind="v5e",
                               compute_dtype="bfloat16",
                               grad_req="write")["mfu_ceiling"]
            for b, _c, _t in V5E_TABLE]
    assert reps[0] < reps[1] < reps[2]


def test_static_ceiling_summary_shared_path():
    out = static_ceiling_summary(_resnet50(),
                                 {"data": (256, 3, 224, 224)},
                                 device_kind="v5e",
                                 compute_dtype="bfloat16",
                                 grad_req="write")
    assert abs(out["static_mfu_ceiling"] - 0.293) <= 0.01
    assert out["static_bound"] == "bandwidth"
    assert out["static_tflops_per_step"] > 6
    # never raises: a broken graph comes back as an error key
    bad = static_ceiling_summary(42, {})
    assert "static_mfu_ceiling_error" in bad


# ----------------------------------------------------------------------
# search: ranking, pruning-before-pricing, memoization
# ----------------------------------------------------------------------
def test_search_ranks_b512_first_above_b256():
    res = at.search("resnet50", device_kind="v5e")
    assert res["entries"], "search produced no feasible configs"
    ranked_batches = [e["config"]["batch"] for e in res["entries"]]
    assert ranked_batches[0] == 512
    assert ranked_batches.index(512) < ranked_batches.index(256)
    top = res["entries"][0]["predicted"]["mfu_ceiling"]
    assert abs(top - 0.331) <= 0.01
    # equal-ceiling tie (b512 remat vs plain) breaks on HBM headroom
    b512 = [e for e in res["entries"] if e["config"]["batch"] == 512]
    assert len(b512) == 2
    assert b512[0]["predicted"]["hbm_headroom_gb"] >= \
        b512[1]["predicted"]["hbm_headroom_gb"]


def test_hbm_infeasible_pruned_without_pricing():
    memo = at.GraphMemo(device_kind="v5e")
    space = at.parse_space("batch=1024;remat=none")
    res = at.search("resnet50", device_kind="v5e", space=space,
                    memo=memo)
    assert res["counts"]["priced"] == 0
    assert res["counts"]["pruned"] == 1
    assert res["pruned"][0]["reason"].startswith("mxl-m:")
    # rejected BEFORE pricing: the memoized context ran the memory
    # report but the roofline was never computed for it
    (_key, ctx), = memo._ctxs.items()
    assert "memory" in ctx.cache
    assert "roofline_report" not in ctx.cache


def test_mxlk_illegal_tile_pruned_without_any_analysis():
    memo = at.GraphMemo(device_kind="v5e")
    space = at.parse_space("batch=64;remat=none;dtype=int8;"
                           "serve_block=8")
    res = at.search("resnet50", device_kind="v5e", space=space,
                    memo=memo)
    assert res["counts"]["priced"] == 0
    assert res["pruned"][0]["reason"].startswith("mxl-k:")
    # the tile gate is graph-free: no symbol was even built
    assert memo.stats == {"symbols_built": 0, "analyses": 0,
                          "memo_hits": 0}


def test_legal_int8_serve_block_prices_in_inference_mode():
    space = at.parse_space("batch=64;remat=none;dtype=int8;"
                           "serve_block=32")
    res = at.search("resnet50", device_kind="v5e", space=space)
    assert len(res["entries"]) == 1
    pred = res["entries"][0]["predicted"]
    assert pred["mode"] == "inference"
    assert pred["mfu_ceiling"] > 0


def test_sweep_memoizes_each_distinct_graph_once():
    space = at.parse_space(
        "batch=64,128,256,512;remat=none,blocks;"
        "bucket_mb=5,25,50;prefetch=1,2,4;"
        "serve_buckets=none,1-8-32,1-16-64")
    configs = at.space_configs(space)
    assert len(configs) >= 200
    t0 = time.time()
    res = at.search("resnet50", device_kind="v5e", space=space)
    elapsed = time.time() - t0
    c = res["counts"]
    assert c["total"] == len(configs)
    # 4 batches x 2 remat policies = 8 distinct graphs, 2 symbols;
    # every other axis is graph-free and memo-hits
    assert c["analyses"] == 8
    assert c["symbols_built"] == 2
    assert c["memo_hits"] > c["analyses"]
    assert elapsed < 60, "sweep took %.1fs" % elapsed


def test_transformer_dp2tp2_search_prices_with_ici_bytes():
    space = at.parse_space("batch=8,16;remat=none;sharding=dp2tp2")
    res = at.search("transformer", device_kind="v5e", space=space)
    assert res["entries"], [p["reason"] for p in res["pruned"]]
    for e in res["entries"]:
        assert e["predicted"]["ici_bytes"], \
            "sharded config should move ICI bytes"


# ----------------------------------------------------------------------
# grammar
# ----------------------------------------------------------------------
def test_parse_sharding_grammar():
    base = {"dp": 1, "tp": 1, "pp": 1, "ep": 1, "fsdp": False}
    assert at.parse_sharding("dp1") == base
    assert at.parse_sharding("dp2tp2") == dict(base, dp=2, tp=2)
    assert at.parse_sharding("fsdp8") == dict(base, dp=8, fsdp=True)
    assert at.parse_sharding("tp4") == dict(base, tp=4)
    # pipeline + expert axes ride the same grammar (MXL-E configs)
    assert at.parse_sharding("dp2pp4") == dict(base, dp=2, pp=4)
    assert at.parse_sharding("ep4") == dict(base, ep=4)
    assert at.parse_sharding("dp2pp2ep2") == dict(base, dp=2, pp=2,
                                                  ep=2)
    # the canonical parser lives with the sharding rules; the tuner
    # re-exports the same function
    from mxnet_tpu.parallel import parse_sharding as canonical
    assert canonical is at.parse_sharding
    with pytest.raises(ValueError):
        at.parse_sharding("zp3")
    with pytest.raises(ValueError):
        at.parse_sharding("pp4dp2")  # grammar order is fixed


def test_parse_space_rejects_unknown_axis():
    with pytest.raises(ValueError):
        at.parse_space("bogus=1")
    sp = at.parse_space("batch=32;serve_block=none,16")
    assert sp["batch"] == (32,)
    assert sp["serve_block"] == (None, 16)
    # unnamed axes keep their defaults
    assert sp["remat"] == at.default_space()["remat"]


# ----------------------------------------------------------------------
# manifest determinism + correction re-ranking
# ----------------------------------------------------------------------
def test_manifest_is_deterministic():
    outs = []
    for _ in range(2):
        res = at.search("resnet50", device_kind="v5e")
        man = at.build_manifest(res, top_k=4,
                                provenance={"tool": "test"})
        outs.append(at.canonical_json(man))
    assert outs[0] == outs[1]
    man = json.loads(outs[0])
    assert man["manifest_hash"]
    assert len(man["configs"]) == 4
    for entry in man["configs"]:
        assert entry["bench_cmd"].endswith("python bench.py")
        assert ("BENCH_AUTOTUNE_CONFIG_ID=%s" % entry["config_id"]) \
            in entry["bench_cmd"]


def test_config_id_is_content_hash():
    cfg = dict(zip(at.AXES, (256, "none", "dp1", "bfloat16", 25, 2,
                             None, None, None, 8, None, None)))
    assert len(cfg) == len(at.AXES)
    cfg["model"] = "resnet50"
    a = at.config_id(cfg)
    assert a == at.config_id(dict(cfg))
    cfg2 = dict(cfg, batch=512)
    assert a != at.config_id(cfg2)
    # the new pipeline/MoE axes are part of the hashed identity
    assert a != at.config_id(dict(cfg, stages=4))
    assert a != at.config_id(dict(cfg, experts=8))
    assert a.startswith("at-")


def test_manifest_deterministic_over_pipeline_and_moe_axes():
    # the new pp/MoE axes must not break same-inputs -> byte-identical
    # manifests: two independent sweeps (fresh memo each) over stages,
    # microbatches, experts and capacity_factor
    outs = []
    for _ in range(2):
        space = at.parse_space(
            "batch=8;remat=none;sharding=dp2pp2,ep4;microbatches=4,8;"
            "experts=none,8;capacity_factor=none,1.25")
        res = at.search("transformer_moe", device_kind="v5e",
                        space=space)
        man = at.build_manifest(res, top_k=16,
                                provenance={"tool": "test"})
        outs.append(at.canonical_json(man))
    assert outs[0] == outs[1]
    man = json.loads(outs[0])
    # a pipelined entry carries its simulated bubble and the pipeline
    # bench envs; an MoE entry carries the expert envs
    piped = [c for c in man["configs"]
             if c["config"]["sharding"] == "dp2pp2"]
    assert piped, [c["config"] for c in man["configs"]]
    for c in piped:
        assert c["predicted"]["bubble_fraction"] is not None
        assert "BENCH_PP_STAGES=2" in c["bench_cmd"]
        assert ("BENCH_MICROBATCHES=%d"
                % c["config"]["microbatches"]) in c["bench_cmd"]
    moe = [c for c in man["configs"] if c["config"]["experts"]]
    for c in moe:
        assert "BENCH_MOE_EXPERTS=8" in c["bench_cmd"]


def test_mxl_e_infeasible_pruned_before_pricing():
    memo = at.GraphMemo(device_kind="v5e")
    # 6 experts over an ep=4 mesh axis: MXL-E006 (indivisible experts)
    # must reject the config before the roofline prices it
    space = at.parse_space("batch=8;remat=none;sharding=ep4;"
                           "experts=6;capacity_factor=1.25")
    res = at.search("transformer_moe", device_kind="v5e", space=space,
                    memo=memo)
    assert res["counts"]["priced"] == 0
    assert res["counts"]["pruned"] == 1
    assert res["pruned"][0]["reason"].startswith("mxl-e:")
    assert "MXL-E006" not in res["pruned"][0]["reason"]  # message only
    # pruned BEFORE pricing: the memoized context ran the schedule
    # rules but the roofline report was never computed
    (_key, ctx), = memo._ctxs.items()
    assert "autotune_mxl_e" in ctx.cache
    assert "roofline_report" not in ctx.cache
    # the schedule gate memoizes: re-pruning the same config re-uses
    # the cached rule run (analyses stays 1)
    assert at.prune_config("transformer_moe", res["pruned"][0]["config"],
                           memo, res["hbm_budget_bytes"]) \
        .startswith("mxl-e:")
    assert memo.stats["analyses"] == 1


def test_pipeline_config_priced_with_bubble_scaled_ceiling():
    # a feasible pp=2 transformer prices with a 1F1B bubble fraction
    # and a ceiling strictly below the unpipelined one
    memo = at.GraphMemo(device_kind="v5e")
    space = at.parse_space("batch=8;remat=none;sharding=dp2,dp2pp2")
    res = at.search("transformer", device_kind="v5e", space=space,
                    memo=memo)
    by_shard = {e["config"]["sharding"]: e["predicted"]
                for e in res["entries"]}
    assert set(by_shard) == {"dp2", "dp2pp2"}, \
        [p["reason"] for p in res["pruned"]]
    assert by_shard["dp2"]["bubble_fraction"] is None
    bubble = by_shard["dp2pp2"]["bubble_fraction"]
    assert 0.0 < bubble < 1.0
    assert by_shard["dp2pp2"]["mfu_ceiling"] < \
        by_shard["dp2"]["mfu_ceiling"]


def test_fit_correction_and_rerank():
    # one point -> ratio; several -> least squares
    ratio = at.fit_correction([(0.30, 0.24)])
    assert ratio["kind"] == "ratio"
    assert abs(at.apply_correction(ratio, 0.30) - 0.24) < 1e-9
    lin = at.fit_correction([(0.30, 0.25), (0.20, 0.10), (0.10, 0.05)])
    assert lin["kind"] == "linear"
    assert lin["a"] > 0
    # measured numbers that invert the predicted order re-rank it
    entries = [
        {"config_id": "at-a", "rank": 1,
         "predicted": {"mfu_ceiling": 0.30}},
        {"config_id": "at-b", "rank": 2,
         "predicted": {"mfu_ceiling": 0.25}},
    ]
    inverting = at.fit_correction([(0.30, 0.10), (0.25, 0.20)])
    order = [e["config_id"] for e in at.rerank(entries, inverting)]
    assert order == ["at-b", "at-a"]
    # no correction: stable original order
    order = [e["config_id"] for e in at.rerank(entries, None)]
    assert order == ["at-a", "at-b"]


# ----------------------------------------------------------------------
# CLI: manifest emit + fixture replay with the slo gate
# ----------------------------------------------------------------------
@pytest.mark.slow
def test_cli_search_and_fixture_replay(tmp_path):
    man_path = tmp_path / "manifest.json"
    proc = _run(ROOT, AUTOTUNE, "--model", "resnet50",
                "--device-kind", "v5e", "--top-k", "3",
                "-o", str(man_path))
    assert proc.returncode == 0, proc.stderr
    man = json.loads(man_path.read_text())
    assert man["configs"][0]["config"]["batch"] == 512
    assert man["provenance"]["tool"] == "tools/autotune.py"

    # dry-run prints one command sheet line per config
    proc = _run(ROOT, AUTOTUNE, "--replay", str(man_path))
    assert proc.returncode == 0, proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if "bench.py" in ln]
    assert len(lines) == 3
    assert all("BENCH_AUTOTUNE_MANIFEST_HASH=%s" % man["manifest_hash"]
               in ln for ln in lines)

    # fixture replay: measured numbers feed the slo gate + correction
    runs = [{"metric": "resnet50_train_images_per_sec",
             "value": 2.0, "unit": "images/sec",
             "mfu": round(0.8 * c["predicted"]["mfu_ceiling"], 4),
             "autotune_config_id": c["config_id"]}
            for c in man["configs"]]
    runs_path = tmp_path / "runs.json"
    runs_path.write_text(json.dumps(runs))
    report_path = tmp_path / "report.json"
    proc = _run(ROOT, AUTOTUNE, "--replay", str(man_path),
                "--results", str(runs_path),
                "--baseline", os.path.join(ROOT, "BENCH_r05.json"),
                "--report", str(report_path), "--fail-on-regression")
    assert proc.returncode == 0, proc.stderr + proc.stdout
    report = json.loads(report_path.read_text())
    assert report["manifest_hash"] == man["manifest_hash"]
    assert report["regressions"] == 0
    assert report["correction"]["n"] == 3
    assert all(r["status"] == "ok" for r in report["runs"])
    assert all("mfu_gap" in r for r in report["runs"])

    # a regressed measured number trips the gate (rc 1)
    runs[0]["value"] = 0.2
    runs_path.write_text(json.dumps(runs))
    proc = _run(ROOT, AUTOTUNE, "--replay", str(man_path),
                "--results", str(runs_path),
                "--baseline", os.path.join(ROOT, "BENCH_r05.json"),
                "--fail-on-regression")
    assert proc.returncode == 1, proc.stderr + proc.stdout


def test_bench_stamps_autotune_ids(monkeypatch):
    import bench
    monkeypatch.setenv("BENCH_AUTOTUNE_CONFIG_ID", "at-test123456")
    monkeypatch.setenv("BENCH_AUTOTUNE_MANIFEST_HASH", "deadbeef")
    payload = {"metric": "x", "value": 1.0}
    bench._stamp_autotune(payload)
    assert payload["autotune_config_id"] == "at-test123456"
    assert payload["autotune_manifest_hash"] == "deadbeef"
    monkeypatch.delenv("BENCH_AUTOTUNE_CONFIG_ID")
    monkeypatch.delenv("BENCH_AUTOTUNE_MANIFEST_HASH")
    clean = {"metric": "x"}
    bench._stamp_autotune(clean)
    assert "autotune_config_id" not in clean


def test_parse_log_mfu_gap_and_config_id_columns(tmp_path):
    ev = tmp_path / "events-rank0.jsonl"
    ev.write_text(
        json.dumps({"kind": "step", "epoch": 1, "dur_ms": 100.0,
                    "samples_per_sec": 640.0}) + "\n" +
        json.dumps({"kind": "summary", "source": "bench", "mfu": 0.28,
                    "static_mfu_ceiling": 0.3297,
                    "autotune_config_id": "at-0888f23e57"}) + "\n")
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "parse_log", os.path.join(ROOT, "tools", "parse_log.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rows = mod.parse_telemetry(str(ev))
    row = rows[1]
    assert abs(row["mfu-gap"] - 0.0497) < 1e-6
    assert row["autotune-config-id"] == "at-0888f23e57"
