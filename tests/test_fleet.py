"""Fleet serving (docs/serving.md "Fleet"): FileKV, heartbeat liveness
scan, router admission math, failover, live weight hot-swap, and the
fleet telemetry rollup.

All CPU-only and in-process: router tests run against duck-typed fake
replica clients (no subprocesses, no HTTP), the swap tests drive
ModelServer.swap_params on a toy MLP over the virtual CPU mesh, and
the liveness tests exercise the SAME scan_dead_ranks rule
KVStore.dead_nodes uses — pointed at a FileKV instead of the jax
coordination client.  The multi-process kill-a-replica drill lives in
tests/nightly/serve_load_fleet.py (CI TASK=serving).
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.executor import program_registry_stats
from mxnet_tpu.kvstore import scan_dead_ranks
from mxnet_tpu.resilience.netkv import CoordKV, KVUnreachable
from mxnet_tpu.serving import ModelServer, ServerBusy
from mxnet_tpu.serving.fleet import (_SWAP_PTR_KEY, FileKV, FleetRouter,
                                     NotLeader, ReplicaDead,
                                     decode_arrays, encode_arrays,
                                     fleet_ledger_path, fleet_max_queue,
                                     fleet_routers, fleet_tenants)
from mxnet_tpu.serving.telemetry import fleet_report


# ---------------------------------------------------------------------------
# FileKV: the file-backed coordination-client stand-in
# ---------------------------------------------------------------------------

def test_filekv_roundtrip_and_prefix_scan(tmp_path):
    kv = FileKV(tmp_path / "kv")
    kv.key_value_set("mxtpu_hb/0", "1.5")
    kv.key_value_set("mxtpu_hb/1", "2.5")
    kv.key_value_set("other/0", "9")
    got = dict(kv.key_value_dir_get("mxtpu_hb/"))
    assert got == {"mxtpu_hb/0": "1.5", "mxtpu_hb/1": "2.5"}
    # last write wins (the heartbeat stamp pattern)
    kv.key_value_set("mxtpu_hb/0", "3.5")
    assert dict(kv.key_value_dir_get("mxtpu_hb/"))["mxtpu_hb/0"] == "3.5"
    kv.key_value_delete("mxtpu_hb/0")
    assert "mxtpu_hb/0" not in dict(kv.key_value_dir_get("mxtpu_hb/"))


def test_filekv_blocking_get(tmp_path):
    kv = FileKV(tmp_path / "kv")
    with pytest.raises(TimeoutError):
        kv.blocking_key_value_get("missing", 60)
    kv.key_value_set("k", "v")
    assert kv.blocking_key_value_get("k", 60) == "v"


def test_filekv_keys_with_slashes_are_flat_files(tmp_path):
    # heartbeat keys contain "/": they must quote into flat filenames,
    # never create subdirectories the prefix scan would miss
    kv = FileKV(tmp_path / "kv")
    kv.key_value_set("a/b/c", "x")
    assert dict(kv.key_value_dir_get("a/"))["a/b/c"] == "x"
    assert not any(p.is_dir() for p in (tmp_path / "kv").iterdir())


# ---------------------------------------------------------------------------
# liveness: the dead_nodes scan rule over a FileKV
# ---------------------------------------------------------------------------

@pytest.fixture(params=["file", "tcp"])
def any_kv(request, tmp_path):
    """A coordination KV over both backends — the router/heartbeat/
    ledger machinery must behave identically on file:// and tcp://."""
    if request.param == "file":
        yield FileKV(tmp_path / "kv")
        return
    from mxnet_tpu.resilience.netkv import TcpKV, TcpKVServer
    srv = TcpKVServer(port=0).start()
    try:
        yield TcpKV(srv.host, srv.port, timeout_s=2.0)
    finally:
        srv.stop()


def test_scan_dead_ranks_fresh_vs_stale(tmp_path, monkeypatch):
    from mxnet_tpu import kvstore as kvmod
    kv = FileKV(tmp_path / "kv")
    monkeypatch.setattr(kvmod, "_now", lambda: 100.0)
    kv.key_value_set("mxtpu_hb/0", "99.0")     # fresh
    kv.key_value_set("mxtpu_hb/1", "80.0")     # stale
    dead = scan_dead_ranks(kv, [0, 1, 2], created=95.0, timeout=10.0)
    # 1 is stale; 2 never stamped but the fleet is young (grace)
    assert dead == [1]
    dead = scan_dead_ranks(kv, [0, 1, 2], created=50.0, timeout=10.0)
    assert dead == [1, 2]                      # grace expired for 2


def test_router_health_loop_uses_shared_scan(tmp_path, any_kv):
    """A replica whose heartbeat goes stale is marked dead by the
    router's health loop — the same machinery dead_nodes uses, over
    file:// and tcp:// alike."""
    kv = any_kv
    now = time.time()
    kv.key_value_set("mxtpu_hb/0", str(now + 1000))  # forever fresh
    kv.key_value_set("mxtpu_hb/1", str(now - 1000))  # long stale
    router = FleetRouter([_OkClient(), _OkClient()], kv=kv,
                         max_queue=8, hb_timeout_s=5.0,
                         directory=str(tmp_path), respawn=False)
    try:
        from mxnet_tpu.resilience import elastic
        led = None
        deadline = time.time() + 10
        while time.time() < deadline:
            st = router.stats()
            led = elastic.read_ledger(
                path=fleet_ledger_path(str(tmp_path)))
            # state flips before the fsync'd ledger write lands:
            # wait for both
            if st["replicas"]["1"]["state"] == "dead" and led:
                break
            time.sleep(0.1)
        st = router.stats()
        assert st["replicas"]["0"]["state"] == "ready"
        assert st["replicas"]["1"]["state"] == "dead"
        assert led["reason"] == "replica_death"
        assert led["members"] == [0]
        assert led["generation"] == 1
    finally:
        router.close(drain=False)


# ---------------------------------------------------------------------------
# fault seams
# ---------------------------------------------------------------------------

def test_replica_death_seam_returned_not_raised(monkeypatch):
    from mxnet_tpu.resilience import faultinject
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "kind=replica_death:rank=2")
    faultinject.reset()
    assert faultinject.maybe_fault("replica_death", rank=1) is None
    spec = faultinject.maybe_fault("replica_death", rank=2)
    assert spec is not None and spec.kind == "replica_death"
    faultinject.reset()


def test_swap_crash_seam_raises(monkeypatch):
    from mxnet_tpu.resilience import faultinject
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "kind=swap_crash")
    faultinject.reset()
    with pytest.raises(faultinject.InjectedFault):
        faultinject.maybe_fault("swap_install")
    faultinject.reset()


# ---------------------------------------------------------------------------
# router admission + dispatch over fake clients
# ---------------------------------------------------------------------------

class _OkClient(object):
    """Duck-typed replica client: records calls, returns instantly."""

    def __init__(self):
        self.calls = []
        self.trace_ids = []

    def predict(self, model, inputs, n=None, trace_id=None):
        self.calls.append(model)
        self.trace_ids.append(trace_id)
        return [np.zeros((int(n or 1), 2), dtype="float32")]

    def stats(self):
        return {"requests": len(self.calls)}

    def swap(self, params, version=None, timeout=None):
        return {"version": version or "v1", "lowerings": 0,
                "models": ["m"], "swap_ms": 0.1}

    def drain(self):
        return True

    def healthz(self):
        return True


class _BlockingClient(_OkClient):
    """Holds every predict until released — keeps work in flight so
    admission tests can fill the aggregate window deterministically."""

    def __init__(self):
        super(_BlockingClient, self).__init__()
        self.release = threading.Event()

    def predict(self, model, inputs, n=None, trace_id=None):
        assert self.release.wait(timeout=30)
        return super(_BlockingClient, self).predict(
            model, inputs, n=n, trace_id=trace_id)


class _DeadClient(_OkClient):
    def predict(self, model, inputs, n=None, trace_id=None):
        raise ConnectionError("replica gone")


def test_fleet_max_queue_defaults_to_replicas_times_serve(monkeypatch):
    monkeypatch.delenv("MXTPU_FLEET_MAX_QUEUE", raising=False)
    monkeypatch.setenv("MXTPU_SERVE_MAX_QUEUE", "32")
    assert fleet_max_queue(n_replicas=3) == 96
    monkeypatch.setenv("MXTPU_FLEET_MAX_QUEUE", "10")
    assert fleet_max_queue(n_replicas=3) == 10
    assert fleet_max_queue(7, n_replicas=3) == 7


def test_router_429_honors_aggregate_not_per_replica(tmp_path):
    """The fleet front door admits against the AGGREGATE depth (queue +
    total in-flight), not any single replica's bound: with max_queue=6
    over two blocked replicas, exactly 6 requests are admitted even
    though each replica alone would have rejected far sooner."""
    clients = [_BlockingClient(), _BlockingClient()]
    router = FleetRouter(clients, max_queue=6, directory=str(tmp_path),
                         respawn=False, threads=2)
    try:
        futs = [router.submit("m", {"x": np.zeros(1)}, n=1)
                for _ in range(6)]
        with pytest.raises(ServerBusy) as exc:
            router.submit("m", {"x": np.zeros(1)}, n=1)
        busy = exc.value
        assert busy.code == 429
        assert busy.queue_depth == 6          # aggregate, fleet-wide
        assert busy.limit == 6
        assert busy.retry_after_ms is not None
        for c in clients:
            c.release.set()
        for f in futs:
            f.result(timeout=30)
        # drained: the next request is admitted again
        router.submit("m", {"x": np.zeros(1)}, n=1).result(timeout=30)
    finally:
        router.close(drain=False)


def test_router_drain_returns_503_fleet_wide(tmp_path):
    clients = [_OkClient(), _OkClient()]
    router = FleetRouter(clients, max_queue=8, directory=str(tmp_path),
                         respawn=False, threads=2)
    try:
        router.predict("m", {"x": np.zeros(1)}, n=1, timeout=10)
        router.drain(timeout=10)
        with pytest.raises(ServerBusy) as exc:
            router.submit("m", {"x": np.zeros(1)}, n=1)
        assert exc.value.code == 503
        assert exc.value.reason == "draining"
    finally:
        router.close(drain=False)


def test_dead_replica_future_fails_structured_not_hangs(tmp_path):
    """Queued futures on a fleet with no survivors fail with a
    structured ReplicaDead carrying a to_dict payload — never hang."""
    router = FleetRouter([_DeadClient()], max_queue=8,
                         directory=str(tmp_path), respawn=False,
                         threads=1, rebind_wait_s=0.2)
    try:
        fut = router.submit("m", {"x": np.zeros(1)}, n=1)
        with pytest.raises(ReplicaDead) as exc:
            fut.result(timeout=15)
        doc = exc.value.to_dict()
        assert doc["error"] == "replica_dead"
        assert doc["model"] == "m"
        st = router.stats()
        assert st["replicas"]["0"]["state"] == "dead"
        assert st["generation"] == 1          # shrink verdict written
    finally:
        router.close(drain=False)


def test_router_fails_over_to_survivor(tmp_path):
    """Transport death on one replica retries on a sibling: the client
    sees a result, the dead replica leaves rotation, and the ledger
    records the shrink."""
    ok = _OkClient()
    router = FleetRouter([_DeadClient(), ok], max_queue=8,
                         directory=str(tmp_path), respawn=False,
                         threads=1)
    try:
        out = router.predict("m", {"x": np.zeros(1)}, n=1, timeout=15)
        assert out[0].shape == (1, 2)
        assert ok.calls == ["m"]
        st = router.stats()
        assert st["replicas"]["0"]["state"] == "dead"
        assert st["replicas"]["1"]["state"] == "ready"
        from mxnet_tpu.resilience import elastic
        led = elastic.read_ledger(path=fleet_ledger_path(str(tmp_path)))
        assert led["reason"] == "replica_death"
        assert led["members"] == [1]
    finally:
        router.close(drain=False)


def test_router_least_loaded_spreads_work(tmp_path):
    class _SlowClient(_OkClient):
        def predict(self, model, inputs, n=None, trace_id=None):
            # long enough that requests overlap and inflight counts
            # drive the pick; instant fakes would let replica 0 (the
            # tie-break winner) legally serve everything
            time.sleep(0.02)
            return super(_SlowClient, self).predict(
                model, inputs, n=n, trace_id=trace_id)

    clients = [_SlowClient(), _SlowClient(), _SlowClient()]
    router = FleetRouter(clients, max_queue=64, directory=str(tmp_path),
                         respawn=False, threads=3)
    try:
        futs = [router.submit("m", {"x": np.zeros(1)}, n=1)
                for _ in range(30)]
        for f in futs:
            f.result(timeout=30)
        counts = [len(c.calls) for c in clients]
        assert sum(counts) == 30
        assert all(c > 0 for c in counts)     # nobody starved
    finally:
        router.close(drain=False)


def test_router_mints_and_threads_trace_ids(tmp_path):
    ok = _OkClient()
    router = FleetRouter([ok], max_queue=8, directory=str(tmp_path),
                         respawn=False, threads=1)
    try:
        router.predict("m", {"x": np.zeros(1)}, n=1, timeout=10)
        assert ok.trace_ids == [None]          # tracing off: no id
        fut = router.submit("m", {"x": np.zeros(1)}, n=1,
                            trace_id="req-42")
        fut.result(timeout=10)
        assert ok.trace_ids[-1] == "req-42"    # explicit id wins
    finally:
        router.close(drain=False)


def test_router_swap_holds_replica_out_only_during_rebind(tmp_path):
    clients = [_OkClient(), _OkClient()]
    router = FleetRouter(clients, max_queue=8, directory=str(tmp_path),
                         respawn=False, threads=2)
    try:
        res = router.swap("/dev/null", version="v2")
        assert sorted(res["replicas"]) == [0, 1]
        assert all(r["version"] == "v2"
                   for r in res["replicas"].values())
        assert len(res["swap_pause_ms"]) == 2
        st = router.stats()
        assert st["version_skew"] == {"v2": [0, 1]}
        assert all(r["state"] == "ready"
                   for r in st["replicas"].values())
        assert st["swap_pause_ms_p95"] is not None
    finally:
        router.close(drain=False)


def test_router_swap_failure_leaves_old_version_in_skew(tmp_path):
    class _BadSwap(_OkClient):
        def swap(self, params, version=None, timeout=None):
            raise ConnectionError("swap wire broke")

    router = FleetRouter([_OkClient(), _BadSwap()], max_queue=8,
                         directory=str(tmp_path), respawn=False,
                         threads=2)
    try:
        res = router.swap("/dev/null", version="v2")
        assert "error" in res["replicas"][1]
        st = router.stats()
        # skew report names the divergence: replica 0 on v2, 1 stale
        assert st["version_skew"]["v2"] == [0]
        assert 1 in st["version_skew"]["?"]
        assert st["replicas"]["1"]["state"] == "ready"   # still serving
        router.predict("m", {"x": np.zeros(1)}, n=1, timeout=10)
    finally:
        router.close(drain=False)


# ---------------------------------------------------------------------------
# npz transport codec
# ---------------------------------------------------------------------------

def test_npz_codec_roundtrip():
    arrays = {"data": np.arange(6, dtype="float32").reshape(2, 3),
              "mask": np.ones((2,), dtype="int32")}
    got = decode_arrays(encode_arrays(arrays))
    for k in arrays:
        np.testing.assert_array_equal(got[k], arrays[k])
    bare = np.arange(4, dtype="float32")
    np.testing.assert_array_equal(decode_arrays(encode_arrays(bare)),
                                  bare)


# ---------------------------------------------------------------------------
# live weight hot-swap on a real ModelServer (in-process)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def toy_model():
    net = mx.models.get_mlp(num_classes=3, hidden=(8,))
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (2, 10))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    arg_params, aux_params = mod.get_params()
    params = {"arg:" + k: v for k, v in arg_params.items()}
    params.update({"aux:" + k: v for k, v in aux_params.items()})
    return net, params


def _perturbed(params, scale=1.25, shift=0.01):
    return {k: mx.nd.array(v.asnumpy() * scale + shift)
            for k, v in params.items()}


def test_swap_params_zero_lowerings_and_bit_identical(toy_model):
    """The hot-swap contract: new params re-bind through the program
    registry (zero new lowerings — the registry counters prove it) and
    post-swap outputs are bit-identical to a fresh Predictor over the
    new params."""
    net, params = toy_model
    v2 = _perturbed(params)
    v2_np = {k: v.asnumpy() for k, v in v2.items()}
    srv = ModelServer(max_delay_ms=2)
    srv.add_model("toy", net.tojson(), params, {"data": (10,)},
                  buckets=(1, 4))
    x = np.random.RandomState(11).rand(3, 10).astype("float32")
    before_out = srv.predict("toy", x, timeout=30)[0]
    before_lower = program_registry_stats()["lowerings"]
    res = srv.swap_params(v2, version="v2")
    assert res["version"] == "v2"
    assert res["lowerings"] == 0
    assert res["models"] == ["toy"]
    assert program_registry_stats()["lowerings"] == before_lower
    after_out = srv.predict("toy", x, timeout=30)[0]
    stats = srv.stats()
    srv.close()
    ref = mx.Predictor(net.tojson(), v2_np,
                       {"data": x.shape}).forward(data=x)[0]
    assert np.array_equal(np.asarray(after_out), np.asarray(ref))
    assert not np.array_equal(np.asarray(after_out),
                              np.asarray(before_out))
    assert stats["param_version"] == "v2"


def test_swap_params_unknown_model_raises(toy_model):
    net, params = toy_model
    srv = ModelServer(max_delay_ms=2)
    srv.add_model("toy", net.tojson(), params, {"data": (10,)},
                  buckets=(1,))
    with pytest.raises(MXNetError):
        srv.swap_params(params, models=["nope"])
    srv.close()


def test_swap_crash_keeps_old_params_serving(toy_model, monkeypatch):
    """An injected swap_crash fires AFTER the new predictors are built
    but BEFORE install: the old version keeps serving untouched and
    param_version never advances — a failed swap is a no-op."""
    from mxnet_tpu.resilience import faultinject
    net, params = toy_model
    srv = ModelServer(max_delay_ms=2)
    srv.add_model("toy", net.tojson(), params, {"data": (10,)},
                  buckets=(1,))
    x = np.random.RandomState(13).rand(1, 10).astype("float32")
    before_out = srv.predict("toy", x, timeout=30)[0]
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "kind=swap_crash")
    faultinject.reset()
    with pytest.raises(faultinject.InjectedFault):
        srv.swap_params(_perturbed(params), version="v2")
    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    faultinject.reset()
    after_out = srv.predict("toy", x, timeout=30)[0]
    stats = srv.stats()
    srv.close()
    assert np.array_equal(np.asarray(after_out), np.asarray(before_out))
    assert stats["param_version"] == "v0"


# ---------------------------------------------------------------------------
# fleet telemetry rollup
# ---------------------------------------------------------------------------

def _serve_rec(replica, version, lat, wall, n=2):
    return {"kind": "serve", "replica": replica,
            "param_version": version, "n_requests": n,
            "n_samples": n, "occupancy": 0.5, "lat_ms": lat,
            "wall_ms": wall}


def test_fleet_report_rollup_and_skew():
    records = [
        _serve_rec(0, "v1", [10.0, 12.0], 1000.0),
        _serve_rec(0, "v1", [11.0, 13.0], 2000.0),
        _serve_rec(1, "v2", [30.0, 50.0], 1000.0, n=6),
        _serve_rec(1, "v2", [40.0, 60.0], 3000.0, n=6),
        {"kind": "serve", "model": "m"},       # unstamped: ignored
        {"kind": "step", "replica": 0},        # wrong kind: ignored
    ]
    fl = fleet_report(records)
    assert sorted(fl["replicas"]) == ["0", "1"]
    r0, r1 = fl["replicas"]["0"], fl["replicas"]["1"]
    assert r0["requests"] == 4 and r1["requests"] == 12
    assert r0["param_version"] == "v1"
    assert r1["latency_ms"]["p95"] > r0["latency_ms"]["p95"]
    assert r0["qps"] == 4.0                    # 4 reqs over 1s span
    assert fl["version_skew"] == {"v1": [0], "v2": [1]}
    assert fl["straggler_gap_ms"] > 0
    assert fl["balance_ratio"] == 1.5          # 12 / mean(8)
    assert fl["requests"] == 16


def test_fleet_report_empty_without_replica_stamps():
    assert fleet_report([{"kind": "serve", "model": "m"}]) \
        == {"replicas": {}}


def test_build_report_carries_fleet_rollup():
    from mxnet_tpu.observability import aggregate
    records = [_serve_rec(0, "v1", [10.0], 1000.0),
               _serve_rec(1, "v1", [12.0], 1500.0)]
    # build_report needs rank-shaped records; serve records qualify
    for i, rec in enumerate(records):
        rec.update(run_id="r", rank=0, model="m", bucket=2)
    report = aggregate.build_report(records)
    assert sorted(report["fleet"]["replicas"]) == ["0", "1"]
    from mxnet_tpu.observability.slo import telemetry_metrics
    metrics = telemetry_metrics(report)
    assert "fleet_straggler_gap_ms" in metrics
    assert "fleet_balance_ratio" in metrics


def test_set_fleet_context_stamps_serve_records(tmp_path, monkeypatch):
    from mxnet_tpu.observability import events
    from mxnet_tpu.serving import telemetry as tel
    monkeypatch.setenv("MXTPU_TELEMETRY", "1")
    monkeypatch.setenv("MXTPU_TELEMETRY_DIR", str(tmp_path))
    events.refresh()
    try:
        tel.set_fleet_context(replica=3, param_version="v7")
        tel.emit_batch("m", 4, 2, 2, 0.5, 0.1, 0, 1.0, 0.1, 0.5, 0.1,
                       [3.0, 4.0])
        events.flush()
        recs = [json.loads(line)
                for p in tmp_path.glob("events-rank*.jsonl")
                for line in open(p) if line.strip()]
        serve = [r for r in recs if r.get("kind") == "serve"]
        assert serve and serve[-1]["replica"] == 3
        assert serve[-1]["param_version"] == "v7"
    finally:
        tel._FLEET.update(replica=None, param_version=None)
        monkeypatch.delenv("MXTPU_TELEMETRY")
        monkeypatch.delenv("MXTPU_TELEMETRY_DIR")
        events.refresh()


def test_fleet_names_are_exported():
    import mxnet_tpu.serving as serving
    for name in ("FleetRouter", "FileKV", "ReplicaDead",
                 "fleet_report", "set_fleet_context", "FleetClient",
                 "NotLeader", "adopt_fleet", "connect_kv"):
        assert hasattr(serving, name)


# ---------------------------------------------------------------------------
# per-tenant admission lanes
# ---------------------------------------------------------------------------

def test_fleet_tenants_parsing(monkeypatch):
    monkeypatch.setenv("MXTPU_FLEET_TENANTS",
                       "teamA:50:100:3;teamB:10:20")
    cfg = fleet_tenants()
    assert cfg["teamA"] == {"rate": 50.0, "burst": 100.0, "weight": 3}
    assert cfg["teamB"] == {"rate": 10.0, "burst": 20.0, "weight": 1}
    assert fleet_tenants("") == {}
    with pytest.raises(ValueError):
        fleet_tenants("teamA:50")              # missing burst
    with pytest.raises(ValueError):
        fleet_tenants("a:1:2:3:4:5")           # too many fields


def test_hot_tenant_429s_while_default_flows(tmp_path):
    """A tenant over ITS token budget gets a structured 429 (reason
    "tenant budget") while the default lane keeps flowing — noisy
    neighbors burn their own bucket, never the fleet's door."""
    ok = _OkClient()
    router = FleetRouter([ok], max_queue=64, directory=str(tmp_path),
                         respawn=False, threads=1,
                         tenants="teamA:0.001:2")
    try:
        for _ in range(2):                     # burst=2 admitted
            router.submit("m", {"x": np.zeros(1)}, n=1,
                          tenant="teamA").result(timeout=10)
        with pytest.raises(ServerBusy) as exc:
            router.submit("m", {"x": np.zeros(1)}, n=1, tenant="teamA")
        busy = exc.value
        assert busy.code == 429
        assert busy.reason == "tenant budget"
        assert busy.to_dict()["tenant"] == "teamA"
        assert busy.limit == 2                 # the tenant's burst,
        assert busy.retry_after_ms is not None # not the fleet queue
        # siblings and the default lane are untouched by teamA's burn
        router.submit("m", {"x": np.zeros(1)}, n=1).result(timeout=10)
        st = router.stats()
        assert st["tenants"]["teamA"]["admitted"] == 2
        assert st["tenants"]["teamA"]["rejected"] == 1
    finally:
        router.close(drain=False)


def test_unknown_tenant_rides_default_lane(tmp_path):
    router = FleetRouter([_OkClient()], max_queue=8,
                         directory=str(tmp_path), respawn=False,
                         threads=1, tenants="teamA:100:100")
    try:
        # a tenant nobody configured is not rejected — it shares the
        # unbudgeted default lane
        router.submit("m", {"x": np.zeros(1)}, n=1,
                      tenant="stranger").result(timeout=10)
        st = router.stats()
        assert st["tenants"]["teamA"]["admitted"] == 0
    finally:
        router.close(drain=False)


def test_weighted_fair_dequeue_order(tmp_path):
    """Weight 3 vs 1 under contention: the dispatch order follows the
    weight-expanded cycle (a,a,a,b,...) deterministically."""
    client = _BlockingClient()
    router = FleetRouter([client], max_queue=64,
                         directory=str(tmp_path), respawn=False,
                         threads=1, tenants="a:100:100:3;b:100:100:1")
    try:
        futs = [router.submit("occupy", {"x": np.zeros(1)}, n=1)]
        deadline = time.time() + 10
        while time.time() < deadline:          # occupy is in flight:
            st = router.stats()                # everything else queues
            if st["replicas"]["0"]["inflight"] == 1:
                break
            time.sleep(0.02)
        for i in range(6):
            futs.append(router.submit("a", {"x": np.zeros(1)}, n=1,
                                      tenant="a"))
        for i in range(2):
            futs.append(router.submit("b", {"x": np.zeros(1)}, n=1,
                                      tenant="b"))
        client.release.set()
        for f in futs:
            f.result(timeout=30)
        assert client.calls == ["occupy",
                                "a", "a", "a", "b", "a", "a", "a", "b"]
    finally:
        router.close(drain=False)


def test_no_tenant_config_keeps_single_fifo(tmp_path):
    """Without MXTPU_FLEET_TENANTS the router is bit-for-bit the old
    single-FIFO front door: no tenant rollup, plain arrival order."""
    client = _BlockingClient()
    router = FleetRouter([client], max_queue=64,
                         directory=str(tmp_path), respawn=False,
                         threads=1)
    try:
        assert router._rr == ["default"]
        futs = [router.submit("m%d" % i, {"x": np.zeros(1)}, n=1,
                              tenant="ignored-%d" % i)
                for i in range(5)]
        client.release.set()
        for f in futs:
            f.result(timeout=30)
        assert client.calls == ["m%d" % i for i in range(5)]
        assert "tenants" not in router.stats()
    finally:
        router.close(drain=False)


# ---------------------------------------------------------------------------
# leader lease: N routers over one KV
# ---------------------------------------------------------------------------

def _fresh_stamps(kv, n):
    now = time.time()
    for i in range(n):
        kv.key_value_set("mxtpu_hb/%d" % i, str(now + 1000))


def _wait_for(predicate, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def test_standby_rejects_swap_and_takes_over_on_leader_exit(tmp_path,
                                                            any_kv):
    """Two routers, one KV: the second stands by, answers swap with a
    structured NotLeader naming the leader, and takes over within the
    health-loop cadence once the leader releases the lease."""
    kv = any_kv
    _fresh_stamps(kv, 1)
    a = FleetRouter([_OkClient()], kv=kv, max_queue=8,
                    directory=str(tmp_path / "a"), respawn=False,
                    threads=1, router_id="a", lease_ttl_s=2.0)
    b = None
    try:
        assert a.stats()["role"] == "leader"
        b = FleetRouter([_OkClient()], kv=kv, max_queue=8,
                        directory=str(tmp_path / "b"), respawn=False,
                        threads=1, router_id="b", lease_ttl_s=2.0)
        assert b.stats()["role"] == "standby"
        assert b.stats()["lease"]["holder"] == "b"
        with pytest.raises(NotLeader) as exc:
            b.swap("/dev/null", version="v2")
        doc = exc.value.to_dict()
        assert doc == {"error": "not_leader", "action": "swap",
                       "router_id": "b", "leader": "a"}
        # standbys still serve reads: predict works on either router
        b.predict("m", {"x": np.zeros(1)}, n=1, timeout=10)
        a.close(drain=False)                   # releases the lease
        assert _wait_for(
            lambda: b.stats()["role"] == "leader"), \
            "standby never took over after leader exit"
        st = b.stats()
        assert st["takeovers"] == 1
        res = b.swap("/dev/null", version="v2") # leader-only op now ok
        assert res["replicas"][0]["version"] == "v2"
    finally:
        if b is not None:
            b.close(drain=False)


def test_standby_mirrors_leader_death_verdicts(tmp_path):
    """The leader writes the shrink verdict ONCE; the standby adopts
    it from the published view (no double generation bump) and stops
    routing to the dead replica."""
    kv = FileKV(tmp_path / "kv")
    now = time.time()
    kv.key_value_set("mxtpu_hb/0", str(now + 1000))   # fresh
    kv.key_value_set("mxtpu_hb/1", str(now - 1000))   # long stale
    shared_dir = str(tmp_path / "fleet")
    a = FleetRouter([_OkClient(), _OkClient()], kv=kv, max_queue=8,
                    hb_timeout_s=5.0, directory=shared_dir,
                    respawn=False, threads=1, router_id="a",
                    lease_ttl_s=2.0)
    b = FleetRouter([_OkClient(), _OkClient()], kv=kv, max_queue=8,
                    hb_timeout_s=5.0, directory=shared_dir,
                    respawn=False, threads=1, router_id="b",
                    lease_ttl_s=2.0)
    try:
        assert _wait_for(lambda: b.stats()["replicas"]["1"]["state"]
                         == "dead"), "standby never mirrored verdict"
        st_a, st_b = a.stats(), b.stats()
        assert st_a["role"] == "leader" and st_b["role"] == "standby"
        assert st_b["replicas"]["1"]["reason"] == "leader verdict"
        from mxnet_tpu.resilience import elastic
        led = elastic.read_ledger(path=fleet_ledger_path(shared_dir))
        assert led["generation"] == 1          # one verdict, not two
        assert st_a["generation"] == 1
        assert st_b["generation"] == 1         # adopted, not re-bumped
    finally:
        b.close(drain=False)
        a.close(drain=False)


# ---------------------------------------------------------------------------
# KV fault discipline in the router (the ISSUE's named regression)
# ---------------------------------------------------------------------------

class _PartitionableKV(CoordKV):
    """FileKV wrapper whose ``down`` flag simulates a KV partition."""

    def __init__(self, root):
        self.kv = FileKV(root)
        self.down = False

    def _gate(self):
        if self.down:
            raise KVUnreachable("injected partition", op="test")

    def key_value_set(self, key, value, allow_overwrite=True):
        self._gate()
        self.kv.key_value_set(key, value, allow_overwrite)

    def blocking_key_value_get(self, key, timeout_ms):
        self._gate()
        return self.kv.blocking_key_value_get(key, timeout_ms)

    def key_value_dir_get(self, prefix):
        self._gate()
        return self.kv.key_value_dir_get(prefix)

    def key_value_delete(self, key):
        self._gate()
        self.kv.key_value_delete(key)


def test_kv_partition_mid_scan_never_fabricates_deaths(tmp_path):
    """THE regression: a KV partition mid-scan must hold the last
    verdict — zero death verdicts, zero generation bumps, replicas keep
    serving — and heal cleanly when the KV answers again."""
    kv = _PartitionableKV(tmp_path / "kv")
    _fresh_stamps(kv, 2)
    router = FleetRouter([_OkClient(), _OkClient()], kv=kv,
                         max_queue=8, hb_timeout_s=5.0,
                         directory=str(tmp_path), respawn=False,
                         threads=1, lease_ttl_s=60.0)
    try:
        assert _wait_for(lambda: not router.stats()["kv_held"],
                         timeout=5.0)
        kv.down = True                         # partition mid-scan
        assert _wait_for(lambda: router.stats()["kv_held"]), \
            "router never noticed the partition"
        time.sleep(1.2)                        # several held ticks
        st = router.stats()
        assert st["replicas"]["0"]["state"] == "ready"
        assert st["replicas"]["1"]["state"] == "ready"
        assert st["generation"] == 0           # no verdict fabricated
        assert st["role"] == "leader"          # lease held through blip
        from mxnet_tpu.resilience import elastic
        assert not elastic.read_ledger(
            path=fleet_ledger_path(str(tmp_path)))
        # the serving path never depended on the KV: requests flow
        router.predict("m", {"x": np.zeros(1)}, n=1, timeout=10)
        kv.down = False                        # heal
        assert _wait_for(lambda: not router.stats()["kv_held"]), \
            "router never released the hold after heal"
        st = router.stats()
        assert st["replicas"]["0"]["state"] == "ready"
        assert st["generation"] == 0
    finally:
        router.close(drain=False)


def test_scan_dead_ranks_raises_structured_on_unreachable(tmp_path):
    """scan_dead_ranks NEVER answers 'all dead' for a dead KV — it
    raises KVUnreachable (both for structured and for generic backend
    failures)."""
    kv = _PartitionableKV(tmp_path / "kv")
    kv.down = True
    with pytest.raises(KVUnreachable):
        scan_dead_ranks(kv, [0, 1, 2], created=0.0, timeout=5.0)

    class _BrokenKV(object):
        def key_value_dir_get(self, prefix):
            raise OSError("stale NFS handle")

    with pytest.raises(KVUnreachable) as exc:
        scan_dead_ranks(_BrokenKV(), [0, 1], created=0.0, timeout=5.0)
    assert exc.value.kind == "kv_unreachable"


# ---------------------------------------------------------------------------
# swap on checkpoint commit
# ---------------------------------------------------------------------------

def test_leader_applies_published_swap_pointer_once(tmp_path):
    """The leader watches mxtpu_fleet/params_ptr and runs ONE drainless
    swap per published version — re-reading the same pointer never
    re-swaps."""
    kv = FileKV(tmp_path / "kv")
    _fresh_stamps(kv, 1)
    router = FleetRouter([_OkClient()], kv=kv, max_queue=8,
                         directory=str(tmp_path), respawn=False,
                         threads=1, lease_ttl_s=60.0)
    try:
        kv.key_value_set(_SWAP_PTR_KEY, json.dumps(
            {"params": "/dev/null", "version": "v9"}))
        assert _wait_for(
            lambda: router.stats()["replicas"]["0"]["param_version"]
            == "v9"), "leader never applied the published pointer"
        assert router.stats()["swaps"] == 1
        time.sleep(1.2)                        # more health ticks
        assert router.stats()["swaps"] == 1    # single-flight per version
        kv.key_value_set(_SWAP_PTR_KEY, json.dumps(
            {"params": "/dev/null", "version": "v10"}))
        assert _wait_for(
            lambda: router.stats()["replicas"]["0"]["param_version"]
            == "v10"), "new pointer version never applied"
        assert router.stats()["swaps"] == 2
    finally:
        router.close(drain=False)


def test_ckptmgr_commit_publishes_swap_pointer(tmp_path, monkeypatch):
    """MXTPU_FLEET_SWAP_ON_COMMIT=1: a committed checkpoint publishes
    the versioned-params pointer into the fleet KV; default off writes
    nothing."""
    from mxnet_tpu.resilience.ckptmgr import CheckpointManager
    from mxnet_tpu.resilience.netkv import KeyAbsent
    fleet_dir = tmp_path / "mxtpu_fleet"
    monkeypatch.setenv("MXTPU_FLEET_DIR", str(fleet_dir))
    monkeypatch.delenv("MXTPU_KV_URL", raising=False)
    tree = {"w": np.arange(8, dtype=np.float32)}
    kv = FileKV(fleet_dir / "kv")

    monkeypatch.delenv("MXTPU_FLEET_SWAP_ON_COMMIT", raising=False)
    mgr = CheckpointManager(str(tmp_path / "run"), keep=0,
                            payload_format="host")
    mgr.save(tree, 1)
    with pytest.raises(KeyAbsent):             # off by default
        kv.blocking_key_value_get(_SWAP_PTR_KEY, 60)

    monkeypatch.setenv("MXTPU_FLEET_SWAP_ON_COMMIT", "1")
    final = mgr.save(tree, 3)
    doc = json.loads(kv.blocking_key_value_get(_SWAP_PTR_KEY, 1000))
    assert doc["params"] == final
    assert doc["step"] == 3
    assert doc["version"] == "step_%08d" % 3


# ---------------------------------------------------------------------------
# front-door failover config
# ---------------------------------------------------------------------------

def test_fleet_routers_env_parsing(monkeypatch):
    monkeypatch.delenv("MXTPU_FLEET_ROUTERS", raising=False)
    monkeypatch.delenv("MXTPU_FLEET_PORT", raising=False)
    assert fleet_routers() == ["http://127.0.0.1:8930"]
    monkeypatch.setenv("MXTPU_FLEET_ROUTERS",
                       "http://r1:8930, http://r2:8931")
    assert fleet_routers() == ["http://r1:8930", "http://r2:8931"]
    assert fleet_routers(["http://x:1"]) == ["http://x:1"]
