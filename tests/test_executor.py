"""Executor binding/running tests (modeled on tests/python/unittest/test_executor.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.test_utils import assert_almost_equal

rng = np.random.RandomState(7)


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _init(exe, seed=0):
    r = np.random.RandomState(seed)
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = r.uniform(-0.1, 0.1, arr.shape).astype(np.float32)


def test_bind_forward_backward():
    net = _mlp()
    exe = net.simple_bind(mx.cpu(0), data=(8, 20))
    _init(exe)
    exe.arg_dict["data"][:] = rng.rand(8, 20).astype(np.float32)
    exe.arg_dict["softmax_label"][:] = np.arange(8) % 4
    out = exe.forward(is_train=True)[0]
    assert out.shape == (8, 4)
    assert np.allclose(out.asnumpy().sum(1), 1, atol=1e-5)
    exe.backward()
    assert np.abs(exe.grad_dict["fc1_weight"].asnumpy()).sum() > 0


def test_bind_explicit_arrays():
    x = sym.Variable("x")
    y = sym.Variable("y")
    z = x * y
    a = mx.nd.array(rng.rand(3, 3).astype(np.float32))
    b = mx.nd.array(rng.rand(3, 3).astype(np.float32))
    ga = mx.nd.zeros((3, 3))
    gb = mx.nd.zeros((3, 3))
    exe = z.bind(mx.cpu(0), args=[a, b], args_grad=[ga, gb])
    out = exe.forward()[0]
    assert_almost_equal(out, a.asnumpy() * b.asnumpy())
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones((3, 3))])
    assert_almost_equal(ga, b.asnumpy())
    assert_almost_equal(gb, a.asnumpy())


def test_grad_req_variants():
    x = sym.Variable("x")
    y = sym.sqrt(x) * 2.0
    data = np.abs(rng.rand(4, 4)).astype(np.float32) + 0.5
    # write
    exe = y.simple_bind(mx.cpu(0), grad_req="write", x=(4, 4))
    exe.arg_dict["x"][:] = data
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones((4, 4))])
    g1 = exe.grad_dict["x"].asnumpy().copy()
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones((4, 4))])
    assert_almost_equal(exe.grad_dict["x"].asnumpy(), g1)
    # add
    exe2 = y.simple_bind(mx.cpu(0), grad_req="add", x=(4, 4))
    exe2.arg_dict["x"][:] = data
    exe2.forward(is_train=True)
    exe2.backward([mx.nd.ones((4, 4))])
    exe2.forward(is_train=True)
    exe2.backward([mx.nd.ones((4, 4))])
    assert_almost_equal(exe2.grad_dict["x"].asnumpy(), 2 * g1, rtol=1e-4)
    # null
    exe3 = y.simple_bind(mx.cpu(0), grad_req="null", x=(4, 4))
    exe3.arg_dict["x"][:] = data
    exe3.forward(is_train=True)
    exe3.backward([mx.nd.ones((4, 4))])
    assert "x" not in exe3.grad_dict


def test_executor_outputs_multi():
    x = sym.Variable("x")
    sc = sym.SliceChannel(x, num_outputs=2, name="sc")
    data = rng.rand(2, 4).astype(np.float32)
    exe = sc.bind(mx.cpu(0), {"x": mx.nd.array(data)})
    outs = exe.forward()
    assert len(outs) == 2
    assert_almost_equal(outs[0], data[:, :2])
    assert_almost_equal(outs[1], data[:, 2:])


def test_reshape_shares_params():
    net = _mlp()
    exe = net.simple_bind(mx.cpu(0), data=(8, 20))
    _init(exe)
    exe2 = exe.reshape(partial_shaping=True, data=(4, 20))
    assert exe2.arg_dict["fc1_weight"] is exe.arg_dict["fc1_weight"]
    out = exe2.forward(is_train=False,
                       data=rng.rand(4, 20).astype(np.float32))[0]
    assert out.shape == (4, 4)
    with pytest.raises(MXNetError):
        exe.reshape(data=(4, 20))  # label shape changes -> needs partial


def test_monitor_callback():
    seen = []
    net = _mlp()
    exe = net.simple_bind(mx.cpu(0), data=(2, 20))
    _init(exe)
    exe.set_monitor_callback(lambda name, arr: seen.append(name))
    exe.forward(is_train=False)
    assert "fc1_output" in seen
    assert any(n.startswith("softmax") for n in seen)
    # VERDICT r3 #5: monitored stats must come from the COMPILED program,
    # not an eager re-trace — the dispatch counter proves which path ran
    assert exe._n_monitored_compiled == 1


def test_monitor_compiled_values_match_unmonitored():
    """The monitored compiled program computes the same numbers as the
    plain jit path, and values stream out correctly per op."""
    import numpy as onp
    net = _mlp()
    exe = net.simple_bind(mx.cpu(0), data=(2, 20))
    _init(exe)
    data = rng.rand(2, 20).astype(np.float32)
    plain = exe.forward(is_train=False, data=data)[0].asnumpy()

    got = {}
    exe.set_monitor_callback(lambda name, arr: got.setdefault(
        name, onp.asarray(arr.asnumpy())))
    out = exe.forward(is_train=False, data=data)[0].asnumpy()
    assert_almost_equal(out, plain)
    # the head op's monitored output equals the executor output
    head = [n for n in got if n.startswith("softmax") and
            n.endswith("_output")]
    assert head, sorted(got)
    assert_almost_equal(got[head[0]], plain)


def test_monitor_interpret_mode(monkeypatch):
    """MXTPU_MONITOR_MODE=interpret keeps the eager op-by-op path."""
    monkeypatch.setenv("MXTPU_MONITOR_MODE", "interpret")
    seen = []
    net = _mlp()
    exe = net.simple_bind(mx.cpu(0), data=(2, 20))
    _init(exe)
    exe.set_monitor_callback(lambda name, arr: seen.append(name))
    exe.forward(is_train=False)
    assert "fc1_output" in seen
    assert exe._n_monitored_compiled == 0


def test_copy_params_from():
    net = _mlp()
    exe = net.simple_bind(mx.cpu(0), data=(2, 20))
    w = mx.nd.array(rng.rand(16, 20).astype(np.float32))
    exe.copy_params_from({"fc1_weight": w})
    assert_almost_equal(exe.arg_dict["fc1_weight"], w.asnumpy())
    with pytest.raises(MXNetError):
        exe.copy_params_from({"nope": w})
    exe.copy_params_from({"nope": w}, allow_extra_params=True)


def test_forward_backward_fused():
    net = _mlp()
    exe = net.simple_bind(mx.cpu(0), data=(8, 20))
    _init(exe)
    exe.arg_dict["data"][:] = rng.rand(8, 20).astype(np.float32)
    exe.arg_dict["softmax_label"][:] = np.arange(8) % 4
    # fused result equals separate forward+backward
    exe.forward(is_train=True)
    exe.backward()
    g_sep = exe.grad_dict["fc2_weight"].asnumpy().copy()
    out_fused = exe.forward_backward()[0]
    assert np.allclose(out_fused.asnumpy().sum(1), 1, atol=1e-5)
    assert_almost_equal(exe.grad_dict["fc2_weight"].asnumpy(), g_sep, rtol=1e-4)


def test_shared_exec_compile_cache():
    net = _mlp()
    exe = net.simple_bind(mx.cpu(0), data=(8, 20))
    exe2 = net.simple_bind(mx.cpu(0), data=(16, 20), shared_exec=exe)
    assert exe2._jit_forward is exe._jit_forward
    _init(exe2)
    out = exe2.forward(is_train=False,
                       data=rng.rand(16, 20).astype(np.float32))[0]
    assert out.shape == (16, 4)


def test_ctx_group_model_parallel():
    """group2ctx placement (test_model_parallel.py:28-40 pattern): same
    result with and without placement."""
    with mx.AttrScope(ctx_group="stage1"):
        data = sym.Variable("data")
        fc1 = sym.FullyConnected(data, num_hidden=8, name="fc1")
        act = sym.Activation(fc1, act_type="relu")
    with mx.AttrScope(ctx_group="stage2"):
        fc2 = sym.FullyConnected(act, num_hidden=4, name="fc2")
        net = sym.LinearRegressionOutput(fc2, name="lro")

    shapes = {"data": (4, 6)}
    exe_plain = net.simple_bind(mx.cpu(0), **shapes)
    exe_mp = net.simple_bind(
        mx.cpu(0), group2ctx={"stage1": mx.cpu(1), "stage2": mx.cpu(2)},
        **shapes)
    r = np.random.RandomState(3)
    for name, arr in exe_plain.arg_dict.items():
        v = r.uniform(-1, 1, arr.shape).astype(np.float32)
        arr[:] = v
        exe_mp.arg_dict[name][:] = v
    o1 = exe_plain.forward(is_train=True)[0].asnumpy()
    o2 = exe_mp.forward(is_train=True)[0].asnumpy()
    assert_almost_equal(o1, o2)
    exe_plain.backward()
    exe_mp.backward()
    for name in exe_plain.grad_dict:
        assert_almost_equal(exe_plain.grad_dict[name].asnumpy(),
                            exe_mp.grad_dict[name].asnumpy(), rtol=1e-4)


def test_interpret_matches_compiled():
    """check_consistency analog (reference test_operator_gpu.py): the
    monitor's eager interpret path and the jitted path must produce
    identical outputs for a conv/bn/pool net — the NaiveEngine-style
    debugging mode is numerically the same program."""
    net = mx.models.get_lenet(num_classes=4)
    shapes = {"data": (2, 1, 28, 28), "softmax_label": (2,)}
    exe = net.simple_bind(mx.cpu(), grad_req="null", **shapes)
    rng = np.random.RandomState(0)
    for name, arr in exe.arg_dict.items():
        if name not in shapes:
            arr[:] = rng.uniform(-0.1, 0.1, arr.shape).astype("float32")
    exe.arg_dict["data"][:] = rng.rand(2, 1, 28, 28).astype("float32")

    compiled = exe.forward(is_train=False)[0].asnumpy()
    seen = []
    exe.set_monitor_callback(lambda name, arr: seen.append(name))
    interpreted = exe.forward(is_train=False)[0].asnumpy()
    exe.set_monitor_callback(None)
    assert seen, "monitor path did not run eagerly"
    np.testing.assert_allclose(interpreted, compiled, rtol=2e-5, atol=2e-6)


def test_program_cache_refreshes_on_env_flip(monkeypatch):
    """The per-symbol program cache key folds in the baked host flags
    (compute dtype etc. — executor._bind_env_fingerprint): a flag flip
    between binds must NOT reuse a stale program, and flipping back
    must reuse the original (MXL-X002 regression)."""
    monkeypatch.delenv("MXNET_COMPUTE_DTYPE", raising=False)
    net = _mlp()
    exe = net.simple_bind(mx.cpu(0), data=(4, 20))
    p1 = exe._program
    monkeypatch.setenv("MXNET_COMPUTE_DTYPE", "bfloat16")
    exe2 = net.simple_bind(mx.cpu(0), data=(4, 20))
    assert exe2._program is not p1
    monkeypatch.setenv("MXNET_COMPUTE_DTYPE", "")
    exe3 = net.simple_bind(mx.cpu(0), data=(4, 20))
    assert exe3._program is p1


def test_fused_step_cache_keys_on_values_not_identity():
    """_get_fused regression (MXL-X002): the fused-step cache must hit
    for a fresh-but-identical optimizer (value fingerprint, not id()),
    rebuild when a hyperparameter actually changes, and ignore the
    per-step update counters that mutate every step."""
    net = _mlp()
    exe = net.simple_bind(mx.cpu(0), data=(4, 20))
    f1 = exe._get_fused(mx.optimizer.SGD(learning_rate=0.1))
    # a different instance with identical hyperparameters: cache hit
    assert exe._get_fused(mx.optimizer.SGD(learning_rate=0.1)) is f1
    # the per-step counter churns every update — it must not miss
    counting = mx.optimizer.SGD(learning_rate=0.1)
    counting.num_update = 99
    assert exe._get_fused(counting) is f1
    # a real hyperparameter change rebuilds
    f2 = exe._get_fused(mx.optimizer.SGD(learning_rate=0.2))
    assert f2 is not f1
    f3 = exe._get_fused(mx.optimizer.SGD(learning_rate=0.2,
                                         momentum=0.9))
    assert f3 is not f2
