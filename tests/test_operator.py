"""Per-operator forward/backward checks vs numpy
(modeled on tests/python/unittest/test_operator.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  check_symbolic_forward,
                                  check_symbolic_backward)

rng = np.random.RandomState(12345)


def _f32(*shape):
    return rng.uniform(-1, 1, size=shape).astype(np.float32)


# ---------------------------------------------------------------- elementwise
def test_elementwise_binary():
    a, b = _f32(3, 4), _f32(3, 4)
    x, y = sym.Variable("x"), sym.Variable("y")
    check_symbolic_forward(x + y, [a, b], [a + b])
    check_symbolic_forward(x - y, [a, b], [a - b])
    check_symbolic_forward(x * y, [a, b], [a * b])
    check_symbolic_forward(x / y, [a, b], [a / b], rtol=1e-3, atol=1e-4)
    check_symbolic_forward(sym._Maximum(x, y), [a, b], [np.maximum(a, b)])
    check_symbolic_forward(sym._Minimum(x, y), [a, b], [np.minimum(a, b)])


def test_elementwise_backward():
    a, b = _f32(3, 4), _f32(3, 4)
    x, y = sym.Variable("x"), sym.Variable("y")
    og = _f32(3, 4)
    check_symbolic_backward(x * y, [a, b], [og], [og * b, og * a])
    check_symbolic_backward(x + y, [a, b], [og], [og, og])


def test_scalar_ops():
    a = _f32(3, 4)
    x = sym.Variable("x")
    check_symbolic_forward(x + 2, [a], [a + 2])
    check_symbolic_forward(2 - x, [a], [2 - a])
    check_symbolic_forward(x * 3, [a], [a * 3])
    check_symbolic_forward(6.0 / (x + 3), [a], [6 / (a + 3)], rtol=1e-3)
    check_symbolic_forward(x ** 2, [a], [a ** 2], rtol=1e-3)


def test_unary_math():
    a = rng.uniform(0.5, 2, size=(3, 4)).astype(np.float32)
    x = sym.Variable("x")
    for s, f in [(sym.sqrt(x), np.sqrt), (sym.exp(x), np.exp),
                 (sym.log(x), np.log), (sym.square(x), np.square),
                 (sym.cos(x), np.cos), (sym.sin(x), np.sin),
                 (sym.abs(x), np.abs), (sym.sign(x), np.sign),
                 (sym.ceil(x), np.ceil), (sym.floor(x), np.floor),
                 (sym.rsqrt(x), lambda v: 1 / np.sqrt(v))]:
        check_symbolic_forward(s, [a], [f(a)], rtol=1e-3, atol=1e-5)
    check_numeric_gradient(sym.sqrt(x) * sym.exp(x), {"x": a.astype(np.float64)})


def test_reductions():
    a = _f32(2, 3, 4)
    x = sym.Variable("x")
    check_symbolic_forward(sym.sum(x), [a], [a.sum().reshape(1)], rtol=1e-3)
    check_symbolic_forward(sym.sum(x, axis=(1,)), [a], [a.sum(1)], rtol=1e-3)
    check_symbolic_forward(sym.sum(x, axis=(0, 2), keepdims=True), [a],
                           [a.sum((0, 2), keepdims=True)], rtol=1e-3)
    check_symbolic_forward(sym.max(x, axis=(1,)), [a], [a.max(1)])
    check_symbolic_forward(sym.min(x), [a], [a.min().reshape(1)])
    check_symbolic_forward(sym.norm(x), [a],
                           [np.sqrt((a ** 2).sum()).reshape(1)], rtol=1e-3)


def test_dot():
    a, b = _f32(4, 5), _f32(5, 6)
    x, y = sym.Variable("x"), sym.Variable("y")
    check_symbolic_forward(sym.dot(x, y), [a, b], [a.dot(b)], rtol=1e-3)
    check_symbolic_forward(sym.dot(x, y, transpose_a=True),
                           [a.T.copy(), b], [a.dot(b)], rtol=1e-3)
    og = _f32(4, 6)
    check_symbolic_backward(sym.dot(x, y), [a, b], [og],
                            [og.dot(b.T), a.T.dot(og)], rtol=1e-3)
    # batched
    ba, bb = _f32(2, 4, 5), _f32(2, 5, 6)
    check_symbolic_forward(sym.batch_dot(x, y), [ba, bb],
                           [np.matmul(ba, bb)], rtol=1e-3)


def test_transpose_reshape_ops():
    a = _f32(2, 3, 4)
    x = sym.Variable("x")
    check_symbolic_forward(sym.transpose(x), [a], [a.T])
    check_symbolic_forward(sym.transpose(x, axes=(1, 0, 2)), [a],
                           [a.transpose(1, 0, 2)])
    check_symbolic_forward(sym.expand_dims(x, axis=1), [a], [a[:, None]])
    check_symbolic_forward(sym.flip(x, axis=1), [a], [a[:, ::-1]])
    check_symbolic_forward(sym.slice_axis(x, axis=2, begin=1, end=3), [a],
                           [a[:, :, 1:3]])
    check_symbolic_forward(sym.SwapAxis(x, dim1=0, dim2=2), [a],
                           [np.swapaxes(a, 0, 2)])


def test_broadcast_ops():
    a = _f32(1, 3, 1)
    x = sym.Variable("x")
    check_symbolic_forward(sym.broadcast_axis(x, axis=(0, 2), size=(2, 4)), [a],
                           [np.broadcast_to(a, (2, 3, 4))])
    check_symbolic_forward(sym.broadcast_to(x, shape=(2, 0, 4)), [a],
                           [np.broadcast_to(a, (2, 3, 4))])
    # broadcast backward sums over broadcast axes
    og = np.ones((2, 3, 4), dtype=np.float32)
    check_symbolic_backward(sym.broadcast_axis(x, axis=(0, 2), size=(2, 4)),
                            [a], [og], [np.full((1, 3, 1), 8, np.float32)])


def test_activation():
    a = _f32(3, 4)
    x = sym.Variable("x")
    check_symbolic_forward(sym.Activation(x, act_type="relu"), [a],
                           [np.maximum(a, 0)])
    check_symbolic_forward(sym.Activation(x, act_type="sigmoid"), [a],
                           [1 / (1 + np.exp(-a))], rtol=1e-3)
    check_symbolic_forward(sym.Activation(x, act_type="tanh"), [a],
                           [np.tanh(a)], rtol=1e-3)
    check_symbolic_forward(sym.Activation(x, act_type="softrelu"), [a],
                           [np.log1p(np.exp(a))], rtol=1e-3)
    check_numeric_gradient(sym.Activation(x, act_type="tanh"),
                           {"x": a.astype(np.float64)})


def test_leaky_relu():
    a = _f32(3, 4)
    x = sym.Variable("x")
    check_symbolic_forward(sym.LeakyReLU(x, act_type="leaky", slope=0.1), [a],
                           [np.where(a > 0, a, 0.1 * a)])
    check_symbolic_forward(sym.LeakyReLU(x, act_type="elu", slope=0.5), [a],
                           [np.where(a > 0, a, 0.5 * (np.exp(a) - 1))], rtol=1e-3)
    # prelu with learnable gamma
    g = np.array([0.1, 0.2, 0.3, 0.4], dtype=np.float32)
    pr = sym.LeakyReLU(x, act_type="prelu", name="pr")
    assert pr.list_arguments() == ["x", "pr_gamma"]
    check_symbolic_forward(pr, [a, g], [np.where(a > 0, a, g[None, :] * a)])


def test_fully_connected():
    a, w, b = _f32(5, 8), _f32(3, 8), _f32(3)
    x = sym.Variable("x")
    fc = sym.FullyConnected(x, num_hidden=3, name="fc")
    check_symbolic_forward(fc, [a, w, b], [a.dot(w.T) + b], rtol=1e-3)
    og = _f32(5, 3)
    check_symbolic_backward(fc, [a, w, b], [og],
                            [og.dot(w), og.T.dot(a), og.sum(0)], rtol=1e-3)
    fc_nb = sym.FullyConnected(x, num_hidden=3, no_bias=True, name="fcnb")
    check_symbolic_forward(fc_nb, [a, w], [a.dot(w.T)], rtol=1e-3)


def test_convolution():
    # compare against explicit im2col-style numpy conv
    data = _f32(2, 3, 7, 7)
    weight = _f32(4, 3, 3, 3)
    bias = _f32(4)
    x = sym.Variable("x")
    conv = sym.Convolution(x, kernel=(3, 3), num_filter=4, pad=(1, 1),
                           stride=(2, 2), name="conv")

    def np_conv(d, w, b, pad, stride):
        n, c, h, ww = d.shape
        f, _, kh, kw = w.shape
        dp = np.pad(d, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        oh = (h + 2 * pad - kh) // stride + 1
        ow = (ww + 2 * pad - kw) // stride + 1
        out = np.zeros((n, f, oh, ow), np.float32)
        for i in range(oh):
            for j in range(ow):
                patch = dp[:, :, i * stride:i * stride + kh,
                           j * stride:j * stride + kw]
                out[:, :, i, j] = np.einsum("nchw,fchw->nf", patch, w)
        return out + b[None, :, None, None]

    expect = np_conv(data, weight, bias, 1, 2)
    check_symbolic_forward(conv, [data, weight, bias], [expect], rtol=1e-3,
                           atol=1e-4)
    # numeric check on a small instance (keeps eval count manageable)
    sconv = sym.Convolution(x, kernel=(3, 3), num_filter=2, pad=(1, 1),
                            name="sc")
    check_numeric_gradient(sconv, {"x": _f32(1, 2, 4, 4).astype(np.float64),
                                   "sc_weight": _f32(2, 2, 3, 3).astype(np.float64),
                                   "sc_bias": _f32(2).astype(np.float64)},
                           rtol=5e-2, atol=5e-2)


def test_grouped_convolution():
    data = _f32(1, 4, 5, 5)
    weight = _f32(4, 2, 3, 3)
    x = sym.Variable("x")
    conv = sym.Convolution(x, kernel=(3, 3), num_filter=4, num_group=2,
                           no_bias=True, name="gconv")
    arg_shapes, out_shapes, _ = conv.infer_shape(x=(1, 4, 5, 5))
    assert dict(zip(conv.list_arguments(), arg_shapes))["gconv_weight"] == (4, 2, 3, 3)
    exe = conv.bind(mx.cpu(0), {"x": mx.nd.array(data),
                                "gconv_weight": mx.nd.array(weight)})
    out = exe.forward()[0].asnumpy()
    # group 0 uses channels 0:2, group 1 uses channels 2:4
    half0 = out[:, :2]
    dp = data[:, :2]
    ref = np.zeros_like(half0)
    for i in range(3):
        for j in range(3):
            ref += np.einsum("nchw,fc->nfhw",
                             dp[:, :, i:i + 3, j:j + 3], weight[:2, :, i, j])
    assert_almost_equal(half0, ref, rtol=1e-3, atol=1e-4)


def test_pooling():
    data = _f32(2, 3, 6, 6)
    x = sym.Variable("x")
    mp = sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    expect = data.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
    check_symbolic_forward(mp, [data], [expect])
    ap = sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    expect = data.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
    check_symbolic_forward(ap, [data], [expect], rtol=1e-3)
    gp = sym.Pooling(x, kernel=(1, 1), global_pool=True, pool_type="max")
    check_symbolic_forward(gp, [data], [data.max(axis=(2, 3), keepdims=True)])
    # 'full' convention rounds up
    fp = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max",
                     pooling_convention="full")
    _, out_shapes, _ = fp.infer_shape(x=(2, 3, 6, 6))
    assert out_shapes[0] == (2, 3, 3, 3)


def test_batchnorm_forward():
    data = _f32(4, 3, 2, 2)
    gamma = np.abs(_f32(3)) + 0.5
    beta = _f32(3)
    x = sym.Variable("x")
    bn = sym.BatchNorm(x, fix_gamma=False, name="bn")
    mean = data.mean(axis=(0, 2, 3))
    var = data.var(axis=(0, 2, 3))
    expect = ((data - mean[None, :, None, None])
              / np.sqrt(var[None, :, None, None] + 1e-3)
              * gamma[None, :, None, None] + beta[None, :, None, None])
    check_symbolic_forward(bn, [data, gamma, beta], [expect], rtol=1e-2,
                           atol=1e-3,
                           aux_states=[np.zeros(3, np.float32),
                                       np.ones(3, np.float32)],
                           is_train=True)


def test_dropout():
    data = np.ones((200, 200), dtype=np.float32)
    x = sym.Variable("x")
    do = sym.Dropout(x, p=0.5)
    exe = do.bind(mx.cpu(0), {"x": mx.nd.array(data)})
    out = exe.forward(is_train=True)[0].asnumpy()
    frac_kept = (out > 0).mean()
    assert abs(frac_kept - 0.5) < 0.05
    assert_almost_equal(out[out > 0], np.full((out > 0).sum(), 2.0, np.float32))
    out_eval = exe.forward(is_train=False)[0].asnumpy()
    assert_almost_equal(out_eval, data)


def test_concat_slice():
    a, b = _f32(2, 3, 4), _f32(2, 5, 4)
    x, y = sym.Variable("x"), sym.Variable("y")
    check_symbolic_forward(sym.Concat(x, y, dim=1, name="cat"), [a, b],
                           [np.concatenate([a, b], 1)])
    og = _f32(2, 8, 4)
    check_symbolic_backward(sym.Concat(x, y, dim=1, name="cat2"), [a, b], [og],
                            [og[:, :3], og[:, 3:]])
    data = _f32(2, 6, 4)
    sc = sym.SliceChannel(sym.Variable("d"), num_outputs=3, name="sc")
    check_symbolic_forward(sc, [data], [data[:, :2], data[:, 2:4], data[:, 4:]])


def test_reshape_flatten():
    a = _f32(2, 3, 4)
    x = sym.Variable("x")
    check_symbolic_forward(sym.Reshape(x, shape=(2, 12)), [a], [a.reshape(2, 12)])
    check_symbolic_forward(sym.Reshape(x, shape=(0, -1)), [a], [a.reshape(2, 12)])
    check_symbolic_forward(sym.Flatten(x), [a], [a.reshape(2, 12)])


def test_embedding():
    ids = np.array([1, 0, 3, 2], dtype=np.float32)
    weight = _f32(4, 5)
    e = sym.Embedding(sym.Variable("ids"), input_dim=4, output_dim=5, name="em")
    check_symbolic_forward(e, [ids, weight], [weight[ids.astype(int)]])
    og = _f32(4, 5)
    expect_w = np.zeros_like(weight)
    for i, ix in enumerate(ids.astype(int)):
        expect_w[ix] += og[i]
    check_symbolic_backward(e, [ids, weight], [og], {"em_weight": expect_w})


def test_blockgrad_makeloss():
    a = _f32(3, 4)
    x = sym.Variable("x")
    bg = sym.BlockGrad(x)
    check_symbolic_forward(bg, [a], [a])
    check_symbolic_backward(bg, [a], [np.ones_like(a)], [np.zeros_like(a)])
    ml = sym.MakeLoss(x, grad_scale=2.0)
    check_symbolic_forward(ml, [a], [a])
    check_symbolic_backward(ml, [a], [np.ones_like(a)],
                            [np.full_like(a, 2.0)])


def test_softmax_output():
    data = _f32(4, 5)
    label = np.array([0, 2, 4, 1], dtype=np.float32)
    x = sym.Variable("x")
    sm = sym.SoftmaxOutput(x, name="sm", grad_scale=1.0)
    e = np.exp(data - data.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    check_symbolic_forward(sm, [data, label], [p], rtol=1e-3)
    onehot = np.eye(5, dtype=np.float32)[label.astype(int)]
    check_symbolic_backward(sm, [data, label], [np.ones_like(data)],
                            {"x": p - onehot}, rtol=1e-3)


def test_softmax_output_ignore():
    data = _f32(4, 5)
    label = np.array([0, -1, 4, -1], dtype=np.float32)
    x = sym.Variable("x")
    sm = sym.SoftmaxOutput(x, name="sm", use_ignore=True, ignore_label=-1)
    e = np.exp(data - data.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    grad = p.copy()
    for i, l in enumerate(label.astype(int)):
        if l == -1:
            grad[i] = 0
        else:
            grad[i, l] -= 1
    check_symbolic_backward(sm, [data, label], [np.ones_like(data)],
                            {"x": grad}, rtol=1e-3)


def test_regression_outputs():
    data = _f32(4, 3)
    label = _f32(4, 3)
    x = sym.Variable("x")
    lin = sym.LinearRegressionOutput(x, name="lin")
    check_symbolic_forward(lin, [data, label], [data])
    check_symbolic_backward(lin, [data, label], [np.ones_like(data)],
                            {"x": data - label}, rtol=1e-3)
    logi = sym.LogisticRegressionOutput(x, name="lo")
    s = 1 / (1 + np.exp(-data))
    check_symbolic_forward(logi, [data, label], [s], rtol=1e-3)
    check_symbolic_backward(logi, [data, label], [np.ones_like(data)],
                            {"x": s - label}, rtol=1e-3)
    mae = sym.MAERegressionOutput(x, name="mae")
    check_symbolic_backward(mae, [data, label], [np.ones_like(data)],
                            {"x": np.sign(data - label)})


def test_smooth_l1():
    a = np.array([-2.0, -0.5, 0.0, 0.3, 1.5], dtype=np.float32)
    x = sym.Variable("x")
    s = sym.smooth_l1(x, scalar=1.0)
    expect = np.where(np.abs(a) < 1, 0.5 * a ** 2, np.abs(a) - 0.5)
    check_symbolic_forward(s, [a], [expect.astype(np.float32)])


def test_softmax_cross_entropy():
    data = _f32(4, 5)
    label = np.array([0, 2, 4, 1], dtype=np.float32)
    out = sym.softmax_cross_entropy(sym.Variable("x"), sym.Variable("l"))
    e = np.exp(data - data.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    expect = -np.log(p[np.arange(4), label.astype(int)]).sum()
    check_symbolic_forward(out, [data, label], [expect.reshape(1)], rtol=1e-3)


def test_lrn():
    data = np.abs(_f32(2, 8, 3, 3))
    x = sym.Variable("x")
    l = sym.LRN(x, nsize=3, alpha=1e-3, beta=0.75, knorm=2.0)
    sq = data ** 2
    pad = np.pad(sq, ((0, 0), (1, 1), (0, 0), (0, 0)))
    ssum = pad[:, 0:8] + pad[:, 1:9] + pad[:, 2:10]
    expect = data * (2.0 + (1e-3 / 3) * ssum) ** -0.75
    check_symbolic_forward(l, [data], [expect.astype(np.float32)], rtol=1e-3)


def test_l2_normalization():
    data = _f32(3, 4, 2)
    x = sym.Variable("x")
    out = sym.L2Normalization(x, mode="instance")
    norm = np.sqrt((data ** 2).sum(axis=(1, 2), keepdims=True) + 1e-10)
    check_symbolic_forward(out, [data], [data / norm], rtol=1e-3)
    out_c = sym.L2Normalization(x, mode="channel")
    norm = np.sqrt((data ** 2).sum(axis=1, keepdims=True) + 1e-10)
    check_symbolic_forward(out_c, [data], [data / norm], rtol=1e-3)


def test_upsampling():
    data = _f32(1, 2, 3, 3)
    x = sym.Variable("x")
    up = sym.UpSampling(x, scale=2, sample_type="nearest")
    expect = data.repeat(2, axis=2).repeat(2, axis=3)
    check_symbolic_forward(up, [data], [expect])


def test_crop():
    data = _f32(1, 2, 6, 6)
    like = _f32(1, 2, 4, 4)
    x, y = sym.Variable("x"), sym.Variable("y")
    c = sym.Crop(x, y, num_args=2, offset=(1, 1), name="crop")
    check_symbolic_forward(c, [data, like], [data[:, :, 1:5, 1:5]])
    c2 = sym.Crop(x, num_args=1, h_w=(3, 3), center_crop=True, name="crop2")
    # center crop of 6x6 to 3x3 starts at (1,1)
    check_symbolic_forward(c2, [data], [data[:, :, 1:4, 1:4]])


def test_cast():
    data = _f32(3, 4)
    x = sym.Variable("x")
    c = sym.Cast(x, dtype="int32")
    exe = c.bind(mx.cpu(0), {"x": mx.nd.array(data)})
    out = exe.forward()[0]
    assert out.dtype == np.int32


def test_sequence_ops():
    # (seq, batch, feat)
    data = _f32(5, 3, 2)
    lengths = np.array([2, 5, 3], dtype=np.float32)
    d, l = sym.Variable("d"), sym.Variable("l")
    last = sym.SequenceLast(d, l, use_sequence_length=True)
    expect = np.stack([data[1, 0], data[4, 1], data[2, 2]])
    check_symbolic_forward(last, [data, lengths], [expect])
    mask = sym.SequenceMask(d, l, use_sequence_length=True, value=-1.0)
    expect = data.copy()
    expect[2:, 0] = -1
    expect[3:, 2] = -1
    check_symbolic_forward(mask, [data, lengths], [expect])
    rev = sym.SequenceReverse(d, l, use_sequence_length=True)
    expect = data.copy()
    expect[:2, 0] = data[:2, 0][::-1]
    expect[:5, 1] = data[:5, 1][::-1]
    expect[:3, 2] = data[:3, 2][::-1]
    check_symbolic_forward(rev, [data, lengths], [expect])


def test_svm_output():
    data = _f32(4, 3)
    label = np.array([0, 1, 2, 1], dtype=np.float32)
    x = sym.Variable("x")
    svm = sym.SVMOutput(x, name="svm", margin=1.0, use_linear=True,
                        regularization_coefficient=1.0)
    check_symbolic_forward(svm, [data, label], [data])
    # grads: for k != l with margin violation: +1; label gets -count
    scores = data
    grad = np.zeros_like(scores)
    for i, l in enumerate(label.astype(int)):
        for k in range(3):
            if k != l and scores[i, k] - scores[i, l] + 1.0 > 0:
                grad[i, k] += 1
                grad[i, l] -= 1
    check_symbolic_backward(svm, [data, label], [np.ones_like(data)],
                            {"x": grad})


def test_upsampling_multi_input_nonsquare():
    """Non-square multi-input upsampling (review regression)."""
    a, b = _f32(1, 1, 4, 6), _f32(1, 1, 2, 3)
    x, y = sym.Variable("x"), sym.Variable("y")
    up = sym.UpSampling(x, y, scale=2, sample_type="nearest", num_args=2)
    exe = up.bind(mx.cpu(0), {"x": mx.nd.array(a), "y": mx.nd.array(b)})
    out = exe.forward()[0]
    assert out.shape == (1, 2, 8, 12)


def test_softmax_output_out_grad():
    """out_grad=True must scale by the head gradient (review regression)."""
    data = _f32(4, 5)
    label = np.array([0, 2, 4, 1], dtype=np.float32)
    x = sym.Variable("x")
    sm = sym.SoftmaxOutput(x, name="sm", out_grad=True)
    e = np.exp(data - data.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    onehot = np.eye(5, dtype=np.float32)[label.astype(int)]
    og = np.full_like(data, 2.0)
    check_symbolic_backward(sm, [data, label], [og],
                            {"x": (p - onehot) * 2.0}, rtol=1e-3)


def test_param_none_validation():
    from mxnet_tpu.base import MXNetError as MXE
    x = sym.Variable("x")
    with pytest.raises(MXE):
        sym.Activation(x, act_type="None")
    with pytest.raises(MXE):
        sym.Convolution(x, kernel="None", num_filter=8)


def test_element_mask():
    """broadcast_mask_op-inl.h:84: rhs masks lhs row-wise; mask gets no
    gradient (reference backward writes only lhs_grad)."""
    a = _f32(4, 3, 2)
    m = np.array([1, 0, 1, 0], dtype=np.float32)
    x, y = sym.Variable("x"), sym.Variable("y")
    out = sym.element_mask(x, y)
    expect = a * m[:, None, None]
    check_symbolic_forward(out, [a, m], [expect])
    og = _f32(4, 3, 2)
    check_symbolic_backward(out, [a, m], [og],
                            {"x": og * m[:, None, None],
                             "y": np.zeros_like(m)})


def test_registry_covers_reference_registrations():
    """Audit: every MXNET_REGISTER_OP_PROPERTY / MXNET_REGISTER_SIMPLE_OP
    name in the reference has a repo registration (VERDICT r3 #8) — keeps
    stragglers from reappearing.  Skips cleanly if the reference checkout
    is absent (CI without /root/reference)."""
    import os
    import re
    ref = "/root/reference/src"
    if not os.path.isdir(ref):
        pytest.skip("reference checkout not present")
    pat = re.compile(
        r"MXNET_REGISTER_(?:OP_PROPERTY|SIMPLE_OP)\(\s*([A-Za-z0-9_]+)")
    names = set()
    for root, _dirs, files in os.walk(ref):
        for fn in files:
            if fn.endswith((".cc", ".cu", ".h")):
                with open(os.path.join(root, fn), errors="replace") as f:
                    names.update(pat.findall(f.read()))
    from mxnet_tpu.ops.registry import OP_REGISTRY
    have = set(OP_REGISTRY._entries)
    missing = sorted(n for n in names if n.lower() not in have)
    assert not missing, "reference ops without a repo registration: %s" % missing
