// Native engine unit test — the reference's tests/cpp/threaded_engine_test.cc
// analog (randomized read/write workloads replayed against serial
// execution, plus a push-throughput figure), driving src/engine.cc
// directly through its C ABI with no Python in the loop.
//
// Built and run by `make test-cpp`
// (tests/test_engine.py::test_native_engine_cpp_unit wraps it).
#include <atomic>
#include <cassert>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {
typedef void (*MXTPUEngineFn)(void* param);
void* MXTPUEngineCreate(int num_threads);
void MXTPUEngineFree(void* h);
uint64_t MXTPUEngineNewVar(void* h);
void MXTPUEnginePush(void* h, MXTPUEngineFn fn, void* param,
                     const uint64_t* const_vars, int n_const,
                     const uint64_t* mutable_vars, int n_mutable);
void MXTPUEngineWaitForVar(void* h, uint64_t var);
void MXTPUEngineWaitForAll(void* h);
void MXTPUEngineDeleteVar(void* h, uint64_t var);
void MXTPUEngineShutdown(void* h);
}

// xorshift PRNG: deterministic workloads across runs/platforms
static uint64_t rng_state = 0x9E3779B97F4A7C15ull;
static uint64_t next_rand() {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return rng_state;
}

// One randomized op: reads its const vars, writes (sum + salt) into its
// mutable vars.  Under a correct grant protocol the engine's execution
// must equal the serial program-order replay exactly.
struct OpSpec {
  std::vector<int> reads, writes;
  int64_t salt;
};

struct OpCtx {
  const OpSpec* spec;
  std::vector<std::atomic<int64_t>>* cells;
  std::atomic<int>* inflight_writes;  // sanity: never two writers at once
};

static void run_op(void* param) {
  OpCtx* ctx = static_cast<OpCtx*>(param);
  int64_t sum = ctx->spec->salt;
  for (int v : ctx->spec->reads)
    sum += (*ctx->cells)[v].load(std::memory_order_relaxed);
  for (int v : ctx->spec->writes) {
    int before = ctx->inflight_writes[v].fetch_add(1);
    assert(before == 0 && "two writers overlapped on one var");
    (*ctx->cells)[v].store(sum, std::memory_order_relaxed);
    ctx->inflight_writes[v].fetch_sub(1);
  }
}

static void randomized_replay(int n_threads, int n_vars, int n_ops) {
  // build a deterministic random workload
  std::vector<OpSpec> specs(n_ops);
  for (auto& s : specs) {
    int n_r = static_cast<int>(next_rand() % 3);
    int n_w = 1 + static_cast<int>(next_rand() % 2);
    for (int i = 0; i < n_r; ++i)
      s.reads.push_back(static_cast<int>(next_rand() % n_vars));
    for (int i = 0; i < n_w; ++i) {
      int v = static_cast<int>(next_rand() % n_vars);
      bool dup = false;
      for (int w : s.writes) dup |= (w == v);
      if (!dup) s.writes.push_back(v);
    }
    s.salt = static_cast<int64_t>(next_rand() % 1000);
  }

  // serial reference replay
  std::vector<int64_t> expect(n_vars, 0);
  for (const auto& s : specs) {
    int64_t sum = s.salt;
    for (int v : s.reads) sum += expect[v];
    for (int v : s.writes) expect[v] = sum;
  }

  // engine replay
  void* eng = MXTPUEngineCreate(n_threads);
  std::vector<uint64_t> vars(n_vars);
  for (int i = 0; i < n_vars; ++i) vars[i] = MXTPUEngineNewVar(eng);
  std::vector<std::atomic<int64_t>> cells(n_vars);
  for (auto& c : cells) c.store(0);
  std::vector<std::atomic<int>> inflight(n_vars);
  for (auto& c : inflight) c.store(0);

  std::vector<OpCtx> ctxs(n_ops);
  std::vector<std::vector<uint64_t>> rvars(n_ops), wvars(n_ops);
  for (int i = 0; i < n_ops; ++i) {
    ctxs[i].spec = &specs[i];
    ctxs[i].cells = &cells;
    ctxs[i].inflight_writes = inflight.data();
    for (int v : specs[i].reads) rvars[i].push_back(vars[v]);
    for (int v : specs[i].writes) wvars[i].push_back(vars[v]);
    MXTPUEnginePush(eng, run_op, &ctxs[i], rvars[i].data(),
                    static_cast<int>(rvars[i].size()), wvars[i].data(),
                    static_cast<int>(wvars[i].size()));
  }
  MXTPUEngineWaitForAll(eng);

  for (int v = 0; v < n_vars; ++v) {
    if (cells[v].load() != expect[v]) {
      std::fprintf(stderr,
                   "FAIL replay threads=%d var=%d engine=%lld serial=%lld\n",
                   n_threads, v, static_cast<long long>(cells[v].load()),
                   static_cast<long long>(expect[v]));
      std::exit(1);
    }
  }
  for (uint64_t v : vars) MXTPUEngineDeleteVar(eng, v);
  MXTPUEngineWaitForAll(eng);
  MXTPUEngineFree(eng);
  std::printf("replay threads=%d vars=%d ops=%d OK\n", n_threads, n_vars,
              n_ops);
}

struct WaitCtx {
  std::atomic<int64_t>* cell;
};

static void bump(void* param) {
  static_cast<WaitCtx*>(param)->cell->fetch_add(1);
}

int main() {
  // randomized replay across engine sizes (reference :20-50 pattern)
  for (int threads : {1, 2, 4}) {
    rng_state = 0x9E3779B97F4A7C15ull + threads;
    randomized_replay(threads, 13, 4000);
  }

  // WaitForVar: after it returns, every prior op touching the var ran
  {
    void* eng = MXTPUEngineCreate(4);
    uint64_t var = MXTPUEngineNewVar(eng);
    std::atomic<int64_t> cell{0};
    WaitCtx ctx{&cell};
    const int kOps = 500;
    for (int i = 0; i < kOps; ++i)
      MXTPUEnginePush(eng, bump, &ctx, nullptr, 0, &var, 1);
    MXTPUEngineWaitForVar(eng, var);
    if (cell.load() != kOps) {
      std::fprintf(stderr, "FAIL WaitForVar: %lld of %d ops ran\n",
                   static_cast<long long>(cell.load()), kOps);
      return 1;
    }
    MXTPUEngineDeleteVar(eng, var);
    MXTPUEngineWaitForAll(eng);
    MXTPUEngineFree(eng);
    std::printf("wait-for-var OK\n");
  }

  // push throughput (the reference prints a benchmark figure too)
  {
    void* eng = MXTPUEngineCreate(4);
    uint64_t var = MXTPUEngineNewVar(eng);
    std::atomic<int64_t> cell{0};
    WaitCtx ctx{&cell};
    const int kOps = 20000;
    auto t0 = std::chrono::steady_clock::now();
    for (int i = 0; i < kOps; ++i)
      MXTPUEnginePush(eng, bump, &ctx, nullptr, 0, &var, 1);
    MXTPUEngineWaitForAll(eng);
    double dt = std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - t0)
                    .count();
    std::printf("push throughput: %.0f ops/sec\n", kOps / dt);
    MXTPUEngineDeleteVar(eng, var);
    MXTPUEngineWaitForAll(eng);
    MXTPUEngineFree(eng);
  }

  // shutdown-window pushes: an op body that chains a push from a worker
  // while Shutdown drains must run inline without self-deadlocking
  // (waiting on pending_ would wait on its own in-flight op); an
  // external straggler thread's push must wait for the full drain.
  {
    alarm(30);  // a regression here deadlocks: turn it into a hard fail
    void* eng = MXTPUEngineCreate(2);
    uint64_t var = MXTPUEngineNewVar(eng);
    static std::atomic<int> a_started{0}, release_a{0}, chained{0};
    struct Ctx { void* eng; uint64_t var; };
    static Ctx ctx2;
    ctx2.eng = eng;
    ctx2.var = var;
    auto a_fn = +[](void* p) {
      auto* c = static_cast<Ctx*>(p);
      a_started.store(1);
      while (!release_a.load()) std::this_thread::yield();
      // stopped_ is set by now: this push takes the drained branch on a
      // worker thread mid-drain
      MXTPUEnginePush(c->eng, +[](void*) { chained.fetch_add(1); },
                      nullptr, nullptr, 0, &c->var, 1);
    };
    MXTPUEnginePush(eng, a_fn, &ctx2, nullptr, 0, &var, 1);
    while (!a_started.load()) std::this_thread::yield();
    std::thread shut([eng] { MXTPUEngineShutdown(eng); });
    // give Shutdown time to flip stopped_ and block in WaitForAll on A
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    release_a.store(1);
    shut.join();
    if (chained.load() != 1) {
      std::fprintf(stderr, "FAIL shutdown chain: %d\n", chained.load());
      return 1;
    }
    MXTPUEngineFree(eng);
    alarm(0);
    std::printf("shutdown-window chain OK\n");
  }

  std::printf("ENGINE CPP OK\n");
  return 0;
}
