// Native RecordIO unit test — write/read/skip/seek/byte-range-resync
// through src/recordio.cc's C ABI with no Python in the loop (the
// reference covers this layer from dmlc-core; its wire format is what
// we must keep: magic-framed, length+cflag word, 4-byte padding).
//
// Built and run by `make test-cpp`
// (tests/test_io.py::test_native_recordio_cpp_unit wraps it).
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
void* MXTPURecordIOWriterCreate(const char* path);
int MXTPURecordIOWriterWrite(void* h, const char* data, uint64_t len);
long MXTPURecordIOWriterTell(void* h);
int MXTPURecordIOWriterFree(void* h);
void* MXTPURecordIOReaderCreate(const char* path, long begin, long end);
int MXTPURecordIOReaderSkip(void* h);
long MXTPURecordIOReaderNext(void* h);
const char* MXTPURecordIOReaderData(void* h);
long MXTPURecordIOReaderTell(void* h);
void MXTPURecordIOReaderSeek(void* h, long pos);
void MXTPURecordIOReaderFree(void* h);
}

#define EXPECT(cond, msg) do { \
    if (!(cond)) { \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, msg); \
      std::exit(1); \
    } } while (0)

static std::string record(int i) {
  // varied lengths exercise the 4-byte padding paths (len % 4 == 0..3)
  std::string s = "rec-" + std::to_string(i) + "-";
  s.append(static_cast<size_t>(i % 7), 'x');
  return s;
}

int main(int argc, char** argv) {
  const char* dir = argc > 1 ? argv[1] : "/tmp";
  std::string path = std::string(dir) + "/recordio_test.rec";
  const int kN = 257;

  // write
  void* w = MXTPURecordIOWriterCreate(path.c_str());
  EXPECT(w != nullptr, "writer create");
  std::vector<long> offsets;
  for (int i = 0; i < kN; ++i) {
    offsets.push_back(MXTPURecordIOWriterTell(w));
    std::string s = record(i);
    EXPECT(MXTPURecordIOWriterWrite(w, s.data(), s.size()) == 0, "write");
  }
  long end_pos = MXTPURecordIOWriterTell(w);
  EXPECT(MXTPURecordIOWriterFree(w) == 0, "writer free");

  // sequential read: every record byte-identical
  void* r = MXTPURecordIOReaderCreate(path.c_str(), 0, -1);
  EXPECT(r != nullptr, "reader create");
  for (int i = 0; i < kN; ++i) {
    long len = MXTPURecordIOReaderNext(r);
    std::string want = record(i);
    EXPECT(len == static_cast<long>(want.size()), "record length");
    EXPECT(std::memcmp(MXTPURecordIOReaderData(r), want.data(),
                       want.size()) == 0, "record payload");
  }
  EXPECT(MXTPURecordIOReaderNext(r) == -1, "EOF sentinel");

  // seek to a remembered offset: random access re-read
  MXTPURecordIOReaderSeek(r, offsets[100]);
  {
    long len = MXTPURecordIOReaderNext(r);
    std::string want = record(100);
    EXPECT(len == static_cast<long>(want.size()), "seek length");
    EXPECT(std::memcmp(MXTPURecordIOReaderData(r), want.data(),
                       want.size()) == 0, "seek payload");
  }
  MXTPURecordIOReaderFree(r);

  // skip-based offset scan (~8 bytes/record): offsets must match the
  // writer's record starts exactly
  r = MXTPURecordIOReaderCreate(path.c_str(), 0, -1);
  std::vector<long> scanned;
  for (;;) {
    long pos = MXTPURecordIOReaderTell(r);
    int rc = MXTPURecordIOReaderSkip(r);
    if (rc == -1) break;
    EXPECT(rc == 0, "skip rc");
    scanned.push_back(pos);
  }
  EXPECT(scanned.size() == static_cast<size_t>(kN), "scan count");
  for (int i = 0; i < kN; ++i)
    EXPECT(scanned[i] == offsets[i], "scan offset mismatch");
  MXTPURecordIOReaderFree(r);

  // byte-range shard (num_parts protocol): a reader dropped mid-file
  // resyncs to the next magic and the two halves partition the records
  {
    long mid = (offsets[kN / 2] + offsets[kN / 2 + 1]) / 2;  // mid-record
    void* a = MXTPURecordIOReaderCreate(path.c_str(), 0, mid);
    void* b = MXTPURecordIOReaderCreate(path.c_str(), mid, end_pos);
    int na = 0, nb = 0;
    while (MXTPURecordIOReaderNext(a) >= 0) ++na;
    while (MXTPURecordIOReaderNext(b) >= 0) ++nb;
    // boundary record belongs to exactly one shard
    EXPECT(na + nb == kN, "shards must partition the records");
    EXPECT(na > 0 && nb > 0, "both shards non-empty");
    MXTPURecordIOReaderFree(a);
    MXTPURecordIOReaderFree(b);
  }

  // corruption detection: flip a magic byte, reader reports -2
  {
    FILE* f = fopen(path.c_str(), "r+b");
    fseek(f, offsets[5], SEEK_SET);
    char junk = 0x5A;
    fwrite(&junk, 1, 1, f);
    fclose(f);
    void* c = MXTPURecordIOReaderCreate(path.c_str(), 0, -1);
    long len = 0;
    int i = 0;
    for (; i < kN; ++i) {
      len = MXTPURecordIOReaderNext(c);
      if (len < 0) break;
    }
    EXPECT(len == -2 && i == 5, "corruption must surface as -2 at rec 5");
    MXTPURecordIOReaderFree(c);
  }

  std::remove(path.c_str());
  std::printf("RECORDIO CPP OK\n");
  return 0;
}
