"""Regression fixture: the pre-fix PR-8 PrefetchingIter shutdown race
(io.py before the review fix).

The prefetcher spawns a producer thread that writes the staged batch
attribute in a loop.  The pre-fix ``reset()`` / ``close()`` cleared
that same attribute and flipped the shutdown flag from the main
thread WITHOUT the event handshake (and without joining the
producer): a producer mid-``next()`` could re-stage a batch after the
reset wiped it, resurrecting a consumed batch — or the process could
exit while the producer still touched a half-torn-down iterator.

MXL-Q must flag this with **MXL-Q001** (attribute written on the
producer thread and accessed on the main path with no common lock)
and **MXL-Q004** (the spawned producer is never joined or registered).
This file is lint input only — never imported by the framework or the
tests (``Prefetcher`` here is a stand-in for
``mxnet_tpu.io.PrefetchingIter``).
"""
import threading


class Prefetcher(object):
    def __init__(self, it):
        self._it = it
        self._staged = None
        self._shutdown = False
        # BUG (MXL-Q004): the producer is started but never joined and
        # never handed to a registry — close() just flips a flag and
        # hopes the daemon thread notices before teardown.
        threading.Thread(target=self._produce, daemon=True).start()

    def _produce(self):
        # producer thread: writes the staged slot with no lock
        while not self._shutdown:
            self._staged = next(self._it)

    def next(self):
        # main path: consumes the same slot, also unlocked — a reset
        # racing _produce can resurrect an already-consumed batch
        batch, self._staged = self._staged, None
        return batch

    def reset(self):
        # BUG (MXL-Q001): main-thread wipe of producer-owned state
        self._staged = None
        self._it = iter(self._it)

    def close(self):
        self._shutdown = True
