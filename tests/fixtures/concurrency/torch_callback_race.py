"""Regression fixture: the pre-fix PR-13 torch host-callback race
(plugin/torch_bridge.py before the review fix).

The torch op wrapper runs its ``forward`` body inside
``jax.pure_callback`` — i.e. on XLA's host-callback worker threads, of
which there can be several when the op appears in a pmapped/sharded
computation.  The pre-fix code mutated a plain dict
(``self._stats``) from that callback body while the training loop's
step path read and reset the same dict from the main thread, with no
lock on either side: counters were lost and, under CPython dict
resize, a concurrent read could see a half-populated view.

MXL-Q must flag this with **MXL-Q005** (host-callback body mutates
state that a step-path method accesses, no common lock).  This file is
lint input only — never imported by the framework or the tests
(``TorchOp`` here is a stand-in for
``mxnet_tpu.plugin.torch_bridge.TorchOpWrapper``).
"""


class TorchOp(object):
    host_callback = True    # forward runs inside jax.pure_callback

    def __init__(self):
        self._stats = {}

    def forward(self, x):
        # BUG: executed on the callback worker thread(s); mutates the
        # shared stats dict with no lock while report()/reset_stats()
        # read and clear it from the step path.
        self._stats["calls"] = self._stats.get("calls", 0) + 1
        return x

    def report(self):
        # step-path read of the same dict, also unlocked
        return dict(self._stats)

    def reset_stats(self):
        self._stats = {}
