"""Regression fixture: the pre-fix PR-3 device-0-only grad-norm
sentinel (resilience/sentinel.py before the review fix).

The NaN/overflow sentinel inspected only ``addressable_data(0)`` —
this rank's first local shard — and skipped the optimizer step when
ITS shard looked bad.  Whether a NaN lands in a given shard is
rank-local, so one rank could skip the update (and the gradient
allreduce behind it) while its peers entered the collective: a pod
deadlock on real faults, and a silently-diverged model when the skip
raced the reduce.  The fix accumulates the norm across every local
shard and folds the skip-verdict into the globally-reduced scalar.

MXL-D must flag this with **MXL-D005** (rank-divergent early exit
ahead of a collective).  Lint input only — never imported.
"""


def _allreduce(kv, grads):             # stand-in for the real seam
    raise NotImplementedError


def sentinel_step(kv, grads, apply_update):
    # BUG: .addressable_data(0) is this rank's local shard; the
    # skip-verdict below is therefore a rank-local decision
    shard = grads.addressable_data(0)
    norm = float(abs(shard).sum())
    if norm != norm or norm > 1e6:     # NaN or overflow in MY shard
        return None                    # ...skips the collective below
    reduced = _allreduce(kv, grads)
    return apply_update(reduced)
