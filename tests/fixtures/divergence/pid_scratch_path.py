"""Regression fixture: the pre-fix PR-3 pid-divergent checkpoint
scratch path (parallel/ckpt.py before the review fix).

Every process derived its own scratch directory from ``os.getpid()``
and handed it to the coordinated multi-host save: each rank wrote its
shards into a DIFFERENT directory, so the commit rename only ever saw
rank 0's shards and restores failed on every other host.  The fix
made the scratch path a pure function of the target path + step, the
same string on every rank.

MXL-D must flag this with **MXL-D004** (rank-divergent value flows
into a coordinated path).  This file is lint input only — never
imported by the framework or the tests (``ocp_save`` here is a stand-in
for ``mxnet_tpu.parallel.ckpt.ocp_save``).
"""
import os


def ocp_save(path, tree, step):        # stand-in for the real writer
    raise NotImplementedError


def save_checkpoint_atomic(path, tree, step):
    # BUG: getpid() differs on every rank, so every rank builds a
    # different scratch directory for what must be ONE coordinated save
    scratch = "%s.tmp.%d" % (path, os.getpid())
    ocp_save(scratch, tree, step)
    os.replace(scratch, path)
