"""Regression fixture: the pre-fix PR-3 per-rank barrier-implementation
probe (kvstore.py before the review fix).

Each process probed locally whether ``sync_global_devices`` worked and
chose its barrier implementation from its OWN probe result.  A probe
failing on a subset of ranks split the pod between two different
barrier implementations — half waiting in the XLA device fence, half
in the coordination-service RPC — and the pod deadlocked.  The fix
(``kvstore._decide_barrier_path``) has rank 0 probe once and publish
the verdict through the coordination KV.

MXL-D must flag this with **MXL-D005** (collective gated on
rank-divergent control flow); the probe's try/except also earns
**MXL-D006** (a swallowed collective failure is itself a rank-local
event).  Lint input only — never imported.
"""

_STATE = {"xla_ok": None}


def sync_global_devices(tag):          # stand-ins for the real seams
    raise NotImplementedError


class _Client(object):
    def wait_at_barrier(self, tag, timeout_ms):
        raise NotImplementedError


def global_barrier(tag, client):
    if _STATE["xla_ok"] is None:
        # BUG: every rank probes locally; whether the probe throws is a
        # rank-local fact, so ranks can disagree on the verdict
        try:
            sync_global_devices("mxtpu_probe")
            _STATE["xla_ok"] = True
        except Exception:
            _STATE["xla_ok"] = False
    if _STATE["xla_ok"]:
        sync_global_devices("mxtpu_" + tag)
    else:
        client.wait_at_barrier("mxtpu_" + tag, 600000)
