"""Expected-FAIL fixture for MXL-X002: id()-keyed compiled-program cache.

Distilled from the pre-fix ``Executor._get_fused`` (PR 17): the fused
optimizer step was cached under ``(id(optimizer), compute_dtype)``.
Object identity is recycled after gc — a fresh-but-identical optimizer
misses the cache and relowers the whole fused step (needless retrace),
while a recycled id can falsely hit and run a stale program with the
wrong hyperparameters.  The fix keys on a value fingerprint
(``overlap.cache_key`` over the baked hyperparameters) instead.

The TASK=lint CI loop asserts ``mxlint --retrace`` flags this file with
MXL-X002; if the lint ever goes blind to it, the loop fails.
"""
import os

import jax


class FusedStepCache:
    def __init__(self):
        self._cache = None  # (key, jitted step)

    def _build_step(self, optimizer):
        def step(states, grads, lr):
            return [s + g * lr for s, g in zip(states, grads)]
        return jax.jit(step)

    def get_fused(self, optimizer):
        key = (id(optimizer), os.environ.get("MXNET_COMPUTE_DTYPE", ""))
        if self._cache is None or self._cache[0] != key:
            self._cache = (key, self._build_step(optimizer))
        return self._cache[1]
