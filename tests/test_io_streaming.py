"""Streaming ImageRecordIter tests.

Covers the round-3 pipeline (parity: src/io/iter_image_recordio.cc +
iter_prefetcher.h): offset-index streaming (no full-dataset
materialization), seek-based num_parts/part_index sharding with disjoint
coverage, per-epoch shuffle of offsets, threaded decode through the
dependency engine, raw-record fast path, and flat-RSS iteration.

The multi-GB throughput demonstration (>=3000 rec/s, flat RSS) is gated on
MXTPU_BIG_IO_TEST=1 — the in-suite version uses a few hundred MB.
"""
import os
import resource
import tempfile
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio as rio
from mxnet_tpu.io import ImageRecordIter, _scan_record_offsets


def _write_jpeg_rec(path, n, hw=(48, 56), distinct=None):
    """n jpeg records; header.label = header.id = record index."""
    from mxnet_tpu.image import imencode
    distinct = distinct or n
    rng = np.random.RandomState(0)
    bufs = [imencode(rng.randint(0, 255, hw + (3,), dtype=np.uint8))
            for _ in range(distinct)]
    w = rio.MXRecordIO(path, "w")
    for i in range(n):
        w.write(rio.pack(rio.IRHeader(0, float(i), i, 0),
                         bufs[i % distinct]))
    w.close()


def _write_raw_rec(path, n, shape=(3, 32, 32)):
    rng = np.random.RandomState(0)
    w = rio.MXRecordIO(path, "w")
    for i in range(n):
        img = rng.randint(0, 255, shape, dtype=np.uint8)
        w.write(rio.pack(rio.IRHeader(0, float(i), i, 0), img.tobytes()))
    w.close()


@pytest.fixture(scope="module")
def jpeg_rec():
    tmp = tempfile.mkdtemp()
    path = os.path.join(tmp, "imgs.rec")
    _write_jpeg_rec(path, 101)
    return path


def test_offset_scan_matches_python_fallback(jpeg_rec):
    native = _scan_record_offsets(jpeg_rec, 0, None)
    # force python path
    os.environ["MXTPU_NO_NATIVE"] = "1"
    try:
        pure = _scan_record_offsets(jpeg_rec, 0, None)
    finally:
        del os.environ["MXTPU_NO_NATIVE"]
    assert native.tolist() == pure.tolist()
    assert native.size == 101


def test_streaming_covers_all_records_and_resets(jpeg_rec):
    it = ImageRecordIter(path_imgrec=jpeg_rec, data_shape=(3, 32, 32),
                         batch_size=16, shuffle=True, preprocess_threads=2,
                         seed=3)
    assert it.num_records == 101
    seen = []
    nb = 0
    for b in it:
        nb += 1
        assert b.data[0].shape == (16, 3, 32, 32)
        seen.extend(b.label[0].asnumpy().tolist())
    # 101 records, batch 16, round_batch pads the tail batch by wrapping
    assert nb == 7 and b.pad == 16 * 7 - 101
    assert set(int(x) for x in seen) == set(range(101))
    it.reset()
    assert sum(1 for _ in it) == nb


def test_epoch_shuffle_differs(jpeg_rec):
    def epoch_labels(it):
        out = []
        for b in it:
            arr = b.label[0].asnumpy()
            out.extend(int(x) for x in arr[:16 - (b.pad or 0)])
        return out
    it = ImageRecordIter(path_imgrec=jpeg_rec, data_shape=(3, 32, 32),
                         batch_size=16, shuffle=True, preprocess_threads=2)
    first = epoch_labels(it)
    it.reset()
    second = epoch_labels(it)
    assert sorted(first) == sorted(second) == list(range(101))
    assert first != second          # per-epoch reshuffle of offsets


def test_shard_disjoint_and_complete(jpeg_rec):
    """num_parts/part_index byte-range sharding: disjoint, complete
    (parity: iter_image_recordio.cc:108-133)."""
    num_parts = 4
    shards = []
    for p in range(num_parts):
        it = ImageRecordIter(path_imgrec=jpeg_rec, data_shape=(3, 32, 32),
                             batch_size=8, num_parts=num_parts, part_index=p,
                             preprocess_threads=1)
        seen = set()
        for b in it:
            arr = b.label[0].asnumpy()
            n = 8 - (b.pad or 0)
            seen.update(int(x) for x in arr[:n])
        shards.append(seen)
    for i in range(num_parts):
        for j in range(i + 1, num_parts):
            assert not (shards[i] & shards[j]), (i, j)
    assert set().union(*shards) == set(range(101))


def test_native_python_decode_agree(jpeg_rec):
    """Center-crop, no augmentation: the native kernel and the cv2/PIL
    fallback must produce identical pixels."""
    a = ImageRecordIter(path_imgrec=jpeg_rec, data_shape=(3, 32, 32),
                        batch_size=101, preprocess_threads=1)
    batch_native = next(a).data[0].asnumpy()
    os.environ["MXTPU_NO_NATIVE"] = "1"
    try:
        b = ImageRecordIter(path_imgrec=jpeg_rec, data_shape=(3, 32, 32),
                            batch_size=101, preprocess_threads=1)
        batch_py = next(b).data[0].asnumpy()
    finally:
        del os.environ["MXTPU_NO_NATIVE"]
    # decoders may differ by +-1 in IDCT rounding; require near-identity
    assert np.abs(batch_native - batch_py).mean() < 0.6
    assert (np.abs(batch_native - batch_py) <= 2).mean() > 0.97


def test_mean_scale_and_uint8(jpeg_rec):
    f = ImageRecordIter(path_imgrec=jpeg_rec, data_shape=(3, 32, 32),
                        batch_size=32, mean_r=10.0, mean_g=20.0, mean_b=30.0,
                        scale=0.5, preprocess_threads=1)
    u = ImageRecordIter(path_imgrec=jpeg_rec, data_shape=(3, 32, 32),
                        batch_size=32, dtype="uint8", preprocess_threads=1)
    fb = next(f).data[0].asnumpy()
    ub = next(u).data[0].asnumpy()
    assert ub.dtype == np.uint8
    mean = np.array([10.0, 20.0, 30.0]).reshape(1, 3, 1, 1)
    np.testing.assert_allclose(fb, (ub.astype(np.float32) - mean) * 0.5,
                               atol=1e-5)


def test_raw_record_roundtrip():
    tmp = tempfile.mkdtemp()
    path = os.path.join(tmp, "raw.rec")
    _write_raw_rec(path, 40, shape=(3, 32, 32))
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                         batch_size=8, dtype="uint8", preprocess_threads=2)
    # raw records round-trip exactly
    rng = np.random.RandomState(0)
    want0 = rng.randint(0, 255, (3, 32, 32), dtype=np.uint8)
    got = next(it).data[0].asnumpy()[0]
    np.testing.assert_array_equal(got, want0)


def test_label_width():
    tmp = tempfile.mkdtemp()
    path = os.path.join(tmp, "multi.rec")
    w = rio.MXRecordIO(path, "w")
    for i in range(20):
        lbl = np.arange(4, dtype=np.float32) + i
        w.write(rio.pack(rio.IRHeader(4, lbl, i, 0),
                         np.zeros((3, 8, 8), np.uint8).tobytes()))
    w.close()
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                         batch_size=5, label_width=4, preprocess_threads=1)
    b = next(it)
    assert b.label[0].shape == (5, 4)
    np.testing.assert_allclose(b.label[0].asnumpy()[0],
                               np.arange(4, dtype=np.float32))


def test_abandoned_iterator_is_collected(jpeg_rec):
    """Dropping a non-exhausted iterator must free its producer thread
    (the thread holds the iterator only via weakref)."""
    import gc
    import threading
    import weakref
    before = threading.active_count()
    it = ImageRecordIter(path_imgrec=jpeg_rec, data_shape=(3, 32, 32),
                         batch_size=16, prefetch_buffer=1,
                         preprocess_threads=1)
    next(it)                      # start consuming, then abandon
    ref = weakref.ref(it)
    del it
    gc.collect()
    deadline = time.time() + 5.0
    while time.time() < deadline and (ref() is not None
                                      or threading.active_count() > before):
        time.sleep(0.05)
        gc.collect()
    assert ref() is None
    assert threading.active_count() <= before


def test_streaming_flat_rss_and_rate():
    """RSS must not grow with dataset size (streaming, not materialised);
    raw uint8 path sustains >=1500 rec/s even on a 1-core CI box."""
    tmp = tempfile.mkdtemp()
    path = os.path.join(tmp, "rate.rec")
    big = os.environ.get("MXTPU_BIG_IO_TEST")
    n = 25000 if big else 2500            # ~3.8 GB / ~380 MB of raw pixels
    _write_raw_rec(path, n, shape=(3, 224, 224))
    size_mb = os.path.getsize(path) / 1e6
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 224, 224),
                         batch_size=64, shuffle=True, dtype="uint8",
                         preprocess_threads=4)

    def one_pass():
        rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        t0 = time.time()
        cnt = 0
        for b in it:
            cnt += 64
        dt = time.time() - t0
        rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return cnt / dt, (rss1 - rss0) / 1024.0

    rate, grow_mb = one_pass()
    # flat RSS: growth must be far below dataset size (buffers only)
    assert grow_mb < max(150, size_mb * 0.15), \
        "RSS grew %.0f MB on a %.0f MB dataset" % (grow_mb, size_mb)
    floor = 3000 if big else 1000     # in-suite floor is conservative:
    # the CI box has one core; a cold page cache can halve the first
    # pass, so retry once warm before judging the rate
    if rate < floor:
        it.reset()
        rate, _ = one_pass()
    assert rate >= floor, "only %.0f rec/s" % rate


def test_streamed_training_on_sharded_mesh(tmp_path):
    """Integration of the round's two big pieces: ImageRecordIter (raw
    uint8 streaming) feeding a multi-device Module whose fused step runs
    on the mesh with in-step all-reduce — the bench's chip path."""
    import mxnet_tpu as mx
    path = str(tmp_path / "train.rec")
    rng = np.random.RandomState(0)
    w = rio.MXRecordIO(path, "w")
    # class = brightness of the raw image
    for i in range(128):
        k = i % 2
        img = np.full((3, 16, 16), 60 if k == 0 else 190, np.uint8)
        img += rng.randint(0, 40, img.shape).astype(np.uint8)
        w.write(rio.pack(rio.IRHeader(0, float(k), i, 0), img.tobytes()))
    w.close()

    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 16, 16),
                         batch_size=32, shuffle=True, dtype="uint8",
                         preprocess_threads=2)
    data = mx.sym.Variable("data")
    # normalize ON DEVICE (uint8 in, f32 math) — the fused-step pattern
    net = mx.sym.Cast(data, dtype="float32")
    net = (net - 128.0) / 64.0
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=8)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2)
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    ctxs = [mx.cpu(i) for i in range(8)]
    mod = mx.mod.Module(net, context=ctxs)
    mod.fit(it, kvstore="device", optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            initializer=mx.init.Xavier(), num_epoch=3)
    assert mod._exec_group.sharded
    assert mod._exec_group.execs[0]._n_fused_step > 0
    it.reset()
    metric = mx.metric.Accuracy()
    score = dict(mod.score(it, metric))
    assert score["accuracy"] > 0.95, score


def test_prefetching_iter_wraps_streaming_iter(jpeg_rec):
    """The reference stacks PrefetcherIter on top of the record iterator;
    the composition must preserve batches and reset cleanly."""
    from mxnet_tpu.io import PrefetchingIter
    base = ImageRecordIter(path_imgrec=jpeg_rec, data_shape=(3, 32, 32),
                           batch_size=16, preprocess_threads=2)
    it = PrefetchingIter(base)
    n1 = 0
    for b in it:
        assert b.data[0].shape == (16, 3, 32, 32)
        n1 += 1
    it.reset()
    n2 = sum(1 for _ in it)
    assert n1 == n2 == 7


def test_decode_cost_regression():
    """Per-record native decode+augment+normalize budget (VERDICT r3 #10):
    the reference publishes >1000 img/s on 4 threads (~4 ms/record/core,
    docs/how_to/perf.md:12-14); this box measured ~900/s single-core in
    round 3 (~1.1 ms/record at 224px).  Assert a GENEROUS 8 ms/record on
    ImageNet-shaped records so a silent 7x regression (e.g. losing the
    native kernel and degrading to the GIL-bound cv2 path at scale, or an
    accidental extra copy) fails the suite while CI noise does not."""
    from mxnet_tpu.libinfo import find_lib
    if find_lib() is None:
        pytest.skip("native decode kernel unavailable on this host")
    tmp = tempfile.mkdtemp()
    path = os.path.join(tmp, "big.rec")
    n = 64
    _write_jpeg_rec(path, n, hw=(256, 256), distinct=8)
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 224, 224),
                         batch_size=16, preprocess_threads=1,
                         prefetch_buffer=2)
    # warm one epoch (spool/open/first-touch costs out of the timing)
    for _ in it:
        pass
    it.reset()
    t0 = time.perf_counter()
    nrec = 0
    for b in it:
        nrec += b.data[0].shape[0] - (b.pad or 0)
    dt = time.perf_counter() - t0
    per_record_ms = dt / nrec * 1e3
    assert per_record_ms < 8.0, (
        "decode+augment regressed: %.2f ms/record (budget 8 ms)"
        % per_record_ms)
