"""CLI tools coverage (parity: the reference's tools/ family is exercised
by its nightly scripts; here each tool gets a direct test)."""
import pytest
import os
import sys

import numpy as np

import mxnet_tpu as mx
# shared hermetic-subprocess runner (strips the TPU plugin that would
# hang worker init; see the rationale comment there)
from test_examples import _run, REPO as ROOT


def _run_tool(*argv, timeout=240):
    return _run(ROOT, *argv, timeout=timeout)


def test_parse_log(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(
        "2026-01-01 INFO Epoch[0] Train-accuracy=0.51\n"
        "2026-01-01 INFO Epoch[0] Time cost=12.3\n"
        "2026-01-01 INFO Epoch[0] Validation-accuracy=0.55\n"
        "2026-01-01 INFO Epoch[1] Train-accuracy=0.81\n"
        "2026-01-01 INFO Epoch[1] Time cost=11.9\n"
        "2026-01-01 INFO Epoch[1] Validation-accuracy=0.78\n")
    proc = _run_tool(os.path.join(ROOT, "tools", "parse_log.py"), str(log))
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "0.81" in out and "0.78" in out and "11.9" in out


def test_im2rec_pack_raw_roundtrip(tmp_path):
    """--pack-raw CHW records stream back through ImageRecordIter's
    zero-decode path."""
    from mxnet_tpu.image import imencode
    root = tmp_path / "imgs"
    (root / "cat").mkdir(parents=True)
    (root / "dog").mkdir(parents=True)
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        for i in range(3):
            img = rng.randint(0, 255, (20, 20, 3), np.uint8)
            with open(root / cls / ("%d.png" % i), "wb") as f:
                f.write(imencode(img, img_fmt=".png"))
    prefix = str(tmp_path / "ds")
    p = _run_tool(os.path.join(ROOT, "tools", "im2rec.py"), prefix,
                  str(root), "--make-list", "--val-ratio", "0")
    assert p.returncode == 0, p.stderr
    p = _run_tool(os.path.join(ROOT, "tools", "im2rec.py"), prefix,
                  str(root), "--list", prefix + "_train.lst",
                  "--pack-raw", "3", "16", "16")
    assert p.returncode == 0, p.stderr
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 16, 16), batch_size=6,
                               dtype="uint8", preprocess_threads=1)
    batch = next(it)
    assert batch.data[0].shape == (6, 3, 16, 16)
    labels = sorted(set(int(x) for x in batch.label[0].asnumpy()))
    assert labels == [0, 1]


def test_bandwidth_measure_cpu():
    p = _run_tool(os.path.join(ROOT, "tools", "bandwidth", "measure.py"),
                  "--sizes", "1048576", "--repeat", "2")
    assert p.returncode == 0, p.stderr[-800:]
    assert "GB/s" in p.stdout or "gbps" in p.stdout.lower() or \
        "bandwidth" in p.stdout.lower(), p.stdout


def test_launch_print_mode():
    p = _run_tool(os.path.join(ROOT, "tools", "launch.py"), "-n", "2",
                  "--launcher", "print", "python", "train.py")
    assert p.returncode == 0, p.stderr
    assert p.stdout.count("MXTPU_WORKER_RANK") == 2
    assert "MXTPU_NUM_WORKERS=2" in p.stdout


def test_amalgamation_standalone_predict(tmp_path):
    """VERDICT r3 #9: the amalgamation artifact predicts from a scratch
    dir through a consumer that NEVER imports mxnet_tpu (StableHLO export
    + params.npz + standalone predict.py), matching the in-framework
    Predictor bit-for-bit."""
    import json
    import subprocess
    rng = np.random.RandomState(0)

    # a small trained-ish checkpoint
    net = mx.models.get_mlp(num_classes=3, hidden=(8,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 6))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.init.Uniform(0.3))
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 0)

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import amalgamation
        art = amalgamation.build(prefix, 0, {"data": (2, 6)},
                                 str(tmp_path / "artifact"))
    finally:
        sys.path.pop(0)
    names = set(os.listdir(art))
    assert {"model.stablehlo", "params.npz", "meta.json",
            "predict.py", "mlp-symbol.json", "mlp-0000.params"} <= names

    x = rng.rand(2, 6).astype(np.float32)
    np.save(str(tmp_path / "in.npy"), x)

    # reference output through the in-framework Predictor
    from mxnet_tpu.predictor import Predictor
    pred = Predictor(os.path.join(art, "mlp-symbol.json"),
                     os.path.join(art, "mlp-0000.params"),
                     {"data": (2, 6), "softmax_label": (2,)})
    pred.set_input("data", x)
    pred.forward()
    want = pred.get_output(0)

    # standalone consumer: scratch cwd, NO repo on PYTHONPATH
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(art, "predict.py"),
         str(tmp_path / "in.npy")],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr[-1500:]
    assert "output[0] shape=(2, 3)" in proc.stdout
    # numeric check: rerun the exported program in-process
    sys.path.insert(0, art)
    try:
        import importlib
        import predict as standalone
        importlib.reload(standalone)
        outs = standalone.predict([x])
    finally:
        sys.path.pop(0)
    np.testing.assert_allclose(np.asarray(outs[0]), want, rtol=1e-5,
                               atol=1e-6)


def test_native_im2rec_byte_exact_and_fast(tmp_path):
    """Native multi-threaded im2rec (reference tools/im2rec.cc):
    unchanged=1 output is byte-exact with im2rec.py --raw; the
    decode->resize->crop->re-encode path packs an MNIST-sized set over
    3k rec/s (the reference's packed-RecordIO story, BASELINE.md)."""
    import re
    import shutil
    import subprocess
    import time

    binary = os.path.join(ROOT, "tools", "im2rec")
    if not os.path.exists(binary):
        r = subprocess.run(["make", "-s", "tools/im2rec"], cwd=ROOT,
                           capture_output=True, text=True, timeout=300)
        if r.returncode != 0 or not os.path.exists(binary):
            import pytest
            pytest.skip("native im2rec unavailable (no toolchain/libjpeg)")

    from mxnet_tpu.image import imencode, imdecode_bytes
    from mxnet_tpu import recordio as rio
    root = tmp_path / "imgs"
    root.mkdir()
    rs = np.random.RandomState(0)
    n_img = 384
    with open(tmp_path / "a.lst", "w") as f:
        for i in range(n_img):
            img = rs.randint(0, 255, (28, 28, 3), np.uint8)
            (root / ("i%04d.jpg" % i)).write_bytes(imencode(img))
            f.write("%d\t%d\ti%04d.jpg\n" % (i, i % 10, i))

    r = _run(os.path.join(ROOT, "tools"), "im2rec.py",
             str(tmp_path / "py"), str(root),
             "--list", str(tmp_path / "a.lst"), "--raw")
    assert r.returncode == 0, r.stderr[-1000:]
    r = subprocess.run([binary, str(tmp_path / "a.lst"), str(root),
                        str(tmp_path / "cc.rec"), "unchanged=1"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-1000:]
    assert (tmp_path / "py.rec").read_bytes() == \
        (tmp_path / "cc.rec").read_bytes()

    # best-of-2 for the rate: absorbs one cold-cache/loaded-box run so
    # the >3k gate tests the packer, not the CI weather
    rate = 0
    for _ in range(2):
        r = subprocess.run([binary, str(tmp_path / "a.lst"), str(root),
                            str(tmp_path / "enc.rec"),
                            "resize=24", "center_crop=1", "quality=90"],
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr[-1000:]
        if "without libjpeg" in r.stderr:
            import pytest
            pytest.skip("im2rec built without libjpeg: no re-encode path")
        m = re.search(r"at (\d+) rec/s", r.stdout)
        assert m, r.stdout
        rate = max(rate, int(m.group(1)))
    reader = rio.MXRecordIO(str(tmp_path / "enc.rec"), "r")
    n = 0
    while True:
        item = reader.read()
        if item is None:
            break
        hdr, buf = rio.unpack(item)
        assert hdr.id == n and float(hdr.label) == n % 10
        assert imdecode_bytes(buf).shape == (24, 24, 3)
        n += 1
    assert n == n_img
    assert rate > 3000, "packed at %d rec/s (target >3000)" % rate


def test_native_im2rec_nsplit_pack_label(tmp_path):
    """nsplit/part slicing and pack_label multi-label records match the
    python packer's wire format."""
    import subprocess

    binary = os.path.join(ROOT, "tools", "im2rec")
    if not os.path.exists(binary):
        import pytest
        pytest.skip("native im2rec unavailable")

    from mxnet_tpu.image import imencode
    from mxnet_tpu import recordio as rio
    root = tmp_path / "imgs"
    root.mkdir()
    rs = np.random.RandomState(1)
    with open(tmp_path / "m.lst", "w") as f:
        for i in range(10):
            img = rs.randint(0, 255, (16, 16, 3), np.uint8)
            (root / ("i%d.jpg" % i)).write_bytes(imencode(img))
            f.write("%d\t%d\t%d\ti%d.jpg\n" % (i, i, i * 2, i))

    # part 1 of 2 -> records 5..9; pack_label keeps both labels
    r = subprocess.run([binary, str(tmp_path / "m.lst"), str(root),
                        str(tmp_path / "p1.rec"), "unchanged=1",
                        "label_width=2", "pack_label=1",
                        "nsplit=2", "part=1"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr[-1000:]
    reader = rio.MXRecordIO(str(tmp_path / "p1.rec"), "r")
    ids = []
    while True:
        item = reader.read()
        if item is None:
            break
        hdr, _ = rio.unpack(item)
        assert list(hdr.label) == [hdr.id, hdr.id * 2]
        ids.append(hdr.id)
    assert ids == [5, 6, 7, 8, 9]


def test_native_im2rec_color_keep(tmp_path):
    """color=-1 keeps the source colorspace: a grayscale JPEG stays
    1-channel through the re-encode (reference IMREAD_UNCHANGED)."""
    import io as _io
    import subprocess

    binary = os.path.join(ROOT, "tools", "im2rec")
    if not os.path.exists(binary):
        import pytest
        pytest.skip("native im2rec unavailable")
    from PIL import Image
    from mxnet_tpu import recordio as rio

    root = tmp_path / "imgs"
    root.mkdir()
    rs = np.random.RandomState(2)
    img = Image.fromarray(rs.randint(0, 255, (20, 20), np.uint8), "L")
    img.save(root / "g.jpg", "JPEG")
    (tmp_path / "g.lst").write_text("0\t0\tg.jpg\n")
    r = subprocess.run([binary, str(tmp_path / "g.lst"), str(root),
                        str(tmp_path / "g.rec"), "color=-1", "quality=90"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr[-1000:]
    if "without libjpeg" in r.stderr:
        import pytest
        pytest.skip("im2rec built without libjpeg")
    reader = rio.MXRecordIO(str(tmp_path / "g.rec"), "r")
    _hdr, buf = rio.unpack(reader.read())
    assert Image.open(_io.BytesIO(buf)).mode == "L"


@pytest.mark.slow
def test_pjrt_predict_runner(tmp_path):
    """Python-free deployment spike (reference amalgamation/
    mxnet_predict0.cc): the amalgamation bundle carries raw StableHLO
    bytecode + a TLV parameter pack, and the plain-C PJRT runner builds,
    links against libc only, loads a real PJRT plugin, and either runs
    or fails loudly at Client_Create when no device exists."""
    import json
    import struct
    import subprocess

    r = subprocess.run(["make", "-s", "example-pjrt"], cwd=ROOT,
                       capture_output=True, text=True, timeout=300)
    binary = os.path.join(ROOT, "example", "cpp", "pjrt-predict")
    if r.returncode != 0 or not os.path.exists(binary):
        import pytest
        pytest.skip("pjrt_c_api.h / toolchain unavailable: %s"
                    % r.stderr[-200:])

    # no libpython in the runner (the whole point)
    ldd = subprocess.run(["ldd", binary], capture_output=True, text=True)
    assert "libpython" not in ldd.stdout

    # artifact: model.mlir is MLIR bytecode; params.bin covers every
    # non-input arg in meta arg_order
    net = mx.models.get_mlp(num_classes=3, hidden=(8,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 6))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.init.Uniform(0.3))
    mod.save_checkpoint(str(tmp_path / "mlp"), 0)
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import amalgamation
        art = amalgamation.build(str(tmp_path / "mlp"), 0,
                                 {"data": (2, 6)},
                                 str(tmp_path / "artifact"))
    finally:
        sys.path.pop(0)
    assert open(os.path.join(art, "model.mlir"), "rb").read(4) == \
        b"ML\xefR"
    meta = json.load(open(os.path.join(art, "meta.json")))
    buf = open(os.path.join(art, "params.bin"), "rb").read()
    assert buf[:4] == b"MXTB"
    _ver, cnt = struct.unpack_from("<II", buf, 4)
    off, seen = 12, []
    for _ in range(cnt):
        nl, = struct.unpack_from("<I", buf, off); off += 4
        seen.append(buf[off:off + nl].decode()); off += nl
        _code, ndim = struct.unpack_from("<II", buf, off); off += 8 + 8 * ndim
        nb, = struct.unpack_from("<Q", buf, off); off += 8 + nb
    assert off == len(buf)
    assert sorted(seen) == sorted(n for n in meta["arg_order"]
                                  if n not in meta["input_names"])

    np.save(str(tmp_path / "in.npy"),
            np.random.RandomState(0).rand(2, 6).astype(np.float32))

    # bad plugin: loud, immediate
    r = subprocess.run([binary, art, str(tmp_path / "in.npy"),
                        "/nonexistent-plugin.so"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode != 0 and "dlopen" in r.stderr

    # real plugin when present: full predict on a TPU host, else the
    # pinned clean Client_Create failure (TPU-less box)
    libtpu = os.environ.get("MXTPU_PJRT_PLUGIN")
    if libtpu is None:
        try:
            import libtpu as _libtpu_mod
            libtpu = os.path.join(
                os.path.dirname(_libtpu_mod.__file__), "libtpu.so")
        except ImportError:
            libtpu = None
    if libtpu and os.path.exists(libtpu):
        r = subprocess.run([binary, art, str(tmp_path / "in.npy"),
                            libtpu, str(tmp_path / "out.npy")],
                           capture_output=True, text=True, timeout=240)
        assert "PJRT C API v" in r.stdout
        if r.returncode == 0:
            assert "PJRT predict OK" in r.stdout
            got = np.load(str(tmp_path / "out.npy"))
            assert got.shape == (2, 3)
        else:
            assert "Client_Create failed" in r.stderr


def test_mfu_audit_smoke():
    """tools/mfu_audit.py: structural audit runs without executing a
    step and reports the bf16/transpose/donation facts as JSON."""
    import json
    p = _run_tool(os.path.join(ROOT, "tools", "mfu_audit.py"),
                  "--batch", "4", "--layers", "18", timeout=600)
    assert p.returncode == 0, p.stderr[-1500:]
    line = [l for l in p.stdout.splitlines() if l.startswith("{")][-1]
    audit = json.loads(line)["audit"][0]
    assert audit["conv_count"] > 0
    assert set(audit["conv_dtypes"]) == {"bf16"}  # bf16 end-to-end
    assert audit["logical_transposes"] <= 5
    assert audit["donation_alias_bytes"] > 0
    assert audit["model_tflops_per_step"] > 0


# ----------------------------------------------------------------------
# tools/mxlint.py: the static graph linter CLI
# ----------------------------------------------------------------------
def _mxlint(*argv, timeout=240):
    return _run_tool(os.path.join(ROOT, "tools", "mxlint.py"), *argv,
                     timeout=timeout)


def test_mxlint_list_rules():
    p = _mxlint("--list-rules")
    assert p.returncode == 0, p.stderr
    assert "MXL-S002" in p.stdout and "MXL-L001" in p.stdout


def test_mxlint_clean_json_exits_zero(tmp_path):
    path = tmp_path / "mlp.json"
    mx.models.get_mlp().save(str(path))
    p = _mxlint(str(path), "--shapes", "data=(8,784)",
                "--fail-on=warning")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "clean" in p.stdout


def test_mxlint_shape_conflict_exits_one(tmp_path):
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=5, name="fc")
    (fc + data).save(str(tmp_path / "bad.json"))
    p = _mxlint(str(tmp_path / "bad.json"), "--shapes", "data=(8,784)")
    assert p.returncode == 1, p.stdout + p.stderr
    assert "MXL-S002" in p.stdout
    # --fail-on=never reports but never gates
    p = _mxlint(str(tmp_path / "bad.json"), "--shapes", "data=(8,784)",
                "--fail-on=never")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "MXL-S002" in p.stdout


def test_mxlint_dead_node_in_saved_graph(tmp_path):
    import json as _json
    graph = _json.loads(mx.models.get_mlp().tojson())
    n = len(graph["nodes"])
    graph["nodes"].append({"op": "null", "name": "orphan_var",
                           "attr": {}, "inputs": []})
    graph["nodes"].append({"op": "Flatten", "name": "orphan_op",
                           "attr": {}, "inputs": [[n, 0]]})
    graph["arg_nodes"].append(n)
    path = tmp_path / "dead.json"
    path.write_text(_json.dumps(graph))
    p = _mxlint(str(path), "--fail-on=warning", "--format", "json")
    assert p.returncode == 1, p.stdout + p.stderr
    doc = _json.loads(p.stdout)
    ids = {i["rule_id"] for t in doc for i in t["issues"]}
    assert {"MXL-G001", "MXL-G002"} <= ids


def test_mxlint_model_sweep_single():
    p = _mxlint("--model", "mlp", "--fail-on=warning")
    assert p.returncode == 0, p.stdout + p.stderr


def test_mxlint_usage_errors_exit_two(tmp_path):
    p = _mxlint("--model", "no_such_model")
    assert p.returncode == 2, p.stdout + p.stderr
    p = _mxlint(str(tmp_path / "missing.json"))
    assert p.returncode == 2, p.stdout + p.stderr


def test_mxlint_mesh_cost_report():
    """The acceptance run: transformer under dp=2,tp=2 exits 0 at
    --fail-on=error and prints the reshard + peak-HBM report."""
    p = _mxlint("--model", "transformer", "--mesh", "dp=2,tp=2",
                "--fail-on=error")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "MXL-C003" in p.stdout          # one-sided contractions listed
    assert "MXL-P004" in p.stdout          # row-parallel psum listed
    assert "communication (per device" in p.stdout
    assert "over ICI" in p.stdout
    assert "peak HBM estimate" in p.stdout
    assert "training mode" in p.stdout


def test_mxlint_mesh_json_cost():
    import json as _json
    p = _mxlint("--model", "mlp", "--mesh", "dp=2,tp=2", "--format",
                "json", "--fail-on=error")
    assert p.returncode == 0, p.stdout + p.stderr
    doc = _json.loads(p.stdout)
    cost = doc[0]["cost"]
    assert cost["memory"]["peak_bytes"] > 0
    assert cost["memory"]["mode"] == "training"
    assert cost["communication"]["total_bytes"] >= 0


def test_mxlint_hbm_budget_gates():
    p = _mxlint("--model", "mlp", "--mesh", "dp=2,tp=2",
                "--hbm-gb", "0.000001")
    assert p.returncode == 1, p.stdout + p.stderr
    assert "MXL-M001" in p.stdout
    p = _mxlint("--model", "mlp", "--mesh", "dp=2,tp=2", "--hbm-gb", "16")
    assert p.returncode == 0, p.stdout + p.stderr


def test_mxlint_wildcard_select_and_skip():
    p = _mxlint("--model", "transformer", "--mesh", "dp=2,tp=2",
                "--select", "MXL-P*", "--fail-on=warning")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "MXL-P004" in p.stdout
    assert "MXL-C003" not in p.stdout
    p = _mxlint("--model", "transformer", "--mesh", "dp=2,tp=2",
                "--skip", "MXL-C*", "--fail-on=warning")
    assert "MXL-C003" not in p.stdout
    assert "MXL-P004" in p.stdout


def test_mxlint_github_annotations():
    p = _mxlint("--model", "transformer", "--mesh", "dp=2,tp=2",
                "--format", "github")
    assert p.returncode == 0, p.stdout + p.stderr
    lines = [l for l in p.stdout.splitlines() if l.startswith("::")]
    assert lines, p.stdout
    assert any(l.startswith("::warning title=MXL-C003") for l in lines)
    assert any("model:transformer" in l for l in lines)
    # annotations are single-line even for multi-line messages
    assert all("\n" not in l for l in lines)


def test_mxlint_sharding_flag():
    # explicit rules override the default policy: a one-sided
    # row-parallel weight turns into MXL-C003 warnings
    p = _mxlint("--model", "mlp", "--mesh", "dp=2,tp=2",
                "--sharding", r".*_weight=(None,tp);.*_bias=-",
                "--fail-on=error")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "MXL-C003" in p.stdout
    # a bad spec is a usage error
    p = _mxlint("--model", "mlp", "--mesh", "dp=2,tp=2",
                "--sharding", "no-equals-sign-here")
    assert p.returncode == 2, p.stdout + p.stderr


def test_mxlint_bad_mesh_is_usage_error():
    p = _mxlint("--model", "mlp", "--mesh", "dp=banana")
    assert p.returncode == 2, p.stdout + p.stderr
    p = _mxlint("--model", "mlp", "--mesh", "dp")
    assert p.returncode == 2, p.stdout + p.stderr


def test_mxlint_kvstore_audit():
    p = _mxlint("--model", "mlp", "--mesh", "dp=64,tp=4",
                "--kvstore", "device")
    assert p.returncode == 1, p.stdout + p.stderr
    assert "MXL-C001" in p.stdout
    p = _mxlint("--model", "mlp", "--mesh", "dp=64,tp=4",
                "--kvstore", "dist_sync")
    assert p.returncode == 0, p.stdout + p.stderr


def test_parse_shapes_edge_cases():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import mxlint
        # whitespace everywhere is tolerated
        assert mxlint.parse_shapes([" data = ( 8 , 784 ) "]) == \
            {"data": (8, 784)}
        # several entries in one flag, trailing comma, bare int
        assert mxlint.parse_shapes(["a=(2,3),b=(4,),c=5,"]) == \
            {"a": (2, 3), "b": (4,), "c": (5,)}
        # nested tuples are not shapes
        import pytest
        with pytest.raises(ValueError, match="flat tuple"):
            mxlint.parse_shapes(["data=((2,3),4)"])
        with pytest.raises(ValueError):
            mxlint.parse_shapes(["data=(a,b)"])
    finally:
        sys.path.pop(0)


def test_mxlint_kernel_roofline_sweep():
    """The CI leg: chip-free MXL-K + MXL-R over resnet at a training
    batch size — comma-joined wildcard select, roofline report, and no
    errors (the registered flash kernel spec must lint clean)."""
    p = _mxlint("--model", "resnet", "--select", "MXL-K*,MXL-R*",
                "--shapes", "data=(256,3,224,224)", "--roofline")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "static roofline" in p.stdout
    assert "MFU ceiling" in p.stdout
    assert "MXL-R005" in p.stdout


def test_mxlint_baseline_suppression(tmp_path):
    base = str(tmp_path / "lint_baseline.json")
    args = ("--model", "resnet", "--select", "MXL-R*",
            "--shapes", "data=(256,3,224,224)", "--fail-on=info")
    p = _mxlint(*args)
    assert p.returncode == 1, p.stdout + p.stderr     # findings exist
    p = _mxlint(*args, "--baseline", base, "--update-baseline")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "recorded" in p.stdout
    # same sweep against the baseline: all findings suppressed
    p = _mxlint(*args, "--baseline", base)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "suppressed" in p.stdout and "clean" in p.stdout
    # a NEW finding (different batch -> different messages) still fails
    p = _mxlint("--model", "resnet", "--select", "MXL-R*",
                "--shapes", "data=(512,3,224,224)", "--fail-on=info",
                "--baseline", base)
    assert p.returncode == 1, p.stdout + p.stderr


# ----------------------------------------------------------------------
# mxlint --distributed: the MXL-D family through the CLI
# ----------------------------------------------------------------------
FIXDIR = os.path.join(ROOT, "tests", "fixtures", "divergence")


def test_mxlint_distributed_fixtures_fail():
    """The three pre-fix PR-3 regression fixtures must flag with their
    documented rule ids and fail the sweep at --fail-on=error."""
    p = _mxlint("--distributed", FIXDIR, "--fail-on=error",
                "--format=github")
    assert p.returncode == 1, p.stdout + p.stderr
    out = p.stdout
    assert "MXL-D004" in out and "pid_scratch_path.py" in out
    assert "MXL-D005" in out and "per_rank_barrier_probe.py" in out
    assert "device0_sentinel.py" in out
    # annotations carry file=/line= params from the anchors
    assert "::error file=" in out and ",line=" in out


def test_mxlint_distributed_self_lint_clean():
    """The fixed framework source is the clean bill the ISSUE demands."""
    p = _mxlint("--distributed", os.path.join(ROOT, "mxnet_tpu"),
                "--fail-on=error")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "sources: clean" in p.stdout


def test_mxlint_distributed_model_graph():
    """--world-size activates the graph-level trace diff on models
    (clean: the zoo has no rank-conditional collectives)."""
    p = _mxlint("--model", "mlp", "--distributed", "--world-size", "4",
                "--fail-on=error")
    assert p.returncode == 0, p.stdout + p.stderr


def _mxlint_module():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_mxlint_under_test", os.path.join(ROOT, "tools", "mxlint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_mxlint_diff_targets_mapping():
    m = _mxlint_module()
    picked = m.diff_targets([
        "graphs/saved.json",
        "mxnet_tpu/models/resnet.py",
        "mxnet_tpu/kvstore.py",
        "mxnet_tpu/models/nosuchmodel.py",
        "tools/mxlint.py",            # outside mxnet_tpu: not source-linted
        "docs/graph_lint.md",
    ])
    assert picked["files"] == ["graphs/saved.json"]
    assert picked["models"] == ["resnet"]
    assert "mxnet_tpu/kvstore.py" in picked["sources"]
    assert "mxnet_tpu/models/resnet.py" in picked["sources"]
    assert "tools/mxlint.py" not in picked["sources"]


def test_mxlint_diff_no_changes_exits_zero(tmp_path):
    """--diff in a repo with an empty diff reports nothing to lint."""
    import subprocess
    repo = tmp_path / "repo"
    repo.mkdir()
    env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
    for cmd in (["git", "init", "-q"],
                ["git", "commit", "-q", "--allow-empty", "-m", "x"]):
        subprocess.run(cmd, cwd=str(repo), env=env, check=True)
    p = _run(str(repo), os.path.join(ROOT, "tools", "mxlint.py"),
             "--diff", "HEAD", "--fail-on=error")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "no lintable changes" in p.stdout


def test_mxlint_baseline_anchor_keys(tmp_path):
    """Divergence findings baseline on file:qualname anchors — and a
    legacy record without anchor fields still loads."""
    m = _mxlint_module()
    base = str(tmp_path / "base.json")
    fx = os.path.join(FIXDIR, "pid_scratch_path.py")
    p = _mxlint("--distributed", fx, "--baseline", base,
                "--update-baseline")
    assert p.returncode == 0, p.stdout + p.stderr
    import json as _json
    with open(base) as f:
        doc = _json.load(f)
    assert any((e.get("anchor") or "").endswith(
        "pid_scratch_path.py:save_checkpoint_atomic")
        for e in doc["findings"])
    # baselined: the same lint now passes
    p = _mxlint("--distributed", fx, "--baseline", base,
                "--fail-on=error")
    assert p.returncode == 0, p.stdout + p.stderr
    # legacy record shape (node only, no anchor) must still load
    with open(base, "w") as f:
        _json.dump({"version": 1, "findings": [
            {"target": "model:x", "rule_id": "MXL-R001",
             "severity": "info", "node": "fc1", "message": "m"}]}, f)
    keys = m.load_baseline(base)
    assert m._baseline_key("model:x", "MXL-R001", "fc1", "m") in keys
