"""CLI tools coverage (parity: the reference's tools/ family is exercised
by its nightly scripts; here each tool gets a direct test)."""
import os
import sys

import numpy as np

import mxnet_tpu as mx
# shared hermetic-subprocess runner (strips the TPU plugin that would
# hang worker init; see the rationale comment there)
from test_examples import _run, REPO as ROOT


def _run_tool(*argv, timeout=240):
    return _run(ROOT, *argv, timeout=timeout)


def test_parse_log(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(
        "2026-01-01 INFO Epoch[0] Train-accuracy=0.51\n"
        "2026-01-01 INFO Epoch[0] Time cost=12.3\n"
        "2026-01-01 INFO Epoch[0] Validation-accuracy=0.55\n"
        "2026-01-01 INFO Epoch[1] Train-accuracy=0.81\n"
        "2026-01-01 INFO Epoch[1] Time cost=11.9\n"
        "2026-01-01 INFO Epoch[1] Validation-accuracy=0.78\n")
    proc = _run_tool(os.path.join(ROOT, "tools", "parse_log.py"), str(log))
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "0.81" in out and "0.78" in out and "11.9" in out


def test_im2rec_pack_raw_roundtrip(tmp_path):
    """--pack-raw CHW records stream back through ImageRecordIter's
    zero-decode path."""
    from mxnet_tpu.image import imencode
    root = tmp_path / "imgs"
    (root / "cat").mkdir(parents=True)
    (root / "dog").mkdir(parents=True)
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        for i in range(3):
            img = rng.randint(0, 255, (20, 20, 3), np.uint8)
            with open(root / cls / ("%d.png" % i), "wb") as f:
                f.write(imencode(img, img_fmt=".png"))
    prefix = str(tmp_path / "ds")
    p = _run_tool(os.path.join(ROOT, "tools", "im2rec.py"), prefix,
                  str(root), "--make-list", "--val-ratio", "0")
    assert p.returncode == 0, p.stderr
    p = _run_tool(os.path.join(ROOT, "tools", "im2rec.py"), prefix,
                  str(root), "--list", prefix + "_train.lst",
                  "--pack-raw", "3", "16", "16")
    assert p.returncode == 0, p.stderr
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 16, 16), batch_size=6,
                               dtype="uint8", preprocess_threads=1)
    batch = next(it)
    assert batch.data[0].shape == (6, 3, 16, 16)
    labels = sorted(set(int(x) for x in batch.label[0].asnumpy()))
    assert labels == [0, 1]


def test_bandwidth_measure_cpu():
    p = _run_tool(os.path.join(ROOT, "tools", "bandwidth", "measure.py"),
                  "--sizes", "1048576", "--repeat", "2")
    assert p.returncode == 0, p.stderr[-800:]
    assert "GB/s" in p.stdout or "gbps" in p.stdout.lower() or \
        "bandwidth" in p.stdout.lower(), p.stdout


def test_launch_print_mode():
    p = _run_tool(os.path.join(ROOT, "tools", "launch.py"), "-n", "2",
                  "--launcher", "print", "python", "train.py")
    assert p.returncode == 0, p.stderr
    assert p.stdout.count("MXTPU_WORKER_RANK") == 2
    assert "MXTPU_NUM_WORKERS=2" in p.stdout
