"""CLI tools coverage (parity: the reference's tools/ family is exercised
by its nightly scripts; here each tool gets a direct test)."""
import os
import sys

import numpy as np

import mxnet_tpu as mx
# shared hermetic-subprocess runner (strips the TPU plugin that would
# hang worker init; see the rationale comment there)
from test_examples import _run, REPO as ROOT


def _run_tool(*argv, timeout=240):
    return _run(ROOT, *argv, timeout=timeout)


def test_parse_log(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(
        "2026-01-01 INFO Epoch[0] Train-accuracy=0.51\n"
        "2026-01-01 INFO Epoch[0] Time cost=12.3\n"
        "2026-01-01 INFO Epoch[0] Validation-accuracy=0.55\n"
        "2026-01-01 INFO Epoch[1] Train-accuracy=0.81\n"
        "2026-01-01 INFO Epoch[1] Time cost=11.9\n"
        "2026-01-01 INFO Epoch[1] Validation-accuracy=0.78\n")
    proc = _run_tool(os.path.join(ROOT, "tools", "parse_log.py"), str(log))
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "0.81" in out and "0.78" in out and "11.9" in out


def test_im2rec_pack_raw_roundtrip(tmp_path):
    """--pack-raw CHW records stream back through ImageRecordIter's
    zero-decode path."""
    from mxnet_tpu.image import imencode
    root = tmp_path / "imgs"
    (root / "cat").mkdir(parents=True)
    (root / "dog").mkdir(parents=True)
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        for i in range(3):
            img = rng.randint(0, 255, (20, 20, 3), np.uint8)
            with open(root / cls / ("%d.png" % i), "wb") as f:
                f.write(imencode(img, img_fmt=".png"))
    prefix = str(tmp_path / "ds")
    p = _run_tool(os.path.join(ROOT, "tools", "im2rec.py"), prefix,
                  str(root), "--make-list", "--val-ratio", "0")
    assert p.returncode == 0, p.stderr
    p = _run_tool(os.path.join(ROOT, "tools", "im2rec.py"), prefix,
                  str(root), "--list", prefix + "_train.lst",
                  "--pack-raw", "3", "16", "16")
    assert p.returncode == 0, p.stderr
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 16, 16), batch_size=6,
                               dtype="uint8", preprocess_threads=1)
    batch = next(it)
    assert batch.data[0].shape == (6, 3, 16, 16)
    labels = sorted(set(int(x) for x in batch.label[0].asnumpy()))
    assert labels == [0, 1]


def test_bandwidth_measure_cpu():
    p = _run_tool(os.path.join(ROOT, "tools", "bandwidth", "measure.py"),
                  "--sizes", "1048576", "--repeat", "2")
    assert p.returncode == 0, p.stderr[-800:]
    assert "GB/s" in p.stdout or "gbps" in p.stdout.lower() or \
        "bandwidth" in p.stdout.lower(), p.stdout


def test_launch_print_mode():
    p = _run_tool(os.path.join(ROOT, "tools", "launch.py"), "-n", "2",
                  "--launcher", "print", "python", "train.py")
    assert p.returncode == 0, p.stderr
    assert p.stdout.count("MXTPU_WORKER_RANK") == 2
    assert "MXTPU_NUM_WORKERS=2" in p.stdout


def test_amalgamation_standalone_predict(tmp_path):
    """VERDICT r3 #9: the amalgamation artifact predicts from a scratch
    dir through a consumer that NEVER imports mxnet_tpu (StableHLO export
    + params.npz + standalone predict.py), matching the in-framework
    Predictor bit-for-bit."""
    import json
    import subprocess
    rng = np.random.RandomState(0)

    # a small trained-ish checkpoint
    net = mx.models.get_mlp(num_classes=3, hidden=(8,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 6))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.init.Uniform(0.3))
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 0)

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import amalgamation
        art = amalgamation.build(prefix, 0, {"data": (2, 6)},
                                 str(tmp_path / "artifact"))
    finally:
        sys.path.pop(0)
    names = set(os.listdir(art))
    assert {"model.stablehlo", "params.npz", "meta.json",
            "predict.py", "mlp-symbol.json", "mlp-0000.params"} <= names

    x = rng.rand(2, 6).astype(np.float32)
    np.save(str(tmp_path / "in.npy"), x)

    # reference output through the in-framework Predictor
    from mxnet_tpu.predictor import Predictor
    pred = Predictor(os.path.join(art, "mlp-symbol.json"),
                     os.path.join(art, "mlp-0000.params"),
                     {"data": (2, 6), "softmax_label": (2,)})
    pred.set_input("data", x)
    pred.forward()
    want = pred.get_output(0)

    # standalone consumer: scratch cwd, NO repo on PYTHONPATH
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(art, "predict.py"),
         str(tmp_path / "in.npy")],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr[-1500:]
    assert "output[0] shape=(2, 3)" in proc.stdout
    # numeric check: rerun the exported program in-process
    sys.path.insert(0, art)
    try:
        import importlib
        import predict as standalone
        importlib.reload(standalone)
        outs = standalone.predict([x])
    finally:
        sys.path.pop(0)
    np.testing.assert_allclose(np.asarray(outs[0]), want, rtol=1e-5,
                               atol=1e-6)
