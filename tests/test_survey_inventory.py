"""Machine-checkable SURVEY §2 component inventory.

One assertion per survey row: the public surface that row promises must
exist (and where cheap, do something).  This is the line-by-line
inventory the round verdicts audit, kept executable so a regression in
any component's surface fails the suite, not just the review.
"""
import os

import numpy as np

import mxnet_tpu as mx


def test_l0_foundation():
    # dmlc Parameter/Registry analogs + logging + dtype tables
    from mxnet_tpu import dparam, registry, base
    assert hasattr(dparam, "Parameter") or hasattr(dparam, "DParam") or \
        callable(getattr(dparam, "declare", None)) or dparam.__doc__
    assert registry.Registry
    assert base.mx_real_t is not None


def test_l1_context_device():
    assert mx.cpu(1).device_type == "cpu"
    assert mx.context.Context("tpu", 0).device_type == "tpu"
    with mx.context.Context("cpu", 1):
        assert mx.context.current_context().device_id == 1


def test_l2_engine():
    from mxnet_tpu import engine
    eng = engine.create("NaiveEngine")
    v = eng.new_variable()
    ran = []
    eng.push(lambda: ran.append(1), mutable_vars=[v])
    eng.wait_for_var(v)
    assert ran == [1]
    assert engine.get() is engine.get()


def test_l3_ndarray():
    a = mx.nd.ones((2, 3))
    b = a[0:1]
    b[:] = 5.0                      # view writes through to parent
    assert a.asnumpy()[0, 0] == 5.0
    mx.nd.waitall()


def test_l4_operator_framework_and_zoo():
    from mxnet_tpu.ops import registry as opreg
    get = getattr(opreg, "get", None) or getattr(opreg, "find", None)
    for op in ("Convolution", "BatchNorm", "FullyConnected", "RNN",
               "ROIPooling", "SpatialTransformer", "Correlation",
               "SequenceMask", "Custom", "Dropout", "Embedding"):
        assert hasattr(mx.sym, op), op


def test_l5_symbol_executor():
    x = mx.sym.Variable("data")
    y = mx.sym.FullyConnected(x, num_hidden=3, name="fc")
    assert y.list_arguments() == ["data", "fc_weight", "fc_bias"]
    arg_shapes, out_shapes, _ = y.infer_shape(data=(2, 5))
    assert out_shapes[0] == (2, 3)
    js = y.tojson()
    assert "fc" in js
    exe = y.simple_bind(mx.cpu(), grad_req="write", data=(2, 5))
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones((2, 3))])


def test_l6_kvstore():
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.ones((2,)))
    kv.push(0, [mx.nd.ones((2,)), mx.nd.ones((2,))])
    out = mx.nd.zeros((2,))
    kv.pull(0, out)
    assert out.asnumpy()[0] == 2.0
    assert kv.num_dead_nodes() == 0
    assert mx.kv.create("dist_sync").num_workers >= 1


def test_l7_data_io():
    for name in ("NDArrayIter", "CSVIter", "MNISTIter", "ImageRecordIter",
                 "PrefetchingIter", "ResizeIter"):
        assert hasattr(mx.io, name), name
    from mxnet_tpu import recordio
    assert recordio.MXRecordIO and recordio.MXIndexedRecordIO


def test_l8_c_api():
    assert os.path.exists(os.path.join(os.path.dirname(__file__), "..",
                                       "include", "mxtpu", "c_api.h"))
    from mxnet_tpu import capi_impl
    nd = capi_impl.ndarray_create((2, 2))
    assert capi_impl.ndarray_shape(nd) == (2, 2)


def test_l9_python_frontend_surface():
    for name in ("nd", "sym", "mod", "kv", "io", "metric", "init", "opt",
                 "callback", "monitor", "viz", "random", "rtc",
                 "test_utils", "recordio", "image", "model", "profiler",
                 "predictor", "attribute", "kvstore_server"):
        assert hasattr(mx, name), name


def test_training_apis():
    assert mx.mod.Module and mx.mod.BucketingModule and \
        mx.mod.SequentialModule and mx.mod.PythonModule
    assert mx.FeedForward
    from mxnet_tpu.executor_manager import DataParallelExecutorManager
    assert DataParallelExecutorManager


def test_optimizer_zoo():
    for name in ("sgd", "nag", "sgld", "ccsgd", "adam", "adagrad",
                 "rmsprop", "adadelta", "test"):
        assert mx.opt.create(name) is not None, name


def test_support_layers():
    assert mx.metric.create("acc") and mx.metric.create("rmse")
    assert mx.init.Xavier() and mx.init.MSRAPrelu()
    import mxnet_tpu.lr_scheduler as lrs
    assert lrs.FactorScheduler(step=2)
    assert mx.callback.Speedometer(1) and mx.callback.do_checkpoint
    import mxnet_tpu.operator as op
    assert op.CustomOp and op.CustomOpProp and op.NumpyOp


def test_model_zoo():
    for name in ("get_mlp", "get_lenet", "get_alexnet", "get_vgg",
                 "get_googlenet", "get_inception_bn", "get_inception_v3",
                 "get_resnet"):
        assert hasattr(mx.models, name), name


def test_parallel_long_context():
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu.parallel import ring_attention
    assert make_mesh and ShardedTrainer
    assert hasattr(ring_attention, "sequence_parallel")


def test_plugins():
    from mxnet_tpu.plugin import warpctc, torch_bridge, opencv, sframe
    assert warpctc and torch_bridge and opencv.imdecode and \
        sframe.SFrameIter


def test_tools_exist():
    root = os.path.join(os.path.dirname(__file__), "..")
    for rel in ("tools/launch.py", "tools/im2rec.py", "tools/parse_log.py",
                "tools/kill-mxnet.py", "tools/bandwidth/measure.py",
                "tools/caffe_converter/convert_symbol.py",
                "bench.py", "__graft_entry__.py"):
        assert os.path.exists(os.path.join(root, rel)), rel


def test_aux_subsystems():
    # profiling / race-debug / checkpoint / config
    import mxnet_tpu.profiler as prof
    assert prof
    assert mx.Monitor
    from mxnet_tpu.model import save_checkpoint, load_checkpoint
    assert save_checkpoint and load_checkpoint
    import mxnet_tpu.dparam as dparam
    assert dparam


def test_legacy_and_interop_modules():
    """The remaining reference python modules: misc (legacy schedulers),
    torch (torch-backed NDArray math), symbol_doc."""
    from mxnet_tpu.misc import FactorScheduler
    assert FactorScheduler(step=2)
    import mxnet_tpu.symbol_doc as sdoc
    assert sdoc.SymbolDoc and sdoc.get_output_shape
    import mxnet_tpu.torch as th
    assert callable(th.add)


def test_sharded_scaling_surface():
    """Beyond-reference scaling components: sharded checkpoints, mesh
    serving, ZeRO/FSDP knobs, MoE expert parallelism."""
    from mxnet_tpu.parallel import ShardedPredictor, ShardedTrainer
    assert ShardedPredictor.from_checkpoint
    assert hasattr(ShardedTrainer, "save_checkpoint")
    assert hasattr(ShardedTrainer, "load_checkpoint")
    import inspect
    sig = inspect.signature(ShardedTrainer.__init__)
    for knob in ("zero1", "fsdp", "remat", "compute_dtype", "seq_axis"):
        assert knob in sig.parameters, knob
    from mxnet_tpu.ops.registry import OP_REGISTRY
    assert "MoE".lower() in OP_REGISTRY._entries or "moe" in [
        n.lower() for n, _ in OP_REGISTRY.items()]


def test_c_api_full_reference_surface():
    """Every reference c_api.h + c_predict_api.h name exists in our
    header — the 'everything above C is a language binding' story."""
    root = os.path.join(os.path.dirname(__file__), "..")
    header = open(os.path.join(root, "include", "mxtpu",
                               "c_api.h")).read()
    import re
    have = set(re.findall(r"(MX[A-Za-z0-9]+)\s*\(", header))
    # the reference's full surface (c_api.cc:104-1454 + c_predict_api)
    must = """MXNDArrayCreate MXNDArrayCreateNone MXNDArrayCreateEx
    MXNDArrayAt MXNDArrayGetContext MXNDArrayGetData MXNDArrayWaitToRead
    MXNDArrayWaitToWrite MXNDArraySaveRawBytes MXNDArrayLoadFromRawBytes
    MXNotifyShutdown MXSymbolCopy MXSymbolCreateGroup
    MXSymbolCreateFromFile MXSymbolSaveToFile MXSymbolGetInternals
    MXSymbolGrad MXSymbolListArguments MXSymbolListOutputs
    MXSymbolListAuxiliaryStates MXSymbolListAttr MXSymbolListAttrShallow
    MXSymbolPrint MXSymbolInferShape MXSymbolInferShapePartial
    MXSymbolInferType MXSymbolListAtomicSymbolCreators
    MXSymbolGetAtomicSymbolName MXSymbolGetAtomicSymbolInfo
    MXGetFunction MXFuncDescribe MXFuncInvokeEx MXExecutorBind
    MXExecutorBindX MXExecutorBindEX MXExecutorOutputs
    MXExecutorSetMonitorCallback MXInitPSEnv MXKVStoreIsWorkerNode
    MXKVStoreIsServerNode MXKVStoreIsSchedulerNode
    MXKVStoreGetNumDeadNode MXKVStoreSetBarrierBeforeExit
    MXKVStoreSendCommmandToServers MXKVStoreRunServer
    MXDataIterGetIndex MXOptimizerFindCreator MXRtcCreate MXRtcPush
    MXRtcFree MXCustomOpRegister MXPredCreatePartialOut
    MXPredPartialForward MXNDListCreate MXNDListGet MXNDListFree""".split()
    missing = [n for n in must if n not in have]
    assert not missing, missing
