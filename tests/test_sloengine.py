"""SLO-engine tests (ISSUE 19): spec grammar, the burn-rate matrix
(breach fires the page pair fast, recovery clears with hysteresis,
steady in-budget load never alerts, thin windows give no verdict), and
the generation-stamped grow/shrink recommendations written to the
coordination KV.  Every clock is injected — no sleeps, no wall time.
"""
import json

import pytest

from mxnet_tpu.observability import events
from mxnet_tpu.observability import metrics as m
from mxnet_tpu.observability import sloengine as se
from mxnet_tpu.observability.sloengine import (
    SLO_PREFIX, SloEngine, SloSpec, parse_specs)


@pytest.fixture(autouse=True)
def _pristine(monkeypatch):
    monkeypatch.delenv("MXTPU_TELEMETRY", raising=False)
    monkeypatch.delenv("MXTPU_SLO_SPEC", raising=False)
    monkeypatch.delenv("MXTPU_METRICS_WINDOWS", raising=False)
    events.refresh()
    m.reset_registry()
    se.reset_engine()
    yield
    events.refresh()
    m.reset_registry()
    se.reset_engine()


class FakeKV(object):
    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        self.store[key] = value


# ------------------------------------------------------------- grammar

def test_parse_inline_spec_with_defaults():
    specs = parse_specs("metric=mxtpu_serve_latency_ms:target=250")
    assert len(specs) == 1
    sp = specs[0]
    assert sp.metric == "mxtpu_serve_latency_ms"
    assert sp.target == 250.0
    assert sp.budget == 0.01
    assert sp.page == 14.0 and sp.ticket == 2.0
    assert sp.fast == 10 and sp.slow == 60
    assert sp.tfast == 60 and sp.tslow == 300
    assert sp.hold == 3 and sp.clear == 0.5 and sp.min_n == 10


def test_parse_multiple_specs_and_overrides():
    specs = parse_specs(
        "metric=a:target=1:budget=0.05:page=10:fast=5:slow=30;"
        "metric=b:target=2:hold=1:min_n=2")
    assert [s.metric for s in specs] == ["a", "b"]
    assert specs[0].budget == 0.05 and specs[0].fast == 5
    assert specs[1].hold == 1 and specs[1].min_n == 2


def test_parse_spec_file(tmp_path):
    f = tmp_path / "slo.spec"
    f.write_text("# objectives\n"
                 "metric=lat:target=100\n"
                 "metric=ttft:target=50:budget=0.02\n")
    specs = parse_specs(str(f))
    assert [s.metric for s in specs] == ["lat", "ttft"]
    assert specs[1].budget == 0.02


def test_parse_rejects_malformed():
    with pytest.raises(ValueError):
        parse_specs("metric=a")                    # no target
    with pytest.raises(ValueError):
        parse_specs("metric=a:target=1:junk")      # token without =
    with pytest.raises(ValueError):
        SloSpec("a", 1.0, budget=1.5)              # budget out of range
    assert parse_specs("") == []
    assert parse_specs(None) == []


# ---------------------------------------------------------- the matrix

def _engine(kv=None, **spec_kw):
    """Engine over a private registry with one latency objective:
    target 100ms, 1% budget, page 14x over (slow=60, fast=10),
    ticket 2x over (tslow=300, tfast=60), hold=2 for short tests."""
    reg = m.MetricsRegistry()
    spec = SloSpec("lat_ms", 100.0, hold=spec_kw.pop("hold", 2),
                   **spec_kw)
    eng = SloEngine(specs=[spec], reg=reg, kv=kv, source="test")
    hist = reg.histogram("lat_ms", windows_s=(10, 60, 300, 3600))
    return eng, hist, spec


def _feed(hist, t0, seconds, bad_frac, per_sec=10):
    """per_sec samples/s for `seconds`; bad_frac of each second's
    samples land above the 100ms target."""
    for s in range(int(seconds)):
        now = t0 + s
        nbad = int(round(per_sec * bad_frac))
        for i in range(per_sec - nbad):
            hist.observe(10.0, now=now)
        for i in range(nbad):
            hist.observe(500.0, now=now)
    return t0 + seconds


def test_steady_in_budget_load_never_alerts():
    eng, hist, _ = _engine()
    # 0.5% bad against a 1% budget = burn 0.5 — inside budget
    t = _feed(hist, 1000.0, 400, bad_frac=0.005, per_sec=200)
    fired = []
    for k in range(20):
        fired.extend(eng.evaluate(now=t + k))
    assert fired == []
    st = eng.state(now=t)
    assert not st["specs"][0]["tiers"]["page"]["active"]
    assert not st["specs"][0]["tiers"]["ticket"]["active"]


def test_breach_fires_page_within_fast_window():
    eng, hist, _ = _engine()
    t = _feed(hist, 1000.0, 60, bad_frac=0.0)      # healthy baseline
    assert eng.evaluate(now=t) == []
    # fault: 50% bad = burn 50x — both page windows blow past 14x
    # within ~the fast window of traffic
    t = _feed(hist, t, 30, bad_frac=0.5)
    alerts = eng.evaluate(now=t)
    kinds = {(a["tier"], a["edge"]) for a in alerts}
    assert ("page", "fire") in kinds
    page = [a for a in alerts if a["tier"] == "page"][0]
    assert page["metric"] == "lat_ms"
    assert page["windows_s"] == [60, 10]
    assert all(b >= 14.0 for b in page["burns"].values())
    # refiring is edge-triggered: a second evaluate emits nothing new
    assert eng.evaluate(now=t) == []


def test_recovery_clears_with_hysteresis_hold():
    eng, hist, spec = _engine(hold=2)
    t = _feed(hist, 1000.0, 30, bad_frac=0.5)
    assert any(a["edge"] == "fire" for a in eng.evaluate(now=t))
    # recovery: clean traffic long enough to flush both pair windows
    t = _feed(hist, t, 70, bad_frac=0.0)
    first = eng.evaluate(now=t)
    assert first == []                 # hold=2: first clean eval holds
    second = eng.evaluate(now=t + 1)
    assert any(a["edge"] == "clear" and a["tier"] == "page"
               for a in second)
    assert not eng.state(now=t + 1)["specs"][0]["tiers"]["page"]["active"]


def test_relapse_resets_clear_streak():
    eng, hist, _ = _engine(hold=2)
    t = _feed(hist, 1000.0, 30, bad_frac=0.5)
    eng.evaluate(now=t)
    t = _feed(hist, t, 70, bad_frac=0.0)
    assert eng.evaluate(now=t) == []   # streak 1 of 2
    t = _feed(hist, t, 15, bad_frac=0.5)   # relapse
    assert eng.evaluate(now=t) == []   # still active, streak reset
    t = _feed(hist, t, 70, bad_frac=0.0)
    eng.evaluate(now=t)
    cleared = eng.evaluate(now=t + 1)
    assert any(a["edge"] == "clear" for a in cleared)


def test_thin_window_gives_no_verdict():
    eng, hist, _ = _engine()
    for i in range(5):                 # 5 samples < min_n=10
        hist.observe(500.0, now=1000.0 + i)
    assert eng.evaluate(now=1005.0) == []
    st = eng.state(now=1005.0)
    assert st["specs"][0]["burns"]["10"]["burn"] is None


def test_missing_histogram_is_silent():
    reg = m.MetricsRegistry()
    eng = SloEngine(specs=[SloSpec("nope", 1.0)], reg=reg)
    assert eng.evaluate(now=1000.0) == []


# ----------------------------------------------------- recommendations

def test_page_fire_writes_recommend_grow():
    kv = FakeKV()
    eng, hist, _ = _engine(kv=kv)
    t = _feed(hist, 1000.0, 30, bad_frac=0.5)
    eng.evaluate(now=t)
    latest = json.loads(kv.store[SLO_PREFIX + "latest"])
    assert latest["action"] == "recommend_grow"
    assert latest["gen"] == 1
    assert latest["metric"] == "lat_ms"
    assert latest["source"] == "test"
    assert SLO_PREFIX + "reco-lat_ms-00001" in kv.store
    # one fire -> exactly one recommendation
    assert len(kv.store) == 2


def test_sustained_idle_writes_recommend_shrink_once():
    kv = FakeKV()
    eng, hist, _ = _engine(kv=kv)
    # real traffic, zero bad: burn 0 <= IDLE_BURN on the slow window
    t = _feed(hist, 1000.0, 350, bad_frac=0.0, per_sec=5)
    for k in range(SloEngine.IDLE_HOLD + 3):
        eng.evaluate(now=t + k)
    recos = [json.loads(v) for k, v in kv.store.items()
             if k.startswith(SLO_PREFIX + "reco-")]
    assert len(recos) == 1
    assert recos[0]["action"] == "recommend_shrink"
    assert recos[0]["gen"] == 1


def test_kv_failure_is_swallowed():
    class BadKV(object):
        def key_value_set(self, *a, **kw):
            raise OSError("kv down")
    eng, hist, _ = _engine(kv=BadKV())
    t = _feed(hist, 1000.0, 30, bad_frac=0.5)
    alerts = eng.evaluate(now=t)       # alert still fires
    assert any(a["edge"] == "fire" for a in alerts)


def test_alert_events_reach_the_event_log(monkeypatch, tmp_path):
    d = str(tmp_path / "tel")
    monkeypatch.setenv("MXTPU_TELEMETRY", "1")
    monkeypatch.setenv("MXTPU_TELEMETRY_DIR", d)
    events.refresh()
    eng, hist, _ = _engine()
    t = _feed(hist, 1000.0, 30, bad_frac=0.5)
    eng.evaluate(now=t)
    recs = []
    import glob
    for path in glob.glob(d + "/events-rank*.jsonl"):
        with open(path) as fin:
            recs.extend(json.loads(ln) for ln in fin if ln.strip())
    kinds = {r["kind"] for r in recs}
    assert "slo_alert" in kinds
    alert = [r for r in recs if r["kind"] == "slo_alert"][0]
    assert alert["tier"] == "page" and alert["edge"] == "fire"


def test_maybe_start_requires_spec(monkeypatch):
    assert se.maybe_start() is None
    monkeypatch.setenv("MXTPU_SLO_SPEC", "metric=lat:target=9")
    eng = se.maybe_start(source="door")
    try:
        assert eng is not None
        assert eng.source == "door"
        assert [s.metric for s in eng.specs] == ["lat"]
    finally:
        eng.stop()
