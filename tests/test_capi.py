"""C ABI smoke test: builds lib/libmxtpu_capi.so + a real C consumer
(tests/capi/capi_smoke.c) and runs it — the proof that the reference's
language-binding story (c_api.h over opaque handles) survives the TPU
rewrite.  Skips cleanly when no compiler/python headers are available.
"""
import os
import shutil
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.skipif(shutil.which("g++") is None or
                    shutil.which("make") is None,
                    reason="no native toolchain")
def test_capi_smoke(tmp_path):
    build = subprocess.run(["make", "-s", "lib/capi_smoke"], cwd=_ROOT,
                           capture_output=True, text=True, timeout=300)
    if build.returncode != 0 and "Python.h" in (build.stderr or ""):
        pytest.skip("python headers unavailable")
    assert build.returncode == 0, build.stderr[-2000:]

    # a symbol + params for the bind/forward and predictor legs
    import mxnet_tpu as mx
    sym = mx.models.get_mlp(num_classes=2, hidden=(8,))
    sym_path = str(tmp_path / "mlp-symbol.json")
    sym.save(sym_path)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 10))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.init.Uniform(0.1))
    mod.save_checkpoint(str(tmp_path / "mlp"), 0)

    env = dict(os.environ)
    env["MXTPU_SYMBOL_JSON"] = sym_path
    env["MXTPU_PARAMS_FILE"] = str(tmp_path / "mlp-0000.params")
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # the embedded interpreter must skip the hanging accelerator plugin
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env["PYTHONPATH"].split(os.pathsep)
        if p and not os.path.isfile(os.path.join(p, "sitecustomize.py")))
    proc = subprocess.run([os.path.join(_ROOT, "lib", "capi_smoke")],
                          env=env, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-1500:])
    assert "CAPI SMOKE OK" in proc.stdout
    assert "forward:" in proc.stdout
    assert "predict:" in proc.stdout


@pytest.mark.skipif(shutil.which("g++") is None or
                    shutil.which("make") is None,
                    reason="no native toolchain")
def test_capi_threads():
    """Second-thread MX* calls must not deadlock (the embedded
    interpreter's startup GIL is parked) and per-thread last-error stays
    isolated (TLS contract)."""
    build = subprocess.run(["make", "-s", "lib/capi_threads"], cwd=_ROOT,
                           capture_output=True, text=True, timeout=300)
    if build.returncode != 0 and "Python.h" in (build.stderr or ""):
        pytest.skip("python headers unavailable")
    assert build.returncode == 0, build.stderr[-2000:]
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env["PYTHONPATH"].split(os.pathsep)
        if p and not os.path.isfile(os.path.join(p, "sitecustomize.py")))
    proc = subprocess.run([os.path.join(_ROOT, "lib", "capi_threads")],
                          env=env, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-1500:])
    assert "CAPI THREADS OK" in proc.stdout


@pytest.mark.skipif(shutil.which("g++") is None or
                    shutil.which("make") is None,
                    reason="no native toolchain")
def test_capi_parity(tmp_path):
    """The reference-surface completion: every remaining MX* family —
    NDArray extras, symbol listing/CSR inference/grad, atomic-symbol
    info, func describe/invoke-ex, full Bind + monitor, kvstore
    roles/server loop, data-iter index, Rtc, and a custom op implemented
    entirely in C through the CustomOpPropCreator struct protocol."""
    build = subprocess.run(["make", "-s", "lib/capi_parity"], cwd=_ROOT,
                           capture_output=True, text=True, timeout=300)
    if build.returncode != 0 and "Python.h" in (build.stderr or ""):
        pytest.skip("python headers unavailable")
    assert build.returncode == 0, build.stderr[-2000:]

    import mxnet_tpu as mx
    sym = mx.models.get_mlp(num_classes=2, hidden=(8,))
    sym_path = str(tmp_path / "mlp-symbol.json")
    sym.save(sym_path)
    mod = mx.mod.Module(sym, context=mx.context.cpu())
    mod.bind(data_shapes=[("data", (2, 10))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.init.Uniform(0.1))
    mod.save_checkpoint(str(tmp_path / "mlp"), 0)

    env = dict(os.environ)
    env["MXTPU_SYMBOL_JSON"] = sym_path
    env["MXTPU_PARAMS_FILE"] = str(tmp_path / "mlp-0000.params")
    env["MXTPU_SCRATCH"] = str(tmp_path)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env["PYTHONPATH"].split(os.pathsep)
        if p and not os.path.isfile(os.path.join(p, "sitecustomize.py")))
    proc = subprocess.run([os.path.join(_ROOT, "lib", "capi_parity")],
                          env=env, capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    assert "capi_parity OK" in proc.stdout


def test_attr_listing_reference_format():
    """Deep attr keys use the reference's '_' namespace separator
    (symbol.cc:19,526) and propagate node attrs onto aux-state names
    (symbol.cc:532-538) — the wire format C consumers parse."""
    import mxnet_tpu as mx
    from mxnet_tpu import capi_impl

    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn0", attr={"ctx_group": "dev1"})
    pairs = capi_impl.symbol_attr_pairs(bn, deep=1)
    d = dict(zip(pairs[0::2], pairs[1::2]))
    assert d.get("bn0_ctx_group") == "dev1"
    # aux propagation: every aux state of bn0 carries the node's attrs
    for aux in ("moving_mean", "moving_var"):
        assert d.get("bn0_%s_ctx_group" % aux) == "dev1", sorted(d)
    assert not any("$" in k for k in d)


def test_infer_type_complete_includes_aux():
    """MXSymbolInferType's complete flag must account for aux states."""
    import mxnet_tpu as mx
    from mxnet_tpu import capi_impl

    data = mx.sym.Variable("data")
    net = mx.sym.BatchNorm(mx.sym.FullyConnected(
        data, num_hidden=4, name="fc"), name="bn0")
    _arg, _out, aux_t, complete = capi_impl.symbol_infer_type_arrays(
        net, ["data"], [0])        # 0 = float32 flag
    # all aux inferable here -> complete stays 1 and auxes are typed
    assert complete == 1 and all(t != -1 for t in aux_t)
