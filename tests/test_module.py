"""Module API + FeedForward + model zoo tests.

Mirrors the reference's tests/python/unittest/test_module.py and
tests/python/train/test_mlp.py (small end-to-end runs asserting an accuracy
threshold, SURVEY §4).
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _toy_problem(n=200, d=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype("float32")
    w = rng.randn(d)
    y = (X @ w > 0).astype("float32")
    return X, y


def test_module_bind_forward():
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 10))],
                            label=[mx.nd.zeros((4,))])
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (4, 2)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1),
                               np.ones(4), rtol=1e-5)


def test_module_fit_accuracy():
    X, y = _toy_problem()
    train = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=True)
    val = mx.io.NDArrayIter(X, y, batch_size=20)
    net = mx.models.get_mlp(num_classes=2, hidden=(16,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5}, num_epoch=5)
    score = dict(mod.score(val, "acc"))
    assert score["accuracy"] > 0.9, score


def test_module_get_set_params_roundtrip():
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    arg_params, aux_params = mod.get_params()
    assert "fc1_weight" in arg_params

    mod2 = mx.mod.Module(net, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (4, 10))],
              label_shapes=[("softmax_label", (4,))])
    mod2.set_params(arg_params, aux_params)
    a1, _ = mod2.get_params()
    np.testing.assert_allclose(a1["fc1_weight"].asnumpy(),
                               arg_params["fc1_weight"].asnumpy())


def test_module_save_load_checkpoint(tmp_path):
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    prefix = str(tmp_path / "mod_test")
    mod.save_checkpoint(prefix, 3)

    mod2 = mx.mod.Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (4, 10))],
              label_shapes=[("softmax_label", (4,))])
    a0, _ = mod.get_params()
    a1, _ = mod2.get_params()
    for k in a0:
        np.testing.assert_allclose(a0[k].asnumpy(), a1[k].asnumpy())


def test_module_input_grads():
    # Pin the global init stream: with only 8 ReLU units, an unlucky
    # ambient RNG state (depends on how much stream earlier tests
    # consumed) can leave every hidden pre-activation negative for the
    # all-ones input, making the input gradient exactly zero (~0.4%).
    mx.random.seed(42)
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))],
             for_training=True, inputs_need_grad=True)
    mod.init_params(initializer=mx.init.Uniform(0.1))
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 10))],
                            label=[mx.nd.zeros((4,))])
    mod.forward(batch, is_train=True)
    mod.backward()
    igrads = mod.get_input_grads()
    assert igrads[0].shape == (4, 10)
    assert np.abs(igrads[0].asnumpy()).sum() > 0


def test_module_multi_context_slicing():
    """Batch slicing across two CPU contexts (reference fakes multi-device
    with cpu dev_ids, test_multi_device_exec.py)."""
    np.random.seed(0)  # NDArrayIter shuffles via the global numpy RNG
    X, y = _toy_problem()
    train = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=True)
    net = mx.models.get_mlp(num_classes=2, hidden=(16,))
    mod = mx.mod.Module(net, context=[mx.cpu(0), mx.cpu(1)])
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5}, num_epoch=3)
    val = mx.io.NDArrayIter(X, y, batch_size=20)
    score = dict(mod.score(val, "acc"))
    assert score["accuracy"] > 0.85, score


def test_feedforward_fit_score_predict(tmp_path):
    X, y = _toy_problem()
    train = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=True)
    val = mx.io.NDArrayIter(X, y, batch_size=20)
    model = mx.FeedForward(mx.models.get_mlp(2, (16,)), ctx=mx.cpu(),
                           num_epoch=5, optimizer="sgd", learning_rate=0.5)
    model.fit(train, eval_data=val)
    assert model.score(val) > 0.9
    pred = model.predict(val)
    assert pred.shape == (200, 2)

    prefix = str(tmp_path / "ff_test")
    model.save(prefix)
    m2 = mx.FeedForward.load(prefix, 5, ctx=mx.cpu())
    assert m2.score(val) > 0.9


def test_feedforward_epoch_size_exact_multiple():
    """epoch_size == batches-per-pass: each epoch drains the iterator
    exactly, so epoch 2+ begins with it exhausted and the driver must
    reset-and-retry instead of raising (reference do_reset semantics)."""
    X, y = _toy_problem()
    train = mx.io.NDArrayIter(X, y, batch_size=20)  # 10 batches/pass
    model = mx.FeedForward(mx.models.get_mlp(2, (16,)), ctx=mx.cpu(),
                           num_epoch=3, epoch_size=10, optimizer="sgd",
                           learning_rate=0.5)
    model.fit(train)
    assert model.score(mx.io.NDArrayIter(X, y, batch_size=20)) > 0.7


def test_feedforward_numpy_input():
    X, y = _toy_problem()
    model = mx.FeedForward(mx.models.get_mlp(2, (16,)), ctx=mx.cpu(),
                           num_epoch=4, optimizer="sgd", learning_rate=0.5,
                           numpy_batch_size=20)
    model.fit(X, y)
    pred = model.predict(X)
    acc = ((pred.argmax(axis=1) == y).mean())
    assert acc > 0.85


def test_bucketing_module():
    """Per-bucket executors sharing params (bucketing_module.py:189)."""
    batch_size = 8

    def sym_gen(seq_len):
        # embedding + pooled sum keeps param shapes independent of seq_len
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, name="embed", input_dim=20,
                                 output_dim=6)
        pooled = mx.sym.sum_axis(embed, axis=1)
        fc = mx.sym.FullyConnected(pooled, name="fc", num_hidden=4)
        net = mx.sym.SoftmaxOutput(fc, label=label, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=12,
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (batch_size, 12))],
             label_shapes=[("softmax_label", (batch_size,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})

    # feed genuinely different buckets: the 8-bucket binds a new executor
    # sharing params with the default 12-bucket (switch_bucket shared path)
    for seq_len in (12, 8, 12, 8):
        data = mx.nd.ones((batch_size, seq_len))
        label = mx.nd.zeros((batch_size,))
        batch = mx.io.DataBatch(data=[data], label=[label],
                                provide_data=[("data", (batch_size, seq_len))],
                                provide_label=[("softmax_label", (batch_size,))],
                                bucket_key=seq_len)
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    out = mod.get_outputs()[0]
    assert out.shape == (batch_size, 4)
    # updates through bucket 8 must be visible in shared params
    arg_params, _ = mod.get_params()
    assert "embed_weight" in arg_params and "fc_weight" in arg_params


def test_sequential_module():
    net1 = mx.sym.FullyConnected(mx.sym.Variable("data"), name="fc1",
                                 num_hidden=8)
    net1 = mx.sym.Activation(net1, act_type="relu")
    net2 = mx.sym.FullyConnected(mx.sym.Variable("data"), name="fc2",
                                 num_hidden=2)
    net2 = mx.sym.SoftmaxOutput(net2, name="softmax")

    mod1 = mx.mod.Module(net1, label_names=None, context=mx.cpu())
    mod2 = mx.mod.Module(net2, context=mx.cpu())
    seq = mx.mod.SequentialModule()
    seq.add(mod1).add(mod2, take_labels=True, auto_wiring=True)

    X, y = _toy_problem()
    train = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=True)
    seq.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    seq.init_params(initializer=mx.init.Uniform(0.1))
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    metric = mx.metric.create("acc")
    for epoch in range(3):
        train.reset()
        metric.reset()
        for batch in train:
            seq.forward_backward(batch)
            seq.update()
            seq.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.8


@pytest.mark.parametrize("name,builder,shape", [
    ("lenet", lambda: mx.models.get_lenet(10), (2, 1, 28, 28)),
    ("resnet18", lambda: mx.models.get_resnet(10, 18, (3, 32, 32)),
     (2, 3, 32, 32)),
])
def test_model_zoo_forward(name, builder, shape):
    net = builder()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", shape)],
             label_shapes=[("softmax_label", (shape[0],))])
    mod.init_params(initializer=mx.init.Xavier())
    batch = mx.io.DataBatch(data=[mx.nd.ones(shape)],
                            label=[mx.nd.zeros((shape[0],))])
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (shape[0], 10)
    assert np.all(np.isfinite(out.asnumpy()))


def test_model_zoo_shapes():
    """All zoo symbols infer shapes (parity: test_symbol/infer_shape)."""
    cases = [
        (mx.models.get_alexnet(100), (2, 3, 224, 224), 100),
        (mx.models.get_vgg(10, 11), (2, 3, 224, 224), 10),
        (mx.models.get_googlenet(10), (2, 3, 224, 224), 10),
        (mx.models.get_inception_bn(10), (2, 3, 224, 224), 10),
        (mx.models.get_inception_v3(10), (2, 3, 299, 299), 10),
        (mx.models.get_resnet(10, 50), (2, 3, 224, 224), 10),
    ]
    for net, dshape, ncls in cases:
        _, out_shapes, _ = net.infer_shape(data=dshape)
        assert out_shapes[0] == (dshape[0], ncls)


def test_module_fixed_params_initialized_and_frozen():
    """fixed_param_names: initialized + checkpointed, but not updated."""
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    mod = mx.mod.Module(net, context=mx.cpu(),
                        fixed_param_names=["fc1_weight"])
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    arg_params, _ = mod.get_params()
    w0 = arg_params["fc1_weight"].asnumpy()
    fc2_0 = arg_params["fc2_weight"].asnumpy()
    assert np.abs(w0).sum() > 0, "fixed param was not initialized"

    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 10))],
                            label=[mx.nd.zeros((4,))])
    for _ in range(3):
        mod.forward_backward(batch)
        mod.update()
    arg_params, _ = mod.get_params()
    np.testing.assert_allclose(arg_params["fc1_weight"].asnumpy(), w0,
                               err_msg="fixed param was updated")
    # non-fixed params must have moved
    assert not np.allclose(arg_params["fc2_weight"].asnumpy(), fc2_0)
    assert not np.allclose(arg_params["fc2_bias"].asnumpy(), 0)


def test_module_reshape_keeps_grad_req():
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))], grad_req="add")
    mod.init_params(initializer=mx.init.Uniform(0.1))
    mod.reshape(data_shapes=[("data", (8, 10))],
                label_shapes=[("softmax_label", (8,))])
    batch = mx.io.DataBatch(data=[mx.nd.ones((8, 10))],
                            label=[mx.nd.zeros((8,))])
    # with grad_req='add', two backward passes double the gradient
    mod.forward(batch, is_train=True)
    mod.backward()
    g1 = mod._exec_group.execs[0].grad_dict["fc1_weight"].asnumpy().copy()
    mod.forward(batch, is_train=True)
    mod.backward()
    g2 = mod._exec_group.execs[0].grad_dict["fc1_weight"].asnumpy()
    np.testing.assert_allclose(g2, 2 * g1, rtol=1e-4)


def test_print_summary_param_count(capsys):
    """Labels don't count as params; shared weights count once."""
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    mx.viz.print_summary(net, shape={"data": (4, 10)})
    out = capsys.readouterr().out
    # mlp 10->8->2: fc1 10*8+8, fc2 8*2+2 = 88 + 18 = 106
    assert "Total params: 106" in out


def test_monitor():
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mon = mx.Monitor(interval=1, pattern=".*weight")
    mod.install_monitor(mon)
    mod.init_params(initializer=mx.init.Uniform(0.1))
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 10))],
                            label=[mx.nd.zeros((4,))])
    mon.tic()
    mod.forward(batch, is_train=False)
    res = mon.toc()
    assert len(res) > 0
    names = [k for _, k, _ in res]
    assert any("weight" in n for n in names)


def test_fit_step_is_one_fused_dispatch():
    """VERDICT r1: the fit hot loop must be ONE trace execution per step —
    fwd+bwd+update fused (no forward-then-recompute-in-backward pair)."""
    X, y = _toy_problem()
    n_batches = len(X) // 20
    train = mx.io.NDArrayIter(X, y, batch_size=20)
    net = mx.models.get_mlp(num_classes=2, hidden=(16,))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Uniform(0.1), num_epoch=2)
    exec_ = mod._exec_group.execs[0]
    assert exec_._n_fused_step == 2 * n_batches, (
        exec_._n_fused_step, n_batches)
    assert exec_._n_forward == 0, exec_._n_forward
    assert exec_._n_fwd_bwd == 0, exec_._n_fwd_bwd
    # and the fused path must actually learn
    score = dict(mod.score(mx.io.NDArrayIter(X, y, batch_size=20), "acc"))
    assert score["accuracy"] > 0.9, score


def test_fused_and_host_update_paths_agree():
    """Fused in-step optimizer update ≡ the host updater path (same math,
    one dispatch instead of 1 + P)."""
    X, y = _toy_problem(n=100)
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    params = {}
    for tag, env in (("fused", "1"), ("host", "0")):
        import os
        os.environ["MXNET_MODULE_FUSED"] = env
        try:
            mx.random.seed(42)
            train = mx.io.NDArrayIter(X, y, batch_size=20)
            mod = mx.mod.Module(net, context=mx.cpu())
            mod.fit(train, optimizer="sgd",
                    optimizer_params={"learning_rate": 0.1,
                                      "momentum": 0.9, "wd": 1e-3},
                    initializer=mx.init.Uniform(0.1), num_epoch=3)
            params[tag] = {k: v.asnumpy()
                           for k, v in mod.get_params()[0].items()}
        finally:
            del os.environ["MXNET_MODULE_FUSED"]
    for k in params["fused"]:
        np.testing.assert_allclose(params["fused"][k], params["host"][k],
                                   rtol=1e-4, atol=1e-5)


def test_sharded_multi_device_fused_fit():
    """VERDICT r2 #3: Module.fit over a device list runs ONE fused dispatch
    per step on a mesh (data sharded, params replicated) — the in-step
    collapse of kvstore device gradient reduction (comm.h:186-345)."""
    X, y = _toy_problem()
    n_batches = len(X) // 40
    train = mx.io.NDArrayIter(X, y, batch_size=40)
    net = mx.models.get_mlp(num_classes=2, hidden=(16,))
    ctxs = [mx.cpu(i) for i in range(8)]
    mod = mx.mod.Module(net, context=ctxs)
    mod.fit(train, kvstore="device", optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            initializer=mx.init.Uniform(0.1), num_epoch=4)
    group = mod._exec_group
    assert group.sharded and len(group.execs) == 1
    exec_ = group.execs[0]
    assert exec_._n_fused_step == 4 * n_batches, (
        exec_._n_fused_step, n_batches)
    assert exec_._n_fwd_bwd == 0
    score = dict(mod.score(mx.io.NDArrayIter(X, y, batch_size=40), "acc"))
    assert score["accuracy"] > 0.9, score


def test_sharded_fused_step_hlo_has_all_reduce():
    """The compiled sharded step must carry the gradient all-reduce over
    the dp mesh axis (assert on lowered text, VERDICT r2 #3 done-bar)."""
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    ctxs = [mx.cpu(i) for i in range(8)]
    mod = mx.mod.Module(net, context=ctxs)
    mod.bind(data_shapes=[("data", (32, 10))],
             label_shapes=[("softmax_label", (32,))])
    mod.init_params()
    mod.init_optimizer(kvstore="device")
    assert mod._kv_inline and mod._fused_step_ok()
    hlo = mod._exec_group.fused_step_hlo(mod._optimizer)
    assert "all-reduce" in hlo


def test_sharded_matches_single_device():
    """Same data, same init: 8-device sharded training must produce the
    same parameters as single-device (the all-reduced grad equals the
    full-batch grad)."""
    X, y = _toy_problem(n=128)
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    results = {}
    for tag, ctx in (("one", mx.cpu()),
                     ("mesh", [mx.cpu(i) for i in range(8)])):
        mx.random.seed(11)
        train = mx.io.NDArrayIter(X, y, batch_size=32)
        mod = mx.mod.Module(net, context=ctx)
        mod.fit(train, kvstore="device" if tag == "mesh" else None,
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
                initializer=mx.init.Uniform(0.1), num_epoch=2)
        results[tag] = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    for k in results["one"]:
        np.testing.assert_allclose(results["mesh"][k], results["one"][k],
                                   rtol=2e-4, atol=2e-5)


def test_zoo_builders_deterministic_names():
    """Auto-named zoo builders must produce identical parameter names on
    every build (NameManager scope per get_symbol) — checkpoint load in a
    fresh process depends on it."""
    from mxnet_tpu.models import alexnet, googlenet, inception_bn
    for mod in (alexnet, googlenet, inception_bn):
        first = mod.get_symbol(num_classes=10).list_arguments()
        # bump the ambient manager's counters with an UNNAMED op
        mx.sym.FullyConnected(mx.sym.Variable("noise"), num_hidden=1)
        second = mod.get_symbol(num_classes=10).list_arguments()
        assert first == second, mod.__name__


def test_fused_step_bf16_compute():
    """MXNET_COMPUTE_DTYPE=bfloat16: fwd/bwd run reduced-precision (the
    compiled step carries bf16 math) while master weights stay f32, and
    training still converges."""
    import os
    X, y = _toy_problem(n=120)
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    os.environ["MXNET_COMPUTE_DTYPE"] = "bfloat16"
    try:
        mx.random.seed(7)
        train = mx.io.NDArrayIter(X, y, batch_size=30)
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.fit(train, optimizer="sgd",
                optimizer_params={"learning_rate": 0.5},
                initializer=mx.init.Uniform(0.1), num_epoch=10)
        score = dict(mod.score(mx.io.NDArrayIter(X, y, batch_size=30),
                               "acc"))
        assert score["accuracy"] > 0.9, score
        exec_ = mod._exec_group.execs[0]
        assert exec_._n_fused_step > 0
        states = exec_.init_fused_states(mod._optimizer)
        hlo = exec_.lower_fused_step(mod._optimizer, states)
        assert "bf16" in hlo                      # compute in bf16
        args, _ = mod.get_params()
        assert all(v.asnumpy().dtype == np.float32
                   for v in args.values())        # f32 master weights
    finally:
        del os.environ["MXNET_COMPUTE_DTYPE"]


def test_bucketing_on_sharded_mesh():
    """BucketingModule over a device list: each bucket shares the sharded
    mesh group (shared_group copies mesh state, VERDICT r2 review)."""
    batch_size = 16

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, name="embed", input_dim=20,
                                 output_dim=6)
        pooled = mx.sym.sum_axis(embed, axis=1)
        fc = mx.sym.FullyConnected(pooled, name="fc", num_hidden=4)
        return (mx.sym.SoftmaxOutput(fc, label=label, name="softmax"),
                ("data",), ("softmax_label",))

    ctxs = [mx.cpu(i) for i in range(8)]
    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=12,
                                 context=ctxs)
    mod.bind(data_shapes=[("data", (batch_size, 12))],
             label_shapes=[("softmax_label", (batch_size,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    for seq_len in (12, 8, 12, 8):
        batch = mx.io.DataBatch(
            data=[mx.nd.ones((batch_size, seq_len))],
            label=[mx.nd.zeros((batch_size,))],
            provide_data=[("data", (batch_size, seq_len))],
            provide_label=[("softmax_label", (batch_size,))],
            bucket_key=seq_len)
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    assert mod._curr_module._exec_group.sharded
    assert mod.get_outputs()[0].shape == (batch_size, 4)


def test_checkpoint_cross_api_roundtrip(tmp_path):
    """FeedForward.save -> Module.load and back: one checkpoint format
    across both training APIs (reference model.py:308 contract)."""
    X, y = _toy_problem(n=80)
    model = mx.FeedForward(mx.models.get_mlp(2, (8,)), ctx=mx.cpu(),
                           num_epoch=2, optimizer="sgd", learning_rate=0.3)
    model.fit(X, y)
    prefix = str(tmp_path / "xapi")
    model.save(prefix, 2)

    mod = mx.mod.Module.load(prefix, 2, context=mx.cpu())
    mod.bind(data_shapes=[("data", (16, 10))],
             label_shapes=[("softmax_label", (16,))])
    val = mx.io.NDArrayIter(X, y, batch_size=16)
    acc_mod = dict(mod.score(val, "acc"))["accuracy"]
    acc_ff = model.score(mx.io.NDArrayIter(X, y, batch_size=16))
    assert abs(acc_mod - acc_ff) < 1e-9

    mod.save_checkpoint(prefix + "2", 0)
    back = mx.FeedForward.load(prefix + "2", 0, ctx=mx.cpu())
    assert abs(back.score(mx.io.NDArrayIter(X, y, batch_size=16))
               - acc_ff) < 1e-9


def test_optimizer_states_roundtrip_fused(tmp_path):
    """Momentum state saved mid-training resumes identically: two more
    epochs after a save/load must equal two more epochs without it."""
    X, y = _toy_problem(n=80)

    def run(resume):
        mx.random.seed(3)
        train = mx.io.NDArrayIter(X, y, batch_size=20)
        mod = mx.mod.Module(mx.models.get_mlp(2, (8,)), context=mx.cpu())
        mod.fit(train, optimizer="sgd",
                optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
                initializer=mx.init.Uniform(0.1), num_epoch=2)
        if resume:
            prefix = str(tmp_path / "opt")
            mod.save_checkpoint(prefix, 2, save_optimizer_states=True)
            mod = mx.mod.Module.load(prefix, 2, load_optimizer_states=True,
                                     context=mx.cpu())
            mod.bind(data_shapes=train.provide_data,
                     label_shapes=train.provide_label)
            mod.init_optimizer(optimizer="sgd",
                               optimizer_params={"learning_rate": 0.2,
                                                 "momentum": 0.9})
        train.reset()
        for _ in range(2):
            for b in train:
                mod.forward_backward(b)
                mod.update()
            train.reset()
        return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    direct, resumed = run(False), run(True)
    for k in direct:
        np.testing.assert_allclose(resumed[k], direct[k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)
