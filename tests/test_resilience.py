"""Resilience subsystem tests: the fault-injection recovery matrix.

Every recovery path in mxnet_tpu.resilience (docs/resilience.md) is
exercised here on the CPU mesh with deterministically injected faults:
NaN gradients, checkpoint-write crashes, hung steps, dead-node
reports, plus the 2-worker kill-and-resume smoke (the full drill stays
in tests/nightly/dist_resume.py; phases A+B run here too, promoted to
tier-1).
"""
import glob
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import parallel, resilience
from mxnet_tpu.resilience import (CheckpointManager, FaultSpec,
                                  InjectedFault, ResilienceError,
                                  RetryPolicy, Sentinel, Watchdog,
                                  faultinject, latest_classic_epoch,
                                  parse_fault_spec, retry_call,
                                  run_with_timeout)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Each test starts and ends with no armed fault specs."""
    monkeypatch.delenv("MXTPU_FAULT_SPEC", raising=False)
    faultinject.reset()
    yield
    faultinject.reset()


def _arm(monkeypatch, spec):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", spec)
    faultinject.reset()


# ----------------------------------------------------------------------
# fault-spec grammar
# ----------------------------------------------------------------------
def test_parse_fault_spec_grammar():
    specs = parse_fault_spec(
        "step=3:kind=hang:seconds=60;step=9:kind=ckpt_crash")
    assert len(specs) == 2
    assert specs[0].kind == "hang" and specs[0].step == 3 \
        and specs[0].seconds == 60.0 and specs[0].seam == "step"
    assert specs[1].kind == "ckpt_crash" and specs[1].seam == "ckpt_commit"

    (s,) = parse_fault_spec("kind=dead_node:n=2:rank=0")
    assert s.n == 2 and s.rank == 0 and s.seam == "dead_node"

    (s,) = parse_fault_spec("kind=nan:sticky=1")
    assert s.sticky and s.seam == "batch"
    assert parse_fault_spec("") == []


def test_parse_fault_spec_rejects_garbage():
    with pytest.raises(ValueError):
        parse_fault_spec("kind=frobnicate")
    with pytest.raises(ValueError):
        parse_fault_spec("step=3")                  # no kind
    with pytest.raises(ValueError):
        parse_fault_spec("kind=nan:wat=1")          # unknown key
    with pytest.raises(ValueError):
        parse_fault_spec("kind")                    # not key=value


def test_fault_spec_fires_once_unless_sticky():
    once = FaultSpec("nan", step=2)
    assert not once.matches("batch", step=1)
    assert once.matches("batch", step=2)
    once.fired = True
    assert not once.matches("batch", step=2)

    sticky = FaultSpec("nan", sticky=True)
    sticky.fired = True
    assert sticky.matches("batch", step=7)


def test_maybe_fault_env_round_trip(monkeypatch):
    _arm(monkeypatch, "step=2:kind=ckpt_crash:seam=ckpt_write")
    assert resilience.maybe_fault("ckpt_write", step=1) is None
    with pytest.raises(InjectedFault):
        resilience.maybe_fault("ckpt_write", step=2)
    # consumed: does not fire twice
    assert resilience.maybe_fault("ckpt_write", step=2) is None


def test_poison_nan_keeps_int_arrays():
    f = resilience.poison_nan(np.ones(3, np.float32))
    assert np.isnan(f).all()
    i = resilience.poison_nan(np.arange(3))
    assert (i == np.arange(3)).all()


# ----------------------------------------------------------------------
# checkpoint manager: atomic, versioned, pruned
# ----------------------------------------------------------------------
def _tree():
    return {"w": jnp.arange(8, dtype=jnp.float32),
            "b": jnp.zeros((3,), jnp.float32)}


def test_ckptmgr_save_latest_prune_auto_resume(tmp_path):
    from mxnet_tpu.parallel.ckpt import abstract_like
    mgr = CheckpointManager(str(tmp_path / "run"), keep=2)
    assert mgr.latest_step() is None
    assert mgr.auto_resume(abstract_like(_tree())) is None

    for step in (1, 2, 5):
        tree = {"w": jnp.arange(8, dtype=jnp.float32) * step,
                "b": jnp.zeros((3,), jnp.float32)}
        mgr.save(tree, step)
    assert mgr.all_steps() == [2, 5]           # keep-last-2 pruned step 1
    assert mgr.latest_step() == 5

    restored, step = mgr.auto_resume(abstract_like(_tree()))
    assert step == 5
    assert np.allclose(np.asarray(restored["w"]), np.arange(8) * 5)

    with pytest.raises(ValueError):
        mgr.save(_tree(), 5)                   # step already committed


def test_ckptmgr_injected_crash_keeps_prior_checkpoint(tmp_path,
                                                       monkeypatch):
    """Acceptance (b): a crash mid-save leaves latest_step() at the
    prior intact checkpoint; the partial write is swept later."""
    mgr = CheckpointManager(str(tmp_path / "run"), keep=0)
    mgr.save(_tree(), 1)

    # crash between the durable tmp write and the commit rename
    _arm(monkeypatch, "kind=ckpt_crash")
    with pytest.raises(InjectedFault):
        mgr.save({"w": jnp.ones(8), "b": jnp.ones(3)}, 2)
    assert mgr.latest_step() == 1              # tmp garbage is invisible
    leftovers = [n for n in os.listdir(mgr.directory)
                 if n.startswith("tmp.")]
    assert leftovers, "expected the uncommitted tmp write on disk"

    # crash BEFORE the write: nothing new on disk either
    _arm(monkeypatch, "kind=ckpt_crash:seam=ckpt_write")
    with pytest.raises(InjectedFault):
        mgr.save({"w": jnp.ones(8), "b": jnp.ones(3)}, 3)
    assert mgr.latest_step() == 1

    # next incarnation saves fine and sweeps the stale tmp
    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    faultinject.reset()
    mgr.save({"w": jnp.ones(8), "b": jnp.ones(3)}, 4)
    assert mgr.latest_step() == 4
    assert not [n for n in os.listdir(mgr.directory)
                if n.startswith("tmp.")]


def test_ocp_save_overwrite_is_atomic(tmp_path, monkeypatch):
    """The flat (non-versioned) ocp_save must never clobber the
    existing checkpoint before the replacement is durable."""
    from mxnet_tpu.parallel.ckpt import ocp_save, ocp_restore, abstract_like
    path = str(tmp_path / "ck")
    ocp_save(path, _tree(), 7)

    _arm(monkeypatch, "kind=ckpt_crash")       # between write and commit
    with pytest.raises(InjectedFault):
        ocp_save(path, {"w": jnp.ones(8), "b": jnp.ones(3)}, 8)
    tree, step = ocp_restore(path, abstract_like(_tree()))
    assert step == 7                           # old checkpoint intact
    assert np.allclose(np.asarray(tree["w"]), np.arange(8))

    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    faultinject.reset()
    ocp_save(path, {"w": jnp.ones(8), "b": jnp.ones(3)}, 8)
    tree, step = ocp_restore(path, abstract_like(_tree()))
    assert step == 8 and np.allclose(np.asarray(tree["w"]), 1.0)


def test_latest_classic_epoch_and_module_load_latest(tmp_path):
    prefix = str(tmp_path / "cls")
    assert latest_classic_epoch(prefix) is None
    mod, epoch = mx.mod.Module.load_latest(prefix)
    assert mod is None and epoch is None

    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                              name="fc"), name="softmax")
    args = {"fc_weight": mx.nd.array(np.ones((2, 4), np.float32)),
            "fc_bias": mx.nd.array(np.zeros(2, np.float32))}
    mx.model.save_checkpoint(prefix, 1, net, args, {})
    mx.model.save_checkpoint(prefix, 3, net, args, {})
    assert latest_classic_epoch(prefix) == 3

    mod, epoch = mx.mod.Module.load_latest(prefix)
    assert epoch == 3 and mod is not None
    assert set(mod._arg_params) == {"fc_weight", "fc_bias"}


# ----------------------------------------------------------------------
# watchdog
# ----------------------------------------------------------------------
def test_run_with_timeout_passthrough_and_timeout():
    assert run_with_timeout(lambda: 41 + 1, 5.0, phase="quick") == 42
    assert run_with_timeout(lambda: 7, None, phase="off") == 7
    with pytest.raises(ZeroDivisionError):
        run_with_timeout(lambda: 1 / 0, 5.0, phase="err")

    t0 = time.monotonic()
    with pytest.raises(ResilienceError) as exc:
        run_with_timeout(lambda: time.sleep(30), 0.3, phase="stuck",
                         step=12)
    assert time.monotonic() - t0 < 5.0         # bounded, not 30s
    err = exc.value
    assert err.kind == "timeout" and err.phase == "stuck" \
        and err.step == 12 and err.timeout_s == 0.3
    assert "phase=stuck" in str(err) and "step=12" in str(err)


def test_watchdog_monitor_fires_on_stall():
    fired = []
    wd = Watchdog(timeout_s=0.3, phase="loop", on_timeout=fired.append,
                  poll_s=0.05)
    with wd:
        wd.feed(step=1)
        time.sleep(0.1)
        wd.feed(step=2)                        # progress: no fire
        assert not wd.fired
        time.sleep(0.8)                        # stall
    assert wd.fired and len(fired) == 1
    err = fired[0]
    assert err.kind == "stall" and err.step == 2 and err.phase == "loop"


def test_watchdog_disabled_without_timeout():
    wd = Watchdog(timeout_s=None, on_timeout=lambda e: None)
    with wd:
        assert wd._thread is None              # unarmed: no monitor


def test_exit_for_restart_subprocess_exits_3():
    """Acceptance (c), exit-code half: the watchdog abort path must
    produce exit code 3 (docs/resilience.md contract)."""
    code = (
        "import time\n"
        "from mxnet_tpu.resilience import run_with_timeout\n"
        "run_with_timeout(lambda: time.sleep(60), 0.2, phase='step',\n"
        "                 step=4, on_timeout='exit')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_ROOT + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          timeout=300, capture_output=True, text=True)
    assert proc.returncode == resilience.EXIT_RESTART, proc.stderr[-2000:]
    assert "RESILIENCE ABORT" in proc.stderr
    assert "phase=step" in proc.stderr and "step=4" in proc.stderr


# ----------------------------------------------------------------------
# retry
# ----------------------------------------------------------------------
def test_retry_call_transient_then_success():
    calls, naps = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("connection refused (transient)")
        return "up"

    policy = RetryPolicy(max_tries=4, base_delay_s=0.5)
    assert retry_call(flaky, policy, sleep=naps.append) == "up"
    assert len(calls) == 3
    assert naps == [0.5, 1.0]                  # exponential, deterministic


def test_retry_call_nonretryable_propagates_immediately():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("num_processes mismatch")  # deterministic config bug

    with pytest.raises(ValueError):
        retry_call(broken, RetryPolicy(max_tries=5), sleep=lambda s: None)
    assert len(calls) == 1                     # no retry for non-transient


def test_retry_call_exhausts_and_raises_last():
    calls = []

    def always_down():
        calls.append(1)
        raise RuntimeError("deadline exceeded")

    with pytest.raises(RuntimeError):
        retry_call(always_down, RetryPolicy(max_tries=3),
                   sleep=lambda s: None)
    assert len(calls) == 3


# ----------------------------------------------------------------------
# host-side sentinel
# ----------------------------------------------------------------------
def test_sentinel_skips_nonfinite_and_backs_off():
    s = Sentinel()
    scale0 = s.loss_scale.scale
    assert s.check(1, loss=0.9) == "ok"
    assert s.check(2, loss=float("nan")) == "skip-nonfinite"
    assert s.loss_scale.scale == scale0 / 2
    assert s.check(3, grad_norm=float("inf")) == "skip-nonfinite"
    assert s.check(4, loss=0.8) == "ok"
    assert s.last_good_step == 4
    assert [rec[0] for rec in s.skipped] == [2, 3]


def test_sentinel_spike_detection():
    s = Sentinel(spike_factor=100.0, warmup_steps=3)
    for step in range(1, 6):
        assert s.check(step, loss=1.0) == "ok"
    assert s.check(6, loss=1e6) == "skip-spike"
    assert s.check(7, loss=1.1) == "ok"


def test_sentinel_escalates_after_max_consecutive_skips():
    s = Sentinel(max_consecutive_skips=3)
    s.check(1, loss=1.0)
    s.check(2, loss=float("nan"))
    s.check(3, loss=float("nan"))
    with pytest.raises(ResilienceError) as exc:
        s.check(4, loss=float("nan"))
    assert exc.value.kind == "numeric"


def test_dynamic_loss_scale_growth_and_clamp():
    from mxnet_tpu.resilience.sentinel import DynamicLossScale
    ls = DynamicLossScale(init=4.0, growth_interval=2, min_scale=1.0,
                          max_scale=8.0)
    ls.good(); ls.good()
    assert ls.scale == 8.0
    ls.good(); ls.good()
    assert ls.scale == 8.0                     # clamped at max
    for _ in range(5):
        ls.bad()
    assert ls.scale == 1.0                     # clamped at min


def test_sentinel_grad_norm_module_structure():
    g = [[mx.nd.array(np.array([3.0, 4.0], np.float32))],
         [None]]
    assert abs(Sentinel.grad_norm(g) - 5.0) < 1e-6
    g_bad = [[mx.nd.array(np.array([np.nan], np.float32))]]
    assert np.isnan(Sentinel.grad_norm(g_bad))


# ----------------------------------------------------------------------
# fused trainer: compiled sentinel gate + injected faults
# ----------------------------------------------------------------------
def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _trainer(sentinel=False, step_timeout_s=None, lr=0.5):
    mesh = parallel.make_mesh(jax.devices()[:2], dp=2)
    opt = mx.optimizer.create("sgd", learning_rate=lr, momentum=0.9,
                              rescale_grad=1.0 / 16)
    tr = parallel.ShardedTrainer(_mlp(), opt, mesh, sentinel=sentinel,
                                 step_timeout_s=step_timeout_s)
    mx.random.seed(3)
    params, opt_state, aux = tr.init_params(
        {"data": (16, 8)}, label_shapes={"softmax_label": (16,)})
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    batch = tr.shard_batch({"data": x, "softmax_label": y})
    return tr, params, opt_state, aux, batch


def _host(params):
    return {k: np.asarray(jax.device_get(v)) for k, v in params.items()}


def test_trainer_sentinel_skips_injected_nan_step(monkeypatch):
    """Acceptance (a): NaN injected at step k -> that step is skipped
    (params unchanged), loss scale halves, training continues."""
    tr, params, opt_state, aux, batch = _trainer(sentinel=True)
    _arm(monkeypatch, "step=3:kind=nan")

    scale0 = None
    for step in range(1, 6):
        before = _host(params)
        params, opt_state, aux, outs = tr.step(params, opt_state, aux,
                                               batch)
        after = _host(params)
        stats = tr.sentinel_stats()
        if step == 1:
            scale0 = stats["scale"]
        if step == 3:
            for name in before:
                assert np.array_equal(before[name], after[name]), \
                    "poisoned step %d must not move %r" % (step, name)
            assert stats["skipped"] == 1
            assert stats["scale"] == scale0 / 2
        else:
            moved = any(not np.array_equal(before[n], after[n])
                        for n in before)
            assert moved, "clean step %d should update params" % step
            assert np.isfinite(np.asarray(outs[0])).all()
    stats = tr.sentinel_stats()
    assert stats["skipped"] == 1 and stats["last_good"] == 5


def test_trainer_sentinel_off_matches_plain_step():
    """The sentinel-off trainer is byte-identical to the pre-resilience
    step (no scaled cotangents, no gating)."""
    tr_a, pa, oa, aa, batch = _trainer(sentinel=False)
    tr_b, pb, ob, ab, _ = _trainer(sentinel=True)
    for _ in range(3):
        pa, oa, aa, _ = tr_a.step(pa, oa, aa, batch)
        pb, ob, ab, _ = tr_b.step(pb, ob, ab, batch)
    ha, hb = _host(pa), _host(pb)
    for name in ha:
        assert np.allclose(ha[name], hb[name], rtol=1e-5, atol=1e-6), name


def test_trainer_sentinel_learns():
    tr, params, opt_state, aux, batch = _trainer(sentinel=True)
    y = None
    for _ in range(30):
        params, opt_state, aux, outs = tr.step(params, opt_state, aux,
                                               batch)
    stats = tr.sentinel_stats()
    assert stats["skipped"] == 0
    pred = np.asarray(outs[0]).argmax(axis=1)
    x = np.asarray(jax.device_get(batch["data"]))
    labels = (x.sum(axis=1) > 0).astype(np.int64)
    assert (pred == labels).mean() > 0.9


def test_trainer_watchdog_catches_injected_hang(monkeypatch):
    """Acceptance (c): an injected hang inside the step converts into a
    structured ResilienceError within the timeout."""
    tr, params, opt_state, aux, batch = _trainer(step_timeout_s=1.0)
    # step 1 compiles + runs clean; step 2 hangs
    params, opt_state, aux, _ = tr.step(params, opt_state, aux, batch)
    _arm(monkeypatch, "step=2:kind=hang:seconds=20")
    t0 = time.monotonic()
    with pytest.raises(ResilienceError) as exc:
        tr.step(params, opt_state, aux, batch)
    assert time.monotonic() - t0 < 10.0
    err = exc.value
    assert err.kind == "timeout" and err.phase == "train_step" \
        and err.step == 2 and err.rank == 0


def test_trainer_slow_step_under_timeout_succeeds(monkeypatch):
    tr, params, opt_state, aux, batch = _trainer(step_timeout_s=30.0)
    params, opt_state, aux, _ = tr.step(params, opt_state, aux, batch)
    _arm(monkeypatch, "step=2:kind=slow:seconds=0.2")
    params, opt_state, aux, _ = tr.step(params, opt_state, aux, batch)
    assert tr.num_update == 2                  # slow but not stuck


def test_trainer_versioned_checkpoint_auto_resume(tmp_path):
    ckdir = str(tmp_path / "ckpts")
    tr, params, opt_state, aux, batch = _trainer()
    for _ in range(2):
        params, opt_state, aux, _ = tr.step(params, opt_state, aux, batch)
    tr.save_checkpoint_versioned(ckdir, params, opt_state, aux, keep=3)
    params, opt_state, aux, _ = tr.step(params, opt_state, aux, batch)
    tr.save_checkpoint_versioned(ckdir, params, opt_state, aux, keep=3)
    assert tr.latest_step(ckdir) == 3
    want = _host(params)

    tr2, _, _, _, _ = _trainer()
    resumed = tr2.auto_resume(ckdir, {"data": (16, 8)},
                              label_shapes={"softmax_label": (16,)})
    assert resumed is not None
    p2, o2, a2, step = resumed
    assert step == 3 and tr2.num_update == 3
    got = _host(p2)
    for name in want:
        assert np.allclose(want[name], got[name]), name

    # fresh directory -> None (the "first boot" branch)
    tr3, _, _, _, _ = _trainer()
    assert tr3.auto_resume(str(tmp_path / "fresh"), {"data": (16, 8)},
                           label_shapes={"softmax_label": (16,)}) is None


# ----------------------------------------------------------------------
# host training loops: sentinel + poisoned grads
# ----------------------------------------------------------------------
def test_feedforward_sentinel_survives_injected_nan(monkeypatch,
                                                    tmp_path):
    """The classic fit loop keeps training through an injected NaN
    batch when MXTPU_SENTINEL=1 (grad-norm gate skips the update)."""
    rng = np.random.RandomState(0)
    X = rng.randn(60, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))

    monkeypatch.setenv("MXTPU_SENTINEL", "1")
    _arm(monkeypatch, "step=2:kind=nan")
    model = mx.FeedForward(net, ctx=mx.context.cpu(), num_epoch=8,
                           optimizer="sgd", learning_rate=0.3,
                           initializer=mx.init.Uniform(0.1))
    model.fit(mx.io.NDArrayIter(X, y, batch_size=20))
    # params survived the poisoned step: finite and usable
    for name, arr in model.arg_params.items():
        assert np.isfinite(arr.asnumpy()).all(), name
    acc = model.score(mx.io.NDArrayIter(X, y, batch_size=20))
    assert acc > 0.65


def test_feedforward_without_sentinel_is_poisoned(monkeypatch):
    """Control for the test above: the same injected NaN without the
    sentinel propagates into the parameters — the failure the sentinel
    exists to stop."""
    rng = np.random.RandomState(0)
    X = rng.randn(60, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    _arm(monkeypatch, "step=2:kind=nan")
    model = mx.FeedForward(net, ctx=mx.context.cpu(), num_epoch=1,
                           optimizer="sgd", learning_rate=0.3,
                           initializer=mx.init.Uniform(0.1))
    model.fit(mx.io.NDArrayIter(X, y, batch_size=20))
    assert any(not np.isfinite(a.asnumpy()).all()
               for a in model.arg_params.values())


def test_feedforward_fit_checkpoint_and_auto_resume(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(40, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    net = mx.models.get_mlp(num_classes=2, hidden=(8,))
    prefix = str(tmp_path / "ff")

    model = mx.FeedForward(net, ctx=mx.context.cpu(), num_epoch=2,
                           optimizer="sgd", learning_rate=0.1,
                           initializer=mx.init.Uniform(0.1))
    model.fit(mx.io.NDArrayIter(X, y, batch_size=20),
              checkpoint_prefix=prefix)
    assert latest_classic_epoch(prefix) == 2   # do_checkpoint auto-wired

    resumed = mx.FeedForward(net, ctx=mx.context.cpu(), num_epoch=3,
                             optimizer="sgd", learning_rate=0.1,
                             initializer=mx.init.Uniform(0.1))
    resumed.fit(mx.io.NDArrayIter(X, y, batch_size=20),
                checkpoint_prefix=prefix, resume="auto")
    assert resumed.begin_epoch == 2            # picked up where A stopped
    assert latest_classic_epoch(prefix) == 3

    with pytest.raises(mx.base.MXNetError):
        mx.FeedForward(net, ctx=mx.context.cpu(), num_epoch=1).fit(
            mx.io.NDArrayIter(X, y, batch_size=20), resume="auto")


# ----------------------------------------------------------------------
# kvstore fault surface
# ----------------------------------------------------------------------
class _FakeClient(object):
    def __init__(self):
        self.kv = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        self.kv[key] = value

    def key_value_dir_get(self, prefix):
        return [(k, v) for k, v in self.kv.items()
                if k.startswith(prefix)]


def test_num_dead_nodes_timeout_and_expiry(monkeypatch):
    from mxnet_tpu import kvstore as kvs
    clock = {"now": 1000.0}
    fake = _FakeClient()
    monkeypatch.setattr(kvs, "_now", lambda: clock["now"])
    monkeypatch.setattr(kvs, "_dist_client", lambda: fake)

    kv = kvs.KVStore("dist_sync")              # _created = 1000.0
    fake.kv["mxtpu_hb/0"] = repr(1000.0)
    assert kv.num_dead_nodes(node_id=0, timeout=10.0) == 0
    clock["now"] = 1011.0                      # stamp is now stale
    assert kv.num_dead_nodes(node_id=0, timeout=10.0) == 1
    fake.kv["mxtpu_hb/0"] = repr(1010.5)       # peer beat again: alive
    assert kv.num_dead_nodes(node_id=0, timeout=10.0) == 0

    # missing stamp: grace until `timeout` after store creation
    assert kv.num_dead_nodes(node_id=1, timeout=20.0) == 0
    clock["now"] = 1030.0
    assert kv.num_dead_nodes(node_id=1, timeout=20.0) == 1
    # non-dist stores never report deaths
    assert kvs.KVStore("local").num_dead_nodes() == 0


def test_num_dead_nodes_injected_dead_node(monkeypatch):
    from mxnet_tpu import kvstore as kvs
    _arm(monkeypatch, "kind=dead_node:n=2")
    kv = kvs.KVStore("dist_sync")
    assert kv.num_dead_nodes() == 2
    assert kv.num_dead_nodes() == 0            # spec consumed


def test_heartbeat_idempotent_and_stoppable(monkeypatch):
    from mxnet_tpu import kvstore as kvs
    fake = _FakeClient()
    monkeypatch.setattr(kvs, "_dist_client", lambda: fake)
    try:
        kvs._start_heartbeat()
        t = kvs._HB_STATE["thread"]
        assert t is not None and t.is_alive()
        kvs._start_heartbeat()                 # idempotent: same thread
        assert kvs._HB_STATE["thread"] is t
        assert t.daemon, "heartbeat must never block interpreter exit"
        deadline = time.time() + 5
        while not fake.kv and time.time() < deadline:
            time.sleep(0.01)
        assert any(k.startswith("mxtpu_hb/") for k in fake.kv)
    finally:
        kvs._stop_heartbeat()
    assert not t.is_alive()
    assert kvs._HB_STATE["thread"] is None
    # restartable after a stop (fresh store in the same process)
    kvs._start_heartbeat()
    assert kvs._HB_STATE["thread"].is_alive()
    kvs._stop_heartbeat()


def test_kvstore_barrier_watchdog_single_process(monkeypatch):
    """With one process the barrier is a no-op even when armed."""
    monkeypatch.setenv("MXTPU_STEP_TIMEOUT_S", "1.0")
    kv = mx.kvstore.KVStore("dist_sync")
    kv.barrier()                               # must not raise or hang


# ----------------------------------------------------------------------
# monitor nonfinite alarm
# ----------------------------------------------------------------------
def test_monitor_alarm_nonfinite():
    mon = mx.monitor.Monitor(interval=1, alarm_nonfinite=True)
    mon.activated = True
    mon._record("clean", mx.nd.array(np.ones(4, np.float32)))
    assert mon.nonfinite_records == []
    mon._record("poisoned",
                mx.nd.array(np.array([1.0, np.inf], np.float32)))
    assert len(mon.nonfinite_records) == 1
    step, name, _stat = mon.nonfinite_records[0]
    assert name == "poisoned"


# ----------------------------------------------------------------------
# 2-worker kill-and-resume smoke (tier-1 promotion of the nightly
# drill: phases A+B of tests/nightly/dist_resume.py)
# ----------------------------------------------------------------------
def _launch(script, n=2, port=9899, extra_env=None, expect_rc=0):
    cmd = [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
           "-n", str(n), "--launcher", "local", "--workdir", _ROOT,
           "--port", str(port),
           sys.executable, os.path.join("tests", "nightly", script)]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.update(extra_env or {})
    proc = subprocess.run(cmd, cwd=_ROOT, env=env, timeout=420,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True)
    assert proc.returncode == expect_rc, (proc.returncode,
                                          proc.stdout[-2000:])
    return proc.stdout


def test_kill_and_resume_smoke(tmp_path):
    """Acceptance (d): kill one worker; the survivor detects it and
    exits with the restart signal (launcher propagates 3); the
    restarted job resumes from the checkpoint, replays the identical
    batch order, and the loss keeps improving."""
    prefix = str(tmp_path / "resume")
    out = _launch("dist_resume.py", port=9899,
                  extra_env={"MXTPU_FAULT_RANK": "1",
                             "MXTPU_RESUME_PREFIX": prefix},
                  expect_rc=3)
    assert "detected 1 dead node" in out, out[-1500:]
    assert os.path.exists(prefix + "-0001.params")
    out = _launch("dist_resume.py", port=9900,
                  extra_env={"MXTPU_RESUME": "1",
                             "MXTPU_RESUME_PREFIX": prefix})
    assert out.count("resume OK") == 2, out[-1500:]


# ----------------------------------------------------------------------
# elastic re-mesh: liveness identities, ledger/fence, decision protocol
# ----------------------------------------------------------------------
from mxnet_tpu.resilience import elastic  # noqa: E402


def test_dead_nodes_returns_sorted_identities(monkeypatch):
    from mxnet_tpu import kvstore as kvs
    clock = {"now": 1000.0}
    fake = _FakeClient()
    monkeypatch.setattr(kvs, "_now", lambda: clock["now"])
    monkeypatch.setattr(kvs, "_dist_client", lambda: fake)
    monkeypatch.setattr(kvs.jax, "process_count", lambda: 3)
    kv = kvs.KVStore("dist_sync")
    fake.kv["mxtpu_hb/0"] = repr(1000.0)
    fake.kv["mxtpu_hb/1"] = repr(1000.0)
    fake.kv["mxtpu_hb/2"] = repr(1000.0)
    assert kv.dead_nodes(timeout=10.0) == []
    clock["now"] = 1011.0
    fake.kv["mxtpu_hb/1"] = repr(1010.0)       # only 1 kept beating
    assert kv.dead_nodes(timeout=10.0) == [0, 2]
    assert kv.dead_nodes(node_id=2, timeout=10.0) == [2]
    assert kv.dead_nodes(node_id=1, timeout=10.0) == []
    assert kv.num_dead_nodes(timeout=10.0) == 2
    assert kvs.KVStore("local").dead_nodes() == []


def test_elastic_ledger_round_trip_and_knobs(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_ELASTIC_DIR", str(tmp_path))
    assert elastic.read_ledger() is None       # fresh: unreadable = None
    verdict = {"generation": 3, "world_size": 2, "members": [0, 1],
               "reason": "dead_node", "from_world": 3}
    elastic.write_ledger(verdict)
    assert elastic.read_ledger() == verdict
    assert not os.path.exists(elastic.ledger_path() + ".tmp")
    # generation(): env stamp wins, ledger is the fallback
    monkeypatch.delenv("MXTPU_ELASTIC_GENERATION", raising=False)
    assert elastic.generation() == 3
    monkeypatch.setenv("MXTPU_ELASTIC_GENERATION", "5")
    assert elastic.generation() == 5
    # capacity file: absent -> default, garbage -> default
    assert elastic.capacity() is None
    with open(elastic.capacity_path(), "w") as f:
        f.write("2\n")
    assert elastic.capacity() == 2
    monkeypatch.setenv("MXTPU_ELASTIC_MIN_WORLD", "2")
    assert elastic.min_world() == 2
    monkeypatch.setenv("MXTPU_ELASTIC_TARGET_WORLD", "4")
    assert elastic.target_world() == 4


def test_generation_fence_stale_raises(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_ELASTIC_GENERATION", "0")
    elastic.write_ledger({"generation": 1, "world_size": 2})
    # not elastic -> never fences (plain jobs must be unaffected)
    monkeypatch.delenv("MXTPU_ELASTIC", raising=False)
    elastic.check_generation_fence()
    monkeypatch.setenv("MXTPU_ELASTIC", "1")
    with pytest.raises(ResilienceError) as ei:
        elastic.check_generation_fence()
    assert ei.value.kind == "stale_generation"
    # at or past the agreed generation: clean
    monkeypatch.setenv("MXTPU_ELASTIC_GENERATION", "1")
    elastic.check_generation_fence()


class _FakeElasticKV(object):
    def __init__(self, rank, num_workers, dead=()):
        self.rank = rank
        self.num_workers = num_workers
        self._dead = sorted(dead)

    def dead_nodes(self, node_id=None, timeout=None):
        return list(self._dead)


class _FakePollClient(object):
    def __init__(self):
        self.kv = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        self.kv[key] = value

    def blocking_key_value_get(self, key, timeout_ms):
        if key in self.kv:
            return self.kv[key]
        raise RuntimeError("DEADLINE_EXCEEDED waiting for %s" % key)

    def key_value_delete(self, key):
        self.kv.pop(key, None)


@pytest.fixture
def _elastic_env(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_ELASTIC", "1")
    monkeypatch.setenv("MXTPU_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_ELASTIC_GENERATION", "0")
    monkeypatch.setenv("MXTPU_ELASTIC_TARGET_WORLD", "3")
    client = _FakePollClient()
    monkeypatch.setattr(elastic, "_kv_client", lambda: client)
    return client


def test_poll_remesh_shrink_verdict(_elastic_env, monkeypatch):
    client = _elastic_env
    kv = _FakeElasticKV(0, 3, dead=[2])
    verdict = elastic.poll_remesh(kv, elastic.recover_round(2),
                                  dead_timeout=6.0)
    assert verdict["generation"] == 1
    assert verdict["world_size"] == 2
    assert verdict["members"] == [0, 1]
    assert verdict["reason"] == "dead_node"
    assert verdict["from_world"] == 3
    # ledger persisted BEFORE publication; key carries generation+round
    assert elastic.read_ledger() == verdict
    key = "mxtpu_elastic/poll/0/recover-2"
    assert json.loads(client.kv[key]) == verdict
    # a survivor adopting the same round reads the identical verdict
    kv1 = _FakeElasticKV(1, 3)
    assert elastic.poll_remesh(kv1, elastic.recover_round(2),
                               timeout_s=1.0) == verdict


def test_poll_remesh_grow_toward_capacity_capped_at_target(
        _elastic_env, tmp_path):
    with open(elastic.capacity_path(), "w") as f:
        f.write("5")                           # more than we ever want
    kv = _FakeElasticKV(0, 2)
    verdict = elastic.poll_remesh(kv, 7)
    assert verdict["reason"] == "grow"
    assert verdict["world_size"] == 3          # capped at target, not 5
    assert verdict["members"] == [0, 1, 2]


def test_poll_remesh_no_verdict_publishes_marker(_elastic_env):
    client = _elastic_env
    kv = _FakeElasticKV(0, 3)
    assert elastic.poll_remesh(kv, 4) is None
    assert client.kv["mxtpu_elastic/poll/0/4"] == "none"
    # the no-op marker is what non-coordinators read: no race, no guess
    assert elastic.poll_remesh(_FakeElasticKV(1, 3), 4,
                               timeout_s=1.0) is None


def test_poll_remesh_orphan_raises_for_restart(_elastic_env):
    kv = _FakeElasticKV(1, 3)                  # coordinator never writes
    with pytest.raises(ResilienceError) as ei:
        elastic.poll_remesh(kv, 9, timeout_s=0.1)
    assert ei.value.kind == "remesh_orphan"


def test_restore_mismatch_names_every_leaf_host_format(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=0, payload_format="host")
    tree = {"w": np.ones((4, 4), np.float32),
            "b": np.zeros((4,), np.float32)}
    mgr.save(tree, 1)
    got, step = mgr.restore({"w": np.zeros((4, 4), np.float32),
                             "b": np.zeros((4,), np.float32)})
    assert step == 1
    assert np.array_equal(got["w"], tree["w"])
    with pytest.raises(ResilienceError) as ei:
        mgr.restore({"w": np.zeros((2, 4), np.float32),
                     "b": np.zeros((4,), np.float64)})
    err = ei.value
    assert err.kind == "restore_mismatch"
    msg = str(err)
    assert "w" in msg and "(2, 4)" in msg      # the mismatched leaf,
    assert "b" in msg and "float64" in msg     # named with its want/got


def test_restore_mismatch_orbax_format(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=0)
    mgr.save({"w": np.arange(8, dtype=np.float32)}, 2)
    with pytest.raises(ResilienceError) as ei:
        mgr.restore({"w": np.zeros((3,), np.float32)})
    assert ei.value.kind == "restore_mismatch"
    assert "w" in str(ei.value)
    # structure mismatch (absent leaf) is named too, not an opaque diff
    with pytest.raises(ResilienceError) as ei:
        mgr.restore({"w": np.zeros((8,), np.float32),
                     "extra": np.zeros((1,), np.float32)})
    assert "extra" in str(ei.value)


def test_checkpoint_world_size_round_trip(tmp_path):
    """Satellite: save under dp=2, restore under dp=1, re-save, restore
    under dp=2 — orbax reshards on restore and every leaf survives
    bit-identical through both world-size changes."""
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    tr, params, opt_state, aux, batch = _trainer()
    for _ in range(2):
        params, opt_state, aux, _ = tr.step(params, opt_state, aux, batch)
    tr.save_checkpoint_versioned(d1, params, opt_state, aux, keep=0)
    want = _host(params)

    mesh1 = parallel.make_mesh(jax.devices()[:1], dp=1)
    opt = mx.optimizer.create("sgd", learning_rate=0.5, momentum=0.9,
                              rescale_grad=1.0 / 16)
    tr1 = parallel.ShardedTrainer(_mlp(), opt, mesh1)
    resumed = tr1.auto_resume(d1, {"data": (16, 8)},
                              label_shapes={"softmax_label": (16,)})
    assert resumed is not None
    p1, o1, a1, step = resumed
    assert step == 2
    mid = _host(p1)
    for name in want:
        assert np.array_equal(want[name], mid[name]), name
    tr1.save_checkpoint_versioned(d2, p1, o1, a1, keep=0)

    tr2, _, _, _, _ = _trainer()               # back to dp=2
    p2, o2, a2, step2 = tr2.auto_resume(
        d2, {"data": (16, 8)}, label_shapes={"softmax_label": (16,)})
    assert step2 == 2
    got = _host(p2)
    for name in want:
        assert np.array_equal(want[name], got[name]), name
    # and the re-grown trainer still steps under the restored layout
    tr2.step(p2, o2, a2, batch)


def test_ndarrayiter_partition_tiles_dataset():
    X = np.arange(100, dtype=np.float32).reshape(100, 1)
    for shuffle in (False, True):
        for nw in (1, 2, 3, 5):
            for epoch in (0, 1, 4):
                parts = []
                for r in range(nw):
                    it = mx.io.NDArrayIter(X, batch_size=10,
                                           shuffle=shuffle, seed=11,
                                           num_parts=nw, part_index=r)
                    it.set_state({"epoch": epoch, "cursor": -10})
                    parts.append([int(i) for i in it.idx])
                flat = sorted(i for p in parts for i in p)
                assert flat == list(range(100)), (shuffle, nw, epoch)
    # stride partition of the SAME global permutation: a world-size
    # change reassigns samples but never changes the epoch's order
    a = mx.io.NDArrayIter(X, batch_size=10, shuffle=True, seed=11,
                          num_parts=2, part_index=0).idx
    b = mx.io.NDArrayIter(X, batch_size=10, shuffle=True, seed=11,
                          num_parts=2, part_index=1).idx
    full = mx.io.NDArrayIter(X, batch_size=10, shuffle=True, seed=11).idx
    order = np.empty(100, dtype=full.dtype)
    order[0::2], order[1::2] = a, b
    assert np.array_equal(order, full)


def test_ndarrayiter_partition_validation():
    X = np.zeros((20, 1), np.float32)
    with pytest.raises(mx.base.MXNetError):
        mx.io.NDArrayIter(X, batch_size=5, num_parts=2, part_index=2)
    with pytest.raises(mx.base.MXNetError):
        mx.io.NDArrayIter(X, batch_size=5, num_parts=0)
    with pytest.raises(mx.base.MXNetError):
        mx.io.NDArrayIter(X, batch_size=5, shuffle=True,
                          num_parts=2, part_index=0)   # needs seed


def test_remesh_axis_math():
    lm = parallel.LogicalMesh(dp=4, tp=2)
    assert dict(parallel.remesh(lm, total=6).shape) == {"dp": 3, "tp": 2}
    with pytest.raises(ValueError):
        parallel.remesh(lm, total=5)           # tp=2 doesn't divide 5
    with pytest.raises(ValueError):
        parallel.remesh(parallel.LogicalMesh(tp=2), total=4)  # no dp
    with pytest.raises(ValueError):
        parallel.remesh(lm)                    # LogicalMesh needs total=
    m = parallel.make_mesh(jax.devices()[:4], dp=2, tp=2)
    m2 = parallel.remesh(m, devices=jax.devices()[:6])
    assert dict(m2.shape) == {"dp": 3, "tp": 2}
    assert m2.devices is not None              # a live mesh, bindable


# ----------------------------------------------------------------------
# 3-worker shrink/grow drill (tier-1 promotion of
# tests/nightly/dist_elastic.py under the elastic supervise loop)
# ----------------------------------------------------------------------
def _launch_raw(cmd_args, extra_env=None, expect_rc=0, timeout=420):
    cmd = [sys.executable, os.path.join(_ROOT, "tools", "launch.py")] \
        + cmd_args
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.update(extra_env or {})
    proc = subprocess.run(cmd, cwd=_ROOT, env=env, timeout=timeout,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True)
    assert proc.returncode == expect_rc, (proc.returncode,
                                          proc.stdout[-3000:])
    return proc.stdout


@pytest.mark.slow
def test_elastic_shrink_grow_drill(tmp_path):
    """The ISSUE 7 acceptance drill: 3 workers, one dies mid-training ->
    survivors agree one generation-stamped shrink verdict, re-mesh to
    world 2 and resume from the latest checkpoint; capacity returns ->
    grow verdict back to world 3; every transition leaves the agreed
    generation in the ledger and propose/adopt/resume telemetry with
    matching generations on all ranks; post-transition loss
    trajectories are bit-identical to fresh fixed-world runs from the
    same checkpoints."""
    edir = str(tmp_path / "elastic")
    tdir = os.path.join(edir, "telemetry")
    drill = os.path.join("tests", "nightly", "dist_elastic.py")
    _launch_raw(["-n", "3", "--launcher", "local", "--workdir", _ROOT,
                 "--port", "9906", "--elastic", "--min-world", "2",
                 "--elastic-dir", edir, "--max-restarts", "4",
                 sys.executable, drill],
                extra_env={"MXTPU_STEP_TIMEOUT_S": "12",
                           "MXTPU_TELEMETRY_DIR": tdir})

    # final agreement: generation 2, grown back to world 3
    with open(os.path.join(edir, "LEDGER.json")) as f:
        led = json.load(f)
    assert led["generation"] == 2 and led["world_size"] == 3
    assert led["reason"] == "grow"

    # one loss row per epoch, worlds 3,3 -> 2 -> 3,3 across generations
    with open(os.path.join(edir, "losses-elastic.jsonl")) as f:
        rows = [json.loads(line) for line in f]
    assert [r["epoch"] for r in rows] == [0, 1, 2, 3, 4]
    assert [r["world"] for r in rows] == [3, 3, 2, 3, 3]
    assert [r["generation"] for r in rows] == [0, 0, 1, 2, 2]

    # every completed epoch's partitions tile the dataset exactly:
    # no sample dropped or duplicated through either transition
    for gen, epoch, world in ((0, 0, 3), (0, 1, 3), (1, 2, 2),
                              (2, 3, 3), (2, 4, 3)):
        idx = []
        for r in range(world):
            p = os.path.join(edir, "part-g%d-e%03d-r%02d.json"
                             % (gen, epoch, r))
            with open(p) as f:
                d = json.load(f)
            assert d["world"] == world
            idx += d["indices"]
        assert sorted(idx) == list(range(240)), (gen, epoch)

    # telemetry: propose/adopt pairs agree on generation+world+reason,
    # and each incarnation emitted a resume for its whole world
    recs = []
    for path in glob.glob(os.path.join(tdir, "events-rank*.jsonl*")):
        with open(path) as f:
            recs += [json.loads(line) for line in f if line.strip()]
    el = [r for r in recs if r.get("kind") == "elastic"]
    props = {(r["generation"], r["world_size"], r["reason"])
             for r in el if r["event"] == "propose"}
    adopts = {(r["generation"], r["world_size"], r["reason"])
              for r in el if r["event"] == "adopt"}
    assert props == {(1, 2, "dead_node"), (2, 3, "grow")}
    assert adopts == props
    resumes = [(r["generation"], r["world_size"])
               for r in el if r["event"] == "resume"]
    assert resumes.count((0, 3)) == 3
    assert resumes.count((1, 2)) == 2
    assert resumes.count((2, 3)) == 3

    # loss trajectory after each transition == a fresh fixed-world run
    # resumed from the same checkpoint (the agreement protocol must not
    # perturb the math)
    for world, step, stop, port in ((2, 2, 3, "9912"), (3, 3, 5, "9913")):
        _launch_raw(["-n", str(world), "--launcher", "local",
                     "--workdir", _ROOT, "--port", port,
                     sys.executable, drill],
                    extra_env={"MXTPU_ELASTIC_DIR": edir,
                               "MXTPU_ELASTIC_REFERENCE": "1",
                               "MXTPU_RESUME_STEP": str(step),
                               "MXTPU_STOP_EPOCH": str(stop)})
        ref = os.path.join(edir, "losses-ref-w%d-s%d.jsonl" % (world,
                                                               step))
        with open(ref) as f:
            ref_rows = [json.loads(line) for line in f]
        assert ref_rows, "reference run recorded no losses"
        by_epoch = {r["epoch"]: r for r in rows}
        for r in ref_rows:
            assert r["loss"] == by_epoch[r["epoch"]]["loss"], \
                (world, r["epoch"])


# ----------------------------------------------------------------------
# warm elasticity: redundant host-memory hot state
# (docs/resilience.md "Warm elasticity")
# ----------------------------------------------------------------------
from mxnet_tpu.resilience import hotstate  # noqa: E402
from mxnet_tpu.resilience.hotstate import HotStateUnavailable  # noqa: E402


def _warm_env(tmp_path, monkeypatch, **env):
    monkeypatch.setenv("MXTPU_WARM_REMESH", "1")
    monkeypatch.setenv("MXTPU_HANDOFF_DIR", str(tmp_path / "handoff"))
    for var in ("MXTPU_NUM_HOSTS", "MXTPU_HOST_INDEX",
                "MXTPU_HOTSTATE_BUDDIES", "MXTPU_ELASTIC_GENERATION"):
        monkeypatch.delenv(var, raising=False)
    for key, val in env.items():
        monkeypatch.setenv(key, val)


def _warm_tree(scale=1.0):
    return {"params": {"w": np.arange(12, dtype=np.float32)
                       .reshape(3, 4) * scale,
                       "b": np.ones(4, np.float32) * scale},
            "opt_state": {"m": np.zeros((3, 4), np.float32)}}


def _warm_abstract():
    return {"params": {"w": np.zeros((3, 4), np.float32),
                       "b": np.zeros(4, np.float32)},
            "opt_state": {"m": np.zeros((3, 4), np.float32)}}


def test_hotstate_snapshot_warm_resume_roundtrip(tmp_path, monkeypatch):
    _warm_env(tmp_path, monkeypatch)
    tree = _warm_tree()
    hotstate.snapshot(tree, step=5)
    out, step, meta = hotstate.warm_resume(_warm_abstract())
    assert step == 5 and meta["n_payloads"] == 1
    for group in ("params", "opt_state"):
        for leaf, want in tree[group].items():
            assert np.array_equal(out[group][leaf], want), (group, leaf)
    # without an abstract target the manifests' own nesting comes back
    out2, step2, _ = hotstate.warm_resume(None)
    assert step2 == 5
    assert np.array_equal(out2["params"]["b"], tree["params"]["b"])


def test_hotstate_newest_complete_step_wins(tmp_path, monkeypatch):
    _warm_env(tmp_path, monkeypatch)
    hotstate.snapshot(_warm_tree(scale=1.0), step=3)
    hotstate.snapshot(_warm_tree(scale=7.0), step=9)
    out, step, _ = hotstate.warm_resume(_warm_abstract())
    assert step == 9
    assert np.array_equal(out["params"]["w"],
                          _warm_tree(scale=7.0)["params"]["w"])


def test_hotstate_disabled_and_cold_verdicts(tmp_path, monkeypatch):
    _warm_env(tmp_path, monkeypatch)
    # empty handoff area -> cold verdict, reason no_payloads
    verdict = hotstate.decide_sources()
    assert verdict == {"mode": "cold", "reason": "no_payloads"}
    with pytest.raises(HotStateUnavailable) as ei:
        hotstate.warm_resume(_warm_abstract())
    assert ei.value.reason == "cold_verdict"
    # a group missing one rank's payload never satisfies the directory
    hotstate._write_payload(
        {"params/w": [([[0, 3], [0, 4]],
                       np.zeros((3, 4), np.float32))]},
        step=4, rank=0, world=2, host=0, namespace="train")
    assert hotstate.decide_sources()["reason"] == "incomplete"
    # the knob itself off -> structured "disabled", nothing read
    monkeypatch.setenv("MXTPU_WARM_REMESH", "0")
    assert not hotstate.warm_enabled()
    with pytest.raises(HotStateUnavailable) as ei:
        hotstate.warm_resume(_warm_abstract())
    assert ei.value.reason == "disabled"


def test_hotstate_buddy_lands_off_host_and_survives_host_loss(
        tmp_path, monkeypatch):
    """4 ranks on 2 simulated hosts; burning host 1 leaves every rank's
    sharded state reconstructible from host 0 (owns + buddy replicas)."""
    _warm_env(tmp_path, monkeypatch, MXTPU_NUM_HOSTS="2")
    # contiguous-block host map, and buddies never on their own host
    assert [hotstate.host_index(r, 4) for r in range(4)] == [0, 0, 1, 1]
    assert hotstate.buddy_hosts(0, 4) == [1]
    assert hotstate.buddy_hosts(3, 4) == [0]
    w = np.arange(16, dtype=np.float32).reshape(4, 4)
    for rank in range(4):
        hotstate._write_payload(
            {"params/w": [([[rank, rank + 1], [0, 4]],
                           w[rank:rank + 1])]},
            step=7, rank=rank, world=4,
            host=hotstate.host_index(rank, 4), namespace="train")
    hotstate.simulate_host_loss(1)
    verdict = hotstate.decide_sources()
    assert verdict["mode"] == "warm" and verdict["step"] == 7
    assert verdict["n_buddy"] == 2          # ranks 2,3 serve via buddies
    out, step, meta = hotstate.load_sources(
        verdict, {"params": {"w": np.zeros((4, 4), np.float32)}})
    assert step == 7 and meta["n_payloads"] == 4
    assert np.array_equal(out["params"]["w"], w)


def test_hotstate_buddy_loss_seam_drops_redundancy(tmp_path, monkeypatch):
    _warm_env(tmp_path, monkeypatch, MXTPU_NUM_HOSTS="2")
    _arm(monkeypatch, "kind=buddy_loss:rank=0")
    hotstate.snapshot(_warm_tree(), step=2, rank=0, world=2)
    recs = hotstate.scan()
    assert {r["source"] for r in recs} == {"own"}   # replica push lost
    # own host burns -> nothing left to serve rank 0 -> cold
    hotstate.simulate_host_loss(0)
    assert hotstate.decide_sources()["mode"] == "cold"


def test_hotstate_corrupt_payload_is_rejected_by_crc(tmp_path,
                                                     monkeypatch):
    _warm_env(tmp_path, monkeypatch)
    hotstate.snapshot(_warm_tree(), step=5)
    _arm(monkeypatch, "kind=corrupt:rank=0")
    with pytest.raises(HotStateUnavailable) as ei:
        hotstate.warm_resume(_warm_abstract())
    assert ei.value.reason == "crc_mismatch"
    # the fault fired once: the next attempt reads clean bytes
    out, step, _ = hotstate.warm_resume(_warm_abstract())
    assert step == 5
    assert np.array_equal(out["params"]["b"], np.ones(4, np.float32))


def test_hotstate_snapshot_crash_seam_raises_injected(tmp_path,
                                                      monkeypatch):
    _warm_env(tmp_path, monkeypatch)
    _arm(monkeypatch, "kind=snapshot_crash:step=3")
    with pytest.raises(InjectedFault):
        hotstate.snapshot(_warm_tree(), step=3)
    assert hotstate.scan() == []            # nothing half-written


def test_hotstate_target_mismatch_names_leaf(tmp_path, monkeypatch):
    _warm_env(tmp_path, monkeypatch)
    hotstate.snapshot(_warm_tree(), step=1)
    bad = _warm_abstract()
    bad["params"]["w"] = np.zeros((5, 4), np.float32)
    with pytest.raises(HotStateUnavailable) as ei:
        hotstate.warm_resume(bad)
    assert ei.value.reason == "target_mismatch"
    assert "params/w" in str(ei.value)


def test_trainer_warm_elastic_resume_and_checkpoint_fallback(
        tmp_path, monkeypatch):
    """ShardedTrainer.elastic_resume: the warm rung re-places the
    handoff tree with the trainer's shardings and never opens a
    checkpoint; a corrupt payload degrades to the checkpoint rung with
    the fallback reason in the resume telemetry."""
    _warm_env(tmp_path, monkeypatch)
    ckdir = str(tmp_path / "ckpts")
    shapes = {"data": (16, 8)}
    lbl = {"softmax_label": (16,)}
    tr, params, opt_state, aux, batch = _trainer()
    for _ in range(2):
        params, opt_state, aux, _ = tr.step(params, opt_state, aux, batch)
    tr.save_checkpoint_versioned(ckdir, params, opt_state, aux)
    tr.hotstate_snapshot(params, opt_state, aux)
    want = _host(params)

    events = []
    monkeypatch.setattr(elastic, "emit_transition",
                        lambda event, **f: events.append((event, f)))
    tr2, _, _, _, _ = _trainer()
    got = tr2.elastic_resume(ckdir, shapes, label_shapes=lbl,
                             source="warm")
    assert got is not None
    p2, _, _, step = got
    assert step == 2 and tr2.num_update == 2
    for name, arr in _host(p2).items():
        assert np.array_equal(want[name], arr), name
    (event, fields), = [e for e in events if e[0] == "resume"]
    assert fields["path"] == "warm" and fields["fallback_reason"] is None
    assert fields["n_payloads"] == 1

    # corrupt the payload -> CRC rejects -> checkpoint rung, reason kept
    events.clear()
    _arm(monkeypatch, "kind=corrupt")
    tr3, _, _, _, _ = _trainer()
    got = tr3.elastic_resume(ckdir, shapes, label_shapes=lbl,
                             source="auto")
    assert got is not None and got[3] == 2
    for name, arr in _host(got[0]).items():
        assert np.array_equal(want[name], arr), name
    (event, fields), = [e for e in events if e[0] == "resume"]
    assert fields["path"] == "cold"
    assert fields["fallback_reason"] == "crc_mismatch"


# ----------------------------------------------------------------------
# auto_resume corruption fallback (satellite: a committed checkpoint
# damaged after the fact must not end the run while an older one works)
# ----------------------------------------------------------------------
def test_auto_resume_walks_back_past_corrupt_latest(tmp_path):
    from mxnet_tpu.parallel.ckpt import abstract_like
    mgr = CheckpointManager(str(tmp_path / "run"), keep=0,
                            payload_format="host")
    for step in (1, 2):
        mgr.save({"w": jnp.arange(8, dtype=jnp.float32) * step}, step)
    # truncate the newest manifest: simulated post-commit damage
    manifest = os.path.join(mgr.step_path(2), "host_ckpt.json")
    with open(manifest, "w") as f:
        f.write('{"step": 2, "keys"')
    restored, step = mgr.auto_resume(
        abstract_like({"w": jnp.zeros(8, jnp.float32)}))
    assert step == 1
    assert np.allclose(np.asarray(restored["w"]), np.arange(8))

    # every kept version bad -> structured restore_corrupt, not a crash
    manifest1 = os.path.join(mgr.step_path(1), "host_ckpt.json")
    with open(manifest1, "w") as f:
        f.write("not json")
    with pytest.raises(ResilienceError) as ei:
        mgr.auto_resume(abstract_like({"w": jnp.zeros(8, jnp.float32)}))
    assert ei.value.kind == "restore_corrupt"
    assert ei.value.phase == "ckpt_restore"


def _read_elastic_events(tdir):
    recs = []
    for path in glob.glob(os.path.join(tdir, "events-rank*.jsonl*")):
        with open(path) as f:
            recs += [json.loads(line) for line in f if line.strip()]
    return recs


@pytest.mark.slow
def test_warm_shrink_grow_drill(tmp_path):
    """The warm-elasticity acceptance drill: the SAME shrink/grow
    timeline as test_elastic_shrink_grow_drill but with
    MXTPU_WARM_REMESH=1 — every transition resumes from the host-memory
    handoff area (the victim's host RAM burns with it; its state is
    served by the off-host ring buddy), ZERO checkpoint reads happen on
    any resume, and the loss trajectory is still bit-identical to
    fixed-world reference runs from the same steps."""
    edir = str(tmp_path / "elastic")
    tdir = os.path.join(edir, "telemetry")
    drill = os.path.join("tests", "nightly", "dist_elastic.py")
    _launch_raw(["-n", "3", "--launcher", "local", "--workdir", _ROOT,
                 "--port", "9916", "--elastic", "--min-world", "2",
                 "--elastic-dir", edir, "--max-restarts", "4", "--warm",
                 sys.executable, drill],
                extra_env={"MXTPU_STEP_TIMEOUT_S": "12",
                           "MXTPU_TELEMETRY_DIR": tdir})

    with open(os.path.join(edir, "LEDGER.json")) as f:
        led = json.load(f)
    assert led["generation"] == 2 and led["world_size"] == 3

    with open(os.path.join(edir, "losses-elastic.jsonl")) as f:
        rows = [json.loads(line) for line in f]
    assert [r["epoch"] for r in rows] == [0, 1, 2, 3, 4]
    assert [r["world"] for r in rows] == [3, 3, 2, 3, 3]

    recs = _read_elastic_events(tdir)
    el = [r for r in recs if r.get("kind") == "elastic"]
    # acceptance: the warm path never opened a checkpoint — zero ckpt
    # resume (or corrupt-skip) events across the whole timeline
    ckpt_reads = [r for r in recs if r.get("kind") == "ckpt"
                  and r.get("phase") in ("resume", "restore_corrupt_skip")]
    assert ckpt_reads == [], ckpt_reads
    # both post-transition incarnations resumed warm, every rank
    resumes = [r for r in el if r["event"] == "resume"]
    warm = [(r["generation"], r["world_size"]) for r in resumes
            if r.get("path") == "warm"]
    assert warm.count((1, 2)) == 2
    assert warm.count((2, 3)) == 3
    for r in resumes:
        if r["generation"] >= 1:
            assert r.get("path") == "warm", r
            assert not r.get("fallback_reason"), r
    # each stable point host-offloaded (snapshot events with bytes and
    # off-host buddy placement), and the handoff area is where the env
    # says it is
    snaps = [r for r in el if r["event"] == "snapshot"]
    assert snaps and all(s["bytes"] > 0 for s in snaps)
    assert any(s["buddies"] and s["host"] not in s["buddies"]
               for s in snaps)
    assert os.path.isdir(os.path.join(edir, "handoff", "train"))

    # warm resumes are bit-identical to fixed-world reference runs
    # restored from the same steps (checkpoints exist for references
    # even though the elastic run never read them)
    for world, step, stop, port in ((2, 2, 3, "9917"), (3, 3, 5, "9918")):
        _launch_raw(["-n", str(world), "--launcher", "local",
                     "--workdir", _ROOT, "--port", port,
                     sys.executable, drill],
                    extra_env={"MXTPU_ELASTIC_DIR": edir,
                               "MXTPU_ELASTIC_REFERENCE": "1",
                               "MXTPU_RESUME_STEP": str(step),
                               "MXTPU_STOP_EPOCH": str(stop)})
        ref = os.path.join(edir, "losses-ref-w%d-s%d.jsonl" % (world,
                                                               step))
        with open(ref) as f:
            ref_rows = [json.loads(line) for line in f]
        assert ref_rows, "reference run recorded no losses"
        by_epoch = {r["epoch"]: r for r in rows}
        for r in ref_rows:
            assert r["loss"] == by_epoch[r["epoch"]]["loss"], \
                (world, r["epoch"])


@pytest.mark.slow
def test_warm_corrupt_shard_falls_back_to_checkpoint(tmp_path):
    """Structured degradation: a corrupt handoff payload on rank 0
    fails the CRC at warm-resume time, and that rank alone falls back
    to the versioned checkpoint — resume completes at the same step,
    with the fallback reason named in its elastic telemetry."""
    edir = str(tmp_path / "elastic")
    tdir = os.path.join(edir, "telemetry")
    drill = os.path.join("tests", "nightly", "dist_elastic.py")
    _launch_raw(["-n", "3", "--launcher", "local", "--workdir", _ROOT,
                 "--port", "9919", "--elastic", "--min-world", "2",
                 "--elastic-dir", edir, "--max-restarts", "4", "--warm",
                 sys.executable, drill],
                extra_env={"MXTPU_STEP_TIMEOUT_S": "12",
                           "MXTPU_TELEMETRY_DIR": tdir,
                           "MXTPU_DRILL_EPOCHS": "3",
                           "MXTPU_DRILL_GROW": "",
                           "MXTPU_FAULT_SPEC": "kind=corrupt:rank=0"})

    with open(os.path.join(edir, "losses-elastic.jsonl")) as f:
        rows = [json.loads(line) for line in f]
    assert [r["epoch"] for r in rows] == [0, 1, 2]
    assert [r["world"] for r in rows] == [3, 3, 2]

    el = [r for r in _read_elastic_events(tdir)
          if r.get("kind") == "elastic"]
    gen1 = [r for r in el if r["event"] == "resume"
            and r["generation"] == 1]
    assert len(gen1) == 2, gen1
    paths = sorted((r.get("path"), r.get("fallback_reason"))
                   for r in gen1)
    # rank 0's payload read is corrupted -> checkpoint rung, named
    # reason; the untouched rank stays warm.  Both land on step 2.
    assert paths == [("cold", "crc_mismatch"), ("warm", None)], paths
    assert all(r["step"] == 2 for r in gen1)


@pytest.mark.slow
def test_multihost_warm_shrink_grow_drill(tmp_path):
    """Multi-host simulation: 4 workers over 2 simulated hosts
    (contiguous block mapping).  Killing rank 3 burns host 1's whole
    handoff RAM — ranks 2 and 3's own payloads vanish together — and
    the survivors still warm-resume the full tree from host 0's owns +
    ring-buddy replicas, bit-identical to a cold reference."""
    edir = str(tmp_path / "elastic")
    tdir = os.path.join(edir, "telemetry")
    drill = os.path.join("tests", "nightly", "dist_elastic.py")
    _launch_raw(["-n", "4", "--launcher", "local", "--workdir", _ROOT,
                 "--port", "9921", "--elastic", "--min-world", "3",
                 "--elastic-dir", edir, "--max-restarts", "4", "--warm",
                 sys.executable, drill],
                extra_env={"MXTPU_STEP_TIMEOUT_S": "12",
                           "MXTPU_TELEMETRY_DIR": tdir,
                           "MXTPU_NUM_HOSTS": "2",
                           "MXTPU_DRILL_EPOCHS": "4",
                           "MXTPU_DRILL_KILL": "0:1:3",
                           "MXTPU_DRILL_GROW": "1:2:4"})

    with open(os.path.join(edir, "losses-elastic.jsonl")) as f:
        rows = [json.loads(line) for line in f]
    assert [r["epoch"] for r in rows] == [0, 1, 2, 3]
    assert [r["world"] for r in rows] == [4, 4, 3, 4]

    recs = _read_elastic_events(tdir)
    ckpt_reads = [r for r in recs if r.get("kind") == "ckpt"
                  and r.get("phase") in ("resume", "restore_corrupt_skip")]
    assert ckpt_reads == [], ckpt_reads
    el = [r for r in recs if r.get("kind") == "elastic"]
    warm = [(r["generation"], r["world_size"])
            for r in el if r["event"] == "resume"
            and r.get("path") == "warm"]
    assert warm.count((1, 3)) == 3
    assert warm.count((2, 4)) == 4

    # warm losses bit-identical to cold (fixed-world, checkpoint-
    # restored) references through both transitions
    for world, step, stop, port in ((3, 2, 3, "9922"), (4, 3, 4, "9923")):
        _launch_raw(["-n", str(world), "--launcher", "local",
                     "--workdir", _ROOT, "--port", port,
                     sys.executable, drill],
                    extra_env={"MXTPU_ELASTIC_DIR": edir,
                               "MXTPU_ELASTIC_REFERENCE": "1",
                               "MXTPU_RESUME_STEP": str(step),
                               "MXTPU_STOP_EPOCH": str(stop)})
        ref = os.path.join(edir, "losses-ref-w%d-s%d.jsonl" % (world,
                                                               step))
        with open(ref) as f:
            ref_rows = [json.loads(line) for line in f]
        assert ref_rows, "reference run recorded no losses"
        by_epoch = {r["epoch"]: r for r in rows}
        for r in ref_rows:
            assert r["loss"] == by_epoch[r["epoch"]]["loss"], \
                (world, r["epoch"])
