"""Custom python ops: CustomOp/CustomOpProp + legacy NumpyOp
(modeled on tests/python/unittest/test_operator.py test_custom_op and
example/numpy-ops/custom_softmax.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu import operator as op_mod
from mxnet_tpu.test_utils import assert_almost_equal

rng = np.random.RandomState(7)


# -- a differentiable custom op: scaled sigmoid ---------------------------
class Sigmoid(op_mod.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        y = 1.0 / (1.0 + np.exp(-in_data[0]))
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1.0 - y))


@op_mod.register("test_sigmoid")
class SigmoidProp(op_mod.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, shapes, dtypes):
        return Sigmoid()


# -- a loss-style op: softmax with label (need_top_grad=False) ------------
class CustomSoftmax(op_mod.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0]
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], e / e.sum(axis=1, keepdims=True))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        lbl = in_data[1].astype(np.int64)
        y = out_data[0].copy()
        y[np.arange(y.shape[0]), lbl] -= 1.0
        self.assign(in_grad[0], req[0], y)
        self.assign(in_grad[1], req[1], np.zeros_like(in_grad[1]))


@op_mod.register("test_softmax")
class CustomSoftmaxProp(op_mod.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shape):
        data = in_shape[0]
        return [data, [data[0]]], [data], []

    def create_operator(self, ctx, shapes, dtypes):
        return CustomSoftmax()


def test_custom_forward_matches_native():
    a = rng.uniform(-2, 2, size=(4, 5)).astype(np.float32)
    x = sym.Variable("x")
    s = sym.Custom(data=x, op_type="test_sigmoid")
    ex = s.simple_bind(mx.cpu(), x=a.shape)
    ex.arg_dict["x"][:] = a
    out = ex.forward()[0].asnumpy()
    assert_almost_equal(out, 1.0 / (1.0 + np.exp(-a)), rtol=1e-5, atol=1e-6)


def test_custom_backward_via_user_code():
    a = rng.uniform(-2, 2, size=(4, 5)).astype(np.float32)
    og = rng.uniform(-1, 1, size=(4, 5)).astype(np.float32)
    x = sym.Variable("x")
    s = sym.Custom(data=x, op_type="test_sigmoid")
    ex = s.simple_bind(mx.cpu(), x=a.shape, grad_req="write")
    ex.arg_dict["x"][:] = a
    ex.forward(is_train=True)
    ex.backward([mx.nd.array(og)])
    y = 1.0 / (1.0 + np.exp(-a))
    assert_almost_equal(ex.grad_dict["x"].asnumpy(), og * y * (1 - y),
                        rtol=1e-4, atol=1e-5)


def test_custom_softmax_loss_style():
    a = rng.uniform(-2, 2, size=(6, 4)).astype(np.float32)
    lbl = rng.randint(0, 4, size=(6,)).astype(np.float32)
    data = sym.Variable("data")
    label = sym.Variable("label")
    s = sym.Custom(data=data, label=label, op_type="test_softmax")
    ex = s.simple_bind(mx.cpu(), data=a.shape, label=lbl.shape,
                       grad_req={"data": "write", "label": "null"})
    ex.arg_dict["data"][:] = a
    ex.arg_dict["label"][:] = lbl
    out = ex.forward(is_train=True)[0].asnumpy()
    e = np.exp(a - a.max(axis=1, keepdims=True))
    want = e / e.sum(axis=1, keepdims=True)
    assert_almost_equal(out, want, rtol=1e-5, atol=1e-6)

    ex.backward()  # loss-style: no head grad
    g = want.copy()
    g[np.arange(6), lbl.astype(np.int64)] -= 1.0
    assert_almost_equal(ex.grad_dict["data"].asnumpy(), g,
                        rtol=1e-4, atol=1e-5)


def test_custom_kwargs_roundtrip_json():
    x = sym.Variable("x")
    s = sym.Custom(data=x, op_type="test_sigmoid")
    s2 = sym.load_json(s.tojson())
    assert s2.list_arguments() == s.list_arguments()


def test_custom_in_network():
    # custom op composed mid-graph with native ops; grads flow through
    a = rng.uniform(-1, 1, size=(3, 4)).astype(np.float32)
    x = sym.Variable("x")
    s = sym.Custom(data=x * 2.0, op_type="test_sigmoid")
    s = sym.sum(s)
    ex = s.simple_bind(mx.cpu(), x=a.shape, grad_req="write")
    ex.arg_dict["x"][:] = a
    ex.forward(is_train=True)
    ex.backward([mx.nd.array(np.ones((1,), np.float32))])
    y = 1.0 / (1.0 + np.exp(-2 * a))
    assert_almost_equal(ex.grad_dict["x"].asnumpy(), 2 * y * (1 - y),
                        rtol=1e-4, atol=1e-5)


# -- legacy NumpyOp -------------------------------------------------------
class LegacySquare(op_mod.NumpyOp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def forward(self, in_data, out_data):
        out_data[0][...] = in_data[0] ** 2

    def backward(self, out_grad, in_data, out_data, in_grad):
        in_grad[0][...] = 2.0 * in_data[0] * out_grad[0]


def test_legacy_numpy_op():
    a = rng.uniform(-1, 1, size=(3, 4)).astype(np.float32)
    og = rng.uniform(-1, 1, size=(3, 4)).astype(np.float32)
    x = sym.Variable("x")
    s = LegacySquare().get_symbol(data=x)
    ex = s.simple_bind(mx.cpu(), x=a.shape, grad_req="write")
    ex.arg_dict["x"][:] = a
    out = ex.forward(is_train=True)[0].asnumpy()
    assert_almost_equal(out, a ** 2, rtol=1e-5, atol=1e-6)
    ex.backward([mx.nd.array(og)])
    assert_almost_equal(ex.grad_dict["x"].asnumpy(), 2 * a * og,
                        rtol=1e-4, atol=1e-5)


# -- custom op with auxiliary state (review finding: aux were np.asarray'd
#    at trace time) ------------------------------------------------------
class Counter(op_mod.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], in_data[0] + aux[0])
        aux[0][...] = aux[0] + 1.0

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], out_grad[0])


@op_mod.register("test_counter")
class CounterProp(op_mod.CustomOpProp):
    def list_auxiliary_states(self):
        return ["count"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], [[1]]

    def create_operator(self, ctx, shapes, dtypes):
        return Counter()


def test_custom_op_with_aux_state():
    a = np.ones((2, 3), np.float32)
    x = sym.Variable("x")
    s = sym.Custom(data=x, op_type="test_counter", name="cnt")
    ex = s.simple_bind(mx.cpu(), x=a.shape)
    ex.arg_dict["x"][:] = a
    ex.aux_dict["cnt_count"][:] = np.zeros((1,), np.float32)
    out = ex.forward(is_train=True)[0].asnumpy()
    assert_almost_equal(out, a, rtol=1e-6, atol=1e-7)


def test_custom_infers_label_shape_from_data():
    # review finding: prop-derived shapes must backfill missing inputs
    data = sym.Variable("data")
    label = sym.Variable("label")
    s = sym.Custom(data=data, label=label, op_type="test_softmax")
    arg_shapes, out_shapes, _ = s.infer_shape(data=(6, 4))
    assert arg_shapes == [(6, 4), (6,)]
    assert out_shapes == [(6, 4)]
    ex = s.simple_bind(mx.cpu(), data=(6, 4))
    assert ex.arg_dict["label"].shape == (6,)
