"""Tests for base/context/registry/param-struct foundations."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import dtype_np_to_mx, dtype_mx_to_np, MXNetError
from mxnet_tpu.dparam import Field, ParamStruct, parse_tuple
from mxnet_tpu.registry import Registry


def test_dtype_flags():
    # reference type_flag numbering (include/mxnet/base.h)
    assert dtype_np_to_mx(np.float32) == 0
    assert dtype_np_to_mx(np.float64) == 1
    assert dtype_np_to_mx(np.float16) == 2
    assert dtype_np_to_mx(np.uint8) == 3
    assert dtype_np_to_mx(np.int32) == 4
    for f in range(5):
        assert dtype_np_to_mx(dtype_mx_to_np(f)) == f


def test_context():
    assert mx.cpu(0) == mx.Context("cpu", 0)
    assert mx.cpu(0) != mx.cpu(1)
    assert mx.tpu(0).device_type == "tpu"
    assert str(mx.gpu(2)) == "gpu(2)"
    with mx.Context("cpu", 1):
        assert mx.current_context() == mx.cpu(1)
    assert mx.current_context() == mx.cpu(0)


def test_registry():
    reg = Registry("thing")

    @reg.register("Foo")
    class Foo:
        pass

    @reg.register
    class Bar:
        pass

    assert reg.get("foo") is Foo
    assert reg.get("Bar") is Bar
    reg.alias("Foo", "F2")
    assert reg.get("f2") is Foo
    with pytest.raises(MXNetError):
        reg.get("nope")
    assert "Bar" in reg.list_names()


def test_param_struct():
    class ConvParam(ParamStruct):
        kernel = Field(tuple, required=True, doc="conv kernel")
        stride = Field(tuple, default=(1, 1), length=2)
        num_filter = Field(int, required=True, lower=1)
        no_bias = Field(bool, default=False)
        layout = Field(str, default="NCHW", enum=("NCHW", "NHWC"))

    p = ConvParam(kernel="(3, 3)", num_filter="64", no_bias="True")
    assert p.kernel == (3, 3)
    assert p.num_filter == 64
    assert p.no_bias is True
    assert p.stride == (1, 1)
    with pytest.raises(MXNetError):
        ConvParam(num_filter=1)  # kernel missing
    with pytest.raises(MXNetError):
        ConvParam(kernel="(3,3)", num_filter=0)  # below lower bound
    with pytest.raises(MXNetError):
        ConvParam(kernel="(3,3)", num_filter=1, layout="NCWH")
    with pytest.raises(MXNetError):
        ConvParam(kernel="(3,3)", num_filter=1, bogus=1)
    # round-trip through string attrs (graph serialization path)
    attrs = p.to_attrs()
    p2 = ConvParam.from_attrs(attrs)
    assert p2.kernel == p.kernel and p2.num_filter == p.num_filter


def test_parse_tuple():
    assert parse_tuple("(2, 2)") == (2, 2)
    assert parse_tuple("[1,2,3]") == (1, 2, 3)
    assert parse_tuple(3, length=2) == (3, 3)


def test_attr_scope():
    from mxnet_tpu.attribute import AttrScope
    with AttrScope(ctx_group="stage1"):
        attrs = AttrScope.current().get({"lr_mult": "2"})
        assert attrs == {"ctx_group": "stage1", "lr_mult": "2"}
        with AttrScope(mirror_stage="0"):
            attrs = AttrScope.current().get(None)
            assert attrs["ctx_group"] == "stage1"
            assert attrs["mirror_stage"] == "0"
    assert AttrScope.current().get(None) == {}


def test_name_manager():
    from mxnet_tpu.name import NameManager, Prefix
    with NameManager() as nm:
        assert nm.get(None, "fc") == "fc0"
        assert nm.get(None, "fc") == "fc1"
        assert nm.get("explicit", "fc") == "explicit"
    with Prefix("net_") as nm:
        assert nm.get(None, "fc") == "net_fc0"
