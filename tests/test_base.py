"""Tests for base/context/registry/param-struct foundations."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import dtype_np_to_mx, dtype_mx_to_np, MXNetError
from mxnet_tpu.dparam import Field, ParamStruct, parse_tuple
from mxnet_tpu.registry import Registry


def test_dtype_flags():
    # reference type_flag numbering (include/mxnet/base.h)
    assert dtype_np_to_mx(np.float32) == 0
    assert dtype_np_to_mx(np.float64) == 1
    assert dtype_np_to_mx(np.float16) == 2
    assert dtype_np_to_mx(np.uint8) == 3
    assert dtype_np_to_mx(np.int32) == 4
    for f in range(5):
        assert dtype_np_to_mx(dtype_mx_to_np(f)) == f


def test_context():
    assert mx.cpu(0) == mx.Context("cpu", 0)
    assert mx.cpu(0) != mx.cpu(1)
    assert mx.tpu(0).device_type == "tpu"
    assert str(mx.gpu(2)) == "gpu(2)"
    with mx.Context("cpu", 1):
        assert mx.current_context() == mx.cpu(1)
    assert mx.current_context() == mx.cpu(0)


def test_registry():
    reg = Registry("thing")

    @reg.register("Foo")
    class Foo:
        pass

    @reg.register
    class Bar:
        pass

    assert reg.get("foo") is Foo
    assert reg.get("Bar") is Bar
    reg.alias("Foo", "F2")
    assert reg.get("f2") is Foo
    with pytest.raises(MXNetError):
        reg.get("nope")
    assert "Bar" in reg.list_names()


def test_param_struct():
    class ConvParam(ParamStruct):
        kernel = Field(tuple, required=True, doc="conv kernel")
        stride = Field(tuple, default=(1, 1), length=2)
        num_filter = Field(int, required=True, lower=1)
        no_bias = Field(bool, default=False)
        layout = Field(str, default="NCHW", enum=("NCHW", "NHWC"))

    p = ConvParam(kernel="(3, 3)", num_filter="64", no_bias="True")
    assert p.kernel == (3, 3)
    assert p.num_filter == 64
    assert p.no_bias is True
    assert p.stride == (1, 1)
    with pytest.raises(MXNetError):
        ConvParam(num_filter=1)  # kernel missing
    with pytest.raises(MXNetError):
        ConvParam(kernel="(3,3)", num_filter=0)  # below lower bound
    with pytest.raises(MXNetError):
        ConvParam(kernel="(3,3)", num_filter=1, layout="NCWH")
    with pytest.raises(MXNetError):
        ConvParam(kernel="(3,3)", num_filter=1, bogus=1)
    # round-trip through string attrs (graph serialization path)
    attrs = p.to_attrs()
    p2 = ConvParam.from_attrs(attrs)
    assert p2.kernel == p.kernel and p2.num_filter == p.num_filter


def test_parse_tuple():
    assert parse_tuple("(2, 2)") == (2, 2)
    assert parse_tuple("[1,2,3]") == (1, 2, 3)
    assert parse_tuple(3, length=2) == (3, 3)


def test_attr_scope():
    from mxnet_tpu.attribute import AttrScope
    with AttrScope(ctx_group="stage1"):
        attrs = AttrScope.current().get({"lr_mult": "2"})
        assert attrs == {"ctx_group": "stage1", "lr_mult": "2"}
        with AttrScope(mirror_stage="0"):
            attrs = AttrScope.current().get(None)
            assert attrs["ctx_group"] == "stage1"
            assert attrs["mirror_stage"] == "0"
    assert AttrScope.current().get(None) == {}


def test_name_manager():
    from mxnet_tpu.name import NameManager, Prefix
    with NameManager() as nm:
        assert nm.get(None, "fc") == "fc0"
        assert nm.get(None, "fc") == "fc1"
        assert nm.get("explicit", "fc") == "explicit"
    with Prefix("net_") as nm:
        assert nm.get(None, "fc") == "net_fc0"


def test_legacy_misc_scheduler():
    """mxnet_tpu.misc: the legacy scheduler module (reference misc.py)."""
    from mxnet_tpu.misc import FactorScheduler, LearningRateScheduler
    s = FactorScheduler(step=10, factor=0.5)
    s.base_lr = 0.8
    assert abs(s(0) - 0.8) < 1e-9
    assert abs(s(10) - 0.4) < 1e-9
    assert abs(s(25) - 0.2) < 1e-9
    with pytest.raises(ValueError):
        FactorScheduler(step=0)
    with pytest.raises(NotImplementedError):
        LearningRateScheduler()(1)


def test_torch_backed_functions():
    """mxnet_tpu.torch: torch math on NDArrays (reference torch.py role)."""
    pytest.importorskip("torch")
    import mxnet_tpu.torch as th

    a = mx.nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], "f"))
    b = mx.nd.array(np.array([[10.0, 20.0], [30.0, 40.0]], "f"))
    c = th.add(a, b)
    assert np.allclose(c.asnumpy(), [[11, 22], [33, 44]])
    m = th.mm(a, b)
    assert np.allclose(m.asnumpy(), a.asnumpy() @ b.asnumpy())
    out = mx.nd.zeros((2, 2))
    r = th.exp(a, out=out)
    assert r is out
    assert np.allclose(out.asnumpy(), np.exp(a.asnumpy()), rtol=1e-5)
    # AttributeError specifically: hasattr/getattr-with-default callers
    # depend on it
    with pytest.raises(AttributeError):
        th.definitely_not_a_torch_fn
    assert not hasattr(th, "definitely_not_a_torch_fn")


def test_symbol_doc_examples():
    """symbol_doc: the documented examples run AS WRITTEN."""
    from mxnet_tpu.symbol_doc import get_output_shape, ConcatDoc

    # ConcatDoc: bind+forward over every dim with the documented shapes
    data = mx.nd.array(np.arange(6).reshape((2, 1, 3)))
    a = mx.sym.Variable('a')
    b = mx.sym.Variable('b')
    expect = {0: (4, 1, 3), 1: (2, 2, 3), 2: (2, 1, 6)}
    for dim, want in expect.items():
        cat = mx.sym.Concat(a, b, dim=dim)
        exe = cat.bind(mx.context.cpu(), args={'a': data, 'b': data})
        assert exe.forward()[0].shape == want
    assert ConcatDoc.__doc__ is not None

    shapes = get_output_shape(mx.sym.Concat(a, b, dim=1),
                              a=(2, 1, 3), b=(2, 1, 3))
    assert list(shapes.values())[0] == (2, 2, 3)

    # BroadcastPlusDoc: (1, 2) broadcasts over rows, everything is 2.0
    c = mx.sym.broadcast_plus(a, b)
    exe = c.bind(mx.context.cpu(), args={'a': mx.nd.ones((2, 2)),
                                         'b': mx.nd.ones((1, 2))})
    assert np.allclose(exe.forward()[0].asnumpy(), 2.0)

    # SoftmaxOutputDoc: backward == softmax - onehot despite head grads
    x = mx.sym.Variable('x')
    so = mx.sym.SoftmaxOutput(x, name='softmax')
    exe = so.simple_bind(mx.context.cpu(), grad_req='write', x=(2, 3))
    exe.arg_dict['x'][:] = [[1.0, 2.0, 3.0], [3.0, 2.0, 1.0]]
    exe.arg_dict['softmax_label'][:] = [2, 0]
    probs = exe.forward(is_train=True)[0].asnumpy()
    exe.backward()
    onehot = np.zeros((2, 3), 'f')
    onehot[0, 2] = onehot[1, 0] = 1.0
    assert np.allclose(exe.grad_dict['x'].asnumpy(),
                       probs - onehot, atol=1e-5)


def test_api_parity_helpers():
    """Module-level helper parity: nd.add/subtract/..., sym.maximum/
    minimum/pow, Symbol pickling, Executor.output_dict."""
    import pickle

    a = mx.nd.array(np.array([1.0, 4.0], "f"))
    b = mx.nd.array(np.array([3.0, 2.0], "f"))
    assert np.allclose(mx.nd.add(a, b).asnumpy(), [4, 6])
    assert np.allclose(mx.nd.add(2, a).asnumpy(), [3, 6])
    assert np.allclose(mx.nd.subtract(10, a).asnumpy(), [9, 6])
    assert np.allclose(mx.nd.multiply(a, b).asnumpy(), [3, 8])
    assert np.allclose(mx.nd.divide(8, b).asnumpy(), [8 / 3, 4])
    assert np.allclose(mx.nd.power(a, 2).asnumpy(), [1, 16])
    assert mx.nd.true_divide is mx.nd.divide

    x = mx.sym.Variable('x')
    y = mx.sym.Variable('y')
    mx_sym = mx.sym.maximum(x, y)
    exe = mx_sym.bind(mx.context.cpu(), args={'x': a, 'y': b})
    assert np.allclose(exe.forward()[0].asnumpy(), [3, 4])
    assert np.allclose(
        mx.sym.minimum(x, 2.0).bind(mx.context.cpu(), args={'x': a})
        .forward()[0].asnumpy(), [1, 2])
    assert np.allclose(
        mx.sym.pow(2.0, x).bind(mx.context.cpu(), args={'x': a})
        .forward()[0].asnumpy(), [2, 16])
    out_named = exe.output_dict
    assert list(out_named.values())[0] is exe.outputs[0]

    # Symbol round trips through pickle via its JSON form
    net = mx.sym.FullyConnected(x, num_hidden=3, name="fc")
    s2 = pickle.loads(pickle.dumps(net))
    assert s2.list_arguments() == net.list_arguments()
    assert s2.tojson() == net.tojson()
