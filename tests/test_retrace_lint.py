"""MXL-X retrace-stability lint (analysis/retrace.py) + the
MXTPU_RETRACE_SENTRY runtime retrace sentry (observability/retrace.py):
traced-scope control flow, cache-key hygiene, per-step jit
construction, weak-type leaks, bucket routing, donation reuse, the
historical regression fixture, and the live attribution witness —
including the deliberate bucket-bypass drill that must name the
divergent cache-key ingredient."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.analysis.retrace import analyze_retrace_paths
from mxnet_tpu.base import traced_scope
from mxnet_tpu.observability import retrace

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "fixtures", "retrace")


def _rules(findings):
    return sorted({f["rule"] for f in findings})


def _lint(tmp_path, code, name="snippet.py"):
    p = tmp_path / name
    p.write_text(code)
    return analyze_retrace_paths([str(p)], root=str(tmp_path))


# ----------------------------------------------------------------------
# X001: python control flow on tensor-derived values in traced scopes
# ----------------------------------------------------------------------
def test_x001_if_on_tracer(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"
        "g = jax.jit(f)\n"))
    assert "MXL-X001" in _rules(fs)
    hit = [f for f in fs if f["rule"] == "MXL-X001"][0]
    assert hit["anchor"].endswith(":f")
    assert "`if`" in hit["message"]


def test_x001_static_argnames_exempt(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "def f(x, n):\n"
        "    if n > 2:\n"
        "        return x * n\n"
        "    return x\n"
        "g = jax.jit(f, static_argnames='n')\n"))
    assert _rules(fs) == []


def test_x001_shape_facts_are_static(tmp_path):
    # shape/dtype reads are host facts even on a tracer: branching on
    # them re-specializes legitimately at trace time, never per value
    fs = _lint(tmp_path, (
        "import jax\n"
        "def f(x):\n"
        "    if x.shape[0] > 2:\n"
        "        return x[:2]\n"
        "    return x\n"
        "g = jax.jit(f)\n"))
    assert _rules(fs) == []


def test_x001_host_coercion_and_item(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = float(x)\n"
        "    b = x.item()\n"
        "    return a + b\n"))
    hits = [f for f in fs if f["rule"] == "MXL-X001"]
    assert len(hits) == 2


def test_x001_traced_scope_decorator(tmp_path):
    # the base.traced_scope marker covers partial-wrapped / indirect
    # kernels the lexical jit inference can't see
    fs = _lint(tmp_path, (
        "from mxnet_tpu.base import traced_scope\n"
        "@traced_scope\n"
        "def kernel(ref):\n"
        "    while ref > 0:\n"
        "        ref = ref - 1\n"))
    assert "MXL-X001" in _rules(fs)


def test_x001_name_collision_resolved_lexically(tmp_path):
    # a host-side method named `step` must NOT inherit tracedness from
    # an unrelated nested `step` def jitted inside a builder
    fs = _lint(tmp_path, (
        "import jax\n"
        "def _build():\n"
        "    def step(x):\n"
        "        if x > 0:\n"
        "            return x\n"
        "        return -x\n"
        "    return jax.jit(step)\n"
        "class Trainer:\n"
        "    def step(self, loss):\n"
        "        return float(loss)\n"))
    assert len(fs) == 1
    assert fs[0]["rule"] == "MXL-X001"
    assert fs[0]["anchor"].endswith("_build.step")


def test_traced_scope_marker_is_noop():
    @traced_scope
    def f(x):
        return x + 1
    assert f(2) == 3

    @traced_scope(grid=(4,))
    def g(x):
        return x * 2
    assert g(2) == 4


# ----------------------------------------------------------------------
# X002: unstable cache-key ingredients
# ----------------------------------------------------------------------
def test_x002_id_key_feeding_cache(tmp_path):
    fs = _lint(tmp_path, (
        "class C:\n"
        "    def get(self, opt):\n"
        "        key = (id(opt),)\n"
        "        if key in self._cache:\n"
        "            return self._cache[key]\n"
        "        self._cache[key] = self._build(opt)\n"
        "        return self._cache[key]\n"))
    assert "MXL-X002" in _rules(fs)
    assert "id()" in fs[0]["message"]


def test_x002_id_in_per_invocation_map_clean(tmp_path):
    # id()-keyed edge maps over LIVE graph nodes, scoped to one call,
    # are fine — the analysis passes use them; only keys that feed a
    # persistent cache/registry store are audited
    fs = _lint(tmp_path, (
        "def edge_shapes(nodes):\n"
        "    shapes = {}\n"
        "    for n in nodes:\n"
        "        key = (id(n), 0)\n"
        "        shapes[key] = n.out\n"
        "    return shapes\n"))
    assert _rules(fs) == []


def test_x002_unsorted_items_in_cache_key(tmp_path):
    fs = _lint(tmp_path, (
        "from mxnet_tpu.parallel.overlap import cache_key\n"
        "def k(cfg):\n"
        "    return cache_key(tuple(cfg.items()))\n"))
    assert "MXL-X002" in _rules(fs)
    assert "iteration order" in fs[0]["message"]


def test_x002_sorted_launders_iteration_order(tmp_path):
    fs = _lint(tmp_path, (
        "from mxnet_tpu.parallel.overlap import cache_key\n"
        "def k(cfg):\n"
        "    return cache_key(tuple(sorted(cfg.items())))\n"))
    assert _rules(fs) == []


def test_x002_env_read_in_traced_body(tmp_path):
    fs = _lint(tmp_path, (
        "import os, jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if os.environ.get('MXNET_COMPUTE_DTYPE') == 'bfloat16':\n"
        "        return x\n"
        "    return x * 2\n"))
    assert "MXL-X002" in _rules(fs)
    hit = [f for f in fs if f["rule"] == "MXL-X002"][0]
    assert "baked at trace time" in hit["message"]


# ----------------------------------------------------------------------
# X003: per-step jit construction bypassing the program registry
# ----------------------------------------------------------------------
def test_x003_jit_on_request_path(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "class S:\n"
        "    def handle_request(self, fn, x):\n"
        "        f = jax.jit(fn)\n"
        "        return f(x)\n"))
    assert "MXL-X003" in _rules(fs)


def test_x003_builder_exempt(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "class S:\n"
        "    def _build_program(self, fn):\n"
        "        return jax.jit(fn)\n"))
    assert _rules(fs) == []


def test_x003_memo_guard_exempt(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "class S:\n"
        "    def predict(self, fn, x):\n"
        "        if self._f is None:\n"
        "            self._f = jax.jit(fn)\n"
        "        return self._f(x)\n"))
    assert _rules(fs) == []


def test_x003_registry_caller_exempt(tmp_path):
    # a function that routes through the registry API IS the cached
    # path — its jit call only runs on a genuine miss
    fs = _lint(tmp_path, (
        "import jax\n"
        "def dispatch(symbol, key, g2c):\n"
        "    prog = compile_cache_get(key)\n"
        "    if prog is None:\n"
        "        prog = jax.jit(symbol)\n"
        "    return prog\n"))
    assert _rules(fs) == []


def test_x003_jit_in_loop(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "def collect(fns):\n"
        "    out = []\n"
        "    for fn in fns:\n"
        "        out.append(jax.jit(fn))\n"
        "    return out\n"))
    assert "MXL-X003" in _rules(fs)
    assert "inside a loop" in fs[0]["message"]


def test_x003_aot_lower_on_hot_path(tmp_path):
    fs = _lint(tmp_path, (
        "def prefill(self, prog, batch):\n"
        "    return prog.lower(batch).compile()\n"))
    assert "MXL-X003" in _rules(fs)


def test_x003_str_lower_not_confused(tmp_path):
    # zero-arg .lower() is string casing, not AOT lowering
    fs = _lint(tmp_path, (
        "def handle(self, name):\n"
        "    return name.lower()\n"))
    assert _rules(fs) == []


# ----------------------------------------------------------------------
# X004: weak-type python scalar across the trace boundary
# ----------------------------------------------------------------------
def test_x004_bare_scalar_to_jit_entry(tmp_path):
    fs = _lint(tmp_path, (
        "class E:\n"
        "    def run(self, x):\n"
        "        return self._jit_forward(0.5, x)\n"))
    assert "MXL-X004" in _rules(fs)


def test_x004_jit_bound_local_name(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "def step(x, lr):\n"
        "    return x * lr\n"
        "jit_step = jax.jit(step)\n"
        "def drive(x, lr):\n"
        "    return jit_step(x, float(lr))\n"))
    assert "MXL-X004" in _rules(fs)


def test_x004_wrapped_scalar_clean(tmp_path):
    fs = _lint(tmp_path, (
        "import jax.numpy as jnp\n"
        "class E:\n"
        "    def run(self, x, lr):\n"
        "        return self._jit_forward(jnp.float32(lr), x)\n"))
    assert _rules(fs) == []


# ----------------------------------------------------------------------
# X005: dynamic shapes into AOT program tables without bucket routing
# ----------------------------------------------------------------------
def test_x005_raw_len_indexes_program_table(tmp_path):
    fs = _lint(tmp_path, (
        "class G:\n"
        "    def prefill(self, tokens):\n"
        "        n = len(tokens)\n"
        "        return self._prefill[n]\n"))
    assert "MXL-X005" in _rules(fs)


def test_x005_bucket_routing_clean(tmp_path):
    fs = _lint(tmp_path, (
        "class G:\n"
        "    def prefill(self, tokens):\n"
        "        b = self._planner.prefill_bucket(len(tokens))\n"
        "        return self._prefill[b]\n"))
    assert _rules(fs) == []


def test_x005_table_iteration_clean(tmp_path):
    # warming every program in the table iterates it — the loop target
    # is by construction a bucketed size
    fs = _lint(tmp_path, (
        "class G:\n"
        "    def probe(self):\n"
        "        for b in self._prefill:\n"
        "            self._prefill[b]\n"))
    assert _rules(fs) == []


# ----------------------------------------------------------------------
# X006: donated buffer reuse
# ----------------------------------------------------------------------
def test_x006_donated_read_after_call(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "def donate_once(fn, state, x):\n"
        "    f = jax.jit(fn, donate_argnums=(0,))\n"
        "    out = f(state, x)\n"
        "    return state + out\n"))
    assert "MXL-X006" in _rules(fs)
    assert "'state'" in fs[0]["message"]


def test_x006_rebind_from_result_clean(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "def donate_once(fn, state, x):\n"
        "    f = jax.jit(fn, donate_argnums=(0,))\n"
        "    state = f(state, x)\n"
        "    return state\n"))
    assert _rules(fs) == []


# ----------------------------------------------------------------------
# suppression markers + parse errors
# ----------------------------------------------------------------------
def test_suppression_marker_on_line(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)  # mxl: retrace-ok (MXL-X001)\n"))
    assert _rules(fs) == []


def test_suppression_marker_on_def(tmp_path):
    fs = _lint(tmp_path, (
        "import jax\n"
        "# mxl: retrace-ok\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)\n"))
    assert _rules(fs) == []


def test_suppression_marker_rule_filtered(tmp_path):
    # a marker for a DIFFERENT rule must not eat the finding
    fs = _lint(tmp_path, (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)  # mxl: retrace-ok (MXL-X005)\n"))
    assert "MXL-X001" in _rules(fs)


def test_parse_error_is_a_warning_finding(tmp_path):
    fs = _lint(tmp_path, "def broken(:\n", name="broken.py")
    assert len(fs) == 1
    assert fs[0]["rule"] == "MXL-X001"
    assert fs[0].get("severity") == "warning"
    assert "cannot parse" in fs[0]["message"]


# ----------------------------------------------------------------------
# historical regression fixture + self-lint
# ----------------------------------------------------------------------
def test_fixture_id_keyed_program_cache():
    fs = analyze_retrace_paths(
        [os.path.join(FIXTURES, "id_keyed_program_cache.py")],
        root=ROOT)
    rules = _rules(fs)
    assert "MXL-X002" in rules
    hit = [f for f in fs if f["rule"] == "MXL-X002"][0]
    assert hit["anchor"].endswith("FusedStepCache.get_fused")


def test_framework_self_lint_clean():
    # the acceptance gate: the shipped package carries no MXL-X
    # findings (real fixes + audited annotations)
    pkg = os.path.join(ROOT, "mxnet_tpu")
    fs = analyze_retrace_paths([pkg], root=ROOT)
    assert fs == [], [(f["rule"], f["anchor"], f["line"]) for f in fs]


# ----------------------------------------------------------------------
# mxlint CLI family plumbing
# ----------------------------------------------------------------------
def test_mxlint_retrace_family(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "mxlint", os.path.join(ROOT, "tools", "mxlint.py"))
    mxlint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mxlint)
    p = tmp_path / "retracy.py"
    p.write_text(
        "import jax\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"
        "g = jax.jit(f)\n")
    _label, issues, _ctx = mxlint.lint_sources(
        [str(p)], None, [], families=["MXL-X*"])
    assert "MXL-X001" in {i.rule_id for i in issues}
    # the distributed family alone must NOT surface X findings
    _label, issues_d, _ctx = mxlint.lint_sources(
        [str(p)], None, [], families=["MXL-D*"])
    assert {i.rule_id for i in issues_d} == set()
    # --select narrows to one rule id
    _label, issues_sel, _ctx = mxlint.lint_sources(
        [str(p)], ["MXL-X001"], [])
    assert {i.rule_id for i in issues_sel} == {"MXL-X001"}


# ----------------------------------------------------------------------
# runtime sentry: observability/retrace.py
# ----------------------------------------------------------------------
@pytest.fixture
def sentry():
    was = retrace.installed()
    retrace.install()
    retrace.reset()
    yield
    retrace.reset()
    if not was:
        retrace.uninstall()


def _net(hidden):
    # odd hidden sizes keep each test's graph fingerprint unique, so
    # the global program registry can't satisfy it from another test
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = sym.Activation(net, act_type="relu")
    return sym.FullyConnected(net, num_hidden=3, name="fc2")


def _bind(net):
    exe = net.simple_bind(mx.cpu(0), data=(2, 7))
    for name, arr in exe.arg_dict.items():
        if name != "data":
            arr[:] = np.ones(arr.shape, dtype=np.float32) * 0.01
    return exe


def test_sentry_install_uninstall_restores():
    from mxnet_tpu.parallel import overlap as _overlap
    from mxnet_tpu import executor as _executor
    was = retrace.installed()
    if was:
        retrace.uninstall()
    orig_note = _overlap.note_lowering
    orig_lookup = _executor._lookup_program
    retrace.install()
    assert _overlap.note_lowering is not orig_note
    assert _executor._lookup_program is not orig_lookup
    retrace.uninstall()
    assert _overlap.note_lowering is orig_note
    assert _executor._lookup_program is orig_lookup
    if was:
        retrace.install()


def test_sentry_maybe_install_env_gated():
    was = retrace.installed()
    if was:
        retrace.uninstall()
    try:
        assert retrace.maybe_install({}) is False
        assert not retrace.installed()
        assert retrace.maybe_install({"MXTPU_RETRACE_SENTRY": "1"})
        assert retrace.installed()
    finally:
        retrace.uninstall()
        if was:
            retrace.install()


def test_sentry_warmup_lowerings_not_counted(sentry):
    retrace.warmup_begin()
    _bind(_net(37))
    st = retrace.stats()
    assert st["lowerings_seen"] >= 1
    assert st["retraces_after_warmup"] == 0
    assert not st["armed"]


def test_sentry_steady_state_is_quiet(sentry):
    retrace.warmup_begin()
    net = _net(41)
    _bind(net)
    retrace.warmup_boundary()
    assert retrace.armed()
    # rebinding the SAME graph in the same env is a registry hit
    _bind(net)
    st = retrace.stats()
    assert st["retraces_after_warmup"] == 0
    assert st["attributions"] == []


def test_sentry_bucket_bypass_names_graph_fingerprint(sentry):
    # the acceptance drill: warm one program, arm, then sneak a NOVEL
    # symbol past the bucket tables — the sentry must not just count
    # the lowering but name the divergent cache-key ingredient
    retrace.warmup_begin()
    _bind(_net(43))
    retrace.warmup_boundary()
    _bind(_net(47))                     # the bypass: unwarmed graph
    st = retrace.stats()
    assert st["retraces_after_warmup"] >= 1
    att = st["attributions"][0]
    assert att["divergent"] == ["graph_fingerprint"]
    detail = att["detail"]["graph_fingerprint"]
    assert detail["incoming"] != detail["closest_seen"]
    assert att["site"]


def test_sentry_env_flip_names_compute_dtype(sentry, monkeypatch):
    monkeypatch.delenv("MXNET_COMPUTE_DTYPE", raising=False)
    retrace.warmup_begin()
    net = _net(53)
    _bind(net)
    retrace.warmup_boundary()
    monkeypatch.setenv("MXNET_COMPUTE_DTYPE", "bfloat16")
    _bind(net)                          # same graph, flipped env
    st = retrace.stats()
    assert st["retraces_after_warmup"] >= 1
    assert "compute_dtype" in st["attributions"][0]["divergent"]


def test_sentry_unregistered_lowering_attributed(sentry):
    # a lowering that never went through the program registry (a
    # hot-path jax.jit — MXL-X003's runtime shape) has no incoming key
    # to diff; the sentry blames the bypass itself and names the site
    from mxnet_tpu.parallel import overlap as _overlap
    retrace.warmup_boundary()
    _overlap.note_lowering()
    st = retrace.stats()
    assert st["retraces_after_warmup"] == 1
    att = st["attributions"][0]
    assert att["divergent"] == ["outside_program_registry"]
    assert "test_retrace_lint" in att["site"]


def test_sentry_warmup_begin_disarms_for_swap(sentry):
    retrace.warmup_boundary()
    assert retrace.armed()
    retrace.warmup_begin()
    assert not retrace.armed()
    from mxnet_tpu.parallel import overlap as _overlap
    _overlap.note_lowering()
    assert retrace.stats()["retraces_after_warmup"] == 0


def test_sentry_never_arms_when_not_installed():
    was = retrace.installed()
    if was:
        retrace.uninstall()
    try:
        retrace.warmup_boundary()
        assert not retrace.armed()
    finally:
        if was:
            retrace.install()


# ----------------------------------------------------------------------
# telemetry rollup + SLO pricing of the retrace counters
# ----------------------------------------------------------------------
def _mk(kind, rank, wall_ms, **f):
    return dict(run_id="r", rank=rank, kind=kind, wall_ms=wall_ms,
                step=f.pop("step", None), **f)


def test_aggregate_retrace_rollup():
    from mxnet_tpu.observability import aggregate
    recs = [
        _mk("step", 0, 1000, step=0, dur_ms=10.0),
        _mk("retrace", 0, 1001, divergent=["graph_fingerprint"],
            site="a.py:10", n=1),
        _mk("retrace", 0, 1002, divergent=["graph_fingerprint"],
            site="a.py:10", n=2),
        _mk("retrace", 1, 1003, divergent=["compute_dtype", "ctx_key"],
            site="b.py:20", n=1),
    ]
    rep = aggregate.build_report(recs)
    rt = rep["retrace"]
    assert rt["count"] == 4
    assert rt["divergent"] == {"graph_fingerprint": 3,
                               "compute_dtype": 1, "ctx_key": 1}
    assert rt["sites"] == ["a.py:10", "b.py:20"]


def test_slo_zero_alert_prices_retraces():
    from mxnet_tpu.observability import slo
    regs, checked = slo.compare({"retraces_after_warmup": 2.0},
                                {"retraces_after_warmup": 0.0})
    assert len(regs) == 1
    assert regs[0]["metric"] == "retraces_after_warmup"
    assert regs[0]["regression"] is True
    # a clean run against the zero baseline stays quiet
    regs0, _ = slo.compare({"retraces_after_warmup": 0.0},
                           {"retraces_after_warmup": 0.0})
    assert regs0 == []


def test_slo_telemetry_metrics_reads_retrace_count():
    from mxnet_tpu.observability import slo
    out = slo.telemetry_metrics({"pod": {}, "retrace": {"count": 3}})
    assert out["retraces_after_warmup"] == 3.0
