"""Networked coordination KV (docs/resilience.md "KV fault
discipline", docs/serving.md "Networked fleet"): backend parity
between FileKV and TcpKV, the TcpKV server/client pair, the
ResilientKV retry discipline and its fault seams, leader-lease
election, and connect_kv URL selection.

All CPU-only and in-process: the TCP tests run a TcpKVServer thread
inside the test process on an ephemeral port.  The multi-process
partition-plus-router-kill drill lives in
tests/nightly/serve_fleet_net.py (CI TASK=serving).
"""
import json
import threading
import time

import pytest

from mxnet_tpu.kvstore import scan_dead_ranks
from mxnet_tpu.resilience.netkv import (CoordKV, FileKV, KVUnreachable,
                                        KeyAbsent, KeyExists, Lease,
                                        ResilientKV, TcpKV,
                                        TcpKVServer, connect_kv,
                                        kv_max_value_bytes, kv_retries,
                                        kv_timeout_s, kv_url)


# ---------------------------------------------------------------------------
# backend fixture: every contract test runs over file:// AND tcp://
# ---------------------------------------------------------------------------

@pytest.fixture(params=["file", "tcp"])
def kv_backend(request, tmp_path):
    """(kv, url) over both backends — the parity matrix the router,
    heartbeat scan, and ledger exchange rely on."""
    if request.param == "file":
        root = tmp_path / "kv"
        yield FileKV(root), "file://%s" % root
        return
    srv = TcpKVServer(port=0).start()
    try:
        yield TcpKV(srv.host, srv.port, timeout_s=2.0), srv.url
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# backend parity: one contract, two transports
# ---------------------------------------------------------------------------

def test_kv_roundtrip_and_prefix_scan(kv_backend):
    kv, _ = kv_backend
    kv.key_value_set("mxtpu_hb/0", "1.5")
    kv.key_value_set("mxtpu_hb/1", "2.5")
    kv.key_value_set("other/0", "9")
    assert dict(kv.key_value_dir_get("mxtpu_hb/")) == {
        "mxtpu_hb/0": "1.5", "mxtpu_hb/1": "2.5"}
    kv.key_value_set("mxtpu_hb/0", "3.5")    # last write wins
    assert dict(kv.key_value_dir_get("mxtpu_hb/"))["mxtpu_hb/0"] == "3.5"
    kv.key_value_delete("mxtpu_hb/0")
    kv.key_value_delete("mxtpu_hb/0")        # idempotent
    assert "mxtpu_hb/0" not in dict(kv.key_value_dir_get("mxtpu_hb/"))


def test_kv_set_if_absent_is_exclusive(kv_backend):
    """allow_overwrite=False is the lease primitive: exactly one of
    two writers may win, and KeyExists is still a ValueError (the
    PR-14 FileKV contract existing callers catch)."""
    kv, _ = kv_backend
    kv.key_value_set("lease", "a", allow_overwrite=False)
    with pytest.raises(KeyExists):
        kv.key_value_set("lease", "b", allow_overwrite=False)
    assert isinstance(KeyExists("x"), ValueError)
    assert kv.blocking_key_value_get("lease", 50) == "a"
    kv.key_value_delete("lease")
    kv.key_value_set("lease", "b", allow_overwrite=False)
    assert kv.blocking_key_value_get("lease", 50) == "b"


def test_kv_blocking_get_absent_raises_keyabsent(kv_backend):
    """A bget deadline with the key never set is the SEMANTIC timeout
    KeyAbsent (a TimeoutError) — never a transport error."""
    kv, _ = kv_backend
    t0 = time.monotonic()
    with pytest.raises(KeyAbsent):
        kv.blocking_key_value_get("missing", 80)
    assert time.monotonic() - t0 < 5.0
    assert isinstance(KeyAbsent("x"), TimeoutError)
    kv.key_value_set("k", "v")
    assert kv.blocking_key_value_get("k", 80) == "v"


def test_dead_scan_matrix_over_both_backends(kv_backend, monkeypatch):
    """The heartbeat dead-scan rule gives the same verdicts over
    file:// and tcp:// — a backend swap is a URL change, not a
    behavior change."""
    from mxnet_tpu import kvstore as kvmod
    kv, _ = kv_backend
    monkeypatch.setattr(kvmod, "_now", lambda: 100.0)
    kv.key_value_set("mxtpu_hb/0", "99.0")     # fresh
    kv.key_value_set("mxtpu_hb/1", "80.0")     # stale
    assert scan_dead_ranks(kv, [0, 1, 2], created=95.0,
                           timeout=10.0) == [1]
    assert scan_dead_ranks(kv, [0, 1, 2], created=50.0,
                           timeout=10.0) == [1, 2]


# ---------------------------------------------------------------------------
# TcpKV specifics
# ---------------------------------------------------------------------------

@pytest.fixture
def tcp_server():
    srv = TcpKVServer(port=0).start()
    yield srv
    srv.stop()


def test_tcpkv_concurrent_clients(tcp_server):
    """Many threads, each with its own per-op connections, never see
    each other's answers (the one-socket-per-op design)."""
    errors = []

    def worker(wid):
        kv = TcpKV(tcp_server.host, tcp_server.port, timeout_s=5.0)
        try:
            for i in range(20):
                kv.key_value_set("w%d/%d" % (wid, i), str(wid * 100 + i))
                got = kv.blocking_key_value_get("w%d/%d" % (wid, i), 500)
                assert got == str(wid * 100 + i)
        except Exception as exc:       # pragma: no cover - failure path
            errors.append((wid, exc))

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    kv = TcpKV(tcp_server.host, tcp_server.port)
    assert len(kv.key_value_dir_get("w")) == 8 * 20


def test_tcpkv_blocking_get_wakes_on_set(tcp_server):
    """A parked bget wakes when another CONNECTION sets the key — the
    condition-variable path, not the poll path."""
    kv_get = TcpKV(tcp_server.host, tcp_server.port, timeout_s=5.0)
    kv_set = TcpKV(tcp_server.host, tcp_server.port, timeout_s=5.0)
    out = {}

    def getter():
        out["value"] = kv_get.blocking_key_value_get("wake", 5000)
        out["at"] = time.monotonic()

    t = threading.Thread(target=getter)
    t.start()
    time.sleep(0.25)
    t0 = time.monotonic()
    kv_set.key_value_set("wake", "now")
    t.join(timeout=10)
    assert out["value"] == "now"
    assert out["at"] - t0 < 2.0        # woke on notify, not at deadline


def test_tcpkv_oversized_value_rejected(tmp_path):
    """Values above MXTPU_KV_MAX_VALUE are rejected server-side with a
    plain ValueError (never retried by ResilientKV) and leave no key."""
    srv = TcpKVServer(port=0, max_value_bytes=64).start()
    try:
        kv = ResilientKV(TcpKV(srv.host, srv.port, timeout_s=2.0),
                         retries=3)
        kv.key_value_set("small", "x" * 32)
        t0 = time.monotonic()
        with pytest.raises(ValueError, match="exceeds"):
            kv.key_value_set("big", "x" * 200)
        assert time.monotonic() - t0 < 1.0     # no retry loop burned
        with pytest.raises(KeyAbsent):
            kv.blocking_key_value_get("big", 60)
    finally:
        srv.stop()


def test_tcpkv_reconnects_after_server_restart():
    """One connection per op means a server restart needs no client
    state reset — the next op just dials the new listener."""
    srv = TcpKVServer(port=0).start()
    host, port = srv.host, srv.port
    kv = TcpKV(host, port, timeout_s=2.0)
    kv.key_value_set("k", "v1")
    srv.stop()
    with pytest.raises(ConnectionError):
        kv.key_value_set("k", "v2")
    srv2 = TcpKVServer(host=host, port=port).start()
    try:
        kv.key_value_set("k", "v2")    # same client object, new server
        assert kv.blocking_key_value_get("k", 100) == "v2"
        assert kv.ping()["ok"]
    finally:
        srv2.stop()


def test_tcpkv_partition_window_then_backoff_recovery(tcp_server):
    """The server-side partition hook drops connections; ResilientKV's
    backoff rides out the window and the op SUCCEEDS — the
    reconnect-with-backoff half of the chaos drill."""
    kv = ResilientKV(TcpKV(tcp_server.host, tcp_server.port,
                           timeout_s=2.0), retries=6)
    kv.key_value_set("k", "v")
    tcp_server.partition(0.4)
    raw = TcpKV(tcp_server.host, tcp_server.port, timeout_s=2.0)
    with pytest.raises(ConnectionError):
        raw.blocking_key_value_get("k", 50)    # unwrapped: transport loss
    assert kv.blocking_key_value_get("k", 50) == "v"   # retried past it


# ---------------------------------------------------------------------------
# ResilientKV: the retry discipline
# ---------------------------------------------------------------------------

class _FlakyKV(CoordKV):
    """Backend failing the first ``fail_n`` calls, counting attempts."""

    def __init__(self, fail_n=0, exc=ConnectionError("down")):
        self.fail_n = fail_n
        self.exc = exc
        self.calls = 0

    def _maybe_fail(self):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise self.exc

    def key_value_set(self, key, value, allow_overwrite=True):
        self._maybe_fail()

    def blocking_key_value_get(self, key, timeout_ms):
        self._maybe_fail()
        return "v"

    def key_value_dir_get(self, prefix):
        self._maybe_fail()
        return []

    def key_value_delete(self, key):
        self._maybe_fail()


def test_resilientkv_retries_then_structured_unreachable():
    flaky = _FlakyKV(fail_n=10**9)
    kv = ResilientKV(flaky, retries=3, timeout_s=1.0, name="unit")
    with pytest.raises(KVUnreachable) as ei:
        kv.key_value_dir_get("mxtpu_hb/")
    err = ei.value
    assert err.kind == "kv_unreachable"
    assert err.op == "dir"
    assert err.attempts == 3
    assert flaky.calls == 3            # the whole budget was spent
    assert isinstance(err, Exception) and "unit" in str(err)


def test_resilientkv_recovers_and_rearms_outage_latch():
    """One outage stretch = one kv_unreachable emission; the next
    success re-arms the latch (asserted via the internal _down edge)."""
    flaky = _FlakyKV(fail_n=2)         # first op burns 2, succeeds 3rd
    kv = ResilientKV(flaky, retries=3, timeout_s=1.0)
    assert kv.blocking_key_value_get("k", 10) == "v"
    assert kv._down is False
    flaky.fail_n = flaky.calls + 10**9  # hard down from here
    with pytest.raises(KVUnreachable):
        kv.key_value_dir_get("x")
    assert kv._down is True
    flaky.fail_n = 0                    # heal
    assert kv.blocking_key_value_get("k", 10) == "v"
    assert kv._down is False


def test_resilientkv_semantic_errors_never_retried():
    class _AnsweredKV(_FlakyKV):
        def blocking_key_value_get(self, key, timeout_ms):
            self.calls += 1
            raise KeyAbsent("not set")

        def key_value_set(self, key, value, allow_overwrite=True):
            self.calls += 1
            raise KeyExists("already set")

    backend = _AnsweredKV()
    kv = ResilientKV(backend, retries=5, timeout_s=1.0)
    with pytest.raises(KeyAbsent):
        kv.blocking_key_value_get("k", 10)
    with pytest.raises(KeyExists):
        kv.key_value_set("k", "v", allow_overwrite=False)
    assert backend.calls == 2          # one attempt each: the KV answered


def test_resilientkv_backoff_is_deterministic():
    """No wall-clock or randomness in the delay schedule — a failing
    chaos drill replays exactly."""
    kv1 = ResilientKV(_FlakyKV(), retries=4, timeout_s=2.0, name="same")
    kv2 = ResilientKV(_FlakyKV(), retries=4, timeout_s=2.0, name="same")
    assert list(kv1._delays()) == list(kv2._delays())
    other = ResilientKV(_FlakyKV(), retries=4, timeout_s=2.0,
                        name="other-router")
    assert list(kv1._delays()) != list(other._delays())  # decorrelated


# ---------------------------------------------------------------------------
# fault seams (MXTPU_FAULT_SPEC, seam kv_op)
# ---------------------------------------------------------------------------

def test_kv_partition_seam_fails_ops_then_heals(tmp_path, monkeypatch):
    from mxnet_tpu.resilience import faultinject
    monkeypatch.setenv("MXTPU_FAULT_SPEC",
                       "kind=kv_partition:seconds=0.3")
    faultinject.reset()
    try:
        kv = ResilientKV(FileKV(tmp_path / "kv"), retries=1,
                         timeout_s=0.2)
        with pytest.raises(KVUnreachable):
            kv.key_value_set("k", "v")
        time.sleep(0.35)               # window closes
        kv.key_value_set("k", "v")     # healed: same client works
        assert kv.blocking_key_value_get("k", 50) == "v"
        assert kv._down is False
    finally:
        faultinject.reset()


def test_kv_flap_seam_is_absorbed_by_retry(tmp_path, monkeypatch):
    """kv_flap alternates fail/ok per attempt — the retry budget
    absorbs it, so callers never see an error."""
    from mxnet_tpu.resilience import faultinject
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "kind=kv_flap:sticky=1")
    faultinject.reset()
    try:
        kv = ResilientKV(FileKV(tmp_path / "kv"), retries=3,
                         timeout_s=0.5)
        kv.key_value_set("k", "v")     # attempt 1 flaps, attempt 2 ok
        assert kv.blocking_key_value_get("k", 50) == "v"
    finally:
        faultinject.reset()


def test_kv_slow_seam_delays_but_succeeds(tmp_path, monkeypatch):
    from mxnet_tpu.resilience import faultinject
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "kind=kv_slow:seconds=0.2")
    faultinject.reset()
    try:
        kv = ResilientKV(FileKV(tmp_path / "kv"), retries=2)
        t0 = time.monotonic()
        kv.key_value_set("k", "v")
        assert time.monotonic() - t0 >= 0.2
    finally:
        faultinject.reset()


# ---------------------------------------------------------------------------
# leader lease
# ---------------------------------------------------------------------------

def test_lease_take_renew_and_stats(kv_backend):
    kv, _ = kv_backend
    lease = Lease(kv, "r1", ttl_s=0.6)
    assert lease.poll() is True
    assert lease.poll() is True        # renew path, still leading
    rec = lease.peek()
    assert rec["holder"] == "r1" and rec["expires"] > time.time()
    st = lease.stats()
    assert st["leading"] and st["holder"] == "r1" and st["takeovers"] == 1


def test_lease_standby_takes_over_on_expiry(kv_backend):
    kv, _ = kv_backend
    a = Lease(kv, "a", ttl_s=0.3)
    b = Lease(kv, "b", ttl_s=0.3)
    assert a.poll() is True
    assert b.poll() is False           # unexpired lease: stand by
    time.sleep(0.4)                    # a never renews (it "died")
    assert b.poll() is True            # expired: exactly one takeover
    assert b.peek()["holder"] == "b"


def test_deposed_incumbent_steps_down_never_stomps(kv_backend):
    """An incumbent paused/partitioned past its own TTL re-competes;
    it must NOT overwrite the successor's record."""
    kv, _ = kv_backend
    a = Lease(kv, "a", ttl_s=0.3)
    b = Lease(kv, "b", ttl_s=0.3)
    assert a.poll() is True
    time.sleep(0.4)                    # a pauses past its own expiry
    assert b.poll() is True            # b took over
    assert a.poll() is False           # a steps down, does not stomp
    assert a.leading is False
    assert a.peek()["holder"] == "b"
    assert b.poll() is True            # b unharmed


def test_lease_release_hands_over_in_one_poll(kv_backend):
    kv, _ = kv_backend
    a = Lease(kv, "a", ttl_s=5.0)
    b = Lease(kv, "b", ttl_s=5.0)
    assert a.poll() is True
    assert b.poll() is False
    a.release()                        # graceful close: no TTL wait
    assert a.leading is False
    assert b.poll() is True


def test_lease_same_holder_restart_renews_in_place(kv_backend):
    """A router restarting with the same id reclaims its own record
    immediately instead of waiting out its own TTL."""
    kv, _ = kv_backend
    old = Lease(kv, "r1", ttl_s=5.0)
    assert old.poll() is True
    fresh = Lease(kv, "r1", ttl_s=5.0)     # restarted incarnation
    assert fresh.poll() is True


def test_lease_holds_leadership_through_kv_blip(tmp_path):
    """KVUnreachable mid-poll: the incumbent keeps leading within its
    own written expiry (the KV being down says nothing about the
    leader), and steps down past it."""

    class _SwitchKV(CoordKV):
        def __init__(self, kv):
            self.kv, self.down = kv, False

        def _gate(self):
            if self.down:
                raise KVUnreachable("blip", op="test")

        def key_value_set(self, key, value, allow_overwrite=True):
            self._gate()
            self.kv.key_value_set(key, value, allow_overwrite)

        def blocking_key_value_get(self, key, timeout_ms):
            self._gate()
            return self.kv.blocking_key_value_get(key, timeout_ms)

        def key_value_dir_get(self, prefix):
            self._gate()
            return self.kv.key_value_dir_get(prefix)

        def key_value_delete(self, key):
            self._gate()
            self.kv.key_value_delete(key)

    kv = _SwitchKV(FileKV(tmp_path / "kv"))
    lease = Lease(kv, "a", ttl_s=0.5)
    assert lease.poll() is True
    kv.down = True
    assert lease.poll() is True        # hold within our written lease
    time.sleep(0.6)                    # ... but never past our own TTL
    assert lease.poll() is False
    assert lease.leading is False
    kv.down = False
    assert lease.poll() is True        # healed: re-elected normally


# ---------------------------------------------------------------------------
# connect_kv + env knobs
# ---------------------------------------------------------------------------

def test_connect_kv_url_selection(tmp_path, monkeypatch, tcp_server):
    monkeypatch.delenv("MXTPU_KV_URL", raising=False)
    # unset -> FileKV on the caller's default root, ResilientKV-wrapped
    kv = connect_kv(default_root=str(tmp_path / "kv"))
    assert isinstance(kv, ResilientKV)
    assert isinstance(kv.kv, FileKV)
    assert kv.kv.root == str(tmp_path / "kv")
    # file:// explicit
    kv = connect_kv(url="file://%s" % (tmp_path / "kv2"))
    assert isinstance(kv.kv, FileKV)
    # tcp:// explicit, and via the environment
    kv = connect_kv(url=tcp_server.url)
    assert isinstance(kv.kv, TcpKV)
    kv.key_value_set("k", "v")
    assert kv.blocking_key_value_get("k", 100) == "v"
    monkeypatch.setenv("MXTPU_KV_URL", tcp_server.url)
    kv = connect_kv()
    assert isinstance(kv.kv, TcpKV) and kv.kv.port == tcp_server.port
    # resilient=False hands back the raw backend
    raw = connect_kv(url=tcp_server.url, resilient=False)
    assert isinstance(raw, TcpKV)
    with pytest.raises(ValueError):
        connect_kv(url="tcp://nohost")         # missing port
    with pytest.raises(ValueError):
        connect_kv(url="zmq://x:1")            # unknown scheme


def test_kv_env_knobs(monkeypatch):
    monkeypatch.delenv("MXTPU_KV_URL", raising=False)
    assert kv_url() is None
    assert kv_url("tcp://h:1") == "tcp://h:1"
    monkeypatch.setenv("MXTPU_KV_TIMEOUT_S", "2.5")
    monkeypatch.setenv("MXTPU_KV_RETRIES", "7")
    monkeypatch.setenv("MXTPU_KV_MAX_VALUE", "4096")
    assert kv_timeout_s() == 2.5
    assert kv_retries() == 7
    assert kv_max_value_bytes() == 4096
    monkeypatch.setenv("MXTPU_KV_TIMEOUT_S", "junk")
    assert kv_timeout_s() == 5.0               # defaults, never raises
    monkeypatch.setenv("MXTPU_KV_RETRIES", "junk")
    assert kv_retries() == 3


def test_lease_record_is_plain_json(tmp_path):
    """The lease record is operator-readable JSON (mxkv get can show
    it) with exactly the documented fields."""
    kv = FileKV(tmp_path / "kv")
    lease = Lease(kv, "r1", ttl_s=2.0)
    assert lease.poll() is True
    doc = json.loads(kv.blocking_key_value_get("mxtpu_router/lease", 50))
    assert set(doc) == {"holder", "expires"}
    assert doc["holder"] == "r1"
