"""tools/aot_audit.py: AOT compile of the fused step through the real
XLA:TPU pipeline via jax's compile-only topology path (no chip, no
tunnel).  The fast tests cover topology creation and the ENTRY-traffic
parser; the end-to-end compile is slow (~minutes) and gated behind
MXTPU_SLOW=1 (nightly tier)."""
import os
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

import aot_audit  # noqa: E402


def _mesh_or_skip():
    mesh = aot_audit._topology_mesh("v5e:2x2")
    if mesh is None:
        pytest.skip("local TPU PJRT topology unavailable (no libtpu)")
    return mesh


def test_topology_mesh_compile_only_devices():
    mesh = _mesh_or_skip()
    assert mesh.shape == {"dp": 1}
    dev = mesh.devices.flat[0]
    assert "TPU" in getattr(dev, "device_kind", "")


def test_entry_breakdown_parser():
    hlo = """
HloModule m

%fused_computation {
  %p = bf16[8,8]{1,0} parameter(0)
  ROOT %t = bf16[8,8]{1,0} transpose(%p), dimensions={1,0}
}

ENTRY %main (p0: bf16[8,8]) -> bf16[8,8] {
  %p0 = bf16[8,8]{1,0:T(8,128)(2,1)} parameter(0)
  %f1 = bf16[8,8]{1,0:T(8,128)(2,1)} fusion(%p0), kind=kLoop, calls=%fused_computation
  %c1 = f32[4,4]{1,0} copy(%p0)
  ROOT %f2 = bf16[8,8]{1,0} fusion(%f1), kind=kLoop, calls=%fused_computation
}
"""
    ranked = aot_audit.entry_breakdown(hlo)
    by_op = {r["op"]: r for r in ranked}
    # two fusions of 8*8 bf16 = 256 bytes; fusion ranks above copy (64B)
    assert by_op["fusion"]["count"] == 2
    assert ranked[0]["op"] == "fusion"
    assert by_op["copy"]["count"] == 1
    # the fusion-internal transpose must NOT be counted
    assert "transpose" not in by_op


@pytest.mark.skipif(not os.environ.get("MXTPU_SLOW"),
                    reason="TPU AOT compile takes minutes (MXTPU_SLOW=1)")
def test_aot_audit_tiny_end_to_end():
    mesh = _mesh_or_skip()
    out = aot_audit.audit(mesh, batch=2, layers=18, dtype="bfloat16")
    assert out["stablehlo_conv_dtypes"].get("bf16", 0) > 0
    assert set(out["stablehlo_conv_dtypes"]) == {"bf16"}
    assert out["temp_bytes"] > 0 and out["model_tflops_per_step"] > 0
