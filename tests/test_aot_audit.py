"""tools/aot_audit.py + tools/aot_longcontext_check.py: AOT compiles of
the fused step through the real XLA:TPU pipeline via jax's compile-only
topology path (no chip, no tunnel).

Every libtpu-touching check runs in a SUBPROCESS: the local libtpu
serves one process at a time and holds its lock for the process
lifetime — an in-process topology would poison later tests that expect
a free plugin (test_tools.py's PJRT C runner pins an exact
Client_Create failure).  The end-to-end compiles are slow (~minutes)
and gated behind MXTPU_SLOW=1 (nightly tier)."""
import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

import aot_audit  # noqa: E402  (parser helpers only — no jax import)


def _run(args, timeout):
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT
    return subprocess.run([sys.executable] + args, env=env, cwd=_ROOT,
                          capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow
def test_topology_mesh_compile_only_devices():
    if os.environ.get("MXTPU_AOT_TOPOLOGY", "1") in ("0", "off", "no"):
        pytest.skip("topology probe disabled (MXTPU_AOT_TOPOLOGY=0)")
    code = ("import jax, sys\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "sys.path.insert(0, %r)\n"
            "import aot_audit\n"
            "mesh = aot_audit._topology_mesh('v5e:2x2')\n"
            "assert mesh is None or ('TPU' in getattr(\n"
            "    mesh.devices.flat[0], 'device_kind', ''))\n"
            "print('NONE' if mesh is None else 'OK')\n"
            % os.path.join(_ROOT, "tools"))
    # a half-installed libtpu can HANG inside get_topology_desc rather
    # than fail — bound the probe and treat a timeout like "unavailable"
    # (set MXTPU_AOT_TOPOLOGY=0 to skip the spawn entirely)
    try:
        p = _run(["-c", code], timeout=60)
    except subprocess.TimeoutExpired:
        pytest.skip("local TPU PJRT topology probe hung (no usable "
                    "libtpu); set MXTPU_AOT_TOPOLOGY=0 to skip the probe")
    assert p.returncode == 0, p.stderr[-1500:]
    if "NONE" in p.stdout:
        pytest.skip("local TPU PJRT topology unavailable (no libtpu)")
    assert "OK" in p.stdout


def test_entry_breakdown_parser():
    hlo = """
HloModule m

%fused_computation {
  %p = bf16[8,8]{1,0} parameter(0)
  ROOT %t = bf16[8,8]{1,0} transpose(%p), dimensions={1,0}
}

ENTRY %main (p0: bf16[8,8]) -> bf16[8,8] {
  %p0 = bf16[8,8]{1,0:T(8,128)(2,1)} parameter(0)
  %f1 = bf16[8,8]{1,0:T(8,128)(2,1)} fusion(%p0), kind=kLoop, calls=%fused_computation
  %ft = (bf16[8,8]{1,0}, f32[4,4]{1,0}) fusion(%f1), kind=kOutput, calls=%fused_computation
  %g0 = bf16[8,8]{1,0} get-tuple-element(%ft), index=0
  %c1 = f32[4,4]{1,0} copy(%g0)
  ROOT %f2 = bf16[8,8]{1,0} fusion(%g0), kind=kLoop, calls=%fused_computation
}
"""
    ranked = aot_audit.entry_breakdown(hlo)
    by_op = {r["op"]: r for r in ranked}
    # three fusions; the tuple-typed one contributes both members
    assert by_op["fusion"]["count"] == 3
    assert ranked[0]["op"] == "fusion"
    assert by_op["copy"]["count"] == 1
    # excluded: fusion-internal ops, zero-copy views, input parameters
    assert "transpose" not in by_op
    assert "get-tuple-element" not in by_op
    assert "parameter" not in by_op


@pytest.mark.skipif(not os.environ.get("MXTPU_SLOW"),
                    reason="TPU AOT compile takes minutes (MXTPU_SLOW=1)")
def test_aot_audit_tiny_end_to_end():
    p = _run([os.path.join(_ROOT, "tools", "aot_audit.py"),
              "--batch", "2", "--layers", "18"], timeout=1800)
    if p.returncode == 2:
        pytest.skip("local TPU PJRT topology unavailable")
    assert p.returncode == 0, p.stderr[-1500:]
    line = [l for l in p.stdout.splitlines() if l.startswith("{")][-1]
    out = json.loads(line)["audit"][0]
    assert out["stablehlo_conv_dtypes"].get("bf16", 0) > 0
    assert set(out["stablehlo_conv_dtypes"]) == {"bf16"}
    assert out["temp_bytes"] > 0 and out["model_tflops_per_step"] > 0


@pytest.mark.skipif(not os.environ.get("MXTPU_SLOW"),
                    reason="TPU AOT compile takes minutes (MXTPU_SLOW=1)")
def test_longcontext_paths_compile_under_mosaic():
    """Flash pallas kernel, transformer fused step, and the ring-
    attention dp2xsp2 step through the REAL Mosaic pipeline; the
    ppermute ring must survive into the compiled HLO."""
    p = _run([os.path.join(_ROOT, "tools", "aot_longcontext_check.py")],
             timeout=2400)
    if p.returncode == 2:
        pytest.skip("local TPU PJRT topology unavailable")
    assert p.returncode == 0, p.stderr[-1500:]
    line = [l for l in p.stdout.splitlines() if l.startswith("{")][-1]
    out = json.loads(line)
    assert out["flash_pallas_custom_calls"] > 0
    assert out["transformer_tf_per_step"] > 0
    # MXTPU_FLASH_FORCE must route the fused step's MHA through the
    # pallas kernel (a Mosaic custom call), not attention_reference
    assert out["transformer_custom_calls"] > 0
    assert out["ring_collective_permutes"] > 0
