"""Data IO tests (mirrors reference tests/python/unittest/test_io.py +
test_recordio.py)."""
import os
import shutil

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio
from mxnet_tpu import recordio as rio


def test_ndarray_iter_basic():
    data = np.arange(100).reshape(25, 4).astype(np.float32)
    label = np.arange(25).astype(np.float32)
    it = mio.NDArrayIter(data, label, batch_size=5)
    batches = list(it)
    assert len(batches) == 5
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:5])
    np.testing.assert_allclose(batches[2].label[0].asnumpy(), label[10:15])
    assert batches[-1].pad == 0
    # second epoch after reset
    it.reset()
    assert len(list(it)) == 5


def test_ndarray_iter_pad_discard():
    data = np.arange(23 * 3).reshape(23, 3).astype(np.float32)
    it = mio.NDArrayIter(data, None, batch_size=5, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 5
    assert batches[-1].pad == 2
    # padded tail wraps to the head
    np.testing.assert_allclose(batches[-1].data[0].asnumpy()[-2:], data[:2])
    it = mio.NDArrayIter(data, None, batch_size=5, last_batch_handle="discard")
    assert len(list(it)) == 4


def test_ndarray_iter_dict_multi_input():
    it = mio.NDArrayIter({"a": np.zeros((10, 2)), "b": np.ones((10, 3))},
                         np.arange(10), batch_size=2)
    names = sorted(d.name for d in it.provide_data)
    assert names == ["a", "b"]
    b = next(it)
    assert len(b.data) == 2


def test_ndarray_iter_shuffle():
    data = np.arange(50).astype(np.float32).reshape(50, 1)
    it = mio.NDArrayIter(data, data[:, 0], batch_size=10, shuffle=True)
    got = np.concatenate([b.data[0].asnumpy() for b in it])
    assert not np.array_equal(got[:, 0], data[:, 0])
    assert sorted(got[:, 0].tolist()) == data[:, 0].tolist()
    # data/label stay aligned under shuffle
    it.reset()
    for b in it:
        np.testing.assert_allclose(b.data[0].asnumpy()[:, 0],
                                   b.label[0].asnumpy())


def test_resize_iter():
    data = np.zeros((10, 2))
    it = mio.ResizeIter(mio.NDArrayIter(data, batch_size=2), size=8)
    assert len(list(it)) == 8
    it.reset()
    assert len(list(it)) == 8


def test_prefetching_iter():
    data = np.arange(40).reshape(20, 2).astype(np.float32)
    base = mio.NDArrayIter(data, np.arange(20), batch_size=4)
    it = mio.PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 5
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4])
    it.reset()
    assert len(list(it)) == 5


def test_csv_iter(tmp_path):
    data = np.random.rand(12, 3).astype(np.float32)
    label = np.arange(12, dtype=np.float32)
    dpath, lpath = str(tmp_path / "d.csv"), str(tmp_path / "l.csv")
    np.savetxt(dpath, data, delimiter=",")
    np.savetxt(lpath, label, delimiter=",")
    it = mio.CSVIter(data_csv=dpath, data_shape=(3,), label_csv=lpath,
                     batch_size=4)
    batches = list(it)
    assert len(batches) == 3
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4],
                               rtol=1e-5)


def test_num_parts_sharding(tmp_path):
    data = np.arange(20, dtype=np.float32).reshape(20, 1)
    dpath = str(tmp_path / "d.csv")
    np.savetxt(dpath, data, delimiter=",")
    parts = []
    for part in range(2):
        it = mio.CSVIter(data_csv=dpath, data_shape=(1,), batch_size=5,
                         num_parts=2, part_index=part)
        parts.append(np.concatenate([b.data[0].asnumpy() for b in it]))
    got = np.concatenate(parts)[:, 0]
    assert sorted(got.tolist()) == data[:, 0].tolist()


# ------------------------------ recordio -----------------------------------
def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    w = rio.MXRecordIO(path, "w")
    payloads = [b"hello", b"x" * 1000, b"", b"tail"]
    for p in payloads:
        w.write(p)
    w.close()
    r = rio.MXRecordIO(path, "r")
    for p in payloads:
        assert r.read() == p
    assert r.read() is None
    r.close()


def test_recordio_magic_in_payload(tmp_path):
    """Payload containing the magic sequence must survive (continuation recs)."""
    import struct
    path = str(tmp_path / "m.rec")
    magic = struct.pack("<I", 0xced7230a)
    payload = b"abc" + magic + b"def" + magic + magic + b"ghi"
    w = rio.MXRecordIO(path, "w")
    w.write(payload)
    w.write(b"next")
    w.close()
    r = rio.MXRecordIO(path, "r")
    assert r.read() == payload
    assert r.read() == b"next"


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "t.rec")
    idx_path = str(tmp_path / "t.idx")
    w = rio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(5):
        w.write_idx(i, b"rec%d" % i)
    w.close()
    r = rio.MXIndexedRecordIO(idx_path, path, "r")
    assert r.read_idx(3) == b"rec3"
    assert r.read_idx(0) == b"rec0"
    assert r.keys == list(range(5))


def test_pack_unpack_scalar_and_vector_label():
    hdr = rio.IRHeader(0, 3.0, 7, 0)
    rec = rio.pack(hdr, b"payload")
    h2, s = rio.unpack(rec)
    assert h2.label == 3.0 and h2.id == 7 and s == b"payload"

    hdr = rio.IRHeader(0, np.array([1.0, 2.0, 3.0], dtype=np.float32), 9, 0)
    rec = rio.pack(hdr, b"xy")
    h2, s = rio.unpack(rec)
    assert h2.flag == 3
    np.testing.assert_allclose(h2.label, [1, 2, 3])
    assert s == b"xy"


def test_pack_img_roundtrip(tmp_path):
    img = (np.random.rand(32, 32, 3) * 255).astype(np.uint8)
    rec = rio.pack_img(rio.IRHeader(0, 1.0, 0, 0), img, quality=95,
                       img_fmt=".png")
    hdr, out = rio.unpack_img(rec)
    assert hdr.label == 1.0
    assert out.shape == (32, 32, 3)
    np.testing.assert_allclose(out, img)  # png is lossless


def test_image_record_iter(tmp_path):
    path = str(tmp_path / "imgs.rec")
    w = rio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    for i in range(8):
        img = (rng.rand(40, 40, 3) * 255).astype(np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(i % 2), i, 0), img,
                             img_fmt=".png"))
    w.close()
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=4, rand_crop=True, rand_mirror=True)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (4, 3, 32, 32)
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    assert set(labels.tolist()) == {0.0, 1.0}


def test_mnist_iter(tmp_path):
    """Synthesize IDX files and read them back through MNISTIter."""
    import gzip
    import struct
    n = 30
    images = (np.random.rand(n, 28, 28) * 255).astype(np.uint8)
    labels = (np.arange(n) % 10).astype(np.uint8)
    ipath = str(tmp_path / "img-idx3-ubyte.gz")
    lpath = str(tmp_path / "lbl-idx1-ubyte.gz")
    with gzip.open(ipath, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(images.tobytes())
    with gzip.open(lpath, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    it = mio.MNISTIter(image=ipath, label=lpath, batch_size=10, shuffle=False)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (10, 1, 28, 28)
    np.testing.assert_allclose(batches[0].label[0].asnumpy(), labels[:10])
    flat = mio.MNISTIter(image=ipath, label=lpath, batch_size=10, flat=True,
                         shuffle=False)
    assert next(flat).data[0].shape == (10, 784)


@pytest.mark.skipif(shutil.which("g++") is None or
                    shutil.which("make") is None,
                    reason="no native toolchain")
def test_native_recordio_cpp_unit(tmp_path):
    """The C++ unit test for src/recordio.cc: write/read/skip/seek,
    byte-range shard resync (num_parts protocol), and corruption
    detection — no Python in the loop."""
    import subprocess
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    build = subprocess.run(["make", "-s", "lib/recordio_test"], cwd=root,
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-1500:]
    proc = subprocess.run([os.path.join(root, "lib", "recordio_test"),
                           str(tmp_path)],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-1000:])
    assert "RECORDIO CPP OK" in proc.stdout


def test_augmenter_geometry_paths():
    """The reference augmenter's geometry knobs (affine
    aspect/shear/rotate with fill, pad, random crop size) all produce
    target-shaped output, deterministically per seed."""
    from mxnet_tpu.image import augment

    rng = np.random.RandomState(0)
    img = rng.randint(0, 255, (40, 48, 3), np.uint8)
    shape = (3, 24, 24)

    # fixed rotate with fill: corners carry the fill color
    out = augment(img, shape, rotate=45, fill_value=0,
                  rng=np.random.RandomState(1))
    assert out.shape == (24, 24, 3)

    for kwargs in (
            {"max_aspect_ratio": 0.3, "rand_crop": True},
            {"max_shear_ratio": 0.2},
            {"max_rotate_angle": 30, "fill_value": 128},
            {"min_crop_size": 20, "max_crop_size": 36, "rand_crop": True},
            {"pad": 6},
            {"max_aspect_ratio": 0.2, "max_shear_ratio": 0.1,
             "max_rotate_angle": 15, "min_random_scale": 0.8,
             "max_random_scale": 1.2}):
        a = augment(img, shape, rng=np.random.RandomState(7), **kwargs)
        b = augment(img, shape, rng=np.random.RandomState(7), **kwargs)
        assert a.shape == (24, 24, 3), kwargs
        assert np.array_equal(a, b), ("nondeterministic", kwargs)
        c = augment(img, shape, rng=np.random.RandomState(8), **kwargs)
        assert a.shape == c.shape


def test_imagerecorditer_geometry_aug(tmp_path):
    """ImageRecordIter accepts the full augmenter surface and the
    geometry knobs route through the python augmenter path."""
    from mxnet_tpu import recordio as rio
    path = str(tmp_path / "g.rec")
    w = rio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    from mxnet_tpu.image import imencode
    for i in range(8):
        img = rng.randint(0, 255, (32, 32, 3), np.uint8)
        w.write(rio.pack(rio.IRHeader(0, float(i), i, 0),
                         imencode(img)))
    w.close()
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 24, 24),
                               batch_size=4, rand_crop=True,
                               max_aspect_ratio=0.25, max_shear_ratio=0.1,
                               max_rotate_angle=20, pad=2, fill_value=0,
                               preprocess_threads=1)
    assert not it._native_aug_ok
    batch = next(it)
    assert batch.data[0].shape == (4, 3, 24, 24)


def test_ndarray_iter_seeded_shuffle_deterministic():
    """With seed=, the batch order is a pure function of (seed, epoch):
    two iterators agree epoch by epoch, epochs differ from each other,
    and a different seed gives a different stream (docs/resilience.md)."""
    data = np.arange(120).reshape(30, 4).astype(np.float32)

    def epoch_order(it):
        order = [b.data[0].asnumpy()[:, 0].copy() for b in it]
        it.reset()
        return np.concatenate(order)

    a = mio.NDArrayIter(data, None, batch_size=5, shuffle=True, seed=9)
    b = mio.NDArrayIter(data, None, batch_size=5, shuffle=True, seed=9)
    orders = []
    for _ in range(3):
        oa, ob = epoch_order(a), epoch_order(b)
        assert np.array_equal(oa, ob)
        orders.append(oa)
    assert not np.array_equal(orders[0], orders[1])   # reshuffled per epoch

    c = mio.NDArrayIter(data, None, batch_size=5, shuffle=True, seed=10)
    assert not np.array_equal(epoch_order(c), orders[0])

    # legacy: shuffle without seed keeps the shuffle-once behavior
    d = mio.NDArrayIter(data, None, batch_size=5, shuffle=True)
    assert np.array_equal(epoch_order(d), epoch_order(d))


def test_ndarray_iter_state_resume_at_step_k():
    """state()/set_state(): a run interrupted at step k and resumed in a
    fresh process replays exactly the batches the uninterrupted run saw."""
    data = np.arange(200).reshape(50, 4).astype(np.float32)
    label = np.arange(50).astype(np.float32)

    def stream(it, n):
        """Draw n batches across epoch boundaries (auto-reset)."""
        out = []
        for _ in range(n):
            try:
                b = next(it)
            except StopIteration:
                it.reset()
                b = next(it)
            out.append((b.data[0].asnumpy().copy(),
                        b.label[0].asnumpy().copy()))
        return out

    # uninterrupted reference run: 2 epochs = 10 batches
    ref_it = mio.NDArrayIter(data, label, batch_size=10, shuffle=True,
                             seed=4)
    ref = stream(ref_it, 10)

    # interrupted run: draw 7 batches, snapshot, "crash"
    it_a = mio.NDArrayIter(data, label, batch_size=10, shuffle=True,
                           seed=4)
    first = stream(it_a, 7)
    snap = it_a.state()
    for (da, la), (dr, lr) in zip(first, ref[:7]):
        assert np.array_equal(da, dr) and np.array_equal(la, lr)

    # fresh-process resume: same ctor args + set_state
    it_b = mio.NDArrayIter(data, label, batch_size=10, shuffle=True,
                           seed=4)
    it_b.set_state(snap)
    rest = stream(it_b, 3)
    for (db, lb), (dr, lr) in zip(rest, ref[7:]):
        assert np.array_equal(db, dr) and np.array_equal(lb, lr)

    # an unseeded shuffled iterator refuses: its order can't be replayed
    it_c = mio.NDArrayIter(data, label, batch_size=10, shuffle=True)
    with pytest.raises(mx.base.MXNetError):
        it_c.set_state({"epoch": 0, "cursor": 0})
