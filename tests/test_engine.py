"""Native engine + recordio tests.

Randomized read/write workload replay (parity:
tests/cpp/threaded_engine_test.cc:20-50 — run random dependency graphs,
check result equality vs serial execution).
"""
import os
import threading

import numpy as np
import pytest

from mxnet_tpu import engine as eng
from mxnet_tpu import recordio as rio
from mxnet_tpu.libinfo import find_lib

HAS_NATIVE = find_lib() is not None


def _random_workload(engine, n_vars=8, n_ops=200, seed=0):
    """Each op reads some vars and writes others; bodies append to a log
    guarded by the engine's ordering only (a data race corrupts the
    per-var sequence check)."""
    rng = np.random.RandomState(seed)
    variables = [engine.new_variable() for _ in range(n_vars)]
    state = {v: [] for v in variables}  # written only by ops holding v
    expected_counts = {v: 0 for v in variables}

    for op_id in range(n_ops):
        n_read = rng.randint(0, 3)
        n_write = rng.randint(1, 3)
        picks = rng.permutation(n_vars)
        reads = [variables[i] for i in picks[:n_read]]
        writes = [variables[i] for i in picks[n_read:n_read + n_write]]
        for w in writes:
            expected_counts[w] += 1

        def body(reads=tuple(reads), writes=tuple(writes), op_id=op_id):
            # reading is safe concurrently; writing appends — if two
            # writers overlap, list.append ordering may interleave but
            # the final length check still holds, so ALSO verify
            # exclusivity with a guard flag
            for w in writes:
                lst = state[w]
                lst.append(("begin", op_id))
            for w in writes:
                state[w].append(("end", op_id))

        engine.push(body, const_vars=reads, mutable_vars=writes)

    engine.wait_for_all()
    # exclusivity: per var the log must be begin/end strictly paired
    for v in variables:
        log = state[v]
        assert len(log) == 2 * expected_counts[v]
        open_op = None
        for kind, op_id in log:
            if kind == "begin":
                assert open_op is None, \
                    "writers overlapped on var %s" % v
                open_op = op_id
            else:
                assert open_op == op_id
                open_op = None


@pytest.mark.skipif(not HAS_NATIVE, reason="native lib not built")
def test_threaded_engine_randomized_replay():
    engine = eng.ThreadedEngine(num_threads=4)
    for seed in range(3):
        _random_workload(engine, seed=seed)


@pytest.mark.skipif(not HAS_NATIVE, reason="native lib not built")
def test_threaded_engine_read_write_ordering():
    """Writes to a var are serialized in program order; reads see the
    preceding write."""
    engine = eng.ThreadedEngine(num_threads=4)
    v = engine.new_variable()
    results = []
    box = [0]

    def writer(val):
        def f():
            box[0] = val
        return f

    def reader():
        results.append(box[0])

    for i in range(1, 21):
        engine.push(writer(i), mutable_vars=[v])
        engine.push(reader, const_vars=[v])
    engine.wait_for_all()
    assert results == list(range(1, 21))


@pytest.mark.skipif(not HAS_NATIVE, reason="native lib not built")
def test_engine_wait_for_var():
    engine = eng.ThreadedEngine(num_threads=2)
    v = engine.new_variable()
    evt = threading.Event()
    out = []

    def slow():
        evt.wait(2.0)
        out.append(1)

    engine.push(slow, mutable_vars=[v])
    evt.set()
    engine.wait_for_var(v)
    assert out == [1]


def test_naive_engine_fallback():
    engine = eng.NaiveEngine()
    v = engine.new_variable()
    out = []
    engine.push(lambda: out.append(1), mutable_vars=[v])
    engine.wait_for_all()
    assert out == [1]


# ---------------------------------------------------------------- recordio
@pytest.mark.skipif(not HAS_NATIVE, reason="native lib not built")
def test_native_python_recordio_interop(tmp_path):
    """Bytes written by the native writer read back identically through
    the python decoder and vice versa (incl. embedded-magic splitting)."""
    magic = (0xced7230a).to_bytes(4, "little")
    payloads = [b"hello", b"x" * 1000, b"a" + magic + b"b" + magic,
                magic * 3, b"", b"tail"]

    # native write -> python read
    p1 = str(tmp_path / "n.rec")
    w = rio.MXRecordIO(p1, "w")
    assert w._native is not None
    for p in payloads:
        w.write(p)
    w.close()
    os.environ["MXTPU_NO_NATIVE"] = "1"
    try:
        r = rio.MXRecordIO(p1, "r")
        assert r._native is None
        got = []
        while True:
            item = r.read()
            if item is None:
                break
            got.append(item)
        r.close()
        assert got == payloads

        # python write -> native read
        p2 = str(tmp_path / "p.rec")
        w2 = rio.MXRecordIO(p2, "w")
        for p in payloads:
            w2.write(p)
        w2.close()
    finally:
        del os.environ["MXTPU_NO_NATIVE"]
    r2 = rio.MXRecordIO(p2, "r")
    assert r2._native is not None
    got2 = []
    while True:
        item = r2.read()
        if item is None:
            break
        got2.append(item)
    r2.close()
    assert got2 == payloads


@pytest.mark.skipif(not HAS_NATIVE, reason="native lib not built")
def test_native_indexed_recordio(tmp_path):
    idx = str(tmp_path / "d.idx")
    rec = str(tmp_path / "d.rec")
    w = rio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(20):
        w.write_idx(i, b"rec-%03d" % i)
    w.close()
    r = rio.MXIndexedRecordIO(idx, rec, "r")
    for i in (5, 0, 19, 7):
        assert r.read_idx(i) == b"rec-%03d" % i
    r.close()


@pytest.mark.skipif(not HAS_NATIVE, reason="native lib not built")
def test_pushed_fn_exception_reraised_from_wait():
    """An exception in a pushed fn must not vanish into the ctypes
    trampoline on the native worker thread: the engine records the first
    failure and re-raises it from wait_for_all / wait_for_var (the analog
    of the reference engine aborting on op error)."""
    e = eng.ThreadedEngine(num_threads=2)
    v = e.new_variable()

    def boom():
        raise ValueError("op failed on worker")

    e.push(boom, mutable_vars=[v])
    with pytest.raises(ValueError, match="op failed on worker"):
        e.wait_for_all()
    # failure is consumed: the engine stays usable afterwards
    hits = []
    e.push(lambda: hits.append(1), mutable_vars=[v])
    e.wait_for_var(v)
    assert hits == [1]
    e.delete_variable(v)


def test_async_checkpoint_via_engine(tmp_path):
    """do_checkpoint(run_async=True) pushes writes through the engine;
    epochs overlap the disk write and wait_for_all makes them durable."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import engine as eng

    X = np.random.RandomState(0).randn(80, 10).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    train = mx.io.NDArrayIter(X, y, batch_size=20)
    prefix = str(tmp_path / "ck")
    model = mx.model.FeedForward(mx.models.get_mlp(2, (8,)),
                                 ctx=mx.context.cpu(), num_epoch=3,
                                 optimizer="sgd", learning_rate=0.1)
    model.fit(X, y,
              epoch_end_callback=mx.callback.do_checkpoint(prefix,
                                                           run_async=True))
    eng.get().wait_for_all()
    import os
    for epoch in (1, 2, 3):
        assert os.path.exists("%s-%04d.params" % (prefix, epoch)), epoch
    # resumable
    m2 = mx.model.FeedForward.load(prefix, 3, ctx=mx.context.cpu())
    assert m2.predict(X).shape == (80, 2)


import shutil as _shutil
import subprocess as _subprocess

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.skipif(_shutil.which("g++") is None or
                    _shutil.which("make") is None,
                    reason="no native toolchain")
def test_native_engine_cpp_unit():
    """The C++ unit test for src/engine.cc (reference tests/cpp/
    threaded_engine_test.cc analog): randomized replay vs serial on
    1/2/4 threads, WaitForVar semantics, push throughput — no Python in
    the loop."""
    build = _subprocess.run(["make", "-s", "lib/engine_test"], cwd=_ROOT,
                            capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-1500:]
    proc = _subprocess.run([os.path.join(_ROOT, "lib", "engine_test")],
                           capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-1000:])
    assert "ENGINE CPP OK" in proc.stdout
    assert proc.stdout.count("OK") >= 5
