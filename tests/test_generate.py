"""Generative serving (docs/serving.md "Generation"): the sequence-axis
planner hook, cached-decode correctness vs the full forward, the
zero-lowerings contract, TokenStream semantics, and the ModelServer
generation path with KV backpressure.

The correctness anchor is :func:`test_decode_matches_full_forward`:
greedy decode through the paged cache must be token-identical, at every
step, to the argmax of a plain full-sequence forward of the same
checkpoint — the strongest equivalence the subsystem can claim.

All on the virtual CPU mesh with a toy LM (vocab 64, 2 layers) so the
AOT compiles stay in seconds.
"""
import itertools
import time

import numpy as np
import pytest

from mxnet_tpu import ndarray as nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.executor import program_registry_stats
from mxnet_tpu.models import transformer as tf
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import (CacheExhausted, GenerationEngine,
                               ModelServer, ServerBusy, TokenStream,
                               generation_mats)
from mxnet_tpu.serving.buckets import (BucketPlan, padded_flops,
                                       plan_buckets, plan_cost,
                                       useful_flops)

V, L, H, E, S = 64, 2, 4, 32, 48        # toy LM dims shared by the module


@pytest.fixture(scope="module")
def lm_params():
    """Random checkpoint of the full :func:`tf.get_symbol` model — the
    same weights must bind the training graph, the full forward, and
    both generation graphs (the weight-name compatibility contract)."""
    full = tf.get_symbol(vocab_size=V, num_layers=L, num_heads=H, dim=E,
                         seq_len=S)
    rng = np.random.RandomState(0)
    shapes = full.infer_shape(data=(1, S), softmax_label=(1, S))[0]
    params = {}
    for name, shp in zip(full.list_arguments(), shapes):
        if name in ("data", "softmax_label"):
            continue
        params[name] = nd.array(rng.randn(*shp).astype(np.float32) * 0.05)
    return full, params


@pytest.fixture(scope="module")
def ref_next(lm_params):
    """Greedy next-token oracle from the uncached full forward."""
    full, params = lm_params
    pred = Predictor(full.tojson(), params,
                     {"data": (1, S), "softmax_label": (1, S)})

    def _next(tokens):
        data = np.zeros((1, S), np.float32)
        data[0, :len(tokens)] = tokens
        out = pred.forward(data=data,
                           softmax_label=np.zeros((1, S), np.float32))
        probs = np.asarray(out[0])               # (S, V) softmax rows
        return int(np.argmax(probs[len(tokens) - 1]))
    return _next


# ---------------------------------------------------------------------------
# planner: the quadratic (sequence) axis
# ---------------------------------------------------------------------------

def test_quad_mats_cost_model():
    """quad rows pay n² useful work and (n·m, k, n·n) padded dims —
    the S² attention term on the prompt-length axis."""
    assert useful_flops(4, mats=(), quad_mats=((1, 1, 1),)) == 16
    assert useful_flops(4, mats=((1, 1, 1),)) == 4
    # padded work with a quad row grows superlinearly in the bucket
    # (tile-saturated dims so MXU rounding doesn't mask the n growth)
    small = padded_flops(128, mats=(), quad_mats=((1, 128, 1),))
    big = padded_flops(256, mats=(), quad_mats=((1, 128, 1),))
    assert big > 2 * small


def test_generation_mats_shapes():
    linear, quad = generation_mats(V, L, H, E, ffn_mult=4)
    assert (1, E, V) in linear                   # lm_head
    assert len(quad) == 2 * L * H                # score + value per head
    assert all(m == 1 for m, _k, _n in quad)


def test_planner_optimal_vs_brute_force_on_seq_axis():
    """With the S² hook active the DP must still match brute force over
    all bucket subsets — the optimality argument survives quad_mats."""
    linear, quad = generation_mats(V, L, H, E)
    hist = {3: 9, 7: 5, 12: 4, 20: 2, 33: 1}
    sizes = sorted(hist)
    best = min(
        plan_cost(combo, hist, mats=linear, quad_mats=quad)
        for k in (1, 2)
        for combo in itertools.combinations(sizes, k)
        if combo[-1] == sizes[-1])
    plan = plan_buckets(hist, mats=linear, max_buckets=2, quad_mats=quad)
    assert plan.cost == pytest.approx(best)
    assert plan.to_dict()["quadratic"]


def test_quad_term_steers_bucket_choice():
    """The quadratic axis must actually price differently: the same
    histogram planned with and without quad_mats yields different
    costs, and the quad cost dominates at long sequences."""
    linear, quad = generation_mats(V, L, H, E)
    hist = {4: 10, 40: 1}
    with_q = BucketPlan((4, 40), hist, linear, "float32", quad_mats=quad)
    without = BucketPlan((4, 40), hist, linear, "float32")
    assert with_q.cost > without.cost


# ---------------------------------------------------------------------------
# TokenStream
# ---------------------------------------------------------------------------

def test_token_stream_iterates_then_closes():
    stream = TokenStream()
    for t in (5, 6, 7):
        stream._put(t)
    stream._close()
    assert stream.next_token(timeout=1.0) == 5
    assert list(stream) == [6, 7]                   # iteration drains to END
    with pytest.raises(TimeoutError):               # END was consumed
        stream.next_token(timeout=0.05)


def test_token_stream_propagates_failure():
    stream = TokenStream()
    stream._put(1)
    stream._fail(MXNetError("boom"))
    assert stream.next_token(timeout=1.0) == 1
    with pytest.raises(MXNetError):
        stream.next_token(timeout=1.0)


# ---------------------------------------------------------------------------
# cached decode == full forward (the acceptance property)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine(lm_params):
    _full, params = lm_params
    return GenerationEngine(
        params=params, vocab_size=V, num_layers=L, num_heads=H, dim=E,
        max_seq_len=S, max_new_tokens=6, prompt_buckets=(8, 16),
        decode_buckets=(1, 2, 4), kv_blocks=32, kv_block_size=8)


def test_decode_matches_full_forward(engine, ref_next):
    """Greedy generation through prefill + paged decode must equal the
    full-forward argmax reference at EVERY step, across mixed prompt
    lengths (different prefill buckets, padded decode rows)."""
    prompts = [[3, 5, 7], [2, 4, 6, 8, 10, 1], [9] * 11]
    max_new = 6
    ref = []
    for p in prompts:
        toks = list(p)
        for _ in range(max_new):
            toks.append(ref_next(toks))
        ref.append(toks[len(p):])
    got = engine.generate(prompts, max_new_tokens=max_new)
    assert got == ref


def test_generate_steady_state_zero_lowerings(engine):
    """After construction (which warms every bucket) generation must
    never lower again — the AOT contract."""
    engine.generate([[1, 2, 3]], max_new_tokens=3)   # shake out any lazies
    before = program_registry_stats()["lowerings"]
    engine.generate([[4, 5], [6, 7, 8, 9, 10, 11, 12]], max_new_tokens=6)
    assert program_registry_stats()["lowerings"] == before


def test_eos_stops_early(engine, ref_next):
    """Declaring the reference's first generated token as EOS must stop
    the sequence at one token with finish_reason 'stop'."""
    prompt = [3, 5, 7]
    eos = ref_next(prompt)
    sid = ("t", "eos")
    engine.admit(sid, prompt, max_new=6, eos_id=eos)
    try:
        pred, inputs, _b = engine.start_prefill(sid)
        engine.finish_prefill(sid, engine.run_async(pred, inputs))
        state = engine.state(sid)
        assert state.done and state.finish_reason == "eos"
        assert state.generated() == [eos]
    finally:
        engine.release(sid)


def test_engine_admission_backpressure(engine):
    """Whole-budget reservation: a flood of admits must hit
    CacheExhausted (with blocks_free) while already-admitted sequences
    keep their blocks; release recovers everything."""
    admitted = []
    with pytest.raises(CacheExhausted) as err:
        for i in range(100):
            sid = ("t", "flood", i)
            engine.admit(sid, [1, 2, 3, 4], max_new=6)
            admitted.append(sid)
    assert err.value.blocks_free < err.value.blocks_needed
    assert admitted                                  # some got in first
    for sid in admitted:
        engine.release(sid)
    assert engine.cache.blocks_used() == 0


def test_engine_stats(engine):
    s = engine.stats()
    assert s["prompt_buckets"] == [8, 16]
    assert s["decode_buckets"] == [1, 2, 4]
    assert s["blocks_total"] == 31
    assert s["tokens_generated"] > 0


# ---------------------------------------------------------------------------
# ModelServer generation path
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def server(lm_params):
    _full, params = lm_params
    srv = ModelServer(max_delay_ms=2.0)
    srv.add_generative_model(
        "lm", params, vocab_size=V, num_layers=L, num_heads=H, dim=E,
        max_seq_len=S, max_new_tokens=6, prompt_buckets=(8, 16),
        decode_buckets=(1, 2, 4), kv_blocks=32, kv_block_size=8)
    yield srv
    srv.close()


def test_server_generate_matches_engine(server, engine):
    """The batcher-driven path (prefill/decode scheduling, streams)
    must produce the same tokens as the inline engine loop."""
    prompts = [[3, 5, 7], [2, 4, 6, 8, 10, 1]]
    expect = engine.generate(prompts, max_new_tokens=6)
    handles = [server.generate("lm", p, max_new_tokens=6)
               for p in prompts]
    for (future, stream), want, prompt in zip(handles, expect, prompts):
        streamed = list(stream)                      # token-by-token
        res = future.result(timeout=60)
        assert res["tokens"] == want
        assert streamed == want
        assert res["finish_reason"] == "length"
        assert res["n_prompt"] == len(prompt)


def test_server_generate_zero_steady_state_lowerings(server):
    server.generate_sync("lm", [1, 2, 3, 4, 5], timeout=60)
    for _ in range(3):
        server.generate_sync("lm", [7, 8], timeout=60)
    stats = server.stats()
    m = stats["models"]["lm"]
    assert m["generative"] is True
    assert m["lowerings_since_warmup"] == 0
    assert m["tokens_generated"] > 0
    assert m["seqs_active"] == 0                     # all released


def test_server_generate_429_with_blocks_free(lm_params):
    """KV exhaustion at admission surfaces as structured 429 carrying
    blocks_free, while the running decode completes untouched."""
    _full, params = lm_params
    srv = ModelServer(max_delay_ms=2.0)
    srv.add_generative_model(
        "lm", params, vocab_size=V, num_layers=L, num_heads=H, dim=E,
        max_seq_len=S, max_new_tokens=6, prompt_buckets=(16,),
        decode_buckets=(1, 2), kv_blocks=4, kv_block_size=8)
    try:
        future, _stream = srv.generate("lm", [1, 2, 3], max_new_tokens=6)
        rejected = None
        for _ in range(50):                          # 3 blocks: pool is full
            try:
                srv.generate("lm", [4, 5, 6], max_new_tokens=6)
            except ServerBusy as busy:
                rejected = busy
                break
        assert rejected is not None
        doc = rejected.to_dict()
        assert rejected.code == 429
        assert doc["error"] == "kv_cache_exhausted"
        assert doc["blocks_total"] == 3
        assert doc["blocks_free"] >= 0
        assert rejected.retry_after_ms > 0
        res = future.result(timeout=60)              # in-flight unharmed
        assert len(res["tokens"]) == 6
        deadline = time.time() + 30
        while srv.stats()["models"]["lm"]["blocks_used"] and \
                time.time() < deadline:
            time.sleep(0.01)
        assert srv.stats()["models"]["lm"]["blocks_used"] == 0
    finally:
        srv.close()


def test_server_submit_rejects_generative(server):
    with pytest.raises(MXNetError):
        server.submit("lm", np.zeros((1, 4), np.float32))
    with pytest.raises(MXNetError):
        server.generate("nope", [1, 2])
