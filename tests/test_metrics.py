"""Quantile-sketch and metrics-registry tests (ISSUE 19).

Covers the DDSketch-style relative-error guarantee across six orders
of magnitude, the exact/associative merge (bit-identical quantiles AND
bit-identical serialized bytes versus the concatenated stream),
serialization round-trips, empty/single-sample edges, the windowed
histogram ring, registry get-or-create semantics, and the Prometheus
text render/parse pair that mxtop --watch and CI scrape.
"""
import json
import math
import random

import pytest

from mxnet_tpu.observability import metrics as m
from mxnet_tpu.observability.metrics import (
    Counter, Gauge, Histogram, MetricsRegistry, QuantileSketch,
    parse_prometheus, render_prometheus, windows)


# ---------------------------------------------------------------- sketch

def test_relative_error_across_six_orders_of_magnitude():
    rng = random.Random(11)
    # values spanning 1e-2 .. 1e4 — six decades in one stream
    vals = [10 ** rng.uniform(-2, 4) for _ in range(20000)]
    sk = QuantileSketch(alpha=0.01)
    sk.extend(vals)
    vals.sort()
    for q in (0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 0.999):
        exact = vals[min(len(vals) - 1, int(q * len(vals)))]
        est = sk.quantile(q)
        assert abs(est - exact) / exact <= 0.011, (q, est, exact)


def test_merge_matches_concatenated_stream_bit_identically():
    rng = random.Random(5)
    vals = [rng.lognormvariate(3.0, 1.5) for _ in range(9000)]
    whole = QuantileSketch()
    whole.extend(vals)
    parts = [QuantileSketch() for _ in range(7)]
    for i, v in enumerate(vals):
        parts[i % 7].add(v)
    merged = QuantileSketch.merged(parts)
    # quantiles depend only on integer bucket counts: exact equality,
    # not approx — this is the fleet-rollup correctness contract
    for q in (0.5, 0.9, 0.95, 0.99):
        assert merged.quantile(q) == whole.quantile(q)
    assert merged.to_dict()["b"] == whole.to_dict()["b"]
    assert merged.count == whole.count


def test_merge_is_associative_and_order_independent():
    rng = random.Random(3)
    parts = []
    for _ in range(5):
        sk = QuantileSketch()
        sk.extend(rng.expovariate(0.01) for _ in range(500))
        parts.append(sk)
    ab_c = QuantileSketch.merged(
        [QuantileSketch.merged(parts[:2]), QuantileSketch.merged(parts[2:])])
    reversed_merge = QuantileSketch.merged(list(reversed(parts)))
    # the quantile state (integer bucket counts, count, extrema) is
    # exactly associative; only the float running sum — which feeds
    # mean, never quantiles — depends on addition order
    da, db = ab_c.to_dict(), reversed_merge.to_dict()
    sa, sb = da.pop("s"), db.pop("s")
    assert da == db
    assert sa == pytest.approx(sb, rel=1e-12)
    for q in (0.5, 0.95, 0.99):
        assert ab_c.quantile(q) == reversed_merge.quantile(q)


def test_serialize_round_trip_is_exact():
    sk = QuantileSketch()
    sk.extend([0.001, 1.0, 250.0, 1e6, 0.0, -3.5])
    back = QuantileSketch.from_json(sk.to_json())
    assert back.to_json() == sk.to_json()
    assert back.count == sk.count
    assert back.quantile(0.5) == sk.quantile(0.5)
    assert back.min == sk.min and back.max == sk.max


def test_serialization_is_deterministic():
    a, b = QuantileSketch(), QuantileSketch()
    for v in (5.0, 17.0, 0.2):
        a.add(v)
    for v in (0.2, 5.0, 17.0):            # insertion order differs
        b.add(v)
    assert a.to_json() == b.to_json()


def test_empty_and_single_sample_edges():
    empty = QuantileSketch()
    assert len(empty) == 0
    assert empty.quantile(0.5) is None
    assert empty.mean() is None
    assert empty.count_above(1.0) == 0
    one = QuantileSketch()
    one.add(42.0)
    assert one.count == 1
    assert one.quantile(0.0) == pytest.approx(42.0, rel=0.011)
    assert one.quantile(1.0) == pytest.approx(42.0, rel=0.011)
    assert one.mean() == 42.0


def test_zero_and_negative_values():
    sk = QuantileSketch()
    sk.extend([0.0, 0.0, -10.0, 10.0])
    assert sk.count == 4
    assert sk.min == -10.0 and sk.max == 10.0
    back = QuantileSketch.from_json(sk.to_json())
    assert back.to_json() == sk.to_json()


def test_count_above_threshold():
    sk = QuantileSketch()
    sk.extend([1.0] * 90 + [1000.0] * 10)
    bad = sk.count_above(250.0)
    assert bad == 10


def test_bounded_memory_collapses_buckets():
    sk = QuantileSketch(alpha=0.001, max_buckets=64)
    rng = random.Random(1)
    sk.extend(10 ** rng.uniform(-3, 6) for _ in range(5000))
    assert len(sk.buckets) <= 64
    assert sk.count == 5000


# ------------------------------------------------------------- registry

def test_registry_get_or_create_and_counter_monotone():
    reg = MetricsRegistry()
    c = reg.counter("reqs", labels={"model": "a"})
    assert reg.counter("reqs", labels={"model": "a"}) is c
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_and_callback():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(3.0)
    assert g.value == 3.0
    live = reg.gauge("live", fn=lambda: 7.5)
    assert live.value == 7.5


def test_histogram_windows(monkeypatch):
    clock = [1000.0]
    h = Histogram("lat_ms", windows_s=(10, 60))
    for i in range(60):
        h.observe(float(i + 1), now=clock[0])
        clock[0] += 1.0
    recent = h.window_sketch(10, now=clock[0])
    full = h.window_sketch(60, now=clock[0])
    assert recent.count <= 10 + 1
    assert full.count > recent.count
    # recent window only saw the large tail values
    assert recent.quantile(0.5) > full.quantile(0.5)


def test_windows_env_parse(monkeypatch):
    monkeypatch.setenv("MXTPU_METRICS_WINDOWS", "5,30,120")
    assert windows() == (5, 30, 120)
    monkeypatch.delenv("MXTPU_METRICS_WINDOWS")
    assert windows() == m.DEFAULT_WINDOWS


# ----------------------------------------------------------- exposition

def test_render_and_parse_prometheus_round_trip():
    reg = MetricsRegistry()
    reg.counter("mxtpu_reqs_total").inc(12)
    reg.gauge("mxtpu_depth", labels={"model": "echo"}).set(4)
    h = reg.histogram("mxtpu_lat_ms")
    for v in (5.0, 10.0, 200.0):
        h.observe(v, now=100.0)
    text = render_prometheus(reg, now=101.0)
    assert "# TYPE mxtpu_reqs_total counter" in text
    assert "# TYPE mxtpu_lat_ms summary" in text
    rows = parse_prometheus(text)
    byname = {}
    for name, labels, value in rows:
        byname.setdefault(name, []).append((labels, value))
    assert byname["mxtpu_reqs_total"][0][1] == 12.0
    assert byname["mxtpu_depth"][0][0] == {"model": "echo"}
    assert any(l.get("quantile") == "0.95"
               for l, _ in byname["mxtpu_lat_ms"])
    count = [v for l, v in byname["mxtpu_lat_ms_count"]][0]
    assert count == 3.0


def test_singleton_registry_reset():
    m.reset_registry()
    reg = m.registry()
    assert m.registry() is reg
    reg.counter("x").inc()
    m.reset_registry()
    assert m.registry() is not reg
