"""Mesh / sharded-trainer tests on the 8-device CPU mesh.

The reference fakes multi-device with multiple cpu(i) contexts
(tests/python/unittest/test_multi_device_exec.py); conftest.py's
xla_force_host_platform_device_count=8 is our analog (SURVEY §4).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import parallel


def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def test_make_mesh_shapes():
    assert len(jax.devices()) == 8, "conftest must force 8 cpu devices"
    mesh = parallel.make_mesh(dp=4, tp=2)
    assert mesh.shape == {"dp": 4, "tp": 2}
    mesh = parallel.make_mesh(dp=-1, tp=2)
    assert mesh.shape["dp"] == 4
    with pytest.raises(ValueError):
        parallel.make_mesh(dp=3, tp=2)
    mesh = parallel.auto_mesh()
    assert mesh.shape == {"dp": 8}


def test_param_pspec_rules():
    mesh = parallel.make_mesh(dp=4, tp=2)
    assert parallel.param_pspec("fc1_weight", (16, 8), mesh) == P("tp", None)
    assert parallel.param_pspec("fc1_bias", (16,), mesh) == P("tp")
    # non-divisible: replicate
    assert parallel.param_pspec("w", (5, 3), mesh) == P(None, None)
    assert parallel.batch_pspec((32, 8), mesh) == P("dp", None)


def test_dp_trainer_step_runs_and_learns():
    mesh = parallel.auto_mesh()  # dp=8
    net = _mlp()
    opt = mx.optimizer.create("sgd", learning_rate=0.5,
                              rescale_grad=1.0 / 64)
    tr = parallel.ShardedTrainer(net, opt, mesh)
    assert set(tr.param_names) == {"fc1_weight", "fc1_bias",
                                   "fc2_weight", "fc2_bias"}
    mx.random.seed(0)
    params, opt_state, aux, = tr.init_params({"data": (64, 8)},
                                             label_shapes={"softmax_label": (64,)})
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32) * 3  # labels in {0,3}
    batch = tr.shard_batch({"data": x, "softmax_label": y})

    first_acc = None
    for i in range(30):
        params, opt_state, aux, outs = tr.step(params, opt_state, aux, batch)
        pred = np.asarray(outs[0]).argmax(axis=1)
        acc = (pred == y).mean()
        if first_acc is None:
            first_acc = acc
    assert acc > 0.9, "did not learn: acc=%s (first=%s)" % (acc, first_acc)


def test_dp_matches_single_device():
    """DP-sharded step == unsharded step (the reference's
    test_model_parallel.py equivalence pattern)."""
    net = _mlp()

    def run(mesh):
        opt = mx.optimizer.create("sgd", learning_rate=0.1)
        tr = parallel.ShardedTrainer(net, opt, mesh)
        mx.random.seed(7)
        params, opt_state, aux = tr.init_params(
            {"data": (16, 8)}, label_shapes={"softmax_label": (16,)})
        rng = np.random.RandomState(1)
        x = rng.randn(16, 8).astype(np.float32)
        y = (rng.rand(16) * 4).astype(np.float32)
        batch = tr.shard_batch({"data": x, "softmax_label": y})
        for _ in range(3):
            params, opt_state, aux, outs = tr.step(params, opt_state, aux, batch)
        return {k: np.asarray(v) for k, v in params.items()}

    p_multi = run(parallel.auto_mesh())          # dp=8
    p_single = run(parallel.make_mesh(jax.devices()[:1], dp=1))
    for k in p_multi:
        np.testing.assert_allclose(p_multi[k], p_single[k], rtol=2e-4,
                                   atol=2e-5)


def test_tp_trainer_matches_replicated():
    """Tensor-parallel sharded params produce the same math."""
    net = _mlp()

    def run(mesh):
        opt = mx.optimizer.create("sgd", learning_rate=0.1)
        tr = parallel.ShardedTrainer(net, opt, mesh)
        mx.random.seed(3)
        params, opt_state, aux = tr.init_params(
            {"data": (8, 8)}, label_shapes={"softmax_label": (8,)})
        rng = np.random.RandomState(2)
        x = rng.randn(8, 8).astype(np.float32)
        y = (rng.rand(8) * 4).astype(np.float32)
        batch = tr.shard_batch({"data": x, "softmax_label": y})
        for _ in range(2):
            params, opt_state, aux, outs = tr.step(params, opt_state, aux, batch)
        return {k: np.asarray(v) for k, v in params.items()}, np.asarray(outs[0])

    p_tp, out_tp = run(parallel.make_mesh(dp=2, tp=4))
    p_rep, out_rep = run(parallel.make_mesh(jax.devices()[:1], dp=1))
    np.testing.assert_allclose(out_tp, out_rep, rtol=2e-4, atol=2e-5)
    for k in p_tp:
        np.testing.assert_allclose(p_tp[k], p_rep[k], rtol=2e-4, atol=2e-5)


def test_batchnorm_aux_updates_in_sharded_step():
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(bn, num_hidden=2),
                               name="softmax")
    mesh = parallel.auto_mesh()
    opt = mx.optimizer.create("sgd", learning_rate=0.01)
    tr = parallel.ShardedTrainer(net, opt, mesh)
    params, opt_state, aux = tr.init_params(
        {"data": (16, 4)}, label_shapes={"softmax_label": (16,)})
    assert "bn_moving_mean" in aux and "bn_moving_var" in aux
    x = np.random.RandomState(0).randn(16, 4).astype(np.float32) * 3 + 1
    batch = tr.shard_batch({"data": x,
                            "softmax_label": np.zeros(16, np.float32)})
    before = np.asarray(aux["bn_moving_mean"]).copy()
    params, opt_state, aux, _ = tr.step(params, opt_state, aux, batch)
    after = np.asarray(aux["bn_moving_mean"])
    assert not np.allclose(before, after)


def test_sharded_trainer_bf16_compute():
    """bf16 compute / f32 master params: step runs, params & aux stay f32,
    outputs track the f32 run loosely."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu import optimizer as opt_mod

    net = mx.models.get_mlp(num_classes=4, hidden=(16,))
    r = np.random.RandomState(0)
    X = r.rand(8, 10).astype(np.float32)
    y = r.randint(0, 4, (8,)).astype(np.float32)

    outs = {}
    for tag, cdt in [("f32", None), ("bf16", "bfloat16")]:
        mesh = make_mesh(jax.devices()[:2], dp=2)
        mx.random.seed(7)
        opt = opt_mod.create("sgd", learning_rate=0.1)
        tr = ShardedTrainer(net, opt, mesh, compute_dtype=cdt)
        params, opt_state, aux = tr.init_params(
            {"data": (8, 10)}, label_shapes={"softmax_label": (8,)})
        batch = tr.shard_batch({"data": X, "softmax_label": y})
        params, opt_state, aux, out = tr.step(params, opt_state, aux, batch)
        assert all(v.dtype == jnp.float32 for v in params.values())
        outs[tag] = np.asarray(out[0], np.float32)
    # bf16 mantissa is 8 bits: outputs agree to ~1e-2
    np.testing.assert_allclose(outs["f32"], outs["bf16"],
                               rtol=5e-2, atol=5e-2)


def test_sharded_trainer_remat():
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu import optimizer as opt_mod

    net = mx.models.get_mlp(num_classes=4, hidden=(16,))
    mesh = make_mesh(jax.devices()[:2], dp=2)
    opt = opt_mod.create("sgd", learning_rate=0.1)
    tr = ShardedTrainer(net, opt, mesh, remat=True)
    params, opt_state, aux = tr.init_params(
        {"data": (8, 10)}, label_shapes={"softmax_label": (8,)})
    r = np.random.RandomState(0)
    batch = tr.shard_batch({
        "data": r.rand(8, 10).astype(np.float32),
        "softmax_label": r.randint(0, 4, (8,)).astype(np.float32)})
    params, opt_state, aux, out = tr.step(params, opt_state, aux, batch)
    assert np.isfinite(np.asarray(out[0])).all()


def test_bf16_labels_stay_exact():
    """review finding: class ids > 256 must not round through bf16."""
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu import optimizer as opt_mod

    n_cls = 1000
    net = mx.models.get_mlp(num_classes=n_cls, hidden=(8,))
    mesh = make_mesh(jax.devices()[:1], dp=1)
    opt = opt_mod.create("sgd", learning_rate=1.0)
    tr = ShardedTrainer(net, opt, mesh, compute_dtype="bfloat16")
    params, opt_state, aux = tr.init_params(
        {"data": (2, 10)}, label_shapes={"softmax_label": (2,)})
    X = np.zeros((2, 10), np.float32)
    y = np.array([999.0, 257.0], np.float32)  # not bf16-representable
    batch = tr.shard_batch({"data": X, "softmax_label": y})
    p2, _, _, _ = tr.step(params, opt_state, aux, batch)
    # the SoftmaxOutput gradient is p - onehot(label): after one big step
    # from zero-init, the bias column of the TRUE class must move up
    bias = np.asarray(p2["fc2_bias"], np.float32)
    assert bias[999] > bias[998] and bias[257] > bias[256], (
        bias[[256, 257, 998, 999]])


def test_bf16_embedding_ids_stay_exact():
    """advisor finding: vocab ids > 256 are not bf16-representable; inputs
    feeding an Embedding's id slot must be exempt from the compute cast."""
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu import optimizer as opt_mod
    from mxnet_tpu.models import transformer

    V, S = 1000, 4
    net = transformer.get_symbol(vocab_size=V, num_layers=1, num_heads=2,
                                 dim=16, seq_len=S)
    mesh = make_mesh(jax.devices()[:1], dp=1)
    tr = ShardedTrainer(net, opt_mod.create("sgd", learning_rate=1.0),
                        mesh, compute_dtype="bfloat16")
    assert "data" in tr._cast_exempt  # detected from the Embedding node
    params, opt_state, aux = tr.init_params(
        {"data": (2, S)}, label_shapes={"softmax_label": (2, S)})
    ids = np.full((2, S), 999.0, np.float32)  # 999 rounds to 1000 in bf16
    batch = tr.shard_batch({"data": ids, "softmax_label": ids})
    p0 = {k: np.asarray(v) for k, v in params.items()}
    p2, _, _, _ = tr.step(params, opt_state, aux, batch)
    # only embedding row 999 (not 1000's neighborhood via rounding) moves
    emb_delta = np.abs(np.asarray(p2["tok_embed_weight"], np.float32)
                       - p0["tok_embed_weight"]).sum(axis=1)
    assert emb_delta[999] > 0
    assert emb_delta[996] == 0 and emb_delta[992] == 0


def test_zero1_optimizer_state_sharding():
    """ZeRO-1 (beyond-reference): momentum state lives dp-sharded (1/dp
    per rank), parameters stay replicated, and training matches the
    replicated-state baseline exactly."""
    net = _mlp()

    def run(zero1):
        mesh = parallel.make_mesh(dp=8)
        opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
        tr = parallel.ShardedTrainer(net, opt, mesh, zero1=zero1)
        mx.random.seed(11)
        params, opt_state, aux = tr.init_params(
            {"data": (16, 8)}, label_shapes={"softmax_label": (16,)})
        rng = np.random.RandomState(3)
        x = rng.randn(16, 8).astype(np.float32)
        y = (rng.rand(16) * 4).astype(np.float32)
        batch = tr.shard_batch({"data": x, "softmax_label": y})
        for _ in range(4):
            params, opt_state, aux, _outs = tr.step(params, opt_state,
                                                    aux, batch)
        return tr, params, opt_state

    tr, params, opt_state = run(zero1=True)
    # state for (16, 8) fc1_weight is dp-sharded: each device holds 1/8
    mom = jax.tree_util.tree_leaves(opt_state["fc1_weight"])[0]
    assert mom.sharding.spec[0] == "dp", mom.sharding
    assert mom.addressable_shards[0].data.shape[0] == mom.shape[0] // 8
    # params stayed replicated
    assert params["fc1_weight"].sharding.is_fully_replicated

    _, params_base, _ = run(zero1=False)
    for k in params:
        np.testing.assert_allclose(np.asarray(params[k]),
                                   np.asarray(params_base[k]),
                                   rtol=2e-5, atol=2e-6)

    # the compiled step really does gather: collective ops in the HLO
    lowered = tr._lower()
    hlo = lowered.compile().as_text()
    assert "all-gather" in hlo or "all-reduce" in hlo


def test_fsdp_param_sharding():
    """FSDP/ZeRO-3 (beyond-reference): params live dp-sharded (1/dp per
    rank), GSPMD gathers/scatters around compute, and training matches
    the replicated baseline."""
    net = _mlp()

    def run(fsdp):
        mesh = parallel.make_mesh(dp=8)
        opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
        tr = parallel.ShardedTrainer(net, opt, mesh, fsdp=fsdp)
        mx.random.seed(13)
        params, opt_state, aux = tr.init_params(
            {"data": (16, 8)}, label_shapes={"softmax_label": (16,)})
        rng = np.random.RandomState(5)
        x = rng.randn(16, 8).astype(np.float32)
        y = (rng.rand(16) * 4).astype(np.float32)
        batch = tr.shard_batch({"data": x, "softmax_label": y})
        for _ in range(4):
            params, opt_state, aux, _ = tr.step(params, opt_state, aux,
                                                batch)
        return params, opt_state

    params, opt_state = run(fsdp=True)
    # fc1_weight (16, 8): axis 0 dp-sharded, 2 rows per device
    w = params["fc1_weight"]
    assert w.sharding.spec[0] == "dp", w.sharding
    assert w.addressable_shards[0].data.shape == (2, 8)
    # its momentum follows the same partition
    mom = jax.tree_util.tree_leaves(opt_state["fc1_weight"])[0]
    assert mom.sharding.spec[0] == "dp"

    params_base, _ = run(fsdp=False)
    for k in params:
        np.testing.assert_allclose(np.asarray(params[k]),
                                   np.asarray(params_base[k]),
                                   rtol=2e-5, atol=2e-6)


def _np_moe(x, wg, w1, b1, w2, b2):
    t = x.reshape(-1, x.shape[-1])
    logits = t @ wg.T
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    top1 = probs.argmax(-1)
    out = np.zeros_like(t)
    for i, k in enumerate(top1):
        h = np.maximum(t[i] @ w1[k].T + b1[k], 0)
        out[i] = (h @ w2[k].T + b2[k]) * probs[i, k]
    return out.reshape(x.shape)


def test_moe_forward_matches_numpy():
    from mxnet_tpu.test_utils import check_symbolic_forward
    rng = np.random.RandomState(0)
    T, E, K, H = 12, 8, 4, 16
    x = rng.randn(T, E).astype(np.float32)
    wg = rng.randn(K, E).astype(np.float32)
    w1 = (rng.randn(K, H, E) * 0.3).astype(np.float32)
    b1 = (rng.randn(K, H) * 0.1).astype(np.float32)
    w2 = (rng.randn(K, E, H) * 0.3).astype(np.float32)
    b2 = (rng.randn(K, E) * 0.1).astype(np.float32)
    s = mx.sym.MoE(mx.sym.Variable("x"), num_experts=K, hidden_size=H,
                   name="moe")
    want = _np_moe(x, wg, w1, b1, w2, b2)
    check_symbolic_forward(s, [x, wg, w1, b1, w2, b2], [want], rtol=1e-4,
                           atol=1e-5)


def test_moe_ep_sharded_matches_replicated():
    """Expert parallelism: expert stacks sharded over 'ep', training step
    equals the replicated run; the combine collective is in the HLO."""
    E, K, H = 8, 4, 16

    def net():
        data = mx.sym.Variable("data")
        y, aux_l = mx.sym.MoE(data, num_experts=K, hidden_size=H,
                              name="moe")
        out = mx.sym.FullyConnected(y, num_hidden=4, name="cls")
        return mx.sym.SoftmaxOutput(out, name="softmax")

    def run(mesh):
        opt = mx.optimizer.create("sgd", learning_rate=0.1)
        tr = parallel.ShardedTrainer(net(), opt, mesh)
        mx.random.seed(17)
        params, opt_state, aux = tr.init_params(
            {"data": (16, E)}, label_shapes={"softmax_label": (16,)})
        rng = np.random.RandomState(7)
        batch = tr.shard_batch({
            "data": rng.randn(16, E).astype(np.float32),
            "softmax_label": (rng.rand(16) * 4).astype(np.float32)})
        for _ in range(3):
            params, opt_state, aux, _ = tr.step(params, opt_state, aux,
                                                batch)
        return tr, params

    mesh_ep = parallel.make_mesh(dp=2, ep=4)
    tr, p_ep = run(mesh_ep)
    w1 = p_ep["moe_expert_fc1_weight"]
    assert w1.sharding.spec[0] == "ep", w1.sharding
    assert w1.addressable_shards[0].data.shape[0] == 1  # 4 experts / 4

    _, p_rep = run(parallel.make_mesh(dp=8))
    for k in p_ep:
        np.testing.assert_allclose(np.asarray(p_ep[k]),
                                   np.asarray(p_rep[k]),
                                   rtol=2e-4, atol=2e-5)


def test_sharded_checkpoint_roundtrip(tmp_path):
    """Orbax sharded save/restore: every array comes back equal AND
    placed with the trainer's shardings (params zero1-sharded state,
    aux replicated) — the pod-scale checkpoint path where no host ever
    gathers the full model."""
    import jax

    def net():
        d = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(d, num_hidden=8, name="fc1")
        h = mx.sym.BatchNorm(h, name="bn")
        h = mx.sym.Activation(h, act_type="relu")
        out = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
        return mx.sym.SoftmaxOutput(out, name="softmax")

    mesh = parallel.make_mesh(dp=8)
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
    tr = parallel.ShardedTrainer(net(), opt, mesh, zero1=True)
    shapes = {"data": (16, 6)}
    lshapes = {"softmax_label": (16,)}
    params, opt_state, aux = tr.init_params(shapes, label_shapes=lshapes)
    rng = np.random.RandomState(0)
    batch = tr.shard_batch({
        "data": rng.rand(16, 6).astype(np.float32),
        "softmax_label": (rng.rand(16) * 4).astype(np.float32)})
    for _ in range(2):   # momentum state becomes nontrivial
        params, opt_state, aux, _ = tr.step(params, opt_state, aux, batch)

    ckpt = tmp_path / "ckpt"
    tr.save_checkpoint(ckpt, params, opt_state, aux)

    # a FRESH trainer restores placed states and continues stepping
    tr2 = parallel.ShardedTrainer(net(), opt, mesh, zero1=True)
    p2, s2, a2 = tr2.load_checkpoint(ckpt, shapes, label_shapes=lshapes)
    for name in params:
        assert np.allclose(np.asarray(params[name]), np.asarray(p2[name]))
        assert p2[name].sharding == tr2.param_sharding(name,
                                                       p2[name].shape)
    for name in opt_state:
        got = jax.tree_util.tree_leaves(s2[name])
        want = jax.tree_util.tree_leaves(opt_state[name])
        for g, w in zip(got, want):
            assert np.allclose(np.asarray(g), np.asarray(w))
    for name in aux:
        assert np.allclose(np.asarray(aux[name]), np.asarray(a2[name]))

    # the restored state steps identically to the original
    pa, sa, aa, outs_a = tr.step(params, opt_state, aux, batch)
    pb, sb, ab, outs_b = tr2.step(p2, s2, a2, batch)
    for name in pa:
        assert np.allclose(np.asarray(pa[name]), np.asarray(pb[name]),
                           atol=1e-6)


def test_sharded_checkpoint_resumes_update_counter():
    """Resume restores num_update: Adam's bias correction continues at
    the saved step (a fresh trainer would otherwise re-apply the step-1
    correction to mature state)."""
    import tempfile

    def net():
        d = mx.sym.Variable("data")
        out = mx.sym.FullyConnected(d, num_hidden=4, name="fc")
        return mx.sym.SoftmaxOutput(out, name="softmax")

    import jax
    mesh = parallel.make_mesh(jax.devices()[:2], dp=2)
    shapes = {"data": (8, 6)}
    lshapes = {"softmax_label": (8,)}
    rng = np.random.RandomState(0)
    batch_host = {"data": rng.rand(8, 6).astype(np.float32),
                  "softmax_label": (rng.rand(8) * 4).astype(np.float32)}

    def make():
        opt = mx.optimizer.create("adam", learning_rate=0.05)
        tr = parallel.ShardedTrainer(net(), opt, mesh)
        return tr

    tr = make()
    mx.random.seed(3)
    params, state, aux = tr.init_params(shapes, label_shapes=lshapes)
    batch = tr.shard_batch(batch_host)
    for _ in range(5):
        params, state, aux, _ = tr.step(params, state, aux, batch)
    with tempfile.TemporaryDirectory() as d:
        tr.save_checkpoint(d + "/ck", params, state, aux)

        tr2 = make()
        p2, s2, a2 = tr2.load_checkpoint(d + "/ck", shapes,
                                         label_shapes=lshapes)
        assert tr2.num_update == tr.num_update == 5

        # step 6 from the restored trainer == step 6 from the original
        pa, _, _, _ = tr.step(params, state, aux, batch)
        pb, _, _, _ = tr2.step(p2, s2, a2, batch)
        for name in pa:
            assert np.allclose(np.asarray(pa[name]), np.asarray(pb[name]),
                               atol=1e-6), name


def test_sharded_predictor_matches_single_device(tmp_path):
    """ShardedPredictor (serving side): tp-sharded inference from a
    classic checkpoint matches the single-device Predictor bitwise-close,
    loss-head label slot bound as zeros."""
    import jax
    from mxnet_tpu.predictor import Predictor

    def net():
        d = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
        h = mx.sym.Activation(h, act_type="relu")
        out = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
        return mx.sym.SoftmaxOutput(out, name="softmax")

    sym = net()
    mod = mx.mod.Module(sym, context=mx.context.cpu())
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.init.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)

    rng = np.random.RandomState(2)
    x = rng.rand(8, 6).astype(np.float32)

    ref = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                    {"data": (8, 6)})
    want = ref.forward(data=x)[0]

    mesh = parallel.make_mesh(dp=4, tp=2)
    sp = parallel.ShardedPredictor.from_checkpoint(prefix, 0, mesh)
    got = sp.predict({"data": x})[0]
    assert got.shape == want.shape
    assert np.allclose(got, want, atol=1e-5)

    # params actually landed tp-sharded where the rules say so
    spec = sp.params["fc1_weight"].sharding.spec
    assert any(ax == "tp" for ax in spec if ax is not None)
