"""Paged KV cache (docs/serving.md "Generation"): block allocator,
table correctness, tile legality, and sharding rules.

All host-side except the functional-update identity test — block
bookkeeping is pure Python, tile checks are the MXL-K static rules, so
these run in milliseconds on CPU.
"""
import numpy as np
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving.kvcache import (TRASH_BLOCK, CacheExhausted,
                                       KVCacheConfig, PagedKVCache,
                                       cache_kernel_spec,
                                       cache_sharding_rules, kv_block_size,
                                       kv_blocks, max_new_tokens)


def small_cache(num_blocks=8, block_size=8, init_pools=False):
    cfg = KVCacheConfig(num_layers=2, num_heads=2, head_dim=8,
                        max_seq_len=4 * block_size, num_blocks=num_blocks,
                        block_size=block_size)
    return PagedKVCache(cfg, init_pools=init_pools)


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------

def test_allocate_reserves_whole_budget():
    cache = small_cache()
    row = cache.allocate("a", 20)                   # ceil(20/8) = 3 blocks
    assert row.dtype == np.int32
    assert row.shape == (cache.config.blocks_per_seq,)
    used = [b for b in row if b != TRASH_BLOCK]
    assert len(used) == 3 and len(set(used)) == 3
    assert all(b == TRASH_BLOCK for b in row[3:])   # tail pads to trash
    assert cache.blocks_used() == 3
    assert cache.blocks_free() == cache.blocks_total() - 3


def test_trash_block_never_allocated():
    cache = small_cache(num_blocks=16)
    handed_out = []
    for i in range(15):                             # drain the whole pool
        row = cache.allocate(i, 8)
        handed_out.extend(b for b in row if b != TRASH_BLOCK)
    assert TRASH_BLOCK not in handed_out
    assert sorted(handed_out) == list(range(1, 16))
    assert cache.blocks_free() == 0


def test_free_and_reuse_out_of_order():
    """Finishing sequences in any order keeps tables disjoint and
    returns every block — the PagedAttention invariant."""
    cache = small_cache(num_blocks=10)
    rows = {s: cache.allocate(s, 24) for s in ("a", "b", "c")}  # 3 each
    assert cache.free("b") == 3
    row_d = cache.allocate("d", 24)                 # reuses b's blocks
    live = {s: {b for b in r if b != TRASH_BLOCK}
            for s, r in dict(rows, d=row_d).items() if s != "b"}
    all_blocks = [b for blocks in live.values() for b in blocks]
    assert len(all_blocks) == len(set(all_blocks))  # no aliasing
    assert set(row_d) - {TRASH_BLOCK} == set(rows["b"]) - {TRASH_BLOCK}
    for s in ("a", "c", "d"):
        cache.free(s)
    assert cache.blocks_used() == 0
    assert sorted(cache.active()) == []


def test_free_unknown_sequence_raises():
    cache = small_cache()
    with pytest.raises(MXNetError):
        cache.free("nope")
    cache.allocate("a", 8)
    cache.free("a")
    with pytest.raises(MXNetError):                 # double free is a bug
        cache.free("a")


def test_double_allocate_raises():
    cache = small_cache()
    cache.allocate("a", 8)
    with pytest.raises(MXNetError):
        cache.allocate("a", 8)


def test_exhaustion_is_structured_and_side_effect_free():
    """CacheExhausted carries the 429 payload and leaves the allocator
    untouched — backpressure, not corruption."""
    cache = small_cache(num_blocks=4)               # 3 usable blocks
    cache.allocate("a", 16)                         # takes 2
    free_before = cache.blocks_free()
    active_before = cache.active()
    with pytest.raises(CacheExhausted) as err:
        cache.allocate("b", 16)                     # needs 2, 1 free
    exc = err.value
    assert exc.to_dict() == {"error": "kv_cache_exhausted",
                             "blocks_needed": 2, "blocks_free": 1,
                             "blocks_total": 3}
    assert cache.blocks_free() == free_before
    assert cache.active() == active_before
    cache.free("a")                                 # recovers fully
    assert cache.blocks_free() == 3
    cache.allocate("b", 16)


def test_over_max_seq_len_raises():
    cache = small_cache()
    with pytest.raises(MXNetError):
        cache.allocate("a", cache.config.max_seq_len + 1)


def test_stats_and_high_water():
    cache = small_cache(num_blocks=8)
    cache.allocate("a", 16)
    cache.allocate("b", 8)
    s = cache.stats()
    assert s["blocks_total"] == 7
    assert s["blocks_used"] == 3
    assert s["seqs_active"] == 2
    assert s["occupancy"] == pytest.approx(3 / 7.0, abs=1e-3)
    cache.free("a")
    s2 = cache.stats()
    assert s2["blocks_used"] == 1
    assert s2["blocks_high_water"] == 3             # watermark sticks
    assert cache.occupancy() == pytest.approx(1 / 7.0)


# ---------------------------------------------------------------------------
# config / env knobs
# ---------------------------------------------------------------------------

def test_config_validation():
    with pytest.raises(MXNetError):                 # trash block needs >= 2
        KVCacheConfig(1, 2, 8, 32, num_blocks=1, block_size=8)
    with pytest.raises(MXNetError):                 # MXL-K001 sublane granule
        KVCacheConfig(1, 2, 8, 32, num_blocks=8, block_size=3)
    cfg = KVCacheConfig(2, 4, 16, 100, num_blocks=8, block_size=8)
    assert cfg.pool_shape == (8, 8, 4, 16)
    assert cfg.blocks_per_seq == 13                 # ceil(100/8)
    assert cfg.blocks_for(1) == 1
    assert cfg.blocks_for(8) == 1
    assert cfg.blocks_for(9) == 2
    assert cfg.to_dict()["block_size"] == 8


def test_env_knobs(monkeypatch):
    monkeypatch.setenv("MXTPU_SERVE_KV_BLOCKS", "99")
    monkeypatch.setenv("MXTPU_SERVE_KV_BLOCK_SIZE", "16")
    monkeypatch.setenv("MXTPU_SERVE_MAX_NEW_TOKENS", "7")
    assert kv_blocks() == 99
    assert kv_block_size() == 16
    assert max_new_tokens() == 7
    assert kv_blocks(12) == 12                      # explicit beats env
    monkeypatch.setenv("MXTPU_SERVE_KV_BLOCKS", "junk")
    assert kv_blocks() == 256                       # default on garbage


# ---------------------------------------------------------------------------
# tile legality (MXL-K) across the dtype sweep
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8"])
def test_cache_layout_is_tile_legal(dtype):
    """The default (block_size, head_dim) block must pass the MXL-K
    static rules at every serving dtype — the quantized tier reuses
    this exact layout."""
    from mxnet_tpu.analysis.tiling import spec_findings
    spec = cache_kernel_spec(dtype=dtype)
    errors = [f for f in spec_findings(spec) if f[1] == "error"]
    assert errors == [], errors


def test_cache_spec_registered_and_clean():
    """The registry sweep (what mxlint/CI runs) must report zero errors
    for the paged_kv_cache spec at all three dtypes."""
    from mxnet_tpu.analysis.tiling import kernel_spec_issues
    bad = [i for i in kernel_spec_issues()
           if i[0] == "paged_kv_cache" and i[2] == "error"]
    assert bad == [], bad
    names = {i[0] for i in kernel_spec_issues()}
    assert "paged_kv_cache" in names or not any(
        i[0] == "paged_kv_cache" for i in kernel_spec_issues())


def test_illegal_block_size_flagged_by_spec():
    """Sanity that the lint actually bites: a bf16 cache with a
    float32-granule block size must raise at config time."""
    with pytest.raises(MXNetError):
        KVCacheConfig(1, 2, 8, 64, num_blocks=8, block_size=8,
                      dtype="int8")                 # int8 granule is 32


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------

def test_sharding_rules_split_heads_over_tp():
    from jax.sharding import PartitionSpec as P
    rules = cache_sharding_rules(tp_axis="tp")
    pool = (8, 8, 4, 16)
    assert rules.match("layer0_k_cache", pool) == P(None, None, "tp", None)
    assert rules.match("layer3_v_cache", pool) == P(None, None, "tp", None)
    assert rules.match("block_table", (4, 13)) == P(None, None)


def test_shard_pools_on_mesh():
    """On the 8-device virtual mesh the pools land head-split; with
    heads == tp size each shard holds one head."""
    import jax
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:2])
    mesh = Mesh(devs, ("tp",))
    cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=8,
                        max_seq_len=32, num_blocks=4, block_size=8)
    cache = PagedKVCache(cfg, init_pools=True)
    spec = cache.shard_pools(mesh, tp_axis="tp")
    assert tuple(spec) == (None, None, "tp", None)
    shard_shapes = {s.data.shape for s in cache.k_pools[0].addressable_shards}
    assert shard_shapes == {(4, 8, 1, 8)}           # one head per tp rank


# ---------------------------------------------------------------------------
# functional update identity
# ---------------------------------------------------------------------------

def test_functional_pool_update_roundtrip():
    """A jit-pure ``.at[].set`` append installed via set_pools must be
    readable back bit-identically — the cache round-trip the decode
    loop performs every step."""
    import jax
    import jax.numpy as jnp
    cache = small_cache(num_blocks=4, block_size=8, init_pools=True)
    row = cache.allocate("s", 8)
    block = int(row[0])
    payload = np.arange(8 * 2 * 8, dtype=np.float32).reshape(8, 2, 8)

    @jax.jit
    def append(pool, val):
        return pool.at[block].set(val)

    new_k = [append(p, jnp.asarray(payload)) for p in cache.k_pools]
    new_v = [append(p, jnp.asarray(-payload)) for p in cache.v_pools]
    cache.set_pools(new_k, new_v)
    np.testing.assert_array_equal(np.asarray(cache.k_pools[1][block]),
                                  payload)
    np.testing.assert_array_equal(np.asarray(cache.v_pools[0][block]),
                                  -payload)
    # trash block untouched
    assert float(jnp.abs(cache.k_pools[0][TRASH_BLOCK]).sum()) == 0.0
    with pytest.raises(MXNetError):                 # layer-count guard
        cache.set_pools(new_k[:1], new_v)
