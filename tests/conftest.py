"""Test harness config: fake an 8-device TPU-like mesh on CPU.

This is the analog of the reference's multi-`mx.cpu(i)` trick
(tests/python/unittest/test_multi_device_exec.py): XLA's host platform is
forced to expose 8 devices so sharding/collective paths run without real
chips (SURVEY §4 "Implication for the TPU build").
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize may have imported jax at interpreter startup (capturing
# JAX_PLATFORMS from the outer env, e.g. a tpu plugin); the runtime config
# update wins over that capture, the env vars above cover the
# not-yet-imported case.
jax.config.update("jax_platforms", "cpu")

# fp64 for numeric-gradient checks (reference CPU tests run fp64 numpy refs)
jax.config.update("jax_enable_x64", True)

# MXTPU_LOCKCHECK=1 (serving/resilience CI legs): patch the lock
# factories BEFORE any package module builds its runtime state, so
# every package lock is traced and a live lock-order inversion raises
# ResilienceError(kind="lock_order") instead of deadlocking the suite.
from mxnet_tpu.observability import locktrace as _locktrace  # noqa: E402

_locktrace.maybe_install()

# MXTPU_RETRACE_SENTRY=1 (serving/resilience CI legs): wrap the
# lowering counter and the program-registry miss path so every
# post-warmup lowering is counted and attributed to the divergent
# cache-key ingredient (the zero-steady-state-lowerings contract's
# runtime witness — docs/perf.md, analysis MXL-X).
from mxnet_tpu.observability import retrace as _retrace  # noqa: E402

_retrace.maybe_install()
