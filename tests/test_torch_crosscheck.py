"""Cross-check core NN operators against torch (CPU) as an independent
oracle — beyond the numpy references in test_operator.py, this validates
convolution/pooling/batchnorm forward AND input/weight gradients against
a second industrial implementation across stride/pad/dilate/group
configurations (the role the reference's check_consistency cpu-vs-gpu
harness played, tests/python/gpu/test_operator_gpu.py there)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import symbol as sym  # noqa: E402


def _run_fwd_bwd(net, inputs, head_grad):
    """Bind, forward, backward with an explicit head gradient; returns
    (output, {name: grad})."""
    exe = net.simple_bind(mx.context.cpu(), grad_req="write",
                          **{k: v.shape for k, v in inputs.items()})
    for k, v in inputs.items():
        exe.arg_dict[k][:] = v
    out = exe.forward(is_train=True)[0].asnumpy()
    exe.backward(out_grads=[mx.nd.array(head_grad)])
    grads = {k: g.asnumpy() for k, g in exe.grad_dict.items()
             if g is not None}
    return out, grads


@pytest.mark.parametrize("stride,pad,dilate,groups", [
    ((1, 1), (0, 0), (1, 1), 1),
    ((2, 2), (1, 1), (1, 1), 1),
    ((1, 1), (2, 2), (2, 2), 1),
    ((2, 1), (1, 0), (1, 1), 2),
])
def test_convolution_vs_torch(stride, pad, dilate, groups):
    rng = np.random.RandomState(0)
    N, Cin, H, W, Cout, K = 2, 4, 9, 10, 6, 3
    x = rng.randn(N, Cin, H, W).astype("f")
    w = rng.randn(Cout, Cin // groups, K, K).astype("f")
    b = rng.randn(Cout).astype("f")

    net = sym.Convolution(sym.Variable("x"), weight=sym.Variable("w"),
                          bias=sym.Variable("b"), kernel=(K, K),
                          num_filter=Cout, stride=stride, pad=pad,
                          dilate=dilate, num_group=groups, name="conv")
    tx = torch.tensor(x, requires_grad=True)
    tw = torch.tensor(w, requires_grad=True)
    tb = torch.tensor(b, requires_grad=True)
    ty = F.conv2d(tx, tw, tb, stride=stride, padding=pad,
                  dilation=dilate, groups=groups)
    hg = rng.randn(*ty.shape).astype("f")
    ty.backward(torch.tensor(hg))

    out, grads = _run_fwd_bwd(net, {"x": x, "w": w, "b": b}, hg)
    assert np.allclose(out, ty.detach().numpy(), atol=1e-4), "forward"
    assert np.allclose(grads["x"], tx.grad.numpy(), atol=1e-4), "dx"
    assert np.allclose(grads["w"], tw.grad.numpy(), atol=1e-4), "dw"
    assert np.allclose(grads["b"], tb.grad.numpy(), atol=1e-4), "db"


@pytest.mark.parametrize("stride,pad", [((1, 1), (0, 0)), ((2, 2), (1, 1))])
def test_deconvolution_vs_torch(stride, pad):
    rng = np.random.RandomState(1)
    N, Cin, H, W, Cout, K = 2, 3, 6, 7, 5, 3
    x = rng.randn(N, Cin, H, W).astype("f")
    w = rng.randn(Cin, Cout, K, K).astype("f")

    net = sym.Deconvolution(sym.Variable("x"), weight=sym.Variable("w"),
                            kernel=(K, K), num_filter=Cout, stride=stride,
                            pad=pad, no_bias=True, name="deconv")
    tx = torch.tensor(x, requires_grad=True)
    tw = torch.tensor(w, requires_grad=True)
    ty = F.conv_transpose2d(tx, tw, stride=stride, padding=pad)
    hg = rng.randn(*ty.shape).astype("f")
    ty.backward(torch.tensor(hg))

    out, grads = _run_fwd_bwd(net, {"x": x, "w": w}, hg)
    assert np.allclose(out, ty.detach().numpy(), atol=1e-4), "forward"
    assert np.allclose(grads["x"], tx.grad.numpy(), atol=1e-4), "dx"
    assert np.allclose(grads["w"], tw.grad.numpy(), atol=1e-4), "dw"


@pytest.mark.parametrize("pool_type,stride", [("max", (2, 2)),
                                              ("avg", (2, 2)),
                                              ("max", (1, 1))])
def test_pooling_vs_torch(pool_type, stride):
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 8, 8).astype("f")
    net = sym.Pooling(sym.Variable("x"), kernel=(2, 2), stride=stride,
                      pool_type=pool_type, name="pool")
    tx = torch.tensor(x, requires_grad=True)
    if pool_type == "max":
        ty = F.max_pool2d(tx, 2, stride=stride)
    else:
        ty = F.avg_pool2d(tx, 2, stride=stride)
    hg = rng.randn(*ty.shape).astype("f")
    ty.backward(torch.tensor(hg))

    out, grads = _run_fwd_bwd(net, {"x": x}, hg)
    assert np.allclose(out, ty.detach().numpy(), atol=1e-5), "forward"
    assert np.allclose(grads["x"], tx.grad.numpy(), atol=1e-4), "dx"


def test_batchnorm_vs_torch():
    rng = np.random.RandomState(3)
    N, C, H, W = 4, 5, 6, 6
    x = rng.randn(N, C, H, W).astype("f")
    gamma = rng.rand(C).astype("f") + 0.5
    beta = rng.randn(C).astype("f")
    eps = 1e-3

    net = sym.BatchNorm(sym.Variable("x"), gamma=sym.Variable("gamma"),
                        beta=sym.Variable("beta"), eps=eps,
                        fix_gamma=False, name="bn")
    tx = torch.tensor(x, requires_grad=True)
    tg = torch.tensor(gamma, requires_grad=True)
    tb = torch.tensor(beta, requires_grad=True)
    ty = F.batch_norm(tx, torch.zeros(C), torch.ones(C), tg, tb,
                      training=True, eps=eps)
    hg = rng.randn(*ty.shape).astype("f")
    ty.backward(torch.tensor(hg))

    out, grads = _run_fwd_bwd(net, {"x": x, "gamma": gamma, "beta": beta},
                              hg)
    assert np.allclose(out, ty.detach().numpy(), atol=1e-4), "forward"
    assert np.allclose(grads["x"], tx.grad.numpy(), atol=1e-3), "dx"
    assert np.allclose(grads["gamma"], tg.grad.numpy(), atol=1e-3), "dg"
    assert np.allclose(grads["beta"], tb.grad.numpy(), atol=1e-3), "db"


def test_fullyconnected_softmax_vs_torch():
    rng = np.random.RandomState(4)
    N, D, K = 6, 10, 4
    x = rng.randn(N, D).astype("f")
    w = rng.randn(K, D).astype("f")
    b = rng.randn(K).astype("f")
    labels = rng.randint(0, K, N).astype("f")

    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Variable("x"), weight=sym.Variable("w"),
                           bias=sym.Variable("b"), num_hidden=K,
                           name="fc"),
        label=sym.Variable("softmax_label"), name="softmax")
    exe = net.simple_bind(mx.context.cpu(), grad_req="write", x=(N, D),
                          w=(K, D), b=(K,), softmax_label=(N,))
    exe.arg_dict["x"][:] = x
    exe.arg_dict["w"][:] = w
    exe.arg_dict["b"][:] = b
    exe.arg_dict["softmax_label"][:] = labels
    probs = exe.forward(is_train=True)[0].asnumpy()
    exe.backward()

    tx = torch.tensor(x, requires_grad=True)
    tw = torch.tensor(w, requires_grad=True)
    tb = torch.tensor(b, requires_grad=True)
    logits = F.linear(tx, tw, tb)
    tprobs = F.softmax(logits, dim=1)
    # SoftmaxOutput backward = probs - onehot (unnormalized), so compare
    # against N * mean-CE loss gradients
    loss = F.cross_entropy(logits, torch.tensor(labels, dtype=torch.long),
                           reduction="sum")
    loss.backward()

    assert np.allclose(probs, tprobs.detach().numpy(), atol=1e-5)
    assert np.allclose(exe.grad_dict["x"].asnumpy(), tx.grad.numpy(),
                       atol=1e-4)
    assert np.allclose(exe.grad_dict["w"].asnumpy(), tw.grad.numpy(),
                       atol=1e-4)
    assert np.allclose(exe.grad_dict["b"].asnumpy(), tb.grad.numpy(),
                       atol=1e-4)


def _pack_torch_rnn(tmod, num_layers, bidirectional,
                    extract=lambda p: p.detach()):
    """torch LSTM/GRU parameters -> our flat RNN vector (per layer+dir:
    w_x, w_h, b_x, b_h — same gate orders as torch).  ``extract`` picks
    what to pack (values by default, ``lambda p: p.grad`` for
    gradients) so the layout is defined exactly once."""
    chunks = []
    for layer in range(num_layers):
        for suffix in ("", "_reverse") if bidirectional else ("",):
            for kind in ("weight_ih", "weight_hh", "bias_ih", "bias_hh"):
                p = getattr(tmod, "%s_l%d%s" % (kind, layer, suffix))
                chunks.append(extract(p).numpy().ravel())
    return np.concatenate(chunks).astype("f")


@pytest.mark.parametrize("mode,layers,bidir", [
    ("lstm", 1, False), ("lstm", 2, False), ("lstm", 1, True),
    ("gru", 1, False), ("gru", 2, True),
])
def test_fused_rnn_vs_torch(mode, layers, bidir):
    """The fused RNN op (lax.scan per layer) matches torch.nn.LSTM/GRU
    outputs and final states bit-close when fed torch's own parameters —
    the cuDNN-parameterization contract the reference's RNN op carried."""
    rng = np.random.RandomState(5)
    S, B, I, H = 7, 3, 5, 4
    x = rng.randn(S, B, I).astype("f")
    ndir = 2 if bidir else 1

    if mode == "lstm":
        tmod = torch.nn.LSTM(I, H, num_layers=layers,
                             bidirectional=bidir)
    else:
        tmod = torch.nn.GRU(I, H, num_layers=layers, bidirectional=bidir)
    flat = _pack_torch_rnn(tmod, layers, bidir)
    h0 = rng.randn(ndir * layers, B, H).astype("f")
    c0 = rng.randn(ndir * layers, B, H).astype("f")
    with torch.no_grad():
        tstate0 = (torch.tensor(h0), torch.tensor(c0)) \
            if mode == "lstm" else torch.tensor(h0)
        tout, tstate = tmod(torch.tensor(x), tstate0)
    if mode == "lstm":
        th, tc = tstate
    else:
        th = tstate

    args = {"data": sym.Variable("data"),
            "parameters": sym.Variable("parameters"),
            "state": sym.Variable("state"),
            "state_size": H, "num_layers": layers, "mode": mode,
            "bidirectional": bidir, "state_outputs": True, "name": "rnn"}
    if mode == "lstm":
        args["state_cell"] = sym.Variable("state_cell")
    net = sym.RNN(**args)

    shapes = {"data": x.shape, "parameters": flat.shape,
              "state": (ndir * layers, B, H)}
    if mode == "lstm":
        shapes["state_cell"] = (ndir * layers, B, H)
    exe = net.simple_bind(mx.context.cpu(), grad_req="null", **shapes)
    exe.arg_dict["data"][:] = x
    exe.arg_dict["parameters"][:] = flat
    exe.arg_dict["state"][:] = h0
    if mode == "lstm":
        exe.arg_dict["state_cell"][:] = c0
    outs = exe.forward()
    assert np.allclose(outs[0].asnumpy(), tout.numpy(), atol=1e-5), "out"
    assert np.allclose(outs[1].asnumpy(), th.numpy(), atol=1e-5), "h_n"
    if mode == "lstm":
        assert np.allclose(outs[2].asnumpy(), tc.numpy(), atol=1e-5), "c_n"


def test_fused_rnn_gradients_vs_torch():
    """Backward through the fused RNN (vjp of the scan) matches torch's
    data, packed-parameter, AND initial-state gradients, from RANDOM
    initial states (all-zero states would mask state-indexing bugs)."""
    rng = np.random.RandomState(6)
    S, B, I, H, L = 5, 2, 4, 3, 2
    x = rng.randn(S, B, I).astype("f")
    h0 = rng.randn(L, B, H).astype("f")
    c0 = rng.randn(L, B, H).astype("f")
    tmod = torch.nn.LSTM(I, H, num_layers=L)
    flat = _pack_torch_rnn(tmod, L, False)
    tx = torch.tensor(x, requires_grad=True)
    th0 = torch.tensor(h0, requires_grad=True)
    tc0 = torch.tensor(c0, requires_grad=True)
    tout, _ = tmod(tx, (th0, tc0))
    hg = rng.randn(*tout.shape).astype("f")
    tout.backward(torch.tensor(hg))
    tgrad_flat = _pack_torch_rnn(tmod, L, False,
                                 extract=lambda p: p.grad)

    net = sym.RNN(data=sym.Variable("data"),
                  parameters=sym.Variable("parameters"),
                  state=sym.Variable("state"),
                  state_cell=sym.Variable("state_cell"),
                  state_size=H, num_layers=L, mode="lstm", name="rnn")
    exe = net.simple_bind(mx.context.cpu(), grad_req="write",
                          data=x.shape, parameters=flat.shape,
                          state=(L, B, H), state_cell=(L, B, H))
    exe.arg_dict["data"][:] = x
    exe.arg_dict["parameters"][:] = flat
    exe.arg_dict["state"][:] = h0
    exe.arg_dict["state_cell"][:] = c0
    exe.forward(is_train=True)
    exe.backward(out_grads=[mx.nd.array(hg)])
    assert np.allclose(exe.grad_dict["data"].asnumpy(), tx.grad.numpy(),
                       atol=1e-4), "d_data"
    assert np.allclose(exe.grad_dict["parameters"].asnumpy(), tgrad_flat,
                       atol=1e-4), "d_parameters"
    assert np.allclose(exe.grad_dict["state"].asnumpy(), th0.grad.numpy(),
                       atol=1e-4), "d_state"
    assert np.allclose(exe.grad_dict["state_cell"].asnumpy(),
                       tc0.grad.numpy(), atol=1e-4), "d_state_cell"


def test_embedding_vs_torch():
    """Embedding gather forward + scatter-add weight gradient."""
    rng = np.random.RandomState(7)
    V, D, N = 11, 6, 9
    ids = rng.randint(0, V, N).astype("f")
    w = rng.randn(V, D).astype("f")

    tw = torch.tensor(w, requires_grad=True)
    ty = F.embedding(torch.tensor(ids, dtype=torch.long), tw)
    hg = rng.randn(*ty.shape).astype("f")
    ty.backward(torch.tensor(hg))

    net = sym.Embedding(sym.Variable("ids"), weight=sym.Variable("w"),
                        input_dim=V, output_dim=D, name="emb")
    out, grads = _run_fwd_bwd(net, {"ids": ids, "w": w}, hg)
    assert np.allclose(out, ty.detach().numpy(), atol=1e-6)
    assert np.allclose(grads["w"], tw.grad.numpy(),
                       atol=1e-5), "scatter-add dw"


def test_prelu_vs_torch():
    """LeakyReLU(act_type='prelu'): learnable per-channel slope, forward
    + data and slope gradients."""
    rng = np.random.RandomState(8)
    N, C, H, W = 3, 4, 5, 5
    x = rng.randn(N, C, H, W).astype("f")
    alpha = rng.rand(C).astype("f") * 0.5

    tx = torch.tensor(x, requires_grad=True)
    ta = torch.tensor(alpha, requires_grad=True)
    ty = F.prelu(tx, ta)
    hg = rng.randn(*ty.shape).astype("f")
    ty.backward(torch.tensor(hg))

    net = sym.LeakyReLU(sym.Variable("x"), gamma=sym.Variable("gamma"),
                        act_type="prelu", name="prelu")
    out, grads = _run_fwd_bwd(net, {"x": x, "gamma": alpha}, hg)
    assert np.allclose(out, ty.detach().numpy(), atol=1e-6)
    assert np.allclose(grads["x"], tx.grad.numpy(), atol=1e-5)
    assert np.allclose(grads["gamma"], ta.grad.numpy(), atol=1e-4)


def test_lrn_vs_torch():
    """Cross-channel LRN: both sides use (k + alpha/n * sum)^-beta, so
    forward and data gradient must match torch's local_response_norm."""
    rng = np.random.RandomState(9)
    x = rng.rand(2, 7, 5, 5).astype("f") + 0.1
    nsize, alpha, beta, k = 5, 1e-2, 0.75, 2.0

    tx = torch.tensor(x, requires_grad=True)
    ty = F.local_response_norm(tx, nsize, alpha=alpha, beta=beta, k=k)
    hg = rng.randn(*ty.shape).astype("f")
    ty.backward(torch.tensor(hg))

    net = sym.LRN(sym.Variable("x"), nsize=nsize, alpha=alpha, beta=beta,
                  knorm=k, name="lrn")
    out, grads = _run_fwd_bwd(net, {"x": x}, hg)
    assert np.allclose(out, ty.detach().numpy(), atol=1e-5), "forward"
    assert np.allclose(grads["x"], tx.grad.numpy(), atol=1e-4), "dx"
