"""KVStore tests (mirrors reference tests/python/unittest/test_kvstore.py:
multiple NDArrays stand in for devices)."""
import numpy as np
import pytest

import mxnet_tpu as mx

shape = (4, 4)
keys = [5, 7, 11]


def init_kv():
    kv = mx.kv.create()
    kv.init(3, mx.nd.zeros(shape))
    kv.init(keys, [mx.nd.zeros(shape)] * len(keys))
    return kv


def check_diff_to_scalar(A, x):
    assert (A.asnumpy() == x).all(), A.asnumpy()


def test_single_kv_pair():
    kv = init_kv()
    kv.push(3, mx.nd.ones(shape))
    val = mx.nd.empty(shape)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 1)


def test_list_kv_pair():
    kv = init_kv()
    kv.push(keys, [mx.nd.ones(shape) * 4] * len(keys))
    val = [mx.nd.empty(shape)] * len(keys)
    kv.pull(keys, out=val)
    for v in val:
        check_diff_to_scalar(v, 4)


def test_aggregator():
    """Multiple NDArrays per key = multiple 'devices'; push sums them."""
    kv = init_kv()
    num_devs = 4
    vals = [mx.nd.ones(shape)] * num_devs
    kv.push(3, vals)
    outs = [mx.nd.empty(shape) for _ in range(num_devs)]
    kv.pull(3, out=outs)
    for out in outs:
        check_diff_to_scalar(out, num_devs)
    # list of keys, flat list of values (num_keys * num_devs)
    kv2 = init_kv()
    flat = [mx.nd.ones(shape) * 2.0 for _ in range(num_devs * len(keys))]
    kv2.push(keys, flat)
    kv2.pull(keys, out=flat)
    for v in flat:
        check_diff_to_scalar(v, 2.0 * num_devs)


def test_updater():
    kv = init_kv()

    def updater(key, recv, stored):
        stored += recv * 2

    kv.set_updater(updater)
    kv.push(3, mx.nd.ones(shape))
    val = mx.nd.empty(shape)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 2)
    kv.push(3, [mx.nd.ones(shape)] * 3)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 8)


def test_get_type_and_ranks():
    kvtype = "local_allreduce_cpu"
    kv = mx.kv.create(kvtype)
    assert kv.type == kvtype
    assert kv.rank == 0
    assert kv.num_workers == 1


def test_set_optimizer_pickles():
    kv = init_kv()
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1))
    kv.push(3, mx.nd.ones(shape))
    val = mx.nd.empty(shape)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, -0.1)


def test_dist_sync_single_process():
    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == 1 and kv.rank == 0
    kv.init(9, mx.nd.ones(shape))
    kv.push(9, mx.nd.ones(shape) * 3)
    out = mx.nd.empty(shape)
    kv.pull(9, out=out)
    check_diff_to_scalar(out, 3)
    kv.barrier()


def test_optimizer_states_roundtrip(tmp_path):
    kv = init_kv()
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1,
                                         momentum=0.9))
    kv.push(3, mx.nd.ones(shape))
    fname = str(tmp_path / "states.bin")
    kv.save_optimizer_states(fname)
    kv2 = init_kv()
    kv2.load_optimizer_states(fname)
    assert 3 in kv2._updater.states


def test_invalid_type():
    with pytest.raises(mx.MXNetError):
        mx.kv.create("nosuchstore")


# ----------------------------------------------------------------------
# 2-worker cluster-wide-decision smoke (tier-1 wrapper around
# tests/nightly/dist_csum.py): both ranks must adopt the verdicts rank 0
# published for the collective-sum and barrier paths — the protocol the
# @collective_seam markers certify for the MXL-D lint, and the fix for
# the pre-fix bug snapshotted in tests/fixtures/divergence/
# per_rank_barrier_probe.py.
# ----------------------------------------------------------------------
def test_cluster_wide_decision_smoke():
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, os.path.join(root, "tools", "launch.py"),
           "-n", "2", "--launcher", "local", "--workdir", root,
           "--port", "9901",
           sys.executable, os.path.join("tests", "nightly",
                                        "dist_csum.py")]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    proc = subprocess.run(cmd, cwd=root, env=env, timeout=420,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True)
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:])
    oks = [l for l in proc.stdout.splitlines()
           if l.strip().endswith("OK") and "verdicts" in l]
    assert len(oks) == 2, proc.stdout[-1500:]
    # the published verdict both ranks report must be identical
    assert len({l.split("csum=")[1] for l in oks}) == 1, oks
