"""Serving stack (docs/serving.md): bucket planner, continuous batcher,
ModelServer, serve telemetry, and the predictor AOT satellites.

All CPU-only: planner tests are pure host math over the MXL-R padding
cost model, batcher tests run against duck-typed fake model entries (no
jax on that path), and the end-to-end server tests use a toy MLP on the
virtual CPU mesh.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.executor import program_registry_stats
from mxnet_tpu.serving import (BucketPlan, ContinuousBatcher, ModelServer,
                               ServerBusy, bucket_for, parse_histogram,
                               plan_buckets, plan_cost, pow2_buckets,
                               serve_report)
from mxnet_tpu.serving.batcher import Request


# ---------------------------------------------------------------------------
# bucket planner
# ---------------------------------------------------------------------------

SKEWED = {3: 100, 5: 40, 65: 10, 70: 2}     # pow2 ceils to {4, 8, 128}


def test_planner_every_size_admissible():
    """Property: every histogram size maps to an admissible bucket."""
    for hist in (SKEWED, {1: 1}, {7: 3, 9: 2, 130: 1},
                 {n: n for n in range(1, 40, 3)}):
        plan = plan_buckets(hist, max_buckets=4)
        for size in hist:
            b = plan.bucket_for(size)
            assert b is not None and b >= size, (hist, size, plan.buckets)
        assert len(plan.buckets) <= 4


def test_planner_deterministic():
    """Property: output is a pure function of the histogram — input
    ordering and repeat runs never change the buckets."""
    items = list(SKEWED.items())
    a = plan_buckets(dict(items), max_buckets=2).buckets
    b = plan_buckets(dict(reversed(items)), max_buckets=2).buckets
    c = plan_buckets("3:100,5:40,65:10,70:2", max_buckets=2).buckets
    assert a == b == c
    assert a == plan_buckets(dict(items), max_buckets=2).buckets


def test_planner_beats_pow2_on_skewed_histogram():
    """The acceptance property: on a skewed histogram the planner's
    buckets cost strictly less total padded MXU work than naive pow-2
    ceilings — the planner demonstrably consumes mxu_padding_waste."""
    plan = plan_buckets(SKEWED, max_buckets=3)
    assert pow2_buckets(SKEWED) == (4, 8, 128)
    assert plan.cost < plan.pow2_cost, (plan.cost, plan.pow2_cost)
    assert plan.waste < plan.pow2_waste


def test_planner_optimal_vs_brute_force():
    """The DP must match brute force over all bucket subsets."""
    import itertools
    hist = {2: 9, 3: 5, 9: 4, 17: 2, 33: 1}
    sizes = sorted(hist)
    best = min(
        (plan_cost(combo, hist) for k in (1, 2)
         for combo in itertools.combinations(sizes, k)
         if combo[-1] == sizes[-1]),
        default=None)
    plan = plan_buckets(hist, max_buckets=2)
    assert plan.cost == pytest.approx(best)


def test_planner_few_sizes_get_exact_buckets():
    plan = plan_buckets({4: 1, 16: 1}, max_buckets=4)
    assert plan.buckets == (4, 16)
    assert plan.waste < 1.0


def test_parse_histogram_forms_and_errors():
    assert parse_histogram("1:100, 8:20") == {1: 100.0, 8: 20.0}
    assert parse_histogram([1, 1, 8]) == {1: 2.0, 8: 1.0}
    assert parse_histogram([(2, 5.0)]) == {2: 5.0}
    with pytest.raises(MXNetError):
        parse_histogram({})
    with pytest.raises(MXNetError):
        parse_histogram({0: 1})
    with pytest.raises(MXNetError):
        parse_histogram({2: -1})


def test_bucket_for_and_inadmissible_cost():
    assert bucket_for(5, (4, 8, 16)) == 8
    assert bucket_for(16, (4, 8, 16)) == 16
    assert bucket_for(17, (4, 8, 16)) is None
    with pytest.raises(MXNetError):
        plan_cost((4,), {5: 1})


def test_plan_to_dict_round_trips_json():
    plan = plan_buckets(SKEWED, max_buckets=2)
    doc = json.loads(json.dumps(plan.to_dict()))
    assert doc["buckets"] == list(plan.buckets)
    assert doc["pow2_buckets"] == [4, 8, 128]


# ---------------------------------------------------------------------------
# continuous batcher (duck-typed fake entries; no jax)
# ---------------------------------------------------------------------------

class FakeEntry(object):
    """Model entry double: payloads are numbers, results double them."""

    def __init__(self, name, buckets=(8,), priority=0, delay_s=0.0):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self.priority = priority
        self.delay_s = delay_s
        self.launched = []              # (bucket, n_requests) in order

    def pack(self, requests, bucket):
        return [r.payload for r in requests]

    def launch(self, payload, bucket):
        self.launched.append((bucket, len(payload)))
        if self.delay_s:
            time.sleep(self.delay_s)
        return payload

    def unpack(self, handle, requests, bucket):
        return [p * 2 for p in handle], {"device_ms": self.delay_s * 1e3,
                                         "unpack_ms": 0.0}

    def waste(self, n, bucket):
        return 1.0 - n / float(bucket)


def test_batcher_round_trip_and_stats():
    b = ContinuousBatcher(max_delay_ms_=5, max_queue_=64)
    b.register(FakeEntry("m", buckets=(4,)))
    futs = [b.submit("m", i) for i in range(10)]
    assert [f.result(timeout=10) for f in futs] == [2 * i
                                                    for i in range(10)]
    st = b.stats()
    assert st["requests"] == 10 and st["failed"] == 0
    assert st["latency_ms"]["p95"] is not None
    assert 0.0 < st["occupancy"] <= 1.0
    b.close()


def test_batcher_packs_up_to_bucket():
    """A busy pipeline lets companions accumulate; batches never exceed
    the largest bucket."""
    entry = FakeEntry("m", buckets=(4,), delay_s=0.02)
    b = ContinuousBatcher(max_delay_ms_=200, max_queue_=64)
    b.register(entry)
    futs = [b.submit("m", i) for i in range(12)]
    for f in futs:
        f.result(timeout=10)
    assert all(n <= 4 for _, n in entry.launched), entry.launched
    # with the pipeline busy 20ms per batch, later batches fill up
    assert any(n == 4 for _, n in entry.launched), entry.launched
    b.close()


def test_batcher_priority_selection():
    """_pick prefers higher priority, then the oldest head request."""
    b = ContinuousBatcher(max_delay_ms_=1000)
    lo, hi = FakeEntry("lo", priority=0), FakeEntry("hi", priority=5)
    b.register(lo)
    b.register(hi)
    # no scheduler thread yet: stage requests directly
    b._pending["lo"].append(Request("lo", 1, 1))
    time.sleep(0.002)
    b._pending["hi"].append(Request("hi", 2, 1))
    entry, _q, kind = b._pick()
    assert entry.name == "hi" and kind == "predict"
    b._pending["hi"].clear()
    entry, _q, kind = b._pick()
    assert entry.name == "lo" and kind == "predict"
    b.close(drain=False)


def test_batcher_backpressure_structured_429():
    """Beyond max_queue, submit raises a structured ServerBusy carrying
    machine-readable backpressure fields."""
    entry = FakeEntry("m", buckets=(8,), delay_s=0.2)
    b = ContinuousBatcher(max_delay_ms_=10_000, max_queue_=2)
    b.register(entry)
    # the idle pipeline dispatches the head eagerly, so the queue only
    # fills once launch() is busy sleeping: submit until the bound trips
    busy = None
    for i in range(5):
        try:
            b.submit("m", i)
        except ServerBusy as exc:
            busy = exc
            break
    assert busy is not None, "queue bound of 2 never tripped in 5 submits"
    assert isinstance(busy, MXNetError)        # catchable as the base
    assert busy.code == 429 and busy.limit == 2
    assert busy.queue_depth >= busy.limit
    doc = busy.to_dict()
    assert doc["error"] == "server_busy" and doc["retry_after_ms"] is not None
    assert b.stats()["rejected"] == 1
    b.close()


def test_batcher_rejects_unknown_and_oversized():
    b = ContinuousBatcher()
    b.register(FakeEntry("m", buckets=(4,)))
    with pytest.raises(MXNetError):
        b.submit("nope", 1)
    with pytest.raises(MXNetError):
        b.submit("m", 0, n=5)          # exceeds largest bucket
    b.close(drain=False)


def test_batcher_drain_flushes_then_refuses():
    """drain() completes every accepted request; submits after drain
    fail with the 503-flavored ServerBusy."""
    entry = FakeEntry("m", buckets=(8,), delay_s=0.01)
    b = ContinuousBatcher(max_delay_ms_=10_000, max_queue_=64)
    b.register(entry)
    futs = [b.submit("m", i) for i in range(5)]
    b.drain(timeout=10)
    assert [f.result(timeout=1) for f in futs] == [0, 2, 4, 6, 8]
    with pytest.raises(ServerBusy) as exc_info:
        b.submit("m", 9)
    assert exc_info.value.code == 503
    b.close()


def test_batcher_failure_propagates_to_futures():
    class Exploding(FakeEntry):
        def launch(self, payload, bucket):
            raise RuntimeError("kaboom")
    b = ContinuousBatcher(max_delay_ms_=5)
    b.register(Exploding("m", buckets=(4,)))
    fut = b.submit("m", 1)
    with pytest.raises(RuntimeError, match="kaboom"):
        fut.result(timeout=10)
    assert b.stats()["failed"] == 1
    b.close(drain=False)


def test_batcher_emits_serve_telemetry(tmp_path, monkeypatch):
    """Each dispatched batch lands one 'serve' record; serve_report
    derives per-model QPS/latency/occupancy from them."""
    from mxnet_tpu.observability import events
    monkeypatch.setenv("MXTPU_TELEMETRY", "1")
    monkeypatch.setenv("MXTPU_TELEMETRY_DIR", str(tmp_path))
    events.refresh()
    try:
        b = ContinuousBatcher(max_delay_ms_=5)
        b.register(FakeEntry("m", buckets=(4,)))
        futs = [b.submit("m", i) for i in range(8)]
        for f in futs:
            f.result(timeout=10)
        b.close()
        events.flush()
        from mxnet_tpu.observability import aggregate
        records = aggregate.read_events(str(tmp_path))
        serves = [r for r in records if r["kind"] == "serve"]
        assert serves, records
        rec = serves[0]
        for field in ("model", "bucket", "n_requests", "occupancy",
                      "padding_waste", "queue_wait_ms", "pack_ms",
                      "device_ms", "unpack_ms", "lat_ms"):
            assert field in rec, rec
        rep = serve_report(records)
        m = rep["models"]["m"]
        assert m["requests"] == 8
        assert m["latency_ms"]["p95"] is not None
        assert rep["total"]["requests"] == 8
        # the merged pod report carries the same rollup for mxtop
        full = aggregate.build_report(records)
        assert full["serve"]["models"]["m"]["requests"] == 8
    finally:
        monkeypatch.delenv("MXTPU_TELEMETRY")
        monkeypatch.delenv("MXTPU_TELEMETRY_DIR")
        events.refresh()


def test_serve_is_a_registered_event_kind():
    from mxnet_tpu.observability.events import KINDS
    assert "serve" in KINDS


# ---------------------------------------------------------------------------
# ModelServer end-to-end (toy MLP on the CPU mesh)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def toy_model():
    net = mx.models.get_mlp(num_classes=3, hidden=(8,))
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (2, 10))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    arg_params, aux_params = mod.get_params()
    params = {"arg:" + k: v for k, v in arg_params.items()}
    params.update({"aux:" + k: v for k, v in aux_params.items()})
    return net, params


def test_server_matches_serial_predictor(toy_model):
    """Batched results must be numerically identical to what a plain
    per-request Predictor computes — batching moves requests, never
    numbers."""
    net, params = toy_model
    srv = ModelServer(max_delay_ms=5)
    srv.add_model("toy", net.tojson(), params, {"data": (10,)},
                  buckets=(1, 4))
    rng = np.random.RandomState(3)
    payloads = [rng.rand(n, 10).astype("float32")
                for n in (1, 2, 4, 3, 1, 2)]
    futs = [srv.submit("toy", x) for x in payloads]
    got = [f.result(timeout=30) for f in futs]
    srv.close()
    for x, out in zip(payloads, got):
        ref = mx.Predictor(net.tojson(), params,
                           {"data": x.shape}).forward(data=x)
        assert out[0].shape == (x.shape[0], 3)
        np.testing.assert_allclose(out[0], ref[0], rtol=1e-5, atol=1e-6)


def test_server_zero_lowerings_after_warmup(toy_model):
    """The AOT contract: add_model pre-compiles every bucket; serving
    any number of requests afterwards performs zero new lowerings."""
    net, params = toy_model
    srv = ModelServer(max_delay_ms=2)
    srv.add_model("toy", net.tojson(), params, {"data": (10,)},
                  histogram={1: 10, 3: 5, 4: 1})
    before = program_registry_stats()["lowerings"]
    rng = np.random.RandomState(5)
    futs = [srv.submit("toy", rng.rand(n, 10).astype("float32"))
            for n in (1, 3, 4) * 10]
    for f in futs:
        f.result(timeout=30)
    stats = srv.stats()
    srv.close()
    assert program_registry_stats()["lowerings"] == before
    assert stats["models"]["toy"]["lowerings_since_warmup"] == 0
    assert stats["registry"]["programs"] >= 1


def test_server_validates_inputs(toy_model):
    net, params = toy_model
    srv = ModelServer(max_delay_ms=2)
    srv.add_model("toy", net.tojson(), params, {"data": (10,)},
                  buckets=(2,))
    with pytest.raises(MXNetError):
        srv.submit("nope", np.zeros((1, 10), "float32"))
    with pytest.raises(MXNetError):            # bad per-sample shape
        srv.submit("toy", np.zeros((1, 7), "float32"))
    with pytest.raises(MXNetError):            # exceeds largest bucket
        srv.submit("toy", np.zeros((3, 10), "float32"))
    # a single bare sample (no batch axis) is promoted to n=1
    out = srv.predict("toy", np.zeros(10, "float32"))
    assert out[0].shape == (1, 3)
    srv.close()


def test_server_plans_from_histogram(toy_model):
    """add_model without explicit buckets consults the planner (and the
    plan beats pow-2 on a skewed histogram, end to end)."""
    net, params = toy_model
    srv = ModelServer(max_delay_ms=2)
    plan = srv.add_model("toy", net.tojson(), params, {"data": (10,)},
                         histogram=SKEWED, max_buckets=3)
    srv.close()
    assert isinstance(plan, BucketPlan)
    assert len(plan.buckets) <= 3
    assert plan.cost < plan.pow2_cost
    for size in SKEWED:
        assert plan.bucket_for(size) is not None


# ---------------------------------------------------------------------------
# predictor satellites
# ---------------------------------------------------------------------------

def test_second_predictor_zero_lowerings(toy_model):
    """Constructing a second Predictor for the same symbol/shape reuses
    the program registry: zero new lowerings, counted hits."""
    net, params = toy_model
    p1 = mx.Predictor(net.tojson(), params, {"data": (2, 10)})
    stats1 = mx.Predictor.compile_stats()
    p2 = mx.Predictor(net.tojson(), params, {"data": (2, 10)})
    stats2 = mx.Predictor.compile_stats()
    assert stats2["lowerings"] == stats1["lowerings"]
    assert stats2["hits"] > stats1["hits"]
    x = np.random.rand(2, 10).astype("float32")
    np.testing.assert_allclose(p1.forward(data=x)[0],
                               p2.forward(data=x)[0], rtol=1e-6)


def test_forward_async_matches_forward(toy_model):
    net, params = toy_model
    pred = mx.Predictor(net.tojson(), params, {"data": (2, 10)})
    x = np.random.rand(2, 10).astype("float32")
    ref = pred.forward(data=x)
    raw = pred.forward_async(data=x)
    assert len(raw) == len(ref)
    np.testing.assert_allclose(np.asarray(raw[0]), ref[0], rtol=1e-6)


def test_forward_async_results_survive_next_dispatch(toy_model):
    """Async results are owned by the caller: dispatching batch N+1
    must not clobber batch N's arrays (the in-place output slots of
    plain forward() would)."""
    net, params = toy_model
    pred = mx.Predictor(net.tojson(), params, {"data": (1, 10)})
    xa = np.full((1, 10), 0.25, "float32")
    xb = np.full((1, 10), 0.75, "float32")
    ref_a = pred.forward(data=xa)[0].copy()
    raw_a = pred.forward_async(data=xa)
    _raw_b = pred.forward_async(data=xb)
    np.testing.assert_allclose(np.asarray(raw_a[0]), ref_a, rtol=1e-6)


def test_load_ndarray_file_round_trip(tmp_path):
    """Satellite: bytes, str path, and os.PathLike all round-trip."""
    from mxnet_tpu.predictor import load_ndarray_file
    arrays = {"arg:w": mx.nd.array(np.arange(6.0).reshape(2, 3)),
              "aux:m": mx.nd.ones((4,))}
    path = tmp_path / "weights.params"
    mx.nd.save(str(path), arrays)
    for src in (str(path), path, open(str(path), "rb").read()):
        loaded = load_ndarray_file(src)
        assert sorted(loaded) == ["arg:w", "aux:m"]
        np.testing.assert_array_equal(loaded["arg:w"].asnumpy(),
                                      arrays["arg:w"].asnumpy())
        np.testing.assert_array_equal(loaded["aux:m"].asnumpy(),
                                      arrays["aux:m"].asnumpy())


def test_predictor_accepts_pathlike_checkpoint(tmp_path, toy_model):
    """Satellite: Predictor takes os.PathLike for both files."""
    import pathlib
    net, params = toy_model
    arg_params = {k[4:]: v for k, v in params.items()
                  if k.startswith("arg:")}
    aux_params = {k[4:]: v for k, v in params.items()
                  if k.startswith("aux:")}
    prefix = str(tmp_path / "toy")
    mx.model.save_checkpoint(prefix, 1, net, arg_params, aux_params)
    sym_path = pathlib.Path(prefix + "-symbol.json")
    params_path = pathlib.Path(prefix + "-0001.params")
    pred = mx.Predictor(sym_path, params_path, {"data": (1, 10)})
    out = pred.forward(data=np.zeros((1, 10), "float32"))
    assert out[0].shape == (1, 3)


def test_server_warm_remesh_rebind_zero_lowerings(toy_model, tmp_path,
                                                  monkeypatch):
    """Serving warm elasticity (docs/resilience.md "Warm elasticity"):
    snapshot_hotstate captures every model's params AND bind config into
    the ``serve`` handoff namespace; a fresh ModelServer rebuilds from
    host memory alone (warm_resume_models) — no checkpoint/param files —
    answers bit-identically, and the per-bucket rebinds ride the PR-8
    program registry, so the swap performs zero new lowerings."""
    net, params = toy_model
    monkeypatch.setenv("MXTPU_WARM_REMESH", "1")
    monkeypatch.setenv("MXTPU_HANDOFF_DIR", str(tmp_path / "handoff"))
    srv = ModelServer(max_delay_ms=2)
    srv.add_model("toy", net.tojson(), params, {"data": (10,)},
                  buckets=(1, 4), priority=2)
    x = np.random.RandomState(9).rand(3, 10).astype("float32")
    want = srv.predict("toy", x)
    srv.snapshot_hotstate(step=11)
    srv.close()

    srv2 = ModelServer(max_delay_ms=2)
    before = program_registry_stats()["lowerings"]
    restored = srv2.warm_resume_models()
    assert restored == ["toy"]
    assert program_registry_stats()["lowerings"] == before
    got = srv2.predict("toy", x)
    stats = srv2.stats()
    srv2.close()
    np.testing.assert_array_equal(got[0], want[0])
    assert stats["models"]["toy"]["lowerings_since_warmup"] == 0
    # the bind config came back from the payload, not from defaults
    assert list(srv2.plan("toy").buckets) == [1, 4]
    assert srv2._entries["toy"].priority == 2

    # no surviving payload -> structured HotStateUnavailable, the cue
    # to fall back to checkpoint files
    from mxnet_tpu.resilience import HotStateUnavailable, hotstate
    hotstate.clear("serve")
    srv3 = ModelServer(max_delay_ms=2)
    with pytest.raises(HotStateUnavailable):
        srv3.warm_resume_models()
    srv3.close()
