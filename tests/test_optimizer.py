"""Optimizer tests: each rule vs a hand-written numpy reference.

Mirrors the reference's tests/python/unittest style (plain asserts, numpy
refs) for the optimizer zoo (python/mxnet/optimizer.py).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt


def _setup(shape=(4, 7), seed=0):
    rng = np.random.RandomState(seed)
    w = rng.uniform(-1, 1, size=shape).astype(np.float32)
    g = rng.uniform(-1, 1, size=shape).astype(np.float32)
    return w, g


def _run(optimizer, w, g, steps=3):
    weight = mx.nd.array(w.copy())
    state = optimizer.create_state(0, weight)
    for _ in range(steps):
        grad = mx.nd.array(g)
        optimizer.update(0, weight, grad, state)
    return weight.asnumpy()


def test_sgd_no_momentum():
    w, g = _setup()
    out = _run(opt.create("sgd", learning_rate=0.1, wd=0.01), w, g, steps=1)
    expect = w - 0.1 * (g + 0.01 * w)
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_sgd_momentum():
    w, g = _setup()
    lr, mu, wd = 0.1, 0.9, 0.001
    out = _run(opt.create("sgd", learning_rate=lr, momentum=mu, wd=wd), w, g, steps=3)
    ww, m = w.copy(), np.zeros_like(w)
    for _ in range(3):
        gg = g + wd * ww
        m = mu * m - lr * gg
        ww = ww + m
    np.testing.assert_allclose(out, ww, rtol=1e-5)


def test_sgd_rescale_clip():
    w, g = _setup()
    o = opt.create("sgd", learning_rate=1.0, rescale_grad=0.5, clip_gradient=0.1)
    out = _run(o, w, g, steps=1)
    expect = w - 1.0 * np.clip(g * 0.5, -0.1, 0.1)
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_nag():
    w, g = _setup()
    lr, mu = 0.05, 0.9
    out = _run(opt.create("nag", learning_rate=lr, momentum=mu), w, g, steps=2)
    ww, m = w.copy(), np.zeros_like(w)
    for _ in range(2):
        gg = g.copy()
        m = mu * m + gg
        ww = ww - lr * (gg + mu * m)
    np.testing.assert_allclose(out, ww, rtol=1e-5)


def test_adam():
    w, g = _setup()
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    out = _run(opt.create("adam", learning_rate=lr), w, g, steps=4)
    ww = w.copy()
    mean = np.zeros_like(w)
    var = np.zeros_like(w)
    for t in range(1, 5):
        mean = b1 * mean + (1 - b1) * g
        var = b2 * var + (1 - b2) * g * g
        mhat = mean / (1 - b1 ** t)
        vhat = var / (1 - b2 ** t)
        ww = ww - lr * mhat / (np.sqrt(vhat) + eps)
    np.testing.assert_allclose(out, ww, rtol=1e-4)


def test_adagrad():
    w, g = _setup()
    lr, eps = 0.1, 1e-7
    out = _run(opt.create("adagrad", learning_rate=lr), w, g, steps=3)
    ww, hist = w.copy(), np.zeros_like(w)
    for _ in range(3):
        hist += g * g
        ww = ww - lr * g / np.sqrt(hist + eps)
    np.testing.assert_allclose(out, ww, rtol=1e-4)


def test_rmsprop():
    w, g = _setup()
    o = opt.create("rmsprop", learning_rate=0.002)
    out = _run(o, w, g, steps=3)
    ww = w.copy()
    n = np.zeros_like(w); gg = np.zeros_like(w); d = np.zeros_like(w)
    for _ in range(3):
        n = 0.05 * g * g + 0.95 * n
        gg = 0.05 * g + 0.95 * gg
        d = 0.9 * d - 0.002 * g / np.sqrt(n - gg * gg + 1e-4)
        ww = ww + d
    np.testing.assert_allclose(out, ww, rtol=1e-4)


def test_adadelta():
    w, g = _setup()
    out = _run(opt.create("adadelta"), w, g, steps=3)
    ww = w.copy()
    ag = np.zeros_like(w); ad = np.zeros_like(w)
    rho, eps = 0.90, 1e-5
    for _ in range(3):
        ag = rho * ag + (1 - rho) * g * g
        delta = np.sqrt(ad + eps) / np.sqrt(ag + eps) * g
        ad = rho * ad + (1 - rho) * delta * delta
        ww = ww - delta
    np.testing.assert_allclose(out, ww, rtol=1e-4)


def test_test_optimizer():
    w, g = _setup()
    out = _run(opt.create("test", rescale_grad=1.0), w, g, steps=1)
    np.testing.assert_allclose(out, w + g, rtol=1e-6)


def test_lamb_trust_ratio_runs():
    w, g = _setup()
    out = _run(opt.create("lamb", learning_rate=0.01), w, g, steps=2)
    assert out.shape == w.shape
    assert np.isfinite(out).all()


def test_get_updater_state_per_index():
    w1, g1 = _setup(seed=1)
    w2, g2 = _setup(seed=2)
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9)
    updater = opt.get_updater(o)
    a1, a2 = mx.nd.array(w1), mx.nd.array(w2)
    updater(0, mx.nd.array(g1), a1)
    updater(3, mx.nd.array(g2), a2)
    assert 0 in updater.states and 3 in updater.states
    np.testing.assert_allclose(a1.asnumpy(), w1 - 0.1 * g1, rtol=1e-5)


def test_lr_scheduler_factor():
    from mxnet_tpu.lr_scheduler import FactorScheduler
    sched = FactorScheduler(step=10, factor=0.5)
    sched.base_lr = 1.0
    assert sched(5) == 1.0
    assert sched(11) == 0.5
    assert sched(21) == 0.25


def test_lr_scheduler_multifactor():
    from mxnet_tpu.lr_scheduler import MultiFactorScheduler
    sched = MultiFactorScheduler(step=[5, 8], factor=0.1)
    sched.base_lr = 1.0
    assert abs(sched(4) - 1.0) < 1e-12
    assert abs(sched(6) - 0.1) < 1e-12
    assert abs(sched(9) - 0.01) < 1e-12


def test_optimizer_with_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler
    w, g = _setup()
    o = opt.create("sgd", learning_rate=0.1,
                   lr_scheduler=FactorScheduler(step=1, factor=0.1))
    weight = mx.nd.array(w.copy())
    o.update(0, weight, mx.nd.array(g), None)
    after1 = weight.asnumpy()
    np.testing.assert_allclose(after1, w - 0.1 * g, rtol=1e-5)


def test_lr_wd_mult():
    w, g = _setup()
    o = opt.create("sgd", learning_rate=0.1, wd=0.1,
                   param_idx2name={0: "fc_weight", 1: "fc_bias"})
    o.set_wd_mult({})
    o.set_lr_mult({"fc_bias": 2.0})
    wt = mx.nd.array(w.copy()); bs = mx.nd.array(w.copy())
    o.update(0, wt, mx.nd.array(g), None)
    o.update(1, bs, mx.nd.array(g), None)
    np.testing.assert_allclose(wt.asnumpy(), w - 0.1 * (g + 0.1 * w), rtol=1e-5)
    # bias: wd_mult defaults to 0 for non-weight/gamma, lr_mult 2x
    np.testing.assert_allclose(bs.asnumpy(), w - 0.2 * g, rtol=1e-5)


def test_create_unknown_raises():
    with pytest.raises(ValueError):
        opt.create("nosuchopt")
