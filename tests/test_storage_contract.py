"""Storage-layer contracts (SURVEY §2 Storage row).

The reference's pooled allocator (src/storage/pooled_storage_manager.h)
recycles buffers and the memory planner aliases in-place ops
(graph_memory_allocator.h).  Here XLA owns buffers, so the testable
contract is: (a) donated step inputs really are aliased to outputs
(in-place update, no 2x parameter memory), (b) donated buffers are
actually invalidated (the reuse happened, not a copy), (c) executors
bound to one symbol share a single compiled program (GraphStoragePool /
shared_exec analog)."""
import numpy as np

import jax
import jax.numpy as jnp

import mxnet_tpu as mx


def test_sharded_trainer_donation_aliases_buffers():
    """donation_verified() reads XLA memory analysis: alias bytes > 0
    means parameters update in place rather than allocating a second
    copy per step."""
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    mesh = make_mesh(jax.devices()[:1], dp=1)
    sym = mx.models.get_mlp(num_classes=4, hidden=(16,))
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
    tr = ShardedTrainer(sym, opt, mesh)
    params, opt_state, aux = tr.init_params(
        {"data": (8, 10)}, label_shapes={"softmax_label": (8,)})
    batch = tr.shard_batch({
        "data": np.random.RandomState(0).rand(8, 10).astype(np.float32),
        "softmax_label": np.zeros(8, np.float32)})
    old_param = params["fc1_weight"]
    params, opt_state, aux, _ = tr.step(params, opt_state, aux, batch)
    assert tr.donation_verified() is True
    # the donated input buffer must be gone (aliased away, not copied)
    assert old_param.is_deleted()


def test_fused_step_donates_optimizer_states():
    """Module fused path donates the optimizer-state pytree: the previous
    step's state buffers are invalidated, so momentum does not cost two
    generations of memory."""
    sym = mx.models.get_mlp(num_classes=2, hidden=(8,))
    exe = sym.simple_bind(mx.cpu(0), data=(4, 10), grad_req="write")
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = np.random.RandomState(1).uniform(
                -0.1, 0.1, arr.shape).astype(np.float32)
    exe.arg_dict["data"][:] = np.random.RandomState(2).rand(
        4, 10).astype(np.float32)
    exe.arg_dict["softmax_label"][:] = np.array([0, 1, 0, 1], np.float32)

    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
    states = exe.init_fused_states(opt)
    states = exe.fused_step(opt, states, 1)
    prev = jax.tree_util.tree_leaves(states)
    states2 = exe.fused_step(opt, states, 2)
    assert all(leaf.is_deleted() for leaf in prev)
    assert not any(leaf.is_deleted()
                   for leaf in jax.tree_util.tree_leaves(states2))


def test_executors_share_compiled_program():
    """Two executors bound to the same symbol share one traced program
    (symbol._jit_cache) — the shared-memory re-bind story
    (GraphExecutor shared_mem_, executor_group shared_data_arrays)."""
    sym = mx.models.get_mlp(num_classes=2, hidden=(8,))
    e1 = sym.simple_bind(mx.cpu(0), data=(4, 10))
    e2 = sym.simple_bind(mx.cpu(0), data=(8, 10))   # different shapes
    assert e1._program is e2._program
    # and the jitted callable is the same object: per-shape compiles land
    # in ONE jit cache, not one per executor
    assert e1._jit_forward is e2._jit_forward
