"""Mirroring (activation recompute) lowered to per-segment jax.checkpoint.

Reference: MakeBackwardPass builds a mirror map and splices duplicate
nodes so backward reads recomputed activations
(static_graph.cc:396-440); the executor drops mirrored forward nodes
from the backward topo (graph_executor.cc:313-352).  Here the same
need_mirror rules partition the trace into ``jax.checkpoint`` segments:
internals leave the vjp residual set and recompute in backward.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp(attr=None, n_layers=5, hidden=64, act="tanh"):
    x = mx.sym.Variable("data")
    h = x
    for i in range(n_layers):
        h = mx.sym.FullyConnected(h, num_hidden=hidden, name="fc%d" % i)
        h = mx.sym.Activation(h, act_type=act, name="act%d" % i,
                              attr=attr or {})
    return mx.sym.SoftmaxOutput(h, mx.sym.Variable("softmax_label"),
                                name="softmax")


def _bind_run(sym, batch=16, dim=64, seed=3):
    ex = sym.simple_bind(mx.cpu(), data=(batch, dim), grad_req="write")
    rs = np.random.RandomState(seed)
    for n, a in ex.arg_dict.items():
        if n not in ("data", "softmax_label"):
            a[:] = rs.rand(*a.shape).astype(np.float32)
    ex.arg_dict["data"][:] = rs.rand(batch, dim).astype(np.float32)
    ex.arg_dict["softmax_label"][:] = rs.randint(
        0, dim, (batch,)).astype(np.float32)
    ex.forward(is_train=True)
    ex.backward()
    return ex


def test_force_mirroring_numerics_and_residuals():
    plain = _bind_run(_mlp())
    mirr = _bind_run(_mlp(attr={"force_mirroring": "true"}))
    assert np.allclose(plain.outputs[0].asnumpy(),
                       mirr.outputs[0].asnumpy(), atol=1e-5)
    for n, g in plain.grad_dict.items():
        assert np.allclose(g.asnumpy(), mirr.grad_dict[n].asnumpy(),
                           atol=1e-5), n
    rp = plain.backward_residual_bytes()
    rm = mirr.backward_residual_bytes()
    if rp is None:
        pytest.skip("saved_residuals introspection unavailable")
    # the mirrored activations left the residual set
    assert rm < rp, (rm, rp)


def test_env_do_mirror(monkeypatch):
    """MXNET_BACKWARD_DO_MIRROR=1 mirrors eligible ops with no attrs at
    all (static_graph.cc:404); FullyConnected stays on the skip list."""
    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    mirr = _bind_run(_mlp())
    monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR")
    plain = _bind_run(_mlp())
    assert np.allclose(plain.outputs[0].asnumpy(),
                       mirr.outputs[0].asnumpy(), atol=1e-5)
    rp = plain.backward_residual_bytes()
    rm = mirr.backward_residual_bytes()
    if rp is None:
        pytest.skip("saved_residuals introspection unavailable")
    assert rm < rp, (rm, rp)


def test_mirror_with_dropout_rng_replay():
    """Dropout inside a mirrored region: the reference excludes Dropout
    from mirroring (its mask would differ on recompute); here the jax
    PRNG key is a segment input so even mirrored neighbours replay the
    SAME randomness — backward must match an unmirrored run
    numerically."""
    def net(attr):
        x = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(x, num_hidden=32, name="fc0")
        h = mx.sym.Activation(h, act_type="relu", name="a0", attr=attr)
        h = mx.sym.Dropout(h, p=0.5, name="drop")
        h = mx.sym.FullyConnected(h, num_hidden=32, name="fc1")
        h = mx.sym.Activation(h, act_type="relu", name="a1", attr=attr)
        return mx.sym.SoftmaxOutput(
            h, mx.sym.Variable("softmax_label"), name="softmax")

    # same PRNG stream for both runs
    mx.random.seed(1234)
    plain = _bind_run(net({}), dim=32)
    mx.random.seed(1234)
    mirr = _bind_run(net({"force_mirroring": "true"}), dim=32)
    assert np.allclose(plain.outputs[0].asnumpy(),
                       mirr.outputs[0].asnumpy(), atol=1e-5)
    for n, g in plain.grad_dict.items():
        assert np.allclose(g.asnumpy(), mirr.grad_dict[n].asnumpy(),
                           atol=1e-5), n


def test_mirror_batchnorm_aux_updates_cross_segment():
    """BatchNorm moving stats computed INSIDE a mirrored segment must
    still land in the executor aux arrays (segment aux updates are
    checkpoint outputs)."""
    def net(attr):
        x = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(x, num_hidden=16, name="fc0")
        h = mx.sym.BatchNorm(h, name="bn0", attr=attr)
        h = mx.sym.Activation(h, act_type="relu", name="a0", attr=attr)
        return mx.sym.SoftmaxOutput(
            h, mx.sym.Variable("softmax_label"), name="softmax")

    plain = _bind_run(net({}), dim=16)
    mirr = _bind_run(net({"force_mirroring": "true"}), dim=16)
    for n, a in plain.aux_dict.items():
        assert np.allclose(a.asnumpy(), mirr.aux_dict[n].asnumpy(),
                           atol=1e-5), n
    # the moving stats actually moved (update happened inside the
    # checkpointed segment)
    mm = mirr.aux_dict["bn0_moving_mean"].asnumpy()
    assert not np.allclose(mm, np.zeros_like(mm))


def test_mirror_monitor_unaffected():
    """A monitor observes every op output: monitored traces run
    unmirrored (a checkpointed callback would double-fire on recompute)
    and values match the mirrored program's."""
    sym = _mlp(attr={"force_mirroring": "true"}, n_layers=2)
    ex = _bind_run(sym)
    seen = {}
    ex.set_monitor_callback(lambda name, arr: seen.setdefault(
        name, arr.asnumpy()))
    ex.forward(is_train=True)
    assert any(k.startswith("act") for k in seen)
    assert np.allclose(seen["softmax_output"],
                       ex.outputs[0].asnumpy(), atol=1e-5)


def test_mirror_on_sharded_trainer_path():
    """The pjit ShardedTrainer traces through the same _build_program,
    so attr-tagged mirroring gives stage-granular recompute on the
    sharded path too (finer than the all-or-nothing remat=True knob);
    numerics must match the unmirrored trainer."""
    import jax
    import numpy as np
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu import optimizer as opt_mod

    def run(attr):
        mx.random.seed(11)      # init_params draws from the global stream
        sym = _mlp(attr=attr, n_layers=4, hidden=32)
        mesh = make_mesh(jax.devices()[:2], dp=2)
        opt = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9)
        tr = ShardedTrainer(sym, opt, mesh)
        params, st, aux = tr.init_params(
            {"data": (8, 32)}, label_shapes={"softmax_label": (8,)})
        rs = np.random.RandomState(0)
        host_batch = {
            "data": rs.rand(8, 32).astype(np.float32),
            "softmax_label": rs.randint(0, 32, (8,)).astype(np.float32)}
        batch = tr.shard_batch(host_batch)
        params, st, aux, outs = tr.step(params, st, aux, batch,
                                        rng=jax.random.PRNGKey(7))

        # the recompute signal: residuals jax saves across the trainer's
        # OWN trace (what the fused step differentiates) — shrinks iff
        # the checkpoint segments actually engaged on this path
        from mxnet_tpu.executor import trace_residual_bytes
        host = {k: np.asarray(v) for k, v in params.items()}
        host.update(host_batch)
        resid = trace_residual_bytes(tr._trace, host, dict(aux),
                                     tr.param_names)
        return jax.tree_util.tree_map(np.asarray, params), resid

    p_plain, res_plain = run({})
    p_mirr, res_mirr = run({"force_mirroring": "true"})
    for k in p_plain:
        np.testing.assert_allclose(p_plain[k], p_mirr[k], atol=1e-5,
                                   err_msg=k)
    if res_plain is not None:
        assert res_mirr < res_plain, (res_mirr, res_plain)


def test_resnet_mirror_blocks_numerics_and_residuals():
    """resnet.get_symbol(mirror_blocks=True): whole residual units
    recompute in backward (force_mirroring overrides the conv skip
    list; per-unit mirror_stage splits segments at block boundaries).
    Numerics must match the plain build; the residual set must shrink
    MORE than the env knob's elementwise-only segments would."""
    from mxnet_tpu.models import resnet

    def run(mb):
        sym = resnet.get_symbol(num_classes=10, num_layers=18,
                                image_shape=(3, 32, 32), mirror_blocks=mb)
        ex = sym.simple_bind(mx.cpu(), data=(4, 3, 32, 32),
                             grad_req="write")
        rs = np.random.RandomState(0)
        for n, a in ex.arg_dict.items():
            if n not in ("data", "softmax_label"):
                a[:] = (rs.rand(*a.shape).astype(np.float32) - 0.5) * 0.2
        ex.arg_dict["data"][:] = rs.rand(4, 3, 32, 32).astype(np.float32)
        ex.arg_dict["softmax_label"][:] = rs.randint(
            0, 10, (4,)).astype(np.float32)
        ex.forward(is_train=True)
        ex.backward()
        return ex

    plain = run(False)
    mirr = run(True)
    assert np.allclose(plain.outputs[0].asnumpy(),
                       mirr.outputs[0].asnumpy(), atol=1e-5)
    for n, g in plain.grad_dict.items():
        assert np.allclose(g.asnumpy(), mirr.grad_dict[n].asnumpy(),
                           atol=1e-4), n
    rp = plain.backward_residual_bytes()
    rm = mirr.backward_residual_bytes()
    if rp is None:
        pytest.skip("saved_residuals introspection unavailable")
    # block-granular remat drops well over a third of the residual set
    assert rm < 0.65 * rp, (rm, rp)

    # the attrs really are on the unit ops (and only on unit ops)
    sym = resnet.get_symbol(num_classes=10, num_layers=18,
                            mirror_blocks=True)
    attrs = sym.attr_dict()
    assert attrs.get("stage1_unit1_conv1", {}).get(
        "force_mirroring") == "true"
    assert attrs.get("stage1_unit1_conv1", {}).get(
        "mirror_stage") == "stage1_unit1"
    assert attrs.get("stage2_unit1_bn1", {}).get(
        "mirror_stage") == "stage2_unit1"
    assert "force_mirroring" not in attrs.get("conv0", {})


def test_transformer_mirror_blocks_numerics_and_residuals():
    """transformer.get_symbol(mirror_blocks=True): per-decoder-layer
    recompute; numerics identical, residual set shrinks."""
    from mxnet_tpu.models import transformer

    def run(mb):
        sym = transformer.get_symbol(vocab_size=64, num_layers=2,
                                     num_heads=2, dim=32, seq_len=16,
                                     mirror_blocks=mb)
        ex = sym.simple_bind(mx.cpu(), data=(2, 16),
                             softmax_label=(2, 16), grad_req="write")
        rs = np.random.RandomState(0)
        for n, a in ex.arg_dict.items():
            if n not in ("data", "softmax_label"):
                a[:] = (rs.rand(*a.shape).astype(np.float32) - 0.5) * 0.1
        ex.arg_dict["data"][:] = rs.randint(0, 64, (2, 16)).astype(
            np.float32)
        ex.arg_dict["softmax_label"][:] = rs.randint(
            0, 64, (2, 16)).astype(np.float32)
        ex.forward(is_train=True)
        ex.backward()
        return ex

    plain = run(False)
    mirr = run(True)
    assert np.allclose(plain.outputs[0].asnumpy(),
                       mirr.outputs[0].asnumpy(), atol=1e-5)
    for n, g in plain.grad_dict.items():
        assert np.allclose(g.asnumpy(), mirr.grad_dict[n].asnumpy(),
                           atol=1e-4), n
    rp = plain.backward_residual_bytes()
    rm = mirr.backward_residual_bytes()
    if rp is None:
        pytest.skip("saved_residuals introspection unavailable")
    assert rm < rp, (rm, rp)
