"""RNN / ROIPooling / SpatialTransformer / Correlation checks vs numpy
(modeled on tests/python/unittest/test_operator.py)."""
import math

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.ops.rnn import rnn_param_size
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient

rng = np.random.RandomState(99)


def _bind_forward(s, arrays, **kwargs):
    ex = s.simple_bind(mx.cpu(), **{k: v.shape for k, v in arrays.items()},
                       **kwargs)
    for k, v in arrays.items():
        ex.arg_dict[k][:] = v
    return ex, [o.asnumpy() for o in ex.forward()]


# ------------------------------------------------------------------ RNN
def _np_lstm(x, wx, wh, bx, bh, h0, c0):
    seq, batch, _ = x.shape
    H = h0.shape[-1]
    h, c = h0.copy(), c0.copy()
    ys = []
    sig = lambda v: 1 / (1 + np.exp(-v))
    for t in range(seq):
        g = x[t] @ wx.T + bx + h @ wh.T + bh
        i, f, gg, o = np.split(g, 4, axis=-1)
        i, f, o = sig(i), sig(f), sig(o)
        c = f * c + i * np.tanh(gg)
        h = o * np.tanh(c)
        ys.append(h)
    return np.stack(ys), h, c


def test_rnn_lstm_forward_matches_numpy():
    seq, batch, inp, H = 5, 3, 4, 6
    psize = rnn_param_size(1, inp, H, False, "lstm")
    assert psize == H * (H + inp + 2) * 4
    x = rng.uniform(-1, 1, (seq, batch, inp)).astype(np.float32)
    flat = rng.uniform(-0.5, 0.5, (psize,)).astype(np.float32)
    h0 = rng.uniform(-1, 1, (1, batch, H)).astype(np.float32)
    c0 = rng.uniform(-1, 1, (1, batch, H)).astype(np.float32)

    data = sym.Variable("data")
    s = sym.RNN(data=data, state_size=H, num_layers=1, mode="lstm",
                state_outputs=True, name="rnn")
    ex, outs = _bind_forward(s, {"data": x, "rnn_parameters": flat,
                                 "rnn_state": h0, "rnn_state_cell": c0})

    o = 0
    wx = flat[o:o + 4 * H * inp].reshape(4 * H, inp); o += 4 * H * inp
    wh = flat[o:o + 4 * H * H].reshape(4 * H, H); o += 4 * H * H
    bx = flat[o:o + 4 * H]; o += 4 * H
    bh = flat[o:o + 4 * H]
    want_y, want_h, want_c = _np_lstm(x, wx, wh, bx, bh, h0[0], c0[0])
    assert_almost_equal(outs[0], want_y, rtol=1e-4, atol=1e-5)
    assert_almost_equal(outs[1], want_h[None], rtol=1e-4, atol=1e-5)
    assert_almost_equal(outs[2], want_c[None], rtol=1e-4, atol=1e-5)


def test_rnn_shapes_and_grad():
    seq, batch, inp, H, L = 3, 2, 3, 4, 2
    for mode, nstate in [("gru", 1), ("rnn_tanh", 1), ("lstm", 2)]:
        psize = rnn_param_size(L, inp, H, True, mode)
        data = sym.Variable("data")
        s = sym.RNN(data=data, state_size=H, num_layers=L, mode=mode,
                    bidirectional=True, name="r")
        arg_shapes, out_shapes, _ = s.infer_shape(data=(seq, batch, inp))
        assert arg_shapes[1] == (psize,)
        assert out_shapes[0] == (seq, batch, 2 * H)

    # gradient flows through the scan
    data = sym.Variable("data")
    s = sym.sum(sym.RNN(data=data, state_size=3, num_layers=1,
                        mode="lstm", name="g"))
    x = rng.uniform(-1, 1, (3, 2, 3)).astype(np.float64)
    psize = rnn_param_size(1, 3, 3, False, "lstm")
    check_numeric_gradient(
        s, {"data": x,
            "g_parameters": rng.uniform(-0.4, 0.4, (psize,)),
            "g_state": np.zeros((1, 2, 3)),
            "g_state_cell": np.zeros((1, 2, 3))},
        grad_nodes=["data", "g_parameters"], rtol=1e-2, atol=1e-3)


# ----------------------------------------------------------- ROIPooling
def _np_roipool(data, rois, pooled, scale):
    N, C, H, W = data.shape
    ph, pw = pooled
    out = np.zeros((rois.shape[0], C, ph, pw), data.dtype)
    for r, roi in enumerate(rois):
        b = int(roi[0])
        x1, y1, x2, y2 = [int(round(v * scale)) for v in roi[1:]]
        rh, rw = max(y2 - y1 + 1, 1), max(x2 - x1 + 1, 1)
        for i in range(ph):
            for j in range(pw):
                # exact rational floor/ceil (the op uses integer arithmetic)
                hs = min(max(i * rh // ph + y1, 0), H)
                he = min(max(-((-(i + 1) * rh) // ph) + y1, 0), H)
                ws = min(max(j * rw // pw + x1, 0), W)
                we = min(max(-((-(j + 1) * rw) // pw) + x1, 0), W)
                if he <= hs or we <= ws:
                    continue
                out[r, :, i, j] = data[b, :, hs:he, ws:we].max(axis=(1, 2))
    return out


def test_roipooling_forward():
    data = rng.uniform(-1, 1, (2, 3, 12, 16)).astype(np.float32)
    rois = np.array([[0, 0, 0, 7, 7],
                     [1, 2, 2, 15, 11],
                     [0, 4, 1, 10, 10]], np.float32)
    d = sym.Variable("data")
    r = sym.Variable("rois")
    s = sym.ROIPooling(data=d, rois=r, pooled_size=(3, 3), spatial_scale=1.0)
    _, outs = _bind_forward(s, {"data": data, "rois": rois})
    want = _np_roipool(data, rois, (3, 3), 1.0)
    assert_almost_equal(outs[0], want, rtol=1e-5, atol=1e-6)


def test_roipooling_scale_and_shape():
    data = rng.uniform(-1, 1, (1, 2, 8, 8)).astype(np.float32)
    rois = np.array([[0, 0, 0, 15, 15]], np.float32)
    d, r = sym.Variable("data"), sym.Variable("rois")
    s = sym.ROIPooling(data=d, rois=r, pooled_size=(2, 2), spatial_scale=0.5)
    _, outs = _bind_forward(s, {"data": data, "rois": rois})
    assert outs[0].shape == (1, 2, 2, 2)
    want = _np_roipool(data, rois, (2, 2), 0.5)
    assert_almost_equal(outs[0], want, rtol=1e-5, atol=1e-6)


# --------------------------------------------------- SpatialTransformer
def test_spatial_transformer_identity():
    data = rng.uniform(-1, 1, (2, 3, 6, 8)).astype(np.float32)
    loc = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
    d, l = sym.Variable("data"), sym.Variable("loc")
    s = sym.SpatialTransformer(data=d, loc=l, target_shape=(6, 8),
                               transform_type="affine",
                               sampler_type="bilinear")
    _, outs = _bind_forward(s, {"data": data, "loc": loc})
    assert_almost_equal(outs[0], data, rtol=1e-4, atol=1e-5)


def test_spatial_transformer_shift_and_grad():
    # shift right by one pixel in normalized coords: x_src = x_t - 2/(W-1)
    W = 5
    data = rng.uniform(-1, 1, (1, 1, 5, W)).astype(np.float32)
    shift = 2.0 / (W - 1)
    loc = np.array([[1, 0, -shift, 0, 1, 0]], np.float32)
    d, l = sym.Variable("data"), sym.Variable("loc")
    s = sym.SpatialTransformer(data=d, loc=l, target_shape=(5, 5),
                               transform_type="affine",
                               sampler_type="bilinear")
    _, outs = _bind_forward(s, {"data": data, "loc": loc})
    # column j of output = column j-1 of input; column 0 samples x=-1-eps -> 0
    assert_almost_equal(outs[0][0, 0, :, 1:], data[0, 0, :, :-1],
                        rtol=1e-4, atol=1e-5)

    sg = sym.sum(sym.SpatialTransformer(
        data=sym.Variable("data"), loc=sym.Variable("loc"),
        target_shape=(4, 4), transform_type="affine",
        sampler_type="bilinear"))
    check_numeric_gradient(
        sg, {"data": rng.uniform(-1, 1, (1, 2, 4, 4)),
             "loc": np.array([[0.9, 0.05, 0.1, -0.05, 1.1, -0.1]])},
        grad_nodes=["data", "loc"], rtol=1e-2, atol=1e-3)


# --------------------------------------------------------- Correlation
def _np_correlation(d1, d2, k, max_d, s1, s2, pad, is_mult):
    N, C, H, W = d1.shape
    t1 = np.pad(d1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    t2 = np.pad(d2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    kr = (k - 1) // 2
    border = max_d + kr
    th = int(math.ceil((H + 2 * pad - 2 * border) / s1))
    tw = int(math.ceil((W + 2 * pad - 2 * border) / s1))
    ngr = max_d // s2
    ngw = 2 * ngr + 1
    out = np.zeros((N, ngw * ngw, th, tw), d1.dtype)
    sumelems = k * k * C
    for i in range(th):
        for j in range(tw):
            x1, y1 = j * s1 + max_d, i * s1 + max_d
            for tc in range(ngw * ngw):
                s2o = (tc % ngw - ngr) * s2
                s2p = (tc // ngw - ngr) * s2
                a = t1[:, :, y1:y1 + k, x1:x1 + k]
                b = t2[:, :, y1 + s2p:y1 + s2p + k, x1 + s2o:x1 + s2o + k]
                v = a * b if is_mult else np.abs(a - b)
                out[:, tc, i, j] = v.sum(axis=(1, 2, 3)) / sumelems
    return out


def test_correlation_forward():
    d1 = rng.uniform(-1, 1, (2, 3, 10, 10)).astype(np.float32)
    d2 = rng.uniform(-1, 1, (2, 3, 10, 10)).astype(np.float32)
    for is_mult in (True, False):
        a, b = sym.Variable("a"), sym.Variable("b")
        s = sym.Correlation(data1=a, data2=b, kernel_size=3,
                            max_displacement=2, stride1=1, stride2=1,
                            pad_size=2, is_multiply=is_mult)
        _, outs = _bind_forward(s, {"a": d1, "b": d2})
        want = _np_correlation(d1, d2, 3, 2, 1, 1, 2, is_mult)
        assert outs[0].shape == want.shape
        assert_almost_equal(outs[0], want, rtol=1e-4, atol=1e-5)


def test_correlation_strided():
    d1 = rng.uniform(-1, 1, (1, 2, 12, 12)).astype(np.float32)
    d2 = rng.uniform(-1, 1, (1, 2, 12, 12)).astype(np.float32)
    a, b = sym.Variable("a"), sym.Variable("b")
    s = sym.Correlation(data1=a, data2=b, kernel_size=1,
                        max_displacement=2, stride1=2, stride2=2,
                        pad_size=0, is_multiply=True)
    _, outs = _bind_forward(s, {"a": d1, "b": d2})
    want = _np_correlation(d1, d2, 1, 2, 2, 2, 0, True)
    assert outs[0].shape == want.shape
    assert_almost_equal(outs[0], want, rtol=1e-4, atol=1e-5)


def test_vanilla_rnn_unroll_trains():
    """models/rnn.py (reference rnn.py parity): the unrolled tanh-RNN LM
    binds, steps, and reduces loss on a learnable pattern."""
    import mxnet_tpu as mx
    from mxnet_tpu.models.rnn import rnn_unroll, init_state_shapes

    V, H, E, L, S, B = 20, 16, 8, 1, 6, 8
    net = rnn_unroll(L, S, V, num_hidden=H, num_embed=E, num_label=V)
    shapes = {"data": (B, S), "softmax_label": (B, S)}
    shapes.update(dict(init_state_shapes(L, B, H)))
    exe = net.simple_bind(mx.context.cpu(), grad_req="write", **shapes)
    rng = np.random.RandomState(0)
    for name, arr in exe.arg_dict.items():
        if name not in shapes:
            arr[:] = rng.uniform(-0.1, 0.1, arr.shape).astype("f")
    data = rng.randint(1, V, (B, S)).astype("f")
    label = data.copy()     # identity mapping: trivially learnable
    exe.arg_dict["data"][:] = data
    exe.arg_dict["softmax_label"][:] = label

    def loss():
        probs = exe.forward(is_train=True)[0].asnumpy()
        flat = label.T.reshape(-1).astype(int)
        return -np.log(np.maximum(
            probs[np.arange(flat.size), flat], 1e-9)).mean()

    first = loss()
    for _ in range(60):
        exe.forward(is_train=True)
        exe.backward()
        for name, g in exe.grad_dict.items():
            if g is not None and name not in shapes:
                exe.arg_dict[name][:] = (exe.arg_dict[name].asnumpy()
                                         - 0.05 * g.asnumpy())
    assert loss() < first * 0.7, (first, loss())
