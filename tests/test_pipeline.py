"""GPipe microbatched pipeline over the 'pp' mesh axis.

Beyond-reference: the reference's model parallelism is placement only
(ctx_group -> AssignContext, graph_executor.cc:391) with no schedule;
this is the TPU-native microbatch pipeline (shard_map + ppermute, one
XLA dispatch for fwd+bwd+update).  Verified against the sequential
(unpipelined) evaluation of the same functions.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.parallel.pipeline import GPipeTrainer
from mxnet_tpu import optimizer as opt_mod

D, V = 12, 8


def _embed(ep, batch):
    return jnp.take(ep["table"], batch["tokens"].astype(jnp.int32),
                    axis=0)


def _block(lp, h):
    return h + jnp.tanh(h @ lp["w"] + lp["b"])


def _head_loss(hp, h, batch):
    logp = jax.nn.log_softmax(h @ hp["w"])
    labels = batch["labels"].astype(jnp.int32)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def _params(rs, n_layers):
    return {
        "embed": {"table": rs.randn(V, D).astype(np.float32) * 0.1},
        "layers": {"w": rs.randn(n_layers, D, D).astype(np.float32) * 0.1,
                   "b": np.zeros((n_layers, D), np.float32)},
        "head": {"w": rs.randn(D, V).astype(np.float32) * 0.1},
    }


def _batch(rs, n):
    return {"tokens": rs.randint(0, V, (n,)).astype(np.int32),
            "labels": rs.randint(0, V, (n,)).astype(np.int32)}


@pytest.mark.parametrize("cfg,layers,micro", [
    ({"pp": 4}, 4, 4),
    ({"pp": 2, "dp": 2}, 4, 2),
    ({"pp": 2}, 6, 5),      # layers > pp, microbatches != pp
])
def test_pipeline_matches_sequential(cfg, layers, micro):
    rs = np.random.RandomState(0)
    ndev = cfg["pp"] * cfg.get("dp", 1)
    mesh = make_mesh(jax.devices()[:ndev], **cfg)
    opt = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9)
    tr = GPipeTrainer(_embed, _block, _head_loss, _params(rs, layers),
                      mesh, opt, num_microbatches=micro)
    batch = _batch(rs, micro * cfg.get("dp", 1) * 4)
    ref = tr.sequential_loss(batch)
    got = tr.step(batch)
    assert abs(got - ref) < 1e-5, (got, ref)
    # gradients flowed through the ppermute chain: training descends
    # and the post-update pipelined loss still equals sequential
    for _ in range(8):
        last = tr.step(batch)
    assert last < got
    ref_now = tr.sequential_loss(batch)   # BEFORE the step advances params
    assert abs(tr.step(batch) - ref_now) < 1e-4


def test_pipeline_single_dispatch_and_collectives():
    """The whole schedule (M+K-1 ticks) compiles into ONE executable
    whose HLO carries the collective-permute chain."""
    rs = np.random.RandomState(1)
    mesh = make_mesh(jax.devices()[:4], pp=4)
    opt = opt_mod.create("sgd", learning_rate=0.1)
    tr = GPipeTrainer(_embed, _block, _head_loss, _params(rs, 4),
                      mesh, opt, num_microbatches=4)
    batch = _batch(rs, 8)
    tr.step(batch)
    hlo = tr._jit_step.lower(
        tr.params, tr.opt_state,
        jax.tree_util.tree_map(jnp.asarray, batch),
        jnp.float32(0.1), jnp.float32(0.0),
        jnp.int32(1)).compile().as_text()
    assert "collective-permute" in hlo


def test_pipeline_validations():
    rs = np.random.RandomState(2)
    mesh = make_mesh(jax.devices()[:2], dp=2)
    opt = opt_mod.create("sgd", learning_rate=0.1)
    with pytest.raises(ValueError, match="pp"):
        GPipeTrainer(_embed, _block, _head_loss, _params(rs, 4), mesh,
                     opt)
    mesh = make_mesh(jax.devices()[:4], pp=4)
    with pytest.raises(ValueError, match="divide"):
        GPipeTrainer(_embed, _block, _head_loss, _params(rs, 3), mesh,
                     opt)


def test_pipeline_batch_divisibility_validated():
    rs = np.random.RandomState(3)
    mesh = make_mesh(jax.devices()[:4], pp=4)
    opt = opt_mod.create("sgd", learning_rate=0.1)
    tr = GPipeTrainer(_embed, _block, _head_loss, _params(rs, 4), mesh,
                      opt, num_microbatches=3)
    with pytest.raises(ValueError, match="num_microbatches"):
        tr.step(_batch(rs, 8))   # 8 rows don't divide into 3 microbatches
