"""GPipe microbatched pipeline over the 'pp' mesh axis.

Beyond-reference: the reference's model parallelism is placement only
(ctx_group -> AssignContext, graph_executor.cc:391) with no schedule;
this is the TPU-native microbatch pipeline (shard_map + ppermute, one
XLA dispatch for fwd+bwd+update).  Verified against the sequential
(unpipelined) evaluation of the same functions.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.parallel.pipeline import GPipeTrainer
from mxnet_tpu import optimizer as opt_mod

D, V = 12, 8


def _embed(ep, batch):
    return jnp.take(ep["table"], batch["tokens"].astype(jnp.int32),
                    axis=0)


def _block(lp, h):
    return h + jnp.tanh(h @ lp["w"] + lp["b"])


def _head_loss(hp, h, batch):
    logp = jax.nn.log_softmax(h @ hp["w"])
    labels = batch["labels"].astype(jnp.int32)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def _params(rs, n_layers):
    return {
        "embed": {"table": rs.randn(V, D).astype(np.float32) * 0.1},
        "layers": {"w": rs.randn(n_layers, D, D).astype(np.float32) * 0.1,
                   "b": np.zeros((n_layers, D), np.float32)},
        "head": {"w": rs.randn(D, V).astype(np.float32) * 0.1},
    }


def _batch(rs, n):
    return {"tokens": rs.randint(0, V, (n,)).astype(np.int32),
            "labels": rs.randint(0, V, (n,)).astype(np.int32)}


@pytest.mark.parametrize("cfg,layers,micro", [
    ({"pp": 4}, 4, 4),
    ({"pp": 2, "dp": 2}, 4, 2),
    ({"pp": 2}, 6, 5),      # layers > pp, microbatches != pp
])
def test_pipeline_matches_sequential(cfg, layers, micro):
    rs = np.random.RandomState(0)
    ndev = cfg["pp"] * cfg.get("dp", 1)
    mesh = make_mesh(jax.devices()[:ndev], **cfg)
    opt = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9)
    tr = GPipeTrainer(_embed, _block, _head_loss, _params(rs, layers),
                      mesh, opt, num_microbatches=micro)
    batch = _batch(rs, micro * cfg.get("dp", 1) * 4)
    ref = tr.sequential_loss(batch)
    got = tr.step(batch)
    assert abs(got - ref) < 1e-5, (got, ref)
    # gradients flowed through the ppermute chain: training descends
    # and the post-update pipelined loss still equals sequential
    for _ in range(8):
        last = tr.step(batch)
    assert last < got
    ref_now = tr.sequential_loss(batch)   # BEFORE the step advances params
    assert abs(tr.step(batch) - ref_now) < 1e-4


def test_pipeline_single_dispatch_and_collectives():
    """The whole schedule (M+K-1 ticks) compiles into ONE executable
    whose HLO carries the collective-permute chain."""
    rs = np.random.RandomState(1)
    mesh = make_mesh(jax.devices()[:4], pp=4)
    opt = opt_mod.create("sgd", learning_rate=0.1)
    tr = GPipeTrainer(_embed, _block, _head_loss, _params(rs, 4),
                      mesh, opt, num_microbatches=4)
    batch = _batch(rs, 8)
    tr.step(batch)
    hlo = tr._jit_step.lower(
        tr.params, tr.opt_state,
        jax.tree_util.tree_map(jnp.asarray, batch),
        jnp.float32(0.1), jnp.float32(0.0),
        jnp.int32(1)).compile().as_text()
    assert "collective-permute" in hlo


def test_pipeline_validations():
    rs = np.random.RandomState(2)
    mesh = make_mesh(jax.devices()[:2], dp=2)
    opt = opt_mod.create("sgd", learning_rate=0.1)
    with pytest.raises(ValueError, match="pp"):
        GPipeTrainer(_embed, _block, _head_loss, _params(rs, 4), mesh,
                     opt)
    mesh = make_mesh(jax.devices()[:4], pp=4)
    with pytest.raises(ValueError, match="divide"):
        GPipeTrainer(_embed, _block, _head_loss, _params(rs, 3), mesh,
                     opt)


def test_pipeline_batch_divisibility_validated():
    rs = np.random.RandomState(3)
    mesh = make_mesh(jax.devices()[:4], pp=4)
    opt = opt_mod.create("sgd", learning_rate=0.1)
    tr = GPipeTrainer(_embed, _block, _head_loss, _params(rs, 4), mesh,
                      opt, num_microbatches=3)
    with pytest.raises(ValueError, match="num_microbatches"):
        tr.step(_batch(rs, 8))   # 8 rows don't divide into 3 microbatches


def test_pipeline_from_block_symbol():
    """Symbol-language entry: a residual cell written in mx.sym runs
    pipelined and matches its own sequential evaluation."""
    import mxnet_tpu as mx

    x = mx.sym.Variable("data")
    cell = x + mx.sym.Activation(
        mx.sym.FullyConnected(x, num_hidden=D, name="fc"),
        act_type="tanh", name="act")

    rs = np.random.RandomState(5)
    mesh = make_mesh(jax.devices()[:4], pp=4)
    opt = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9)
    tr = GPipeTrainer.from_block_symbol(
        cell, n_layers=4, mesh=mesh, optimizer=opt,
        embed_fn=_embed, head_loss_fn=_head_loss,
        embed_params={"table": rs.randn(V, D).astype(np.float32) * 0.1},
        head_params={"w": rs.randn(D, V).astype(np.float32) * 0.1},
        input_shape=(D,), num_microbatches=4)
    batch = _batch(rs, 16)
    ref = tr.sequential_loss(batch)
    got = tr.step(batch)
    assert abs(got - ref) < 1e-5, (got, ref)
    first = got
    for _ in range(8):
        last = tr.step(batch)
    assert last < first


def test_pipeline_block_symbol_rejects_aux_and_rng():
    import mxnet_tpu as mx
    mesh = make_mesh(jax.devices()[:2], pp=2)
    opt = opt_mod.create("sgd", learning_rate=0.1)
    kw = dict(n_layers=2, mesh=mesh, optimizer=opt, embed_fn=_embed,
              head_loss_fn=_head_loss, embed_params={}, head_params={},
              input_shape=(D,))

    x = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(x, name="bn")
    with pytest.raises(ValueError, match="aux-free"):
        GPipeTrainer.from_block_symbol(bn, **kw)
    drop = mx.sym.Dropout(x, p=0.5, name="dr")
    with pytest.raises(ValueError, match="rng-free"):
        GPipeTrainer.from_block_symbol(drop, **kw)
    shrink = mx.sym.FullyConnected(x, num_hidden=D // 2, name="fc2")
    with pytest.raises(ValueError, match="same"):
        GPipeTrainer.from_block_symbol(shrink, **kw)


def test_pipeline_block_symbol_guards():
    """Underdetermined shapes and parameter-free blocks fail with named
    errors, and construction leaves the global mx.random stream intact."""
    import mxnet_tpu as mx
    from mxnet_tpu import random as mxrand

    mesh = make_mesh(jax.devices()[:2], pp=2)
    opt = opt_mod.create("sgd", learning_rate=0.1)
    kw = dict(n_layers=2, mesh=mesh, optimizer=opt, embed_fn=_embed,
              head_loss_fn=_head_loss, embed_params={}, head_params={},
              input_shape=(D,))

    x = mx.sym.Variable("data")
    nop = x + mx.sym.Activation(x, act_type="tanh", name="a")
    with pytest.raises(ValueError, match="no parameters"):
        GPipeTrainer.from_block_symbol(nop, **kw)

    under = mx.sym.dot(x, mx.sym.Variable("w"))
    with pytest.raises(ValueError, match="underdetermined"):
        GPipeTrainer.from_block_symbol(under, **kw)

    # constructor must not clobber the caller's seeded stream
    mx.random.seed(123)
    want = np.asarray(mx.random.uniform(shape=(4,)).asnumpy())
    mx.random.seed(123)
    cell = x + mx.sym.Activation(
        mx.sym.FullyConnected(x, num_hidden=D, name="fc"),
        act_type="tanh", name="act")
    GPipeTrainer.from_block_symbol(cell, **kw)
    got = np.asarray(mx.random.uniform(shape=(4,)).asnumpy())
    np.testing.assert_array_equal(want, got)


def test_pipeline_checkpoint_resume(tmp_path):
    """Save mid-training, restore into a FRESH trainer, and the next
    step matches a never-stopped twin (momentum state + update counter
    both resume, pp-sharded end-to-end)."""
    def make(seed=7):
        rs = np.random.RandomState(seed)
        mesh = make_mesh(jax.devices()[:4], pp=4)
        opt = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9)
        return GPipeTrainer(_embed, _block, _head_loss, _params(rs, 4),
                            mesh, opt, num_microbatches=4)

    rs = np.random.RandomState(9)
    batch = _batch(rs, 16)
    tr = make()
    for _ in range(3):
        tr.step(batch)
    tr.save_checkpoint(tmp_path / "ck")
    ref_next = tr.step(batch)          # the never-stopped twin's 4th step

    fresh = make(seed=99)              # different init: restore must win
    fresh.load_checkpoint(tmp_path / "ck")
    assert fresh.num_update == 3
    got_next = fresh.step(batch)
    assert abs(got_next - ref_next) < 1e-6, (got_next, ref_next)


# ----------------------------------------------------------------------
# 1F1B: loss parity + the predicted-vs-measured bubble drill
# ----------------------------------------------------------------------
@pytest.mark.parametrize("micro", [4, 8])
def test_1f1b_matches_microbatched_sequential(micro):
    """1F1B on a 4-stage CPU mesh: the loss is BIT-identical to the
    unpipelined microbatched reference (same float summation order),
    and training descends."""
    rs = np.random.RandomState(1)
    mesh = make_mesh(jax.devices()[:4], pp=4)
    opt = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9)
    tr = GPipeTrainer(_embed, _block, _head_loss, _params(rs, 4),
                      mesh, opt, num_microbatches=micro,
                      schedule="1f1b")
    batch = _batch(rs, micro * 4)
    ref = tr.sequential_loss_microbatched(batch)
    got = tr.step(batch)
    assert got == ref, (got, ref)
    for _ in range(8):
        last = tr.step(batch)
    assert last < got
    ref_now = tr.sequential_loss_microbatched(batch)
    assert tr.step(batch) == ref_now


@pytest.mark.parametrize("micro", [4, 8])
def test_1f1b_predicted_bubble_tracks_measured(monkeypatch, micro):
    """The acceptance drill: the analyzer's slot-synchronous 1F1B
    simulation (MXL-E, over a 4-stage ctx_group graph) predicts the
    bubble the runtime's compiled tables measure, within 15% relative.
    Predicted comes from roofline-priced stage times (fwd = t/3,
    bwd = 2t/3 in training), measured from schedule_occupancy's
    fwd=1/bwd=2 slot weights over the SAME build_1f1b_tables — so the
    drill pins the whole pricing chain, not just the table shape."""
    import mxnet_tpu as mx
    from mxnet_tpu.analysis import analyze
    from mxnet_tpu.analysis.schedule import schedule_report

    monkeypatch.setenv("MXTPU_LINT_MICROBATCHES", str(micro))
    data = mx.sym.Variable("data")
    h = data
    for s in range(4):
        with mx.AttrScope(ctx_group="pp%d" % s):
            h = mx.sym.FullyConnected(data=h, num_hidden=4096,
                                      name="fc%d" % s)
    ctxs = []
    analyze(h, shapes={"data": (256, 4096)}, _ctx_out=ctxs)
    predicted = schedule_report(ctxs[0])["schedules"]["1f1b"][
        "bubble_fraction"]

    rs = np.random.RandomState(2)
    mesh = make_mesh(jax.devices()[:4], pp=4)
    opt = opt_mod.create("sgd", learning_rate=0.1)
    tr = GPipeTrainer(_embed, _block, _head_loss, _params(rs, 4),
                      mesh, opt, num_microbatches=micro,
                      schedule="1f1b")
    tr.step(_batch(rs, micro * 4))    # compiles + emits the tables
    measured = tr.schedule_occupancy()["bubble_fraction"]

    assert measured > 0.0
    assert abs(predicted - measured) / measured < 0.15, \
        (predicted, measured)
