"""Quantized + fused kernel tier (docs/perf.md "Quantization & fused
kernels"): weight-only int8 quantization end-to-end (array -> symbol
rewrite -> Predictor -> GenerationEngine), flash-decode equivalence
over the paged KV cache, bit-identity of the fused optimizer sweep on
the 8-device mesh, MXL-K lint coverage of all three kernel specs, and
the benchdiff gate catching a simulated decode-throughput regression.

Pallas kernels run in interpret mode on the CPU test mesh — the same
trace Mosaic compiles on TPU, so everything but the hardware lowering
is covered.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu import parallel
from mxnet_tpu.base import MXNetError
from mxnet_tpu.executor import program_registry_stats
from mxnet_tpu.kernels import flash_decode as fd
from mxnet_tpu.kernels import fused_opt as fo
from mxnet_tpu.kernels import quantize as qz
from mxnet_tpu.models import transformer as tf
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import GenerationEngine

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V, L, H, E, S = 64, 2, 4, 32, 48        # toy LM dims shared by the module


def _cosine(a, b):
    a = np.asarray(a, np.float64).ravel()
    b = np.asarray(b, np.float64).ravel()
    denom = float(np.linalg.norm(a) * np.linalg.norm(b))
    return float(np.dot(a, b)) / denom if denom else 1.0


@pytest.fixture(scope="module")
def lm_params():
    """Random full-model checkpoint (test_generate.py idiom)."""
    full = tf.get_symbol(vocab_size=V, num_layers=L, num_heads=H, dim=E,
                         seq_len=S)
    rng = np.random.RandomState(0)
    shapes = full.infer_shape(data=(1, S), softmax_label=(1, S))[0]
    params = {}
    for name, shp in zip(full.list_arguments(), shapes):
        if name in ("data", "softmax_label"):
            continue
        params[name] = nd.array(rng.randn(*shp).astype(np.float32) * 0.05)
    return params


# ---------------------------------------------------------------------------
# weight-only quantization: array / symbol / params
# ---------------------------------------------------------------------------

def test_quantize_array_roundtrip():
    rng = np.random.RandomState(3)
    w = rng.randn(16, 64).astype(np.float32)
    w[5] = 0.0                                  # all-zero row edge case
    q, scale = qz.quantize_array(w)
    assert q.dtype == np.int8 and q.shape == w.shape
    assert scale.dtype == np.float32 and scale.shape == (16,)
    assert scale[5] == 1.0 and not q[5].any()
    back = qz.dequantize_array(q, scale)
    # symmetric per-row: error bounded by half an int8 step per row
    err = np.abs(back - w)
    assert (err <= scale[:, None] * 0.5 + 1e-7).all()


def test_quantize_array_rejects_non_2d():
    with pytest.raises(MXNetError):
        qz.quantize_array(np.zeros(8, np.float32))


def test_quantized_matmul_kernel_matches_reference():
    """The Pallas dequant-in-registers matmul (interpret mode) against
    the exact jnp reference — including non-block-aligned dims, which
    pick_block must absorb by shrinking to exact divisors."""
    rng = np.random.RandomState(5)
    for m, k, n in ((8, 256, 256), (6, 96, 80), (1, 64, 64)):
        x = jnp.asarray(rng.randn(m, k).astype(np.float32))
        q, scale = qz.quantize_array(rng.randn(n, k).astype(np.float32))
        want = qz.quantized_matmul_reference(x, jnp.asarray(q),
                                             jnp.asarray(scale))
        got = qz.quantized_matmul(x, jnp.asarray(q), jnp.asarray(scale),
                                  interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


def test_quantize_symbol_rewrites_fc_and_remaps():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")
    names = qz.quantizable_weights(net.tojson())
    assert names == ["fc1_weight", "fc2_weight"]
    qjs, qnames = qz.quantize_symbol(net.tojson())
    assert tuple(names) == qnames
    doc = json.loads(qjs)
    ops = [nd_["op"] for nd_ in doc["nodes"]]
    assert ops.count("QuantizedDense") == 2 and "FullyConnected" not in ops
    rewritten = mx.sym.load_json(qjs)
    args = rewritten.list_arguments()
    assert "fc1_weight_scale" in args and "fc2_weight_scale" in args
    # rule filter: only fc2 when the pattern says so
    assert qz.quantizable_weights(net.tojson(), rules=(r"fc2_.*",)) \
        == ["fc2_weight"]


def test_quantize_params_idempotent():
    rng = np.random.RandomState(1)
    params = {"fc1_weight": rng.randn(8, 16).astype(np.float32),
              "fc1_bias": np.zeros(8, np.float32)}
    once = qz.quantize_params(params, ["fc1_weight"])
    assert once["fc1_weight"].dtype == np.int8
    assert "fc1_weight_scale" in once
    twice = qz.quantize_params(once, ["fc1_weight"])
    assert twice["fc1_weight"] is once["fc1_weight"]


def test_predictor_quantized_cosine(tmp_path):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=8, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")
    rng = np.random.RandomState(2)
    params = {"fc1_weight": rng.randn(32, 20).astype(np.float32),
              "fc1_bias": rng.randn(32).astype(np.float32),
              "fc2_weight": rng.randn(8, 32).astype(np.float32),
              "fc2_bias": rng.randn(8).astype(np.float32)}
    x = rng.randn(4, 20).astype(np.float32)
    ref = Predictor(net.tojson(), dict(params), {"data": (4, 20)})
    out_f32 = np.asarray(ref.forward(data=x)[0])
    qp = Predictor(net.tojson(), dict(params), {"data": (4, 20)},
                   quantize="int8")
    out_q = np.asarray(qp.forward(data=x)[0])
    assert _cosine(out_f32, out_q) >= 0.999


def test_predictor_quantize_env_default(monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_QUANTIZE", "int8")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    rng = np.random.RandomState(4)
    params = {"fc1_weight": rng.randn(8, 12).astype(np.float32),
              "fc1_bias": np.zeros(8, np.float32)}
    pred = Predictor(net.tojson(), dict(params), {"data": (2, 12)})
    assert "QuantizedDense" in pred.symbol.tojson()
    # quantize="" is an explicit opt-out even with the env set
    off = Predictor(net.tojson(), dict(params), {"data": (2, 12)},
                    quantize="")
    assert "QuantizedDense" not in off.symbol.tojson()


# ---------------------------------------------------------------------------
# quantized generation: the serving acceptance gate
# ---------------------------------------------------------------------------

def test_engine_quantized_decode_matches_f32(lm_params):
    """Greedy decode at int8 across mixed prompt lengths: per-step
    logits cosine >= 0.999 vs the f32 engine (tokens are identical on
    this toy LM) and ZERO lowerings in the generation steady state."""
    kw = dict(vocab_size=V, num_layers=L, num_heads=H, dim=E,
              max_seq_len=S, max_new_tokens=6, prompt_buckets=(8, 16),
              decode_buckets=(1, 2, 4), kv_blocks=32, kv_block_size=8)
    prompts = [[3, 5, 7], [2, 4, 6, 8, 10, 1], [9] * 11]

    ref = GenerationEngine(params=dict(lm_params), **kw)
    ref.collect_logits = True
    ref_tokens = ref.generate(prompts)
    ref_logits = ref.last_logits

    eng = GenerationEngine(params=dict(lm_params), quantize="int8", **kw)
    assert eng.serving_dtype == "int8"
    eng.collect_logits = True
    before = program_registry_stats()["lowerings"]
    q_tokens = eng.generate(prompts)
    assert program_registry_stats()["lowerings"] == before
    q_logits = eng.last_logits

    assert q_tokens == ref_tokens
    worst = min(_cosine(a, b)
                for rrows, qrows in zip(ref_logits, q_logits)
                for a, b in zip(rrows, qrows))
    assert worst >= 0.999, worst


def test_engine_quantize_env_and_optout(monkeypatch, lm_params):
    monkeypatch.setenv("MXTPU_QUANTIZE", "int8")
    kw = dict(vocab_size=V, num_layers=L, num_heads=H, dim=E,
              max_seq_len=S, max_new_tokens=2, prompt_buckets=(8,),
              decode_buckets=(1,), kv_blocks=16, kv_block_size=8)
    eng = GenerationEngine(params=dict(lm_params), **kw)
    assert eng.serving_dtype == "int8"
    off = GenerationEngine(params=dict(lm_params), quantize="", **kw)
    assert off.serving_dtype != "int8"


# ---------------------------------------------------------------------------
# flash decode over the paged KV cache
# ---------------------------------------------------------------------------

def _decode_case(seed=11, b=4, h=4, d=32, nb=16, bs=8, mb=4):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, h, d).astype(np.float32))
    k_pool = jnp.asarray(rng.randn(nb, bs, h, d).astype(np.float32))
    v_pool = jnp.asarray(rng.randn(nb, bs, h, d).astype(np.float32))
    table = jnp.asarray(
        rng.choice(nb, size=(b, mb), replace=False).astype(np.int32))
    # positions hit block boundaries, a single token, and a full table
    pos = jnp.asarray(np.array([1, bs, bs + 1, mb * bs], np.int32)[:b])
    return q, k_pool, v_pool, table, pos


def test_flash_decode_matches_reference():
    q, k_pool, v_pool, table, pos = _decode_case()
    want = fd.decode_attention_reference(q, k_pool, v_pool, table, pos)
    got = fd.flash_decode_attention(q, k_pool, v_pool, table, pos,
                                    interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_decode_explicit_scale_and_dtype():
    q, k_pool, v_pool, table, pos = _decode_case(seed=12)
    q = q.astype(jnp.bfloat16)
    k_pool = k_pool.astype(jnp.bfloat16)
    v_pool = v_pool.astype(jnp.bfloat16)
    want = fd.decode_attention_reference(q, k_pool, v_pool, table, pos,
                                         scale=0.25)
    got = fd.flash_decode_attention(q, k_pool, v_pool, table, pos,
                                    scale=0.25, interpret=True)
    assert got.dtype == q.dtype
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2)


def test_flash_decode_env_flag(monkeypatch):
    monkeypatch.delenv("MXTPU_FLASH_DECODE", raising=False)
    assert not fd.flash_decode_enabled()
    monkeypatch.setenv("MXTPU_FLASH_DECODE", "1")
    assert fd.flash_decode_enabled()
    monkeypatch.setenv("MXTPU_FLASH_DECODE", "0")
    assert not fd.flash_decode_enabled()


def test_engine_kernel_path_reports_flag(monkeypatch, lm_params):
    kw = dict(vocab_size=V, num_layers=L, num_heads=H, dim=E,
              max_seq_len=S, max_new_tokens=3, prompt_buckets=(8,),
              decode_buckets=(1, 2), kv_blocks=16, kv_block_size=8)
    eng = GenerationEngine(params=dict(lm_params), **kw)
    monkeypatch.delenv("MXTPU_FLASH_DECODE", raising=False)
    assert eng.kernel_path() == "gather"
    base = eng.generate([[3, 5, 7], [2, 4]])
    monkeypatch.setenv("MXTPU_FLASH_DECODE", "1")
    assert eng.kernel_path() == "flash_decode"
    assert eng.stats()["kernel_path"] == "flash_decode"
    # off-TPU the flag routes through the exact reference: identical
    eng2 = GenerationEngine(params=dict(lm_params), **kw)
    assert eng2.generate([[3, 5, 7], [2, 4]]) == base


# ---------------------------------------------------------------------------
# fused optimizer sweep
# ---------------------------------------------------------------------------

def test_fused_opt_mode_parsing(monkeypatch):
    monkeypatch.delenv("MXTPU_FUSED_OPT", raising=False)
    assert fo.fused_opt_mode() == ""
    monkeypatch.setenv("MXTPU_FUSED_OPT", "1")
    assert fo.fused_opt_mode() == "1"
    monkeypatch.setenv("MXTPU_FUSED_OPT", "kernel")
    assert fo.fused_opt_mode() == "kernel"
    assert fo.fused_opt_mode("") == ""          # explicit beats env
    with pytest.raises(MXNetError):
        fo.fused_opt_mode("bogus")


def test_supports_fused_elementwise_only():
    assert fo.supports_fused(mx.optimizer.create("sgd"))
    assert fo.supports_fused(mx.optimizer.create("adam"))
    assert fo.supports_fused(mx.optimizer.create("nag"))
    assert not fo.supports_fused(mx.optimizer.create("lamb"))
    assert not fo.supports_fused(mx.optimizer.create("sgld"))
    with pytest.raises(MXNetError):
        fo.fused_apply(mx.optimizer.create("lamb"), {}, {}, {}, 0.1,
                       0.0, 1)


def test_plan_buckets_covers_and_splits_by_dtype():
    params = {"a": jnp.zeros((4, 4), jnp.float32),
              "b": jnp.zeros((8,), jnp.float32),
              "c": jnp.zeros((2, 2), jnp.bfloat16)}
    buckets = fo.plan_buckets(params)
    flat = sorted(n for b in buckets for n in b)
    assert flat == ["a", "b", "c"]
    for bucket in buckets:
        dts = {str(params[n].dtype) for n in bucket}
        assert len(dts) == 1


def _leaf_case(opt_name, seed=9):
    opt = mx.optimizer.create(opt_name, learning_rate=0.05)
    rng = np.random.RandomState(seed)
    shapes = {"w0": (5,), "w1": (3, 7), "w2": (2, 4, 8), "w3": (129,)}
    params = {n: jnp.asarray(rng.randn(*s).astype(np.float32))
              for n, s in shapes.items()}
    grads = {n: jnp.asarray(rng.randn(*s).astype(np.float32))
             for n, s in shapes.items()}
    state = {n: opt.create_state_arrays(s, jnp.float32)
             for n, s in shapes.items()}
    return opt, params, grads, state


@pytest.mark.parametrize("opt_name", ["sgd", "adam"])
def test_fused_apply_bit_identical_to_leafwise(opt_name):
    """Fused concat-update-slice == per-leaf tree-map, bitwise, even
    with tiny buckets forcing several sweeps per dtype group."""
    opt, params, grads, state = _leaf_case(opt_name)
    lr, wd, t = 0.05, 0.01, jnp.asarray(3.0, jnp.float32)
    want_w, want_s = {}, {}
    for n in params:
        want_w[n], want_s[n] = opt.update_fn(params[n], grads[n],
                                             state[n], lr, wd, t)
    got_w, got_s = fo.fused_apply(opt, params, grads, state, lr, wd, t,
                                  nbytes=256, mode="1")
    for n in params:
        np.testing.assert_array_equal(np.asarray(got_w[n]),
                                      np.asarray(want_w[n]))
        a = jax.tree_util.tree_leaves(want_s[n])
        b = jax.tree_util.tree_leaves(got_s[n])
        assert len(a) == len(b)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_fused_apply_kernel_mode_matches_xla_mode():
    """The Pallas sweep (interpret) over padded (rows, 128) sheets must
    agree bitwise with the plain fused XLA path — the padding rows drop
    cleanly on unflatten."""
    opt, params, grads, state = _leaf_case("adam", seed=13)
    w1, s1 = fo.fused_apply(opt, params, grads, state, 0.05, 0.0, 2.0,
                            mode="1")
    w2, s2 = fo.fused_apply(opt, params, grads, state, 0.05, 0.0, 2.0,
                            mode="kernel", interpret=True)
    for n in params:
        np.testing.assert_array_equal(np.asarray(w1[n]),
                                      np.asarray(w2[n]))
        for x, y in zip(jax.tree_util.tree_leaves(s1[n]),
                        jax.tree_util.tree_leaves(s2[n])):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def test_trainer_fused_opt_bit_identical_on_mesh(monkeypatch):
    """MXTPU_FUSED_OPT=1 on the dp=8 mesh: params AND optimizer state
    bitwise equal to the per-leaf tree-map path after several steps —
    the acceptance criterion for the fused step."""
    net = _mlp()

    def run(fused):
        if fused:
            monkeypatch.setenv("MXTPU_FUSED_OPT", "1")
        else:
            monkeypatch.delenv("MXTPU_FUSED_OPT", raising=False)
        opt = mx.optimizer.create("sgd", learning_rate=0.1,
                                  momentum=0.9, rescale_grad=1.0 / 16)
        tr = parallel.ShardedTrainer(net, opt, parallel.auto_mesh())
        assert tr._fused_opt == ("1" if fused else "")
        mx.random.seed(7)
        params, opt_state, aux = tr.init_params(
            {"data": (16, 8)}, label_shapes={"softmax_label": (16,)})
        rng = np.random.RandomState(1)
        x = rng.randn(16, 8).astype(np.float32)
        y = (rng.rand(16) * 4).astype(np.float32)
        batch = tr.shard_batch({"data": x, "softmax_label": y})
        for _ in range(4):
            params, opt_state, aux, _outs = tr.step(params, opt_state,
                                                    aux, batch)
        return ({k: np.asarray(v) for k, v in params.items()},
                jax.tree_util.tree_map(np.asarray, opt_state))

    p_ref, s_ref = run(fused=False)
    p_fused, s_fused = run(fused=True)
    for k in p_ref:
        np.testing.assert_array_equal(p_ref[k], p_fused[k])
    a = jax.tree_util.tree_leaves(s_ref)
    b = jax.tree_util.tree_leaves(s_fused)
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_trainer_lamb_refuses_fused(monkeypatch):
    monkeypatch.setenv("MXTPU_FUSED_OPT", "1")
    opt = mx.optimizer.create("lamb", learning_rate=0.01)
    tr = parallel.ShardedTrainer(_mlp(), opt, parallel.auto_mesh())
    assert tr._fused_opt == ""


# ---------------------------------------------------------------------------
# MXL-K coverage of the new kernel specs
# ---------------------------------------------------------------------------

def test_kernel_specs_registered_and_lint_clean():
    from mxnet_tpu.analysis.tiling import (KERNEL_SPECS,
                                           _ensure_builtin_specs,
                                           kernel_spec_issues)
    _ensure_builtin_specs()
    for name in ("kernels.quantize.quantized_matmul",
                 "kernels.flash_decode", "kernels.fused_opt.sweep"):
        assert name in KERNEL_SPECS, name
    assert kernel_spec_issues() == []


def test_mis_tiled_qmm_spec_is_flagged():
    """A deliberately regressed copy of the quantized-matmul spec — the
    out block shrunk to a PARTIAL 64-lane tile — must trip MXL-K002
    while the registered spec stays clean."""
    from mxnet_tpu.analysis import analyze
    from mxnet_tpu.analysis.tiling import (register_kernel_spec,
                                           unregister_kernel_spec)
    bad = qz.qmm_kernel_spec()
    for blk in bad["blocks"]:
        if blk["role"] == "out":
            blk["block"] = (blk["block"][0], 64)    # 64 < lane granule
            blk["array"] = (blk["array"][0], 1024)  # ...and partial
    register_kernel_spec("test.qmm_mis_tiled", bad)
    try:
        issues = analyze(None, select={"MXL-K002"})
        hits = [i for i in issues if i.rule_id == "MXL-K002"]
        assert hits and any("out" in i.message for i in hits), issues
    finally:
        unregister_kernel_spec("test.qmm_mis_tiled")
    assert not analyze(None, select={"MXL-K*"})     # registry clean again


# ---------------------------------------------------------------------------
# benchdiff: the decode-regression fixture
# ---------------------------------------------------------------------------

def test_benchdiff_flags_decode_regression(tmp_path):
    """The sentry contract for the quantized-serving BENCH line: a
    simulated 20% tokens/sec drop against the committed-schema baseline
    exits 1; matching or improved throughput exits 0."""
    baseline = {"n": 6, "cmd": "serve_bench --generate", "rc": 0,
                "parsed": {"metric": "serve_tokens_per_sec",
                           "value": 1000.0, "unit": "tok/s",
                           "ttft_ms": {"p50": 2.0, "p95": 9.0},
                           "itl_ms": {"p50": 1.0, "p95": 3.0}}}
    bpath = str(tmp_path / "BENCH_gen.json")
    with open(bpath, "w") as f:
        json.dump(baseline, f)

    def run(metrics):
        return subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools", "benchdiff.py"),
             "--baseline", bpath, "--metrics", json.dumps(metrics)],
            cwd=_ROOT, capture_output=True, text=True, timeout=180)

    proc = run({"serve_tokens_per_sec": 800.0})     # -20%: flags
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "serve_tokens_per_sec" in proc.stdout
    proc = run({"serve_tokens_per_sec": 1000.0, "serve_ttft_ms_p95": 9.0})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = run({"serve_tokens_per_sec": 1200.0,     # faster but ttft blew up
                "serve_ttft_ms_p95": 12.0})
    assert proc.returncode == 1, proc.stdout + proc.stderr
