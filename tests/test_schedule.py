"""mxnet_tpu/analysis/schedule.py: the MXL-E static schedule lint.

Partition resolution (ctx_group first-appearance vs pp flops-balanced),
the slot-synchronous simulator against closed forms, and every rule
E001..E008 firing on a known-bad graph while staying silent on clean /
toy-sized ones.  The 1F1B tables come from parallel.pipeline — the same
tables the runtime compiles — so these tests also pin that contract.
"""
import pytest

import mxnet_tpu as mx
from mxnet_tpu.analysis import analyze
from mxnet_tpu.analysis.schedule import (gpipe_kind_rows, schedule_report,
                                         simulate_schedule, stage_partition)
from mxnet_tpu.parallel import LogicalMesh


def _ids(issues):
    return {i.rule_id for i in issues}


def _only(issues, rule_id):
    return [i for i in issues if i.rule_id == rule_id]


# ----------------------------------------------------------------------
# graph builders
# ----------------------------------------------------------------------
def _balanced_pipeline(hidden=4096, per_stage=2):
    """Two ctx_group stages, ``per_stage`` equal FCs each."""
    data = mx.sym.Variable("data")
    h = data
    i = 0
    for g in ("pp0", "pp1"):
        with mx.AttrScope(ctx_group=g):
            for _ in range(per_stage):
                h = mx.sym.FullyConnected(data=h, num_hidden=hidden,
                                          name="fc%d" % i)
                i += 1
    return h


def _imbalanced_pipeline():
    """pp0 holds one FC, pp1 holds four: 4x stage imbalance."""
    data = mx.sym.Variable("data")
    with mx.AttrScope(ctx_group="pp0"):
        h = mx.sym.FullyConnected(data=data, num_hidden=4096, name="fc0")
    with mx.AttrScope(ctx_group="pp1"):
        for i in range(1, 5):
            h = mx.sym.FullyConnected(data=h, num_hidden=4096,
                                      name="fc%d" % i)
    return h


def _backedge_pipeline():
    """pp0 -> pp1 -> pp0: the last FC returns to the earlier group."""
    data = mx.sym.Variable("data")
    with mx.AttrScope(ctx_group="pp0"):
        a = mx.sym.FullyConnected(data=data, num_hidden=256, name="fc_a")
    with mx.AttrScope(ctx_group="pp1"):
        b = mx.sym.FullyConnected(data=a, num_hidden=256, name="fc_b")
    with mx.AttrScope(ctx_group="pp0"):
        c = mx.sym.FullyConnected(data=b, num_hidden=256, name="fc_c")
    return c


def _moe_net(num_experts, capacity_factor, hidden_size=128):
    data = mx.sym.Variable("data")
    return mx.sym.MoE(data=data, num_experts=num_experts,
                      hidden_size=hidden_size, top_k=1,
                      capacity_factor=capacity_factor, name="moe")


_BIG = {"data": (256, 4096)}


# ----------------------------------------------------------------------
# stage partition
# ----------------------------------------------------------------------
def test_partition_ctx_group_first_appearance_order():
    ctxs = []
    analyze(_imbalanced_pipeline(), shapes=_BIG, _ctx_out=ctxs)
    part = stage_partition(ctxs[0])
    assert part["mode"] == "ctx_group"
    assert part["k"] == 2
    assert part["groups"] == ["pp0", "pp1"]
    assert part["stage_of"]["fc0"] == 0
    assert all(part["stage_of"]["fc%d" % i] == 1 for i in range(1, 5))


def test_partition_pp_axis_flops_balanced():
    """No ctx_group attrs + a pp mesh axis: contiguous balanced cut."""
    net = mx.models.get_mlp()
    ctxs = []
    analyze(net, shapes={"data": (32, 784)},
            mesh=LogicalMesh(dp=1, pp=2), _ctx_out=ctxs)
    part = stage_partition(ctxs[0])
    assert part["mode"] == "pp"
    assert part["k"] == 2
    assert all(len(s) >= 1 for s in part["stages"])
    # contiguous: stage index never decreases along the topo order
    seen = [part["stage_of"][n] for s in part["stages"] for n in s]
    assert seen == sorted(seen)


def test_partition_none_without_groups_or_pp():
    ctxs = []
    analyze(mx.models.get_mlp(), shapes={"data": (32, 784)},
            _ctx_out=ctxs)
    assert stage_partition(ctxs[0]) is None


# ----------------------------------------------------------------------
# the slot-synchronous simulator: closed forms
# ----------------------------------------------------------------------
@pytest.mark.parametrize("k,m,expect", [
    (2, 2, 0.4), (4, 4, 0.5), (4, 8, 0.4), (2, 6, 0.3077)])
def test_1f1b_bubble_closed_forms(k, m, expect):
    from mxnet_tpu.analysis.schedule import _1f1b_kind_rows
    sim = simulate_schedule(_1f1b_kind_rows(k, m), [1.0] * k, [2.0] * k)
    assert sim["bubble_fraction"] == pytest.approx(expect, abs=1e-4)


@pytest.mark.parametrize("k,m", [(2, 4), (4, 4), (4, 8)])
def test_gpipe_bubble_closed_form(k, m):
    sim = simulate_schedule(gpipe_kind_rows(k, m), [1.0] * k, [2.0] * k)
    assert sim["bubble_fraction"] == \
        pytest.approx((k - 1) / (m + k - 1.0), abs=1e-9)


def test_more_microbatches_shrink_the_bubble():
    from mxnet_tpu.analysis.schedule import _1f1b_kind_rows
    bubbles = [simulate_schedule(_1f1b_kind_rows(4, m), [1.0] * 4,
                                 [2.0] * 4)["bubble_fraction"]
               for m in (4, 8, 16)]
    assert bubbles == sorted(bubbles, reverse=True)


def test_transfer_dominated_slot_costs_the_transfer():
    sim = simulate_schedule(gpipe_kind_rows(2, 2), [1.0] * 2, [2.0] * 2,
                            xfer=10.0)
    assert sim["total_time"] == pytest.approx(10.0 * sim["slots"])


# ----------------------------------------------------------------------
# the report
# ----------------------------------------------------------------------
def test_schedule_report_prices_both_schedules():
    ctxs = []
    analyze(_balanced_pipeline(), shapes=_BIG, _ctx_out=ctxs)
    rep = schedule_report(ctxs[0])
    assert rep["partition"]["k"] == 2
    assert set(rep["schedules"]) == {"gpipe", "1f1b"}
    for sim in rep["schedules"].values():
        assert 0.0 <= sim["bubble_fraction"] < 1.0
    assert len(rep["stage_hbm"]) == 2
    # 1F1B stash: stage s holds at most K - s microbatches, never more
    # than GPipe's full M
    for h in rep["stage_hbm"]:
        assert h["stash_1f1b"] <= h["stash_gpipe"]
        assert h["peak_1f1b"] <= h["peak_gpipe"]
    assert rep["back_edges"] == []
    assert rep["boundaries"] and rep["boundaries"][0]["bytes"] > 0


def test_schedule_report_none_without_pipeline_or_moe():
    ctxs = []
    analyze(mx.models.get_mlp(), shapes={"data": (32, 784)},
            _ctx_out=ctxs)
    assert schedule_report(ctxs[0]) is None


# ----------------------------------------------------------------------
# rules: pipeline
# ----------------------------------------------------------------------
def test_e001_stage_imbalance_fires_and_names_the_stage():
    issues = _only(analyze(_imbalanced_pipeline(), shapes=_BIG),
                   "MXL-E001")
    assert issues, "expected a stage-imbalance finding"
    assert "stage 1" in issues[0].message
    assert "MXTPU_LINT_STAGE_IMBALANCE" in issues[0].message


def test_e001_silent_on_balanced_stages():
    assert not _only(analyze(_balanced_pipeline(), shapes=_BIG),
                     "MXL-E001")


def test_e001_silent_below_flops_floor():
    """The same 4x imbalance on a toy graph stays quiet."""
    net = _imbalanced_pipeline()
    issues = analyze(net, shapes={"data": (8, 16)})
    assert not _ids(issues) & {"MXL-E001", "MXL-E002", "MXL-E005"}


def test_e002_bubble_overrun_names_the_fix(monkeypatch):
    monkeypatch.setenv("MXTPU_LINT_MICROBATCHES", "1")
    issues = _only(analyze(_balanced_pipeline(), shapes=_BIG),
                   "MXL-E002")
    assert issues, "expected a bubble finding at 1 microbatch"
    assert "microbatches would reach the bound" in issues[0].message \
        or "rebalance stages first" in issues[0].message


def test_e002_silent_at_ample_microbatches(monkeypatch):
    monkeypatch.setenv("MXTPU_LINT_MICROBATCHES", "64")
    assert not _only(analyze(_balanced_pipeline(), shapes=_BIG),
                     "MXL-E002")


def test_e003_cross_stage_backedge():
    issues = _only(analyze(_backedge_pipeline(),
                           shapes={"data": (8, 256)}), "MXL-E003")
    assert issues, "expected a back-edge finding"
    assert "fc_c" in issues[0].message
    assert "deadlock" in issues[0].message


def test_e004_activation_stash_overflow():
    issues = _only(analyze(_balanced_pipeline(), shapes=_BIG,
                           hbm_bytes=1 << 20), "MXL-E004")
    assert issues, "expected a stash-HBM finding at a 1MiB budget"
    assert "stashed microbatch activations" in issues[0].message


def test_e005_ici_bound_seam(monkeypatch):
    monkeypatch.setenv("MXTPU_LINT_ICI_GBPS", "0.0001")
    issues = _only(analyze(_balanced_pipeline(), shapes=_BIG),
                   "MXL-E005")
    assert issues, "expected an ICI-bound boundary finding"
    assert "cannot hide under compute" in issues[0].message


def test_kill_switch_disables_the_family(monkeypatch):
    monkeypatch.setenv("MXTPU_LINT_SCHEDULE", "0")
    issues = analyze(_imbalanced_pipeline(), shapes=_BIG)
    assert not {i for i in _ids(issues) if i.startswith("MXL-E")}


# ----------------------------------------------------------------------
# rules: MoE
# ----------------------------------------------------------------------
def test_e006_indivisible_experts():
    issues = _only(analyze(_moe_net(6, 1.25), shapes={"data": (512, 64)},
                           mesh=LogicalMesh(ep=4)), "MXL-E006")
    assert issues, "expected an expert-divisibility finding"
    assert "6 experts" in issues[0].message


def test_e006_silent_when_divisible():
    assert not _only(analyze(_moe_net(8, 1.25),
                             shapes={"data": (512, 64)},
                             mesh=LogicalMesh(ep=4)), "MXL-E006")


def test_e007_capacity_factor_under_one():
    issues = _only(analyze(_moe_net(8, 0.5), shapes={"data": (512, 64)}),
                   "MXL-E007")
    assert issues, "expected a token-drop finding at cf=0.5"
    assert "dropped" in issues[0].message


def test_e007_silent_at_unbounded_capacity():
    """cf=0 means unbounded expert buffers: nothing can drop."""
    assert not _only(analyze(_moe_net(8, 0.0),
                             shapes={"data": (512, 64)}), "MXL-E007")


def test_e008_prices_the_alltoall_and_replays_mxl_d():
    issues = _only(analyze(_moe_net(8, 1.25), shapes={"data": (512, 64)},
                           mesh=LogicalMesh(ep=4), world_size=4),
                   "MXL-E008")
    assert issues, "expected the all-to-all pricing info"
    assert issues[0].severity == "info"
    assert "all-to-all" in issues[0].message
    assert "MXL-D collective trace" in issues[0].message


def test_e008_silent_without_ep_axis():
    assert not _only(analyze(_moe_net(8, 1.25),
                             shapes={"data": (512, 64)}), "MXL-E008")
