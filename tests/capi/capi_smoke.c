/* Real C consumer of the mxnet_tpu ABI (the proof the reference's
 * language-binding story survives the TPU rewrite): ndarray round trip,
 * kvstore push/pull aggregation, symbol load -> bind -> forward.
 *
 * Built and run by `make test-capi`; expects MXTPU_SYMBOL_JSON to point
 * at a saved -symbol.json (the pytest wrapper generates one). */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu/c_api.h"

#define CHECK(rc) do { \
    if ((rc) != 0) { \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, \
              MXGetLastError()); \
      return 1; \
    } } while (0)

int main(void) {
  /* --- ndarray round trip --- */
  uint32_t shape[2] = {2, 3};
  NDArrayHandle a;
  CHECK(MXNDArrayCreate(shape, 2, &a));
  float in[6] = {1, 2, 3, 4, 5, 6}, out[6] = {0};
  CHECK(MXNDArraySyncCopyFromCPU(a, in, 6));
  CHECK(MXNDArraySyncCopyToCPU(a, out, 6));
  for (int i = 0; i < 6; ++i) {
    if (out[i] != in[i]) {
      fprintf(stderr, "FAIL roundtrip at %d: %f\n", i, out[i]);
      return 1;
    }
  }
  uint32_t ndim, got[8];
  CHECK(MXNDArrayGetShape(a, &ndim, got, 8));
  if (ndim != 2 || got[0] != 2 || got[1] != 3) {
    fprintf(stderr, "FAIL shape\n");
    return 1;
  }

  /* --- kvstore aggregation --- */
  KVStoreHandle kv;
  CHECK(MXKVStoreCreate("local", &kv));
  CHECK(MXKVStoreInit(kv, 3, a));
  CHECK(MXKVStorePush(kv, 3, a));
  NDArrayHandle pulled;
  CHECK(MXNDArrayCreate(shape, 2, &pulled));
  CHECK(MXKVStorePull(kv, 3, pulled));
  CHECK(MXNDArraySyncCopyToCPU(pulled, out, 6));
  if (out[5] != 6.0f) {
    fprintf(stderr, "FAIL kvstore pull: %f\n", out[5]);
    return 1;
  }

  /* --- symbol -> executor -> forward --- */
  const char* path = getenv("MXTPU_SYMBOL_JSON");
  if (path != NULL) {
    FILE* f = fopen(path, "rb");
    if (!f) {
      fprintf(stderr, "FAIL: cannot open %s\n", path);
      return 1;
    }
    fseek(f, 0, SEEK_END);
    long n = ftell(f);
    fseek(f, 0, SEEK_SET);
    char* json = (char*)malloc(n + 1);
    if (fread(json, 1, n, f) != (size_t)n) {
      fprintf(stderr, "FAIL: short read of %s\n", path);
      return 1;
    }
    json[n] = 0;
    fclose(f);

    SymbolHandle sym;
    CHECK(MXSymbolCreateFromJSON(json, &sym));
    free(json);
    uint32_t nargs;
    CHECK(MXSymbolGetNumArguments(sym, &nargs));
    char name[64];
    CHECK(MXSymbolGetArgument(sym, 0, name, sizeof(name)));
    printf("symbol: %u args, first=%s\n", nargs, name);

    ExecutorHandle exec;
    CHECK(MXExecutorSimpleBind(
        sym, "{\"data\": [4, 10], \"softmax_label\": [4]}", &exec));
    float data[40];
    for (int i = 0; i < 40; ++i) data[i] = (float)i / 40.0f;
    CHECK(MXExecutorSetArg(exec, "data", data, 40));
    uint32_t nout;
    CHECK(MXExecutorForward(exec, 0, &nout));
    uint32_t oshape[8], ondim;
    CHECK(MXExecutorOutputShape(exec, 0, &ondim, oshape, 8));
    float probs[8];
    CHECK(MXExecutorOutputCopy(exec, 0, probs, oshape[0] * oshape[1]));
    float rowsum = probs[0] + probs[1];
    if (rowsum < 0.99f || rowsum > 1.01f) {
      fprintf(stderr, "FAIL softmax rowsum %f\n", rowsum);
      return 1;
    }
    printf("forward: %u outputs, shape (%u,%u), row0 sum=%f\n",
           nout, oshape[0], oshape[1], rowsum);
    CHECK(MXExecutorFree(exec));

    /* --- predict API (c_predict_api subset) --- */
    const char* params = getenv("MXTPU_PARAMS_FILE");
    if (params != NULL) {
      /* re-read symbol json for the predictor */
      FILE* f2 = fopen(path, "rb");
      if (!f2) {
        fprintf(stderr, "FAIL: cannot reopen %s\n", path);
        return 1;
      }
      fseek(f2, 0, SEEK_END);
      long n2 = ftell(f2);
      fseek(f2, 0, SEEK_SET);
      char* json2 = (char*)malloc(n2 + 1);
      if (fread(json2, 1, n2, f2) != (size_t)n2) return 1;
      json2[n2] = 0;
      fclose(f2);
      PredictorHandle pred;
      CHECK(MXPredCreate(json2, params,
                         "{\"data\": [2, 10], \"softmax_label\": [2]}",
                         &pred));
      free(json2);
      float pin[20];
      for (int i = 0; i < 20; ++i) pin[i] = 0.1f * i;
      CHECK(MXPredSetInput(pred, "data", pin, 20));
      CHECK(MXPredForward(pred));
      uint32_t pndim, pshape[8];
      CHECK(MXPredGetOutputShape(pred, 0, &pndim, pshape, 8));
      if (pndim != 2 || pshape[0] * pshape[1] > 4) {
        fprintf(stderr, "FAIL predictor output rank/size\n");
        return 1;
      }
      float pout[4];
      CHECK(MXPredGetOutput(pred, 0, pout, pshape[0] * pshape[1]));
      if (pout[0] + pout[1] < 0.99f || pout[0] + pout[1] > 1.01f) {
        fprintf(stderr, "FAIL predictor softmax\n");
        return 1;
      }
      printf("predict: shape (%u,%u) OK\n", pshape[0], pshape[1]);
      CHECK(MXPredFree(pred));
    }
    CHECK(MXSymbolFree(sym));
  }

  CHECK(MXNDArrayFree(a));
  CHECK(MXNDArrayFree(pulled));
  CHECK(MXKVStoreFree(kv));
  printf("CAPI SMOKE OK\n");
  return 0;
}
