/* Real C consumer of the mxnet_tpu ABI (the proof the reference's
 * language-binding story survives the TPU rewrite): ndarray round trip,
 * kvstore push/pull aggregation, symbol load -> bind -> forward.
 *
 * Built and run by `make test-capi`; expects MXTPU_SYMBOL_JSON to point
 * at a saved -symbol.json (the pytest wrapper generates one). */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu/c_api.h"

#define CHECK(rc) do { \
    if ((rc) != 0) { \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, \
              MXGetLastError()); \
      return 1; \
    } } while (0)

/* kvstore updater written in C: local -= 0.5 * recv */
static void c_updater(int key, NDArrayHandle recv, NDArrayHandle local,
                      void* user) {
  (void)key;
  float r[16], l[16];
  uint32_t nd, shp[4];
  if (MXNDArrayGetShape(recv, &nd, shp, 4) != 0 || nd != 1) return;
  if (MXNDArraySyncCopyToCPU(recv, r, shp[0]) != 0) return;
  if (MXNDArraySyncCopyToCPU(local, l, shp[0]) != 0) return;
  for (uint32_t i = 0; i < shp[0]; ++i) l[i] -= 0.5f * r[i];
  if (MXNDArraySyncCopyFromCPU(local, l, shp[0]) != 0) return;
  ++*(int*)user;
}

int main(void) {
  /* --- ndarray round trip --- */
  uint32_t shape[2] = {2, 3};
  NDArrayHandle a;
  CHECK(MXNDArrayCreate(shape, 2, &a));
  float in[6] = {1, 2, 3, 4, 5, 6}, out[6] = {0};
  CHECK(MXNDArraySyncCopyFromCPU(a, in, 6));
  CHECK(MXNDArraySyncCopyToCPU(a, out, 6));
  for (int i = 0; i < 6; ++i) {
    if (out[i] != in[i]) {
      fprintf(stderr, "FAIL roundtrip at %d: %f\n", i, out[i]);
      return 1;
    }
  }
  uint32_t ndim, got[8];
  CHECK(MXNDArrayGetShape(a, &ndim, got, 8));
  if (ndim != 2 || got[0] != 2 || got[1] != 3) {
    fprintf(stderr, "FAIL shape\n");
    return 1;
  }

  /* --- kvstore aggregation --- */
  KVStoreHandle kv;
  CHECK(MXKVStoreCreate("local", &kv));
  CHECK(MXKVStoreInit(kv, 3, a));
  CHECK(MXKVStorePush(kv, 3, a));
  NDArrayHandle pulled;
  CHECK(MXNDArrayCreate(shape, 2, &pulled));
  CHECK(MXKVStorePull(kv, 3, pulled));
  CHECK(MXNDArraySyncCopyToCPU(pulled, out, 6));
  if (out[5] != 6.0f) {
    fprintf(stderr, "FAIL kvstore pull: %f\n", out[5]);
    return 1;
  }

  /* --- symbol -> executor -> forward --- */
  const char* path = getenv("MXTPU_SYMBOL_JSON");
  if (path != NULL) {
    FILE* f = fopen(path, "rb");
    if (!f) {
      fprintf(stderr, "FAIL: cannot open %s\n", path);
      return 1;
    }
    fseek(f, 0, SEEK_END);
    long n = ftell(f);
    fseek(f, 0, SEEK_SET);
    char* json = (char*)malloc(n + 1);
    if (fread(json, 1, n, f) != (size_t)n) {
      fprintf(stderr, "FAIL: short read of %s\n", path);
      return 1;
    }
    json[n] = 0;
    fclose(f);

    SymbolHandle sym;
    CHECK(MXSymbolCreateFromJSON(json, &sym));
    free(json);
    uint32_t nargs;
    CHECK(MXSymbolGetNumArguments(sym, &nargs));
    char name[64];
    CHECK(MXSymbolGetArgument(sym, 0, name, sizeof(name)));
    printf("symbol: %u args, first=%s\n", nargs, name);

    ExecutorHandle exec;
    CHECK(MXExecutorSimpleBind(
        sym, "{\"data\": [4, 10], \"softmax_label\": [4]}", &exec));
    float data[40];
    for (int i = 0; i < 40; ++i) data[i] = (float)i / 40.0f;
    CHECK(MXExecutorSetArg(exec, "data", data, 40));
    uint32_t nout;
    CHECK(MXExecutorForward(exec, 0, &nout));
    uint32_t oshape[8], ondim;
    CHECK(MXExecutorOutputShape(exec, 0, &ondim, oshape, 8));
    float probs[8];
    CHECK(MXExecutorOutputCopy(exec, 0, probs, oshape[0] * oshape[1]));
    float rowsum = probs[0] + probs[1];
    if (rowsum < 0.99f || rowsum > 1.01f) {
      fprintf(stderr, "FAIL softmax rowsum %f\n", rowsum);
      return 1;
    }
    printf("forward: %u outputs, shape (%u,%u), row0 sum=%f\n",
           nout, oshape[0], oshape[1], rowsum);
    CHECK(MXExecutorFree(exec));

    /* --- predict API (c_predict_api subset) --- */
    const char* params = getenv("MXTPU_PARAMS_FILE");
    if (params != NULL) {
      /* re-read symbol json for the predictor */
      FILE* f2 = fopen(path, "rb");
      if (!f2) {
        fprintf(stderr, "FAIL: cannot reopen %s\n", path);
        return 1;
      }
      fseek(f2, 0, SEEK_END);
      long n2 = ftell(f2);
      fseek(f2, 0, SEEK_SET);
      char* json2 = (char*)malloc(n2 + 1);
      if (fread(json2, 1, n2, f2) != (size_t)n2) return 1;
      json2[n2] = 0;
      fclose(f2);
      PredictorHandle pred;
      CHECK(MXPredCreate(json2, params,
                         "{\"data\": [2, 10], \"softmax_label\": [2]}",
                         &pred));
      free(json2);
      float pin[20];
      for (int i = 0; i < 20; ++i) pin[i] = 0.1f * i;
      CHECK(MXPredSetInput(pred, "data", pin, 20));
      CHECK(MXPredForward(pred));
      uint32_t pndim, pshape[8];
      CHECK(MXPredGetOutputShape(pred, 0, &pndim, pshape, 8));
      if (pndim != 2 || pshape[0] * pshape[1] > 4) {
        fprintf(stderr, "FAIL predictor output rank/size\n");
        return 1;
      }
      float pout[4];
      CHECK(MXPredGetOutput(pred, 0, pout, pshape[0] * pshape[1]));
      if (pout[0] + pout[1] < 0.99f || pout[0] + pout[1] > 1.01f) {
        fprintf(stderr, "FAIL predictor softmax\n");
        return 1;
      }
      printf("predict: shape (%u,%u) OK\n", pshape[0], pshape[1]);
      CHECK(MXPredFree(pred));
    }
    CHECK(MXSymbolFree(sym));
  }

  /* --- function-registry listing with docs --- */
  uint32_t nfn = 0;
  FunctionHandle* fns = NULL;
  CHECK(MXListFunctions(&nfn, &fns));
  if (nfn < 80) {
    fprintf(stderr, "FAIL: registry lists only %u ops\n", nfn);
    return 1;
  }
  int saw_conv = 0;
  for (uint32_t i = 0; i < nfn; ++i) {
    const char *fname, *fdesc;
    uint32_t na;
    const char **anames, **atypes, **adescs;
    CHECK(MXFuncGetInfo(fns[i], &fname, &fdesc, &na, &anames, &atypes,
                        &adescs));
    if (strcmp(fname, "Convolution") == 0) {
      saw_conv = 1;
      if (strlen(fdesc) == 0 || na == 0) {
        fprintf(stderr, "FAIL: Convolution info empty\n");
        return 1;
      }
      printf("registry: %u ops; Convolution has %u params, first=%s (%s)\n",
             nfn, na, anames[0], atypes[0]);
    }
  }
  if (!saw_conv) {
    fprintf(stderr, "FAIL: Convolution not listed\n");
    return 1;
  }
  /* imperative invoke through the registry: dot((2,3),(3,2)) */
  {
    FunctionHandle dot_fn = NULL;
    for (uint32_t i = 0; i < nfn; ++i) {
      const char* fname;
      CHECK(MXFuncGetInfo(fns[i], &fname, NULL, NULL, NULL, NULL, NULL));
      if (strcmp(fname, "dot") == 0) dot_fn = fns[i];
    }
    if (!dot_fn) {
      fprintf(stderr, "FAIL: dot not in registry\n");
      return 1;
    }
    uint32_t s23[2] = {2, 3}, s32[2] = {3, 2};
    NDArrayHandle da, db, douts[4];
    CHECK(MXNDArrayCreate(s23, 2, &da));
    CHECK(MXNDArrayCreate(s32, 2, &db));
    float fa[6] = {1, 2, 3, 4, 5, 6}, fb[6] = {1, 0, 0, 1, 1, 1};
    CHECK(MXNDArraySyncCopyFromCPU(da, fa, 6));
    CHECK(MXNDArraySyncCopyFromCPU(db, fb, 6));
    uint32_t ndout = 0;
    NDArrayHandle din[2] = {da, db};
    CHECK(MXFuncInvoke(dot_fn, 2, din, "", &ndout, douts, 4));
    float dres[4];
    CHECK(MXNDArraySyncCopyToCPU(douts[0], dres, 4));
    /* [[1,2,3],[4,5,6]] x [[1,0],[0,1],[1,1]] = [[4,5],[10,11]] */
    if (ndout != 1 || dres[0] != 4.f || dres[3] != 11.f) {
      fprintf(stderr, "FAIL MXFuncInvoke dot: %f %f\n", dres[0], dres[3]);
      return 1;
    }
    printf("func-invoke: dot through the registry OK\n");
    CHECK(MXNDArrayFree(da));
    CHECK(MXNDArrayFree(db));
    CHECK(MXNDArrayFree(douts[0]));
  }

  /* --- compose a symbol entirely through C --- */
  SymbolHandle var, fc_atomic, fc, sm_atomic, net;
  CHECK(MXSymbolCreateVariable("cdata", &var));
  CHECK(MXSymbolCreateAtomicSymbol("FullyConnected",
                                   "{\"num_hidden\": 4}", "cfc",
                                   &fc_atomic));
  const char* ckeys[1] = {"data"};
  SymbolHandle cargs[1] = {var};
  CHECK(MXSymbolCompose(fc_atomic, 1, ckeys, cargs, &fc));
  CHECK(MXSymbolCreateAtomicSymbol("SoftmaxOutput", "", "csm", &sm_atomic));
  SymbolHandle cargs2[1] = {fc};
  CHECK(MXSymbolCompose(sm_atomic, 1, ckeys, cargs2, &net));
  uint32_t cnargs = 0, cnout = 0;
  CHECK(MXSymbolGetNumArguments(net, &cnargs));
  CHECK(MXSymbolGetNumOutputs(net, &cnout));
  char outname[64];
  CHECK(MXSymbolGetOutput(net, 0, outname, sizeof(outname)));
  CHECK(MXSymbolSetAttr(fc, "ctx_group", "stage1"));
  char attr[32];
  int ok = 0;
  CHECK(MXSymbolGetAttr(fc, "ctx_group", attr, sizeof(attr), &ok));
  if (!ok || strcmp(attr, "stage1") != 0) {
    fprintf(stderr, "FAIL attr roundtrip: %d %s\n", ok, attr);
    return 1;
  }
  const char* netjson = NULL;
  CHECK(MXSymbolSaveToJSON(net, &netjson));
  const char* shapes = NULL;
  CHECK(MXSymbolInferShapeJSON(net, "{\"cdata\": [2, 8]}", &shapes));
  if (strstr(shapes, "out_shapes") == NULL) {
    fprintf(stderr, "FAIL infer_shape json: %s\n", shapes);
    return 1;
  }
  printf("compose: %u args, %u outputs, head=%s, json %zu B\n",
         cnargs, cnout, outname, strlen(netjson));
  CHECK(MXSymbolFree(var));
  CHECK(MXSymbolFree(fc_atomic));
  CHECK(MXSymbolFree(fc));
  CHECK(MXSymbolFree(sm_atomic));
  CHECK(MXSymbolFree(net));

  /* --- RecordIO through C --- */
  const char* rec_path = "/tmp/mxtpu_capi_smoke.rec";
  RecordIOHandle w;
  CHECK(MXRecordIOWriterCreate(rec_path, &w));
  CHECK(MXRecordIOWriterWriteRecord(w, "hello", 5));
  CHECK(MXRecordIOWriterWriteRecord(w, "worlds", 6));
  size_t wpos = 0;
  CHECK(MXRecordIOWriterTell(w, &wpos));
  CHECK(MXRecordIOWriterFree(w));
  RecordIOHandle r;
  CHECK(MXRecordIOReaderCreate(rec_path, &r));
  const char* rbuf = NULL;
  size_t rlen = 0;
  CHECK(MXRecordIOReaderReadRecord(r, &rbuf, &rlen));
  if (rlen != 5 || memcmp(rbuf, "hello", 5) != 0) {
    fprintf(stderr, "FAIL recordio read 1 (%zu)\n", rlen);
    return 1;
  }
  CHECK(MXRecordIOReaderReadRecord(r, &rbuf, &rlen));
  if (rlen != 6 || memcmp(rbuf, "worlds", 6) != 0) {
    fprintf(stderr, "FAIL recordio read 2\n");
    return 1;
  }
  CHECK(MXRecordIOReaderReadRecord(r, &rbuf, &rlen));
  if (rbuf != NULL || rlen != 0) {
    fprintf(stderr, "FAIL recordio EOF\n");
    return 1;
  }
  CHECK(MXRecordIOReaderFree(r));
  remove(rec_path);
  printf("recordio: wrote %zu bytes, read back OK\n", wpos);

  /* --- optimizer through C --- */
  OptimizerHandle opt;
  CHECK(MXOptimizerCreateOptimizer(
      "sgd", "{\"learning_rate\": 0.5, \"momentum\": 0.0}", &opt));
  NDArrayHandle wgt, grd;
  uint32_t oshp[1] = {4};
  CHECK(MXNDArrayCreate(oshp, 1, &wgt));
  CHECK(MXNDArrayCreate(oshp, 1, &grd));
  float ones[4] = {1, 1, 1, 1};
  CHECK(MXNDArraySyncCopyFromCPU(wgt, ones, 4));
  CHECK(MXNDArraySyncCopyFromCPU(grd, ones, 4));
  CHECK(MXOptimizerUpdate(opt, 0, wgt, grd, -1.0f, 0.0f));
  float wout[4];
  CHECK(MXNDArraySyncCopyToCPU(wgt, wout, 4));
  if (wout[0] > 0.51f || wout[0] < 0.49f) {
    fprintf(stderr, "FAIL optimizer update: %f\n", wout[0]);
    return 1;
  }
  printf("optimizer: sgd step 1.0 -> %f\n", wout[0]);
  CHECK(MXOptimizerFree(opt));
  CHECK(MXNDArrayFree(wgt));
  CHECK(MXNDArrayFree(grd));

  /* --- data iterator through C (CSVIter) --- */
  {
    FILE* csv = fopen("/tmp/mxtpu_capi_smoke.csv", "w");
    if (!csv) return 1;
    for (int i = 0; i < 8; ++i)
      fprintf(csv, "%d,%d,%d\n", i, i + 1, i + 2);
    fclose(csv);
    uint32_t nit = 0;
    FunctionHandle* iters = NULL;
    CHECK(MXListDataIters(&nit, &iters));
    if (nit < 3) {
      fprintf(stderr, "FAIL: %u data iters listed\n", nit);
      return 1;
    }
    const char* itname = NULL;
    CHECK(MXDataIterGetIterInfo(iters[0], &itname, NULL));
    DataIterHandle it;
    CHECK(MXDataIterCreateIter(
        "CSVIter",
        "{\"data_csv\": \"/tmp/mxtpu_capi_smoke.csv\", "
        "\"data_shape\": [3], \"batch_size\": 4}", &it));
    int more = 0, batches = 0;
    CHECK(MXDataIterNext(it, &more));
    while (more) {
      NDArrayHandle d;
      CHECK(MXDataIterGetData(it, &d));
      uint32_t dn, ds[4];
      CHECK(MXNDArrayGetShape(d, &dn, ds, 4));
      if (dn != 2 || ds[0] != 4 || ds[1] != 3) {
        fprintf(stderr, "FAIL iter batch shape\n");
        return 1;
      }
      CHECK(MXNDArrayFree(d));
      ++batches;
      CHECK(MXDataIterNext(it, &more));
    }
    if (batches != 2) {
      fprintf(stderr, "FAIL iter batches %d\n", batches);
      return 1;
    }
    CHECK(MXDataIterBeforeFirst(it));
    CHECK(MXDataIterNext(it, &more));
    if (!more) {
      fprintf(stderr, "FAIL iter reset\n");
      return 1;
    }
    CHECK(MXDataIterFree(it));
    remove("/tmp/mxtpu_capi_smoke.csv");
    printf("dataiter: %u listed (first=%s), CSVIter 2 batches OK\n",
           nit, itname);
  }

  /* --- a FULL training loop driven from C ---
   * compose net -> bind grad_req=write -> loop { set data/label,
   * forward(train), backward, MXOptimizerUpdate over bound arg/grad
   * handles } -> cross-entropy must drop. */
  {
    int version = 0;
    CHECK(MXGetVersion(&version));
    CHECK(MXRandomSeed(42));
    SymbolHandle v, fca, fcs, sma, tnet;
    CHECK(MXSymbolCreateVariable("data", &v));
    CHECK(MXSymbolCreateAtomicSymbol("FullyConnected",
                                     "{\"num_hidden\": 2}", "tfc", &fca));
    const char* tk[1] = {"data"};
    SymbolHandle ta[1] = {v};
    CHECK(MXSymbolCompose(fca, 1, tk, ta, &fcs));
    CHECK(MXSymbolCreateAtomicSymbol("SoftmaxOutput", "", "softmax",
                                     &sma));
    SymbolHandle ta2[1] = {fcs};
    CHECK(MXSymbolCompose(sma, 1, tk, ta2, &tnet));

    ExecutorHandle tex;
    CHECK(MXExecutorSimpleBindTrain(
        tnet, "{\"data\": [8, 4], \"softmax_label\": [8]}", &tex));
    /* init weights from C */
    float w0[2 * 4], b0[2] = {0, 0};
    for (int i = 0; i < 8; ++i) w0[i] = 0.05f * (i % 5) - 0.1f;
    CHECK(MXExecutorSetArg(tex, "tfc_weight", w0, 8));
    CHECK(MXExecutorSetArg(tex, "tfc_bias", b0, 2));
    /* separable toy data: class = (x0 + x1 > x2 + x3) */
    float data_t[8 * 4], label_t[8];
    for (int i = 0; i < 8; ++i) {
      for (int j = 0; j < 4; ++j)
        data_t[i * 4 + j] = ((i * 7 + j * 13) % 11) / 11.0f - 0.5f;
      label_t[i] = (data_t[i * 4] + data_t[i * 4 + 1] >
                    data_t[i * 4 + 2] + data_t[i * 4 + 3]) ? 1.f : 0.f;
    }
    CHECK(MXExecutorSetArg(tex, "data", data_t, 32));
    CHECK(MXExecutorSetArg(tex, "softmax_label", label_t, 8));

    OptimizerHandle topt;
    CHECK(MXOptimizerCreateOptimizer(
        "sgd", "{\"learning_rate\": 0.5, \"momentum\": 0.9}", &topt));
    NDArrayHandle warg, wgrad, barg, bgrad;
    CHECK(MXExecutorArgHandle(tex, "tfc_weight", &warg));
    CHECK(MXExecutorGradHandle(tex, "tfc_weight", &wgrad));
    CHECK(MXExecutorArgHandle(tex, "tfc_bias", &barg));
    CHECK(MXExecutorGradHandle(tex, "tfc_bias", &bgrad));

    float first_loss = -1.f, loss = 0.f;
    for (int step = 0; step < 40; ++step) {
      uint32_t nout = 0;
      CHECK(MXExecutorForward(tex, 1, &nout));
      float probs[16];
      CHECK(MXExecutorOutputCopy(tex, 0, probs, 16));
      loss = 0.f;
      for (int i = 0; i < 8; ++i) {
        float p = probs[i * 2 + (int)label_t[i]];
        loss += -(float)log(p > 1e-6f ? p : 1e-6f);
      }
      if (first_loss < 0) first_loss = loss;
      CHECK(MXExecutorBackward(tex));
      CHECK(MXOptimizerUpdate(topt, 0, warg, wgrad, -1.f, 0.f));
      CHECK(MXOptimizerUpdate(topt, 1, barg, bgrad, -1.f, 0.f));
    }
    if (!(loss < first_loss * 0.5f)) {
      fprintf(stderr, "FAIL C training loop: loss %f -> %f\n",
              first_loss, loss);
      return 1;
    }
    printf("train-from-C: loss %.3f -> %.3f over 40 steps (version %d)\n",
           first_loss, loss, version);

    /* checkpoint the trained weights through C and load them back */
    NDArrayHandle saved[2] = {warg, barg};
    const char* names[2] = {"arg:tfc_weight", "arg:tfc_bias"};
    CHECK(MXNDArraySave("/tmp/mxtpu_capi_train.params", 2, saved, names));
    uint32_t ln = 0, lnames_n = 0;
    NDArrayHandle* larr = NULL;
    const char** lnames = NULL;
    CHECK(MXNDArrayLoad("/tmp/mxtpu_capi_train.params", &ln, &larr,
                        &lnames_n, &lnames));
    /* names come back in FILE order == the order passed to Save (the
     * reference MXNDArrayLoad contract) */
    if (ln != 2 || lnames_n != 2 ||
        strcmp(lnames[0], "arg:tfc_weight") != 0 ||
        strcmp(lnames[1], "arg:tfc_bias") != 0) {
      fprintf(stderr, "FAIL save/load roundtrip (%u, %u)\n", ln, lnames_n);
      return 1;
    }
    int dtype = -1;
    CHECK(MXNDArrayGetDType(larr[0], &dtype));
    NDArrayHandle resh;
    uint32_t rshape[1] = {8};
    CHECK(MXNDArrayReshape(larr[0], 1, rshape, &resh));  /* the weight */
    NDArrayHandle slc;
    CHECK(MXNDArraySlice(resh, 2, 6, &slc));
    uint32_t sn, ss[4];
    CHECK(MXNDArrayGetShape(slc, &sn, ss, 4));
    if (sn != 1 || ss[0] != 4) {
      fprintf(stderr, "FAIL slice shape\n");
      return 1;
    }
    remove("/tmp/mxtpu_capi_train.params");
    printf("checkpoint-from-C: 2 arrays, dtype %d, reshape+slice OK\n",
           dtype);
    for (uint32_t i = 0; i < ln; ++i) CHECK(MXNDArrayFree(larr[i]));
    CHECK(MXNDArrayFree(resh));
    CHECK(MXNDArrayFree(slc));
    CHECK(MXNDArrayFree(warg));
    CHECK(MXNDArrayFree(wgrad));
    CHECK(MXNDArrayFree(barg));
    CHECK(MXNDArrayFree(bgrad));
    CHECK(MXOptimizerFree(topt));
    CHECK(MXExecutorFree(tex));
    CHECK(MXSymbolFree(v));
    CHECK(MXSymbolFree(fca));
    CHECK(MXSymbolFree(fcs));
    CHECK(MXSymbolFree(sma));
    CHECK(MXSymbolFree(tnet));
  }

  /* --- a C function as the kvstore updater --- */
  {
    KVStoreHandle ukv;
    CHECK(MXKVStoreCreate("local_update_cpu", &ukv));
    uint32_t ushp[1] = {4};
    NDArrayHandle uw, ug;
    CHECK(MXNDArrayCreate(ushp, 1, &uw));
    CHECK(MXNDArrayCreate(ushp, 1, &ug));
    float wv[4] = {10, 10, 10, 10}, gv[4] = {1, 2, 3, 4};
    CHECK(MXNDArraySyncCopyFromCPU(uw, wv, 4));
    CHECK(MXNDArraySyncCopyFromCPU(ug, gv, 4));
    CHECK(MXKVStoreInit(ukv, 5, uw));
    int hits = 0;
    CHECK(MXKVStoreSetUpdater(ukv, c_updater, &hits));
    CHECK(MXKVStorePush(ukv, 5, ug));
    NDArrayHandle upulled;
    CHECK(MXNDArrayCreate(ushp, 1, &upulled));
    CHECK(MXKVStorePull(ukv, 5, upulled));
    float got_u[4];
    CHECK(MXNDArraySyncCopyToCPU(upulled, got_u, 4));
    /* updater: local -= 0.5 * recv  ->  10 - 0.5*g */
    if (hits != 1 || got_u[0] != 9.5f || got_u[3] != 8.0f) {
      fprintf(stderr, "FAIL C updater: hits=%d %f %f\n", hits, got_u[0],
              got_u[3]);
      return 1;
    }
    printf("kvstore C updater: key 5, %d call, local -= 0.5*recv OK\n",
           hits);
    CHECK(MXNDArrayFree(uw));
    CHECK(MXNDArrayFree(ug));
    CHECK(MXNDArrayFree(upulled));
    CHECK(MXKVStoreFree(ukv));
  }

  /* --- executor plan dump + symbol attrs through C --- */
  {
    SymbolHandle pv, pfa, pnet;
    CHECK(MXSymbolCreateVariable("data", &pv));
    CHECK(MXSymbolCreateAtomicSymbol("FullyConnected",
                                     "{\"num_hidden\": 2}", "pfc", &pfa));
    const char* pk[1] = {"data"};
    SymbolHandle pa[1] = {pv};
    CHECK(MXSymbolCompose(pfa, 1, pk, pa, &pnet));
    CHECK(MXSymbolSetAttr(pnet, "lr_mult", "2.0"));
    const char* attrs_json = NULL;
    CHECK(MXSymbolListAttrJSON(pnet, &attrs_json));
    if (strstr(attrs_json, "lr_mult") == NULL) {
      fprintf(stderr, "FAIL attr json: %s\n", attrs_json);
      return 1;
    }
    ExecutorHandle pex;
    CHECK(MXExecutorSimpleBind(pnet, "{\"data\": [2, 3]}", &pex));
    const char* plan = NULL;
    CHECK(MXExecutorPrint(pex, &plan));
    if (strstr(plan, "pfc") == NULL) {
      fprintf(stderr, "FAIL executor print lacks op: %.120s\n", plan);
      return 1;
    }
    printf("plan-dump: %zu chars, attrs json OK\n", strlen(plan));
    CHECK(MXExecutorFree(pex));
    CHECK(MXSymbolFree(pv));
    CHECK(MXSymbolFree(pfa));
    CHECK(MXSymbolFree(pnet));
  }

  /* --- kvstore cluster queries --- */
  {
    int rank = -1, size = -1;
    const char* ktype = NULL;
    CHECK(MXKVStoreGetRank(kv, &rank));
    CHECK(MXKVStoreGetGroupSize(kv, &size));
    CHECK(MXKVStoreGetType(kv, &ktype));
    CHECK(MXKVStoreBarrier(kv));
    if (rank != 0 || size != 1 || strcmp(ktype, "local") != 0) {
      fprintf(stderr, "FAIL kvstore queries: %d %d %s\n", rank, size,
              ktype);
      return 1;
    }
    printf("kvstore queries: rank %d/%d type %s\n", rank, size, ktype);
  }

  /* --- deliberate failures: the last-error contract --- */
  SymbolHandle bad = NULL;
  if (MXSymbolCreateAtomicSymbol("NoSuchOperator", "", "x", &bad) == 0) {
    /* staging is lazy; composing must fail */
    SymbolHandle out2 = NULL;
    if (MXSymbolCompose(bad, 0, NULL, NULL, &out2) == 0) {
      fprintf(stderr, "FAIL: composing unknown op succeeded\n");
      return 1;
    }
    MXSymbolFree(bad);
  }
  if (strlen(MXGetLastError()) == 0) {
    fprintf(stderr, "FAIL: empty last error after failure\n");
    return 1;
  }
  RecordIOHandle nor;
  if (MXRecordIOReaderCreate("/nonexistent/dir/x.rec", &nor) == 0) {
    fprintf(stderr, "FAIL: opening nonexistent rec succeeded\n");
    return 1;
  }
  if (strstr(MXGetLastError(), "x.rec") == NULL &&
      strlen(MXGetLastError()) == 0) {
    fprintf(stderr, "FAIL: useless error message: %s\n", MXGetLastError());
    return 1;
  }
  /* the failed call must not poison the next one */
  NDArrayHandle after;
  uint32_t ashp[1] = {2};
  CHECK(MXNDArrayCreate(ashp, 1, &after));
  CHECK(MXNDArrayFree(after));
  printf("error-path: rc -1, message=\"%.40s...\", recovery OK\n",
         MXGetLastError());

  CHECK(MXNDArrayFree(a));
  CHECK(MXNDArrayFree(pulled));
  CHECK(MXKVStoreFree(kv));
  printf("CAPI SMOKE OK\n");
  return 0;
}
